"""North-star benchmark (BASELINE.json): 1M-node Watts–Strogatz single-source
flood to 99% coverage, one chip, whole run device-side (lax.while_loop — zero
host round-trips per round), plus the 10M-node scale config.

Prints the headline JSON record — {"metric", "value", "unit", "vs_baseline",
...} — as its LAST stdout line. ``value`` is the wall-clock seconds of the
best aggregation path at 1M; ``vs_baseline`` is (1 s north-star target) /
value, so > 1 beats the target; ``scale_10M`` carries the 10M-node result
(driver-verified scale row).

Hang containment (this environment's device tunnel has wedged for hours at
a time, twice exactly when the driver ran this file):

- backend init is probed in a child process (``_backend_alive``) — a
  wedged PJRT client hangs holding the GIL, so no in-process watchdog can
  fire. Probes are CAPPED at 2 attempts (BENCH_PROBE_MAX_ATTEMPTS; a
  retry window still bounds them from above) before handing off to a
  ``JAX_PLATFORMS=cpu`` child that publishes a real record tagged
  ``"backend": "cpu-fallback"`` — never a ``value: null`` kill when a
  fallback number is obtainable. BENCH_r05 burned its ENTIRE 40-minute
  window on 8 × 120 s wedged probes and published nothing; two probes
  (~4 min worst case) leave the window to the fallback measurement that
  actually produces a record;
- each measurement stage then runs in its OWN child process under a hard
  timeout (``--stage 1m`` / ``--stage 10m``), so a tunnel that wedges
  MID-measurement turns into a bounded, reported error instead of an
  unbounded hang;
- the 1M record is printed the moment the 1M stage returns — before the
  10M stage starts — so a late wedge cannot sink the already-measured
  headline. On success the final merged record (1M + scale_10M) is the
  last line; on a 10M failure the merged record carries the error;
- each measuring stage first runs its workload once under
  ``SupervisedRun`` (supervise/runner.py): chunked, watchdog-guarded,
  auto-checkpointing into ``_supervise_dir(stage)``. A stage that dies
  MID-run therefore leaves a resumable checkpoint trail, and the parent
  publishes a partial structured record tagged ``"backend": "resumed"``
  (rounds-completed + checkpoint path, mirrored into the stage's
  BENCH_TELEMETRY artifact) instead of dropping the stage; the next run's
  supervised pass resumes that trail bit-identically.

Graph construction is the dominant host-side cost (≈16 s at 1M, ≈49 s at
10M): built graphs are persisted once through the shared content-addressed
layout store (``sim/layoutcache.py``, which generalized this file's
original private cache) under ``bench_cache/`` and reloaded on later
runs, shrinking the healthy-tunnel window a successful bench needs.
``BENCH_CACHE=0`` disables; a corrupt/missing cache file falls back to a
fresh build, reported as a structured ``bench_cache_miss`` warning event
(stderr JSONL, telemetry-schema) plus a
``bench_cache_miss_total{reason=...}`` counter — never swallowed. Cold
builds additionally publish the per-phase attribution of where the build
seconds went (dedup/sort/tables/CSR/layouts/reorder — sim/graph.py) as
``build_phases`` in the stage telemetry artifact.

Telemetry (telemetry/): each measuring stage writes a per-stage artifact —
``BENCH_TELEMETRY.json`` for the 1M headline stage (``BENCH_TELEMETRY_10M
.json`` for the scale row; override dir via BENCH_TELEMETRY_DIR) — carrying
graph-build / cache / compile / run / transfer timings and the full
registry snapshot; the ``frontier`` method column additionally attributes
per-round frontier occupancy (``frontier_occupancy_per_round``) so the
sparse/dense crossover constant (ops/frontier.py) is measured, not
guessed. The 1M stage additionally publishes the ``batched`` message-plane
column: B concurrent floods advanced by ONE compiled program per round
(models/messagebatch.py lane packing + engine.run_batch_until_coverage)
on the 100k-node WS class, with ``batch_completion_rounds_p99`` and the
aggregate-throughput ratio vs sequential single-message runs
(BENCH_BATCH_B=1024 / BENCH_BATCH_N=100000 / BENCH_BATCH=0 to disable),
and the ``queries`` column: the three non-boolean batched query families
(models/querybatch.py — min-plus route lookups and push-sum aggregations
on the batched WS class, DHT greedy lookups on a 100k-node chord
overlay), each with lanes/s, completion-rounds p50/p99 and the aggregate
speedup vs warm sequential capacity-1 runs (BENCH_QUERY_K_MINPLUS=64 /
_PUSHSUM=32 / _DHT=2048, BENCH_QUERY_DHT_N=100000, BENCH_QUERIES=0 to
disable). Each measuring stage runs inside an ``analysis.retrace_guard``
with a per-stage jit compile budget (BENCH_COMPILE_BUDGET_1M/_10M):
a breach — something retracing mid-measurement — emits a structured
``bench_recompile_budget_breach`` warning plus the
``bench_recompile_total{stage}`` counter, never a failed bench. The
last-line headline JSON record is unchanged.

Reference anchor: the reference implementation moves one message per peer per
10 ms poll tick per Python thread [ref: p2pnetwork/nodeconnection.py:220];
simulating this workload there would take hours — it publishes no numbers
(BASELINE.md), so the driver-set 1 s target is the baseline.
"""

import contextlib
import json
import os
import subprocess
import sys
import time
import traceback

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)

from p2pnetwork_tpu import telemetry  # noqa: E402 — stdlib-only, no jax


def _warn_event(name: str, **data) -> None:
    """Structured warning on stderr in the shared telemetry JSONL schema
    (export.event_record) — greppable by the driver, parseable by tools,
    and mirrored as a counter by the callers that need one."""
    rec = telemetry.event_record(name, time.time(), data=data)
    print("# WARN " + json.dumps(rec), file=sys.stderr, flush=True)


def time_flood(graph, method: str, *, target: float, max_rounds: int,
               reps: int = None, occupancy_attribution: bool = False):
    """Returns ``(best_seconds, last_out, timing)`` where ``timing`` splits
    the wall clock into the warmup (compile-carrying) call and the measured
    reps — the per-stage attribution BENCH_TELEMETRY.json reports.
    ``reps`` defaults to BENCH_REPS (5) — the cpu-fallback path shrinks it.

    ``occupancy_attribution=True`` re-runs the measured round count once
    through the scan engine and attaches the per-round
    ``frontier_occupancy`` series to ``timing`` — the measurement that
    lets the frontier crossover constant (ops/frontier.py) be re-fit from
    real runs instead of guessed."""
    import jax
    import numpy as np

    from p2pnetwork_tpu.models.adaptive_flood import AdaptiveFlood
    from p2pnetwork_tpu.models.flood import Flood
    from p2pnetwork_tpu.sim import engine

    if reps is None:
        reps = int(os.environ.get("BENCH_REPS", "5"))
    if method.startswith("adaptive"):
        # "adaptive-<k>": frontier-sparse rounds under k, dense hybrid above
        # (models/adaptive_flood.py) — bit-identical results to Flood.
        k = int(method.split("-")[1])
        protocol = AdaptiveFlood(source=0, method="hybrid", k=k)
    elif method == "frontier":
        # lax.cond-compacted sparse rounds with dense fallback
        # (ops/frontier.py), packed carry state — bit-identical to Flood.
        protocol = Flood(source=0, method="frontier", bitset=True)
    else:
        protocol = Flood(source=0, method=method)
    key = jax.random.key(0)

    def once():
        # run_until_coverage itself blocks on a real device->host transfer
        # of the packed run summary (engine._unpack_summary) — the sync
        # that keeps these timings honest on tunneled backends, where
        # jax.block_until_ready can return before execution finishes.
        state, out = engine.run_until_coverage(
            graph, protocol, key, coverage_target=target, max_rounds=max_rounds
        )
        return out

    t0 = time.perf_counter()
    out = once()  # compile + warm up
    warmup_s = time.perf_counter() - t0
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = once()
        times.append(time.perf_counter() - t0)
    timing = {"warmup_s": round(warmup_s, 4),
              "measure_s": round(sum(times), 4), "reps": reps}
    if occupancy_attribution:
        # One scan-engine pass at the measured round count: per-round
        # frontier occupancy, straight off the device-side stat.
        _, stats = engine.run(graph, protocol, key, int(out["rounds"]))
        timing["frontier_occupancy_per_round"] = [
            round(float(v), 6)
            for v in np.asarray(stats["frontier_occupancy"])]
    return min(times), out, timing


# --------------------------------------------------------------- graph cache

def _cache_dir():
    return os.environ.get("BENCH_CACHE_DIR", os.path.join(_HERE, "bench_cache"))


def _layout_fingerprint():
    """Hash of the sources that determine a built graph's arrays and kernel
    layouts, via the shared library-level store (sim/layoutcache.py — its
    DEFAULT_SOURCES cover the graph builder, reorder pass, topology
    generators, kernel layouts, native sort/merge kernels and the
    serializer). bench.py itself is folded in on top: the cache NAME only
    carries n, so an edit to a build call's other kwargs (k, p, layout
    flags) must also invalidate."""
    from p2pnetwork_tpu.sim import layoutcache

    return layoutcache.fingerprint(
        extra_sources=(os.path.join(_HERE, "bench.py"),))


def _cached_graph(name: str, build):
    """Load ``bench_cache/<name>.npz`` if present, else build + persist —
    the shared content-addressed layout store (sim/layoutcache.py) keyed
    under BENCH_CACHE_DIR.

    Returns ``(graph, build_seconds, from_cache)``. Any cache failure
    (missing file, version skew, truncated write) falls back to a fresh
    build — the cache can only ever make the bench faster, never wrong:
    topology is seed-determined, so cached and rebuilt graphs are
    identical arrays. Every fallback is REPORTED: a structured
    ``bench_cache_miss`` warning event on stderr plus a
    ``bench_cache_miss_total{reason=missing|corrupt|disabled}`` counter —
    a driver round quietly paying a 49 s rebuild is a diagnosis, not noise.
    """
    from p2pnetwork_tpu.sim import layoutcache

    misses = telemetry.default_registry().counter(
        "bench_cache_miss_total",
        "Graph-cache misses by cause; every miss costs a full rebuild.",
        ("reason",))

    def on_miss(reason, path, error):
        misses.labels(reason=reason).inc()
        data = {"reason": reason, "graph": name}
        if reason != "disabled":
            data["path"] = path
        if error is not None:
            data["error"] = error
        _warn_event("bench_cache_miss", **data)

    return layoutcache.cached_graph(
        name, build, cache_dir=_cache_dir(),
        extra_sources=(os.path.join(_HERE, "bench.py"),),
        enabled=os.environ.get("BENCH_CACHE", "1") != "0",
        on_miss=on_miss,
        log=lambda msg: print(f"# {msg}", file=sys.stderr, flush=True))


# --------------------------------------------------------- supervised stages

def _supervise_dir(stage: str) -> str:
    """Checkpoint-store directory of a stage's supervised pass. Parent and
    child compute the same path from the same env (stdlib-only — the
    parent reads the manifest without importing jax)."""
    base = os.environ.get("BENCH_SUPERVISE_DIR", _cache_dir())
    return os.path.join(base, f"supervise_{stage}")


def _supervised_pass(stage: str, g, *, target: float, max_rounds: int):
    """Run the stage's workload once under ``SupervisedRun`` before the
    timed contest: chunked, watchdog-guarded, auto-checkpointed into
    ``_supervise_dir(stage)``.

    This is the crash-evidence pass: a tunnel that wedges anywhere in the
    stage after it leaves behind a resumable checkpoint trail plus a
    manifest the PARENT can read (``_partial_stage_record``), so the
    driver gets rounds-completed and a checkpoint path instead of a bare
    null. The pass resumes its own previous trail (a re-run after a
    mid-pass kill continues, bit-identically, rather than restarting),
    and its summary lands in the stage telemetry. A failure here must not
    sink the bench — it degrades to a structured warning.

    BENCH_SUPERVISE_KILL_AT_ROUND (test seam) SIGKILLs the stage child at
    the first chunk boundary at or past that round — the deterministic
    stand-in for a mid-run preemption the partial-record tests drive."""
    import jax

    from p2pnetwork_tpu.models.flood import Flood
    from p2pnetwork_tpu.supervise import SupervisedRun

    chunk = int(os.environ.get("BENCH_SUPERVISE_CHUNK", "8"))
    deadline = float(os.environ.get("BENCH_SUPERVISE_DEADLINE_S", "300"))
    kill_at = int(os.environ.get("BENCH_SUPERVISE_KILL_AT_ROUND", "0"))

    def on_stall(dog):
        telemetry.default_registry().counter(
            "bench_supervised_stalls_total",
            "Watchdog stalls observed by bench supervised passes.",
            ("stage",)).labels(stage).inc()
        _warn_event("bench_supervised_stall", stage=stage,
                    stalled_s=round(dog.last_stall_s, 1),
                    deadline_s=dog.deadline_s)

    def on_chunk(run, info):
        if kill_at and info["round"] >= kill_at:
            import signal

            os.kill(os.getpid(), signal.SIGKILL)

    try:
        run = SupervisedRun(
            g, Flood(source=0), _supervise_dir(stage), chunk_rounds=chunk,
            deadline_s=deadline, on_stall=on_stall, on_chunk=on_chunk)
        _, summary = run.run_until_coverage(
            jax.random.key(0), coverage_target=target, max_rounds=max_rounds)
        print(f"# {stage}: supervised pass rounds={summary['rounds']} "
              f"coverage={summary.get('coverage', 0):.4f} "
              f"checkpoints={summary['checkpoints']} "
              f"resumed_from={summary['resumed_from']}",
              file=sys.stderr, flush=True)
        return {k: summary[k] for k in
                ("rounds", "chunks", "checkpoints", "resumed_from", "stalls")}
    except Exception as e:
        _warn_event("bench_supervised_pass_failed", stage=stage,
                    error=f"{type(e).__name__}: {e}")
        return {"error": f"{type(e).__name__}: {e}"}


def _partial_stage_record(stage: str, err: str, since: float = 0.0):
    """A dead measuring stage is not a dropped stage: when its supervised
    pass left a checkpoint trail, publish a partial structured record —
    tagged ``"backend": "resumed"`` with rounds-completed and the
    checkpoint path — plus a partial BENCH_TELEMETRY artifact, instead of
    a bare error. Stdlib-only: runs in the parent, which never imports
    jax. Returns the partial dict, or None when there is no trail.

    ``since`` (epoch seconds): trails whose manifest predates it are
    ignored — a stage that died before its supervised pass even started
    must not republish a PREVIOUS round's leftover trail as if it were
    this run's progress (bench_cache/ persists across driver rounds)."""
    sdir = _supervise_dir(stage)
    try:
        manifest = os.path.join(sdir, "manifest.json")
        # 2 s slack: coarse filesystem mtime granularity must not gate out
        # a trail the child genuinely wrote this attempt (stale trails are
        # minutes-to-days older, far outside the slack).
        if os.path.getmtime(manifest) < since - 2.0:
            return None
        with open(manifest, encoding="utf-8") as f:
            doc = json.load(f)
        latest = (doc.get("entries") or [])[-1]
        partial = {
            "backend": "resumed",
            "rounds_completed": int(latest["round"]),
            "checkpoint_path": os.path.join(sdir, latest["file"]),
            "error": err,
        }
    except Exception:
        return None
    artifact = {"schema": "bench-telemetry-v1", "stage": stage,
                "partial": True, **partial}
    path = _telemetry_path(stage)
    try:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(artifact, f, indent=1)
    except Exception as e:
        _warn_event("bench_telemetry_write_failed", path=path,
                    error=f"{type(e).__name__}: {e}")
    _warn_event("bench_stage_resumable", stage=stage, **partial)
    return partial


def time_batch_flood(graph, *, B: int, target: float, max_rounds: int,
                     reps: int = None, seq_sample: int = 4):
    """The batched message plane's bench column: advance ``B`` concurrent
    floods (random distinct-ish sources, seeded) through ONE compiled
    program per round (`engine.run_batch_until_coverage`), and price the
    same B messages as SEQUENTIAL single-message engine runs from a
    measured sample of ``seq_sample`` of them — the aggregate-throughput
    ratio (sequential-estimate / batched wall) is the number ROADMAP item
    2a targets (>= 20x at B=1024 on the 100k-node class). Returns the
    column dict BENCH_TELEMETRY.json publishes, ``batch_completion_
    rounds_p99`` included."""
    import jax
    import numpy as np

    from p2pnetwork_tpu.models.flood import Flood
    from p2pnetwork_tpu.models.messagebatch import BatchFlood
    from p2pnetwork_tpu.sim import engine

    if reps is None:
        reps = int(os.environ.get("BENCH_REPS", "5"))
    rng = np.random.default_rng(0)
    n_live = graph.n_nodes
    sources = rng.integers(0, n_live, size=B).astype(np.int32)
    proto = BatchFlood(method="auto")
    key = jax.random.key(0)

    def once():
        batch = proto.init(graph, sources, coverage_target=target)
        return engine.run_batch_until_coverage(
            graph, proto, batch, key, max_rounds=max_rounds)

    t0 = time.perf_counter()
    _, out = once()  # compile + warm up
    warmup_s = time.perf_counter() - t0
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        _, out = once()
        times.append(time.perf_counter() - t0)
    batch_s = min(times)

    # Sequential baseline: a seeded sample of the SAME messages run one
    # at a time through the single-message engine (what production pays
    # today), extrapolated to B — measuring all B sequentially would
    # take B x the batched run's win, which is the point. Each sampled
    # source runs once UNTIMED first: Flood(source) is a static jit arg,
    # so a cold run carries a per-source recompile — charging compile
    # time to the baseline would flatter the ratio.
    seq = []
    for s in sources[:max(seq_sample, 1)]:
        proto_s = Flood(source=int(s))
        engine.run_until_coverage(graph, proto_s, key,
                                  coverage_target=target,
                                  max_rounds=max_rounds)
        t0 = time.perf_counter()
        _, single = engine.run_until_coverage(
            graph, proto_s, key, coverage_target=target,
            max_rounds=max_rounds)
        seq.append(time.perf_counter() - t0)
        del single
    seq_per_run = sum(seq) / len(seq)
    seq_est = seq_per_run * B
    lane_rounds = int(np.sum(out["lane_rounds"]))
    return {
        "B": int(B),
        "n_nodes": graph.n_nodes,
        "best_s": round(batch_s, 6),
        "warmup_s": round(warmup_s, 4),
        "reps": reps,
        "rounds": int(out["rounds"]),
        "completed": int(out["completed"]),
        "active_lanes_end": int(out["active_lanes"]),
        "messages": int(out["messages"]),
        "batch_completion_rounds_p99": out.get("completion_rounds_p99"),
        "batch_completion_rounds_p50": out.get("completion_rounds_p50"),
        "batch_occupancy_mean": round(float(out["occupancy_mean"]), 6),
        "lane_rounds_per_s": round(lane_rounds / batch_s, 1),
        "msgs_per_sec": round(int(out["messages"]) / batch_s, 1),
        "seq_sample_runs": len(seq),
        "seq_per_run_s": round(seq_per_run, 6),
        "aggregate_speedup_vs_sequential": round(seq_est / batch_s, 2),
    }


# -------------------------------------------------------------------- stages

def _graph_spec_batch():
    """(n, cache name, build thunk) for the batched column's 100k-node WS
    class (ROADMAP 2a's target shape). Separate cache entry from the 1M
    headline graph — different n, different layout kwargs (the batched
    kernels ride the neighbor table + source CSR; no MXU layouts)."""
    from p2pnetwork_tpu.sim import graph as G

    n = int(os.environ.get("BENCH_BATCH_N", 100_000))
    return n, f"ws_n{n}_k10_p0.1_s0_batchcol", lambda: G.watts_strogatz(
        n, 10, 0.1, seed=0, source_csr=True)


def bench_batched():
    """The ``batched`` bench column: B concurrent floods through the
    lane-packed message plane on the 100k-node WS class. Failure must
    not sink the stage — callers catch and record the error."""
    B = int(os.environ.get("BENCH_BATCH_B", 1024))
    _, name, build = _graph_spec_batch()
    g, build_s, cached = _cached_graph(name, build)
    col = time_batch_flood(g, B=B, target=0.99, max_rounds=64)
    col["graph_build_s"] = round(build_s, 2)
    col["graph_cached"] = cached
    print(f"# batched B={B}: {col['best_s']*1000:.1f} ms/run, "
          f"rounds={col['rounds']}, p99={col['batch_completion_rounds_p99']}"
          f", aggregate x{col['aggregate_speedup_vs_sequential']} vs "
          f"sequential", file=sys.stderr, flush=True)
    return col


def time_durability(graph, *, cap: int, chunk: int, ticks: int,
                    rate: float, seed: int = 0,
                    policies=("off", "tick", "record"),
                    replay_records: int = 1000) -> dict:
    """The ``durability`` slice of the serving column (graftdur): what
    the write-ahead journal costs per fsync policy, and how fast a
    recovery scan replays.

    Drives the SAME seeded traffic schedule four times over a scratch
    checkpoint store — once unjournaled (the baseline: checkpoint
    cadence included, so the ratio isolates the JOURNAL, not the
    store), once per fsync policy — and reports
    ``overhead_ratio = journaled_wall / unjournaled_wall``. The
    slow-marked ratchet (tests/test_graftdur.py) pins fsync=tick at
    <= 1.10x. ``replay_scan_ms_per_1k`` times the torn-tail-tolerant
    segment scan (:func:`serve.journal.read_records`) over a
    synthetic ``replay_records``-record journal — the recovery-path
    latency a resume pays per 1k acknowledged intents."""
    import shutil
    import tempfile

    from p2pnetwork_tpu.serve import SimService, TrafficPattern
    from p2pnetwork_tpu.serve import drive as serve_drive
    from p2pnetwork_tpu.serve import generate as serve_generate
    from p2pnetwork_tpu.serve.journal import Journal, read_records

    pattern = TrafficPattern(ticks=ticks, rate=rate,
                             coverage_target=0.99)
    sched = serve_generate(pattern, graph.n_nodes, seed=seed)

    def one_drive(journal, fsync):
        d = tempfile.mkdtemp(prefix="bench_dur_")
        try:
            svc = SimService(graph, capacity=cap, queue_depth=cap,
                             chunk_rounds=chunk, seed=seed, store=d,
                             journal=journal, journal_fsync=fsync)
            t0 = time.perf_counter()
            out = serve_drive(svc, sched)
            wall = time.perf_counter() - t0
            stats = svc.stats()
            svc.close()
            return wall, out, stats
        finally:
            shutil.rmtree(d, ignore_errors=True)

    # Warm the engine program (and the store/sidecar write path) before
    # any timed drive: called standalone — e.g. by the ratchet test —
    # the first drive would otherwise charge one-time XLA compile to
    # whichever arm runs first and invert the ratio.
    one_drive(False, "tick")
    base_wall, base_out, _ = one_drive(False, "tick")
    col = {
        "ticks": ticks, "rate": rate,
        "offered": base_out["submitted"] + len(base_out["shed"]),
        "unjournaled_wall_s": round(base_wall, 4),
        "fsync": {},
    }
    for pol in policies:
        wall, _, stats = one_drive(True, pol)
        jstats = stats.get("journal") or {}
        col["fsync"][pol] = {
            "wall_s": round(wall, 4),
            "overhead_ratio": round(wall / max(base_wall, 1e-9), 4),
            "appends": jstats.get("appended"),
            "fsyncs": jstats.get("fsyncs"),
        }
    jd = tempfile.mkdtemp(prefix="bench_dur_replay_")
    try:
        j = Journal(jd, fsync="off")
        for i in range(int(replay_records)):
            j.append("submit", ticket=f"t{i:08d}", source=i % 1024,
                     tenant="default", round=i, tick=i // 8)
        j.close()
        t0 = time.perf_counter()
        records, corrupt = read_records(jd)
        scan_s = time.perf_counter() - t0
        assert len(records) == int(replay_records) and corrupt == 0
        col["replay_scan_ms_per_1k"] = round(
            scan_s * 1000.0 * 1000.0 / max(int(replay_records), 1), 3)
    finally:
        shutil.rmtree(jd, ignore_errors=True)
    return col


def bench_serving():
    """The ``serving`` bench column: seeded open-loop traffic
    (serve/traffic.py — Poisson arrivals, hot-key skew, diurnal bursts)
    through the admission-controlled SimService on the batched column's
    100k-node WS class, driven synchronously (deterministic). Publishes
    the serving-SLO numbers ROADMAP item 2 asks for: sustained lanes/s
    (completed tickets over the drive wall), submit→completion p50/p99
    in engine rounds (queue wait included), peak concurrent lanes, and
    the shed rate of the structured load-shedding path. Env seams:
    BENCH_SERVE_CAP (lane capacity, default 1024), BENCH_SERVE_TICKS,
    BENCH_SERVE_RATE (arrivals/tick; default oversubscribes capacity so
    the queue and shed path engage), BENCH_SERVE_CHUNK (engine rounds
    per tick). Failure must not sink the stage — callers catch and
    record the error."""
    from p2pnetwork_tpu.serve import SimService, TrafficPattern
    from p2pnetwork_tpu.serve import drive as serve_drive
    from p2pnetwork_tpu.serve import generate as serve_generate

    cap = int(os.environ.get("BENCH_SERVE_CAP", 1024))
    ticks = int(os.environ.get("BENCH_SERVE_TICKS", 16))
    rate = float(os.environ.get("BENCH_SERVE_RATE", cap / 3.0))
    chunk = int(os.environ.get("BENCH_SERVE_CHUNK", 4))
    _, name, build = _graph_spec_batch()
    g, build_s, cached = _cached_graph(name, build)
    pattern = TrafficPattern(
        ticks=ticks, rate=rate, hot_fraction=0.5, hot_keys=32,
        diurnal_amplitude=0.3, diurnal_period=max(ticks / 2.0, 1.0),
        burst_prob=0.125, burst_mult=3.0, coverage_target=0.99)
    sched = serve_generate(pattern, g.n_nodes, seed=0)
    # Warm the (capacity, chunk_rounds) engine program on a scratch
    # service first — the batched column warms up the same way; a cold
    # drive would charge one-time XLA compile to the SLO headline.
    warm = SimService(g, capacity=cap, queue_depth=cap, chunk_rounds=chunk,
                      seed=0)
    warm.submit(0)
    warm.tick()
    warm.close()
    svc = SimService(g, capacity=cap, queue_depth=cap, chunk_rounds=chunk,
                     seed=0)
    t0 = time.perf_counter()
    out = serve_drive(svc, sched)
    wall = time.perf_counter() - t0
    stats = svc.stats()
    offered = out["submitted"] + len(out["shed"])
    col = {
        "capacity": svc.capacity,
        "n_nodes": g.n_nodes,
        "ticks": ticks + out["drain_ticks"],
        "chunk_rounds": chunk,
        "wall_s": round(wall, 4),
        "offered": offered,
        "submitted": out["submitted"],
        "completed": out["completed"],
        "shed": len(out["shed"]),
        "shed_rate": round(len(out["shed"]) / max(offered, 1), 4),
        "peak_concurrent_lanes": out["peak_concurrent_lanes"],
        "executed_rounds": out["executed_rounds"],
        "sustained_lanes_per_s": round(out["completed"] / wall, 1),
        "submit_to_completion_rounds_p50":
            stats.get("completion_rounds_p50"),
        "submit_to_completion_rounds_p99":
            stats.get("completion_rounds_p99"),
        "graph_build_s": round(build_s, 2),
        "graph_cached": cached,
        # graftsight tick-phase profile: where the driven ticks spent
        # their wall (retire/admit/dispatch/harvest/checkpoint) — the
        # same document /dashboard publishes live.
        "tick_phases": svc.tick_phases(),
    }
    print(f"# serving cap={svc.capacity}: {col['sustained_lanes_per_s']} "
          f"lanes/s sustained, peak {col['peak_concurrent_lanes']} "
          f"concurrent, p99={col['submit_to_completion_rounds_p99']} "
          f"rounds, shed_rate={col['shed_rate']}",
          file=sys.stderr, flush=True)
    # graftdur durability slice: journal overhead per fsync policy +
    # recovery-scan latency, on a reduced drive (BENCH_DUR=0 disables).
    if os.environ.get("BENCH_DUR", "1") != "0":
        dur_ticks = int(os.environ.get("BENCH_DUR_TICKS", 8))
        dur_rate = float(os.environ.get("BENCH_DUR_RATE", cap / 8.0))
        try:
            col["durability"] = time_durability(
                g, cap=cap, chunk=chunk, ticks=dur_ticks,
                rate=dur_rate, seed=0)
            tick_ratio = \
                col["durability"]["fsync"]["tick"]["overhead_ratio"]
            print(f"# durability: fsync=tick x{tick_ratio} vs "
                  f"unjournaled, replay "
                  f"{col['durability']['replay_scan_ms_per_1k']} "
                  f"ms/1k records", file=sys.stderr, flush=True)
        except Exception as e:
            col["durability"] = {"error": f"{type(e).__name__}: {e}"}
            print(f"# durability slice failed: {type(e).__name__}: {e}",
                  file=sys.stderr, flush=True)
    return col


def _graph_spec_query_dht():
    """(n, cache name, build thunk) for the query column's DHT overlay:
    a chord graph — the structured topology whose fingers the greedy
    lookup lanes actually chase (a lookup on the WS class would mostly
    measure stalls)."""
    from p2pnetwork_tpu.sim import graph as G

    n = int(os.environ.get("BENCH_QUERY_DHT_N", 100_000))
    return n, f"chord_n{n}_querycol", lambda: G.chord(n)


def time_query_family(graph, proto, make_batch, make_single, *, K: int,
                      max_rounds: int = 256, reps: int = None,
                      seq_sample: int = 3) -> dict:
    """One query family's bench row: run the K-lane batch through
    ``engine.run_queries_until_done`` (one compiled program per round)
    and price the same K queries as WARM sequential capacity-1 runs of
    the SAME family — one query per engine call, what a serving loop
    without lane batching would pay — extrapolated from ``seq_sample``
    measured runs. ``make_batch()`` / ``make_single(i)`` build the
    admitted batches (each run re-admits, so donation invalidating the
    carry between reps is fine)."""
    import jax

    from p2pnetwork_tpu.sim import engine

    if reps is None:
        reps = int(os.environ.get("BENCH_REPS", "5"))
    key = jax.random.key(0)

    def once():
        return engine.run_queries_until_done(
            graph, proto, make_batch(), key, max_rounds=max_rounds)

    t0 = time.perf_counter()
    _, out = once()  # compile + warm up
    warmup_s = time.perf_counter() - t0
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        _, out = once()
        times.append(time.perf_counter() - t0)
    batch_s = min(times)

    # Warm the capacity-1 program once untimed (its own compile), then
    # measure the sequential sample (clamped to K — a tiny lane-count
    # knob must shrink the sample, not index past the query list).
    engine.run_queries_until_done(graph, proto, make_single(0), key,
                                  max_rounds=max_rounds)
    seq = []
    for i in range(max(min(seq_sample, int(K)), 1)):
        t0 = time.perf_counter()
        engine.run_queries_until_done(graph, proto, make_single(i), key,
                                      max_rounds=max_rounds)
        seq.append(time.perf_counter() - t0)
    seq_per_run = sum(seq) / len(seq)
    return {
        "K": int(K),
        "n_nodes": graph.n_nodes,
        "best_s": round(batch_s, 6),
        "warmup_s": round(warmup_s, 4),
        "reps": reps,
        "rounds": int(out["rounds"]),
        "completed": int(out["completed"]),
        "active_lanes_end": int(out["active_lanes"]),
        "messages": int(out["messages"]),
        "completion_rounds_p50": out.get("completion_rounds_p50"),
        "completion_rounds_p99": out.get("completion_rounds_p99"),
        "lanes_per_s": round(int(out["completed"]) / batch_s, 1),
        "seq_sample_runs": len(seq),
        "seq_per_run_s": round(seq_per_run, 6),
        "aggregate_speedup_vs_sequential": round(
            seq_per_run * K / batch_s, 2),
    }


def bench_queries():
    """The ``queries`` bench column (ROADMAP item 3): the three
    non-boolean batched query families — min-plus route lookups and
    push-sum aggregations on the batched column's 100k-node WS class,
    DHT greedy lookups on a 100k-node chord overlay — each publishing
    aggregate speedup vs warm sequential capacity-1 runs, lanes/s, and
    completion-rounds p50/p99. Env seams: BENCH_QUERY_K_MINPLUS /
    _PUSHSUM / _DHT (lane counts), BENCH_QUERY_DHT_N (chord size).
    Failure must not sink the stage — callers catch and record."""
    import numpy as np

    from p2pnetwork_tpu.models.querybatch import (DhtLookups,
                                                  MinPlusQueries,
                                                  PushSumQueries)

    rng = np.random.default_rng(0)
    col = {}
    _, name, build = _graph_spec_batch()
    g, build_s, cached = _cached_graph(name, build)
    col["graph_build_s"] = round(build_s, 2)
    col["graph_cached"] = cached

    k_mp = int(os.environ.get("BENCH_QUERY_K_MINPLUS", 64))
    mp = MinPlusQueries(method="auto")
    srcs = rng.integers(0, g.n_nodes, k_mp).astype(np.int32)
    tgts = rng.integers(0, g.n_nodes, k_mp).astype(np.int32)
    col["minplus"] = time_query_family(
        g, mp,
        lambda: mp.init(g, srcs, tgts),
        lambda i: mp.init(g, srcs[i:i + 1], tgts[i:i + 1]),
        K=k_mp)

    k_ps = int(os.environ.get("BENCH_QUERY_K_PUSHSUM", 32))
    ps = PushSumQueries(method="auto")
    seeds = (np.arange(k_ps) * 7 + 1).astype(np.int32)
    col["pushsum"] = time_query_family(
        g, ps,
        lambda: ps.init(g, seeds, threshold=1e-4),
        lambda i: ps.init(g, seeds[i:i + 1], threshold=1e-4),
        K=k_ps, max_rounds=512)

    k_dht = int(os.environ.get("BENCH_QUERY_K_DHT", 2048))
    _, dname, dbuild = _graph_spec_query_dht()
    gd, dbuild_s, dcached = _cached_graph(dname, dbuild)
    dht = DhtLookups(metric="ring")
    orgs = rng.integers(0, gd.n_nodes, k_dht).astype(np.int32)
    keys = rng.integers(0, gd.n_nodes, k_dht).astype(np.int32)
    col["dht"] = time_query_family(
        gd, dht,
        lambda: dht.init(gd, orgs, keys),
        lambda i: dht.init(gd, orgs[i:i + 1], keys[i:i + 1]),
        K=k_dht, max_rounds=128)
    col["dht"]["graph_build_s"] = round(dbuild_s, 2)
    col["dht"]["graph_cached"] = dcached

    for fam in ("minplus", "dht", "pushsum"):
        f = col[fam]
        print(f"# queries {fam} K={f['K']}: {f['best_s']*1000:.1f} ms/run"
              f", rounds={f['rounds']}, p99={f['completion_rounds_p99']},"
              f" aggregate x{f['aggregate_speedup_vs_sequential']} vs "
              f"sequential", file=sys.stderr, flush=True)
    return col


def _graph_spec_multichip():
    """(n, cache name, build thunk) for the ``multichip`` column's ring
    class: plain segment-bucket layout — the ring pass carries its own
    edge-bucket representation (parallel/sharded.py), so the single-chip
    tables/MXU layouts would be dead weight in the cache entry."""
    from p2pnetwork_tpu.sim import graph as G

    n = int(os.environ.get("BENCH_MULTICHIP_N", 65_536))
    return n, f"ws_n{n}_k10_p0.1_s0_ring", lambda: G.watts_strogatz(
        n, 10, 0.1, seed=0)


def bench_multichip():
    """The ``multichip`` bench column: the ring-sharded run-to-coverage
    flood over every visible device (the promoted Makefile
    ``dryrun_multichip``, measured and published instead of side-channel
    MULTICHIP_r*.json files) — multi-chip wall-clock, the scaling ratio
    vs a single-chip engine run of the SAME graph on the SAME backend,
    and the per-round ICI byte estimates of BOTH halo-exchange backends
    from the commviz comm census (the pallas ring-DMA traffic is censused
    like its ppermute twin — a Pallas-comm program must never read as
    zero ICI bytes). On CPU this is the dryrun-backed record (8 virtual
    devices); near-linear scaling is the on-device target, not a CI gate
    — virtual-device "chips" share one socket, so the published ratio is
    honest about its backend."""
    import jax
    import jax.numpy as jnp

    from p2pnetwork_tpu.models.flood import Flood
    from p2pnetwork_tpu.parallel import auto, commviz
    from p2pnetwork_tpu.parallel import mesh as M
    from p2pnetwork_tpu.parallel import sharded
    from p2pnetwork_tpu.sim import engine

    n_devices = min(8, len(jax.devices()))
    if n_devices < 2:
        return {"skipped": f"need >= 2 devices, have {n_devices} "
                           "(set XLA_FLAGS=--xla_force_host_platform_"
                           "device_count=8 JAX_PLATFORMS=cpu)"}
    n, name, build = _graph_spec_multichip()
    g, build_s, cached = _cached_graph(name, build)
    mesh = M.ring_mesh(n_devices)
    sg = sharded.shard_graph(g, mesh)
    comm = auto.resolve_comm(os.environ.get("BENCH_MULTICHIP_COMM", "auto"))
    reps = int(os.environ.get("BENCH_REPS", "3"))
    target, max_rounds = 0.99, 64

    def once():
        _, out = sharded.flood_until_coverage(
            sg, mesh, source=0, coverage_target=target,
            max_rounds=max_rounds, comm=comm)
        return out  # summary transfer = the honest sync point

    t0 = time.perf_counter()
    out = once()
    warmup_s = time.perf_counter() - t0
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = once()
        times.append(time.perf_counter() - t0)
    multi_s = min(times)

    # Single-chip baseline: the same flood on the same backend through
    # the engine loop — the ratio's denominator runs in THIS process, so
    # backend and clock are held fixed.
    proto = Flood(source=0)
    engine.run_until_coverage(g, proto, jax.random.key(0),
                              coverage_target=target, max_rounds=max_rounds)
    single_times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        _, sout = engine.run_until_coverage(
            g, proto, jax.random.key(0), coverage_target=target,
            max_rounds=max_rounds)
        single_times.append(time.perf_counter() - t0)
    single_s = min(single_times)

    # Per-round ICI bytes per halo backend: static comm census of the
    # actual compiled-shape program, scan-trip-weighted — all S-1 hops
    # of the round body's ring pass are priced; the while loop's dynamic
    # trip count is what the measured `rounds` multiplies back in.
    seen0, frontier0 = sharded.init_state(sg, proto, None)
    ici = {}
    for backend in sharded.COMM_BACKENDS:
        fn = sharded._flood_cov_fn(mesh, mesh.axis_names[0], sg.n_shards,
                                   sg.block, max_rounds, sg.diag_pieces,
                                   sg.mxu_block, backend)
        args = (jnp.float32(target), sg.bkt_src, sg.bkt_dst, sg.bkt_mask,
                *sharded._dyn_or_empty(sg), *sharded._mxu_or_empty(sg),
                sharded._diag_masks_or_empty(sg), sg.node_mask,
                sg.out_degree, seen0, frontier0)
        ici[backend] = {
            "per_round_bytes": commviz.ici_bytes_estimate(fn, args,
                                                          n_devices),
            "census": commviz.jaxpr_comm_census(fn, args, n_devices),
        }
    rounds = int(out["rounds"])
    col = {
        "n_nodes": n,
        "n_edges": g.n_edges,
        "n_devices": n_devices,
        "platform": jax.devices()[0].platform,
        "comm": comm,
        "best_s": round(multi_s, 6),
        "warmup_s": round(warmup_s, 4),
        "reps": reps,
        "rounds": rounds,
        "coverage": round(float(out["coverage"]), 5),
        "messages": int(out["messages"]),
        "single_chip_best_s": round(single_s, 6),
        "scaling_ratio": round(single_s / multi_s, 3),
        "per_round_ici_bytes": {b: ici[b]["per_round_bytes"] for b in ici},
        "ici_bytes_total_est": ici[comm]["per_round_bytes"] * rounds,
        "ici_census": {b: ici[b]["census"] for b in ici},
        "graph_build_s": round(build_s, 2),
        "graph_cached": cached,
    }
    print(f"# multichip {n_devices}dev comm={comm}: "
          f"{multi_s*1000:.1f} ms/run vs single {single_s*1000:.1f} ms "
          f"(ratio {col['scaling_ratio']}), "
          f"ICI/round {col['per_round_ici_bytes']}",
          file=sys.stderr, flush=True)
    return col


def _multichip_in_child():
    """Run the multichip column in its own child process — the measuring
    stage may sit on a single-device backend (one TPU chip, plain CPU),
    so the child gets the 8-device virtual CPU platform whenever the
    current process cannot see >= 2 devices. Bounded by its own timeout;
    failure degrades to an error-carrying column, never a sunk stage."""
    import jax

    timeout = int(os.environ.get("BENCH_MULTICHIP_TIMEOUT_S", "420"))
    extra = None
    if len(jax.devices()) < 2:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            flags = (flags + " --xla_force_host_platform_device_count=8"
                     ).strip()
        extra = {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": flags}
    return _stage_in_child("multichip", timeout, extra_env=extra)


def _graph_spec_1m():
    """(cache name, build thunk) for the 1M config — one definition shared
    by the measuring stage and ``--stage prebuild``, so the cache they
    key on cannot drift. BENCH_N_* shrink the configs so the
    orchestration is testable on CPU in seconds (tests/test_bench.py);
    the driver runs the defaults."""
    from p2pnetwork_tpu.sim import graph as G

    n = int(os.environ.get("BENCH_N_1M", 1_000_000))
    return n, f"ws_n{n}_k10_p0.1_s0", lambda: G.watts_strogatz(
        n, 10, 0.1, seed=0, blocked=True, hybrid=True, source_csr=True)


def _graph_spec_10m():
    from p2pnetwork_tpu.sim import graph as G

    n = int(os.environ.get("BENCH_N_10M", 10_000_000))
    return n, f"ws_n{n}_k10_p0.1_s0_notable", lambda: G.watts_strogatz(
        n, 10, 0.1, seed=0, hybrid=True, build_neighbor_table=False,
        source_csr=True)


def bench_1m(record):
    """Fills ``record`` (the headline JSON, format pinned by the driver)
    and returns the per-stage telemetry dict BENCH_TELEMETRY.json carries."""
    import jax

    from p2pnetwork_tpu.sim import graph as G

    n, name, build = _graph_spec_1m()
    target = 0.99
    g, build_s, cached = _cached_graph(name, build)
    # Per-phase attribution of where the build seconds went (dedup/sort/
    # tables/CSR/layouts/reorder) — empty on a cache hit, which built
    # nothing.
    build_phases = {} if cached else G.last_build_phases()
    # Crash-evidence pass FIRST: everything after this point wedging still
    # leaves a resumable checkpoint trail + manifest for the parent.
    supervised = _supervised_pass("1m", g, target=target, max_rounds=64)

    methods = ["pallas", "hybrid", "adaptive-1024", "adaptive-2048",
               "frontier"]
    # BENCH_METHODS replaces the contest list — the cpu-fallback parent
    # pins it to paths that stay fast WITHOUT the TPU (pallas/hybrid drop
    # to the Pallas interpreter on CPU: orders of magnitude slower, which
    # would blow the stage timeout and null the record the fallback
    # exists to save). A method failing stays a caught per-method error.
    only = os.environ.get("BENCH_METHODS")
    if only:
        methods = [s.strip() for s in only.split(",") if s.strip()] or methods
    results = {}
    per_method = {}
    for m in methods:
        try:
            secs, out, timing = time_flood(
                g, m, target=target, max_rounds=64,
                occupancy_attribution=(m == "frontier"))
            results[m] = (secs, out)
            per_method[m] = {"best_s": round(secs, 6), **timing}
            print(f"# 1M {m}: {secs*1000:.1f} ms, rounds={int(out['rounds'])}, "
                  f"coverage={float(out['coverage']):.4f}, "
                  f"messages={int(out['messages'])}", file=sys.stderr, flush=True)
        except Exception as e:  # a path failing must not sink the bench
            per_method[m] = {"error": f"{type(e).__name__}: {e}"}
            print(f"# 1M {m}: failed: {type(e).__name__}: {e}",
                  file=sys.stderr, flush=True)

    if not results:
        raise RuntimeError("all 1M aggregation methods failed")

    # The batched message-plane column (ROADMAP 2a): B concurrent floods
    # per compiled program on the 100k-node class, with the aggregate
    # throughput ratio vs sequential single-message runs and the
    # completion-rounds p99. Its own try — a batched failure must not
    # sink the measured headline. BENCH_BATCH=0 disables (the
    # cpu-fallback parent does: B=1024 interpreted on CPU would eat the
    # stage timeout the fallback exists to respect).
    batched = {}
    if os.environ.get("BENCH_BATCH", "1") != "0":
        try:
            batched = bench_batched()
        except Exception as e:
            batched = {"error": f"{type(e).__name__}: {e}"}
            print(f"# batched column failed: {type(e).__name__}: {e}",
                  file=sys.stderr, flush=True)

    # The serving column (ROADMAP 2): seeded open-loop traffic through
    # the admission-controlled service on the batched class — sustained
    # lanes/s, submit→completion p50/p99, shed rate. Own try, same
    # failure isolation as the batched column. BENCH_SERVE=0 disables
    # (the cpu-fallback parent does: cap=1024 service ticks on the CPU
    # backend would eat the stage timeout).
    serving = {}
    if os.environ.get("BENCH_SERVE", "1") != "0":
        try:
            serving = bench_serving()
        except Exception as e:
            serving = {"error": f"{type(e).__name__}: {e}"}
            print(f"# serving column failed: {type(e).__name__}: {e}",
                  file=sys.stderr, flush=True)

    # The queries column (ROADMAP item 3): the three non-boolean batched
    # query families with their aggregate-vs-sequential ratios. Own try,
    # same failure isolation. BENCH_QUERIES=0 disables (the cpu-fallback
    # parent does: three 100k-node families would eat its timeout).
    queries = {}
    if os.environ.get("BENCH_QUERIES", "1") != "0":
        try:
            queries = bench_queries()
        except Exception as e:
            queries = {"error": f"{type(e).__name__}: {e}"}
            print(f"# queries column failed: {type(e).__name__}: {e}",
                  file=sys.stderr, flush=True)

    # The multichip column (the promoted dryrun_multichip): ring-sharded
    # flood over 8 devices — real chips when visible, the virtual CPU
    # mesh otherwise — in its own bounded child, so a wedged multi-device
    # path cannot sink the measured single-chip headline. BENCH_MULTICHIP
    # =0 disables.
    multichip = {}
    if os.environ.get("BENCH_MULTICHIP", "1") != "0":
        multichip = _multichip_in_child()
        if "error" in multichip:
            print(f"# multichip column failed: {multichip['error']}",
                  file=sys.stderr, flush=True)

    best_method = min(results, key=lambda m: results[m][0])
    secs, out = results[best_method]
    msgs = int(out["messages"])
    record.update({
        "value": round(secs, 6),
        "vs_baseline": round(1.0 / secs, 3),  # north-star target: 1 s
        "method": best_method,
        "platform": jax.devices()[0].platform,
        "rounds": int(out["rounds"]),
        "coverage": round(float(out["coverage"]), 5),
        "messages": msgs,
        "msgs_per_sec_per_chip": round(msgs / secs, 1),
        "graph_build_s": round(build_s, 2),
        "graph_cached": cached,
        "n_nodes": n,
        "n_edges": g.n_edges,
    })
    return {"graph_build_s": round(build_s, 4), "cache_hit": cached,
            "build_phases": build_phases,
            "supervised": supervised, "per_method": per_method,
            "batched": batched, "serving": serving, "queries": queries,
            "multichip": multichip}


def bench_10m():
    """The scale row: 10M nodes / ~100M directed edges on ONE chip."""
    from p2pnetwork_tpu.sim import graph as G

    n, name, build = _graph_spec_10m()
    g, build_s, cached = _cached_graph(name, build)
    build_phases = {} if cached else G.last_build_phases()
    supervised = _supervised_pass("10m", g, target=0.99, max_rounds=64)
    secs, out, timing = time_flood(g, "adaptive-2048", target=0.99,
                                   max_rounds=64, reps=3)
    msgs = int(out["messages"])
    print(f"# 10M adaptive-2048: {secs:.3f} s, rounds={int(out['rounds'])}, "
          f"coverage={float(out['coverage']):.4f}, messages={msgs}",
          file=sys.stderr, flush=True)
    return {
        "value_s": round(secs, 4),
        "method": "adaptive-2048",
        "rounds": int(out["rounds"]),
        "coverage": round(float(out["coverage"]), 5),
        "messages": msgs,
        "msgs_per_sec_per_chip": round(msgs / secs, 1),
        "graph_build_s": round(build_s, 1),
        "graph_cached": cached,
        "n_nodes": n,
        "n_edges": g.n_edges,
    }, {"graph_build_s": round(build_s, 4), "cache_hit": cached,
        "build_phases": build_phases, "supervised": supervised,
        "per_method": {"adaptive-2048": {"best_s": round(secs, 6), **timing}}}


def _telemetry_path(stage: str) -> str:
    base = os.environ.get("BENCH_TELEMETRY_DIR", _HERE)
    suffix = "" if stage == "1m" else f"_{stage.upper()}"
    return os.path.join(base, f"BENCH_TELEMETRY{suffix}.json")


def _write_stage_telemetry(stage: str, tel: dict, stage_wall_s: float) -> None:
    """The per-stage telemetry artifact: where the time and bytes of one
    measuring stage went — graph build vs cache, compile (jax.monitoring
    lowering hooks; warmup wall as the fallback when hooks are absent),
    measured run, device->host transfer — plus the full registry snapshot.
    ``graph_build_s`` / ``warmup_s`` / ``run_s`` are disjoint wall-clock
    attributions summing (with untracked host overhead) to
    ``stage_wall_s``; ``compile_s`` and ``transfer_s``/``transfer_bytes``
    are finer-grained attributions INSIDE the warmup/run phases, not
    additional siblings.
    Written next to the headline (BENCH_TELEMETRY.json for the 1M stage);
    failure to write must not sink a measured bench."""
    from p2pnetwork_tpu.telemetry import jaxhooks

    reg = telemetry.default_registry()
    compile_s = jaxhooks.compile_seconds(reg)
    per_method = {k: v for k, v in tel.get("per_method", {}).items()
                  if isinstance(v, dict)}
    warmup_s = sum(m.get("warmup_s", 0.0) for m in per_method.values())
    run_s = sum(m.get("measure_s", 0.0) for m in per_method.values())
    artifact = {
        "schema": "bench-telemetry-v1",
        "stage": stage,
        "stage_wall_s": round(stage_wall_s, 4),
        "build_phases": tel.get("build_phases", {}),
        "stages": {
            "graph_build_s": tel.get("graph_build_s", 0.0),
            "cache_hit": tel.get("cache_hit", False),
            "compile_s": round(compile_s if compile_s > 0 else warmup_s, 4),
            "compile_count": int(jaxhooks.compile_count(reg)),
            "warmup_s": round(warmup_s, 4),
            "run_s": round(run_s, 4),
            "transfer_s": round(reg.value("sim_transfer_seconds_total"), 6),
            "transfer_bytes": int(reg.value("sim_transfer_bytes_total")),
        },
        # Structured probe-failure diagnostics (the `# probe N: ...`
        # stderr lines, now artifact-resident): empty on clean rounds,
        # the outage story on wedged ones (_PROBE_LOG docstring).
        "probe_log": _probe_log_for_artifact(),
        "supervised": tel.get("supervised", {}),
        "per_method": tel.get("per_method", {}),
        # The batched message-plane column: B in-flight floods per
        # compiled program, aggregate-throughput ratio vs sequential
        # runs, batch_completion_rounds_p99 (empty for stages without
        # the column, error-carrying when it failed).
        "batched": tel.get("batched", {}),
        # The serving column: seeded open-loop traffic through the
        # admission-controlled SimService — sustained lanes/s,
        # submit→completion p50/p99 rounds, peak concurrent lanes, shed
        # rate (empty for stages without the column, error-carrying
        # when it failed).
        "serving": tel.get("serving", {}),
        # The queries column: the three non-boolean batched query
        # families (min-plus routing, DHT lookups, push-sum) — per-family
        # aggregate speedup vs warm sequential capacity-1 runs, lanes/s,
        # completion-rounds p50/p99 (empty for stages without the
        # column, error-carrying when it failed).
        "queries": tel.get("queries", {}),
        # The multichip ring column: multi-device run-to-coverage wall,
        # scaling ratio vs a single-chip run of the same graph, and the
        # per-round ICI byte estimates of both halo-exchange backends
        # (commviz comm census — Pallas ring DMAs priced like ppermute).
        "multichip": tel.get("multichip", {}),
        # The static cost model beside the measured numbers: graftaudit's
        # blessed flops/bytes per lowering for this stage's shape-class,
        # so drift between model and wall-clock is visible per artifact.
        "ir_cost_model": _ir_cost_slice(stage),
        # The graftmem slice: the static capacity plan for this stage's
        # node count (checked-in membudgets.json closed-form
        # coefficients — nothing is built or compiled) beside the live
        # allocator numbers (`device_memory_stats`), so planned-vs-
        # resident drift is visible per artifact.
        "memory": _memory_slice(stage),
        "metrics": reg.snapshot(),
    }
    path = _telemetry_path(stage)
    try:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(artifact, f, indent=1)
        print(f"# stage {stage}: telemetry written to {path}",
              file=sys.stderr, flush=True)
    except Exception as e:
        _warn_event("bench_telemetry_write_failed", path=path,
                    error=f"{type(e).__name__}: {e}")


def _ir_cost_slice(stage: str) -> dict:
    """The graftaudit cost-table slice for this stage — flops/bytes (and
    the collective census) per lowering on the stage's shape-class, read
    from the checked-in analysis/ir/budgets.json. Both measuring stages
    run the WS family, so the canonical ``ws1k`` class is the static
    model the measured per-method wall-clocks are compared against
    (cost_analysis prices the program; the graph scale multiplies both
    sides). Failure to load must not sink a measured bench."""
    try:
        from p2pnetwork_tpu.analysis.ir import budgets as irb

        doc = irb.load_budgets()
        cls = "ws1k"
        entries = {name: rec for name, rec in
                   doc.get("entries", {}).items()
                   if name.endswith("@" + cls) and "error" not in rec}
        return {"shape_class": cls, "jaxlib": doc.get("jaxlib"),
                "tolerance": doc.get("tolerance"), "entries": entries}
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def _device_memory_stats() -> dict:
    """Per-device allocator occupancy at snapshot time
    (``device.memory_stats()``). Backends without allocator stats — the
    CPU backend returns None — record ``available: False`` with a
    structured warning, never a crash: the static plan beside it is the
    number the artifact is really for on such hosts."""
    out = {"available": False, "devices": []}
    try:
        import jax

        for d in jax.devices():
            stats = getattr(d, "memory_stats", lambda: None)()
            if not stats:
                out["devices"].append(
                    {"id": d.id, "platform": d.platform, "stats": None})
                continue
            out["available"] = True
            out["devices"].append(
                {"id": d.id, "platform": d.platform,
                 "stats": {k: int(v) for k, v in stats.items()
                           if isinstance(v, (int, float))}})
        if not out["available"]:
            _warn_event("bench_device_memory_stats_unavailable",
                        platform=jax.devices()[0].platform
                        if jax.devices() else "none")
    except Exception as e:
        _warn_event("bench_device_memory_stats_failed",
                    error=f"{type(e).__name__}: {e}")
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def _memory_slice(stage: str) -> dict:
    """The graftmem slice: capacity.plan at this stage's node count
    (the 1M headline plans the north-star 10k-lane shape) from the
    checked-in coefficients, beside the measured per-device allocator
    stats. Failure to plan must not sink a measured bench — a host
    without a blessed capacity model records the error and moves on."""
    nodes = {"1m": 1_000_000, "10m": 10_000_000}.get(stage, 1_000_000)
    out = {"device_memory_stats": _device_memory_stats()}
    try:
        from p2pnetwork_tpu.analysis.ir import capacity as irc

        p = irc.plan(nodes, lanes=10_016)
        out["plan"] = {k: p[k] for k in
                       ("entry", "n_nodes", "n_pad", "e_pad", "lanes",
                        "lane_words", "global_bytes",
                        "recommended_shards")}
    except Exception as e:
        out["plan"] = {"error": f"{type(e).__name__}: {e}"}
    return out


def _stage_compile_budget(stage: str) -> int:
    """Per-stage jit compile budget for retrace_guard. The 1M contest
    stage legitimately compiles several programs per method (engine loop
    variants, occupancy re-run); the 10M stage runs one method. Beyond
    the budget something is RE-tracing — shape churn, a fresh jit wrapper
    per call — which silently eats the wins the stage measures. Override
    with BENCH_COMPILE_BUDGET_1M / BENCH_COMPILE_BUDGET_10M."""
    defaults = {"1m": 64, "10m": 24}
    return int(os.environ.get(f"BENCH_COMPILE_BUDGET_{stage.upper()}",
                              defaults.get(stage, 64)))


def _on_stage_breach(guard) -> None:
    """retrace_guard breach handler: never sinks the bench — emits the
    structured warning plus the ``bench_recompile_total{stage}`` counter
    (the registry snapshot lands in BENCH_TELEMETRY.json; the headline
    record is untouched)."""
    telemetry.default_registry().counter(
        "bench_recompile_total",
        "Backend compiles beyond a bench stage's compile budget "
        "(retrace_guard breaches) — recompiles eating measured time.",
        ("stage",)).labels(guard.block).inc(guard.compiles - guard.budget)
    _warn_event("bench_recompile_budget_breach", stage=guard.block,
                compiles=guard.compiles, budget=guard.budget)


@contextlib.contextmanager
def _maybe_profile(stage: str):
    """Opt-in ``jax.profiler.trace`` bracket around a measuring stage
    (graftscope profiler wiring): BENCH_PROFILE_DIR=<dir> writes the
    XLA/TraceMe profile for stage ``<dir>/<stage>`` — load it in
    TensorBoard's profile plugin or Perfetto. Off by default (profiling
    is not free), and failure-tolerant both ways: an unavailable
    profiler degrades to a structured warning, never a failed bench."""
    pdir = os.environ.get("BENCH_PROFILE_DIR")
    if not pdir:
        yield
        return
    outdir = os.path.join(pdir, stage)
    try:
        import jax

        os.makedirs(outdir, exist_ok=True)
        jax.profiler.start_trace(outdir)
    except Exception as e:
        _warn_event("bench_profile_unavailable", stage=stage,
                    error=f"{type(e).__name__}: {e}")
        yield
        return
    try:
        yield
    finally:
        try:
            jax.profiler.stop_trace()
            print(f"# stage {stage}: profiler trace written to {outdir}",
                  file=sys.stderr, flush=True)
        except Exception as e:
            _warn_event("bench_profile_stop_failed", stage=stage,
                        error=f"{type(e).__name__}: {e}")


def _run_stage(stage: str) -> int:
    """Child-process entry (``--stage 1m|10m``): init the backend, run one
    stage, print ONE JSON line on stdout. Comments go to stderr, which the
    parent inherits straight through to the driver log."""
    try:
        from p2pnetwork_tpu.utils.jax_env import apply_platform_env

        apply_platform_env()
        from p2pnetwork_tpu.analysis import retrace_guard
        from p2pnetwork_tpu.telemetry import jaxhooks

        jaxhooks.install()  # compile accounting for the whole stage
        if stage == "1m":
            record = {}
            t0 = time.perf_counter()
            # The guard closes before the telemetry write, so a breach's
            # counter is already in the registry snapshot it publishes.
            with _maybe_profile("1m"), \
                    retrace_guard("1m", budget=_stage_compile_budget("1m"),
                                  on_breach=_on_stage_breach):
                tel = bench_1m(record)
            _write_stage_telemetry(stage, tel, time.perf_counter() - t0)
            print(json.dumps(record))
            return 0
        if stage == "10m":
            t0 = time.perf_counter()
            with _maybe_profile("10m"), \
                    retrace_guard("10m",
                                  budget=_stage_compile_budget("10m"),
                                  on_breach=_on_stage_breach):
                rec, tel = bench_10m()
            _write_stage_telemetry(stage, tel, time.perf_counter() - t0)
            print(json.dumps(rec))
            return 0
        if stage == "multichip":
            # The multichip column child: measures the ring-sharded flood
            # on this process's devices and prints the column JSON (the
            # 1m stage embeds it into BENCH_TELEMETRY.json).
            print(json.dumps(bench_multichip()))
            return 0
        if stage == "prebuild":
            # Populate the graph cache without measuring — run once on a
            # quiet host (any backend; builds are host-side) so a later
            # driver run inside a flaky-tunnel window only LOADS.
            for _, name, build in (_graph_spec_1m(), _graph_spec_10m()):
                _cached_graph(name, build)
            print(json.dumps({"prebuilt": True}))
            return 0
    except Exception as e:
        # The error must reach the driver's parsed record, not just the
        # stderr log: emit it as this stage's JSON line (the parent
        # forwards it) before exiting nonzero.
        traceback.print_exc(file=sys.stderr)
        print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
        return 1
    print(f"# unknown stage {stage!r}", file=sys.stderr)
    return 2


def _stage_in_child(stage: str, timeout_s: int, extra_env: dict = None):
    """Run ``--stage <stage>`` in a child under a hard timeout. Returns the
    stage's parsed JSON record, or ``{"error": ...}`` — never raises, never
    hangs: a tunnel wedging mid-measurement is a bounded, reported error.
    ``extra_env`` overlays the child's environment (the cpu-fallback path
    pins JAX_PLATFORMS=cpu there)."""
    cmd = [sys.executable, os.path.abspath(__file__), "--stage", stage]
    env = {**os.environ, **(extra_env or {})}
    if _PROBE_LOG:
        # The child writes the telemetry artifact; hand it the parent's
        # probe diagnostics so outage rounds are explained in-artifact.
        env["BENCH_PROBE_LOG"] = json.dumps(_PROBE_LOG)
    t0 = time.perf_counter()
    try:
        r = subprocess.run(cmd, stdout=subprocess.PIPE, timeout=timeout_s,
                           text=True, cwd=_HERE, env=env)
    except subprocess.TimeoutExpired:
        return {"error": f"stage {stage} exceeded {timeout_s}s "
                         f"(device tunnel wedged mid-run?)"}
    except Exception as e:
        return {"error": f"stage {stage} launcher failed: "
                         f"{type(e).__name__}: {e}"}
    dt = time.perf_counter() - t0
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    parsed = None
    if lines:
        try:
            parsed = json.loads(lines[-1])
        except ValueError:
            pass
    if r.returncode != 0:
        # A failing stage still emits an error-carrying JSON line
        # (_run_stage's handler) — prefer its actual cause over a bare
        # exit-code report.
        if isinstance(parsed, dict) and "error" in parsed:
            return {"error": f"stage {stage}: {parsed['error']}"}
        return {"error": f"stage {stage} exited rc={r.returncode} "
                         f"after {dt:.0f}s with "
                         f"{'no output' if not lines else lines[-1][-200:]}"}
    if parsed is None:
        return {"error": f"stage {stage} emitted unparseable output: "
                         f"{lines[-1][-200:] if lines else 'no output'}"}
    return parsed


# ----------------------------------------------------------- backend probing

#: Structured probe-failure diagnostics, in parent-process order. The
#: `# probe N: ... wedged` stderr comment lines were the ONLY trail the
#: BENCH_r03–r05 null rounds left — stdout-only, gone unless someone kept
#: the driver log. Every probe outcome now also lands here and rides into
#: the measuring child's BENCH_TELEMETRY artifact as ``probe_log``
#: (via the BENCH_PROBE_LOG env seam, _stage_in_child), so an outage
#: round is diagnosable from artifacts alone.
_PROBE_LOG: list = []


def _probe_log_for_artifact() -> list:
    """The probe log as the measuring CHILD sees it: the parent's
    _PROBE_LOG serialized through the BENCH_PROBE_LOG env seam (the
    parent probes, the child writes the artifact), merged with any
    probes this process ran itself."""
    entries = list(_PROBE_LOG)
    raw = os.environ.get("BENCH_PROBE_LOG")
    if raw:
        try:
            entries = list(json.loads(raw)) + entries
        except ValueError:
            entries = [{"error": "unparseable BENCH_PROBE_LOG",
                        "raw": raw[:200]}] + entries
    return entries


def _probe_backend_once(timeout_s: int):
    """Probe JAX backend init in a CHILD process. A wedged device tunnel
    hangs PJRT client creation while holding the GIL, so no in-process
    watchdog (signal.alarm included — verified) can fire; probing in a
    subprocess turns an unbounded hang into a bounded, reportable error.
    Returns None when healthy, else an error string."""
    probe = (
        "import sys; sys.path.insert(0, {!r}); "
        "from p2pnetwork_tpu.utils.jax_env import apply_platform_env; "
        "apply_platform_env(); import jax, jax.numpy as jnp; "
        "print(jax.devices()); "
        # Enumeration alone can succeed on a half-wedged tunnel: require a
        # real compile + execute + device->host round trip. Not an assert —
        # PYTHONOPTIMIZE would strip that and quietly weaken the probe.
        "v = int(jax.jit(lambda: jnp.sum(jnp.arange(8)))()); "
        "print(f'probe compute round-trip returned {{v}}, want 28', "
        "file=sys.stderr); "
        "raise SystemExit(0 if v == 28 else 1)"
        .format(_HERE)
    )
    try:
        r = subprocess.run([sys.executable, "-c", probe],
                           timeout=timeout_s, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return (f"JAX backend init hung for {timeout_s}s "
                f"(device tunnel wedged?)")
    if r.returncode != 0:
        return "backend probe failed: " + r.stderr.strip()[-300:]
    return None


def _backend_alive(window_s=None, probe_timeout_s=None, max_attempts=None):
    """Wait for the backend to come up — at most ``max_attempts`` probes
    (default 2, BENCH_PROBE_MAX_ATTEMPTS) within a ``window_s`` ceiling.

    The tunnel has wedged and then recovered on its own across past
    rounds, so ONE probe gives up too early; but unbounded retries are
    worse — BENCH_r05 spent its whole 40-minute window on 8 × 120 s
    wedged probes and published a null headline. The cap keeps the
    wedged-backend path to two probes (one retry after a short sleep —
    the transient-recovery case) and hands the rest of the window to the
    cpu-fallback measuring child in ``main``, which always produces a
    real record. Each attempt emits a heartbeat comment line so the
    driver log shows liveness; the window (BENCH_BACKEND_WINDOW_S) still
    bounds everything from above when the cap is raised. Returns None
    when healthy, else the last error string.

    Retry gaps come from the supervise plane's shared
    :class:`~p2pnetwork_tpu.supervise.heal.RetryPolicy` (graftquake):
    exponential backoff with SEEDED jitter instead of the old fixed
    60 s/1.5x ladder — when several benches restart against one
    recovering tunnel, their seeds (BENCH_PROBE_BACKOFF_SEED, default
    0) de-synchronize the retry storm, and the same seed replays the
    same delays. Every attempt's chosen backoff lands in the probe log
    (``backoff_s``), and the session closes with one ``policy_summary``
    entry — policy parameters, the full deterministic backoff schedule,
    and the outcome (clean / healed / gave_up) — so an outage round's
    timing is reconstructible from artifacts alone."""
    from p2pnetwork_tpu.supervise.heal import RetryPolicy  # jax-free

    if window_s is None:
        # 40 min ceiling: with the probe cap at 2 the wedged path spends
        # ~4-5 min here worst case; the window only matters when an
        # operator raises BENCH_PROBE_MAX_ATTEMPTS to wait out a tunnel.
        window_s = int(os.environ.get("BENCH_BACKEND_WINDOW_S", "2400"))
    if probe_timeout_s is None:
        probe_timeout_s = int(os.environ.get("BENCH_PROBE_TIMEOUT_S", "120"))
    if max_attempts is None:
        max_attempts = int(os.environ.get("BENCH_PROBE_MAX_ATTEMPTS", "2"))
    max_attempts = max(max_attempts, 1)
    policy = RetryPolicy(
        max_attempts=max_attempts,
        backoff_base_s=float(os.environ.get("BENCH_PROBE_BACKOFF_S", "60")),
        backoff_max_s=120.0, jitter=0.5,
        seed=int(os.environ.get("BENCH_PROBE_BACKOFF_SEED", "0")))
    def _summarize(outcome: str, attempts: int) -> None:
        # graftsight satellite: one policy-summary entry per probe
        # session — the policy's parameters, its full (deterministic)
        # backoff schedule, and how the session ended
        # (clean / healed / gave_up), so an outage round's retry timing
        # is reconstructible from the artifact without re-deriving the
        # seeded jitter.
        _PROBE_LOG.append({
            "policy_summary": True, "ts": time.time(),
            "outcome": outcome, "attempts": attempts,
            "max_attempts": policy.max_attempts,
            "backoff_base_s": policy.backoff_base_s,
            "backoff_max_s": policy.backoff_max_s,
            "jitter": policy.jitter, "seed": policy.seed,
            "backoff_schedule_s": [
                round(d, 3) for d in policy.delays(policy.max_attempts)],
        })

    deadline = time.monotonic() + window_s
    attempt = 0
    while True:
        attempt += 1
        err = _probe_backend_once(probe_timeout_s)
        if err is None:
            if attempt > 1:
                _PROBE_LOG.append({"attempt": attempt, "ts": time.time(),
                                   "recovered": True})
                print(f"# backend recovered on probe attempt {attempt}",
                      file=sys.stderr, flush=True)
                _summarize("healed", attempt)
            else:
                _summarize("clean", attempt)
            return None
        remaining = deadline - time.monotonic()
        backoff_s = policy.backoff_s(attempt)
        _PROBE_LOG.append({"attempt": attempt, "ts": time.time(),
                           "error": err,
                           "backoff_s": round(backoff_s, 3),
                           "window_remaining_s": round(max(remaining, 0), 1)})
        print(f"# probe {attempt}: {err}; backoff {backoff_s:.1f}s; "
              f"{max(remaining, 0):.0f}s left in window",
              file=sys.stderr, flush=True)
        if attempt >= max_attempts:
            _PROBE_LOG.append({"attempt": attempt, "ts": time.time(),
                               "gave_up": f"probe cap {max_attempts}"})
            _summarize("gave_up", attempt)
            return (f"{err} [gave up after {attempt} probes "
                    f"(cap {max_attempts}); handing off to fallback]")
        if remaining <= 0:
            _PROBE_LOG.append({"attempt": attempt, "ts": time.time(),
                               "gave_up": f"window {window_s}s"})
            _summarize("gave_up", attempt)
            return f"{err} [gave up after {attempt} probes over {window_s}s]"
        time.sleep(min(backoff_s, max(remaining, 1.0)))


def main():
    record = {
        "metric": "1M-node WS flood to 99% coverage (single chip)",
        "value": None,
        "unit": "s",
        "vs_baseline": 0.0,
    }
    # Provisional record FIRST: if the caller kills this process mid
    # probe-window (a driver budget shorter than the window), the last
    # stdout JSON line is still parseable instead of absent. Every later
    # print supersedes it.
    print(json.dumps({**record, "error": "killed while probing backend "
                      "(provisional record; superseded by later lines)"}),
          flush=True)
    stage_timeout = int(os.environ.get("BENCH_STAGE_TIMEOUT_S", "900"))
    err = _backend_alive()
    if err is not None:
        # The configured backend is gone for the whole window. A null
        # record wastes the round (BENCH_r05: 8 failed probes, 40 minutes,
        # nothing published) — measure the 1M stage on the CPU backend
        # instead and tag the record, so the driver gets a real number
        # plus the outage cause. Fewer reps (BENCH_REPS=2 default here):
        # CPU runs are minutes-not-ms and the record is a liveness
        # fallback, not the headline contest.
        print(f"# {err}", file=sys.stderr, flush=True)
        print("# falling back to a JAX_PLATFORMS=cpu measuring child "
              "(record tagged backend=cpu-fallback)",
              file=sys.stderr, flush=True)
        _warn_event("bench_backend_fallback", error=err)
        r1m = _stage_in_child("1m", stage_timeout, extra_env={
            "JAX_PLATFORMS": "cpu",
            "BENCH_REPS": os.environ.get("BENCH_REPS", "2"),
            # Only the XLA-native lowerings: pallas/hybrid interpret-mode
            # on CPU would eat the whole stage timeout at 1M nodes.
            "BENCH_METHODS": os.environ.get("BENCH_METHODS",
                                            "segment,frontier"),
            # B=1024 on the CPU backend is minutes of extra wall — the
            # fallback's job is a real headline within the timeout.
            "BENCH_BATCH": os.environ.get("BENCH_BATCH", "0"),
            # Same reasoning for the serving column's 1024-lane drive.
            "BENCH_SERVE": os.environ.get("BENCH_SERVE", "0"),
            # And the query column's three 100k-node families.
            "BENCH_QUERIES": os.environ.get("BENCH_QUERIES", "0"),
        })
        if "error" in r1m:
            record["error"] = f"{err}; cpu fallback also failed: {r1m['error']}"
            print(f"# {record['error']}", file=sys.stderr, flush=True)
            print(json.dumps(record))
            return 1
        record.update(r1m)
        record["backend"] = "cpu-fallback"
        record["backend_error"] = err
        record["scale_10M"] = {
            "skipped": "cpu-fallback (the 10M scale row runs on the real "
                       "chip only)"}
        print(json.dumps(record))
        return 0

    # Probe passed: supersede the provisional line so a kill from here on
    # is attributed to the measuring stage, not a tunnel outage that
    # never happened.
    print(json.dumps({**record, "error": "backend probe passed; killed "
                      "during measuring stage (provisional record; "
                      "superseded by later lines)"}), flush=True)
    t_1m = time.time()
    r1m = _stage_in_child("1m", stage_timeout)
    if "error" in r1m:
        # A mid-run wedge/preemption with a supervised checkpoint trail is
        # a PARTIAL stage, not a dropped one: publish the resumable-state
        # record (backend=resumed, rounds-completed, checkpoint path).
        partial = _partial_stage_record("1m", r1m["error"], since=t_1m)
        if partial is not None:
            record.update(partial)
            record["scale_10M"] = {
                "skipped": "1M stage died mid-run (partial resumable "
                           "record published)"}
            print(f"# 1m stage died; published partial resumable record "
                  f"(rounds_completed={partial['rounds_completed']})",
                  file=sys.stderr, flush=True)
            print(json.dumps(record))
            return 0
        record["error"] = r1m["error"]
        print(f"# {r1m['error']}", file=sys.stderr, flush=True)
        print(json.dumps(record))
        return 1
    record.update(r1m)
    # Emit the measured headline NOW: if the 10M stage's child is killed by
    # its timeout the merged line below still prints, but if this parent
    # itself dies (driver timeout, OOM-kill) the 1M number is already out.
    print(json.dumps(record), flush=True)

    t_10m = time.time()
    r10m = _stage_in_child("10m", stage_timeout)
    if "error" in r10m:
        partial = _partial_stage_record("10m", r10m["error"], since=t_10m)
        if partial is not None:
            r10m = partial
    record["scale_10M"] = r10m
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--stage":
        sys.exit(_run_stage(sys.argv[2]))
    sys.exit(main())
