"""North-star benchmark (BASELINE.json): 1M-node Watts–Strogatz single-source
flood to 99% coverage, one chip, whole run device-side (lax.while_loop — zero
host round-trips per round), plus the 10M-node scale config.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
``value`` is the wall-clock seconds of the best aggregation path at 1M;
``vs_baseline`` is (1 s north-star target) / value, so > 1 beats the target;
``scale_10M`` carries the 10M-node result (driver-verified scale row).

Every stage is wrapped: any failure — graph build included — emits an
error-carrying JSON record instead of dying with no evidence, and a 10M
failure cannot sink the 1M result.

Reference anchor: the reference implementation moves one message per peer per
10 ms poll tick per Python thread [ref: p2pnetwork/nodeconnection.py:220];
simulating this workload there would take hours — it publishes no numbers
(BASELINE.md), so the driver-set 1 s target is the baseline.
"""

import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from p2pnetwork_tpu.utils.jax_env import apply_platform_env  # noqa: E402

apply_platform_env()

import jax  # noqa: E402


def time_flood(graph, method: str, *, target: float, max_rounds: int, reps: int = 5):
    from p2pnetwork_tpu.models.adaptive_flood import AdaptiveFlood
    from p2pnetwork_tpu.models.flood import Flood
    from p2pnetwork_tpu.sim import engine

    if method.startswith("adaptive"):
        # "adaptive-<k>": frontier-sparse rounds under k, dense hybrid above
        # (models/adaptive_flood.py) — bit-identical results to Flood.
        k = int(method.split("-")[1])
        protocol = AdaptiveFlood(source=0, method="hybrid", k=k)
    else:
        protocol = Flood(source=0, method=method)
    key = jax.random.key(0)

    def once():
        # run_until_coverage itself blocks on a real device->host transfer
        # of the packed run summary (engine._unpack_summary) — the sync
        # that keeps these timings honest on tunneled backends, where
        # jax.block_until_ready can return before execution finishes.
        state, out = engine.run_until_coverage(
            graph, protocol, key, coverage_target=target, max_rounds=max_rounds
        )
        return out

    out = once()  # compile + warm up
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = once()
        times.append(time.perf_counter() - t0)
    return min(times), out


def bench_1m(record):
    from p2pnetwork_tpu.sim import graph as G

    n, k, target = 1_000_000, 10, 0.99
    t_build0 = time.perf_counter()
    g = G.watts_strogatz(n, k, 0.1, seed=0, blocked=True, hybrid=True,
                         source_csr=True)
    build_s = time.perf_counter() - t_build0

    methods = ["pallas", "hybrid", "adaptive-1024", "adaptive-2048"]
    results = {}
    for m in methods:
        try:
            secs, out = time_flood(g, m, target=target, max_rounds=64)
            results[m] = (secs, out)
            print(f"# 1M {m}: {secs*1000:.1f} ms, rounds={int(out['rounds'])}, "
                  f"coverage={float(out['coverage']):.4f}, "
                  f"messages={int(out['messages'])}", file=sys.stderr, flush=True)
        except Exception as e:  # a path failing must not sink the bench
            print(f"# 1M {m}: failed: {type(e).__name__}: {e}",
                  file=sys.stderr, flush=True)

    if not results:
        raise RuntimeError("all 1M aggregation methods failed")

    best_method = min(results, key=lambda m: results[m][0])
    secs, out = results[best_method]
    msgs = int(out["messages"])
    record.update({
        "value": round(secs, 6),
        "vs_baseline": round(1.0 / secs, 3),  # north-star target: 1 s
        "method": best_method,
        "platform": jax.devices()[0].platform,
        "rounds": int(out["rounds"]),
        "coverage": round(float(out["coverage"]), 5),
        "messages": msgs,
        "msgs_per_sec_per_chip": round(msgs / secs, 1),
        "graph_build_s": round(build_s, 2),
        "n_nodes": n,
        "n_edges": g.n_edges,
    })


def bench_10m():
    """The scale row: 10M nodes / ~100M directed edges on ONE chip."""
    from p2pnetwork_tpu.sim import graph as G

    n = 10_000_000
    t_build0 = time.perf_counter()
    g = G.watts_strogatz(n, 10, 0.1, seed=0, hybrid=True,
                         build_neighbor_table=False, source_csr=True)
    build_s = time.perf_counter() - t_build0
    print(f"# 10M graph built in {build_s:.1f}s ({g.n_edges} edges)",
          file=sys.stderr, flush=True)
    secs, out = time_flood(g, "adaptive-2048", target=0.99, max_rounds=64,
                           reps=3)
    msgs = int(out["messages"])
    print(f"# 10M adaptive-2048: {secs:.3f} s, rounds={int(out['rounds'])}, "
          f"coverage={float(out['coverage']):.4f}, messages={msgs}",
          file=sys.stderr, flush=True)
    return {
        "value_s": round(secs, 4),
        "method": "adaptive-2048",
        "rounds": int(out["rounds"]),
        "coverage": round(float(out["coverage"]), 5),
        "messages": msgs,
        "msgs_per_sec_per_chip": round(msgs / secs, 1),
        "graph_build_s": round(build_s, 1),
        "n_nodes": n,
        "n_edges": g.n_edges,
    }


def _probe_backend_once(timeout_s: int):
    """Probe JAX backend init in a CHILD process. A wedged device tunnel
    hangs PJRT client creation while holding the GIL, so no in-process
    watchdog (signal.alarm included — verified) can fire; probing in a
    subprocess turns an unbounded hang into a bounded, reportable error.
    Returns None when healthy, else an error string."""
    import subprocess

    probe = (
        "import sys; sys.path.insert(0, {!r}); "
        "from p2pnetwork_tpu.utils.jax_env import apply_platform_env; "
        "apply_platform_env(); import jax, jax.numpy as jnp; "
        "print(jax.devices()); "
        # Enumeration alone can succeed on a half-wedged tunnel: require a
        # real compile + execute + device->host round trip. Not an assert —
        # PYTHONOPTIMIZE would strip that and quietly weaken the probe.
        "v = int(jax.jit(lambda: jnp.sum(jnp.arange(8)))()); "
        "print(f'probe compute round-trip returned {{v}}, want 28', "
        "file=sys.stderr); "
        "raise SystemExit(0 if v == 28 else 1)"
        .format(os.path.dirname(os.path.abspath(__file__)))
    )
    try:
        r = subprocess.run([sys.executable, "-c", probe],
                           timeout=timeout_s, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return (f"JAX backend init hung for {timeout_s}s "
                f"(device tunnel wedged?)")
    if r.returncode != 0:
        return "backend probe failed: " + r.stderr.strip()[-300:]
    return None


def _backend_alive(window_s=None, probe_timeout_s=None):
    """Wait for the backend to come up, retrying across ``window_s`` seconds.

    The tunnel has wedged and then recovered on its own across past rounds;
    a single probe therefore gives up too early and forfeits the whole bench
    window. Instead: probe (bounded by ``probe_timeout_s``), and on failure
    sleep and retry until the window is spent, emitting a heartbeat comment
    line per attempt so the driver log shows liveness. The sleep backs off
    60 s -> 120 s. Override via BENCH_BACKEND_WINDOW_S / BENCH_PROBE_TIMEOUT_S
    (useful to shrink in tests). Returns None when healthy, else the last
    error string."""
    if window_s is None:
        window_s = int(os.environ.get("BENCH_BACKEND_WINDOW_S", "1500"))
    if probe_timeout_s is None:
        probe_timeout_s = int(os.environ.get("BENCH_PROBE_TIMEOUT_S", "120"))
    deadline = time.monotonic() + window_s
    attempt, sleep_s = 0, 60.0
    while True:
        attempt += 1
        err = _probe_backend_once(probe_timeout_s)
        if err is None:
            if attempt > 1:
                print(f"# backend recovered on probe attempt {attempt}",
                      file=sys.stderr, flush=True)
            return None
        remaining = deadline - time.monotonic()
        print(f"# probe {attempt}: {err}; {max(remaining, 0):.0f}s left in "
              f"window", file=sys.stderr, flush=True)
        if remaining <= 0:
            return f"{err} [gave up after {attempt} probes over {window_s}s]"
        time.sleep(min(sleep_s, max(remaining, 1.0)))
        sleep_s = min(sleep_s * 1.5, 120.0)


def main():
    record = {
        "metric": "1M-node WS flood to 99% coverage (single chip)",
        "value": None,
        "unit": "s",
        "vs_baseline": 0.0,
    }
    err = _backend_alive()
    if err is not None:
        record["error"] = err
        print(f"# {err}", file=sys.stderr, flush=True)
        print(json.dumps(record))
        return 1
    try:
        bench_1m(record)
    except Exception as e:
        record["error"] = f"{type(e).__name__}: {e}"
        traceback.print_exc(file=sys.stderr)
        print(json.dumps(record))
        return 1
    try:
        record["scale_10M"] = bench_10m()
    except Exception as e:  # the scale row must not sink the 1M result
        record["scale_10M"] = {"error": f"{type(e).__name__}: {e}"}
        traceback.print_exc(file=sys.stderr)
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
