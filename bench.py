"""North-star benchmark (BASELINE.json): 1M-node Watts–Strogatz single-source
flood to 99% coverage, one chip, whole run device-side (lax.while_loop — zero
host round-trips per round).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
``value`` is the wall-clock seconds of the best aggregation path;
``vs_baseline`` is (1 s north-star target) / value, so > 1 beats the target.

Reference anchor: the reference implementation moves one message per peer per
10 ms poll tick per Python thread [ref: p2pnetwork/nodeconnection.py:220];
simulating this workload there would take hours — it publishes no numbers
(BASELINE.md), so the driver-set 1 s target is the baseline.
"""

import json
import sys
import time

import jax


def time_flood(graph, method: str, *, target: float, max_rounds: int, reps: int = 5):
    from p2pnetwork_tpu.models.flood import Flood
    from p2pnetwork_tpu.sim import engine

    protocol = Flood(source=0, method=method)
    key = jax.random.key(0)

    def once():
        state, out = engine.run_until_coverage(
            graph, protocol, key, coverage_target=target, max_rounds=max_rounds
        )
        # Synchronize via a real host transfer: on tunneled backends
        # jax.block_until_ready can return before execution finishes, which
        # would make these timings dispatch-only fiction.
        out["rounds"] = int(out["rounds"])
        return out

    out = once()  # compile + warm up
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = once()
        times.append(time.perf_counter() - t0)
    return min(times), out


def main():
    n = 1_000_000
    k = 10  # 10M directed edges
    target = 0.99
    t_build0 = time.perf_counter()
    from p2pnetwork_tpu.sim import graph as G

    g = G.watts_strogatz(n, k, 0.1, seed=0, blocked=True, hybrid=True)
    build_s = time.perf_counter() - t_build0

    platform = jax.devices()[0].platform
    methods = ["pallas", "hybrid"]
    results = {}
    for m in methods:
        try:
            secs, out = time_flood(g, m, target=target, max_rounds=64)
            results[m] = (secs, out)
            print(f"# {m}: {secs*1000:.1f} ms, rounds={int(out['rounds'])}, "
                  f"coverage={float(out['coverage']):.4f}, "
                  f"messages={int(out['messages'])}", file=sys.stderr)
        except Exception as e:  # a path failing must not sink the bench
            print(f"# {m}: failed: {type(e).__name__}: {e}", file=sys.stderr)

    if not results:
        print(json.dumps({"metric": "1M-node flood to 99% coverage",
                          "value": None, "unit": "s", "vs_baseline": 0.0,
                          "error": "all methods failed"}))
        return 1

    best_method = min(results, key=lambda m: results[m][0])
    secs, out = results[best_method]
    msgs = int(out["messages"])
    record = {
        "metric": "1M-node WS flood to 99% coverage (single chip)",
        "value": round(secs, 6),
        "unit": "s",
        "vs_baseline": round(1.0 / secs, 3),  # north-star target: 1 s
        "method": best_method,
        "platform": platform,
        "rounds": int(out["rounds"]),
        "coverage": round(float(out["coverage"]), 5),
        "messages": msgs,
        "msgs_per_sec_per_chip": round(msgs / secs, 1),
        "graph_build_s": round(build_s, 2),
        "n_nodes": n,
        "n_edges": g.n_edges,
    }
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
