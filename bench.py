"""North-star benchmark (BASELINE.json): 1M-node Watts–Strogatz single-source
flood to 99% coverage, one chip, whole run device-side (lax.while_loop — zero
host round-trips per round), plus the 10M-node scale config.

Prints the headline JSON record — {"metric", "value", "unit", "vs_baseline",
...} — as its LAST stdout line. ``value`` is the wall-clock seconds of the
best aggregation path at 1M; ``vs_baseline`` is (1 s north-star target) /
value, so > 1 beats the target; ``scale_10M`` carries the 10M-node result
(driver-verified scale row).

Hang containment (this environment's device tunnel has wedged for hours at
a time, twice exactly when the driver ran this file):

- backend init is probed in a child process with retry/backoff across a
  window (``_backend_alive``) — a wedged PJRT client hangs holding the GIL,
  so no in-process watchdog can fire;
- each measurement stage then runs in its OWN child process under a hard
  timeout (``--stage 1m`` / ``--stage 10m``), so a tunnel that wedges
  MID-measurement turns into a bounded, reported error instead of an
  unbounded hang;
- the 1M record is printed the moment the 1M stage returns — before the
  10M stage starts — so a late wedge cannot sink the already-measured
  headline. On success the final merged record (1M + scale_10M) is the
  last line; on a 10M failure the merged record carries the error.

Graph construction is the dominant host-side cost (≈16 s at 1M, ≈49 s at
10M): built graphs are persisted once via the repo's own
``sim/checkpoint.py`` ``save_graph``/``load_graph`` under ``bench_cache/``
and reloaded on later runs, shrinking the healthy-tunnel window a
successful bench needs. ``BENCH_CACHE=0`` disables; a corrupt/missing
cache file silently falls back to a fresh build.

Reference anchor: the reference implementation moves one message per peer per
10 ms poll tick per Python thread [ref: p2pnetwork/nodeconnection.py:220];
simulating this workload there would take hours — it publishes no numbers
(BASELINE.md), so the driver-set 1 s target is the baseline.
"""

import json
import os
import subprocess
import sys
import time
import traceback

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)


def time_flood(graph, method: str, *, target: float, max_rounds: int, reps: int = 5):
    import jax

    from p2pnetwork_tpu.models.adaptive_flood import AdaptiveFlood
    from p2pnetwork_tpu.models.flood import Flood
    from p2pnetwork_tpu.sim import engine

    if method.startswith("adaptive"):
        # "adaptive-<k>": frontier-sparse rounds under k, dense hybrid above
        # (models/adaptive_flood.py) — bit-identical results to Flood.
        k = int(method.split("-")[1])
        protocol = AdaptiveFlood(source=0, method="hybrid", k=k)
    else:
        protocol = Flood(source=0, method=method)
    key = jax.random.key(0)

    def once():
        # run_until_coverage itself blocks on a real device->host transfer
        # of the packed run summary (engine._unpack_summary) — the sync
        # that keeps these timings honest on tunneled backends, where
        # jax.block_until_ready can return before execution finishes.
        state, out = engine.run_until_coverage(
            graph, protocol, key, coverage_target=target, max_rounds=max_rounds
        )
        return out

    out = once()  # compile + warm up
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = once()
        times.append(time.perf_counter() - t0)
    return min(times), out


# --------------------------------------------------------------- graph cache

def _cache_dir():
    return os.environ.get("BENCH_CACHE_DIR", os.path.join(_HERE, "bench_cache"))


def _layout_fingerprint():
    """Hash of the sources that determine a built graph's arrays and kernel
    layouts. Folded into cache filenames so an edit to the builder or the
    blocked/hybrid/CSR layout code invalidates stale caches automatically —
    bench_cache/ persists across rounds on the driver box, and measuring a
    previous round's data layout would be a silently wrong benchmark."""
    import hashlib

    h = hashlib.blake2b(digest_size=6)
    # bench.py itself is in the set: the cache NAME only carries n, so an
    # edit to a build call's other kwargs (k, p, layout flags) must also
    # invalidate.
    for rel in ("bench.py", "p2pnetwork_tpu/sim/graph.py",
                "p2pnetwork_tpu/ops/blocked.py", "p2pnetwork_tpu/ops/diag.py",
                "p2pnetwork_tpu/ops/skew.py",
                "p2pnetwork_tpu/sim/checkpoint.py"):
        with open(os.path.join(_HERE, rel), "rb") as f:
            h.update(f.read())
    return h.hexdigest()


def _cached_graph(name: str, build):
    """Load ``bench_cache/<name>.npz`` if present, else build + persist.

    Returns ``(graph, build_seconds, from_cache)``. Any cache failure
    (missing file, version skew, truncated write) falls back to a fresh
    build — the cache can only ever make the bench faster, never wrong:
    topology is seed-determined, so cached and rebuilt graphs are
    identical arrays.
    """
    from p2pnetwork_tpu.sim import checkpoint as ckpt

    path = os.path.join(_cache_dir(), f"{name}_{_layout_fingerprint()}.npz")
    enabled = os.environ.get("BENCH_CACHE", "1") != "0"
    if enabled and os.path.exists(path):
        try:
            t0 = time.perf_counter()
            g = ckpt.load_graph(path)
            dt = time.perf_counter() - t0
            print(f"# {name}: loaded cached graph in {dt:.1f}s ({path})",
                  file=sys.stderr, flush=True)
            return g, dt, True
        except Exception as e:
            print(f"# {name}: cache load failed ({type(e).__name__}: {e}); "
                  f"rebuilding", file=sys.stderr, flush=True)
    t0 = time.perf_counter()
    g = build()
    dt = time.perf_counter() - t0
    if enabled:
        try:
            os.makedirs(_cache_dir(), exist_ok=True)
            ckpt.save_graph(path, g)
            print(f"# {name}: built in {dt:.1f}s, cached to {path}",
                  file=sys.stderr, flush=True)
        except Exception as e:  # a full disk must not sink the bench
            print(f"# {name}: cache save failed ({type(e).__name__}: {e})",
                  file=sys.stderr, flush=True)
    return g, dt, False


# -------------------------------------------------------------------- stages

def _graph_spec_1m():
    """(cache name, build thunk) for the 1M config — one definition shared
    by the measuring stage and ``--stage prebuild``, so the cache they
    key on cannot drift. BENCH_N_* shrink the configs so the
    orchestration is testable on CPU in seconds (tests/test_bench.py);
    the driver runs the defaults."""
    from p2pnetwork_tpu.sim import graph as G

    n = int(os.environ.get("BENCH_N_1M", 1_000_000))
    return n, f"ws_n{n}_k10_p0.1_s0", lambda: G.watts_strogatz(
        n, 10, 0.1, seed=0, blocked=True, hybrid=True, source_csr=True)


def _graph_spec_10m():
    from p2pnetwork_tpu.sim import graph as G

    n = int(os.environ.get("BENCH_N_10M", 10_000_000))
    return n, f"ws_n{n}_k10_p0.1_s0_notable", lambda: G.watts_strogatz(
        n, 10, 0.1, seed=0, hybrid=True, build_neighbor_table=False,
        source_csr=True)


def bench_1m(record):
    import jax

    n, name, build = _graph_spec_1m()
    target = 0.99
    g, build_s, cached = _cached_graph(name, build)

    methods = ["pallas", "hybrid", "adaptive-1024", "adaptive-2048"]
    results = {}
    for m in methods:
        try:
            secs, out = time_flood(g, m, target=target, max_rounds=64)
            results[m] = (secs, out)
            print(f"# 1M {m}: {secs*1000:.1f} ms, rounds={int(out['rounds'])}, "
                  f"coverage={float(out['coverage']):.4f}, "
                  f"messages={int(out['messages'])}", file=sys.stderr, flush=True)
        except Exception as e:  # a path failing must not sink the bench
            print(f"# 1M {m}: failed: {type(e).__name__}: {e}",
                  file=sys.stderr, flush=True)

    if not results:
        raise RuntimeError("all 1M aggregation methods failed")

    best_method = min(results, key=lambda m: results[m][0])
    secs, out = results[best_method]
    msgs = int(out["messages"])
    record.update({
        "value": round(secs, 6),
        "vs_baseline": round(1.0 / secs, 3),  # north-star target: 1 s
        "method": best_method,
        "platform": jax.devices()[0].platform,
        "rounds": int(out["rounds"]),
        "coverage": round(float(out["coverage"]), 5),
        "messages": msgs,
        "msgs_per_sec_per_chip": round(msgs / secs, 1),
        "graph_build_s": round(build_s, 2),
        "graph_cached": cached,
        "n_nodes": n,
        "n_edges": g.n_edges,
    })


def bench_10m():
    """The scale row: 10M nodes / ~100M directed edges on ONE chip."""
    n, name, build = _graph_spec_10m()
    g, build_s, cached = _cached_graph(name, build)
    secs, out = time_flood(g, "adaptive-2048", target=0.99, max_rounds=64,
                           reps=3)
    msgs = int(out["messages"])
    print(f"# 10M adaptive-2048: {secs:.3f} s, rounds={int(out['rounds'])}, "
          f"coverage={float(out['coverage']):.4f}, messages={msgs}",
          file=sys.stderr, flush=True)
    return {
        "value_s": round(secs, 4),
        "method": "adaptive-2048",
        "rounds": int(out["rounds"]),
        "coverage": round(float(out["coverage"]), 5),
        "messages": msgs,
        "msgs_per_sec_per_chip": round(msgs / secs, 1),
        "graph_build_s": round(build_s, 1),
        "graph_cached": cached,
        "n_nodes": n,
        "n_edges": g.n_edges,
    }


def _run_stage(stage: str) -> int:
    """Child-process entry (``--stage 1m|10m``): init the backend, run one
    stage, print ONE JSON line on stdout. Comments go to stderr, which the
    parent inherits straight through to the driver log."""
    try:
        from p2pnetwork_tpu.utils.jax_env import apply_platform_env

        apply_platform_env()
        if stage == "1m":
            record = {}
            bench_1m(record)
            print(json.dumps(record))
            return 0
        if stage == "10m":
            print(json.dumps(bench_10m()))
            return 0
        if stage == "prebuild":
            # Populate the graph cache without measuring — run once on a
            # quiet host (any backend; builds are host-side) so a later
            # driver run inside a flaky-tunnel window only LOADS.
            for _, name, build in (_graph_spec_1m(), _graph_spec_10m()):
                _cached_graph(name, build)
            print(json.dumps({"prebuilt": True}))
            return 0
    except Exception as e:
        # The error must reach the driver's parsed record, not just the
        # stderr log: emit it as this stage's JSON line (the parent
        # forwards it) before exiting nonzero.
        traceback.print_exc(file=sys.stderr)
        print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
        return 1
    print(f"# unknown stage {stage!r}", file=sys.stderr)
    return 2


def _stage_in_child(stage: str, timeout_s: int):
    """Run ``--stage <stage>`` in a child under a hard timeout. Returns the
    stage's parsed JSON record, or ``{"error": ...}`` — never raises, never
    hangs: a tunnel wedging mid-measurement is a bounded, reported error."""
    cmd = [sys.executable, os.path.abspath(__file__), "--stage", stage]
    t0 = time.perf_counter()
    try:
        r = subprocess.run(cmd, stdout=subprocess.PIPE, timeout=timeout_s,
                           text=True, cwd=_HERE)
    except subprocess.TimeoutExpired:
        return {"error": f"stage {stage} exceeded {timeout_s}s "
                         f"(device tunnel wedged mid-run?)"}
    except Exception as e:
        return {"error": f"stage {stage} launcher failed: "
                         f"{type(e).__name__}: {e}"}
    dt = time.perf_counter() - t0
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    parsed = None
    if lines:
        try:
            parsed = json.loads(lines[-1])
        except ValueError:
            pass
    if r.returncode != 0:
        # A failing stage still emits an error-carrying JSON line
        # (_run_stage's handler) — prefer its actual cause over a bare
        # exit-code report.
        if isinstance(parsed, dict) and "error" in parsed:
            return {"error": f"stage {stage}: {parsed['error']}"}
        return {"error": f"stage {stage} exited rc={r.returncode} "
                         f"after {dt:.0f}s with "
                         f"{'no output' if not lines else lines[-1][-200:]}"}
    if parsed is None:
        return {"error": f"stage {stage} emitted unparseable output: "
                         f"{lines[-1][-200:] if lines else 'no output'}"}
    return parsed


# ----------------------------------------------------------- backend probing

def _probe_backend_once(timeout_s: int):
    """Probe JAX backend init in a CHILD process. A wedged device tunnel
    hangs PJRT client creation while holding the GIL, so no in-process
    watchdog (signal.alarm included — verified) can fire; probing in a
    subprocess turns an unbounded hang into a bounded, reportable error.
    Returns None when healthy, else an error string."""
    probe = (
        "import sys; sys.path.insert(0, {!r}); "
        "from p2pnetwork_tpu.utils.jax_env import apply_platform_env; "
        "apply_platform_env(); import jax, jax.numpy as jnp; "
        "print(jax.devices()); "
        # Enumeration alone can succeed on a half-wedged tunnel: require a
        # real compile + execute + device->host round trip. Not an assert —
        # PYTHONOPTIMIZE would strip that and quietly weaken the probe.
        "v = int(jax.jit(lambda: jnp.sum(jnp.arange(8)))()); "
        "print(f'probe compute round-trip returned {{v}}, want 28', "
        "file=sys.stderr); "
        "raise SystemExit(0 if v == 28 else 1)"
        .format(_HERE)
    )
    try:
        r = subprocess.run([sys.executable, "-c", probe],
                           timeout=timeout_s, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return (f"JAX backend init hung for {timeout_s}s "
                f"(device tunnel wedged?)")
    if r.returncode != 0:
        return "backend probe failed: " + r.stderr.strip()[-300:]
    return None


def _backend_alive(window_s=None, probe_timeout_s=None):
    """Wait for the backend to come up, retrying across ``window_s`` seconds.

    The tunnel has wedged and then recovered on its own across past rounds;
    a single probe therefore gives up too early and forfeits the whole bench
    window. Instead: probe (bounded by ``probe_timeout_s``), and on failure
    sleep and retry until the window is spent, emitting a heartbeat comment
    line per attempt so the driver log shows liveness. The sleep backs off
    60 s -> 120 s. Override via BENCH_BACKEND_WINDOW_S / BENCH_PROBE_TIMEOUT_S
    (useful to shrink in tests). Returns None when healthy, else the last
    error string."""
    if window_s is None:
        # 40 min: the r4 driver tolerated a 25+ min probe window, and with
        # the graph cache prebuilt the measuring stages need only ~3 min
        # of healthy tunnel after it — a longer window is all upside for
        # the revives-mid-window case this environment has shown.
        window_s = int(os.environ.get("BENCH_BACKEND_WINDOW_S", "2400"))
    if probe_timeout_s is None:
        probe_timeout_s = int(os.environ.get("BENCH_PROBE_TIMEOUT_S", "120"))
    deadline = time.monotonic() + window_s
    attempt, sleep_s = 0, 60.0
    while True:
        attempt += 1
        err = _probe_backend_once(probe_timeout_s)
        if err is None:
            if attempt > 1:
                print(f"# backend recovered on probe attempt {attempt}",
                      file=sys.stderr, flush=True)
            return None
        remaining = deadline - time.monotonic()
        print(f"# probe {attempt}: {err}; {max(remaining, 0):.0f}s left in "
              f"window", file=sys.stderr, flush=True)
        if remaining <= 0:
            return f"{err} [gave up after {attempt} probes over {window_s}s]"
        time.sleep(min(sleep_s, max(remaining, 1.0)))
        sleep_s = min(sleep_s * 1.5, 120.0)


def main():
    record = {
        "metric": "1M-node WS flood to 99% coverage (single chip)",
        "value": None,
        "unit": "s",
        "vs_baseline": 0.0,
    }
    # Provisional record FIRST: if the caller kills this process mid
    # probe-window (a driver budget shorter than the window), the last
    # stdout JSON line is still parseable instead of absent. Every later
    # print supersedes it.
    print(json.dumps({**record, "error": "killed while probing backend "
                      "(provisional record; superseded by later lines)"}),
          flush=True)
    err = _backend_alive()
    if err is not None:
        record["error"] = err
        print(f"# {err}", file=sys.stderr, flush=True)
        print(json.dumps(record))
        return 1

    # Probe passed: supersede the provisional line so a kill from here on
    # is attributed to the measuring stage, not a tunnel outage that
    # never happened.
    print(json.dumps({**record, "error": "backend probe passed; killed "
                      "during measuring stage (provisional record; "
                      "superseded by later lines)"}), flush=True)
    stage_timeout = int(os.environ.get("BENCH_STAGE_TIMEOUT_S", "900"))
    r1m = _stage_in_child("1m", stage_timeout)
    if "error" in r1m:
        record["error"] = r1m["error"]
        print(f"# {r1m['error']}", file=sys.stderr, flush=True)
        print(json.dumps(record))
        return 1
    record.update(r1m)
    # Emit the measured headline NOW: if the 10M stage's child is killed by
    # its timeout the merged line below still prints, but if this parent
    # itself dies (driver timeout, OOM-kill) the 1M number is already out.
    print(json.dumps(record), flush=True)

    record["scale_10M"] = _stage_in_child("10m", stage_timeout)
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--stage":
        sys.exit(_run_stage(sys.argv[2]))
    sys.exit(main())
