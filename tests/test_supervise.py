"""Supervised execution plane: watchdogs, checkpoint store, crash-tolerant
runs, preemption faults, deadline-bounded shutdown, bench partial records.

The crash-recovery core is proven two ways: fast in-process tests drive the
deterministic ``preempt`` fault (a SIGKILL stand-in at an exact round), and
a slow-marked subprocess test SIGKILLs a real ``SupervisedRun`` child —
twice, at different rounds — and asserts the resumed final state is
bit-identical to an uninterrupted run's (PRNG-dependent protocol, so the
per-chunk key discipline is what's under test, not just idempotent state).
"""

import hashlib
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from p2pnetwork_tpu import telemetry  # noqa: E402
from p2pnetwork_tpu.models import SIR, Flood  # noqa: E402
from p2pnetwork_tpu.sim import checkpoint as ckpt  # noqa: E402
from p2pnetwork_tpu.sim import engine  # noqa: E402
from p2pnetwork_tpu.sim import failures  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402
from p2pnetwork_tpu.supervise import (  # noqa: E402
    CheckpointStore, Preempted, StallTimeout, SupervisedRun, Watchdog)
from tests.helpers import wait_until  # noqa: E402

pytestmark = pytest.mark.supervise

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _state_digest(state) -> str:
    leaves = jax.tree_util.tree_leaves(jax.device_get(state))
    h = hashlib.sha256()
    for leaf in leaves:
        h.update(np.ascontiguousarray(leaf).tobytes())
    return h.hexdigest()


# ------------------------------------------------------------------ watchdog


class TestWatchdog:
    def test_stall_detected_within_deadline_and_counted(self):
        # The acceptance scenario: an artificially stalled dispatch (the
        # supervised thread simply stops heartbeating) must fire a stall
        # event within its deadline, with the timeout counter incremented.
        reg = telemetry.Registry()
        fired = []
        deadline = 0.2
        with Watchdog(deadline, name="stalled", on_stall=fired.append,
                      registry=reg) as dog:
            t0 = time.monotonic()
            assert wait_until(lambda: fired, timeout=3 * deadline,
                              interval=0.005)
            detect_s = time.monotonic() - t0
        assert detect_s < 2 * deadline
        assert dog.stalls == 1
        assert fired[0] is dog
        assert reg.value("supervise_watchdog_timeouts_total",
                         watchdog="stalled") == 1
        assert dog.last_stall_s >= deadline

    def test_heartbeats_prevent_stall(self):
        reg = telemetry.Registry()
        with Watchdog(0.25, name="alive", on_stall="warn",
                      registry=reg) as dog:
            for _ in range(8):
                dog.heartbeat()
                time.sleep(0.05)
        assert dog.stalls == 0
        assert reg.value("supervise_watchdog_timeouts_total",
                         watchdog="alive") == 0

    def test_raise_mode_raises_at_next_heartbeat(self):
        reg = telemetry.Registry()
        with pytest.raises(StallTimeout) as e:
            with Watchdog(0.1, name="r", registry=reg) as dog:
                assert wait_until(lambda: dog.stalls > 0, timeout=1.0,
                                  interval=0.005)
                dog.heartbeat()  # the pending stall surfaces HERE
                pytest.fail("heartbeat should have raised")
        assert e.value.deadline_s == 0.1
        assert e.value.stalled_s >= 0.1

    def test_raise_mode_raises_at_exit_without_final_heartbeat(self):
        with pytest.raises(StallTimeout):
            with Watchdog(0.1, name="x", registry=telemetry.Registry()) as dog:
                assert wait_until(lambda: dog.stalls > 0, timeout=1.0,
                                  interval=0.005)

    def test_one_event_per_gap_and_gauge_climbs(self):
        reg = telemetry.Registry()
        with Watchdog(0.1, name="g", on_stall=lambda d: None,
                      registry=reg) as dog:
            assert wait_until(lambda: dog.stalls > 0, timeout=1.0,
                              interval=0.005)
            g1 = reg.value("supervise_stall_seconds", watchdog="g")
            time.sleep(0.25)
            g2 = reg.value("supervise_stall_seconds", watchdog="g")
            assert dog.stalls == 1  # same gap: one event, climbing gauge
            assert g2 > g1 > 0
            dog.heartbeat()
            assert reg.value("supervise_stall_seconds", watchdog="g") == 0
            assert wait_until(lambda: dog.stalls == 2, timeout=1.0,
                              interval=0.005)  # new gap: a second event
        # close() resets the gauge: a finished run must not scrape as a
        # still-climbing stall.
        assert reg.value("supervise_stall_seconds", watchdog="g") == 0

    def test_crashing_stall_hook_does_not_kill_the_watchdog(self):
        def bad_hook(dog):
            raise RuntimeError("driver hook bug")

        with pytest.warns(RuntimeWarning, match="on_stall callback raised"):
            with Watchdog(0.08, name="h", on_stall=bad_hook,
                          registry=telemetry.Registry()) as dog:
                assert wait_until(lambda: dog.stalls > 0, timeout=1.0,
                                  interval=0.005)
                dog.heartbeat()
                assert wait_until(lambda: dog.stalls > 1, timeout=1.0,
                                  interval=0.005)  # still watching

    def test_invalid_configuration_rejected(self):
        with pytest.raises(ValueError):
            Watchdog(0)
        with pytest.raises(ValueError):
            Watchdog(1.0, on_stall="explode")


# --------------------------------------------- checkpoint integrity (file)


class TestCheckpointIntegrity:
    def _save_one(self, tmp_path):
        path = str(tmp_path / "c.npz")
        state = {"a": np.arange(6, dtype=np.int32),
                 "b": np.ones(3, dtype=np.float32)}
        ckpt.save(path, state, jax.random.key(7), 5, 42)
        return path, state

    def test_roundtrip_with_hash(self, tmp_path):
        path, state = self._save_one(tmp_path)
        got, key, rnd, msgs = ckpt.load(path, state)
        assert rnd == 5 and msgs == 42
        np.testing.assert_array_equal(np.asarray(got["a"]), state["a"])

    def test_truncated_file_raises_checkpoint_corrupt(self, tmp_path):
        path, state = self._save_one(tmp_path)
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) // 2)
        with pytest.raises(ckpt.CheckpointCorrupt) as e:
            ckpt.load(path, state)
        assert e.value.path == path

    def test_garbage_file_raises_checkpoint_corrupt(self, tmp_path):
        path = str(tmp_path / "junk.npz")
        with open(path, "wb") as f:
            f.write(b"not a zip at all")
        with pytest.raises(ckpt.CheckpointCorrupt):
            ckpt.load(path, {"a": np.zeros(1)})

    def test_content_tamper_reports_expected_and_actual_hash(self, tmp_path):
        path, state = self._save_one(tmp_path)
        # Rewrite the npz with one leaf modified but the ORIGINAL digest:
        # the zip container stays valid, so only the content hash can
        # catch it.
        with np.load(path) as data:
            payload = {k: np.asarray(data[k]) for k in data.files}
        payload["leaf_0"] = payload["leaf_0"] + 1
        with open(path, "wb") as f:
            np.savez(f, **payload)
        with pytest.raises(ckpt.CheckpointCorrupt) as e:
            ckpt.load(path, state)
        assert e.value.expected is not None
        assert e.value.actual is not None
        assert e.value.expected != e.value.actual
        assert "hash mismatch" in str(e.value)

    def test_legacy_hashless_file_still_loads(self, tmp_path):
        # Old-format back-compat: files written before the integrity hash
        # landed have no __sha256__ entry and must load unverified.
        path, state = self._save_one(tmp_path)
        with np.load(path) as data:
            payload = {k: np.asarray(data[k]) for k in data.files
                       if k != "__sha256__"}
        with open(path, "wb") as f:
            np.savez(f, **payload)
        got, key, rnd, msgs = ckpt.load(path, state)
        assert rnd == 5 and msgs == 42

    def test_template_mismatch_stays_value_error(self, tmp_path):
        path, state = self._save_one(tmp_path)
        with pytest.raises(ValueError) as e:
            ckpt.load(path, {"different": np.zeros(2)})
        assert not isinstance(e.value, ckpt.CheckpointCorrupt)


# ------------------------------------------------------------------- store


class TestCheckpointStore:
    def _fill(self, store, rounds):
        key = jax.random.key(0)
        state = {"x": np.arange(8, dtype=np.int32)}
        for r in rounds:
            state = {"x": state["x"] + 1}
            store.save(state, key, r, r * 10)
        return state

    def test_manifest_updated_atomically_and_points_to_latest(self, tmp_path):
        store = CheckpointStore(str(tmp_path), retain=5,
                                registry=telemetry.Registry())
        self._fill(store, [1, 2, 3])
        with open(tmp_path / "manifest.json", encoding="utf-8") as f:
            doc = json.load(f)
        assert doc["latest"] == doc["entries"][-1]["file"]
        assert [e["round"] for e in doc["entries"]] == [1, 2, 3]
        for e in doc["entries"]:
            assert (tmp_path / e["file"]).exists()
        # No half-written temp artifacts survive a completed save.
        assert not [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]

    def test_retention_prunes_oldest(self, tmp_path):
        store = CheckpointStore(str(tmp_path), retain=2,
                                registry=telemetry.Registry())
        self._fill(store, [1, 2, 3, 4])
        assert [e["round"] for e in store.entries()] == [3, 4]
        files = [n for n in os.listdir(tmp_path) if n.endswith(".npz")]
        assert len(files) == 2

    def test_corrupt_latest_entry_skipped_on_load(self, tmp_path):
        reg = telemetry.Registry()
        store = CheckpointStore(str(tmp_path), retain=3, registry=reg)
        self._fill(store, [1, 2, 3])
        newest = tmp_path / store.entries()[-1]["file"]
        with open(newest, "r+b") as f:
            f.truncate(os.path.getsize(newest) // 2)
        template = {"x": np.zeros(8, np.int32)}
        state, key, rnd, msgs, path = store.load_latest(template)
        assert rnd == 2 and msgs == 20
        assert reg.value("supervise_checkpoints_skipped_total",
                         reason="hash_mismatch") == 1

    def test_missing_entry_file_skipped(self, tmp_path):
        reg = telemetry.Registry()
        store = CheckpointStore(str(tmp_path), retain=3, registry=reg)
        self._fill(store, [1, 2])
        os.unlink(tmp_path / store.entries()[-1]["file"])
        state, key, rnd, msgs, path = store.load_latest(
            {"x": np.zeros(8, np.int32)})
        assert rnd == 1
        assert reg.value("supervise_checkpoints_skipped_total",
                         reason="missing") == 1

    def test_lost_manifest_falls_back_to_directory_scan(self, tmp_path):
        store = CheckpointStore(str(tmp_path), retain=3,
                                registry=telemetry.Registry())
        self._fill(store, [1, 2])
        os.unlink(tmp_path / "manifest.json")
        got = store.load_latest({"x": np.zeros(8, np.int32)})
        assert got is not None and got[2] == 2

    def test_empty_store_loads_none(self, tmp_path):
        store = CheckpointStore(str(tmp_path),
                                registry=telemetry.Registry())
        assert store.load_latest({"x": np.zeros(1)}) is None
        assert store.latest_round() is None

    def test_save_never_prunes_its_own_entry(self, tmp_path):
        # Regression: a save whose round sorts below a stale higher-round
        # trail used to have ITS OWN entry retention-pruned as written
        # (and returned a path to an already-deleted file).
        store = CheckpointStore(str(tmp_path), retain=3,
                                registry=telemetry.Registry())
        self._fill(store, [20, 24, 28])
        key = jax.random.key(0)
        path = store.save({"x": np.full(8, 7, np.int32)}, key, 8, 80)
        assert os.path.exists(path)
        rounds = [e["round"] for e in store.entries()]
        assert 8 in rounds and len(rounds) == 3  # oldest survivor evicted

    def test_clear_resets_to_empty(self, tmp_path):
        store = CheckpointStore(str(tmp_path), retain=3,
                                registry=telemetry.Registry())
        self._fill(store, [1, 2])
        store.clear()
        assert store.entries() == []
        assert not [n for n in os.listdir(tmp_path)
                    if n.endswith(".npz") or n == "manifest.json"]

    def test_concurrent_saves_lose_no_entry(self, tmp_path):
        # Regression: the manifest read-modify-write races a concurrent
        # emergency_checkpoint from the watchdog thread without the save
        # lock — the last writer won with a stale entries list.
        import threading

        store = CheckpointStore(str(tmp_path), retain=64,
                                registry=telemetry.Registry())
        key = jax.random.key(0)

        def writer(base):
            for i in range(8):
                store.save({"x": np.full(4, base + i, np.int32)},
                           key, base + i, 0)

        threads = [threading.Thread(target=writer, args=(b,))
                   for b in (100, 200)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        rounds = sorted(e["round"] for e in store.entries())
        assert rounds == sorted(list(range(100, 108)) +
                                list(range(200, 208)))


# ----------------------------------------------------------- supervised run


class TestSupervisedRun:
    def test_chunked_flood_bit_identical_to_unchunked_engine(self, tmp_path):
        # Flood is PRNG-independent, so the chunked supervised run must
        # reproduce the one-program engine loop bit-for-bit.
        g = G.watts_strogatz(1024, 8, 0.1, seed=1)
        run = SupervisedRun(g, Flood(source=0), str(tmp_path),
                            chunk_rounds=3)
        st, summary = run.run_until_coverage(
            jax.random.key(0), coverage_target=0.99, max_rounds=64)
        st_ref, out_ref = engine.run_until_coverage(
            g, Flood(source=0), jax.random.key(0),
            coverage_target=0.99, max_rounds=64)
        np.testing.assert_array_equal(np.asarray(st.seen),
                                      np.asarray(st_ref.seen))
        assert summary["rounds"] == int(out_ref["rounds"])
        assert summary["messages"] == int(out_ref["messages"])
        assert summary["checkpoints"] >= 1
        assert summary["resumed_from"] is None
        assert os.path.exists(summary["checkpoint_path"])

    def test_preempt_twice_then_resume_bit_identical_prng_protocol(
            self, tmp_path):
        # SIR draws randomness every round: the resumed run is only
        # bit-identical if the per-chunk key discipline is exact.
        g = G.watts_strogatz(512, 6, 0.1, seed=3)
        proto = SIR(beta=0.4, gamma=0.15)
        ref = SupervisedRun(g, proto, str(tmp_path / "ref"), chunk_rounds=4)
        st_ref, sum_ref = ref.run_rounds(jax.random.key(5), 20)

        run = SupervisedRun(g, proto, str(tmp_path / "killed"),
                            chunk_rounds=4)
        # Preemption fires BEFORE the checkpoint due at its boundary (a
        # SIGKILL would not have waited for the save): a kill at round 4
        # leaves NO trail, a kill at round 12 leaves rounds 4 and 8.
        failures.preempt(run, at_round=4)
        with pytest.raises(Preempted) as e:
            run.run_rounds(jax.random.key(5), 20)
        assert e.value.round_index == 4
        assert run.store.latest_round() is None
        failures.preempt(run, at_round=12)
        with pytest.raises(Preempted):
            run.run_rounds(jax.random.key(5), 20)
        assert run.store.latest_round() == 8
        st, summary = run.run_rounds(jax.random.key(5), 20)

        assert summary["rounds"] == sum_ref["rounds"] == 20
        assert summary["messages"] == sum_ref["messages"]
        assert summary["resumed_from"] == 8
        assert _state_digest(st) == _state_digest(st_ref)

    def test_preempt_counts_injection(self, tmp_path):
        g = G.ring(64)
        run = SupervisedRun(g, Flood(source=0), str(tmp_path))
        before = telemetry.default_registry().value(
            "sim_injected_failures_total", kind="preempt")
        failures.preempt(run, at_round=2)
        after = telemetry.default_registry().value(
            "sim_injected_failures_total", kind="preempt")
        assert after == before + 1

    def test_resume_skips_corrupt_latest_checkpoint(self, tmp_path):
        g = G.watts_strogatz(512, 6, 0.1, seed=3)
        proto = SIR(beta=0.4, gamma=0.15)
        ref = SupervisedRun(g, proto, str(tmp_path / "ref"), chunk_rounds=4)
        st_ref, _ = ref.run_rounds(jax.random.key(5), 16)

        run = SupervisedRun(g, proto, str(tmp_path / "dmg"), chunk_rounds=4,
                            retain=4)
        failures.preempt(run, at_round=12)
        with pytest.raises(Preempted):
            run.run_rounds(jax.random.key(5), 16)
        # Damage the newest surviving entry (round 8 — the preemption fired
        # before the round-12 save, like a real kill): resume must fall
        # back to the round-4 entry and still match bit-exactly.
        newest = run.store.entries()[-1]
        assert newest["round"] == 8
        path = os.path.join(run.store.directory, newest["file"])
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) // 2)
        st, summary = run.run_rounds(jax.random.key(5), 16)
        assert summary["resumed_from"] == 4
        assert _state_digest(st) == _state_digest(st_ref)

    def test_time_cadence_and_final_checkpoint(self, tmp_path):
        g = G.ring(128)
        # Huge time cadence, no round cadence: only the final checkpoint.
        run = SupervisedRun(g, Flood(source=0), str(tmp_path / "t1"),
                            chunk_rounds=2, checkpoint_every_s=3600.0)
        _, summary = run.run_rounds(jax.random.key(0), 8)
        assert summary["checkpoints"] == 1
        assert run.store.latest_round() == 8
        # Zero time cadence: every chunk boundary checkpoints.
        run2 = SupervisedRun(g, Flood(source=0), str(tmp_path / "t2"),
                             chunk_rounds=2, checkpoint_every_s=0.0)
        _, summary2 = run2.run_rounds(jax.random.key(0), 8)
        assert summary2["checkpoints"] == summary2["chunks"] == 4

    def test_donation_between_chunks_fallback_at_boundaries(
            self, tmp_path, monkeypatch):
        # PR 3's donation semantics across chunks: mid-cadence chunks
        # donate their carry; the chunk feeding a checkpoint runs
        # donate=False. Observable contract: when a boundary chunk's
        # dispatch dies, its (undonated) input state is emergency-
        # checkpointed, so the store resumes from the boundary instead of
        # the previous cadence point.
        g = G.watts_strogatz(512, 6, 0.1, seed=2)
        donate_flags = []
        real = engine.run_from

        def spy(graph, protocol, state, key, rounds, *, donate=True):
            donate_flags.append(donate)
            if len(donate_flags) == 4:  # the 4th chunk feeds a checkpoint
                raise RuntimeError("simulated dispatch death")
            return real(graph, protocol, state, key, rounds, donate=donate)

        monkeypatch.setattr(engine, "run_from", spy)
        run = SupervisedRun(g, Flood(source=0), str(tmp_path),
                            chunk_rounds=2, checkpoint_every_rounds=4)
        with pytest.raises(RuntimeError, match="simulated dispatch death"):
            run.run_rounds(jax.random.key(0), 16)
        # Chunks 1-2 cover rounds 0-4 (chunk 2 feeds the round-4 save);
        # chunk 3 donates; chunk 4 (rounds 6-8) feeds the next save and
        # died — its input (round 6) must have been emergency-saved.
        assert donate_flags == [True, False, True, False]
        assert run.store.latest_round() == 6

    def test_watchdog_stall_during_run_counted_in_summary(self, tmp_path):
        g = G.ring(256)
        reg = telemetry.Registry()
        stalls = []
        slept = []

        def slow_chunk(run, info):
            if not slept:  # one artificial stall, mid-run
                slept.append(True)
                time.sleep(0.5)

        run = SupervisedRun(g, Flood(source=0), str(tmp_path),
                            chunk_rounds=1, deadline_s=0.15,
                            on_stall=stalls.append, on_chunk=slow_chunk,
                            registry=reg)
        _, summary = run.run_until_coverage(
            jax.random.key(0), coverage_target=0.99, max_rounds=64)
        assert summary["stalls"] >= 1
        assert len(stalls) >= 1
        assert reg.value("supervise_watchdog_timeouts_total",
                         watchdog="supervised-coverage") >= 1

    def test_fresh_start_clears_stale_trail(self, tmp_path):
        # resume=False into a directory holding a previous trail: the
        # fresh run owns the directory — stale entries are cleared, the
        # fresh trail is durable, and a subsequent resume continues the
        # FRESH run (not the stale one whose rounds were higher).
        g = G.watts_strogatz(512, 6, 0.1, seed=2)
        run = SupervisedRun(g, Flood(source=0), str(tmp_path),
                            chunk_rounds=4)
        run.run_rounds(jax.random.key(0), 24)
        assert run.store.latest_round() == 24
        run2 = SupervisedRun(g, Flood(source=0), str(tmp_path),
                             chunk_rounds=4)
        failures.preempt(run2, at_round=8)
        with pytest.raises(Preempted):
            run2.run_rounds(jax.random.key(1), 12, resume=False)
        assert run2.store.latest_round() == 4  # fresh trail, stale gone
        _, summary = run2.run_rounds(jax.random.key(1), 12)
        assert summary["resumed_from"] == 4
        assert summary["rounds"] == 12

    def test_resume_on_finished_run_is_noop(self, tmp_path):
        g = G.watts_strogatz(512, 6, 0.1, seed=1)
        run = SupervisedRun(g, Flood(source=0), str(tmp_path),
                            chunk_rounds=4)
        st1, s1 = run.run_until_coverage(jax.random.key(0),
                                         coverage_target=0.99, max_rounds=64)
        st2, s2 = run.run_until_coverage(jax.random.key(0),
                                         coverage_target=0.99, max_rounds=64)
        assert s2["rounds"] == s1["rounds"]
        assert s2["resumed_from"] == s1["rounds"]
        assert s2["chunks"] == 1  # one zero-round probe chunk, no rework
        assert _state_digest(st1) == _state_digest(st2)

    def test_invalid_configuration_rejected(self, tmp_path):
        g = G.ring(16)
        with pytest.raises(ValueError):
            SupervisedRun(g, Flood(source=0), str(tmp_path), chunk_rounds=0)
        with pytest.raises(ValueError):
            SupervisedRun(g, Flood(source=0), str(tmp_path),
                          checkpoint_every_rounds=0)
        with pytest.raises(ValueError):
            CheckpointStore(str(tmp_path), retain=0)


# -------------------------------------- engine: double-resume donation guard


class TestDonatedStateDetection:
    def test_run_from_deleted_state_raises_clear_error(self):
        # Regression: this used to surface as an opaque XLA deleted-buffer
        # error from inside the dispatch.
        g = G.watts_strogatz(256, 4, 0.2, seed=2)
        state = Flood(source=0).init(g, jax.random.key(0))
        state, _ = engine.run_from(g, Flood(source=0), state,
                                   jax.random.key(1), 2)
        # Donate the buffers away...
        engine.run_from(g, Flood(source=0), state, jax.random.key(2), 2)
        # ...then resume the same state again.
        with pytest.raises(ValueError, match="donate=False"):
            engine.run_from(g, Flood(source=0), state, jax.random.key(3), 2)

    def test_coverage_and_converged_resumes_also_guarded(self):
        g = G.watts_strogatz(256, 4, 0.2, seed=2)
        state = Flood(source=0).init(g, jax.random.key(0))
        state, _ = engine.run_from(g, Flood(source=0), state,
                                   jax.random.key(1), 2)
        engine.run_until_coverage_from(g, Flood(source=0), state,
                                       jax.random.key(2), max_rounds=2)
        with pytest.raises(ValueError, match="donate=False"):
            engine.run_until_coverage_from(g, Flood(source=0), state,
                                           jax.random.key(3), max_rounds=2)

    def test_donate_false_keeps_state_resumable(self):
        g = G.watts_strogatz(256, 4, 0.2, seed=2)
        state = Flood(source=0).init(g, jax.random.key(0))
        state, _ = engine.run_from(g, Flood(source=0), state,
                                   jax.random.key(1), 2)
        a, _ = engine.run_from(g, Flood(source=0), state, jax.random.key(2),
                               2, donate=False)
        b, _ = engine.run_from(g, Flood(source=0), state, jax.random.key(2),
                               2, donate=False)
        np.testing.assert_array_equal(np.asarray(a.seen), np.asarray(b.seen))


# ----------------------------------------------------- chaos preempt mirror


class TestChaosPreempt:
    def test_preempt_and_revive_lifecycle(self):
        from p2pnetwork_tpu.chaos import ChaosPlane

        reg = telemetry.Registry()
        plane = ChaosPlane(seed=1, registry=reg)
        plane.preempt(["a", "b"])
        assert not plane.link_ok("a", "c")
        assert not plane.link_ok("c", "b")
        assert reg.value("chaos_injected_failures_total", kind="preempt") == 2
        assert reg.value("chaos_active_faults", kind="preempted_nodes") == 2
        assert reg.value("chaos_active_faults", kind="dead_nodes") == 2
        revived = plane.revive_preempted()
        assert revived == ["a", "b"]
        assert plane.link_ok("a", "c") and plane.link_ok("c", "b")
        assert reg.value("chaos_injected_failures_total",
                         kind="preempt_revive") == 2
        assert reg.value("chaos_active_faults", kind="preempted_nodes") == 0

    def test_revive_nodes_also_clears_preempted(self):
        from p2pnetwork_tpu.chaos import ChaosPlane

        reg = telemetry.Registry()
        plane = ChaosPlane(seed=1, registry=reg)
        plane.preempt(["a"])
        plane.kill_nodes(["b"])
        plane.revive_nodes(["a"])
        assert plane.link_ok("a", "c")
        assert not plane.link_ok("b", "c")
        assert plane.revive_preempted() == []

    def test_kill_stays_dead_across_revive_preempted(self):
        from p2pnetwork_tpu.chaos import ChaosPlane

        plane = ChaosPlane(seed=1, registry=telemetry.Registry())
        plane.kill_nodes(["k"])
        plane.preempt(["p"])
        plane.revive_preempted()
        assert not plane.link_ok("k", "x")  # a kill is a decision
        assert plane.link_ok("p", "x")      # a preemption comes back


# -------------------------------------------- Node.stop(deadline=) drain


class TestNodeStopDeadline:
    def test_undrained_peer_counted_and_stop_bounded(self):
        import socket as socket_mod

        from p2pnetwork_tpu import Node
        from p2pnetwork_tpu.config import NodeConfig

        reg = telemetry.Registry()
        node = Node("127.0.0.1", 0, id="drainer", registry=reg,
                    config=NodeConfig(max_send_buffer=256 * 1024 * 1024))
        node.start()
        raw = socket_mod.create_connection(("127.0.0.1", node.port))
        try:
            raw.sendall(b"peer:12345")
            raw.recv(4096)  # node's id — handshake complete
            assert wait_until(lambda: len(node.nodes_inbound) == 1)
            # A peer that stops reading: flood it far past the socket
            # buffers so bytes are still queued at stop time.
            blob = b"x" * (1 << 20)
            for _ in range(64):
                node.send_to_nodes(blob)
            conn = node.nodes_inbound[0]
            assert wait_until(
                lambda: (conn.writer.transport is not None and
                         conn.writer.transport.get_write_buffer_size() > 0),
                timeout=10.0)
            t0 = time.monotonic()
            node.stop(deadline=0.3)
            node.join(timeout=15.0)
            assert not node.is_alive()
            # Bounded: far under the legacy 10 s-per-connection close wait.
            assert time.monotonic() - t0 < 8.0
            assert reg.value("p2p_shutdown_undelivered_total",
                             node="drainer") > 0
            events = [e for e in node.event_log.snapshot()
                      if e.event == "shutdown_undelivered"]
            assert events and events[0].data["bytes"] > 0
        finally:
            raw.close()
            node.stop()

    def test_drained_peer_counts_nothing(self):
        from p2pnetwork_tpu import Node
        from tests.helpers import stop_all

        reg = telemetry.Registry()
        a = Node("127.0.0.1", 0, id="a", registry=reg)
        b = Node("127.0.0.1", 0, id="b", registry=reg)
        a.start()
        b.start()
        try:
            assert a.connect_with_node("127.0.0.1", b.port)
            a.send_to_nodes("bye")
            assert wait_until(lambda: b.message_count_recv == 1)
            a.stop(deadline=2.0)
            a.join(timeout=10.0)
            assert reg.value("p2p_shutdown_undelivered_total", node="a") == 0
        finally:
            stop_all([a, b])


# --------------------------------------------------- bench partial records


class TestBenchPartialRecord:
    def _bench_env(self, tmp_path, **extra):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "BENCH_N_1M": "2000",
            "BENCH_N_10M": "3000",
            "BENCH_BACKEND_WINDOW_S": "5",
            "BENCH_PROBE_TIMEOUT_S": "60",
            "BENCH_CACHE_DIR": str(tmp_path / "cache"),
            "BENCH_TELEMETRY_DIR": str(tmp_path),
            "BENCH_SUPERVISE_CHUNK": "1",
        })
        env.update({k: str(v) for k, v in extra.items()})
        return env

    def test_dead_stage_publishes_partial_resumed_record(self, tmp_path):
        # The stage child SIGKILLs itself mid-supervised-pass (the
        # deterministic stand-in for a mid-run wedge/preemption): the
        # parent must publish a partial record tagged backend=resumed with
        # rounds-completed and a checkpoint path, not drop the stage.
        env = self._bench_env(tmp_path, BENCH_SUPERVISE_KILL_AT_ROUND="2")
        r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                           env=env, capture_output=True, text=True,
                           timeout=600, cwd=REPO)
        assert r.returncode == 0, r.stderr[-2000:]
        rec = json.loads(
            [ln for ln in r.stdout.splitlines() if ln.strip()][-1])
        assert rec["backend"] == "resumed"
        assert rec["rounds_completed"] >= 2
        assert os.path.exists(rec["checkpoint_path"])
        assert "error" in rec
        artifact_path = tmp_path / "BENCH_TELEMETRY.json"
        assert artifact_path.exists()
        artifact = json.loads(artifact_path.read_text())
        assert artifact["partial"] is True
        assert artifact["backend"] == "resumed"
        assert artifact["rounds_completed"] == rec["rounds_completed"]

        # Second run, kill seam disarmed: the supervised pass RESUMES the
        # trail (no restart from round 0) and the stage completes with a
        # real measured headline.
        env2 = self._bench_env(tmp_path)
        r2 = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                            env=env2, capture_output=True, text=True,
                            timeout=600, cwd=REPO)
        assert r2.returncode == 0, r2.stderr[-2000:]
        rec2 = json.loads(
            [ln for ln in r2.stdout.splitlines() if ln.strip()][-1])
        assert rec2["value"] is not None and rec2["value"] > 0
        assert rec2.get("backend") != "resumed"
        artifact2 = json.loads(artifact_path.read_text())
        sup = artifact2["supervised"]
        assert sup["resumed_from"] >= 2  # continued, not restarted


# --------------------------------------- SIGKILL crash-recovery subprocess

_CHILD = """
import hashlib, sys, time

import numpy as np

sys.path.insert(0, {repo!r})
import jax

from p2pnetwork_tpu.models import SIR
from p2pnetwork_tpu.sim import graph as G
from p2pnetwork_tpu.supervise import SupervisedRun

store_dir, sleep_s = sys.argv[1], float(sys.argv[2])
g = G.watts_strogatz(512, 6, 0.1, seed=3)


def on_chunk(run, info):
    if sleep_s:
        time.sleep(sleep_s)  # widen the SIGKILL window per chunk


run = SupervisedRun(g, SIR(beta=0.4, gamma=0.15), store_dir,
                    chunk_rounds=2, retain=50, on_chunk=on_chunk)
state, summary = run.run_rounds(jax.random.key(5), 30)
leaves = jax.tree_util.tree_leaves(jax.device_get(state))
h = hashlib.sha256()
for leaf in leaves:
    h.update(np.ascontiguousarray(leaf).tobytes())
print("DONE", h.hexdigest(), summary["rounds"], summary["resumed_from"],
      flush=True)
"""


@pytest.mark.slow
class TestSigkillRecovery:
    def _spawn(self, script, store_dir, sleep_s):
        return subprocess.Popen(
            [sys.executable, str(script), str(store_dir), str(sleep_s)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=REPO)

    def _entries(self, store_dir):
        try:
            with open(os.path.join(store_dir, "manifest.json"),
                      encoding="utf-8") as f:
                return json.load(f)["entries"]
        except (OSError, ValueError, KeyError):
            return []

    def _kill_at_round(self, script, store_dir, at_round):
        """Run the child until its checkpoint trail reaches ``at_round``,
        then SIGKILL it mid-run. Returns False (never fails) if the child
        finished before the kill landed — the box was too fast, and the
        other kill point still exercises the path."""
        p = self._spawn(script, store_dir, sleep_s=0.3)
        try:
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                rounds = [e["round"] for e in self._entries(store_dir)]
                if rounds and max(rounds) >= at_round:
                    os.kill(p.pid, signal.SIGKILL)
                    p.wait(timeout=30)
                    return True
                if p.poll() is not None:
                    return False  # finished before the kill landed
                time.sleep(0.02)
            pytest.fail("child never reached the kill point")
        finally:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)

    def test_sigkill_twice_resumed_state_bit_identical(self, tmp_path):
        script = tmp_path / "child.py"
        script.write_text(_CHILD.format(repo=REPO))

        # Reference: one uninterrupted child run.
        ref_dir = tmp_path / "ref"
        p = self._spawn(script, ref_dir, sleep_s=0.0)
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, err[-2000:]
        ref_line = [ln for ln in out.splitlines() if ln.startswith("DONE")][0]
        _, ref_digest, ref_rounds, _ = ref_line.split()

        # Killed run: SIGKILL mid-chunk at two different points of the
        # trail, then run to completion.
        kill_dir = tmp_path / "killed"
        killed_first = self._kill_at_round(script, kill_dir, 4)
        rounds_after_first = [e["round"] for e in self._entries(kill_dir)]
        killed_second = self._kill_at_round(script, kill_dir, 12)
        p = self._spawn(script, kill_dir, sleep_s=0.0)
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, err[-2000:]
        line = [ln for ln in out.splitlines() if ln.startswith("DONE")][0]
        _, digest, rounds, resumed_from = line.split()

        assert rounds == ref_rounds == "30"
        assert digest == ref_digest, (
            "resumed final state diverged from the uninterrupted run")
        if killed_first or killed_second:
            assert resumed_from != "None"  # at least one real resume
        if killed_first and rounds_after_first:
            # The second attempt resumed a partial trail, not round 0.
            assert max(rounds_after_first) < 30
