"""Blocked (one-hot matmul) and Pallas aggregation paths vs the segment
reference — exact equality on every graph family (Pallas runs in
interpreter mode on CPU)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from p2pnetwork_tpu.models import Flood  # noqa: E402
from p2pnetwork_tpu.ops import blocked as B  # noqa: E402
from p2pnetwork_tpu.ops import pallas_edge as PK  # noqa: E402
from p2pnetwork_tpu.ops import segment  # noqa: E402
from p2pnetwork_tpu.sim import engine  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


@pytest.fixture(params=["ws", "er", "ba"])
def graph(request):
    make = {
        "ws": lambda: G.watts_strogatz(400, 6, 0.2, seed=0),
        "er": lambda: G.erdos_renyi(500, 0.02, seed=1),
        "ba": lambda: G.barabasi_albert(300, 4, seed=2),
    }[request.param]
    return make().with_blocked()


class TestBlockedRepresentation:
    def test_lossless(self, graph):
        assert int(np.asarray(graph.blocked.mask).sum()) == graph.n_edges

    def test_local_dst_in_range(self, graph):
        ld = np.asarray(graph.blocked.local_dst)
        assert ld.min() >= 0 and ld.max() < graph.blocked.block


@pytest.mark.parametrize("method", ["blocked", "pallas"])
class TestAggregationEquality:
    def test_or_matches_segment(self, graph, method):
        key = jax.random.key(0)
        signal = jax.random.uniform(key, (graph.n_nodes_padded,)) < 0.15
        signal = signal & graph.node_mask
        ref = segment.propagate_or(graph, signal, "segment")
        out = segment.propagate_or(graph, signal, method)
        assert (np.asarray(out) == np.asarray(ref)).all()

    def test_sum_matches_segment(self, graph, method):
        key = jax.random.key(1)
        x = jax.random.normal(key, (graph.n_nodes_padded,), dtype=jnp.float32)
        x = x * graph.node_mask
        ref = np.asarray(segment.propagate_sum(graph, x, "segment"))
        out = np.asarray(segment.propagate_sum(graph, x, method))
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_flood_end_to_end(self, graph, method):
        ref_state, _ = engine.run(graph, Flood(source=0, method="segment"),
                                  jax.random.key(0), 5)
        state, _ = engine.run(graph, Flood(source=0, method=method),
                              jax.random.key(0), 5)
        assert (np.asarray(state.seen) == np.asarray(ref_state.seen)).all()


def test_pallas_nondefault_block_size():
    # Regression: the kernel used to hard-code block=128 and broke (or
    # silently dropped local_dst >= 128) for with_blocked(block=256).
    g = G.watts_strogatz(300, 4, 0.2, seed=5).with_blocked(block=256)
    signal = jnp.arange(g.n_nodes_padded, dtype=jnp.float32) * g.node_mask
    out = np.asarray(segment.propagate_sum(g, signal, "pallas"))
    ref = np.asarray(segment.propagate_sum(g, signal, "segment"))
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_gossip_capped_neighbor_table_unbiased():
    # Regression: with a width-capped table, sampling over full in_degree
    # clamped excess slots onto the last column (it got picked with
    # probability 6/9 in the reviewed repro). All stored neighbors must be
    # picked approximately uniformly.
    from p2pnetwork_tpu.models import Gossip

    hub_edges_src = np.arange(1, 10, dtype=np.int32)  # 9 in-neighbors of node 0
    hub_edges_dst = np.zeros(9, dtype=np.int32)
    g = G.from_edges(hub_edges_src, hub_edges_dst, 10, max_degree=4)
    proto = Gossip(alpha=1.0)  # node 0 copies its sampled partner's value
    counts = np.zeros(10)
    state = proto.init(g, jax.random.key(0))
    values = np.asarray(state.values)
    for i in range(400):
        nxt, _ = proto.step(g, state, jax.random.key(i))
        picked = np.asarray(nxt.values)[0]
        src = int(np.argmin(np.abs(values - picked)))
        counts[src] += 1
    stored = np.asarray(g.neighbors)[0][np.asarray(g.neighbor_mask)[0]]
    picks = counts[stored]
    assert picks.max() < 3 * max(picks.min(), 1), f"biased sampling: {counts}"


def test_pallas_wide_block_tiling():
    # A hub node forces a wide edge strip -> multiple width tiles per block.
    src = np.concatenate([np.arange(1, 1200, dtype=np.int32), [0, 0]])
    dst = np.concatenate([np.zeros(1199, dtype=np.int32), [1, 2]])
    g = G.from_edges(src, dst, 1200).with_blocked()
    assert g.blocked.width > PK.TILE_W  # exercises accumulation across tiles
    signal = jnp.ones(g.n_nodes_padded, dtype=jnp.float32) * g.node_mask
    out = np.asarray(segment.propagate_sum(g, signal, "pallas"))
    ref = np.asarray(segment.propagate_sum(g, signal, "segment"))
    np.testing.assert_allclose(out, ref, rtol=1e-5)
