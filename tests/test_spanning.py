"""SpanningTree: the extracted tree must be a valid rooted BFS tree of the
source's reachable component — parents are live in-neighbors one hop
closer to the source, depths match HopDistance exactly, and the parent
choice (highest-id deliverer) is deterministic."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from p2pnetwork_tpu.models import HopDistance, SpanningTree  # noqa: E402
from p2pnetwork_tpu.sim import engine, failures, topology  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def _edge_set(g):
    pairs = set()
    em = np.asarray(g.edge_mask)
    for s, r in zip(np.asarray(g.senders)[em], np.asarray(g.receivers)[em]):
        pairs.add((int(s), int(r)))
    if g.dyn_senders is not None:
        dm = np.asarray(g.dyn_mask)
        for s, r in zip(np.asarray(g.dyn_senders)[dm],
                        np.asarray(g.dyn_receivers)[dm]):
            pairs.add((int(s), int(r)))
    return pairs


def _check_tree(g, st, source):
    """Structural validity + BFS-depth parity against HopDistance."""
    parent = np.asarray(st.parent)
    dist = np.asarray(st.dist)
    alive = np.asarray(g.node_mask)
    edges = _edge_set(g)
    ref, _ = engine.run(g, HopDistance(source=source), jax.random.key(0), 64)
    ref_dist = np.asarray(ref.dist)
    np.testing.assert_array_equal(dist, ref_dist)  # same BFS layers
    assert parent[source] == source and dist[source] == 0
    for v in np.nonzero((parent >= 0) & alive)[0]:
        if v == source:
            continue
        p = int(parent[v])
        assert alive[p], f"dead parent {p} for {v}"
        assert (p, int(v)) in edges, f"parent edge {p}->{v} not in graph"
        assert dist[p] == dist[v] - 1, f"non-BFS parent depth at {v}"
    # Unreached nodes have no parent.
    assert (parent[ref_dist < 0] == -1).all()


class TestSpanningTree:
    @pytest.mark.parametrize("method", ["segment", "gather"])
    def test_ws_tree_is_valid(self, method):
        g = G.watts_strogatz(2048, 6, 0.2, seed=0)
        st, out = engine.run_until_coverage(
            g, SpanningTree(source=5, method=method), jax.random.key(0),
            coverage_target=1.0, max_rounds=64,
        )
        st2, _ = engine.run(g, SpanningTree(source=5, method=method),
                            jax.random.key(0), int(out["rounds"]))
        _check_tree(g, st2, 5)

    def test_parent_choice_is_highest_id(self):
        # Node 3 is fed by 0, 1 and 2 in round one: the deterministic
        # parent is the highest id, 2.
        senders = [0, 0, 0, 1, 2]
        receivers = [1, 2, 3, 3, 3]
        g = G.from_edges(senders, receivers, 8)
        st, _ = engine.run(g, SpanningTree(source=0), jax.random.key(0), 2)
        assert int(np.asarray(st.parent)[3]) == 0  # round 1: only 0 sends
        # Remove the direct 0->3 edge: now 3 is reached in round 2 via the
        # higher of {1, 2}.
        g2 = G.from_edges([0, 0, 1, 2], [1, 2, 3, 3], 8)
        st2, _ = engine.run(g2, SpanningTree(source=0), jax.random.key(0), 3)
        assert int(np.asarray(st2.parent)[3]) == 2

    def test_under_failures_and_links(self):
        g = failures.fail_nodes(G.watts_strogatz(1024, 6, 0.2, seed=1), [9])
        g = topology.connect(topology.with_capacity(g, extra_edges=8),
                             [2], [900])
        st, out = engine.run_until_coverage(
            g, SpanningTree(source=0), jax.random.key(0),
            coverage_target=1.0, max_rounds=64,
        )
        st2, _ = engine.run(g, SpanningTree(source=0), jax.random.key(0),
                            int(out["rounds"]))
        _check_tree(g, st2, 0)
        assert np.asarray(st2.parent)[9] == -1  # dead node outside the tree

    def test_disconnected_remainder_unreached(self):
        idx = np.arange(64)
        g = G.from_edges(np.concatenate([idx, 64 + idx]),
                         np.concatenate([(idx + 1) % 64,
                                         64 + (idx + 1) % 64]), 128)
        st, _ = engine.run(g, SpanningTree(source=0), jax.random.key(0), 70)
        parent = np.asarray(st.parent)
        assert (parent[:64] >= 0).all()
        assert (parent[64:128] == -1).all()
        proto = SpanningTree(source=0)
        assert float(proto.coverage(g, st)) == pytest.approx(0.5)


class TestSpanningTreeSharded:
    @pytest.mark.parametrize("n_shards", [2, 8])
    def test_tree_via_max_seam_matches_engine(self, n_shards):
        import jax.numpy as jnp

        from p2pnetwork_tpu.parallel import mesh as M, sharded

        g = G.watts_strogatz(1024, 6, 0.2, seed=2)
        mesh = M.ring_mesh(n_shards)
        sg = sharded.shard_graph(g, mesh)
        S, block = sg.n_shards, sg.block
        ids = jnp.arange(S * block, dtype=jnp.int32).reshape(S, block)
        neutral = jnp.int32(jnp.iinfo(jnp.int32).min)
        parent = jnp.where(
            (ids == 0) & sg.node_mask, 0, -1).astype(jnp.int32)
        frontier = (ids == 0) & sg.node_mask
        for _ in range(20):
            offer = jnp.where(frontier & sg.node_mask, ids, neutral)
            best = sharded.propagate(sg, mesh, offer, op="max")
            newly = (best >= 0) & (parent < 0) & sg.node_mask
            parent = jnp.where(newly, best, parent)
            frontier = newly
        ref, _ = engine.run(g, SpanningTree(source=0), jax.random.key(0), 20)
        np.testing.assert_array_equal(
            np.asarray(parent).reshape(-1), np.asarray(ref.parent))
