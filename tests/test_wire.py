"""Unit tests for the wire format (framing, codecs, parsing).

The reference has no unit tests at all — every test is a socket integration
test (SURVEY.md section 4). Testing the codec as pure functions is one of the
deliberate improvements."""

import json

import pytest

from p2pnetwork_tpu import wire


class TestCompression:
    @pytest.mark.parametrize("algo", ["zlib", "bzip2", "lzma"])
    def test_roundtrip(self, algo):
        raw = b"hello p2p world " * 100
        blob = wire.compress(raw, algo)
        assert blob != raw
        assert wire.decompress(blob) == raw

    @pytest.mark.parametrize("algo,tag", [("zlib", b"zlib"), ("bzip2", b"bzip2"), ("lzma", b"lzma")])
    def test_wire_format_is_b64_with_tag_suffix(self, algo, tag):
        # Parity with reference nodeconnection.py:63-70: base64(comp + tag).
        import base64

        blob = wire.compress(b"data", algo)
        decoded = base64.b64decode(blob)
        assert decoded.endswith(tag)

    def test_unknown_algorithm_raises(self):
        with pytest.raises(wire.UnknownCompressionError):
            wire.compress(b"data", "snappy")

    def test_decompress_unknown_tag_returns_decoded(self):
        import base64

        blob = base64.b64encode(b"not compressed at all")
        assert wire.decompress(blob) == b"not compressed at all"


class TestPayloads:
    def test_str_roundtrip(self):
        frame = wire.encode_frame("hello")
        assert frame == b"hello\x04"
        assert wire.parse_packet(frame[:-1]) == "hello"

    def test_dict_roundtrip(self):
        data = {"k": [1, 2, 3], "nested": {"a": "b"}}
        frame = wire.encode_frame(data)
        assert frame.endswith(wire.EOT_CHAR)
        assert wire.parse_packet(frame[:-1]) == data

    def test_bytes_roundtrip(self):
        payload = bytes(range(256))
        frame = wire.encode_frame(payload)
        assert wire.parse_packet(frame[:-1]) == payload

    def test_numeric_string_stays_parsed_as_json(self):
        # Parity quirk: the reference parses "42" back as the int 42 because
        # json.loads runs on every utf-8 payload [ref: nodeconnection.py:176-181].
        frame = wire.encode_frame("42")
        assert wire.parse_packet(frame[:-1]) == 42

    def test_invalid_type_raises(self):
        with pytest.raises(TypeError):
            wire.encode_payload(object())

    @pytest.mark.parametrize("algo", ["zlib", "bzip2", "lzma"])
    def test_compressed_frame_roundtrip(self, algo):
        data = {"payload": "x" * 5000}
        frame = wire.encode_frame(data, compression=algo)
        assert frame.endswith(wire.COMPR_CHAR + wire.EOT_CHAR)
        assert wire.parse_packet(frame[:-1]) == data


class TestFrameDecoder:
    def test_multiple_frames_in_one_chunk(self):
        dec = wire.FrameDecoder()
        chunk = wire.encode_frame("a") + wire.encode_frame({"b": 1}) + wire.encode_frame("c")
        packets = list(dec.feed(chunk))
        assert [wire.parse_packet(p) for p in packets] == ["a", {"b": 1}, "c"]
        assert dec.pending == 0

    def test_frame_split_across_chunks(self):
        dec = wire.FrameDecoder()
        frame = wire.encode_frame("x" * 10000)
        packets = []
        for i in range(0, len(frame), 4096):
            packets.extend(dec.feed(frame[i : i + 4096]))
        assert len(packets) == 1
        assert wire.parse_packet(packets[0]) == "x" * 10000

    def test_empty_frame_is_consumed(self):
        # Deliberate fix of SURVEY.md 2.3.2: the reference's `while eot_pos > 0`
        # never consumes an EOT at position 0 and wedges the stream.
        dec = wire.FrameDecoder()
        packets = list(dec.feed(wire.EOT_CHAR + wire.encode_frame("after")))
        assert packets == [b"", b"after"]
        assert dec.pending == 0

    def test_buffer_bound_enforced(self):
        # Deliberate fix of SURVEY.md 2.3.3 (unbounded recv buffer).
        dec = wire.FrameDecoder(max_buffer=1024)
        with pytest.raises(wire.FrameOverflowError):
            list(dec.feed(b"x" * 2048))
        # The decoder resets so the connection can report and die cleanly.
        assert dec.pending == 0


class TestLengthFraming:
    """framing="length": 4-byte big-endian prefix + flag byte + payload
    (wire.py). Not reference-compatible by design; carries arbitrary
    binary safely — including payloads ending in the 0x02 marker the EOT
    mode's sniff would strip."""

    def test_encode_frame_length_mode(self):
        frame = wire.encode_frame(b"\x04\x02\x00", framing="length")
        assert frame == ((4).to_bytes(4, "big") + wire.LENGTH_PLAIN
                         + b"\x04\x02\x00")

    def test_roundtrip_all_payload_types(self):
        dec = wire.make_decoder("length")
        # The last payload ENDS in 0x02 — the case the sniffing EOT chain
        # cannot carry raw.
        payloads = ["text", {"a": 1}, b"\xff\x04\xfe", b"\xff\x02"]
        stream = b"".join(
            wire.encode_frame(p, framing="length") for p in payloads)
        # Feed byte-by-byte to exercise partial-header and partial-body.
        out = []
        for i in range(len(stream)):
            out.extend(wire.parse_length_body(b)
                       for b in dec.feed(stream[i:i + 1]))
        assert out == payloads
        assert dec.pending == 0

    def test_compressed_body_carries_flag(self):
        dec = wire.make_decoder("length")
        frame = wire.encode_frame({"k": 2}, compression="lzma",
                                  framing="length")
        (body,) = list(dec.feed(frame))
        assert body[:1] == wire.LENGTH_COMPRESSED
        assert wire.parse_length_body(body) == {"k": 2}

    def test_oversize_declared_length_rejected_immediately(self):
        dec = wire.LengthFrameDecoder(max_buffer=1024)
        header = (1 << 30).to_bytes(4, "big")
        with pytest.raises(wire.FrameOverflowError):
            list(dec.feed(header))
        assert dec.pending == 0  # poisoned stream was dropped

    def test_empty_frame(self):
        dec = wire.make_decoder("length")
        (body,) = list(dec.feed(wire.encode_frame(b"", framing="length")))
        assert body == wire.LENGTH_PLAIN
        assert wire.parse_length_body(body) == ""  # decode chain: b"" -> ""

    def test_unknown_framing_rejected(self):
        with pytest.raises(ValueError, match="framing"):
            wire.encode_frame("x", framing="sctp")
        with pytest.raises(ValueError, match="framing"):
            wire.make_decoder("sctp")


def test_decompress_of_non_base64_garbage_returns_input():
    # The reference's decompress raises binascii.Error here (its b64decode
    # sits outside the try, nodeconnection.py:91); ours honors the
    # documented as-is contract for malformed frames.
    junk = b"\xff\xfenot base64!!"
    assert wire.decompress(junk) == junk
    # ...and parse_packet survives a garbage frame carrying the marker.
    assert wire.parse_packet(junk + wire.COMPR_CHAR) is not None
