"""Flood protocol: bit-exact parity with a BFS oracle, determinism, engine.

The sim replaces the reference's sleep-and-assert integration style
(SURVEY.md section 4) with exact assertions: flooding from one source for r
rounds must mark exactly the nodes at BFS distance <= r."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")


def test_auto_method_avoids_padded_gather_on_skewed_graphs():
    """'auto' must route degree-skewed graphs to the segment lowering:
    one hub widens EVERY padded neighbor-table row, and the flat
    per-slot gather floor then loses to sorted segment reductions
    (measured 33x at BA 100K — ops/segment._GATHER_WASTE_BOUND)."""
    from p2pnetwork_tpu.ops import segment as S
    from p2pnetwork_tpu.sim import graph as G

    ws = G.watts_strogatz(1024, 6, 0.1, seed=0)
    assert S._gather_ok(ws)  # quasi-regular: table waste ~1.5x, gather wins
    ba = G.barabasi_albert(1024, 3, seed=0)
    waste = ba.neighbors.shape[0] * ba.neighbors.shape[1] / ba.n_edges
    assert waste > S._GATHER_WASTE_BOUND  # the scenario the bound exists for
    assert not S._gather_ok(ba)
import networkx as nx  # noqa: E402

from p2pnetwork_tpu.models.flood import Flood  # noqa: E402
from p2pnetwork_tpu.sim import engine  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def bfs_levels(g: "G.Graph", source: int):
    """Oracle: BFS distances on the directed edge list via networkx."""
    nxg = nx.DiGraph()
    nxg.add_nodes_from(range(g.n_nodes))
    s = np.asarray(g.senders)[np.asarray(g.edge_mask)]
    r = np.asarray(g.receivers)[np.asarray(g.edge_mask)]
    nxg.add_edges_from(zip(s.tolist(), r.tolist()))
    return nx.single_source_shortest_path_length(nxg, source)


@pytest.mark.parametrize(
    "make",
    [
        lambda: G.erdos_renyi(1000, 0.01, seed=1),  # BASELINE configs[1] shape
        lambda: G.watts_strogatz(500, 6, 0.1, seed=2),
        lambda: G.barabasi_albert(300, 3, seed=3),
        lambda: G.ring(64),
    ],
)
def test_flood_matches_bfs_oracle(make):
    g = make()
    dist = bfs_levels(g, source=0)
    proto = Flood(source=0)
    key = jax.random.key(0)
    for rounds in (1, 3, 7):
        state, stats = engine.run(g, proto, key, rounds)
        seen = np.asarray(state.seen)[: g.n_nodes]
        expected = np.zeros(g.n_nodes, dtype=bool)
        for v, d in dist.items():
            expected[v] = d <= rounds
        assert (seen == expected).all(), f"round {rounds} mismatch"


def test_flood_is_deterministic():
    g = G.watts_strogatz(256, 4, 0.2, seed=5)
    key = jax.random.key(42)
    s1, st1 = engine.run(g, Flood(source=3), key, 5)
    s2, st2 = engine.run(g, Flood(source=3), key, 5)
    assert (np.asarray(s1.seen) == np.asarray(s2.seen)).all()
    np.testing.assert_array_equal(np.asarray(st1["messages"]), np.asarray(st2["messages"]))


def test_flood_stats_shapes_and_monotone_coverage():
    g = G.erdos_renyi(512, 0.02, seed=7)
    _, stats = engine.run(g, Flood(source=0), jax.random.key(0), 8)
    cov = np.asarray(stats["coverage"])
    assert cov.shape == (8,)
    assert (np.diff(cov) >= -1e-6).all()  # coverage never decreases
    assert np.asarray(stats["messages"]).dtype == np.int32


def test_messages_match_reference_counter_semantics():
    # A frontier node "sends" once per outgoing edge — the batched analog of
    # message_count_send incrementing per send_to_node [ref: node.py:116].
    g = G.ring(8)
    _, stats = engine.run(g, Flood(source=0), jax.random.key(0), 1)
    # Round 1: only the source broadcasts, to its 2 ring neighbors.
    assert int(np.asarray(stats["messages"])[0]) == 2


def test_run_until_coverage():
    g = G.watts_strogatz(1000, 6, 0.1, seed=9)
    state, out = engine.run_until_coverage(
        g, Flood(source=0), jax.random.key(0), coverage_target=0.99, max_rounds=64
    )
    assert float(out["coverage"]) >= 0.99
    assert 0 < int(out["rounds"]) < 64
    # Cross-check against the scan engine at the same round count.
    _, stats = engine.run(g, Flood(source=0), jax.random.key(0), int(out["rounds"]))
    assert float(np.asarray(stats["coverage"])[-1]) >= 0.99
    assert int(np.asarray(stats["messages"]).sum()) == int(out["messages"])


@pytest.mark.parametrize("method", ["segment", "gather"])
def test_methods_agree(method):
    g = G.barabasi_albert(200, 4, seed=11)
    state, _ = engine.run(g, Flood(source=0, method=method), jax.random.key(0), 4)
    state_auto, _ = engine.run(g, Flood(source=0, method="auto"), jax.random.key(0), 4)
    assert (np.asarray(state.seen) == np.asarray(state_auto.seen)).all()
