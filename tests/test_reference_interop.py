"""Live interoperability with the reference implementation.

The wire format (EOT framing, COMPR marker, tagged-b64 compression, plaintext
id handshake) is designed to be byte-compatible with the reference so a
tpu-p2p node can join a reference network (SURVEY.md section 7 step 1). When
the reference package is available on disk these tests prove it by speaking
to an actual reference ``Node`` over loopback; otherwise they skip."""

import os
import sys
import time

import pytest

from p2pnetwork_tpu import Node
from tests.helpers import EventRecorder, stop_all, wait_until

REFERENCE_PATH = "/root/reference"

if not os.path.isdir(os.path.join(REFERENCE_PATH, "p2pnetwork")):
    pytest.skip("reference implementation not available", allow_module_level=True)

sys.path.insert(0, REFERENCE_PATH)
from p2pnetwork.node import Node as ReferenceNode  # noqa: E402


@pytest.fixture
def ref_node():
    # The reference cannot bind port 0 meaningfully (it never re-reads the
    # chosen port), so pick a free port first.
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    received = []
    node = ReferenceNode(
        "127.0.0.1", port,
        callback=lambda ev, mn, cn, d: received.append((ev, d)),
    )
    node.start()
    yield node, port, received
    node.stop()
    node.join()


def test_ours_connects_and_messages_reference(ref_node):
    refnode, port, received = ref_node
    ours = Node("127.0.0.1", 0)
    ours.start()
    try:
        assert ours.connect_with_node("127.0.0.1", port)
        assert wait_until(lambda: len(ours.nodes_outbound) == 1)
        assert ours.nodes_outbound[0].id == refnode.id
        assert wait_until(lambda: len(refnode.nodes_inbound) == 1)

        ours.send_to_nodes("hello reference")
        ours.send_to_nodes({"answer": 42})
        ours.send_to_nodes("compressed hello", compression="zlib")
        assert wait_until(
            lambda: [d for e, d in received if e == "node_message"]
            == ["hello reference", {"answer": 42}, "compressed hello"],
            timeout=10.0,
        )
    finally:
        stop_all([ours])


def test_reference_connects_and_messages_ours(ref_node):
    refnode, port, _ = ref_node
    rec = EventRecorder()
    ours = Node("127.0.0.1", 0, callback=rec)
    ours.start()
    try:
        assert refnode.connect_with_node("127.0.0.1", ours.port)
        assert wait_until(lambda: len(ours.nodes_inbound) == 1)
        assert ours.nodes_inbound[0].id == refnode.id
        # Inbound port semantics: the peer's server port from the handshake.
        assert ours.nodes_inbound[0].port == port

        refnode.send_to_nodes("hello tpu")
        refnode.send_to_nodes({"k": [1, 2]}, compression="lzma")
        assert wait_until(lambda: rec.count("node_message") == 2, timeout=10.0)
        assert rec.data_for("node_message") == ["hello tpu", {"k": [1, 2]}]
    finally:
        stop_all([ours])
