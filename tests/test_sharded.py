"""Sharded ring propagation vs the single-device engine — bit-exact parity
on a real 8-device CPU mesh (conftest forces the virtual devices)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from p2pnetwork_tpu.models import Flood  # noqa: E402
from p2pnetwork_tpu.parallel import mesh as M  # noqa: E402
from p2pnetwork_tpu.parallel import sharded  # noqa: E402
from p2pnetwork_tpu.sim import engine  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def test_eight_virtual_devices_present():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("n_shards", [1, 2, 4, 8])
@pytest.mark.parametrize(
    "make",
    [
        lambda: G.watts_strogatz(512, 6, 0.2, seed=0),
        lambda: G.erdos_renyi(700, 0.01, seed=1),
        lambda: G.barabasi_albert(300, 3, seed=2),
    ],
)
def test_sharded_flood_matches_single_device(n_shards, make):
    g = make()
    mesh = M.ring_mesh(n_shards)
    sg = sharded.shard_graph(g, mesh)
    rounds = 6

    seen_sh, stats_sh = sharded.flood(sg, mesh, source=0, rounds=rounds)
    ref_state, ref_stats = engine.run(g, Flood(source=0), jax.random.key(0), rounds)

    seen_flat = np.asarray(seen_sh).reshape(-1)[: g.n_nodes]
    ref_seen = np.asarray(ref_state.seen)[: g.n_nodes]
    assert (seen_flat == ref_seen).all()

    np.testing.assert_array_equal(
        np.asarray(stats_sh["messages"]), np.asarray(ref_stats["messages"])
    )
    np.testing.assert_allclose(
        np.asarray(stats_sh["coverage"]),
        np.asarray(ref_stats["coverage"]),
        rtol=1e-6,
    )


def test_cross_shard_edges_resolve():
    # A ring graph sharded across 4 devices has every shard boundary crossed;
    # full coverage proves cross-shard edges deliver.
    g = G.ring(256)
    mesh = M.ring_mesh(4)
    sg = sharded.shard_graph(g, mesh)
    seen, stats = sharded.flood(sg, mesh, source=0, rounds=128)
    assert np.asarray(seen).reshape(-1)[:256].all()
    assert float(np.asarray(stats["coverage"])[-1]) == 1.0


def test_source_on_nonzero_shard():
    g = G.watts_strogatz(512, 4, 0.1, seed=3)
    mesh = M.ring_mesh(8)
    sg = sharded.shard_graph(g, mesh)
    src = 300  # lives on a middle shard
    seen_sh, _ = sharded.flood(sg, mesh, source=src, rounds=5)
    ref_state, _ = engine.run(g, Flood(source=src), jax.random.key(0), 5)
    assert (
        np.asarray(seen_sh).reshape(-1)[: g.n_nodes]
        == np.asarray(ref_state.seen)[: g.n_nodes]
    ).all()


def test_shard_graph_partition_is_lossless():
    g = G.erdos_renyi(400, 0.02, seed=4)
    mesh = M.ring_mesh(4)
    sg = sharded.shard_graph(g, mesh)
    # Total active bucketed edges == total active edges.
    assert int(np.asarray(sg.bkt_mask).sum()) == g.n_edges
    assert int(np.asarray(sg.node_mask).sum()) == g.n_nodes


@pytest.mark.parametrize("n_shards", [2, 8])
def test_sharded_sir_matches_single_device(n_shards):
    from p2pnetwork_tpu.models import SIR

    # 1024 = 8 * 128: S*block == n_pad, so exact_rng draws the same uniforms
    # as the single-device engine and the run is bit-identical.
    g = G.watts_strogatz(1024, 6, 0.2, seed=0)
    mesh = M.ring_mesh(n_shards)
    sg = sharded.shard_graph(g, mesh)
    proto = SIR(beta=0.4, gamma=0.15, source=3, method="segment")
    rounds = 8

    status_sh, stats_sh = sharded.sir(
        sg, mesh, proto, jax.random.key(7), rounds, exact_rng=True
    )
    ref_state, ref_stats = engine.run(g, proto, jax.random.key(7), rounds)

    flat = np.asarray(status_sh).reshape(-1)[: g.n_nodes]
    ref = np.asarray(ref_state.status)[: g.n_nodes]
    np.testing.assert_array_equal(flat, ref)
    np.testing.assert_array_equal(
        np.asarray(stats_sh["messages"]), np.asarray(ref_stats["messages"])
    )
    for k in ("s_frac", "i_frac", "r_frac", "coverage"):
        np.testing.assert_allclose(
            np.asarray(stats_sh[k]), np.asarray(ref_stats[k]), rtol=1e-6
        )


class TestTileRNG:
    """The scalable default RNG must be invariant across shard counts —
    the regression oracle the fold_in-per-shard mode lacked: the SAME
    population run on 1, 2, 4, or 8 shards gives the SAME epidemic."""

    def test_sir_invariant_across_shard_counts(self):
        from p2pnetwork_tpu.models import SIR

        g = G.watts_strogatz(1024, 6, 0.2, seed=0)
        proto = SIR(beta=0.4, gamma=0.15, source=3, method="segment")
        results = {}
        for n_shards in (1, 2, 4, 8):
            mesh = M.ring_mesh(n_shards)
            sg = sharded.shard_graph(g, mesh)
            assert sg.block % sharded.RNG_TILE == 0
            status, stats = sharded.sir(sg, mesh, proto, jax.random.key(7), 8)
            results[n_shards] = (
                np.asarray(status).reshape(-1),
                np.asarray(stats["coverage"]),
            )
        for n_shards in (2, 4, 8):
            np.testing.assert_array_equal(
                results[n_shards][0], results[1][0], err_msg=f"S={n_shards}"
            )
            np.testing.assert_array_equal(results[n_shards][1], results[1][1])

    def test_gossip_invariant_across_shard_counts(self):
        from p2pnetwork_tpu.models import Gossip

        g = G.barabasi_albert(1024, 3, seed=1)
        vals = {}
        for n_shards in (1, 8):
            mesh = M.ring_mesh(n_shards)
            sg = sharded.shard_graph(g, mesh)
            v, _ = sharded.gossip(sg, mesh, Gossip(alpha=0.5),
                                  jax.random.key(2), 6)
            vals[n_shards] = np.asarray(v).reshape(-1)
        np.testing.assert_array_equal(vals[8], vals[1])

    def test_fold_fallback_for_unaligned_blocks(self):
        from p2pnetwork_tpu.models import SIR

        g = G.watts_strogatz(640, 6, 0.2, seed=0)  # block 80: not tile-able
        mesh = M.ring_mesh(8)
        sg = sharded.shard_graph(g, mesh)
        assert sg.block % sharded.RNG_TILE != 0  # pin: fold path exercised
        assert sharded._resolve_rng(sg, False, None) == "fold"
        with pytest.raises(ValueError, match="rng must be"):
            sharded._resolve_rng(sg, False, "Tile")
        status, stats = sharded.sir(
            sg, mesh, SIR(beta=0.5, gamma=0.1, source=0), jax.random.key(0), 10
        )
        total = (np.asarray(stats["s_frac"]) + np.asarray(stats["i_frac"])
                 + np.asarray(stats["r_frac"]))
        np.testing.assert_allclose(total, 1.0, rtol=1e-6)
        assert float(np.asarray(stats["coverage"])[-1]) > 0.3


def test_sharded_sir_scalable_rng_is_plausible():
    # The fold_in-per-shard default is not bit-identical to the engine but
    # must still produce a real epidemic: infection spreads beyond the
    # source and conservation holds (s+i+r == 1).
    from p2pnetwork_tpu.models import SIR

    g = G.watts_strogatz(1024, 8, 0.1, seed=1)
    mesh = M.ring_mesh(8)
    sg = sharded.shard_graph(g, mesh)
    status, stats = sharded.sir(
        sg, mesh, SIR(beta=0.6, gamma=0.05, source=0), jax.random.key(0), 12
    )
    total = (np.asarray(stats["s_frac"]) + np.asarray(stats["i_frac"])
             + np.asarray(stats["r_frac"]))
    np.testing.assert_allclose(total, 1.0, rtol=1e-6)
    assert float(np.asarray(stats["coverage"])[-1]) > 0.5


class TestShardedChurn:
    """Failures and runtime links on the SHARDED representation — the same
    no-recompile mask flips as sim/failures.py / sim/topology.py, parity-
    tested bit-exact against the single-device engine."""

    def test_fail_nodes_matches_single_device(self):
        from p2pnetwork_tpu.sim import failures

        g = G.watts_strogatz(512, 6, 0.2, seed=0)
        mesh = M.ring_mesh(8)
        sg0 = sharded.shard_graph(g, mesh)
        # Empty failure set (a computed churn set can be empty) is a no-op,
        # like the sim counterpart — regression: float64 scatter indices.
        np.testing.assert_array_equal(
            np.asarray(sharded.fail_nodes(sg0, []).node_mask),
            np.asarray(sg0.node_mask),
        )
        sg = sharded.fail_nodes(sg0, [3, 200, 400])
        gf = failures.fail_nodes(g, [3, 200, 400])
        rounds = 6

        seen_sh, stats_sh = sharded.flood(sg, mesh, source=0, rounds=rounds)
        ref_state, ref_stats = engine.run(gf, Flood(source=0), jax.random.key(0), rounds)
        assert (
            np.asarray(seen_sh).reshape(-1)[: g.n_nodes]
            == np.asarray(ref_state.seen)[: g.n_nodes]
        ).all()
        np.testing.assert_array_equal(
            np.asarray(stats_sh["messages"]), np.asarray(ref_stats["messages"])
        )
        np.testing.assert_allclose(
            np.asarray(stats_sh["coverage"]), np.asarray(ref_stats["coverage"]),
            rtol=1e-6,
        )

    def test_random_failures_bit_identical(self):
        from p2pnetwork_tpu.sim import failures

        # 1024 = 8 * 128: S*block == n_pad, so the failure draw is the
        # same bernoulli mask as the single-device path.
        g = G.watts_strogatz(1024, 6, 0.2, seed=1)
        mesh = M.ring_mesh(8)
        key = jax.random.key(42)
        sg = sharded.random_node_failures(sharded.shard_graph(g, mesh), key, 0.3)
        gf = failures.random_node_failures(g, key, 0.3)
        np.testing.assert_array_equal(
            np.asarray(sg.node_mask).reshape(-1), np.asarray(gf.node_mask)
        )
        np.testing.assert_array_equal(
            np.asarray(sg.out_degree).reshape(-1), np.asarray(gf.out_degree)
        )
        seen_sh, _ = sharded.flood(sg, mesh, source=0, rounds=5)
        ref_state, _ = engine.run(gf, Flood(source=0), jax.random.key(0), 5)
        assert (
            np.asarray(seen_sh).reshape(-1)[: g.n_nodes]
            == np.asarray(ref_state.seen)[: g.n_nodes]
        ).all()

    def test_connect_matches_single_device(self):
        from p2pnetwork_tpu.sim import topology

        g = G.watts_strogatz(512, 4, 0.1, seed=2)
        mesh = M.ring_mesh(8)
        sg = sharded.with_capacity(sharded.shard_graph(g, mesh), 16)
        sg = sharded.connect(sg, [10, 77], [400, 205])

        gc = topology.with_capacity(g, extra_edges=16)
        gc = topology.connect(gc, [10, 77], [400, 205])

        np.testing.assert_array_equal(
            np.asarray(sg.out_degree).reshape(-1), np.asarray(gc.out_degree)
        )
        rounds = 6
        seen_sh, stats_sh = sharded.flood(sg, mesh, source=0, rounds=rounds)
        ref_state, ref_stats = engine.run(gc, Flood(source=0), jax.random.key(0), rounds)
        assert (
            np.asarray(seen_sh).reshape(-1)[: g.n_nodes]
            == np.asarray(ref_state.seen)[: g.n_nodes]
        ).all()
        np.testing.assert_array_equal(
            np.asarray(stats_sh["messages"]), np.asarray(ref_stats["messages"])
        )

    def test_connect_bridges_partition(self):
        # The reference's identity: topology mutation on a LIVE network
        # [ref: p2pnetwork/node.py:122]. A partitioned ring stalls the
        # flood; a runtime connect bridges it — with the same compiled
        # program (shapes unchanged).
        g = G.ring(256)
        mesh = M.ring_mesh(4)
        sg = sharded.fail_nodes(sharded.shard_graph(g, mesh), [64, 192])
        seen, _ = sharded.flood(sg, mesh, source=0, rounds=128)
        flat = np.asarray(seen).reshape(-1)
        assert not flat[65:192].any()  # far side unreachable
        sg = sharded.with_capacity(sg, 8)
        sg = sharded.connect(sg, [32], [128])
        seen2, _ = sharded.flood(sg, mesh, source=0, rounds=128)
        flat2 = np.asarray(seen2).reshape(-1)[:256]
        alive = np.asarray(sg.node_mask).reshape(-1)[:256]
        assert (flat2 | ~alive).all()

    def test_connect_duplicate_is_noop(self):
        g = G.ring(256)
        mesh = M.ring_mesh(4)
        sg = sharded.with_capacity(sharded.shard_graph(g, mesh), 8)
        sg = sharded.connect(sg, [0], [100])
        before = int(np.asarray(sg.dyn_mask).sum())
        assert before == 2  # both directions
        sg2 = sharded.connect(sg, [0, 0], [100, 1])  # dup pair + static edge
        assert int(np.asarray(sg2.dyn_mask).sum()) == before
        np.testing.assert_array_equal(
            np.asarray(sg2.out_degree), np.asarray(sg.out_degree)
        )

    def test_disconnect(self):
        g = G.ring(256)
        mesh = M.ring_mesh(4)
        sg = sharded.with_capacity(sharded.shard_graph(g, mesh), 8)
        sg = sharded.connect(sg, [0, 5], [100, 150])
        sg = sharded.disconnect(sg, [0, 0], [100, 100])  # dup query: once
        assert int(np.asarray(sg.dyn_mask).sum()) == 2  # 5<->150 survives
        out = np.asarray(sg.out_degree).reshape(-1)
        assert out[0] == 2 and out[100] == 2  # back to ring degrees
        assert out[5] == 3 and out[150] == 3

    def test_failures_kill_dynamic_links(self):
        from p2pnetwork_tpu.sim import failures, topology

        g = G.ring(256)
        mesh = M.ring_mesh(4)
        sg = sharded.with_capacity(sharded.shard_graph(g, mesh), 8)
        sg = sharded.connect(sg, [0], [100])
        sg = sharded.fail_nodes(sg, [0])
        gc = topology.connect(topology.with_capacity(g, extra_edges=8), [0], [100])
        gc = failures.fail_nodes(gc, [0])
        np.testing.assert_array_equal(
            np.asarray(sg.out_degree).reshape(-1), np.asarray(gc.out_degree)
        )
        seen, _ = sharded.flood(sg, mesh, source=100, rounds=4)
        ref, _ = engine.run(gc, Flood(source=100), jax.random.key(0), 4)
        assert (
            np.asarray(seen).reshape(-1)[:256] == np.asarray(ref.seen)[:256]
        ).all()

    def test_sir_under_churn_exact_parity(self):
        from p2pnetwork_tpu.models import SIR
        from p2pnetwork_tpu.sim import failures, topology

        g = G.watts_strogatz(1024, 6, 0.2, seed=3)
        mesh = M.ring_mesh(8)
        sg = sharded.with_capacity(sharded.shard_graph(g, mesh), 16)
        sg = sharded.fail_nodes(sg, [9, 500])
        sg = sharded.connect(sg, [4], [900])

        gc = topology.with_capacity(g, extra_edges=16)
        gc = failures.fail_nodes(gc, [9, 500])
        gc = topology.connect(gc, [4], [900])

        proto = SIR(beta=0.4, gamma=0.15, source=3, method="segment")
        status_sh, stats_sh = sharded.sir(
            sg, mesh, proto, jax.random.key(7), 8, exact_rng=True
        )
        ref_state, ref_stats = engine.run(gc, proto, jax.random.key(7), 8)
        np.testing.assert_array_equal(
            np.asarray(status_sh).reshape(-1)[: g.n_nodes],
            np.asarray(ref_state.status)[: g.n_nodes],
        )
        np.testing.assert_array_equal(
            np.asarray(stats_sh["messages"]), np.asarray(ref_stats["messages"])
        )
        for k in ("s_frac", "i_frac", "r_frac", "coverage"):
            np.testing.assert_allclose(
                np.asarray(stats_sh[k]), np.asarray(ref_stats[k]), rtol=1e-6
            )

    def test_shard_graph_consolidates_dynamic_edges(self):
        # Re-sharding a churned Graph is the documented consolidation path:
        # runtime links fold into the static buckets losslessly.
        from p2pnetwork_tpu.sim import topology

        g = topology.with_capacity(G.ring(256), extra_edges=8)
        g = topology.connect(g, [0], [128])
        mesh = M.ring_mesh(4)
        sg = sharded.shard_graph(g, mesh)
        assert int(np.asarray(sg.bkt_mask).sum()) == g.n_edges + 2
        seen, _ = sharded.flood(sg, mesh, source=0, rounds=3)
        ref, _ = engine.run(g, Flood(source=0), jax.random.key(0), 3)
        assert (
            np.asarray(seen).reshape(-1)[:256] == np.asarray(ref.seen)[:256]
        ).all()


class TestMxuBuckets:
    """shard_graph(mxu=True): the ring pass applies static buckets as
    one-hot matmuls (MXU) instead of segment reductions — measured ~1.8x
    per chip at 1M nodes (BENCH.md). Must stay bit-exact everywhere."""

    def _pair(self, g, mesh):
        return sharded.shard_graph(g, mesh, mxu=True)

    def test_flood_and_sir_parity(self):
        from p2pnetwork_tpu.models import SIR

        g = G.watts_strogatz(1024, 6, 0.2, seed=0)
        mesh = M.ring_mesh(8)
        sg = self._pair(g, mesh)
        assert sg.mxu_src is not None
        seen, stats = sharded.flood(sg, mesh, source=0, rounds=6)
        ref, ref_stats = engine.run(g, Flood(source=0), jax.random.key(0), 6)
        np.testing.assert_array_equal(
            np.asarray(seen).reshape(-1)[: g.n_nodes],
            np.asarray(ref.seen)[: g.n_nodes],
        )
        np.testing.assert_array_equal(
            np.asarray(stats["messages"]), np.asarray(ref_stats["messages"])
        )
        proto = SIR(beta=0.4, gamma=0.15, source=3, method="segment")
        st, _ = sharded.sir(sg, mesh, proto, jax.random.key(7), 8,
                            exact_rng=True)
        ref2, _ = engine.run(g, proto, jax.random.key(7), 8)
        np.testing.assert_array_equal(
            np.asarray(st).reshape(-1)[: g.n_nodes],
            np.asarray(ref2.status)[: g.n_nodes],
        )

    def test_churn_and_coverage_parity(self):
        from p2pnetwork_tpu.sim import failures, topology

        g = G.watts_strogatz(1024, 6, 0.2, seed=1)
        mesh = M.ring_mesh(8)
        sg = sharded.with_capacity(self._pair(g, mesh), 8)
        sg = sharded.fail_nodes(sg, [3, 500])  # re-masks mxu_mask too
        sg = sharded.connect(sg, [4], [900])
        gf = topology.connect(
            topology.with_capacity(failures.fail_nodes(g, [3, 500]),
                                   extra_edges=8), [4], [900])
        seen, _ = sharded.flood(sg, mesh, source=0, rounds=6)
        ref, _ = engine.run(gf, Flood(source=0), jax.random.key(0), 6)
        np.testing.assert_array_equal(
            np.asarray(seen).reshape(-1)[: g.n_nodes],
            np.asarray(ref.seen)[: g.n_nodes],
        )
        _, out = sharded.flood_until_coverage(sg, mesh, source=0)
        _, refo = engine.run_until_coverage(gf, Flood(source=0),
                                            jax.random.key(0))
        assert int(np.asarray(out["rounds"])) == int(np.asarray(refo["rounds"]))
        assert out["messages"] == refo["messages"]

    def test_checkpoint_carries_mxu_mask(self):
        g = G.ring(512)
        mesh = M.ring_mesh(4)
        sg = sharded.fail_nodes(self._pair(g, mesh), [7])
        ts = sharded.topology_state(sg)
        assert "mxu_mask" in ts
        fresh = self._pair(g, mesh)
        restored = sharded.apply_topology_state(fresh, ts)
        np.testing.assert_array_equal(
            np.asarray(restored.mxu_mask), np.asarray(sg.mxu_mask)
        )


class TestHybridSharded:
    """shard_graph(hybrid=True): ring-decomposed circular diagonals (static
    per-step shifts) + MXU remainder — the sharded mirror of ops/diag.py's
    gather-free fast path; 1.98 s -> 0.27 s at 1M on one chip (BENCH.md).
    Every graph family and churn op must stay bit-exact."""

    @pytest.mark.parametrize("n_shards", [1, 2, 8])
    @pytest.mark.parametrize("make", [
        lambda: G.watts_strogatz(1024, 6, 0.2, seed=0),
        lambda: G.ring(1024),
        lambda: G.barabasi_albert(1024, 3, seed=2),  # no diagonals: degrade
    ])
    def test_flood_parity(self, n_shards, make):
        g = make()
        mesh = M.ring_mesh(n_shards)
        sg = sharded.shard_graph(g, mesh, hybrid=True, min_count=64)
        seen, stats = sharded.flood(sg, mesh, source=0, rounds=6)
        ref, ref_stats = engine.run(g, Flood(source=0), jax.random.key(0), 6)
        np.testing.assert_array_equal(
            np.asarray(seen).reshape(-1)[: g.n_nodes],
            np.asarray(ref.seen)[: g.n_nodes],
        )
        np.testing.assert_array_equal(
            np.asarray(stats["messages"]), np.asarray(ref_stats["messages"])
        )

    def test_sir_exact_parity(self):
        from p2pnetwork_tpu.models import SIR

        g = G.watts_strogatz(1024, 6, 0.2, seed=0)
        mesh = M.ring_mesh(8)
        sg = sharded.shard_graph(g, mesh, hybrid=True, min_count=64)
        assert len(sg.diag_pieces) > 0
        proto = SIR(beta=0.4, gamma=0.15, source=3, method="segment")
        st, _ = sharded.sir(sg, mesh, proto, jax.random.key(7), 8,
                            exact_rng=True)
        ref, _ = engine.run(g, proto, jax.random.key(7), 8)
        np.testing.assert_array_equal(
            np.asarray(st).reshape(-1)[: g.n_nodes],
            np.asarray(ref.status)[: g.n_nodes],
        )

    def test_churn_and_coverage_parity(self):
        from p2pnetwork_tpu.sim import failures, topology

        g = G.watts_strogatz(1024, 6, 0.2, seed=1)
        mesh = M.ring_mesh(8)
        sg = sharded.with_capacity(
            sharded.shard_graph(g, mesh, hybrid=True, min_count=64), 8
        )
        sg = sharded.fail_nodes(sg, [3, 500])  # re-masks diag pieces too
        sg = sharded.connect(sg, [4], [900])
        gf = topology.connect(
            topology.with_capacity(failures.fail_nodes(g, [3, 500]),
                                   extra_edges=8), [4], [900])
        seen, _ = sharded.flood(sg, mesh, source=0, rounds=6)
        ref, _ = engine.run(gf, Flood(source=0), jax.random.key(0), 6)
        np.testing.assert_array_equal(
            np.asarray(seen).reshape(-1)[: g.n_nodes],
            np.asarray(ref.seen)[: g.n_nodes],
        )
        _, out = sharded.flood_until_coverage(sg, mesh, source=0)
        _, refo = engine.run_until_coverage(gf, Flood(source=0),
                                            jax.random.key(0))
        assert int(np.asarray(out["rounds"])) == int(np.asarray(refo["rounds"]))
        assert out["messages"] == refo["messages"]

    def test_consolidated_padded_node_edges_stay_in_remainder(self):
        # Regression: a dynamic edge from a joined SPARE node (id >= n)
        # folded in at re-shard has an offset-mod-n that can alias a real
        # diagonal; extraction marking it diag-covered both dropped the
        # real message and delivered a phantom one. Padded-endpoint edges
        # must never be diagonal candidates.
        from p2pnetwork_tpu.sim import topology

        # A ring MISSING the directed edge 8->7, so the offset-1 diagonal
        # slot at receiver 7 is vacant. Spare node 520's link 520->7 has
        # offset (520 - 7) mod 512 == 1: without the padded-sender
        # exclusion it fills that vacant slot — delivering a phantom 8->7
        # and dropping the real 520->7.
        base = np.arange(512, dtype=np.int32)
        src = np.concatenate([base, (base + 1) % 512])
        dst = np.concatenate([(base + 1) % 512, base])
        keep = ~((src == 8) & (dst == 7))
        g = G.from_edges(src[keep], dst[keep], 512)
        g = topology.with_capacity(g, extra_nodes=128, extra_edges=8)
        g = topology.join_node(g, 520, [7])
        mesh = M.ring_mesh(4)
        sg = sharded.shard_graph(g, mesh, hybrid=True, min_count=64)
        assert len(sg.diag_pieces) > 0
        rounds = 2
        seen, _ = sharded.flood(sg, mesh, source=520, rounds=rounds)
        ref, _ = engine.run(g, Flood(source=520), jax.random.key(0), rounds)
        np.testing.assert_array_equal(
            np.asarray(seen).reshape(-1)[: g.n_nodes_padded],
            np.asarray(ref.seen),
        )
        assert np.asarray(seen).reshape(-1)[7]  # the 520->7 link delivered

    def test_checkpoint_carries_diag_masks(self):
        g = G.ring(512)
        mesh = M.ring_mesh(4)
        sg = sharded.fail_nodes(
            sharded.shard_graph(g, mesh, hybrid=True, min_count=64), [7]
        )
        ts = sharded.topology_state(sg)
        assert "diag_masks" in ts and "mxu_mask" in ts
        fresh = sharded.shard_graph(g, mesh, hybrid=True, min_count=64)
        restored = sharded.apply_topology_state(fresh, ts)
        np.testing.assert_array_equal(
            np.asarray(restored.diag_masks), np.asarray(sg.diag_masks)
        )
        seen_a, _ = sharded.flood(sg, mesh, source=0, rounds=60)
        seen_b, _ = sharded.flood(restored, mesh, source=0, rounds=60)
        np.testing.assert_array_equal(np.asarray(seen_a), np.asarray(seen_b))


class TestShardedGossip:
    @pytest.mark.parametrize("n_shards", [1, 2, 8])
    def test_matches_single_device(self, n_shards):
        from p2pnetwork_tpu.models import Gossip

        # 1024 = 8 * 128: S*block == n_pad, so exact_rng reproduces the
        # engine's init draw and slot draws bit-for-bit.
        g = G.barabasi_albert(1024, 3, seed=0)
        mesh = M.ring_mesh(n_shards)
        sg = sharded.shard_graph(g, mesh)
        proto = Gossip(alpha=0.5)
        rounds = 6

        vals_sh, stats_sh = sharded.gossip(
            sg, mesh, proto, jax.random.key(5), rounds, exact_rng=True
        )
        ref_state, ref_stats = engine.run(g, proto, jax.random.key(5), rounds)
        np.testing.assert_array_equal(
            np.asarray(vals_sh).reshape(-1)[: g.n_nodes_padded],
            np.asarray(ref_state.values),
        )
        np.testing.assert_array_equal(
            np.asarray(stats_sh["messages"]), np.asarray(ref_stats["messages"])
        )
        for k in ("variance", "mean"):
            np.testing.assert_allclose(
                np.asarray(stats_sh[k]), np.asarray(ref_stats[k]),
                rtol=1e-4, atol=1e-6,
            )

    def test_under_failures_matches_single_device(self):
        from p2pnetwork_tpu.models import Gossip
        from p2pnetwork_tpu.sim import failures

        g = G.watts_strogatz(1024, 6, 0.1, seed=2)
        mesh = M.ring_mesh(8)
        key = jax.random.key(9)
        sg = sharded.random_node_failures(sharded.shard_graph(g, mesh), key, 0.25)
        gf = failures.random_node_failures(g, key, 0.25)
        np.testing.assert_array_equal(
            np.asarray(sg.in_degree).reshape(-1), np.asarray(gf.in_degree)
        )
        vals_sh, _ = sharded.gossip(
            sg, mesh, Gossip(alpha=0.5), jax.random.key(1), 5, exact_rng=True
        )
        ref_state, _ = engine.run(gf, Gossip(alpha=0.5), jax.random.key(1), 5)
        np.testing.assert_array_equal(
            np.asarray(vals_sh).reshape(-1), np.asarray(ref_state.values)
        )

    def test_after_connect_matches_single_device(self):
        # Regression: connect bumps in_degree but not the stored table; the
        # old min(in_degree, width) sampling window then hit padding slots
        # (node id 0) after a runtime connect. Sampling the k-th VALID slot
        # keeps both paths exact and garbage-free.
        from p2pnetwork_tpu.models import Gossip
        from p2pnetwork_tpu.sim import topology

        g = G.barabasi_albert(1024, 3, seed=0)
        mesh = M.ring_mesh(8)
        sg = sharded.with_capacity(sharded.shard_graph(g, mesh), 8)
        sg = sharded.connect(sg, [10], [900])
        gc = topology.connect(topology.with_capacity(g, extra_edges=8), [10], [900])
        vals_sh, _ = sharded.gossip(
            sg, mesh, Gossip(alpha=0.5), jax.random.key(3), 5, exact_rng=True
        )
        ref_state, _ = engine.run(gc, Gossip(alpha=0.5), jax.random.key(3), 5)
        np.testing.assert_array_equal(
            np.asarray(vals_sh).reshape(-1), np.asarray(ref_state.values)
        )

    def test_scalable_rng_converges(self):
        from p2pnetwork_tpu.models import Gossip

        g = G.barabasi_albert(1024, 4, seed=1)
        mesh = M.ring_mesh(8)
        sg = sharded.shard_graph(g, mesh)
        _, stats = sharded.gossip(sg, mesh, Gossip(alpha=0.5),
                                  jax.random.key(0), 40)
        var = np.asarray(stats["variance"])
        assert var[-1] < var[0] / 100  # consensus forming

    def test_requires_neighbor_table(self):
        from p2pnetwork_tpu.models import Gossip

        g = G.ring(256, build_neighbor_table=False)
        mesh = M.ring_mesh(4)
        sg = sharded.shard_graph(g, mesh)
        with pytest.raises(ValueError, match="neighbor table"):
            sharded.gossip(sg, mesh, Gossip(), jax.random.key(0), 2)


class TestShardedTopologyCheckpoint:
    def test_orbax_roundtrip_restores_churned_graph(self, tmp_path):
        # The multi-chip mirror of topology-as-checkpoint-state: a sharded
        # graph that failed nodes and grew links checkpoints via orbax
        # (shardings preserved) and restores onto a fresh shard of the same
        # pristine construction, continuing bit-identically.
        from p2pnetwork_tpu.sim import checkpoint as ckpt

        g = G.watts_strogatz(1024, 6, 0.2, seed=6)
        mesh = M.ring_mesh(8)
        sg = sharded.with_capacity(sharded.shard_graph(g, mesh), 8)
        sg = sharded.fail_nodes(sg, [7, 300])
        sg = sharded.connect(sg, [2], [800])
        path = str(tmp_path / "sharded_topo")
        ckpt.save_orbax(path, sharded.topology_state(sg), jax.random.key(0), 4)

        fresh = sharded.with_capacity(sharded.shard_graph(g, mesh), 8)
        template = sharded.topology_state(fresh)
        ts, _, rnd, _ = ckpt.load_orbax(path, template)
        assert rnd == 4
        restored = sharded.apply_topology_state(fresh, ts)
        assert restored.node_mask.sharding.device_set == sg.node_mask.sharding.device_set
        seen_a, stats_a = sharded.flood(sg, mesh, source=0, rounds=5)
        seen_b, stats_b = sharded.flood(restored, mesh, source=0, rounds=5)
        np.testing.assert_array_equal(np.asarray(seen_a), np.asarray(seen_b))
        np.testing.assert_array_equal(
            np.asarray(stats_a["messages"]), np.asarray(stats_b["messages"])
        )

    def test_mismatch_rejected(self):
        g = G.ring(256)
        mesh = M.ring_mesh(4)
        sg_cap = sharded.with_capacity(sharded.shard_graph(g, mesh), 8)
        sg_plain = sharded.shard_graph(g, mesh)
        with pytest.raises(ValueError, match="keys mismatch"):
            sharded.apply_topology_state(
                sg_plain, sharded.topology_state(sg_cap)
            )


class TestShardedCoverage:
    def test_until_coverage_matches_engine(self):
        g = G.watts_strogatz(512, 6, 0.2, seed=0)
        mesh = M.ring_mesh(8)
        sg = sharded.shard_graph(g, mesh)
        seen, out = sharded.flood_until_coverage(sg, mesh, source=0)
        ref_state, ref_out = engine.run_until_coverage(
            g, Flood(source=0), jax.random.key(0)
        )
        assert int(np.asarray(out["rounds"])) == int(np.asarray(ref_out["rounds"]))
        assert out["messages"] == ref_out["messages"]
        np.testing.assert_allclose(
            float(np.asarray(out["coverage"])),
            float(np.asarray(ref_out["coverage"])), rtol=1e-6,
        )
        assert (
            np.asarray(seen).reshape(-1)[: g.n_nodes]
            == np.asarray(ref_state.seen)[: g.n_nodes]
        ).all()

    def test_until_coverage_under_churn(self):
        from p2pnetwork_tpu.sim import failures

        g = G.watts_strogatz(1024, 6, 0.2, seed=5)
        mesh = M.ring_mesh(8)
        key = jax.random.key(11)
        sg = sharded.random_node_failures(sharded.shard_graph(g, mesh), key, 0.2)
        gf = failures.random_node_failures(g, key, 0.2)
        _, out = sharded.flood_until_coverage(sg, mesh, source=0)
        _, ref_out = engine.run_until_coverage(gf, Flood(source=0), jax.random.key(0))
        assert int(np.asarray(out["rounds"])) == int(np.asarray(ref_out["rounds"]))
        assert out["messages"] == ref_out["messages"]

    def test_sir_until_coverage_matches_engine(self):
        from p2pnetwork_tpu.models import SIR

        g = G.watts_strogatz(1024, 6, 0.2, seed=0)
        mesh = M.ring_mesh(8)
        sg = sharded.shard_graph(g, mesh)
        proto = SIR(beta=0.5, gamma=0.1, source=0, method="segment")
        status, out = sharded.sir_until_coverage(
            sg, mesh, proto, jax.random.key(9), coverage_target=0.8,
            max_rounds=64, exact_rng=True,
        )
        ref_state, ref_out = engine.run_until_coverage(
            g, proto, jax.random.key(9), coverage_target=0.8, max_rounds=64
        )
        assert int(np.asarray(out["rounds"])) == int(np.asarray(ref_out["rounds"]))
        assert out["messages"] == ref_out["messages"]
        np.testing.assert_array_equal(
            np.asarray(status).reshape(-1)[: g.n_nodes],
            np.asarray(ref_state.status)[: g.n_nodes],
        )

    def test_max_rounds_cap(self):
        g = G.ring(256)  # diameter 128: can't reach 99% in 3 rounds
        mesh = M.ring_mesh(4)
        sg = sharded.shard_graph(g, mesh)
        _, out = sharded.flood_until_coverage(sg, mesh, source=0, max_rounds=3)
        assert int(np.asarray(out["rounds"])) == 3
        assert float(np.asarray(out["coverage"])) < 0.99


class TestAutoSharding:
    @pytest.mark.parametrize("protocol_name", [
        "flood", "sir", "gossip", "components", "mis", "kcore", "bipartite",
    ])
    def test_auto_matches_single_device(self, protocol_name):
        from p2pnetwork_tpu.models import (
            SIR, BipartiteCheck, ConnectedComponents, Flood, Gossip, KCore,
            LubyMIS,
        )
        from p2pnetwork_tpu.parallel import auto

        proto = {
            "flood": Flood(source=0, method="segment"),
            "sir": SIR(beta=0.3, gamma=0.1, method="segment"),
            "gossip": Gossip(alpha=0.5),
            "components": ConnectedComponents(method="segment"),
            "mis": LubyMIS(method="segment", or_method="segment"),
            "kcore": KCore(k=4, method="segment"),
            "bipartite": BipartiteCheck(method="segment"),
        }[protocol_name]
        g = G.watts_strogatz(512, 6, 0.2, seed=0)
        mesh = M.ring_mesh(8)
        gs = auto.shard_graph_auto(g, mesh)

        state, stats = auto.run_auto(gs, proto, jax.random.key(0), 5)
        ref_state, ref_stats = engine.run(g, proto, jax.random.key(0), 5)

        s = jax.tree.leaves(state)[0]
        r = jax.tree.leaves(ref_state)[0]
        # GSPMD may reorder float reductions; values agree to tolerance.
        np.testing.assert_allclose(
            np.asarray(s, dtype=np.float32), np.asarray(r, dtype=np.float32),
            rtol=1e-5, atol=1e-6,
        )

    def test_auto_graph_is_actually_sharded(self):
        from p2pnetwork_tpu.parallel import auto

        # Big enough that the bucket counts divide the 8 shards (the
        # divisibility guard replicates tiny layouts instead).
        g = G.watts_strogatz(8192, 4, 0.1, seed=0, hybrid=True, blocked=True)
        mesh = M.ring_mesh(8)
        gs = auto.shard_graph_auto(g, mesh)
        assert len(gs.node_mask.sharding.device_set) == 8
        assert len(gs.senders.sharding.device_set) == 8
        # The kernel layouts carry over ONTO the mesh (round 4): diagonal
        # masks sharded on their node axis, remainder/blocked buckets on
        # their destination-block axis — not dropped, not replicated.
        assert gs.hybrid is not None and gs.blocked is not None
        assert not gs.hybrid.masks.sharding.is_fully_replicated
        assert not gs.blocked.src.sharding.is_fully_replicated
        if gs.hybrid.remainder is not None:
            assert not gs.hybrid.remainder.src.sharding.is_fully_replicated


class TestShardedValueProtocols:
    """PageRank / PushSum on the ring, and the generic propagate seam.

    Edge sums accumulate in bucket/ring order here vs receiver order on the
    engine, so value parity is to f32 tolerance (unlike the bit-exact OR
    and integer-sum protocols)."""

    @pytest.mark.parametrize("n_shards", [1, 2, 8])
    def test_pagerank_matches_single_device(self, n_shards):
        from p2pnetwork_tpu.models import PageRank

        g = G.barabasi_albert(1024, 3, seed=0)
        mesh = M.ring_mesh(n_shards)
        sg = sharded.shard_graph(g, mesh)
        proto = PageRank(damping=0.85)
        rounds = 20

        ranks_sh, stats_sh = sharded.pagerank(sg, mesh, proto, rounds)
        ref_state, ref_stats = engine.run(g, proto, jax.random.key(0), rounds)
        np.testing.assert_allclose(
            np.asarray(ranks_sh).reshape(-1)[: g.n_nodes],
            np.asarray(ref_state.ranks)[: g.n_nodes],
            rtol=1e-4, atol=1e-9,
        )
        np.testing.assert_array_equal(
            np.asarray(stats_sh["messages"]), np.asarray(ref_stats["messages"])
        )
        np.testing.assert_allclose(
            np.asarray(stats_sh["rank_total"]), 1.0, atol=1e-4
        )

    def test_pagerank_under_churn_matches_single_device(self):
        from p2pnetwork_tpu.models import PageRank
        from p2pnetwork_tpu.sim import failures, topology

        g = G.watts_strogatz(1024, 6, 0.1, seed=2)
        mesh = M.ring_mesh(8)
        sg = sharded.with_capacity(
            sharded.fail_nodes(sharded.shard_graph(g, mesh), [7, 500]), 8
        )
        sg = sharded.connect(sg, [10], [900])
        gc = topology.connect(
            topology.with_capacity(failures.fail_nodes(g, [7, 500]),
                                   extra_edges=8),
            [10], [900],
        )
        ranks_sh, _ = sharded.pagerank(sg, mesh, PageRank(), 10)
        ref_state, _ = engine.run(gc, PageRank(), jax.random.key(0), 10)
        np.testing.assert_allclose(
            np.asarray(ranks_sh).reshape(-1)[: g.n_nodes],
            np.asarray(ref_state.ranks)[: g.n_nodes],
            rtol=1e-4, atol=1e-9,
        )
        assert np.asarray(ranks_sh).reshape(-1)[7] == 0.0

    @pytest.mark.parametrize("n_shards", [1, 2, 8])
    def test_pushsum_matches_single_device(self, n_shards):
        from p2pnetwork_tpu.models import PushSum

        # 1024 = 8 * 128: S*block == n_pad, so the init draw matches the
        # engine's bit-for-bit (Gossip-init parity).
        g = G.barabasi_albert(1024, 3, seed=1)
        mesh = M.ring_mesh(n_shards)
        sg = sharded.shard_graph(g, mesh)
        proto = PushSum()
        key = jax.random.key(5)
        rounds = 10

        (s_sh, w_sh), stats_sh = sharded.pushsum(sg, mesh, proto, key, rounds)
        ref_state, ref_stats = engine.run(g, proto, key, rounds)
        np.testing.assert_allclose(
            np.asarray(s_sh).reshape(-1)[: g.n_nodes],
            np.asarray(ref_state.s)[: g.n_nodes], rtol=1e-4, atol=1e-7,
        )
        np.testing.assert_allclose(
            np.asarray(w_sh).reshape(-1)[: g.n_nodes],
            np.asarray(ref_state.w)[: g.n_nodes], rtol=1e-4, atol=1e-7,
        )
        # Conservation on the sharded path: sum(w) == live count.
        np.testing.assert_allclose(
            np.asarray(stats_sh["w_total"]), g.n_nodes, rtol=1e-5
        )

    def test_pushsum_conservation_under_failures(self):
        from p2pnetwork_tpu.models import PushSum

        g = G.watts_strogatz(1024, 6, 0.1, seed=3)
        mesh = M.ring_mesh(8)
        sg = sharded.fail_nodes(sharded.shard_graph(g, mesh), [3, 900])
        key = jax.random.key(7)
        (s_sh, w_sh), stats_sh = sharded.pushsum(sg, mesh, PushSum(), key, 15)
        s0 = np.asarray(sharded.init_state(sg, PushSum(), key)[0]).sum()
        np.testing.assert_allclose(np.asarray(stats_sh["s_total"])[-1], s0,
                                   rtol=1e-4)
        np.testing.assert_allclose(np.asarray(stats_sh["w_total"])[-1], 1022,
                                   rtol=1e-5)

    @pytest.mark.parametrize("op", ["or", "sum"])
    @pytest.mark.parametrize("hybrid", [False, True])
    def test_generic_propagate_matches_segment(self, op, hybrid):
        from p2pnetwork_tpu.ops import segment

        g = G.watts_strogatz(1024, 6, 0.2, seed=4)
        mesh = M.ring_mesh(8)
        sg = sharded.shard_graph(g, mesh, hybrid=hybrid, min_count=32)
        S, block = sg.n_shards, sg.block
        key = jax.random.key(11)
        if op == "or":
            sig = jax.random.bernoulli(key, 0.1, (S * block,))
            ref = segment.propagate_or(g, sig[: g.n_nodes_padded], "segment")
        else:
            sig = jax.random.normal(key, (S * block,), dtype=jnp.float32)
            ref = segment.propagate_sum(g, sig[: g.n_nodes_padded], "segment")
        out = sharded.propagate(sg, mesh, sig.reshape(S, block), op=op)
        flat = np.asarray(out).reshape(-1)[: g.n_nodes]
        want = np.asarray(ref)[: g.n_nodes]
        if op == "or":
            np.testing.assert_array_equal(flat, want)
        else:
            np.testing.assert_allclose(flat, want, rtol=1e-4, atol=1e-6)

    def test_generic_propagate_sees_dynamic_edges(self):
        from p2pnetwork_tpu.ops import segment
        from p2pnetwork_tpu.sim import topology

        g = G.ring(512)
        mesh = M.ring_mesh(4)
        sg = sharded.connect(
            sharded.with_capacity(sharded.shard_graph(g, mesh), 8),
            [100], [400],
        )
        gc = topology.connect(topology.with_capacity(g, extra_edges=8),
                              [100], [400])
        sig = jnp.zeros(sg.n_nodes_padded, dtype=bool).at[100].set(True)
        out = sharded.propagate(sg, mesh,
                                sig.reshape(sg.n_shards, sg.block), op="or")
        ref = segment.propagate_or(gc, sig[: gc.n_nodes_padded])
        np.testing.assert_array_equal(
            np.asarray(out).reshape(-1)[:512], np.asarray(ref)[:512]
        )


class TestShardedHopDistance:
    @pytest.mark.parametrize("n_shards", [1, 2, 8])
    def test_matches_single_device(self, n_shards):
        from p2pnetwork_tpu.models import HopDistance

        g = G.watts_strogatz(1024, 6, 0.2, seed=0)
        mesh = M.ring_mesh(n_shards)
        sg = sharded.shard_graph(g, mesh)
        proto = HopDistance(source=5)
        rounds = 6

        (dist_sh, _, rnd), stats_sh = sharded.hopdist(sg, mesh, proto, rounds)
        ref_state, ref_stats = engine.run(g, proto, jax.random.key(0), rounds)
        np.testing.assert_array_equal(
            np.asarray(dist_sh).reshape(-1)[: g.n_nodes],
            np.asarray(ref_state.dist)[: g.n_nodes],
        )
        assert int(np.asarray(rnd)) == rounds
        for k in ("messages", "frontier", "max_dist"):
            np.testing.assert_array_equal(
                np.asarray(stats_sh[k]), np.asarray(ref_stats[k])
            )

    def test_until_done_full_bfs(self):
        from p2pnetwork_tpu.models import HopDistance

        g = G.ring(256)  # eccentricity 128, wave dies at round 128
        mesh = M.ring_mesh(4)
        sg = sharded.shard_graph(g, mesh)
        (dist, frontier, rnd), out = sharded.hopdist_until_done(
            sg, mesh, HopDistance(source=0)
        )
        dist_flat = np.asarray(dist).reshape(-1)[:256]
        ref = np.minimum(np.arange(256), 256 - np.arange(256))
        np.testing.assert_array_equal(dist_flat, ref)
        # 128 delivery rounds + the final round that proves the frontier
        # died (frontier-based termination observes emptiness one round
        # after the last delivery); eccentricity is max(dist) = 128.
        assert out["rounds"] == 129
        assert out["coverage"] == 1.0
        assert not np.asarray(frontier).any()
        # Resume from the finished state: zero further rounds.
        (_, _, _), out2 = sharded.hopdist_until_done(
            sg, mesh, HopDistance(source=0),
            state0=(dist, frontier, jnp.int32(int(np.asarray(rnd)))),
        )
        assert out2["rounds"] == 0

    def test_under_churn_matches_single_device(self):
        from p2pnetwork_tpu.models import HopDistance
        from p2pnetwork_tpu.sim import failures, topology

        g = G.watts_strogatz(1024, 6, 0.1, seed=2)
        mesh = M.ring_mesh(8)
        sg = sharded.with_capacity(
            sharded.fail_nodes(sharded.shard_graph(g, mesh), [9, 700]), 8
        )
        sg = sharded.connect(sg, [11], [901])
        gc = topology.connect(
            topology.with_capacity(failures.fail_nodes(g, [9, 700]),
                                   extra_edges=8),
            [11], [901],
        )
        (dist_sh, _, _), _ = sharded.hopdist(sg, mesh, HopDistance(source=0), 8)
        ref_state, _ = engine.run(gc, HopDistance(source=0),
                                  jax.random.key(0), 8)
        np.testing.assert_array_equal(
            np.asarray(dist_sh).reshape(-1)[: g.n_nodes],
            np.asarray(ref_state.dist)[: g.n_nodes],
        )
        assert np.asarray(dist_sh).reshape(-1)[9] == -1


class TestShardedAdaptiveFlood:
    """Frontier-adaptive run-to-coverage on the ring: bit-identical to the
    dense sharded loop and the single-device engine through sparse-only,
    crossing, and churned regimes."""

    @pytest.mark.parametrize("n_shards", [1, 2, 8])
    @pytest.mark.parametrize("k", [16, 256])
    def test_matches_dense_loop_and_engine(self, n_shards, k):
        from p2pnetwork_tpu.models import Flood

        g = G.watts_strogatz(1024, 6, 0.2, seed=0)
        mesh = M.ring_mesh(n_shards)
        sg = sharded.shard_graph(g, mesh, source_csr=True)
        seen_a, out_a = sharded.flood_until_coverage(
            sg, mesh, source=0, coverage_target=0.99, adaptive_k=k
        )
        seen_d, out_d = sharded.flood_until_coverage(
            sg, mesh, source=0, coverage_target=0.99
        )
        np.testing.assert_array_equal(np.asarray(seen_a), np.asarray(seen_d))
        assert out_a == out_d
        _, ref = engine.run_until_coverage(
            g, Flood(source=0), jax.random.key(0), coverage_target=0.99
        )
        assert out_a["rounds"] == ref["rounds"]
        assert out_a["messages"] == ref["messages"]

    def test_hybrid_layout_and_churn(self):
        from p2pnetwork_tpu.models import Flood
        from p2pnetwork_tpu.sim import failures, topology

        g = G.watts_strogatz(1024, 6, 0.2, seed=1)
        mesh = M.ring_mesh(8)
        sg = sharded.shard_graph(g, mesh, hybrid=True, min_count=32,
                                 source_csr=True)
        sg = sharded.with_capacity(sharded.fail_nodes(sg, [3, 700]), 8)
        sg = sharded.connect(sg, [2], [900])
        gc = topology.connect(
            topology.with_capacity(failures.fail_nodes(g, [3, 700]),
                                   extra_edges=8),
            [2], [900],
        )
        seen_a, out_a = sharded.flood_until_coverage(
            sg, mesh, source=0, coverage_target=0.95, adaptive_k=64
        )
        _, ref = engine.run_until_coverage(
            gc, Flood(source=0), jax.random.key(0), coverage_target=0.95
        )
        assert out_a["rounds"] == ref["rounds"]
        assert out_a["messages"] == ref["messages"]
        assert not np.asarray(seen_a).reshape(-1)[3]

    def test_dynamic_link_carries_in_sparse_mode(self):
        # On a ring with k large enough to stay sparse the whole run, a
        # runtime link must jump the wave across the ring.
        from p2pnetwork_tpu.models import Flood
        from p2pnetwork_tpu.sim import topology

        g = G.ring(512)
        mesh = M.ring_mesh(4)
        sg = sharded.connect(
            sharded.with_capacity(
                sharded.shard_graph(g, mesh, source_csr=True), 8
            ),
            [100], [400],
        )
        gc = topology.connect(topology.with_capacity(g, extra_edges=8),
                              [100], [400])
        seen_a, out_a = sharded.flood_until_coverage(
            sg, mesh, source=0, coverage_target=0.5, adaptive_k=1024,
            max_rounds=200,
        )
        _, ref = engine.run_until_coverage(
            gc, Flood(source=0), jax.random.key(0), coverage_target=0.5,
            max_rounds=200,
        )
        assert out_a["rounds"] == ref["rounds"]
        assert out_a["messages"] == ref["messages"]

    def test_requires_csr(self):
        g = G.ring(256)
        mesh = M.ring_mesh(2)
        sg = sharded.shard_graph(g, mesh)
        with pytest.raises(ValueError, match="source_csr"):
            sharded.flood_until_coverage(sg, mesh, source=0, adaptive_k=32)

    def test_resume_state_roundtrip(self):
        from p2pnetwork_tpu.models import Flood

        g = G.watts_strogatz(1024, 6, 0.1, seed=2)
        mesh = M.ring_mesh(8)
        sg = sharded.shard_graph(g, mesh, source_csr=True)
        state, out1 = sharded.flood_until_coverage(
            sg, mesh, source=0, coverage_target=0.3, adaptive_k=64,
            return_state=True,
        )
        state, out2 = sharded.flood_until_coverage(
            sg, mesh, source=0, coverage_target=0.99, adaptive_k=64,
            state0=state, return_state=True,
        )
        _, ref = engine.run_until_coverage(
            g, Flood(source=0), jax.random.key(0), coverage_target=0.99
        )
        assert out1["rounds"] + out2["rounds"] == ref["rounds"]
        assert out1["messages"] + out2["messages"] == ref["messages"]


class TestShardedPageRankResidual:
    @pytest.mark.parametrize("n_shards", [1, 8])
    def test_matches_engine_loop(self, n_shards):
        from p2pnetwork_tpu.models import PageRank

        g = G.barabasi_albert(1024, 3, seed=0)
        mesh = M.ring_mesh(n_shards)
        sg = sharded.shard_graph(g, mesh)
        ranks, out = sharded.pagerank_until_residual(
            sg, mesh, PageRank(), tol=1e-5
        )
        _, ref = engine.run_until_converged(
            g, PageRank(), jax.random.key(0), stat="residual",
            threshold=1e-5,
        )
        # f32 summation order differs (ring vs receiver order), so the
        # loop may exit one round apart right at the threshold; rank
        # values agree to tolerance either way.
        assert abs(out["rounds"] - ref["rounds"]) <= 1
        assert out["value"] < 1e-5
        ref_state, _ = engine.run(g, PageRank(), jax.random.key(0),
                                  out["rounds"])
        np.testing.assert_allclose(
            np.asarray(ranks).reshape(-1)[: g.n_nodes],
            np.asarray(ref_state.ranks)[: g.n_nodes],
            rtol=1e-4, atol=1e-9,
        )

    def test_under_churn(self):
        from p2pnetwork_tpu.models import PageRank
        from p2pnetwork_tpu.sim import failures

        g = G.watts_strogatz(1024, 6, 0.1, seed=1)
        mesh = M.ring_mesh(8)
        sg = sharded.fail_nodes(sharded.shard_graph(g, mesh), [5, 600])
        gf = failures.fail_nodes(g, [5, 600])
        ranks, out = sharded.pagerank_until_residual(
            sg, mesh, PageRank(), tol=1e-5
        )
        assert out["value"] < 1e-5
        assert np.asarray(ranks).reshape(-1)[5] == 0.0
        ref_ranks = engine.run(gf, PageRank(), jax.random.key(0),
                               out["rounds"])[0].ranks
        np.testing.assert_allclose(
            np.asarray(ranks).reshape(-1)[: g.n_nodes],
            np.asarray(ref_ranks)[: g.n_nodes], rtol=1e-4, atol=1e-9,
        )


class TestShardedConvergenceBatched:
    """steps_per_round on the sharded convergence loops: T rounds per
    while iteration, bit-exact vs T=1 (the engine-loop freeze contract —
    deterministic rounds, so state, rounds, value, and messages must all
    agree exactly)."""

    @pytest.mark.parametrize("T", [3, 8])
    def test_pagerank_residual_bitexact(self, T):
        from p2pnetwork_tpu.models import PageRank

        g = G.barabasi_albert(1024, 3, seed=0)
        mesh = M.ring_mesh(8)
        sg = sharded.shard_graph(g, mesh)
        r1, o1 = sharded.pagerank_until_residual(
            sg, mesh, PageRank(), tol=1e-5)
        rT, oT = sharded.pagerank_until_residual(
            sg, mesh, PageRank(), tol=1e-5, steps_per_round=T)
        assert o1 == oT
        np.testing.assert_array_equal(np.asarray(r1), np.asarray(rT))

    @pytest.mark.parametrize("T", [4])
    def test_pushsum_variance_bitexact(self, T):
        from p2pnetwork_tpu.models import PushSum

        g = G.watts_strogatz(1024, 8, 0.1, seed=0)
        mesh = M.ring_mesh(8)
        sg = sharded.shard_graph(g, mesh)
        key = jax.random.key(4)
        (s1, w1), o1 = sharded.pushsum_until_variance(
            sg, mesh, PushSum(), key, tol=1e-9)
        (sT, wT), oT = sharded.pushsum_until_variance(
            sg, mesh, PushSum(), key, tol=1e-9, steps_per_round=T)
        assert o1 == oT
        np.testing.assert_array_equal(np.asarray(s1), np.asarray(sT))
        np.testing.assert_array_equal(np.asarray(w1), np.asarray(wT))


class TestShardedPushSumVariance:
    @pytest.mark.parametrize("n_shards", [1, 8])
    def test_matches_engine_loop(self, n_shards):
        from p2pnetwork_tpu.models import PushSum

        g = G.watts_strogatz(1024, 8, 0.1, seed=0)
        mesh = M.ring_mesh(n_shards)
        sg = sharded.shard_graph(g, mesh)
        key = jax.random.key(4)
        (s, w), out = sharded.pushsum_until_variance(
            sg, mesh, PushSum(), key, tol=1e-9
        )
        _, ref = engine.run_until_converged(
            g, PushSum(), key, stat="variance", threshold=1e-9
        )
        # f32 summation order differs; the loop may exit a round apart.
        assert abs(out["rounds"] - ref["rounds"]) <= 1
        assert out["value"] < 1e-9
        # Conservation held all the way to consensus.
        s0 = np.asarray(sharded.init_state(sg, PushSum(), key)[0]).sum()
        np.testing.assert_allclose(np.asarray(s).sum(), s0, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(w).sum(), g.n_nodes, rtol=1e-5)


class TestShardedAdaptiveHubGraphs:
    """Degree-skewed graphs on the sharded adaptive path (the multi-chip
    mirror of the models/adaptive_flood.py hub tolerance): budgeting by
    per-shard work-item mass keeps sparse rounds exact and bounded."""

    @pytest.mark.parametrize("n_shards", [2, 8])
    def test_ba_matches_dense_and_engine(self, n_shards):
        from p2pnetwork_tpu.models import Flood

        g = G.barabasi_albert(2048, 4, seed=0)
        mesh = M.ring_mesh(n_shards)
        sg = sharded.shard_graph(g, mesh, source_csr=True)
        seen_a, out_a = sharded.flood_until_coverage(
            sg, mesh, source=5, coverage_target=0.99, adaptive_k=64
        )
        seen_d, out_d = sharded.flood_until_coverage(
            sg, mesh, source=5, coverage_target=0.99
        )
        _, ref = engine.run_until_coverage(
            g, Flood(source=5), jax.random.key(0), coverage_target=0.99
        )
        np.testing.assert_array_equal(np.asarray(seen_a), np.asarray(seen_d))
        assert out_a["rounds"] == out_d["rounds"] == ref["rounds"]
        assert out_a["messages"] == out_d["messages"] == ref["messages"]

    def test_star_hub_forces_chunked_work_items(self):
        # A star's hub row is ~n/S slots wide per shard — far past the
        # 128-wide item limit — so sparse rounds MUST run the chunked
        # work-item expansion (cumsum + searchsorted), the branch the
        # quasi-regular fast path (span <= w) statically skips. Guards
        # against that branch rotting now that every other test graph
        # takes the fast path.
        from p2pnetwork_tpu.models import Flood

        n = 2048
        hub = np.zeros(n - 1, dtype=np.int32)
        leaves = np.arange(1, n, dtype=np.int32)
        g = G.from_edges(np.concatenate([hub, leaves]),
                         np.concatenate([leaves, hub]), n)
        mesh = M.ring_mesh(2)
        sg = sharded.shard_graph(g, mesh, source_csr=True)
        assert sg.csr_span > 128  # the chunked branch really runs
        seen_a, out_a = sharded.flood_until_coverage(
            sg, mesh, source=5, coverage_target=0.99, adaptive_k=512
        )
        seen_d, out_d = sharded.flood_until_coverage(
            sg, mesh, source=5, coverage_target=0.99
        )
        np.testing.assert_array_equal(np.asarray(seen_a), np.asarray(seen_d))
        assert out_a == out_d
        _, ref = engine.run_until_coverage(
            g, Flood(source=5), jax.random.key(0), coverage_target=0.99
        )
        assert out_a["rounds"] == ref["rounds"]
        assert out_a["messages"] == ref["messages"]

    def test_hub_source_runs_exact_under_tiny_budget(self):
        # Source 0 is a BA hub: its row overflows a tiny item budget, so
        # round one must go dense — and stay bit-identical throughout.
        from p2pnetwork_tpu.models import Flood

        g = G.barabasi_albert(1024, 6, seed=1)
        mesh = M.ring_mesh(4)
        sg = sharded.shard_graph(g, mesh, source_csr=True)
        seen_a, out_a = sharded.flood_until_coverage(
            sg, mesh, source=0, coverage_target=0.99, adaptive_k=4
        )
        _, ref = engine.run_until_coverage(
            g, Flood(source=0), jax.random.key(0), coverage_target=0.99
        )
        assert out_a["rounds"] == ref["rounds"]
        assert out_a["messages"] == ref["messages"]

    def test_ba_with_churn(self):
        from p2pnetwork_tpu.models import Flood
        from p2pnetwork_tpu.sim import failures, topology

        g = G.barabasi_albert(1024, 3, seed=2)
        mesh = M.ring_mesh(4)
        sg = sharded.shard_graph(g, mesh, source_csr=True)
        sg = sharded.with_capacity(sharded.fail_nodes(sg, [2]), 8)
        sg = sharded.connect(sg, [10], [1000])
        gc = topology.connect(
            topology.with_capacity(failures.fail_nodes(g, [2]),
                                   extra_edges=8),
            [10], [1000],
        )
        seen_a, out_a = sharded.flood_until_coverage(
            sg, mesh, source=5, coverage_target=0.95, adaptive_k=32
        )
        _, ref = engine.run_until_coverage(
            gc, Flood(source=5), jax.random.key(0), coverage_target=0.95
        )
        assert out_a["rounds"] == ref["rounds"]
        assert out_a["messages"] == ref["messages"]
        assert not np.asarray(seen_a).reshape(-1)[2]


class TestShardedAdaptiveHopDistance:
    """adaptive_k on the BFS loops: layers, rounds and message totals
    bit-identical to the dense sharded loop, including the sparse tail
    (the wave's last layers) and hub-skewed graphs."""

    @pytest.mark.parametrize("n_shards", [2, 8])
    @pytest.mark.parametrize("k", [16, 256])
    def test_until_done_matches_dense(self, n_shards, k):
        from p2pnetwork_tpu.models import HopDistance

        g = G.watts_strogatz(1024, 6, 0.2, seed=20)
        mesh = M.ring_mesh(n_shards)
        sg = sharded.shard_graph(g, mesh, source_csr=True)
        (d_a, _, r_a), out_a = sharded.hopdist_until_done(
            sg, mesh, HopDistance(source=3), adaptive_k=k)
        (d_d, _, r_d), out_d = sharded.hopdist_until_done(
            sg, mesh, HopDistance(source=3))
        np.testing.assert_array_equal(np.asarray(d_a), np.asarray(d_d))
        assert out_a["rounds"] == out_d["rounds"]
        assert out_a["messages"] == out_d["messages"]
        assert int(r_a) == int(r_d)

    def test_ba_hub_graph_until_coverage(self):
        from p2pnetwork_tpu.models import HopDistance

        g = G.barabasi_albert(2048, 4, seed=21)
        mesh = M.ring_mesh(4)
        sg = sharded.shard_graph(g, mesh, source_csr=True)
        (d_a, _, _), out_a = sharded.hopdist_until_coverage(
            sg, mesh, HopDistance(source=7), coverage_target=0.99,
            adaptive_k=64)
        (d_d, _, _), out_d = sharded.hopdist_until_coverage(
            sg, mesh, HopDistance(source=7), coverage_target=0.99)
        np.testing.assert_array_equal(np.asarray(d_a), np.asarray(d_d))
        assert out_a["rounds"] == out_d["rounds"]
        assert out_a["messages"] == out_d["messages"]

    def test_under_churn_and_resume(self):
        from p2pnetwork_tpu.models import HopDistance

        g = G.ring(512)
        mesh = M.ring_mesh(4)
        sg = sharded.shard_graph(g, mesh, source_csr=True)
        sg = sharded.with_capacity(sharded.fail_nodes(sg, [100]), 8)
        sg = sharded.connect(sg, [5], [400])
        proto = HopDistance(source=0)
        st, _ = sharded.hopdist(sg, mesh, proto, 10)
        (d_a, _, _), out_a = sharded.hopdist_until_done(
            sg, mesh, proto, state0=st, adaptive_k=32)
        (d_d, _, _), out_d = sharded.hopdist_until_done(
            sg, mesh, proto, state0=st)
        np.testing.assert_array_equal(np.asarray(d_a), np.asarray(d_d))
        assert out_a["rounds"] == out_d["rounds"]
        assert np.asarray(d_a).reshape(-1)[100] == -1

    def test_requires_source_csr(self):
        from p2pnetwork_tpu.models import HopDistance

        g = G.ring(256)
        mesh = M.ring_mesh(4)
        sg = sharded.shard_graph(g, mesh)
        with pytest.raises(ValueError, match="source_csr"):
            sharded.hopdist_until_done(sg, mesh, HopDistance(source=0),
                                       adaptive_k=16)
