"""Sharded ring propagation vs the single-device engine — bit-exact parity
on a real 8-device CPU mesh (conftest forces the virtual devices)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from p2pnetwork_tpu.models import Flood  # noqa: E402
from p2pnetwork_tpu.parallel import mesh as M  # noqa: E402
from p2pnetwork_tpu.parallel import sharded  # noqa: E402
from p2pnetwork_tpu.sim import engine  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def test_eight_virtual_devices_present():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("n_shards", [1, 2, 4, 8])
@pytest.mark.parametrize(
    "make",
    [
        lambda: G.watts_strogatz(512, 6, 0.2, seed=0),
        lambda: G.erdos_renyi(700, 0.01, seed=1),
        lambda: G.barabasi_albert(300, 3, seed=2),
    ],
)
def test_sharded_flood_matches_single_device(n_shards, make):
    g = make()
    mesh = M.ring_mesh(n_shards)
    sg = sharded.shard_graph(g, mesh)
    rounds = 6

    seen_sh, stats_sh = sharded.flood(sg, mesh, source=0, rounds=rounds)
    ref_state, ref_stats = engine.run(g, Flood(source=0), jax.random.key(0), rounds)

    seen_flat = np.asarray(seen_sh).reshape(-1)[: g.n_nodes]
    ref_seen = np.asarray(ref_state.seen)[: g.n_nodes]
    assert (seen_flat == ref_seen).all()

    np.testing.assert_array_equal(
        np.asarray(stats_sh["messages"]), np.asarray(ref_stats["messages"])
    )
    np.testing.assert_allclose(
        np.asarray(stats_sh["coverage"]),
        np.asarray(ref_stats["coverage"]),
        rtol=1e-6,
    )


def test_cross_shard_edges_resolve():
    # A ring graph sharded across 4 devices has every shard boundary crossed;
    # full coverage proves cross-shard edges deliver.
    g = G.ring(256)
    mesh = M.ring_mesh(4)
    sg = sharded.shard_graph(g, mesh)
    seen, stats = sharded.flood(sg, mesh, source=0, rounds=128)
    assert np.asarray(seen).reshape(-1)[:256].all()
    assert float(np.asarray(stats["coverage"])[-1]) == 1.0


def test_source_on_nonzero_shard():
    g = G.watts_strogatz(512, 4, 0.1, seed=3)
    mesh = M.ring_mesh(8)
    sg = sharded.shard_graph(g, mesh)
    src = 300  # lives on a middle shard
    seen_sh, _ = sharded.flood(sg, mesh, source=src, rounds=5)
    ref_state, _ = engine.run(g, Flood(source=src), jax.random.key(0), 5)
    assert (
        np.asarray(seen_sh).reshape(-1)[: g.n_nodes]
        == np.asarray(ref_state.seen)[: g.n_nodes]
    ).all()


def test_shard_graph_partition_is_lossless():
    g = G.erdos_renyi(400, 0.02, seed=4)
    mesh = M.ring_mesh(4)
    sg = sharded.shard_graph(g, mesh)
    # Total active bucketed edges == total active edges.
    assert int(np.asarray(sg.bkt_mask).sum()) == g.n_edges
    assert int(np.asarray(sg.node_mask).sum()) == g.n_nodes
