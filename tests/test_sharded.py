"""Sharded ring propagation vs the single-device engine — bit-exact parity
on a real 8-device CPU mesh (conftest forces the virtual devices)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from p2pnetwork_tpu.models import Flood  # noqa: E402
from p2pnetwork_tpu.parallel import mesh as M  # noqa: E402
from p2pnetwork_tpu.parallel import sharded  # noqa: E402
from p2pnetwork_tpu.sim import engine  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def test_eight_virtual_devices_present():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("n_shards", [1, 2, 4, 8])
@pytest.mark.parametrize(
    "make",
    [
        lambda: G.watts_strogatz(512, 6, 0.2, seed=0),
        lambda: G.erdos_renyi(700, 0.01, seed=1),
        lambda: G.barabasi_albert(300, 3, seed=2),
    ],
)
def test_sharded_flood_matches_single_device(n_shards, make):
    g = make()
    mesh = M.ring_mesh(n_shards)
    sg = sharded.shard_graph(g, mesh)
    rounds = 6

    seen_sh, stats_sh = sharded.flood(sg, mesh, source=0, rounds=rounds)
    ref_state, ref_stats = engine.run(g, Flood(source=0), jax.random.key(0), rounds)

    seen_flat = np.asarray(seen_sh).reshape(-1)[: g.n_nodes]
    ref_seen = np.asarray(ref_state.seen)[: g.n_nodes]
    assert (seen_flat == ref_seen).all()

    np.testing.assert_array_equal(
        np.asarray(stats_sh["messages"]), np.asarray(ref_stats["messages"])
    )
    np.testing.assert_allclose(
        np.asarray(stats_sh["coverage"]),
        np.asarray(ref_stats["coverage"]),
        rtol=1e-6,
    )


def test_cross_shard_edges_resolve():
    # A ring graph sharded across 4 devices has every shard boundary crossed;
    # full coverage proves cross-shard edges deliver.
    g = G.ring(256)
    mesh = M.ring_mesh(4)
    sg = sharded.shard_graph(g, mesh)
    seen, stats = sharded.flood(sg, mesh, source=0, rounds=128)
    assert np.asarray(seen).reshape(-1)[:256].all()
    assert float(np.asarray(stats["coverage"])[-1]) == 1.0


def test_source_on_nonzero_shard():
    g = G.watts_strogatz(512, 4, 0.1, seed=3)
    mesh = M.ring_mesh(8)
    sg = sharded.shard_graph(g, mesh)
    src = 300  # lives on a middle shard
    seen_sh, _ = sharded.flood(sg, mesh, source=src, rounds=5)
    ref_state, _ = engine.run(g, Flood(source=src), jax.random.key(0), 5)
    assert (
        np.asarray(seen_sh).reshape(-1)[: g.n_nodes]
        == np.asarray(ref_state.seen)[: g.n_nodes]
    ).all()


def test_shard_graph_partition_is_lossless():
    g = G.erdos_renyi(400, 0.02, seed=4)
    mesh = M.ring_mesh(4)
    sg = sharded.shard_graph(g, mesh)
    # Total active bucketed edges == total active edges.
    assert int(np.asarray(sg.bkt_mask).sum()) == g.n_edges
    assert int(np.asarray(sg.node_mask).sum()) == g.n_nodes


@pytest.mark.parametrize("n_shards", [2, 8])
def test_sharded_sir_matches_single_device(n_shards):
    from p2pnetwork_tpu.models import SIR

    # 1024 = 8 * 128: S*block == n_pad, so exact_rng draws the same uniforms
    # as the single-device engine and the run is bit-identical.
    g = G.watts_strogatz(1024, 6, 0.2, seed=0)
    mesh = M.ring_mesh(n_shards)
    sg = sharded.shard_graph(g, mesh)
    proto = SIR(beta=0.4, gamma=0.15, source=3, method="segment")
    rounds = 8

    status_sh, stats_sh = sharded.sir(
        sg, mesh, proto, jax.random.key(7), rounds, exact_rng=True
    )
    ref_state, ref_stats = engine.run(g, proto, jax.random.key(7), rounds)

    flat = np.asarray(status_sh).reshape(-1)[: g.n_nodes]
    ref = np.asarray(ref_state.status)[: g.n_nodes]
    np.testing.assert_array_equal(flat, ref)
    np.testing.assert_array_equal(
        np.asarray(stats_sh["messages"]), np.asarray(ref_stats["messages"])
    )
    for k in ("s_frac", "i_frac", "r_frac", "coverage"):
        np.testing.assert_allclose(
            np.asarray(stats_sh[k]), np.asarray(ref_stats[k]), rtol=1e-6
        )


def test_sharded_sir_scalable_rng_is_plausible():
    # The fold_in-per-shard default is not bit-identical to the engine but
    # must still produce a real epidemic: infection spreads beyond the
    # source and conservation holds (s+i+r == 1).
    from p2pnetwork_tpu.models import SIR

    g = G.watts_strogatz(1024, 8, 0.1, seed=1)
    mesh = M.ring_mesh(8)
    sg = sharded.shard_graph(g, mesh)
    status, stats = sharded.sir(
        sg, mesh, SIR(beta=0.6, gamma=0.05, source=0), jax.random.key(0), 12
    )
    total = (np.asarray(stats["s_frac"]) + np.asarray(stats["i_frac"])
             + np.asarray(stats["r_frac"]))
    np.testing.assert_allclose(total, 1.0, rtol=1e-6)
    assert float(np.asarray(stats["coverage"])[-1]) > 0.5


class TestAutoSharding:
    @pytest.mark.parametrize("protocol_name", ["flood", "sir", "gossip"])
    def test_auto_matches_single_device(self, protocol_name):
        from p2pnetwork_tpu.models import SIR, Flood, Gossip
        from p2pnetwork_tpu.parallel import auto

        proto = {
            "flood": Flood(source=0, method="segment"),
            "sir": SIR(beta=0.3, gamma=0.1, method="segment"),
            "gossip": Gossip(alpha=0.5),
        }[protocol_name]
        g = G.watts_strogatz(512, 6, 0.2, seed=0)
        mesh = M.ring_mesh(8)
        gs = auto.shard_graph_auto(g, mesh)

        state, stats = auto.run_auto(gs, proto, jax.random.key(0), 5)
        ref_state, ref_stats = engine.run(g, proto, jax.random.key(0), 5)

        s = jax.tree.leaves(state)[0]
        r = jax.tree.leaves(ref_state)[0]
        # GSPMD may reorder float reductions; values agree to tolerance.
        np.testing.assert_allclose(
            np.asarray(s, dtype=np.float32), np.asarray(r, dtype=np.float32),
            rtol=1e-5, atol=1e-6,
        )

    def test_auto_graph_is_actually_sharded(self):
        from p2pnetwork_tpu.parallel import auto

        g = G.watts_strogatz(512, 4, 0.1, seed=0)
        mesh = M.ring_mesh(8)
        gs = auto.shard_graph_auto(g, mesh)
        assert len(gs.node_mask.sharding.device_set) == 8
        assert len(gs.senders.sharding.device_set) == 8
        assert gs.blocked is None and gs.hybrid is None
