"""Chaos plane for the sockets backend (chaos/plane.py).

Covers: seeded determinism (same seed => byte-identical fault schedule and
identical telemetry counters; different seed => different schedule), each
fault kind end to end over real TCP connections, the reconnect backoff +
next-retry gauge, the bounded ``reconnect_nodes`` cross-thread trigger, the
telemetry names the ISSUE pins down, and — ``slow``-marked — the seeded
8-node partition-heal soak proving gossip reconverges within a bounded tick
budget, reproducibly."""

import time

import pytest

from p2pnetwork_tpu import Node, NodeConfig, telemetry
from p2pnetwork_tpu.chaos import ChaosPlane
from tests.helpers import EventRecorder, stop_all, wait_until

HOST = "127.0.0.1"

#: Fast cadences so chaos tests recover within test timeouts.
FAST = dict(reconnect_interval=0.05, reconnect_backoff_base=0.1,
            reconnect_backoff_max=0.5)


@pytest.fixture
def registry():
    """Isolate each test in a fresh default registry so counter values are
    exact, not cumulative across tests."""
    fresh = telemetry.Registry()
    prev = telemetry.set_default_registry(fresh)
    yield fresh
    telemetry.set_default_registry(prev)


def make_node(id, callback=None, plane=None, cls=Node, **cfg):
    node = cls(HOST, 0, id=id, callback=callback,
               config=NodeConfig(**{**FAST, **cfg}))
    if plane is not None:
        plane.attach(node)
    node.start()
    return node


class TestDeterminism:
    def test_schedule_same_seed_identical(self):
        a = ChaosPlane(seed=42, registry=telemetry.Registry())
        b = ChaosPlane(seed=42, registry=telemetry.Registry())
        assert a.fault_schedule("A", "B", 256) == b.fault_schedule("A", "B", 256)
        # Per-stream independence: the reverse direction and other peers
        # get their own schedules.
        assert a.fault_schedule("A", "B", 16) != a.fault_schedule("B", "A", 16)

    def test_schedule_different_seed_differs(self):
        a = ChaosPlane(seed=42, registry=telemetry.Registry())
        b = ChaosPlane(seed=43, registry=telemetry.Registry())
        assert a.fault_schedule("A", "B", 16) != b.fault_schedule("A", "B", 16)

    @staticmethod
    def _run_drop_scenario(seed, n_frames=60, drop_p=0.4):
        """One sender, one receiver, seeded frame drops: returns the
        delivered seq pattern and the chaos counter values."""
        reg = telemetry.Registry()
        prev = telemetry.set_default_registry(reg)
        try:
            plane = ChaosPlane(seed=seed)
            rec = EventRecorder()
            a = make_node("A", plane=plane)
            b = make_node("B", callback=rec, plane=plane)
            try:
                assert a.connect_with_node(HOST, b.port)
                assert wait_until(lambda: len(b.nodes_inbound) == 1)
                plane.drop_frames(drop_p)
                for i in range(n_frames):
                    a.send_to_nodes({"seq": i})
                assert wait_until(
                    lambda: rec.count("node_message")
                    + reg.value("chaos_injected_failures_total", kind="drop")
                    >= n_frames, timeout=10.0)
                delivered = tuple(m["seq"] for m in rec.messages())
                counters = {
                    kind: reg.value("chaos_injected_failures_total", kind=kind)
                    for kind in ("drop", "duplicate", "corrupt")}
                dropped = [e for e in plane.fault_log() if e[0] == "drop"]
                return delivered, counters, dropped
            finally:
                stop_all([a, b])
        finally:
            telemetry.set_default_registry(prev)

    def test_live_run_reproducible_same_seed(self):
        d1, c1, log1 = self._run_drop_scenario(seed=7)
        d2, c2, log2 = self._run_drop_scenario(seed=7)
        assert d1 == d2
        assert c1 == c2
        assert log1 == log2
        assert 0 < len(d1) < 60  # the fault actually fired

    def test_live_run_differs_across_seeds(self):
        d1, _, _ = self._run_drop_scenario(seed=7)
        d3, _, _ = self._run_drop_scenario(seed=8)
        # 60 Bernoulli(0.4) draws: identical drop PATTERNS across seeds
        # would be a 2^-60-ish coincidence.
        assert d1 != d3


class TestSimParity:
    def test_api_mirrors_sim_failures_name_for_name(self):
        failures = pytest.importorskip("p2pnetwork_tpu.sim.failures")
        for name in ("kill_nodes", "revive_nodes", "cut_links", "partition"):
            assert hasattr(failures, name), f"sim missing {name}"
            assert callable(getattr(ChaosPlane, name)), f"chaos missing {name}"


class TestStructuralFaults:
    def test_kill_then_revive_self_heals(self, registry):
        plane = ChaosPlane(seed=0)
        a = make_node("A", plane=plane)
        b = make_node("B", plane=plane)
        try:
            assert a.connect_with_node(HOST, b.port, reconnect=True)
            assert wait_until(lambda: len(a.nodes_outbound) == 1)
            plane.kill_nodes(["B"])
            assert wait_until(lambda: len(a.nodes_outbound) == 0)
            assert registry.value("chaos_injected_failures_total", kind="node") == 1
            assert registry.value("chaos_active_faults", kind="dead_nodes") == 1
            plane.revive_nodes(["B"])
            # Self-healing: the reconnect registry re-establishes the link
            # without any application action.
            assert wait_until(
                lambda: any(c.id == "B" for c in a.nodes_outbound), timeout=10.0)
            assert registry.value("chaos_injected_failures_total",
                                  kind="node_revive") == 1
            assert registry.value("chaos_active_faults", kind="dead_nodes") == 0
        finally:
            stop_all([a, b])

    def test_cut_then_heal_links(self, registry):
        plane = ChaosPlane(seed=0)
        a = make_node("A", plane=plane)
        b = make_node("B", plane=plane)
        c = make_node("C", plane=plane)
        try:
            assert a.connect_with_node(HOST, b.port, reconnect=True)
            assert a.connect_with_node(HOST, c.port)
            assert wait_until(lambda: len(a.nodes_outbound) == 2)
            plane.cut_links([("A", "B")])
            assert wait_until(
                lambda: not any(x.id == "B" for x in a.nodes_outbound))
            # The uninvolved link survives.
            assert any(x.id == "C" for x in a.nodes_outbound)
            assert registry.value("chaos_injected_failures_total", kind="link") == 1
            plane.heal_links([("A", "B")])
            assert wait_until(
                lambda: any(x.id == "B" for x in a.nodes_outbound), timeout=10.0)
            assert registry.value("chaos_injected_failures_total",
                                  kind="link_heal") == 1
        finally:
            stop_all([a, b, c])


class TestTimeAndFrameFaults:
    def test_added_latency_delays_delivery(self, registry):
        plane = ChaosPlane(seed=0)
        rec = EventRecorder()
        a = make_node("A", plane=plane)
        b = make_node("B", callback=rec, plane=plane)
        try:
            assert a.connect_with_node(HOST, b.port)
            assert wait_until(lambda: len(b.nodes_inbound) == 1)
            plane.add_latency(0.4)
            t0 = time.monotonic()
            a.send_to_nodes("delayed")
            assert wait_until(lambda: rec.count("node_message") == 1, timeout=5.0)
            assert time.monotonic() - t0 >= 0.3
            assert registry.value("chaos_injected_failures_total",
                                  kind="latency") == 1
        finally:
            stop_all([a, b])

    def test_duplicate_frames_arrive_twice(self, registry):
        plane = ChaosPlane(seed=0)
        rec = EventRecorder()
        a = make_node("A", plane=plane)
        b = make_node("B", callback=rec, plane=plane)
        try:
            assert a.connect_with_node(HOST, b.port)
            assert wait_until(lambda: len(b.nodes_inbound) == 1)
            plane.duplicate_frames(1.0)
            for i in range(5):
                a.send_to_nodes({"seq": i})
            assert wait_until(lambda: rec.count("node_message") == 10, timeout=5.0)
            assert [m["seq"] for m in rec.messages()] == \
                [0, 0, 1, 1, 2, 2, 3, 3, 4, 4]
            assert registry.value("chaos_injected_failures_total",
                                  kind="duplicate") == 5
        finally:
            stop_all([a, b])

    def test_corrupt_frames_damage_payloads(self, registry):
        plane = ChaosPlane(seed=0)
        rec = EventRecorder()
        a = make_node("A", plane=plane)
        b = make_node("B", callback=rec, plane=plane)
        try:
            assert a.connect_with_node(HOST, b.port)
            assert wait_until(lambda: len(b.nodes_inbound) == 1)
            plane.corrupt_frames(1.0)
            original = "A" * 64
            for _ in range(5):
                a.send_to_nodes(original)
            assert wait_until(
                lambda: registry.value("chaos_injected_failures_total",
                                       kind="corrupt") == 5, timeout=5.0)
            assert wait_until(
                lambda: rec.count("node_message")
                + b.message_count_rerr >= 5, timeout=5.0)
            # Whatever made it through is NOT the original payload.
            assert all(m != original for m in rec.messages())
        finally:
            stop_all([a, b])

    def test_corrupt_never_forges_the_eot_delimiter(self, registry):
        # '^' (0x5E) XOR 0x5A would become 0x04 = EOT and split one frame
        # into two; the fallback mask must keep the damage inside one
        # payload — exactly one delivery-or-error per sent frame.
        plane = ChaosPlane(seed=0)
        rec = EventRecorder()
        a = make_node("A", plane=plane)
        b = make_node("B", callback=rec, plane=plane)
        try:
            assert a.connect_with_node(HOST, b.port)
            assert wait_until(lambda: len(b.nodes_inbound) == 1)
            plane.corrupt_frames(1.0)
            original = "^" * 64
            for _ in range(20):
                a.send_to_nodes(original)
            assert wait_until(
                lambda: rec.count("node_message")
                + b.message_count_rerr >= 20, timeout=5.0)
            time.sleep(0.2)
            assert rec.count("node_message") + b.message_count_rerr == 20
            assert all(m != original for m in rec.messages())
        finally:
            stop_all([a, b])

    def test_corrupt_spares_length_frame_prefix(self, registry):
        # Under framing="length" the 4-byte prefix + flag byte must never
        # be corrupted: a damaged prefix would desync or tear down the
        # stream instead of damaging one payload. Every frame is
        # corrupted, yet the connection survives all of them.
        plane = ChaosPlane(seed=0)
        rec = EventRecorder()
        a = make_node("A", plane=plane, framing="length")
        b = make_node("B", callback=rec, plane=plane, framing="length")
        try:
            assert a.connect_with_node(HOST, b.port)
            assert wait_until(lambda: len(b.nodes_inbound) == 1)
            plane.corrupt_frames(1.0)
            original = "B" * 64
            for _ in range(20):
                a.send_to_nodes(original)
            assert wait_until(
                lambda: rec.count("node_message")
                + b.message_count_rerr >= 20, timeout=5.0)
            assert all(m != original for m in rec.messages())
            # The stream stayed framed: the connection is still up.
            assert len(b.nodes_inbound) == 1
            assert registry.value("chaos_injected_failures_total",
                                  kind="corrupt") == 20
        finally:
            stop_all([a, b])

    def test_dropped_frames_do_not_count_corruptions(self, registry):
        # Per-frame kinds count APPLIED faults: a frame that is dropped
        # never reached the wire, so it must not also count a corruption.
        plane = ChaosPlane(seed=0)
        a = make_node("A", plane=plane)
        b = make_node("B", plane=plane)
        try:
            assert a.connect_with_node(HOST, b.port)
            assert wait_until(lambda: len(b.nodes_inbound) == 1)
            plane.drop_frames(1.0)
            plane.corrupt_frames(1.0)
            for i in range(10):
                a.send_to_nodes({"seq": i})
            assert wait_until(
                lambda: registry.value("chaos_injected_failures_total",
                                       kind="drop") == 10, timeout=5.0)
            assert registry.value("chaos_injected_failures_total",
                                  kind="corrupt") == 0
        finally:
            stop_all([a, b])

    def test_disarm_calls_are_not_counted_as_injected(self, registry):
        plane = ChaosPlane(seed=0)
        plane.add_latency(0.2)
        plane.add_latency(0.0)      # disarm
        plane.throttle(1024.0)
        plane.throttle(None)        # disarm
        plane.slow_drain("X", 0.5)
        plane.slow_drain("X", 0.0)  # disarm
        for kind in ("latency", "throttle", "slow_drain"):
            assert registry.value("chaos_injected_failures_total",
                                  kind=kind) == 1, kind

    def test_slow_drain_peer_trips_sender_backpressure(self, registry):
        plane = ChaosPlane(seed=0)
        # Small send-buffer bound so the stalled peer is detected fast.
        a = make_node("A", plane=plane, max_send_buffer=128 * 1024)
        b = make_node("B", plane=plane)
        try:
            assert a.connect_with_node(HOST, b.port)
            assert wait_until(lambda: len(b.nodes_inbound) == 1)
            plane.slow_drain("B", stall=1.0)
            blob = b"x" * (64 * 1024)
            for _ in range(200):
                a.send_to_nodes(blob)
                if a.message_count_rerr:
                    break
            # The sender treats the non-draining peer as a failed
            # transport: rerr counted, connection closed.
            assert wait_until(lambda: a.message_count_rerr >= 1, timeout=10.0)
            assert wait_until(lambda: len(a.nodes_outbound) == 0, timeout=10.0)
            assert registry.value("chaos_injected_failures_total",
                                  kind="slow_drain") == 1
        finally:
            plane.clear_faults()
            stop_all([a, b])


class TestReconnectBackoff:
    def test_backoff_spaces_attempts(self, registry):
        server = make_node("S")
        client = make_node("C")
        try:
            port = server.port
            assert client.connect_with_node(HOST, port, reconnect=True)
            assert wait_until(lambda: len(client.nodes_outbound) == 1)
            stop_all([server])
            assert wait_until(lambda: len(client.nodes_outbound) == 0)
            start = registry.value("p2p_reconnect_attempts_total", node="C")
            time.sleep(1.2)
            attempts = registry.value("p2p_reconnect_attempts_total",
                                      node="C") - start
            # Tick floor is 0.05 s: fixed-cadence hammering would make ~24
            # attempts; decorrelated backoff (base 0.1, cap 0.5) allows at
            # most ~13 and at least 2.
            assert 2 <= attempts <= 15, attempts
            entry = client.reconnect_to_nodes[0]
            assert entry["trials"] >= 2
            assert entry["backoff"] > 0
            # Next-retry horizon is published as a gauge.
            assert registry.value("p2p_reconnect_next_retry_seconds",
                                  node="C", peer=f"{HOST}:{port}") > 0
        finally:
            stop_all([server, client])

    def test_backoff_resets_on_successful_reconnect(self, registry):
        server = make_node("S")
        port = server.port
        client = make_node("C")
        try:
            assert client.connect_with_node(HOST, port, reconnect=True)
            assert wait_until(lambda: len(client.nodes_outbound) == 1)
            stop_all([server])
            assert wait_until(lambda: len(client.nodes_outbound) == 0)
            assert wait_until(
                lambda: client.reconnect_to_nodes[0]["backoff"] > 0)
            server = Node(HOST, port, id="S2",
                          config=NodeConfig(**FAST))
            server.start()
            assert wait_until(lambda: len(client.nodes_outbound) == 1,
                              timeout=10.0)
            assert wait_until(
                lambda: client.reconnect_to_nodes[0]["backoff"] == 0.0)
            assert wait_until(
                lambda: registry.value(
                    "p2p_reconnect_next_retry_seconds",
                    node="C", peer=f"{HOST}:{port}") == 0.0)
        finally:
            stop_all([server, client])

    def test_reconnect_nodes_trigger_bounded_when_loop_wedged(self, registry):
        node = make_node("W", connect_timeout=0.3)
        try:
            # Wedge the event loop with a blocking callback, then fire the
            # manual trigger from this thread: it must return within the
            # bound (connect_timeout + 1s headroom) instead of hanging,
            # and surface a structured warning.
            node._loop.call_soon_threadsafe(time.sleep, 2.5)
            t0 = time.monotonic()
            node.reconnect_nodes()
            elapsed = time.monotonic() - t0
            assert elapsed < 2.2, elapsed
            assert registry.value("p2p_reconnect_trigger_timeouts_total",
                                  node="W") == 1
            assert node.event_log.count("reconnect_trigger_timeout") == 1
            time.sleep(1.3)  # let the loop unwedge before shutdown
        finally:
            stop_all([node])


class TestTelemetryNames:
    def test_chaos_and_recovery_families_registered(self, registry):
        plane = ChaosPlane(seed=0)
        a = make_node("A", plane=plane)
        b = make_node("B", plane=plane)
        try:
            assert a.connect_with_node(HOST, b.port, reconnect=True)
            assert wait_until(lambda: len(a.nodes_outbound) == 1)
            plane.add_latency(0.01)
            plane.kill_nodes(["B"])
            assert wait_until(lambda: len(a.nodes_outbound) == 0)
            assert wait_until(
                lambda: registry.value("p2p_reconnect_attempts_total",
                                       node="A") >= 1, timeout=5.0)
            snap = registry.snapshot()
            for family in (
                "chaos_injected_failures_total",
                "chaos_active_faults",
                "p2p_reconnect_attempts_total",
                "p2p_reconnect_next_retry_seconds",
            ):
                assert family in snap, family
            assert snap["chaos_injected_failures_total"]["type"] == "counter"
            assert snap["chaos_active_faults"]["type"] == "gauge"
            kinds = {s["labels"]["kind"] for s in
                     snap["chaos_injected_failures_total"]["samples"]}
            assert {"latency", "node"} <= kinds
        finally:
            stop_all([a, b])


class GossipNode(Node):
    """Flood-with-dedup gossip used by the soak test: every rumor set
    change is re-broadcast, and full state is exchanged on every new
    connection, so a healed partition reconverges through any bridge."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.rumors = set()

    def add_rumor(self, rumor):
        self.rumors.add(rumor)
        self.send_to_nodes({"rumors": sorted(self.rumors)})

    def _merge(self, rumors):
        new = set(rumors) - self.rumors
        if new:
            self.rumors |= new
            self.send_to_nodes({"rumors": sorted(self.rumors)})

    def node_message(self, conn, data):
        if isinstance(data, dict) and "rumors" in data:
            self._merge(data["rumors"])
            return
        super().node_message(conn, data)

    def _share_state(self, conn):
        if self.rumors:
            self.send_to_node(conn, {"rumors": sorted(self.rumors)})

    def outbound_node_connected(self, conn):
        super().outbound_node_connected(conn)
        self._share_state(conn)

    def inbound_node_connected(self, conn):
        super().inbound_node_connected(conn)
        self._share_state(conn)


@pytest.mark.slow
class TestPartitionHealSoak:
    """The ISSUE's headline deliverable: split an 8-node overlay in two,
    heal it, and prove gossip reconverges within a bounded tick budget —
    reproducibly under a fixed seed."""

    TICK = 0.05                # reconnect_interval of every node
    BUDGET_TICKS = 240         # reconvergence bound after heal (12 s)
    GROUPS = (("N0", "N1", "N2", "N3"), ("N4", "N5", "N6", "N7"))

    def _run(self, seed):
        reg = telemetry.Registry()
        prev = telemetry.set_default_registry(reg)
        try:
            plane = ChaosPlane(seed=seed)
            nodes = [make_node(f"N{i}", plane=plane, cls=GossipNode)
                     for i in range(8)]
            try:
                # Ring overlay with self-healing links.
                for i, n in enumerate(nodes):
                    peer = nodes[(i + 1) % 8]
                    assert n.connect_with_node(HOST, peer.port, reconnect=True)
                assert wait_until(lambda: all(
                    len(n.nodes_outbound) >= 1 and len(n.nodes_inbound) >= 1
                    for n in nodes), timeout=10.0)

                plane.partition(self.GROUPS)
                # Both crossing links (N3->N4 and N7->N0) die.
                assert wait_until(lambda: not any(
                    c.id == "N4" for c in nodes[3].nodes_outbound), timeout=10.0)
                assert wait_until(lambda: not any(
                    c.id == "N0" for c in nodes[7].nodes_outbound), timeout=10.0)

                # A rumor born inside group 0 cannot cross the partition...
                nodes[0].add_rumor("r-partition")
                assert wait_until(lambda: all(
                    "r-partition" in n.rumors for n in nodes[:4]), timeout=10.0)
                time.sleep(0.5)
                assert all("r-partition" not in n.rumors for n in nodes[4:])

                # ...until the partition heals: reconnect backoff re-bridges
                # the ring and the state exchange reconverges ALL nodes,
                # within the tick budget.
                plane.heal_partition()
                budget = self.TICK * self.BUDGET_TICKS
                assert wait_until(lambda: all(
                    "r-partition" in n.rumors for n in nodes), timeout=budget), \
                    {n.id: sorted(n.rumors) for n in nodes}

                rumor_sets = tuple(tuple(sorted(n.rumors)) for n in nodes)
                counters = {
                    kind: reg.value("chaos_injected_failures_total", kind=kind)
                    for kind in ("partition", "partition_heal")}
                return rumor_sets, counters, plane.fault_log()
            finally:
                stop_all(nodes)
        finally:
            telemetry.set_default_registry(prev)

    def test_partition_heal_reconverges_reproducibly(self):
        r1, c1, log1 = self._run(seed=1234)
        r2, c2, log2 = self._run(seed=1234)
        # Bit-identical outcome under the same seed.
        assert r1 == r2
        assert c1 == c2 == {"partition": 1.0, "partition_heal": 1.0}
        assert log1 == log2
        # Every node converged to the same gossip state.
        assert len(set(r1)) == 1
