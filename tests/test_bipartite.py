"""BipartiteCheck (rooted parity flooding) vs a numpy 2-coloring oracle."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from p2pnetwork_tpu.models import BipartiteCheck  # noqa: E402
from p2pnetwork_tpu.sim import engine, failures, topology  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def _live_pairs(g):
    pairs = []
    send, recv = np.asarray(g.senders), np.asarray(g.receivers)
    em = np.asarray(g.edge_mask)
    pairs.append((send[em], recv[em]))
    if g.dyn_senders is not None:
        dm = np.asarray(g.dyn_mask)
        pairs.append((np.asarray(g.dyn_senders)[dm],
                      np.asarray(g.dyn_receivers)[dm]))
    return pairs


def _oracle(g):
    """(bipartite_overall, per_node_component_bipartite) by BFS 2-coloring
    each component of the undirected live-edge graph."""
    n_pad = g.n_nodes_padded
    alive = np.asarray(g.node_mask)
    adj = [[] for _ in range(n_pad)]
    for s, r in _live_pairs(g):
        for a, b in zip(s, r):
            adj[a].append(b)
            adj[b].append(a)  # bipartiteness is an undirected question
    color = np.full(n_pad, -1)
    comp_ok = np.ones(n_pad, dtype=bool)
    for root in range(n_pad):
        if not alive[root] or color[root] >= 0:
            continue
        comp, ok, queue = [root], True, [root]
        color[root] = 0
        while queue:
            u = queue.pop()
            for v in adj[u]:
                if color[v] < 0:
                    color[v] = color[u] ^ 1
                    comp.append(v)
                    queue.append(v)
                elif color[v] == color[u]:
                    ok = False
        for v in comp:
            comp_ok[v] = ok
    comp_ok[~alive] = False
    return bool(comp_ok[alive].all()), comp_ok


def _run(g, method="auto"):
    p = BipartiteCheck(method=method)
    st, out = engine.run_until_converged(
        g, p, jax.random.key(0), stat="changed", threshold=1, max_rounds=512,
    )
    return p, st, out


def _check_against_oracle(g, method="auto"):
    want_all, want_per_node = _oracle(g)
    p, st, _ = _run(g, method)
    odd = int(p.odd_edges(g, st))
    assert (odd == 0) == want_all
    got = np.asarray(p.component_bipartite(g, st))
    np.testing.assert_array_equal(got, want_per_node)
    return odd


class TestBipartiteCheck:
    @pytest.mark.parametrize("method", ["segment", "gather"])
    def test_even_ring_is_bipartite(self, method):
        odd = _check_against_oracle(G.ring(128), method)
        assert odd == 0

    @pytest.mark.parametrize("method", ["segment", "gather"])
    def test_odd_ring_is_not(self, method):
        odd = _check_against_oracle(G.ring(127), method)
        # Exactly one odd edge in a 2-coloring attempt of an odd ring —
        # two directed slots.
        assert odd == 2

    def test_star_is_bipartite(self):
        hub = np.zeros(63, dtype=np.int32)
        leaves = np.arange(1, 64, dtype=np.int32)
        g = G.from_edges(*G._undirect(hub, leaves), 64)
        _check_against_oracle(g)

    def test_triangle_plus_square_components(self):
        # Component {0,1,2} is an odd cycle; component {3,4,5,6} an even one.
        s = np.array([0, 1, 2, 3, 4, 5, 6], dtype=np.int32)
        r = np.array([1, 2, 0, 4, 5, 6, 3], dtype=np.int32)
        g = G.from_edges(*G._undirect(s, r), 7)
        want_all, want_per = _oracle(g)
        assert not want_all
        assert not want_per[:3].any() and want_per[3:7].all()
        _check_against_oracle(g)

    def test_er_matches_oracle(self):
        _check_against_oracle(G.erdos_renyi(96, 0.03, seed=3))

    def test_ws_matches_oracle(self):
        # k=2, p=0: a pure even ring (bipartite); rewired: almost surely not.
        _check_against_oracle(G.watts_strogatz(64, 2, 0.0, seed=0))
        _check_against_oracle(G.watts_strogatz(64, 4, 0.2, seed=1))

    def test_failing_a_node_can_restore_bipartiteness(self):
        # An odd ring loses its odd cycle when any node dies.
        g = G.ring(9)
        _check_against_oracle(g)
        _check_against_oracle(failures.fail_nodes(g, [4]))

    def test_dynamic_edge_creates_odd_cycle(self):
        # A path 0-1-2-3 is bipartite; adding 0-2 closes a triangle.
        s = np.array([0, 1, 2], dtype=np.int32)
        r = np.array([1, 2, 3], dtype=np.int32)
        g = topology.with_capacity(
            G.from_edges(*G._undirect(s, r), 4), extra_edges=4)
        _check_against_oracle(g)
        g2 = topology.connect(g, [0], [2])
        want_all, _ = _oracle(g2)
        assert not want_all
        _check_against_oracle(g2)

    def test_dist_is_bfs_layer_from_component_max(self):
        g = G.ring(8)
        p, st, _ = _run(g)
        # Root (max id 7) at layer 0; ring distances from 7.
        dist = np.asarray(st.dist)[:8]
        want = np.array([1, 2, 3, 4, 3, 2, 1, 0])
        np.testing.assert_array_equal(dist, want)
        label = np.asarray(st.label)[:8]
        assert (label == 7).all()
