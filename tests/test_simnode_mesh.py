"""JaxSimNode on the multi-chip (mesh) backend.

The same Node event surface — run_rounds, run_until_coverage, failures,
churn, runtime links, checkpoint/restore — driving the sharded
representation (parallel/sharded.py), parity-tested against the
single-device node on the 8-device CPU mesh.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from p2pnetwork_tpu.models import SIR, Flood, Gossip  # noqa: E402
from p2pnetwork_tpu.parallel import mesh as M  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402
from p2pnetwork_tpu.sim import topology  # noqa: E402
from p2pnetwork_tpu.sim.simnode import JaxSimNode  # noqa: E402
from tests.helpers import EventRecorder  # noqa: E402


def _graph():
    # 1024 = 8 * 128: exact-RNG and churn draws align with the engine.
    return G.watts_strogatz(1024, 6, 0.2, seed=0)


class TestMeshBackedNode:
    def test_flood_matches_single_device_node(self):
        g = _graph()
        a = JaxSimNode(graph=g, protocol=Flood(source=0), seed=3)
        b = JaxSimNode(graph=g, protocol=Flood(source=0), seed=3,
                       mesh=M.ring_mesh(8))
        a.run_rounds(3)
        a.run_rounds(2)
        b.run_rounds(3)
        b.run_rounds(2)
        np.testing.assert_array_equal(
            np.asarray(b.sim_state[0]).reshape(-1),
            np.asarray(a.sim_state.seen),
        )
        assert a.sim_message_count == b.sim_message_count
        assert a.sim_round == b.sim_round == 5

    def test_sir_exact_rng_matches_single_device_node(self):
        g = _graph()
        proto = SIR(beta=0.4, gamma=0.15, source=3, method="segment")
        a = JaxSimNode(graph=g, protocol=proto, seed=7)
        b = JaxSimNode(graph=g, protocol=proto, seed=7,
                       mesh=M.ring_mesh(8), rng="exact")
        a.run_rounds(4)
        a.run_rounds(4)
        b.run_rounds(4)
        b.run_rounds(4)
        np.testing.assert_array_equal(
            np.asarray(b.sim_state).reshape(-1), np.asarray(a.sim_state.status)
        )
        assert a.sim_message_count == b.sim_message_count

    def test_gossip_exact_rng_matches_single_device_node(self):
        g = G.barabasi_albert(1024, 3, seed=1)
        a = JaxSimNode(graph=g, protocol=Gossip(alpha=0.5), seed=2)
        b = JaxSimNode(graph=g, protocol=Gossip(alpha=0.5), seed=2,
                       mesh=M.ring_mesh(8), rng="exact")
        a.run_rounds(5)
        b.run_rounds(5)
        np.testing.assert_array_equal(
            np.asarray(b.sim_state).reshape(-1), np.asarray(a.sim_state.values)
        )

    def test_churn_and_events_match(self):
        g = _graph()
        rec = EventRecorder()
        a = JaxSimNode(graph=topology.with_capacity(g, extra_edges=16),
                       protocol=Flood(source=0), seed=0)
        b = JaxSimNode(graph=g, protocol=Flood(source=0), seed=0,
                       mesh=M.ring_mesh(8), dynamic_edges=8, callback=rec)
        a.fail_sim_nodes([5, 500])
        b.fail_sim_nodes([5, 500])
        a.inject_sim_churn(0.1)
        b.inject_sim_churn(0.1)  # same key schedule -> same failure set
        a.connect_sim_nodes([2], [900])
        b.connect_sim_nodes([2], [900])
        # Backend-agnostic topology introspection: sim_node_alive reads the
        # ACTIVE backend (on the mesh, sim_graph stays pristine by design).
        np.testing.assert_array_equal(b.sim_node_alive, a.sim_node_alive)
        assert a.sim_node_alive.sum() == b.sim_node_alive.sum() < 1024
        np.testing.assert_array_equal(
            np.asarray(b.sim_sharded.out_degree).reshape(-1),
            np.asarray(a.sim_graph.out_degree),
        )
        a.run_rounds(6)
        b.run_rounds(6)
        np.testing.assert_array_equal(
            np.asarray(b.sim_state[0]).reshape(-1), np.asarray(a.sim_state.seen)
        )
        topo_events = [d for d in rec.data_for("node_message")
                       if isinstance(d, dict) and "sim_topology" in d]
        assert [e["sim_topology"] for e in topo_events] == [
            "fail_nodes", "churn", "connect"
        ]
        assert topo_events[0]["alive_nodes"] == 1022

    def test_run_until_coverage_matches(self):
        g = _graph()
        a = JaxSimNode(graph=g, protocol=Flood(source=0), seed=0)
        b = JaxSimNode(graph=g, protocol=Flood(source=0), seed=0,
                       mesh=M.ring_mesh(8))
        a.run_rounds(2)
        b.run_rounds(2)
        out_a = a.run_until_coverage(0.99)
        out_b = b.run_until_coverage(0.99)
        assert out_a["rounds"] == out_b["rounds"]
        assert out_a["messages"] == out_b["messages"]
        assert a.sim_round == b.sim_round

    def test_run_until_coverage_sir_matches(self):
        g = _graph()
        proto = SIR(beta=0.5, gamma=0.1, source=0, method="segment")
        a = JaxSimNode(graph=g, protocol=proto, seed=5)
        b = JaxSimNode(graph=g, protocol=proto, seed=5,
                       mesh=M.ring_mesh(8), rng="exact")
        a.run_rounds(2)
        b.run_rounds(2)
        out_a = a.run_until_coverage(0.7, max_rounds=64)
        out_b = b.run_until_coverage(0.7, max_rounds=64)
        assert out_a["rounds"] == out_b["rounds"]
        assert out_a["messages"] == out_b["messages"]
        np.testing.assert_array_equal(
            np.asarray(b.sim_state).reshape(-1), np.asarray(a.sim_state.status)
        )

    def test_run_until_coverage_gossip_rejected(self):
        from p2pnetwork_tpu.models import Gossip

        b = JaxSimNode(graph=G.barabasi_albert(1024, 3, seed=0),
                       protocol=Gossip(), seed=0, mesh=M.ring_mesh(4))
        with pytest.raises(ValueError, match="coverage stat"):
            b.run_until_coverage(0.5)

    def test_checkpoint_roundtrip_with_churned_topology(self, tmp_path):
        g = _graph()
        mesh = M.ring_mesh(8)
        proto = SIR(beta=0.5, gamma=0.2, source=0)
        path = str(tmp_path / "mesh_node.npz")
        a = JaxSimNode(graph=g, protocol=proto, seed=9, mesh=mesh,
                       dynamic_edges=8, rng="exact")
        a.run_rounds(3)
        a.fail_sim_nodes([11, 400])
        a.inject_sim_churn(0.05)
        a.connect_sim_nodes([1], [700])
        a.run_rounds(2)
        a.save_checkpoint(path)
        a.run_rounds(4)

        b = JaxSimNode(graph=g, protocol=proto, seed=9, mesh=mesh,
                       dynamic_edges=8, rng="exact")
        b.load_checkpoint(path)
        assert b.sim_round == 5
        np.testing.assert_array_equal(
            np.asarray(b.sim_sharded.node_mask),
            np.asarray(a.sim_sharded.node_mask),
        )
        b.run_rounds(4)
        np.testing.assert_array_equal(
            np.asarray(b.sim_state), np.asarray(a.sim_state)
        )
        # Next churn draws identically (counter restored).
        a.inject_sim_churn(0.05)
        b.inject_sim_churn(0.05)
        np.testing.assert_array_equal(
            np.asarray(b.sim_sharded.node_mask),
            np.asarray(a.sim_sharded.node_mask),
        )


class TestMeshBackedValueProtocols:
    def test_pagerank_matches_single_device_node(self):
        from p2pnetwork_tpu.models import PageRank

        g = G.barabasi_albert(1024, 3, seed=2)
        a = JaxSimNode(graph=g, protocol=PageRank(), seed=5)
        b = JaxSimNode(graph=g, protocol=PageRank(), seed=5,
                       mesh=M.ring_mesh(8))
        a.run_rounds(6)
        a.run_rounds(4)
        b.run_rounds(6)
        b.run_rounds(4)
        np.testing.assert_allclose(
            np.asarray(b.sim_state).reshape(-1),
            np.asarray(a.sim_state.ranks),
            rtol=1e-4, atol=1e-9,
        )
        assert a.sim_round == b.sim_round == 10

    def test_pushsum_matches_single_device_node(self):
        from p2pnetwork_tpu.models import PushSum

        g = _graph()
        a = JaxSimNode(graph=g, protocol=PushSum(), seed=11)
        b = JaxSimNode(graph=g, protocol=PushSum(), seed=11,
                       mesh=M.ring_mesh(8))
        a.run_rounds(5)
        b.run_rounds(5)
        np.testing.assert_allclose(
            np.asarray(b.sim_state[0]).reshape(-1),
            np.asarray(a.sim_state.s), rtol=1e-4, atol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(b.sim_state[1]).reshape(-1),
            np.asarray(a.sim_state.w), rtol=1e-4, atol=1e-6,
        )
        assert a.sim_message_count == b.sim_message_count

    def test_hopdist_matches_single_device_node(self):
        from p2pnetwork_tpu.models import HopDistance

        g = _graph()
        a = JaxSimNode(graph=g, protocol=HopDistance(source=0), seed=1)
        b = JaxSimNode(graph=g, protocol=HopDistance(source=0), seed=1,
                       mesh=M.ring_mesh(8))
        a.run_rounds(4)
        b.run_rounds(4)
        np.testing.assert_array_equal(
            np.asarray(b.sim_state[0]).reshape(-1),
            np.asarray(a.sim_state.dist),
        )
        assert a.sim_message_count == b.sim_message_count

    def test_hopdist_coverage_and_checkpoint_roundtrip(self, tmp_path):
        # The scalar round leaf in HopDistance's state must replicate, not
        # take the rank-1 shard spec (regression: load_checkpoint crashed
        # on 0-d leaves); and run_until_coverage must ride the sharded
        # BFS loop with engine-identical rounds when coverage binds.
        from p2pnetwork_tpu.models import HopDistance

        g = _graph()
        proto = HopDistance(source=0)
        a = JaxSimNode(graph=g, protocol=proto, seed=2)
        b = JaxSimNode(graph=g, protocol=proto, seed=2, mesh=M.ring_mesh(8))
        out_a = a.run_until_coverage(0.99)
        out_b = b.run_until_coverage(0.99)
        assert out_a["rounds"] == out_b["rounds"]
        assert out_a["messages"] == out_b["messages"]
        np.testing.assert_array_equal(
            np.asarray(b.sim_state[0]).reshape(-1),
            np.asarray(a.sim_state.dist),
        )

        path = str(tmp_path / "hopdist_mesh.npz")
        b.save_checkpoint(path)
        c = JaxSimNode(graph=g, protocol=proto, seed=2, mesh=M.ring_mesh(8))
        c.load_checkpoint(path)
        np.testing.assert_array_equal(
            np.asarray(c.sim_state[0]), np.asarray(b.sim_state[0])
        )
        assert int(np.asarray(c.sim_state[2])) == int(np.asarray(b.sim_state[2]))

    def test_pagerank_run_until_converged(self):
        from p2pnetwork_tpu.models import PageRank

        g = G.barabasi_albert(1024, 3, seed=3)
        a = JaxSimNode(graph=g, protocol=PageRank(), seed=1)
        b = JaxSimNode(graph=g, protocol=PageRank(), seed=1,
                       mesh=M.ring_mesh(8))
        out_a = a.run_until_converged("residual", 1e-5)
        out_b = b.run_until_converged("residual", 1e-5)
        assert out_a["value"] < 1e-5 and out_b["value"] < 1e-5
        assert abs(out_a["rounds"] - out_b["rounds"]) <= 1
        assert a.sim_round == out_a["rounds"]
        with pytest.raises(ValueError, match="sharded backend"):
            JaxSimNode(graph=g, protocol=PageRank(), seed=1,
                       mesh=M.ring_mesh(4)).run_until_converged("rank_max",
                                                                0.5)

    def test_flood_adaptive_coverage_matches(self):
        g = _graph()
        a = JaxSimNode(graph=g, protocol=Flood(source=0), seed=0)
        b = JaxSimNode(graph=g, protocol=Flood(source=0), seed=0,
                       mesh=M.ring_mesh(8), adaptive_k=64)
        out_a = a.run_until_coverage(0.99)
        out_b = b.run_until_coverage(0.99)
        assert out_a == out_b
        np.testing.assert_array_equal(
            np.asarray(b.sim_state[0]).reshape(-1),
            np.asarray(a.sim_state.seen),
        )

    def test_pushsum_run_until_converged(self):
        from p2pnetwork_tpu.models import PushSum

        g = _graph()
        a = JaxSimNode(graph=g, protocol=PushSum(), seed=4)
        b = JaxSimNode(graph=g, protocol=PushSum(), seed=4,
                       mesh=M.ring_mesh(8))
        out_a = a.run_until_converged("variance", 1e-9)
        out_b = b.run_until_converged("variance", 1e-9)
        assert out_a["value"] < 1e-9 and out_b["value"] < 1e-9
        assert abs(out_a["rounds"] - out_b["rounds"]) <= 1


class TestSimNodeAdaptiveHopDistance:
    def test_hopdist_adaptive_coverage_matches(self):
        from p2pnetwork_tpu.models import HopDistance
        from p2pnetwork_tpu.parallel import mesh as M
        from p2pnetwork_tpu.sim import engine
        from p2pnetwork_tpu.sim import graph as G
        from p2pnetwork_tpu.sim.simnode import JaxSimNode

        g = G.watts_strogatz(1024, 6, 0.2, seed=30)
        node = JaxSimNode(graph=g, protocol=HopDistance(source=0),
                          mesh=M.ring_mesh(8), adaptive_k=64)
        out = node.run_until_coverage(0.99)
        _, ref = engine.run_until_coverage(
            g, HopDistance(source=0), jax.random.key(0),
            coverage_target=0.99,
        )
        assert out["rounds"] == ref["rounds"]
        assert out["messages"] == ref["messages"]
