"""SecureNode: signed messaging — envelope verification units plus
end-to-end delivery/rejection over real sockets.

The reference documents this class but does not ship it (README.md:224-238
advertises `p2pnetwork.securenode`; SURVEY.md section 2.2 records the file
as absent), so the scenarios here are derived from its described contract:
sign all messages, verify all messages, only verified payloads reach the
application."""

import pytest

# Without `cryptography` SecureNode degrades to the shared-key HMAC
# fallback, which needs a network_key these scenarios don't model — the
# Ed25519 contract under test here needs the real dependency. Skip the
# module cleanly instead of failing every test on this image.
pytest.importorskip("cryptography")

from p2pnetwork_tpu import Node, SecureNode
from p2pnetwork_tpu.securenode import payload_digest

from .helpers import EventRecorder, stop_all, wait_until


@pytest.fixture
def pair():
    rec_a, rec_b = EventRecorder(), EventRecorder()
    a = SecureNode("127.0.0.1", 0, id="alice", callback=rec_a)
    b = SecureNode("127.0.0.1", 0, id="bob", callback=rec_b)
    a.start()
    b.start()
    assert a.connect_with_node("127.0.0.1", b.port)
    assert wait_until(lambda: len(b.nodes_inbound) == 1)
    yield a, b, rec_a, rec_b
    stop_all([a, b])


class TestEnvelope:
    def test_roundtrip_verifies(self):
        n = SecureNode("127.0.0.1", 0, id="solo")
        try:
            env = n.make_envelope({"amount": 10, "to": "carol"})
            assert n.check_envelope(env) is None
        finally:
            stop_all([n])

    def test_tampered_payload_rejected(self):
        n = SecureNode("127.0.0.1", 0, id="solo")
        try:
            env = n.make_envelope({"amount": 10})
            env["payload"]["amount"] = 1000
            assert n.check_envelope(env) == "hash mismatch"
        finally:
            stop_all([n])

    def test_forged_hash_rejected(self):
        # Re-hashing a tampered payload without the key fails the signature.
        n = SecureNode("127.0.0.1", 0, id="solo")
        try:
            env = n.make_envelope({"amount": 10})
            env["payload"]["amount"] = 1000
            env["hash"] = payload_digest(env["payload"], env["signer"], env["nonce"])
            assert n.check_envelope(env) == "bad signature"
        finally:
            stop_all([n])

    def test_signer_id_is_covered(self):
        # Claiming someone else's id invalidates the message (non-repudiation).
        n = SecureNode("127.0.0.1", 0, id="solo")
        try:
            env = n.make_envelope("hello")
            env["signer"] = "mallory"
            assert n.check_envelope(env) is not None
        finally:
            stop_all([n])

    def test_other_nodes_key_rejected(self):
        a = SecureNode("127.0.0.1", 0, id="a")
        b = SecureNode("127.0.0.1", 0, id="b")
        try:
            env = a.make_envelope("hi")
            env["public_key"] = b.public_key_hex  # signature no longer matches
            assert a.check_envelope(env) == "bad signature"
        finally:
            stop_all([a, b])

    def test_impersonation_with_fresh_keypair_rejected(self):
        # Regression: a valid signature under the attacker's OWN key must
        # not authenticate a message claiming someone else's signer id once
        # the real key is known (pinned or seen).
        alice = SecureNode("127.0.0.1", 0, id="alice")
        mallory = SecureNode("127.0.0.1", 0, id="mallory")
        bob = SecureNode("127.0.0.1", 0, id="bob")
        try:
            forged = mallory.make_envelope({"pay": "mallory"})
            forged["signer"] = "alice"
            digest = payload_digest(forged["payload"], "alice", forged["nonce"])
            forged["hash"] = digest
            forged["signature"] = mallory._sign(digest)
            # Internally consistent envelope; only the key binding can stop it.
            bob.trust_key("alice", alice.public_key_hex)
            assert bob.check_envelope(forged) == "key mismatch for signer 'alice'"
            # TOFU: a genuine alice envelope pins her key; the forgery then
            # fails on carol too, with no explicit trust_key call.
            carol = SecureNode("127.0.0.1", 0, id="carol")
            try:
                assert carol.check_envelope(alice.make_envelope("hello")) is None
                assert carol.check_envelope(forged) == "key mismatch for signer 'alice'"
            finally:
                stop_all([carol])
        finally:
            stop_all([alice, mallory, bob])

    def test_scheme_mismatch_is_named(self, monkeypatch):
        import p2pnetwork_tpu.securenode as sn

        a = sn.SecureNode("127.0.0.1", 0, id="a")
        env = a.make_envelope("hi")
        stop_all([a])
        monkeypatch.setattr(sn, "_HAVE_ED25519", False)
        b = sn.SecureNode("127.0.0.1", 0, id="b", network_key=b"k")
        try:
            assert b.check_envelope(env) == "scheme mismatch: envelope ed25519, local hmac-sha512"
        finally:
            stop_all([b])

    def test_replayed_envelope_rejected(self):
        a = SecureNode("127.0.0.1", 0, id="a")
        b = SecureNode("127.0.0.1", 0, id="b")
        try:
            env = a.make_envelope({"tx": "pay", "amount": 5})
            assert b.check_envelope(env) is None
            assert b.check_envelope(env) == "replayed nonce"
            # A fresh envelope with the same payload has a fresh nonce.
            assert b.check_envelope(a.make_envelope({"tx": "pay", "amount": 5})) is None
        finally:
            stop_all([a, b])

    def test_replay_window_is_bounded(self):
        a = SecureNode("127.0.0.1", 0, id="a")
        b = SecureNode("127.0.0.1", 0, id="b")
        try:
            b.replay_window = 3
            envs = [a.make_envelope(i) for i in range(4)]
            for env in envs:
                assert b.check_envelope(env) is None
            # envs[0] fell out of the window; envs[3] is still inside.
            assert b.check_envelope(envs[0]) is None
            assert b.check_envelope(envs[3]) == "replayed nonce"
        finally:
            stop_all([a, b])

    def test_unhashable_nonce_is_invalid_not_crash(self):
        a = SecureNode("127.0.0.1", 0, id="a")
        b = SecureNode("127.0.0.1", 0, id="b")
        try:
            env = a.make_envelope("x")
            env["nonce"] = ["not", "a", "string"]  # JSON-legal, unhashable
            assert b.check_envelope(env) == "nonce must be a string"
        finally:
            stop_all([a, b])

    def test_tracked_signer_count_is_bounded(self):
        b = SecureNode("127.0.0.1", 0, id="b")
        signers = [SecureNode("127.0.0.1", 0, id=f"s{i}") for i in range(5)]
        try:
            b.max_tracked_signers = 3
            for s in signers:
                assert b.check_envelope(s.make_envelope("hi")) is None
            assert len(b._seen_nonces) == 3  # oldest signers evicted
        finally:
            stop_all([b] + signers)

    def test_active_signer_survives_eviction_pressure(self):
        # LRU, not FIFO: a signer that keeps messaging must not be flushed
        # by a burst of fresh signer ids (which would reopen replays).
        b = SecureNode("127.0.0.1", 0, id="b")
        victim = SecureNode("127.0.0.1", 0, id="victim")
        minted = [SecureNode("127.0.0.1", 0, id=f"m{i}") for i in range(4)]
        try:
            b.max_tracked_signers = 3
            captured = victim.make_envelope("pay me")
            assert b.check_envelope(captured) is None
            for s in minted[:2]:
                assert b.check_envelope(s.make_envelope("x")) is None
            # victim stays active -> refreshed to the fresh end
            assert b.check_envelope(victim.make_envelope("again")) is None
            for s in minted[2:]:
                assert b.check_envelope(s.make_envelope("x")) is None
            assert b.check_envelope(captured) == "replayed nonce"
        finally:
            stop_all([b, victim] + minted)

    def test_known_keys_bounded_but_explicit_pins_kept(self):
        b = SecureNode("127.0.0.1", 0, id="b")
        alice = SecureNode("127.0.0.1", 0, id="alice")
        minted = [SecureNode("127.0.0.1", 0, id=f"k{i}") for i in range(4)]
        try:
            b.max_known_keys = 3
            b.trust_key("alice", alice.public_key_hex)
            for s in minted:
                assert b.check_envelope(s.make_envelope("x")) is None
            assert len(b.known_keys) <= 3 + 1  # bounded (pin exempt)
            assert b.known_keys["alice"] == alice.public_key_hex  # never evicted
        finally:
            stop_all([b, alice] + minted)

    def test_hmac_nonstring_signature_is_invalid_not_crash(self, monkeypatch):
        import p2pnetwork_tpu.securenode as sn

        monkeypatch.setattr(sn, "_HAVE_ED25519", False)
        b = sn.SecureNode("127.0.0.1", 0, id="b", network_key=b"k")
        try:
            env = b.make_envelope("x")
            env["signature"] = 123
            assert b.check_envelope(env) == "bad signature"
        finally:
            stop_all([b])

    def test_stable_digest_across_key_order(self):
        d1 = payload_digest({"a": 1, "b": 2}, "s", "n")
        d2 = payload_digest({"b": 2, "a": 1}, "s", "n")
        assert d1 == d2


class TestEndToEnd:
    def test_signed_broadcast_delivered(self, pair):
        a, b, rec_a, rec_b = pair
        a.send_to_nodes_signed({"tx": "a->b", "amount": 5})
        assert wait_until(lambda: rec_b.count("secure_message") == 1)
        assert rec_b.data_for("secure_message") == [{"tx": "a->b", "amount": 5}]
        assert b.message_count_rerr == 0

    def test_forged_envelope_rejected_end_to_end(self, pair):
        a, b, rec_a, rec_b = pair
        # A plain (non-secure) node forging the envelope shape: bob must
        # reject it and never surface the payload as verified.
        mallory = Node("127.0.0.1", 0, id="mallory")
        mallory.start()
        try:
            assert mallory.connect_with_node("127.0.0.1", b.port)
            assert wait_until(lambda: len(b.nodes_inbound) == 2)
            mallory.send_to_nodes({
                "_secure": 1, "payload": {"evil": True}, "signer": "alice",
                "nonce": "00", "hash": "beef", "signature": "dead",
                "public_key": a.public_key_hex,
            })
            assert wait_until(lambda: rec_b.count("secure_message_invalid") == 1)
            assert rec_b.count("secure_message") == 0
            assert b.message_count_rerr == 1
        finally:
            stop_all([mallory])

    def test_plain_traffic_passes_through(self, pair):
        a, b, rec_a, rec_b = pair
        a.send_to_nodes("plain hello")
        assert wait_until(lambda: rec_b.count("node_message") == 1)
        assert rec_b.count("secure_message") == 0

    def test_relay_preserves_verifiability(self, pair):
        # Non-repudiation: bob can relay alice's envelope onward and carol
        # still verifies it as alice's (key travels with the message).
        a, b, rec_a, rec_b = pair
        rec_c = EventRecorder()
        c = SecureNode("127.0.0.1", 0, id="carol", callback=rec_c)
        c.start()
        try:
            env = a.make_envelope({"from": "alice"})
            assert b.connect_with_node("127.0.0.1", c.port)
            assert wait_until(lambda: len(c.nodes_inbound) == 1)
            b.send_to_nodes(env)  # bob relays without re-signing
            assert wait_until(lambda: rec_c.count("secure_message") == 1)
            assert rec_c.data_for("secure_message") == [{"from": "alice"}]
        finally:
            stop_all([c])


def test_hmac_fallback_scheme(monkeypatch):
    import p2pnetwork_tpu.securenode as sn

    monkeypatch.setattr(sn, "_HAVE_ED25519", False)
    with pytest.raises(ValueError, match="network_key"):
        n = sn.SecureNode("127.0.0.1", 0, id="nokey")
        stop_all([n])  # unreachable; ctor raises before binding teardown
    key = b"shared-secret"
    a = sn.SecureNode("127.0.0.1", 0, id="a", network_key=key)
    b = sn.SecureNode("127.0.0.1", 0, id="b", network_key=key)
    w = sn.SecureNode("127.0.0.1", 0, id="w", network_key=b"wrong")
    try:
        assert a.scheme == "hmac-sha512"
        env = a.make_envelope("hi")
        assert b.check_envelope(env) is None
        assert w.check_envelope(env) == "bad signature"
    finally:
        stop_all([a, b, w])


class TestSecureOverLengthFraming:
    def test_signed_broadcast_on_length_framing(self):
        # Feature composition: signed envelopes ride the opt-in
        # length-prefixed framing unchanged (the envelope is a dict — the
        # framing layer is invisible to the security layer).
        from p2pnetwork_tpu import NodeConfig

        rec = EventRecorder()
        cfg = NodeConfig(framing="length")
        a = SecureNode("127.0.0.1", 0, id="alice",
                       config=NodeConfig(framing="length"))
        b = SecureNode("127.0.0.1", 0, id="bob", callback=rec, config=cfg)
        a.start()
        b.start()
        try:
            assert a.connect_with_node("127.0.0.1", b.port)
            assert wait_until(lambda: len(b.nodes_inbound) == 1)
            a.send_to_nodes_signed({"tx": "framed", "n": 1},
                                   compression="zlib")
            assert wait_until(lambda: rec.count("secure_message") == 1)
            assert rec.data_for("secure_message") == [{"tx": "framed",
                                                       "n": 1}]
            assert b.message_count_rerr == 0
        finally:
            stop_all([a, b])
