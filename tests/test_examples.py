"""Every example script runs green, end to end.

The reference ships runnable example scripts as part of its surface
[ref: examples/my_own_p2p_application.py, _compression.py:37-40,
_using_dict.py:29] but never executes them in its test suite. Here each
example is a subprocess smoke test with a hard timeout — an example that
hangs, crashes, or rots against the API fails the suite.
"""

import os
import pathlib
import subprocess
import sys

import pytest

EXAMPLES_DIR = pathlib.Path(__file__).resolve().parent.parent / "examples"
EXAMPLES = sorted(p.name for p in EXAMPLES_DIR.glob("*.py"))


def test_examples_discovered():
    # The parity set must at least contain the reference's example shapes:
    # subclass app, callback app, compression, dict payloads, plus the sim
    # demos. A refactor that drops one should fail loudly here.
    for required in (
        "my_p2p_application.py",
        "my_peer2peer_node.py",
        "callback_application.py",
        "compression_application.py",
        "dict_application.py",
        "flood_demo.py",
        "simnode_demo.py",
        "auto_sharding_demo.py",
        "epidemic_with_failures.py",
        "secure_node_demo.py",
        "snapshot_application.py",
        "coordination_stack.py",
        "weighted_backbone.py",
        "crdt_application.py",
    ):
        assert required in EXAMPLES, f"missing example: {required}"


@pytest.mark.parametrize("name", EXAMPLES)
def test_example_runs(name):
    if name == "secure_node_demo.py":
        # The demo's Ed25519 path needs the `secure` extra; the HMAC
        # fallback covers the library (tests/test_securenode.py) but the
        # demo script itself signs with real keys.
        pytest.importorskip(
            "cryptography",
            reason="secure_node_demo needs the `cryptography` package "
                   "(install the `secure` extra)")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # examples must not grab the bench TPU
    proc = subprocess.run(
        [sys.executable, str(EXAMPLES_DIR / name)],
        cwd=str(EXAMPLES_DIR.parent),
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, (
        f"{name} exited {proc.returncode}\n"
        f"stdout:\n{proc.stdout[-2000:]}\nstderr:\n{proc.stderr[-2000:]}"
    )
