"""Comm-seam parity: the ppermute and Pallas ring-DMA halo backends are
bit-identical peers on every sharded protocol, lane-word batched path
included.

The seam (parallel/sharded.py ``comm=`` knob / ``_RingComm``) swaps how
the ring moves each resident block — ``lax.ppermute`` vs
``pltpu.make_async_remote_copy`` kernels (ops/pallas_ring.py, interpret
mode on the 8-device virtual CPU mesh) — without touching any protocol
arithmetic, so every sweep here pins exact equality, not tolerance. The
accounting half (commviz / graftaudit) must price the DMA hops like the
ppermute hops they replace: the ICI-estimate test is the acceptance
bound (within 20%; structurally identical today).
"""

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from p2pnetwork_tpu.models.flood import Flood  # noqa: E402
from p2pnetwork_tpu.models.gossip import Gossip  # noqa: E402
from p2pnetwork_tpu.models.hopdist import HopDistance  # noqa: E402
from p2pnetwork_tpu.models.messagebatch import BatchFlood  # noqa: E402
from p2pnetwork_tpu.models.sir import SIR  # noqa: E402
from p2pnetwork_tpu.ops import bitset  # noqa: E402
from p2pnetwork_tpu.ops import pallas_ring as PR  # noqa: E402
from p2pnetwork_tpu.ops import segment as SEG  # noqa: E402
from p2pnetwork_tpu.ops.pallas_edge import segment_sum_pallas_impl  # noqa: E402
from p2pnetwork_tpu.parallel import auto, commviz, sharded  # noqa: E402
from p2pnetwork_tpu.parallel import mesh as M  # noqa: E402
from p2pnetwork_tpu.sim import engine, failures, topology  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402

pytestmark = pytest.mark.ring

S = 8
needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < S, reason=f"needs {S} devices (virtual CPU mesh)")

BACKENDS = sharded.COMM_BACKENDS


def _mesh():
    return M.ring_mesh(S)


def _eq(a, b):
    return np.array_equal(np.asarray(a), np.asarray(b))


def _out_eq(a: dict, b: dict):
    assert a.keys() == b.keys()
    return all(np.array_equal(np.asarray(a[k]), np.asarray(b[k]))
               for k in a)


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < S:
        pytest.skip(f"needs {S} devices")
    return _mesh()


@pytest.fixture(scope="module")
def ws512():
    return G.watts_strogatz(512, 4, 0.2, seed=0, build_neighbor_table=True)


@pytest.fixture(scope="module")
def ragged300():
    # 300 nodes pad to 384; 384 / 8 shards = 48-node blocks — the last
    # shard's block is mostly padding and 48 is NOT a multiple of 32, so
    # the lane popcounts exercise their ragged-tail padding too.
    return G.erdos_renyi(300, 0.02, seed=1)


# ------------------------------------------------------------ kernel units


@needs_mesh
class TestRingShiftUnit:
    @pytest.mark.parametrize("dtype,shape", [
        (jnp.bool_, (64,)), (jnp.int32, (64,)), (jnp.float32, (48,)),
        (jnp.uint32, (3, 64)),
    ])
    @pytest.mark.parametrize("reverse", [False, True])
    def test_shift_matches_ppermute(self, dtype, shape, reverse):
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = _mesh()
        rng = np.random.default_rng(0)
        full = (S,) + shape
        x = jnp.asarray(rng.integers(0, 100, full)).astype(dtype)
        xs = jax.device_put(x, NamedSharding(mesh, P("shards")))

        def pallas_body(xb):
            return PR.ring_shift(xb[0], "shards", S, reverse=reverse)[None]

        perm = ([( (i + 1) % S, i) for i in range(S)] if reverse
                else [(i, (i + 1) % S) for i in range(S)])

        def ppermute_body(xb):
            return jax.lax.ppermute(xb, "shards", perm)

        spec = P("shards")
        got = jax.jit(sharded.shard_map(
            pallas_body, mesh=mesh, in_specs=spec, out_specs=spec,
            check_vma=False))(xs)
        ref = jax.jit(sharded.shard_map(
            ppermute_body, mesh=mesh, in_specs=spec, out_specs=spec,
            check_vma=False))(xs)
        assert _eq(got, ref)

    def test_single_shard_is_identity(self):
        x = jnp.arange(8.0)
        assert PR.ring_shift(x, "shards", 1) is x


@needs_mesh
class TestFusedKernel:
    @pytest.mark.parametrize("exact", [True, False])
    def test_fused_equals_shift_plus_segsum(self, exact):
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = _mesh()
        rng = np.random.default_rng(1)
        NB, W, BLK = 8, 512, 128
        contrib = jnp.asarray(rng.random((S, NB, W)), jnp.float32)
        dst = jnp.asarray(rng.integers(0, BLK, (S, NB, W)), jnp.int32)
        rot = jnp.asarray(rng.random((S, BLK)), jnp.float32)
        sh = NamedSharding(mesh, P("shards"))
        cs, ds, rs = (jax.device_put(a, sh) for a in (contrib, dst, rot))

        def fused(rb, cb, db):
            rn, out = PR.ring_segment_sum(rb[0], cb[0], db[0], "shards", S,
                                          BLK, exact=exact)
            return rn[None], out[None]

        spec = P("shards")
        rn, out = jax.jit(sharded.shard_map(
            fused, mesh=mesh, in_specs=(spec,) * 3, out_specs=(spec,) * 2,
            check_vma=False))(rs, cs, ds)
        ref_out = np.stack([
            np.asarray(segment_sum_pallas_impl(contrib[d], dst[d], BLK,
                                               exact=exact))
            for d in range(S)])
        assert _eq(out, ref_out)
        assert _eq(rn, np.roll(np.asarray(rot), 1, axis=0))

    def test_rejects_single_shard(self):
        with pytest.raises(ValueError, match="ring of >= 2"):
            PR.ring_segment_sum(jnp.zeros(4), jnp.zeros((8, 512)),
                                jnp.zeros((8, 512), jnp.int32), "shards", 1)


# ----------------------------------------------------- protocol parity sweep


@needs_mesh
class TestCommParity:
    """Every sharded protocol, ppermute vs pallas, exact equality."""

    @pytest.mark.parametrize("layout", ["segment", "mxu", "hybrid"])
    def test_flood_fixed_rounds(self, mesh, ws512, layout):
        kw = {"mxu": True} if layout == "mxu" else (
            {"hybrid": True, "min_count": 32} if layout == "hybrid" else {})
        sg = sharded.shard_graph(ws512, mesh, **kw)
        outs = {}
        for comm in BACKENDS:
            seen, stats = sharded.flood(sg, mesh, source=0, rounds=4,
                                        comm=comm)
            outs[comm] = (np.asarray(seen), jax.tree_util.tree_map(
                np.asarray, stats))
        assert _eq(outs["ppermute"][0], outs["pallas"][0])
        assert _out_eq(outs["ppermute"][1], outs["pallas"][1])
        # and the sharded result is the engine's result
        ref, _ = engine.run(ws512, Flood(source=0), jax.random.key(0), 4)
        assert _eq(outs["pallas"][0].reshape(-1)[: ws512.n_nodes],
                   np.asarray(ref.seen)[: ws512.n_nodes])

    def test_flood_coverage_ragged_last_shard(self, mesh, ragged300):
        sg = sharded.shard_graph(ragged300, mesh)
        outs = []
        for comm in BACKENDS:
            seen, out = sharded.flood_until_coverage(
                sg, mesh, source=0, coverage_target=0.9, comm=comm)
            outs.append((np.asarray(seen), out))
        assert _eq(outs[0][0], outs[1][0])
        assert outs[0][1] == outs[1][1]

    def test_flood_coverage_failed_edges_and_runtime_links(self, mesh,
                                                           ws512):
        fail_ids = [3, 200]
        sgc = sharded.with_capacity(
            sharded.fail_nodes(sharded.shard_graph(ws512, mesh), fail_ids),
            8)
        sgc = sharded.connect(sgc, [1], [ws512.n_nodes - 2])
        outs = []
        for comm in BACKENDS:
            seen, out = sharded.flood_until_coverage(
                sgc, mesh, source=0, coverage_target=0.9, comm=comm)
            outs.append((np.asarray(seen), out))
        assert _eq(outs[0][0], outs[1][0])
        assert outs[0][1] == outs[1][1]
        # cross-check against the single-device engine under the same churn
        gc = topology.connect(
            topology.with_capacity(failures.fail_nodes(ws512, fail_ids),
                                   extra_edges=8),
            [1], [ws512.n_nodes - 2])
        _, ref = engine.run_until_coverage(
            gc, Flood(source=0), jax.random.key(0), coverage_target=0.9)
        assert outs[0][1]["rounds"] == ref["rounds"]
        assert outs[0][1]["messages"] == ref["messages"]

    def test_remask_parity(self, mesh, ws512):
        sg = sharded.shard_graph(ws512, mesh)
        alive = jnp.ones(sg.n_nodes_padded, bool).at[
            jnp.asarray([5, 100, 300])].set(False)
        a = sharded.with_node_liveness(sg, alive, comm="ppermute")
        b = sharded.with_node_liveness(sg, alive, comm="pallas")
        for f in ("bkt_mask", "node_mask", "out_degree", "in_degree"):
            assert _eq(getattr(a, f), getattr(b, f)), f

    def test_sir_exact_rng(self, mesh, ws512):
        sg = sharded.shard_graph(ws512, mesh)
        proto = SIR(beta=0.4, gamma=0.1, source=0)
        key = jax.random.key(3)
        a, sa = sharded.sir(sg, mesh, proto, key, 4, exact_rng=True,
                            comm="ppermute")
        b, sb = sharded.sir(sg, mesh, proto, key, 4, exact_rng=True,
                            comm="pallas")
        assert _eq(a, b)
        assert _out_eq(jax.tree_util.tree_map(np.asarray, sa),
                       jax.tree_util.tree_map(np.asarray, sb))

    def test_gossip(self, mesh, ws512):
        sg = sharded.shard_graph(ws512, mesh)
        key = jax.random.key(4)
        a, _ = sharded.gossip(sg, mesh, Gossip(alpha=0.5), key, 4,
                              comm="ppermute")
        b, _ = sharded.gossip(sg, mesh, Gossip(alpha=0.5), key, 4,
                              comm="pallas")
        assert _eq(a, b)

    @pytest.mark.parametrize("op,dtype", [
        ("or", bool), ("sum", jnp.float32), ("max", jnp.float32),
        ("minplus", jnp.float32),
    ])
    @pytest.mark.parametrize("graph_name", ["ws512", "ragged300"])
    def test_propagate_ops(self, mesh, ws512, ragged300, op, dtype,
                           graph_name):
        g = ws512 if graph_name == "ws512" else ragged300
        sg = sharded.shard_graph(g, mesh)
        rng = np.random.default_rng(7)
        if op == "or":
            sig = jnp.asarray(rng.random(sg.n_nodes_padded) < 0.2)
        elif op == "minplus":
            sig = jnp.where(jnp.arange(sg.n_nodes_padded) == 0, 0.0,
                            jnp.inf)
        else:
            sig = jnp.asarray(rng.random(sg.n_nodes_padded), jnp.float32)
        sig = sig.reshape(sg.n_shards, sg.block)
        a = sharded.propagate(sg, mesh, sig, op, comm="ppermute")
        b = sharded.propagate(sg, mesh, sig, op, comm="pallas")
        assert _eq(a, b)

    def test_minplus_matches_single_device(self, mesh, ws512):
        sg = sharded.shard_graph(ws512, mesh)
        dist = jnp.where(jnp.arange(ws512.n_nodes_padded) == 0, 0.0,
                         jnp.inf)
        ref = np.asarray(SEG.propagate_min_plus(ws512, dist,
                                                method="segment"))
        for comm in BACKENDS:
            got = np.asarray(sharded.propagate(
                sg, mesh, dist.reshape(sg.n_shards, sg.block), "minplus",
                comm=comm)).reshape(-1)
            assert _eq(got, ref), comm

    def test_sir_until_coverage(self, mesh, ws512):
        sg = sharded.shard_graph(ws512, mesh)
        key = jax.random.key(0)
        proto = SIR(beta=0.5, gamma=0.05, source=0)
        a = sharded.sir_until_coverage(sg, mesh, proto, key,
                                       coverage_target=0.8, comm="ppermute")
        b = sharded.sir_until_coverage(sg, mesh, proto, key,
                                       coverage_target=0.8, comm="pallas")
        assert _eq(a[0], b[0]) and a[1] == b[1]

    def test_convergence_loops(self, mesh, ws512):
        from p2pnetwork_tpu.models.pagerank import PageRank
        from p2pnetwork_tpu.models.pushsum import PushSum

        sg = sharded.shard_graph(ws512, mesh)
        key = jax.random.key(0)
        ra, oa = sharded.pagerank_until_residual(sg, mesh, PageRank(),
                                                 tol=1e-3, comm="ppermute")
        rb, ob = sharded.pagerank_until_residual(sg, mesh, PageRank(),
                                                 tol=1e-3, comm="pallas")
        assert _eq(ra, rb) and oa == ob
        (sa, _), va = sharded.pushsum_until_variance(
            sg, mesh, PushSum(), key, tol=1e-4, comm="ppermute")
        (sb, _), vb = sharded.pushsum_until_variance(
            sg, mesh, PushSum(), key, tol=1e-4, comm="pallas")
        assert _eq(sa, sb) and va == vb

    def test_hopdist_until_done(self, mesh, ws512):
        sg = sharded.shard_graph(ws512, mesh)
        (da, _, ra), oa = sharded.hopdist_until_done(
            sg, mesh, HopDistance(source=0), comm="ppermute")
        (db, _, rb), ob = sharded.hopdist_until_done(
            sg, mesh, HopDistance(source=0), comm="pallas")
        assert _eq(da, db) and oa == ob and int(ra) == int(rb)


# ------------------------------------------------- lane-word batched plane


@needs_mesh
class TestLaneWords:
    def test_shard_lanes_roundtrip(self, mesh, ragged300):
        sg = sharded.shard_graph(ragged300, mesh)
        rng = np.random.default_rng(2)
        lanes = jnp.asarray(rng.integers(0, 2**32, (3, 384),
                                         dtype=np.uint64).astype(np.uint32))
        back = sharded.unshard_lanes(sg, sharded.shard_lanes(sg, lanes),
                                     384)
        assert _eq(back, lanes)

    @pytest.mark.parametrize("graph_name", ["ws512", "ragged300"])
    def test_or_lanes_matches_single_device(self, mesh, ws512, ragged300,
                                            graph_name):
        g = ws512 if graph_name == "ws512" else ragged300
        sg = sharded.shard_graph(g, mesh)
        rng = np.random.default_rng(3)
        lanes = jnp.asarray(rng.integers(
            0, 2**32, (2, g.n_nodes_padded),
            dtype=np.uint64).astype(np.uint32))
        ref = np.asarray(SEG.propagate_or_lanes(g, lanes, "segment"))
        for comm in BACKENDS:
            got = sharded.unshard_lanes(
                sg,
                sharded.propagate_or_lanes(
                    sg, mesh, sharded.shard_lanes(sg, lanes), comm=comm),
                g.n_nodes_padded)
            assert _eq(got, ref), comm

    def test_or_lanes_rejects_mxu_layout(self, mesh, ws512):
        sg = sharded.shard_graph(ws512, mesh, mxu=True)
        lanes = sharded.shard_lanes(
            sg, jnp.zeros((1, ws512.n_nodes_padded), jnp.uint32))
        with pytest.raises(ValueError, match="MXU one-hot layout"):
            sharded.propagate_or_lanes(sg, mesh, lanes)

    def _batch_on_both(self, g, sg, mesh, sources, comm, target=0.97,
                       max_rounds=64):
        proto = BatchFlood(method="auto")
        b_engine = proto.init(g, sources, coverage_target=target)
        b_ring = proto.init(g, sources, coverage_target=target)
        eb, eout = engine.run_batch_until_coverage(
            g, proto, b_engine, jax.random.key(0), max_rounds=max_rounds,
            donate=False)
        sb, sout = sharded.run_batch_until_coverage(
            sg, mesh, proto, b_ring, max_rounds=max_rounds, comm=comm,
            donate=False)
        return eb, eout, sb, sout

    @pytest.mark.parametrize("comm", BACKENDS)
    @pytest.mark.parametrize("graph_name", ["ws512", "ragged300"])
    def test_batch_bit_identical_to_engine(self, mesh, ws512, ragged300,
                                           comm, graph_name):
        g = ws512 if graph_name == "ws512" else ragged300
        sg = sharded.shard_graph(g, mesh)
        # 40 lanes -> ragged last word; duplicate sources are independent
        # messages (PR-10 contract), kept in the sweep on purpose.
        sources = np.concatenate([
            (np.arange(38, dtype=np.int32) * 7) % g.n_nodes,
            np.asarray([5, 5], dtype=np.int32),
        ])
        eb, eout, sb, sout = self._batch_on_both(g, sg, mesh, sources, comm)
        for k in ("rounds", "messages", "active_lanes", "completed",
                  "occupancy_mean"):
            assert eout[k] == sout[k], k
        assert _eq(eout["lane_done"], sout["lane_done"])
        assert _eq(eout["lane_rounds"], sout["lane_rounds"])
        assert eout.get("completion_rounds_p99") == \
            sout.get("completion_rounds_p99")
        for f in ("seen", "frontier", "sent", "done", "rounds",
                  "seen_count", "source", "admitted"):
            assert _eq(getattr(eb, f), getattr(sb, f)), f

    def test_batch_backends_agree(self, mesh, ws512):
        sg = sharded.shard_graph(ws512, mesh)
        sources = (np.arange(40, dtype=np.int32) * 13) % ws512.n_nodes
        proto = BatchFlood()
        b1 = proto.init(ws512, sources)
        b2 = proto.init(ws512, sources)
        a, oa = sharded.run_batch_until_coverage(
            sg, mesh, proto, b1, comm="ppermute", donate=False)
        b, ob = sharded.run_batch_until_coverage(
            sg, mesh, proto, b2, comm="pallas", donate=False)
        assert all(np.array_equal(np.asarray(oa[k]), np.asarray(ob[k]))
                   for k in oa)
        assert _eq(a.seen, b.seen)

    def test_batch_second_wave_admission(self, mesh, ws512):
        # retire + admit a second wave into the RETURNED batch, continue
        # on both paths — the serving-loop shape, multi-chip.
        g, sg = ws512, sharded.shard_graph(ws512, mesh)
        proto = BatchFlood()
        src1 = (np.arange(20, dtype=np.int32) * 11) % g.n_nodes
        src2 = (np.arange(10, dtype=np.int32) * 17 + 3) % g.n_nodes
        eb = proto.init(g, src1, capacity=40)
        sb = proto.init(g, src1, capacity=40)
        eb, _ = engine.run_batch_until_coverage(
            g, proto, eb, jax.random.key(0), donate=False)
        sb, _ = sharded.run_batch_until_coverage(
            sg, mesh, proto, sb, donate=False)
        eb = proto.retire(eb)
        sb = proto.retire(sb)
        eb, el = proto.admit(g, eb, src2)
        sb, sl = proto.admit(g, sb, src2)
        assert _eq(el, sl)
        eb, eout = engine.run_batch_until_coverage(
            g, proto, eb, jax.random.key(1), donate=False)
        sb, sout = sharded.run_batch_until_coverage(
            sg, mesh, proto, sb, donate=False)
        assert all(np.array_equal(np.asarray(eout[k]), np.asarray(sout[k]))
                   for k in eout)
        assert _eq(eb.seen, sb.seen)

    def test_batch_refresh_after_failures(self, mesh, ws512):
        # Node failures BETWEEN calls: the sharded entry's eager refresh
        # must re-decide done-ness against the CURRENT mask exactly like
        # the engine's (latched completion included).
        g = ws512
        sg = sharded.shard_graph(g, mesh)
        proto = BatchFlood()
        sources = (np.arange(8, dtype=np.int32) * 29) % g.n_nodes
        eb = proto.init(g, sources, coverage_target=0.9)
        sb = proto.init(g, sources, coverage_target=0.9)
        eb, _ = engine.run_batch_until_coverage(
            g, proto, eb, jax.random.key(0), max_rounds=3, donate=False)
        sb, _ = sharded.run_batch_until_coverage(
            sg, mesh, proto, sb, max_rounds=3, donate=False)
        dead = [7, 9, 11, 40, 41]
        g2 = failures.fail_nodes(g, dead)
        sg2 = sharded.fail_nodes(sg, dead)
        eb, eout = engine.run_batch_until_coverage(
            g2, proto, eb, jax.random.key(1), donate=False)
        sb, sout = sharded.run_batch_until_coverage(
            sg2, mesh, proto, sb, donate=False)
        assert all(np.array_equal(np.asarray(eout[k]), np.asarray(sout[k]))
                   for k in eout)
        assert _eq(eb.seen_count, sb.seen_count)
        assert _eq(eb.done, sb.done)

    def test_batch_donation_consumes_input(self, mesh, ws512):
        sg = sharded.shard_graph(ws512, mesh)
        proto = BatchFlood()
        b = proto.init(ws512, [1, 2, 3])
        b2 = proto.init(ws512, [1, 2, 3])
        sb1, o1 = sharded.run_batch_until_coverage(
            sg, mesh, proto, b, donate=True)
        sb2, o2 = sharded.run_batch_until_coverage(
            sg, mesh, proto, b2, donate=False)
        assert all(np.array_equal(np.asarray(o1[k]), np.asarray(o2[k]))
                   for k in o1)
        assert _eq(sb1.seen, sb2.seen)
        # the donated input is consumed (engine contract): reuse raises
        # the friendly deleted-buffer error
        with pytest.raises(ValueError, match="deleted device buffers"):
            sharded.run_batch_until_coverage(sg, mesh, proto, b,
                                             donate=False)

    def test_batch_rejects_mxu_layout(self, mesh, ws512):
        sg = sharded.shard_graph(ws512, mesh, hybrid=True, min_count=32)
        proto = BatchFlood()
        b = proto.init(ws512, [1])
        with pytest.raises(ValueError, match="MXU one-hot layout"):
            sharded.run_batch_until_coverage(sg, mesh, proto, b)


# ------------------------------------------------------- ICI accounting


@needs_mesh
class TestCommAccounting:
    def test_marker_constants_locked(self):
        # commviz stays importable without jax, so it duplicates the
        # marker — the two must never drift.
        assert commviz.RING_DMA_MARKER == PR.RING_DMA_MARKER

    def _cov_fn_args(self, comm, n=1024):
        g = G.watts_strogatz(n, 6, 0.2, seed=0)
        mesh = _mesh()
        sg = sharded.shard_graph(g, mesh)
        seen0, frontier0 = sharded.init_state(sg, Flood(source=0), None)
        fn = sharded._flood_cov_fn(mesh, "shards", sg.n_shards, sg.block,
                                   64, sg.diag_pieces, sg.mxu_block, comm)
        args = (jnp.float32(0.99), sg.bkt_src, sg.bkt_dst, sg.bkt_mask,
                *sharded._dyn_or_empty(sg), *sharded._mxu_or_empty(sg),
                sharded._diag_masks_or_empty(sg), sg.node_mask,
                sg.out_degree, seen0, frontier0)
        return fn, args

    def test_pallas_ici_estimate_within_20pct_of_ppermute(self):
        # The acceptance bound: the pallas backend's commviz ICI byte
        # estimate within 20% of the ppermute backend on the same graph
        # (the shared ring model makes them identical today; 20% is the
        # drift ceiling, not the expectation).
        est = {}
        for comm in BACKENDS:
            fn, args = self._cov_fn_args(comm)
            est[comm] = commviz.ici_bytes_estimate(fn, args, S)
        assert est["ppermute"] > 0
        ratio = est["pallas"] / est["ppermute"]
        assert 0.8 <= ratio <= 1.2, est

    def test_census_sees_ring_dma_not_zero(self):
        fn, args = self._cov_fn_args("pallas")
        census = commviz.jaxpr_comm_census(fn, args, S)
        # S-1 hops per ring pass: the hop sits in a length-(S-1) scan and
        # the census weights by static trip counts (the last bucket is
        # peeled — its hop would be wasted ICI).
        assert census["ring_dma"]["count"] == S - 1
        assert census["ring_dma"]["bytes"] > 0
        assert "ppermute" not in census
        fnp, argsp = self._cov_fn_args("ppermute")
        censusp = commviz.jaxpr_comm_census(fnp, argsp, S)
        assert censusp["ppermute"]["count"] == S - 1
        assert censusp["ppermute"]["bytes"] == census["ring_dma"]["bytes"]

    def test_lane_word_halo_priced_per_word(self):
        # The lane-word payload is W u32 words per node block — one hop
        # moves 32·W messages' boundary state, and the census prices the
        # whole stack.
        g = G.watts_strogatz(1024, 6, 0.2, seed=0)
        mesh = _mesh()
        sg = sharded.shard_graph(g, mesh)
        est = {}
        for w in (1, 4):
            lanes = sharded.shard_lanes(
                sg, jnp.zeros((w, g.n_nodes_padded), jnp.uint32))
            fn = sharded._or_lanes_fn(mesh, "shards", sg.n_shards,
                                      sg.block, "pallas")
            args = (sg.bkt_src, sg.bkt_dst, sg.bkt_mask,
                    *sharded._dyn_or_empty(sg), sg.node_mask, lanes)
            est[w] = commviz.jaxpr_comm_census(fn, args, S)[
                "ring_dma"]["bytes"]
        assert est[4] == 4 * est[1]

    def test_registry_has_ringstep_parity_pair(self):
        from p2pnetwork_tpu.analysis.ir import registry

        names = {e.name: e for e in registry.all_lowerings()}
        assert "ringstep/ppermute@ws1k" in names
        assert "ringstep/pallas@ws1k" in names
        assert "or_lanes/sharded-ring@ws1k" in names
        assert "cov/batchflood-ring@ws1k" in names
        pair = [names["ringstep/ppermute@ws1k"],
                names["ringstep/pallas@ws1k"]]
        assert all(e.parity for e in pair)
        traces = [registry.trace_lowering(e) for e in pair]
        assert traces[0].error is None and traces[1].error is None
        assert traces[0].out_sig == traces[1].out_sig
        assert traces[0].collectives.get("ppermute", 0) == 1
        assert traces[1].collectives.get(commviz.RING_DMA_KEY, 0) == 1
        assert traces[0].ici_bytes_est == traces[1].ici_bytes_est

    def test_sharded_batch_donation_audited(self):
        from p2pnetwork_tpu.analysis.ir import donation

        audits = {a.name: a for a in donation.all_donation_audits()}
        assert "sharded/batch_from" in audits
        fn, args, kwargs, expected = audits["sharded/batch_from"].build()
        counts = donation.check_aliasing(fn, args, expected, kwargs)
        assert counts["requested"] >= expected
        assert counts["honored"] >= expected


class TestRouting:
    def test_backend_sets_pinned_together(self):
        # Three declarations (sharded owns the truth; auto's literal is
        # doc-only, config's keeps config jax-free) — they must never
        # drift, like the RING_DMA_MARKER duplicate.
        from p2pnetwork_tpu import config

        assert auto.COMM_BACKENDS == sharded.COMM_BACKENDS
        assert config.COMM_CHOICES == sharded.COMM_BACKENDS + ("auto",)

    def test_resolve_comm_validates(self):
        assert auto.resolve_comm("ppermute") == "ppermute"
        assert auto.resolve_comm("pallas") == "pallas"
        # this suite runs on CPU: auto routes to ppermute there
        assert auto.resolve_comm("auto") == "ppermute"
        with pytest.raises(ValueError, match="comm must be one of"):
            auto.resolve_comm("smoke-signals")

    def test_mesh_config_knob(self):
        from p2pnetwork_tpu.config import MeshConfig

        assert MeshConfig().comm == "ppermute"
        assert MeshConfig(comm="auto").comm == "auto"
        with pytest.raises(ValueError, match="unknown comm backend"):
            MeshConfig(comm="carrier-pigeon")

    def test_sharded_entry_rejects_bad_comm(self):
        with pytest.raises(ValueError):
            sharded._resolve_comm("nope")

    def test_ring_comm_object_rejects_bad_backend(self):
        with pytest.raises(ValueError, match="comm must be one of"):
            sharded._make_ring_comm("nope", "shards", 8)
