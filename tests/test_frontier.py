"""Frontier-compacted fast path (ops/frontier.py) and bit-packed state
(ops/bitset.py): bit-exact equivalence vs the dense lowerings over a
seeded sweep, packed-state protocol parity, engine buffer donation, the
occupancy stat plumbing, and the slow-marked edge-gather work bench.

The equivalence sweep is deliberately hypothesis-free: fixed seeds over
three graph families x three sizes x an occupancy ladder, including the
padded-slot and isolated-node edge cases — every case is reproducible
from its parameters alone."""

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from p2pnetwork_tpu.models.adaptive_flood import AdaptiveFlood  # noqa: E402
from p2pnetwork_tpu.models.flood import Flood, FloodBitState  # noqa: E402
from p2pnetwork_tpu.models.plumtree import Plumtree  # noqa: E402
from p2pnetwork_tpu.ops import bitset, frontier, segment  # noqa: E402
from p2pnetwork_tpu.sim import engine, failures  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def _families(n, **kw):
    return [
        G.erdos_renyi(n, min(8.0 / n, 0.4), seed=n, source_csr=True, **kw),
        G.watts_strogatz(n, 4, 0.2, seed=n + 1, source_csr=True, **kw),
        G.ring(n, source_csr=True, **kw),
    ]


#: Occupancy ladder: empty, singleton, sparse (the fast-path regime),
#: past any crossover, full.
_OCCUPANCIES = (0.0, "one", 0.05, 0.5, 1.0)


def _signals(g, rng):
    n_pad = g.n_nodes_padded
    for occ in _OCCUPANCIES:
        if occ == "one":
            sig = np.zeros(n_pad, dtype=bool)
            sig[rng.integers(0, g.n_nodes)] = True
        else:
            sig = rng.random(n_pad) < occ
        yield jnp.asarray(sig) & g.node_mask


class TestEquivalenceSweep:
    @pytest.mark.parametrize("n", [17, 128, 1000])
    def test_or_max_min_plus_match_dense(self, n):
        rng = np.random.default_rng(7)
        for g in _families(n):
            n_pad = g.n_nodes_padded
            # One jitted pair per (graph, op), reused across the whole
            # occupancy ladder — per-call eager lax.cond would recompile
            # its branches for every fresh closure.
            pairs = [
                (jax.jit(lambda s: segment.propagate_or(g, s, "frontier")),
                 jax.jit(lambda s: segment.propagate_or(g, s, "segment")),
                 lambda s: s),
                (jax.jit(lambda x: segment.propagate_max(g, x, "frontier")),
                 jax.jit(lambda x: segment.propagate_max(g, x, "segment")),
                 lambda s: jnp.where(s, jnp.asarray(
                     rng.integers(0, 1000, n_pad), jnp.int32),
                     jnp.iinfo(jnp.int32).min)),
                (jax.jit(lambda d: segment.propagate_min_plus(g, d,
                                                              "frontier")),
                 jax.jit(lambda d: segment.propagate_min_plus(g, d,
                                                              "segment")),
                 lambda s: jnp.where(s, jnp.asarray(
                     rng.random(n_pad), jnp.float32), jnp.inf)),
            ]
            for sig in _signals(g, rng):
                for fr, dense, make in pairs:
                    x = make(sig)
                    np.testing.assert_array_equal(np.asarray(fr(x)),
                                                  np.asarray(dense(x)))

    def test_weighted_min_plus_matches_dense(self):
        g = G.watts_strogatz(256, 4, 0.2, seed=3, source_csr=True).with_weights(
            lambda s, r: 0.5 + (s % 7) / 3.0)
        rng = np.random.default_rng(5)
        for d0 in _signals(g, rng):
            d = jnp.where(d0, 1.0, jnp.inf)
            np.testing.assert_array_equal(
                np.asarray(segment.propagate_min_plus(g, d, "frontier")),
                np.asarray(segment.propagate_min_plus(g, d, "segment")))

    def test_padded_slot_signal_contributes_nothing(self):
        # n=17 pads to 128 nodes / 128 edge slots; a signal lit on PADDED
        # slots must not leak through either path (and both must agree).
        g = G.ring(17, source_csr=True)
        sig = jnp.ones(g.n_nodes_padded, dtype=bool)  # padded slots lit
        a = segment.propagate_or(g, sig, "frontier")
        b = segment.propagate_or(g, sig, "segment")
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not np.asarray(a)[17:].any()

    def test_isolated_node_gets_identity(self):
        # Nodes 3/4 have no edges at all; an ACTIVE isolated node sends to
        # no one and receives the aggregation identity on both paths.
        g = G.from_edges([0, 1, 1, 2], [1, 0, 2, 1], 5, source_csr=True)
        sig = jnp.zeros(g.n_nodes_padded, dtype=bool).at[3].set(True)
        a = segment.propagate_or(g, sig, "frontier")
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(segment.propagate_or(g, sig, "segment")))
        assert not np.asarray(a)[:5].any()
        d = jnp.where(sig, 0.0, jnp.inf)
        mp = segment.propagate_min_plus(g, d, "frontier")
        np.testing.assert_array_equal(
            np.asarray(mp),
            np.asarray(segment.propagate_min_plus(g, d, "segment")))
        assert np.isinf(np.asarray(mp)[3])  # no in-edges -> identity

    def test_dynamic_edges_fold_in(self):
        from p2pnetwork_tpu.sim import topology

        g = topology.with_capacity(G.ring(64, source_csr=True),
                                   extra_edges=8)
        g = topology.connect(g, [0], [32])
        sig = jnp.zeros(g.n_nodes_padded, dtype=bool).at[0].set(True)
        a = np.asarray(segment.propagate_or(g, sig, "frontier"))
        np.testing.assert_array_equal(
            a, np.asarray(segment.propagate_or(g, sig, "segment")))
        assert a[32]  # the runtime link delivered

    def test_failed_edges_masked(self):
        g = G.ring(128, source_csr=True)
        gf = failures.fail_edges(g, [0, 1, 5])
        rng = np.random.default_rng(11)
        for sig in _signals(gf, rng):
            np.testing.assert_array_equal(
                np.asarray(segment.propagate_or(gf, sig, "frontier")),
                np.asarray(segment.propagate_or(gf, sig, "segment")))

    def test_requires_source_csr(self):
        g = G.ring(64)
        with pytest.raises(ValueError, match="source-CSR"):
            segment.propagate_or(g, g.node_mask, "frontier")

    def test_budget_override_and_bounds(self):
        g = G.ring(1000, source_csr=True)
        auto = frontier.budget(g)
        assert frontier._MIN_BUDGET <= auto <= g.n_nodes_padded
        assert frontier.budget(g, 0.5) == g.n_nodes_padded // 2
        assert frontier.budget(g, 300) == 300
        with pytest.raises(ValueError, match="fraction"):
            frontier.budget(g, 1.5)

    def test_hub_graph_disables_sparse_but_stays_exact(self):
        # A hub's out-row widens every compaction slot: when even the
        # _MIN_BUDGET floor breaks the slot bound, auto disables the
        # sparse branch outright (k=0) and method='frontier' is a pure
        # dense pass-through — never a slowdown, always exact.
        g = G.barabasi_albert(1024, 3, seed=2, source_csr=True)
        if frontier.budget(g) == 0:  # the scenario the guard exists for
            rng = np.random.default_rng(4)
            for sig in _signals(g, rng):
                np.testing.assert_array_equal(
                    np.asarray(segment.propagate_or(g, sig, "frontier")),
                    np.asarray(segment.propagate_or(g, sig, "segment")))
        # an explicit override still forces the sparse machinery
        assert frontier.budget(g, 256) == 256

    def test_crossover_override_threads_through_and_stays_exact(self):
        # The re-fit "apply" step: an explicit crossover reaches the
        # budget through propagate_* and through Flood's config, forcing
        # either branch — results stay bit-exact in both regimes.
        g = G.watts_strogatz(512, 4, 0.2, seed=6, source_csr=True)
        rng = np.random.default_rng(3)
        sig = jnp.asarray(rng.random(g.n_nodes_padded) < 0.3) & g.node_mask
        ref = np.asarray(segment.propagate_or(g, sig, "segment"))
        for crossover in (1.0, frontier._MIN_BUDGET):  # always-sparse, ~dense
            out = segment.propagate_or(g, sig, "frontier",
                                       frontier_crossover=crossover)
            np.testing.assert_array_equal(np.asarray(out), ref)
        key = jax.random.key(0)
        _, o_ref = engine.run_until_coverage(
            g, Flood(source=0), key, coverage_target=0.99)
        _, o_cfg = engine.run_until_coverage(
            g, Flood(source=0, method="frontier", frontier_crossover=0.25),
            key, coverage_target=0.99)
        assert o_ref["rounds"] == o_cfg["rounds"]
        assert o_ref["messages"] == o_cfg["messages"]

    def test_both_branches_exercised(self):
        # The auto budget must sit strictly inside (0, n) for this config
        # so the sweep above really ran BOTH cond branches.
        g = G.watts_strogatz(1000, 4, 0.2, seed=1, source_csr=True)
        k = frontier.budget(g)
        assert frontier._MIN_BUDGET <= k < g.n_nodes  # full frontier -> dense


class TestBitset:
    def test_pack_unpack_roundtrip_and_popcount(self):
        rng = np.random.default_rng(0)
        for n in (32, 128, 1000):  # 1000: ragged tail
            bits = rng.random(n) < 0.3
            words = bitset.pack_bits(jnp.asarray(bits))
            assert words.dtype == jnp.uint32
            assert words.shape == (bitset.n_words(n),)
            np.testing.assert_array_equal(
                np.asarray(bitset.unpack_bits(words, n)), bits)
            assert int(bitset.popcount(words)) == int(bits.sum())

    def test_test_bits_and_set_bits(self):
        rng = np.random.default_rng(1)
        bits = rng.random(512) < 0.5
        words = bitset.pack_bits(jnp.asarray(bits))
        idx = jnp.asarray(rng.integers(0, 512, 64, dtype=np.int32))
        np.testing.assert_array_equal(
            np.asarray(bitset.test_bits(words, idx)),
            bits[np.asarray(idx)])
        valid = jnp.asarray(rng.random(64) < 0.5)
        out = bitset.set_bits(words, idx, valid)
        ref = bits.copy()
        ref[np.asarray(idx)[np.asarray(valid)]] = True
        np.testing.assert_array_equal(
            np.asarray(bitset.unpack_bits(out, 512)), ref)


class TestBitsetProtocolParity:
    def test_flood_bitset_bitexact(self):
        g = G.watts_strogatz(1000, 6, 0.1, seed=9, source_csr=True)
        key = jax.random.key(0)
        for method in ("auto", "frontier"):
            sd, od = engine.run_until_coverage(
                g, Flood(source=0, method=method), key, coverage_target=0.99)
            sb, ob = engine.run_until_coverage(
                g, Flood(source=0, method=method, bitset=True), key,
                coverage_target=0.99)
            assert isinstance(sb, FloodBitState)
            assert od == ob
            np.testing.assert_array_equal(
                np.asarray(sd.seen),
                np.asarray(bitset.unpack_bits(sb.seen, g.n_nodes_padded)))

    def test_flood_bitset_per_round_stats_match(self):
        g = G.erdos_renyi(512, 0.02, seed=7, source_csr=True)
        key = jax.random.key(1)
        _, st_d = engine.run(g, Flood(source=0), key, 8)
        _, st_b = engine.run(g, Flood(source=0, bitset=True), key, 8)
        for k in ("messages", "coverage", "frontier", "frontier_occupancy"):
            np.testing.assert_array_equal(np.asarray(st_d[k]),
                                          np.asarray(st_b[k]))

    def test_adaptive_flood_bitset_bitexact(self):
        g = G.watts_strogatz(2048, 6, 0.1, seed=8, source_csr=True)
        key = jax.random.key(0)
        sd, od = engine.run_until_coverage(
            g, AdaptiveFlood(source=0, k=64), key, coverage_target=0.99)
        sb, ob = engine.run_until_coverage(
            g, AdaptiveFlood(source=0, k=64, bitset=True), key,
            coverage_target=0.99)
        assert od == ob
        np.testing.assert_array_equal(
            np.asarray(sd.seen),
            np.asarray(bitset.unpack_bits(sb.seen, g.n_nodes_padded)))

    def test_plumtree_bitset_bitexact_and_tree_extracts(self):
        g = G.watts_strogatz(256, 4, 0.1, seed=3)
        key = jax.random.key(0)
        s1, st1 = engine.run(g, Plumtree(source=0), key, 2)
        s2, st2 = engine.run(g, Plumtree(source=0, bitset=True), key, 2)
        for k in st1:
            np.testing.assert_array_equal(np.asarray(st1[k]),
                                          np.asarray(st2[k]))
        np.testing.assert_array_equal(
            np.asarray(s1.eager),
            np.asarray(bitset.unpack_bits(s2.eager, g.n_edges_padded)))
        t1 = Plumtree(source=0).tree_graph(g, s1)
        t2 = Plumtree(source=0, bitset=True).tree_graph(g, s2)
        np.testing.assert_array_equal(np.asarray(t1.senders),
                                      np.asarray(t2.senders))
        np.testing.assert_array_equal(np.asarray(t1.receivers),
                                      np.asarray(t2.receivers))

    def test_plumtree_bitset_heals_after_failures(self):
        g = G.watts_strogatz(128, 4, 0.1, seed=4)
        key = jax.random.key(0)
        proto = Plumtree(source=0, bitset=True)
        state, _ = engine.run(g, proto, key, 2)  # tree formed
        gf = failures.fail_nodes(g, [7, 19])
        state, stats = engine.run_from(gf, proto, state, key, 1)
        assert float(np.asarray(stats["coverage"])[-1]) > 0.9


class TestDonation:
    def test_run_from_does_not_retain_prestep_state(self):
        g = G.ring(256)
        proto = Flood(source=0)
        key = jax.random.key(0)
        st, _ = engine.run(g, proto, key, 2)
        pre_seen, pre_frontier = st.seen, st.frontier
        st2, _ = engine.run_from(g, proto, st, key, 2)
        # The pre-step carry was donated into the loop, not retained as a
        # second HBM copy beside it.
        assert pre_seen.is_deleted() and pre_frontier.is_deleted()
        with pytest.raises(RuntimeError, match="deleted"):
            np.asarray(st.seen)

    def test_run_from_donate_false_keeps_state(self):
        g = G.ring(256)
        proto = Flood(source=0)
        key = jax.random.key(0)
        st, _ = engine.run(g, proto, key, 2)
        a, _ = engine.run_from(g, proto, st, key, 3, donate=False)
        b, _ = engine.run_from(g, proto, st, key, 3, donate=False)
        np.testing.assert_array_equal(np.asarray(a.seen), np.asarray(b.seen))

    def test_aliased_state_skips_donation_transparently(self):
        # Fresh inits alias one buffer at several leaves (Flood's seed IS
        # seen AND frontier): donation must auto-skip, not trip XLA's
        # double-donate check, and the aliased input must stay readable.
        g = G.ring(256)
        proto = Flood(source=0)
        st0 = proto.init(g, jax.random.key(0))
        assert st0.seen is st0.frontier
        st1, _ = engine.run_from(g, proto, st0, jax.random.key(0), 2)
        assert not st0.seen.is_deleted()
        ref, _ = engine.run(g, proto, jax.random.key(0), 2)
        np.testing.assert_array_equal(np.asarray(st1.seen),
                                      np.asarray(ref.seen))

    def test_coverage_from_donates(self):
        g = G.watts_strogatz(512, 4, 0.2, seed=2, source_csr=True)
        proto = Flood(source=0)
        key = jax.random.key(0)
        st, _ = engine.run(g, proto, key, 2)
        pre = st.seen
        _, out = engine.run_until_coverage_from(
            g, proto, st, key, coverage_target=0.99, max_rounds=64)
        assert pre.is_deleted()
        assert float(out["coverage"]) >= 0.99


class TestOccupancyStat:
    def test_scan_stats_carry_per_round_occupancy(self):
        g = G.ring(128, source_csr=True)
        _, stats = engine.run(g, Flood(source=0), jax.random.key(0), 4)
        occ = np.asarray(stats["frontier_occupancy"])
        assert occ.shape == (4,)
        # ring flood: every round 2 new nodes (one per direction)
        np.testing.assert_allclose(occ, 2 / 128, rtol=1e-6)

    def test_coverage_loop_reports_mean_and_histogram(self):
        from p2pnetwork_tpu import telemetry

        reg = telemetry.Registry()
        prev = telemetry.set_default_registry(reg)
        try:
            g = G.watts_strogatz(1000, 6, 0.1, seed=9, source_csr=True)
            _, out = engine.run_until_coverage(
                g, Flood(source=0), jax.random.key(0), coverage_target=0.99)
            assert 0.0 < out["frontier_occupancy_mean"] < 1.0
            # cross-check against the per-round series at the same rounds
            _, stats = engine.run(g, Flood(source=0), jax.random.key(0),
                                  int(out["rounds"]))
            mean = float(np.asarray(stats["frontier_occupancy"]).mean())
            assert out["frontier_occupancy_mean"] == pytest.approx(
                mean, rel=1e-5)
            hist = reg.get("sim_frontier_occupancy")
            assert hist is not None
            (child,) = hist.children()
            assert child.labels == ("coverage", "Flood")
            assert child.count == 1
        finally:
            telemetry.set_default_registry(prev)

    def test_histogram_cardinality_pruned(self):
        from p2pnetwork_tpu import telemetry
        from p2pnetwork_tpu.sim.engine import (_OCCUPANCY_MAX_CHILDREN,
                                               _observe_occupancy)

        reg = telemetry.Registry()
        prev = telemetry.set_default_registry(reg)
        try:
            _observe_occupancy("coverage", "HotProto", 0.2)
            for i in range(3 * _OCCUPANCY_MAX_CHILDREN):
                # keep the long-lived protocol HOT through the sweep
                _observe_occupancy("coverage", "HotProto", 0.2)
                _observe_occupancy("coverage", f"Sweep{i}", 0.1)
            hist = reg.get("sim_frontier_occupancy")
            assert len(hist.children()) <= _OCCUPANCY_MAX_CHILDREN
            names = {c.labels[1] for c in hist.children()}
            # LRU, not FIFO: the oldest-REGISTERED but still-hot child
            # survives with its history; cold sweep labels are evicted.
            assert "HotProto" in names
            assert "Sweep0" not in names
            (hot,) = [c for c in hist.children()
                      if c.labels[1] == "HotProto"]
            assert hot.count > 1  # history kept, not reset by pruning
        finally:
            telemetry.set_default_registry(prev)

    def test_protocols_without_the_stat_stay_out(self):
        from p2pnetwork_tpu import telemetry
        from p2pnetwork_tpu.models.sir import SIR

        reg = telemetry.Registry()
        prev = telemetry.set_default_registry(reg)
        try:
            g = G.watts_strogatz(256, 4, 0.1, seed=5)
            _, out = engine.run_until_coverage(
                g, SIR(beta=0.9, gamma=0.05), jax.random.key(0),
                coverage_target=0.5, max_rounds=64)
            assert "frontier_occupancy_mean" not in out
            assert reg.get("sim_frontier_occupancy") is None
        finally:
            telemetry.set_default_registry(prev)


@pytest.mark.slow
def test_frontier_halves_edge_gather_work_on_flood_tails():
    """Acceptance bench: on a 10k-node WS flood, the frontier path's
    edge-gather work — measured off the frontier-occupancy stat as
    (sent-frontier nodes) * max_out_span slots — is >= 2x below the dense
    path's E_pad slots on the first 3 AND last 3 rounds."""
    # Low rewiring: the wave must have a real straggler tail (p=0.1's
    # ~log-N wave peaks right up to its second-to-last round).
    g = G.watts_strogatz(10_000, 10, 0.01, seed=0, source_csr=True)
    key = jax.random.key(0)
    # Run the flood to EXHAUSTION (empty frontier), not to the 99% target
    # — the sparse tail the fast path exists for lives past that cut.
    sd, stats = engine.run(g, Flood(source=0, method="frontier"), key, 32)
    sref, ref_stats = engine.run(g, Flood(source=0), key, 32)
    np.testing.assert_array_equal(np.asarray(sd.seen), np.asarray(sref.seen))
    for k in ("messages", "coverage", "frontier", "frontier_occupancy"):
        np.testing.assert_array_equal(np.asarray(stats[k]),
                                      np.asarray(ref_stats[k]))
    occ = np.asarray(stats["frontier_occupancy"])
    n_live = g.n_nodes
    # Round r sends the frontier that round r-1 produced; round 1 sends
    # the seed (1 node). Only rounds that sent anything count.
    sent = np.concatenate([[1.0 / n_live], occ[:-1]]) * n_live
    active_rounds = np.flatnonzero(sent > 0)
    assert active_rounds.size >= 6
    sparse_slots = sent * g.max_out_span
    dense_slots = g.n_edges_padded
    for r in list(active_rounds[:3]) + list(active_rounds[-3:]):
        assert 2 * sparse_slots[r] <= dense_slots, (
            f"round {r + 1}: {sparse_slots[r]} gathered slots vs dense "
            f"{dense_slots} — frontier fast path must be >= 2x cheaper")
