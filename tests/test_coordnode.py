"""Live Vivaldi coordinates over real sockets.

Real loopback RTTs are all-equal microseconds (no geometry to learn),
so the split is: the spring rule is verified on a PLANTED metric by
feeding fabricated samples through _absorb (deterministic, scalar form
of the sim model's tested update), and the network layer is verified
live — pings measure, pongs carry remote state, samples are absorbed,
error estimates drop, and mutual predictions agree with measurement."""

import numpy as np

from p2pnetwork_tpu.coordnode import CoordinateNode
from tests.helpers import stop_all, wait_until

HOST = "127.0.0.1"


class TestSpringRule:
    def _fresh(self, id="x", dim=1):
        # Unstarted node: _absorb is pure arithmetic on the instance.
        return CoordinateNode(HOST, 0, id=id, dim=dim, rtt_floor=1e-9)

    def test_line_metric_recovered(self):
        # Three virtual peers on a line: A(0) - B(10) - C(20) ms. Feed A
        # alternating samples against B's and C's (converged) positions.
        a = self._fresh("a")
        b_coord, c_coord = [0.010], [0.020]
        for _ in range(400):
            a._absorb(0.010, b_coord, 1e-6, 0.05)
            a._absorb(0.020, c_coord, 1e-6, 0.05)
        # A should sit near 0 (10ms from B at 10ms, 20ms from C at 20ms
        # on the same side).
        assert abs(a.coord[0]) < 0.002, a.coord
        assert a.ce < 0.2
        assert a.samples == 800

    def test_update_direction(self):
        a = self._fresh()
        a.coord = [0.0]
        before = a.coord[0]
        # Peer at +10ms predicts 10ms; measured 30ms -> too close -> A
        # must move AWAY (negative direction).
        a._absorb(0.030, [0.010], 1e-6, 0.5)
        assert a.coord[0] < before
        a2 = self._fresh()
        a2.coord = [0.0]
        # Measured 2ms -> too far -> move toward the peer.
        a2._absorb(0.002, [0.010], 1e-6, 0.5)
        assert a2.coord[0] > 0.0

    def test_dim_mismatch_sample_dropped(self):
        # Regression: a shorter remote coord used to TRUNCATE our vector
        # via zip; it must drop the sample and leave state untouched.
        a = self._fresh(dim=2)
        before = (list(a.coord), a.height, a.ce, a.samples)
        a._absorb(0.010, [0.010], 1e-6, 0.5)  # 1-D peer, we are 2-D
        assert (list(a.coord), a.height, a.ce, a.samples) == before
        assert len(a.coord) == 2

    def test_height_floor_holds(self):
        a = self._fresh()
        for _ in range(50):
            a._absorb(0.001, [0.050], 1e-6, 0.5)  # wildly over-predicted
        assert a.height >= a.height_min


class TestLiveCoordinates:
    def test_ping_pong_and_convergence(self):
        a = CoordinateNode(HOST, 0, id="A")
        b = CoordinateNode(HOST, 0, id="B")
        nodes = [a, b]
        try:
            for n in nodes:
                n.start()
            assert a.connect_with_node(HOST, b.port)
            assert wait_until(lambda: len(a.all_nodes) == 1
                              and len(b.all_nodes) == 1)
            for _ in range(60):
                a.tick()
                b.tick()
            assert wait_until(lambda: a.samples >= 40 and b.samples >= 40,
                              timeout=10.0), (a.samples, b.samples)
            # The samples demonstrably moved state: coordinates left the
            # 1e-6 init blob. (ce is NOT asserted below 1.0: under load,
            # loopback RTTs vary by orders of magnitude between samples,
            # relative errors sit >= 1, and a ceiling-pinned ce is the
            # honest reading — its EWMA dynamics are pinned
            # deterministically in TestSpringRule.)
            assert any(abs(x) > 1e-5 for x in a.coord + b.coord)
            # Mutual prediction is in the measured loopback ballpark.
            # Real RTT is tens of microseconds, but 60 back-to-back
            # ticks queue on the event loop and some samples absorb
            # milliseconds of queueing delay — the bound is a sanity
            # check, not a precision claim.
            bc, bh, _ = b.coordinate()
            assert 0.0 <= a.predicted_rtt(bc, bh) < 0.050
        finally:
            stop_all(nodes)

    def test_pings_invisible_to_app(self):
        seen = []

        class App(CoordinateNode):
            def node_message(self, node, data):
                if isinstance(data, dict) and (
                        "_viv_ping" in data or "_viv_pong" in data):
                    return super().node_message(node, data)
                seen.append(data)
                return super().node_message(node, data)

        a = App(HOST, 0, id="A")
        b = App(HOST, 0, id="B")
        nodes = [a, b]
        try:
            for n in nodes:
                n.start()
            assert a.connect_with_node(HOST, b.port)
            assert wait_until(lambda: len(b.all_nodes) == 1)
            a.tick()
            a.send_to_nodes("real traffic")
            assert wait_until(lambda: "real traffic" in seen)
            assert wait_until(lambda: a.samples >= 1)
            assert seen == ["real traffic"]
        finally:
            stop_all(nodes)

    def test_tick_without_peers_is_noop(self):
        a = CoordinateNode(HOST, 0, id="A")
        try:
            a.start()
            a.tick()
            assert not wait_until(lambda: a.samples > 0, timeout=0.3)
        finally:
            stop_all([a])
