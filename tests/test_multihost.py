"""Multi-host mesh helpers on the 8-device virtual CPU platform (a single
"host" of 8 chips — the degenerate but fully exercised case)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from p2pnetwork_tpu.models import Flood  # noqa: E402
from p2pnetwork_tpu.parallel import multihost, sharded  # noqa: E402
from p2pnetwork_tpu.sim import engine  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def test_initialize_noop_single_process():
    assert multihost.initialize_distributed() is False


def test_hierarchical_ring_mesh_covers_all_devices():
    mesh = multihost.hierarchical_ring_mesh()
    assert mesh.devices.size == 8
    # host-major order: sorted by (process_index, id)
    ids = [(d.process_index, d.id) for d in mesh.devices.flat]
    assert ids == sorted(ids)


def test_ring_flood_on_hierarchical_mesh_matches_engine():
    g = G.watts_strogatz(512, 6, 0.2, seed=0)
    mesh = multihost.hierarchical_ring_mesh()
    sg = sharded.shard_graph(g, mesh)
    seen, _ = sharded.flood(sg, mesh, source=0, rounds=6)
    ref, _ = engine.run(g, Flood(source=0), jax.random.key(0), 6)
    assert (
        np.asarray(seen).reshape(-1)[: g.n_nodes]
        == np.asarray(ref.seen)[: g.n_nodes]
    ).all()


def test_mesh_2d_shape():
    mesh = multihost.mesh_2d()
    assert mesh.axis_names == ("dcn", "ici")
    assert mesh.devices.shape == (1, 8)  # one virtual host of 8 chips


def test_mesh_2d_auto_run():
    # Auto-sharded protocol over the ici axis of the 2-D mesh.
    from p2pnetwork_tpu.parallel import auto

    g = G.watts_strogatz(512, 4, 0.1, seed=1)
    mesh = multihost.mesh_2d()
    gs = auto.shard_graph_auto(g, mesh, axis_name="ici")
    state, _ = auto.run_auto(gs, Flood(source=0, method="segment"),
                             jax.random.key(0), 5)
    ref, _ = engine.run(g, Flood(source=0, method="segment"),
                        jax.random.key(0), 5)
    assert (np.asarray(state.seen) == np.asarray(ref.seen)).all()


def test_two_process_distributed_protocol_suite():
    """The REAL multi-process path: two OS processes rendezvous through
    jax.distributed (loopback coordinator, gloo CPU collectives), build
    the hierarchical ring mesh spanning both processes' devices, and run
    the phase suite across it — flood, exact-RNG gossip, a churn step
    (failures + runtime link) under run-to-coverage, and an orbax
    checkpoint saved AND restored collectively by both processes — each
    cross-checked against the engine oracle (tests/multihost_worker.py)."""
    import os
    import pathlib
    import re
    import socket
    import subprocess
    import sys

    worker = pathlib.Path(__file__).resolve().parent / "multihost_worker.py"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # Preserve any other pre-set XLA flags; only the virtual device count
    # differs from the suite's (2 per process here, 8 in-process).
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    env["XLA_FLAGS"] = " ".join(
        flags + ["--xla_force_host_platform_device_count=2"])

    def run_pair():
        with socket.socket() as s:  # pick a free loopback port
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs = [
            subprocess.Popen(
                [sys.executable, str(worker), str(pid), str(port)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True,
            )
            for pid in (0, 1)
        ]
        try:
            return [p.communicate(timeout=180)[0] for p in procs], procs
        finally:  # a hung rendezvous must not leak live workers
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.communicate()

    outs, procs = run_pair()
    if any(p.returncode != 0 for p in procs):
        # Some jax builds ship CPU collectives that cannot actually span
        # processes (no gloo backend wired up): the workers rendezvous,
        # then every cross-process device_put/psum dies with this
        # signature. That is a missing platform capability on the image,
        # not a regression in this repo's multihost path — skip with the
        # reason instead of failing tier-1 forever.
        unprovisionable = (
            "Multiprocess computations aren't implemented",
            "distributed module is not available",
        )
        for out in outs:
            for sig in unprovisionable:
                if sig in out:
                    pytest.skip(
                        "second jax process cannot be provisioned on "
                        f"this image ({sig!r} from the worker) — the "
                        "2-process suite needs CPU collectives with "
                        "real multiprocess support")
        # The bind-then-close port pick has an inherent race window while
        # the workers' interpreters start; one retry with a fresh port.
        outs, procs = run_pair()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert f"MULTIHOST_OK pid={pid}" in out, out[-3000:]
        for phase in ("flood", "gossip", "churn", "checkpoint"):
            assert f"MULTIHOST_PHASE {phase} OK" in out, \
                f"worker {pid} missing phase {phase}:\n{out[-3000:]}"
    # Both controllers computed the same replicated summary.
    summaries = [
        re.search(r"MULTIHOST_OK pid=\d (.*)$", out, re.M).group(1)
        for out in outs
    ]
    assert summaries[0] == summaries[1]
