"""Multi-host mesh helpers on the 8-device virtual CPU platform (a single
"host" of 8 chips — the degenerate but fully exercised case)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from p2pnetwork_tpu.models import Flood  # noqa: E402
from p2pnetwork_tpu.parallel import multihost, sharded  # noqa: E402
from p2pnetwork_tpu.sim import engine  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def test_initialize_noop_single_process():
    assert multihost.initialize_distributed() is False


def test_hierarchical_ring_mesh_covers_all_devices():
    mesh = multihost.hierarchical_ring_mesh()
    assert mesh.devices.size == 8
    # host-major order: sorted by (process_index, id)
    ids = [(d.process_index, d.id) for d in mesh.devices.flat]
    assert ids == sorted(ids)


def test_ring_flood_on_hierarchical_mesh_matches_engine():
    g = G.watts_strogatz(512, 6, 0.2, seed=0)
    mesh = multihost.hierarchical_ring_mesh()
    sg = sharded.shard_graph(g, mesh)
    seen, _ = sharded.flood(sg, mesh, source=0, rounds=6)
    ref, _ = engine.run(g, Flood(source=0), jax.random.key(0), 6)
    assert (
        np.asarray(seen).reshape(-1)[: g.n_nodes]
        == np.asarray(ref.seen)[: g.n_nodes]
    ).all()


def test_mesh_2d_shape():
    mesh = multihost.mesh_2d()
    assert mesh.axis_names == ("dcn", "ici")
    assert mesh.devices.shape == (1, 8)  # one virtual host of 8 chips


def test_mesh_2d_auto_run():
    # Auto-sharded protocol over the ici axis of the 2-D mesh.
    from p2pnetwork_tpu.parallel import auto

    g = G.watts_strogatz(512, 4, 0.1, seed=1)
    mesh = multihost.mesh_2d()
    gs = auto.shard_graph_auto(g, mesh, axis_name="ici")
    state, _ = auto.run_auto(gs, Flood(source=0, method="segment"),
                             jax.random.key(0), 5)
    ref, _ = engine.run(g, Flood(source=0, method="segment"),
                        jax.random.key(0), 5)
    assert (np.asarray(state.seen) == np.asarray(ref.seen)).all()
