"""graftmem (analysis/ir/memory.py + capacity.py + the serve gate) tests.

Three layers, mirroring test_iraudit.py's contract for the cost ratchet:

- **model fixtures** — the analytic liveness walk priced against
  ``Compiled.memory_analysis()`` on deliberately simple programs
  (pruned arguments, donation credit, folded constants), plus the
  degrade path: a backend without ``memory_analysis()`` lands on the
  skip-list loudly and can never bless;
- **machinery** — membudgets round-trip, ratchet arithmetic (peak
  growth fails P1, shrink asks for a re-bless, model drift is P2,
  stale rows name their shape-class), capacity-plan evaluation and its
  failure modes;
- **the live tree** — the checked-in membudgets.json must cover every
  registry entry with analytic-vs-compiled parity inside the model
  tolerance, the checked-in capacity model must price the north-star
  serving shape, and SimService's ``hbm_budget_bytes`` knob must shed
  over-plan admissions as a typed 429, never queue them.
"""

import copy
import json
import urllib.error
import urllib.request

import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from p2pnetwork_tpu import telemetry  # noqa: E402
from p2pnetwork_tpu.analysis.ir import capacity as C  # noqa: E402
from p2pnetwork_tpu.analysis.ir import memory as M  # noqa: E402
from p2pnetwork_tpu.analysis.ir import registry  # noqa: E402
from p2pnetwork_tpu.analysis.ir.registry import Lowering  # noqa: E402
from p2pnetwork_tpu.serve import MemoryBudgetExceeded, SimService  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402
from p2pnetwork_tpu.telemetry.httpd import MetricsServer  # noqa: E402

pytestmark = pytest.mark.mem


def _entry(name, build, **kw):
    op, rest = name.split("/", 1)
    variant, cls = rest.split("@", 1)
    kw.setdefault("parity", False)
    return Lowering(name=name, op=op, variant=variant, shape_class=cls,
                    build=build, **kw)


def _collect_one(entry):
    return M.collect_memory([registry.trace_lowering(entry)])[entry.name]


def _rec(argument=600, output=300, temp=150, alias=50, ratio=1.0):
    """A synthetic memory record for ratchet-arithmetic tests — no
    compile needed to exercise check_membudgets."""
    comp = {"argument": argument, "output": output, "temp": temp,
            "alias": alias, "peak": argument + output + temp - alias}
    ana = {"argument": argument, "output": output, "const": 0,
           "temp": temp, "alias": alias,
           "interface": argument + output - alias}
    return {"compiled": comp, "analytic": ana, "model_ratio": ratio}


# ------------------------------------------------------- analytic walk


class TestAnalyticWalk:
    def test_interface_matches_compiled_on_simple_program(self):
        x = jnp.zeros(1024, jnp.float32)
        e = _entry("or/simple@ws1k", lambda: (lambda a: a * 2.0 + 1.0, (x,)))
        rec = _collect_one(e)
        assert rec["analytic"]["argument"] == 4096
        assert rec["analytic"]["argument"] == rec["compiled"]["argument"]
        assert rec["analytic"]["output"] == rec["compiled"]["output"]
        assert rec["model_ratio"] == 1.0
        assert rec["compiled"]["peak"] > 0

    def test_unused_arguments_are_pruned(self):
        # jit drops parameters nothing reads before XLA prices them —
        # the analytic walk must agree, or every partial-application
        # lowering would drift.
        x = jnp.zeros(1024, jnp.float32)
        e = _entry("or/pruned@ws1k",
                   lambda: ((lambda a, unused: a * 2.0), (x, x)))
        rec = _collect_one(e)
        assert rec["analytic"]["argument"] == 4096
        assert rec["analytic"]["argument"] == rec["compiled"]["argument"]

    def test_folded_constants_are_priced_separately(self):
        # A closure-captured table becomes a jaxpr const: XLA folds it
        # into the executable (absent from every memory_analysis
        # bucket), so it must land in `const`, not `argument`.
        table = jnp.arange(256, dtype=jnp.int32)
        closed = jax.make_jaxpr(lambda a: a + table)(
            jnp.zeros(256, jnp.int32))
        ana = M.analytic_memory(closed)
        assert ana["const"] == 1024
        assert ana["argument"] == 1024

    def test_alias_credit_and_shards_arithmetic(self):
        closed = jax.make_jaxpr(lambda a: a + 1.0)(
            jnp.zeros(1024, jnp.float32))
        ana = M.analytic_memory(closed, alias_bytes=4096)
        assert ana["alias"] == 4096
        assert ana["interface"] == ana["argument"] + ana["output"] - 4096
        # alias credit can never exceed the argument bytes it aliases
        capped = M.analytic_memory(closed, alias_bytes=10**9)
        assert capped["alias"] == capped["argument"]
        # memory_analysis reports per-device bytes: shards divide
        sharded = M.analytic_memory(closed, shards=4)
        assert sharded["argument"] == ana["argument"] // 4


# ------------------------------------------------------- degrade path


class TestDegrade:
    def _simple(self):
        x = jnp.zeros(128, jnp.float32)
        return _entry("or/degrade@ws1k", lambda: ((lambda a: a * 2.0), (x,)))

    def test_memory_analysis_unavailable_is_a_loud_skip(self, monkeypatch):
        monkeypatch.setattr(jax.stages.Compiled, "memory_analysis",
                            lambda self: None)
        e = self._simple()
        recs = M.collect_memory([registry.trace_lowering(e)])
        assert recs[e.name] == {"skipped": M.MEM_UNAVAILABLE}
        assert M.mem_skipped(recs) == [e.name]
        # skipped records gate nothing and do not read as stale
        doc = {"entries": {e.name: _rec()}}
        assert M.check_membudgets(recs, doc) == []

    def test_write_membudgets_drops_skipped_entries(self, tmp_path):
        # The reason the CLI refuses a degraded bless: the written file
        # would silently lose the skipped rows and fail the next full
        # run as "no blessed memory budget".
        path = str(tmp_path / "m.json")
        M.write_membudgets({"or/a@ws1k": _rec(),
                            "or/b@ws1k": {"skipped": M.MEM_UNAVAILABLE}},
                           path)
        assert set(M.load_membudgets(path)["entries"]) == {"or/a@ws1k"}

    def test_compile_failure_is_a_gated_error_record(self):
        # Traces fine, then the memory pass's rebuild blows up — the
        # failure must become a P1 finding, never a silent ungate.
        calls = {"n": 0}
        x = jnp.zeros(128, jnp.float32)

        def build():
            calls["n"] += 1
            if calls["n"] > 1:
                raise RuntimeError("backend cannot lower this entry")
            return (lambda a: a * 2.0), (x,)

        e = _entry("or/nocompile@ws1k", build)
        recs = M.collect_memory([registry.trace_lowering(e)])
        assert "error" in recs[e.name]
        found = M.check_membudgets(recs, {"entries": {}})
        assert any("failed to AOT-compile" in f.message
                   and f.severity == "P1" for f in found)

    def test_cli_degrades_loudly_and_refuses_bless(self, monkeypatch,
                                                   tmp_path, capsys):
        # Full cycle on a one-entry registry: bless clean, break
        # memory_analysis(), and the gate must still pass (loud skip
        # list on stderr) while --write-membudgets refuses.
        from p2pnetwork_tpu.analysis.ir import __main__ as cli
        from p2pnetwork_tpu.analysis.ir import budgets as B

        e = self._simple()
        monkeypatch.setattr(registry, "all_lowerings", lambda: [e])
        monkeypatch.setattr(
            C, "fit_capacity_model",
            lambda recs=None: {"schema": C.CAPACITY_SCHEMA, "entries": {}})
        bpath = str(tmp_path / "b.json")
        mpath = str(tmp_path / "m.json")
        assert cli.main(["--write-budgets", "--budgets", bpath]) == 0
        assert cli.main(["--write-membudgets", "--membudgets", mpath,
                         "--budgets", bpath]) == 0
        assert e.name in M.load_membudgets(mpath)["entries"]
        capsys.readouterr()
        monkeypatch.setattr(jax.stages.Compiled, "memory_analysis",
                            lambda self: None)
        assert cli.main(["--budgets", bpath, "--membudgets", mpath]) == 0
        err = capsys.readouterr().err
        assert "memory plane degraded" in err and e.name in err
        assert cli.main(["--write-membudgets", "--membudgets",
                         str(tmp_path / "m2.json"),
                         "--budgets", bpath]) == 2
        assert "refusing --write-membudgets on a degraded run" in \
            capsys.readouterr().err
        assert not (tmp_path / "m2.json").exists()
        del B  # imported for parity with the CLI's budget path


# ----------------------------------------------------------- the ratchet


class TestMemRatchet:
    def test_round_trip(self, tmp_path):
        recs = {"or/a@ws1k": _rec()}
        path = M.write_membudgets(recs, str(tmp_path / "m.json"))
        doc = M.load_membudgets(path)
        assert doc["schema"] == M.SCHEMA
        assert doc["tolerance"] == M.DEFAULT_TOLERANCE
        assert M.check_membudgets(recs, doc) == []

    def test_peak_growth_fails_and_shrink_asks_for_a_bless(self):
        recs = {"or/a@ws1k": _rec()}
        doc = {"entries": {"or/a@ws1k": _rec()}}
        grown = copy.deepcopy(doc)
        grown["entries"]["or/a@ws1k"]["compiled"]["peak"] = 100
        found = M.check_membudgets(recs, grown)
        assert found and found[0].rule == "ir-mem-regression"
        assert found[0].severity == "P1" and "grew" in found[0].message
        shrunk = copy.deepcopy(doc)
        shrunk["entries"]["or/a@ws1k"]["compiled"]["peak"] = 10**6
        found = M.check_membudgets(recs, shrunk)
        assert found and found[0].severity == "P2"
        assert "shrank" in found[0].message

    def test_stored_tolerance_is_honored(self):
        recs = {"or/a@ws1k": _rec()}
        doc = {"tolerance": 0.5, "entries": {"or/a@ws1k": _rec()}}
        doc["entries"]["or/a@ws1k"]["compiled"]["peak"] = \
            int(recs["or/a@ws1k"]["compiled"]["peak"] / 1.4)
        assert M.check_membudgets(recs, doc) == []
        assert M.check_membudgets(recs, doc, tolerance=0.2) != []

    def test_unbudgeted_lowering_is_P1(self):
        found = M.check_membudgets({"or/new@ws1k": _rec()}, {"entries": {}})
        assert found and found[0].rule == "ir-mem-unbudgeted"
        assert found[0].severity == "P1"

    def test_model_drift_is_P2(self):
        recs = {"or/a@ws1k": _rec(ratio=1.5)}
        doc = {"entries": {"or/a@ws1k": _rec(ratio=1.5)}}
        found = [f for f in M.check_membudgets(recs, doc)
                 if f.rule == "ir-mem-model-drift"]
        assert found and found[0].severity == "P2"
        assert "1.50x" in found[0].message

    def test_stale_entry_names_the_shape_class(self):
        doc = {"entries": {"or/ghost@ws1k": _rec()}}
        found = M.check_membudgets({}, doc)
        assert found and "no longer produces" in found[0].message
        assert "shape-class ws1k" in found[0].message
        # the device/mem skip-lists exempt their rows from staleness
        assert M.check_membudgets({}, doc, skipped=["or/ghost@ws1k"]) == []

    def test_blessed_error_record_is_a_finding_not_an_ungate(self):
        recs = {"or/a@ws1k": _rec()}
        doc = {"entries": {"or/a@ws1k": {"error": "RuntimeError: OOM"}}}
        found = M.check_membudgets(recs, doc)
        assert found and "compile-error record" in found[0].message


# ------------------------------------------------------- the live tree


class TestCheckedInMembudgets:
    @pytest.fixture(scope="class")
    def doc(self):
        doc = M.load_membudgets()
        assert doc, "analysis/ir/membudgets.json is missing"
        return doc

    def test_covers_every_registry_entry(self, doc):
        names = {e.name for e in registry.all_lowerings()}
        assert set(doc["entries"]) == names

    def test_parity_within_model_tolerance_on_every_entry(self, doc):
        # THE planner-trust gate: on every entry the analytic walk must
        # agree with memory_analysis() to within the model tolerance,
        # or capacity.plan's extrapolations are fiction.
        tol = doc["model_tolerance"]
        off = {n: rec.get("model_ratio")
               for n, rec in doc["entries"].items()
               if rec.get("model_ratio") is None
               or abs(rec["model_ratio"] - 1.0) > tol}
        assert off == {}

    def test_live_recompute_matches_the_blessed_records(self, doc):
        # Reprice a sample at HEAD against the checked-in file — the
        # same comparison `graftaudit` makes in CI, kept cheap by
        # sampling (the full sweep is the CLI gate's job).
        sample = ["or/segment@ws1k", "or/gather@ws1k", "sum/segment@ws1k"]
        entries = [e for e in registry.all_lowerings() if e.name in sample]
        assert len(entries) == len(sample)
        recs = M.collect_memory(
            [registry.trace_lowering(e) for e in entries])
        others = sorted(set(doc["entries"]) - set(recs))
        assert M.check_membudgets(recs, doc, skipped=others) == []

    def test_capacity_model_is_checked_in(self, doc):
        cap = doc.get("capacity_model")
        assert cap and cap["schema"] == C.CAPACITY_SCHEMA
        assert C.DEFAULT_SERVING_ENTRY in cap["entries"]
        assert cap["lane"]["cW"] > 0
        for base, fit in cap["entries"].items():
            assert fit["points"] >= 2, base
            assert "max_resid" in fit, base


# --------------------------------------------------------- the planner


class TestCapacityPlanner:
    @pytest.fixture(scope="class")
    def model(self):
        cap = M.load_membudgets().get("capacity_model")
        assert cap, "membudgets.json lacks capacity_model"
        return cap

    def test_northstar_plan_fits_one_chip(self, model):
        # ROADMAP item 2's scale question, answered without building
        # anything: 1M nodes / 10k lanes (W=313 u32 words).
        p = C.northstar_plan(model=model)
        assert p["n_pad"] == 1_000_064 and p["n_pad"] % 128 == 0
        assert p["lane_words"] == 313
        assert p["e_pad"] >= 5_000_000  # WS k=6: ~6 edge slots per node
        assert p["global_bytes"] > 0
        assert p["recommended_shards"] == 1

    def test_plan_requires_a_model_and_a_fitted_entry(self, model):
        with pytest.raises(ValueError, match="no capacity model"):
            C.plan(1000, model={})
        with pytest.raises(ValueError, match="no fitted capacity entry"):
            C.plan(1000, entry="or/ghost@ws", model=model)

    def test_footprint_consistent_with_plan(self, model):
        p = C.plan(50_000, lanes=64, model=model)
        fp = C.serving_footprint_bytes(p["n_pad"], p["e_pad"],
                                       p["lane_words"], shards=1,
                                       model=model)
        assert fp == p["per_chip"][0]["per_chip_bytes"]
        assert abs(fp - p["global_bytes"]) <= 1

    def test_footprint_degrades_to_none_without_a_model(self):
        assert C.serving_footprint_bytes(128, 256, 1, model={}) is None
        assert C.serving_footprint_bytes(
            128, 256, 1, entry="or/ghost@ws",
            model={"entries": {}}) is None

    def test_per_chip_shrinks_with_shards_and_grows_with_lanes(self, model):
        p = C.plan(200_000, lanes=1024, model=model)
        per_chip = [row["per_chip_bytes"] for row in p["per_chip"]]
        assert per_chip == sorted(per_chip, reverse=True)
        narrow = C.plan(200_000, lanes=0, model=model)
        assert p["global_bytes"] > narrow["global_bytes"]


# ------------------------------------------------------ the serve gate


def _post(url, doc=None, timeout=10):
    data = json.dumps(doc or {}).encode()
    req = urllib.request.Request(
        url, data=data, method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read().decode())


class TestServeMemoryGate:
    @pytest.fixture(scope="class")
    def ws300(self):
        return G.watts_strogatz(300, 6, 0.2, seed=3, source_csr=True)

    def _svc(self, g, **kw):
        kw.setdefault("capacity", 32)
        kw.setdefault("queue_depth", 8)
        kw.setdefault("seed", 0)
        kw.setdefault("registry", telemetry.Registry())
        return SimService(g, **kw)

    def test_construction_refuses_an_over_budget_graph(self, ws300):
        with pytest.raises(ValueError, match="over hbm_budget_bytes"):
            self._svc(ws300, hbm_budget_bytes=1024.0)

    def test_construction_refuses_the_knob_without_a_model(self, ws300,
                                                           monkeypatch):
        monkeypatch.setattr(M, "load_membudgets", lambda *a: {})
        with pytest.raises(ValueError, match="no capacity model"):
            self._svc(ws300, hbm_budget_bytes=float(1 << 30))

    def test_grow_over_budget_sheds_typed_and_queues_nothing(self, ws300):
        # 16 MiB: roomy for the 384-padded construction footprint,
        # far under the ~65 MB the 16.7M-node repad plans.
        svc = self._svc(ws300, hbm_budget_bytes=float(1 << 24))
        before = svc.stats()["rejected"]
        with pytest.raises(MemoryBudgetExceeded) as ei:
            svc.grow(10_000_000)
        d = ei.value.to_dict()
        assert d["reason"] == "memory_budget"
        assert d["planned_bytes"] > d["hbm_budget_bytes"]
        assert d["planned_capacity"] >= 10_000_000
        assert svc.stats()["rejected"] == before + 1
        # the over-plan growth must never reach the mutate phase
        assert not svc._mutations
        # an affordable grow still queues
        svc.grow(10)
        assert len(svc._mutations) == 1

    def test_submit_over_plan_sheds_as_http_429(self, ws300):
        reg = telemetry.Registry()
        svc = self._svc(ws300, registry=reg,
                        hbm_budget_bytes=float(1 << 30))
        t = svc.submit(0)  # under budget: admitted
        assert t.startswith("t")
        # Shrink the budget under the already-planned footprint — the
        # operator tightening the knob on a live service — and every
        # admission must shed with the structured payload.
        svc.hbm_budget_bytes = 1.0
        with pytest.raises(MemoryBudgetExceeded):
            svc.submit(1)
        with MetricsServer(registry=reg, port=0, service=svc) as srv:
            base = f"http://127.0.0.1:{srv.port}"
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(base + "/submit", {"source": 1})
            assert ei.value.code == 429
            doc = json.loads(ei.value.read().decode())
            assert doc["reason"] == "memory_budget"
            assert doc["planned_bytes"] > doc["hbm_budget_bytes"]
            met = urllib.request.urlopen(base + "/metrics").read()
            assert b'serve_rejected_total{reason="memory_budget"}' in met
