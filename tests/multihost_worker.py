"""Worker process for the real multi-process jax.distributed test.

Launched (twice) by tests/test_multihost.py: each process brings up
``jax.distributed`` over a loopback coordinator, builds the hierarchical
ring mesh spanning both processes' CPU devices, runs a sharded flood over
it, and cross-checks rounds/messages/coverage against the single-device
engine oracle computed locally. Prints one MULTIHOST_OK line on success.

Usage: python tests/multihost_worker.py <process_id> <coordinator_port>
(env: JAX_PLATFORMS=cpu, XLA_FLAGS=--xla_force_host_platform_device_count=N)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from p2pnetwork_tpu.utils.jax_env import apply_platform_env  # noqa: E402

apply_platform_env()

import jax  # noqa: E402
import numpy as np  # noqa: E402


def main() -> int:
    pid, port = int(sys.argv[1]), sys.argv[2]
    from p2pnetwork_tpu.parallel import multihost

    is_multi = multihost.initialize_distributed(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2,
        process_id=pid,
    )
    assert is_multi, "initialize_distributed must report multi-process"
    assert jax.process_count() == 2
    n_local = len(jax.local_devices())
    assert len(jax.devices()) == 2 * n_local

    mesh = multihost.hierarchical_ring_mesh()
    # ICI-major ring: every process's devices sit consecutive on the ring.
    procs = [d.process_index for d in mesh.devices.flat]
    assert procs == sorted(procs), f"ring not host-major: {procs}"

    from p2pnetwork_tpu.models import Flood
    from p2pnetwork_tpu.parallel import sharded
    from p2pnetwork_tpu.sim import engine
    from p2pnetwork_tpu.sim import graph as G

    g = G.watts_strogatz(512, 6, 0.2, seed=0)
    sg = sharded.shard_graph(g, mesh)
    seen, out = sharded.flood_until_coverage(
        sg, mesh, source=0, coverage_target=0.99
    )
    _, ref = engine.run_until_coverage(
        g, Flood(source=0), jax.random.key(0), coverage_target=0.99
    )
    assert out["rounds"] == ref["rounds"], (out, ref)
    assert out["messages"] == ref["messages"], (out, ref)
    assert abs(out["coverage"] - ref["coverage"]) < 1e-6

    # 2-D DCN x ICI mesh builds over the same job.
    m2 = multihost.mesh_2d()
    assert m2.devices.shape == (2, n_local)
    assert {d.process_index for d in m2.devices[0]} == {0}

    print(f"MULTIHOST_OK pid={pid} rounds={out['rounds']} "
          f"messages={out['messages']}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
