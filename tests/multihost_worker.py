"""Worker process for the real multi-process jax.distributed test.

Launched (twice) by tests/test_multihost.py: each process brings up
``jax.distributed`` over a loopback coordinator, builds the hierarchical
ring mesh spanning both processes' CPU devices, and runs a PHASE SUITE
across it — sharded flood, gossip (exact-RNG), a churn step (node
failures + a runtime link) under the run-to-coverage loop, and an
orbax checkpoint save/restore whose restored arrays land back sharded
over the 2-process mesh — each cross-checked against the single-device
engine oracle computed locally. Prints one ``MULTIHOST_PHASE <name> OK``
line per phase and a final MULTIHOST_OK summary line on success.

Cross-process comparison note: in a multi-process job, shards of a
mesh-sharded array live on different PROCESSES, so ``np.asarray`` on one
is an error by design — every value check here either reads a replicated
summary scalar or runs the comparison device-side under ``jit`` (all
processes execute the same program) and reads the replicated boolean.

Usage: python tests/multihost_worker.py <process_id> <coordinator_port>
(env: JAX_PLATFORMS=cpu, XLA_FLAGS=--xla_force_host_platform_device_count=N)
"""

import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from p2pnetwork_tpu.utils.jax_env import apply_platform_env  # noqa: E402

apply_platform_env()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def _phase(name: str) -> None:
    print(f"MULTIHOST_PHASE {name} OK", flush=True)


@jax.jit
def _all_equal(sharded_flat, replicated) -> jax.Array:
    """Device-side equality between a mesh-sharded array and a locally
    computed replicated oracle; the output is a replicated scalar every
    process can read."""
    return jnp.all(sharded_flat.reshape(-1) == replicated.reshape(-1))


def main() -> int:
    pid, port = int(sys.argv[1]), sys.argv[2]
    from p2pnetwork_tpu.parallel import multihost

    is_multi = multihost.initialize_distributed(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2,
        process_id=pid,
    )
    assert is_multi, "initialize_distributed must report multi-process"
    assert jax.process_count() == 2
    n_local = len(jax.local_devices())
    assert len(jax.devices()) == 2 * n_local

    mesh = multihost.hierarchical_ring_mesh()
    # ICI-major ring: every process's devices sit consecutive on the ring.
    procs = [d.process_index for d in mesh.devices.flat]
    assert procs == sorted(procs), f"ring not host-major: {procs}"

    from p2pnetwork_tpu.models import Flood, Gossip
    from p2pnetwork_tpu.parallel import sharded
    from p2pnetwork_tpu.sim import engine, failures, topology
    from p2pnetwork_tpu.sim import graph as G

    g = G.watts_strogatz(512, 6, 0.2, seed=0)
    sg = sharded.shard_graph(g, mesh)

    # ---- Phase 1: flood to coverage, summary parity with the engine.
    seen, out = sharded.flood_until_coverage(
        sg, mesh, source=0, coverage_target=0.99
    )
    _, ref = engine.run_until_coverage(
        g, Flood(source=0), jax.random.key(0), coverage_target=0.99
    )
    assert out["rounds"] == ref["rounds"], (out, ref)
    assert out["messages"] == ref["messages"], (out, ref)
    assert abs(out["coverage"] - ref["coverage"]) < 1e-6
    _phase("flood")

    # ---- Phase 2: gossip averaging, exact-RNG value parity (the sharded
    # partner draws are keyed by edge identity, so the distributed values
    # must equal the engine's bit for bit).
    rounds = 5
    gp = Gossip(alpha=0.5)
    vals, _ = sharded.gossip(sg, mesh, gp, jax.random.key(1), rounds,
                             exact_rng=True)
    ref_g, _ = engine.run(g, gp, jax.random.key(1), rounds)
    ok = _all_equal(vals, jnp.asarray(np.asarray(ref_g.values)))
    assert bool(ok), "sharded gossip diverged from the engine across processes"
    _phase("gossip")

    # ---- Phase 3: churn — fail nodes, add a runtime bridge, rerun the
    # coverage while_loop on the damaged overlay; summaries must match the
    # engine's run over an identically churned graph.
    fail_ids = [3, g.n_nodes // 2]
    sgc = sharded.with_capacity(sharded.fail_nodes(sg, fail_ids), 8)
    sgc = sharded.connect(sgc, [1], [g.n_nodes - 2])
    gc = topology.connect(
        topology.with_capacity(failures.fail_nodes(g, fail_ids),
                               extra_edges=8),
        [1], [g.n_nodes - 2],
    )
    _, out_c = sharded.flood_until_coverage(sgc, mesh, source=0,
                                            coverage_target=0.9)
    _, ref_c = engine.run_until_coverage(gc, Flood(source=0),
                                         jax.random.key(0),
                                         coverage_target=0.9)
    assert out_c["rounds"] == ref_c["rounds"], (out_c, ref_c)
    assert out_c["messages"] == ref_c["messages"], (out_c, ref_c)
    _phase("churn")

    # ---- Phase 4: orbax checkpoint roundtrip ACROSS the process pair —
    # both processes save collectively, restore against a mesh-sharded
    # template, and verify the restored array still spans both processes'
    # devices with identical contents.
    from p2pnetwork_tpu.sim import checkpoint as ckpt

    ckpt_dir = os.path.join("/tmp", f"mh_ckpt_{port}")
    try:
        ckpt.save_orbax(ckpt_dir, {"vals": vals}, jax.random.key(2), rounds)
        restored, _, rnd, _ = ckpt.load_orbax(ckpt_dir, {"vals": vals})
        assert rnd == rounds
        assert restored["vals"].sharding.device_set == vals.sharding.device_set
        assert {d.process_index
                for d in restored["vals"].sharding.device_set} == {0, 1}, \
            "restored array no longer spans both processes"
        assert bool(_all_equal(restored["vals"],
                               jnp.asarray(np.asarray(ref_g.values))))
    finally:
        if pid == 0:
            shutil.rmtree(ckpt_dir, ignore_errors=True)
    _phase("checkpoint")

    # 2-D DCN x ICI mesh builds over the same job.
    m2 = multihost.mesh_2d()
    assert m2.devices.shape == (2, n_local)
    assert {d.process_index for d in m2.devices[0]} == {0}

    print(f"MULTIHOST_OK pid={pid} rounds={out['rounds']} "
          f"messages={out['messages']} phases=flood,gossip,churn,checkpoint",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
