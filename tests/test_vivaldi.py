"""Vivaldi coordinates: does the embedding actually predict latency?

The oracle is the protocol's purpose — after springing, coordinate
distance must predict the RTTs of links it trained on (and, more
interestingly, of PAIRS IT NEVER SAMPLED TOGETHER, via the geometry) far
better than at init. A planted 2-D metric gives ground truth: nodes on
a grid, link latency = Euclidean ground distance, so the embedding can
in principle be near-perfect."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from p2pnetwork_tpu.models import Vivaldi  # noqa: E402
from p2pnetwork_tpu.sim import engine, failures  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def _planted_grid(side=8, connect=2.6, long_links=3):
    """Nodes on a side x side unit grid; edges between all pairs within
    ground distance ``connect`` PLUS ``long_links`` random far partners
    per node, all weighted by true ground distance. The long links
    matter: with only short-range springs the embedding can satisfy
    every sampled spring while globally FOLDED (the known Vivaldi
    cold-start pathology); real deployments measure peers across all
    RTT scales, which is what the extra links model."""
    n = side * side
    xs = np.array([(i % side, i // side) for i in range(n)], np.float32)
    rng = np.random.default_rng(42)
    pairs = set()
    for i in range(n):
        d = np.linalg.norm(xs - xs[i], axis=1)
        for j in np.nonzero((d > 0) & (d <= connect))[0]:
            pairs.add((min(i, int(j)), max(i, int(j))))
        for j in rng.choice(n, size=long_links, replace=False):
            if j != i:
                pairs.add((min(i, int(j)), max(i, int(j))))
    srcs = np.array([p for a, b in pairs for p in (a, b)], np.int32)
    dsts = np.array([p for a, b in pairs for p in (b, a)], np.int32)
    g = G.from_edges(srcs, dsts, n)
    g = g.with_weights(
        lambda s, r: jnp.sqrt(jnp.sum(
            (jnp.asarray(xs)[s] - jnp.asarray(xs)[r]) ** 2, axis=-1)))
    return g, xs


def _pair_error(proto, st, g, xs, rng, k=300):
    """Median relative error of predicted vs ground distance over random
    CONNECTED-component pairs (the grid is connected)."""
    n = xs.shape[0]
    i = rng.integers(0, n, size=k)
    j = rng.integers(0, n, size=k)
    keep = i != j
    i, j = i[keep], j[keep]
    pred = np.asarray(proto.predicted(st, jnp.asarray(i), jnp.asarray(j)))
    true = np.linalg.norm(xs[i] - xs[j], axis=1)
    return float(np.median(np.abs(pred - true) / true))


class TestVivaldi:
    def test_embeds_a_planted_metric(self):
        g, xs = _planted_grid()
        proto = Vivaldi(dim=2)
        st0 = proto.init(g, jax.random.key(0))
        rng = np.random.default_rng(0)
        err0 = _pair_error(proto, st0, g, xs, rng)
        # The trajectory has a slow unfolding plateau (~rounds 100-700)
        # before collapsing to a near-exact embedding; 1500 rounds is
        # comfortably past it.
        st, out = engine.run(g, proto, jax.random.key(1), 1500)
        err = _pair_error(proto, st, g, xs, rng)
        # Init coords are a 1e-3 blob: initial relative error ~ 1.
        assert err0 > 0.5
        assert err < 0.05, f"median relative error {err:.3f} after springing"
        # Per-round sampled rmse fell accordingly.
        assert float(np.asarray(out["rmse"])[-1]) < 0.2 * float(
            np.asarray(out["rmse"])[0])

    def test_predicts_unsampled_pairs(self):
        # The whole point of coordinates: pairs far beyond any single
        # link (ground distance >> connect radius) are predicted through
        # the geometry.
        g, xs = _planted_grid()
        proto = Vivaldi(dim=2)
        st, _ = engine.run(g, proto, jax.random.key(1), 1500)
        rng = np.random.default_rng(1)
        n = xs.shape[0]
        i = rng.integers(0, n, size=500)
        j = rng.integers(0, n, size=500)
        far = np.linalg.norm(xs[i] - xs[j], axis=1) > 5.0  # >> connect=2.6
        i, j = i[far], j[far]
        pred = np.asarray(proto.predicted(st, jnp.asarray(i), jnp.asarray(j)))
        true = np.linalg.norm(xs[i] - xs[j], axis=1)
        assert float(np.median(np.abs(pred - true) / true)) < 0.05

    def test_noise_tolerance(self):
        g, xs = _planted_grid()
        proto = Vivaldi(dim=2, noise=0.2)
        st, _ = engine.run(g, proto, jax.random.key(1), 1500)
        rng = np.random.default_rng(2)
        assert _pair_error(proto, st, g, xs, rng) < 0.3

    def test_error_estimate_drops(self):
        g, _ = _planted_grid()
        proto = Vivaldi(dim=2)
        st, out = engine.run(g, proto, jax.random.key(1), 1500)
        ce = np.asarray(out["mean_ce"])
        assert ce[-1] < 0.05 and ce[-1] < 0.1 * ce[0]

    def test_height_learns_access_penalties(self):
        # Two "stub" nodes carry a +3.0 access-link penalty on every RTT
        # (the non-Euclidean residual heights exist for). Regression for
        # an absorbing-zero height update: with height multiplicative in
        # itself, a 0.0 init could never learn — the positive floor
        # (Serf's HeightMin) keeps the term live.
        g, xs = _planted_grid()
        pen = np.zeros(xs.shape[0], np.float32)
        stubs = [10, 53]
        pen[stubs] = 3.0
        xj, pj = jnp.asarray(xs), jnp.asarray(pen)
        g = g.with_weights(
            lambda s, r: jnp.sqrt(jnp.sum((xj[s] - xj[r]) ** 2, axis=-1))
            + pj[s] + pj[r])
        proto = Vivaldi(dim=2)
        st, _ = engine.run(g, proto, jax.random.key(1), 3000)
        h = np.asarray(st.height)[:xs.shape[0]]
        assert np.allclose(h[stubs], 3.0, atol=0.1), h[stubs]
        assert float(np.delete(h, stubs).mean()) < 0.1
        n = xs.shape[0]
        i = np.arange(n)
        j = (i + 17) % n
        pred = np.asarray(proto.predicted(st, jnp.asarray(i), jnp.asarray(j)))
        true = np.linalg.norm(xs[i] - xs[j], axis=1) + pen[i] + pen[j]
        assert float(np.median(np.abs(pred - true) / true)) < 0.05

    def test_dead_nodes_hold_position(self):
        g, _ = _planted_grid()
        dead = np.array([3, 17, 40])
        g = failures.fail_nodes(g, dead)
        proto = Vivaldi(dim=2)
        st0 = proto.init(g, jax.random.key(0))
        st, _ = engine.run(g, proto, jax.random.key(1), 100)
        assert np.allclose(np.asarray(st.coord)[dead],
                           np.asarray(st0.coord)[dead])
        assert (np.asarray(st.ce)[dead] == 1.0).all()

    def test_deterministic(self):
        g, _ = _planted_grid(side=5)
        proto = Vivaldi(dim=2)
        st1, _ = engine.run(g, proto, jax.random.key(1), 50)
        st2, _ = engine.run(g, proto, jax.random.key(1), 50)
        assert (np.asarray(st1.coord) == np.asarray(st2.coord)).all()

    def test_requires_neighbor_table(self):
        g = G.watts_strogatz(32, 4, 0.1, seed=1,
                             build_neighbor_table=False)
        with pytest.raises(ValueError):
            Vivaldi().init(g, jax.random.key(0))
