"""graftsight tests: ticket-scoped tracing, tick phases, the SLO engine.

The contract under test (PR 16): one serve ticket's whole lifecycle —
submit → queue → admit → engine chunks → device fault → integrity
verdict → heal retry → completion — exports as ONE Perfetto tree under
a single ``tkt-<id>`` trace id, chaos included; the driver's tick wall
decomposes into named phases (retire/admit/dispatch/harvest/checkpoint)
published through ``/dashboard``; declarative SLOs evaluate over
rolling observation windows with multi-window burn-rate alerts that
AIMD admission consumes as an explicit, deterministic signal; and all
of it rides the determinism contract — tracing+SLO on is bit-identical
to off, with the slow-marked 1.10x serve-tick overhead ratchet keeping
the instrumentation honest.
"""

import dataclasses
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from p2pnetwork_tpu import telemetry  # noqa: E402
from p2pnetwork_tpu.chaos.device import (  # noqa: E402
    DispatchChaos, install_dispatch_chaos)
from p2pnetwork_tpu.serve import (  # noqa: E402
    SimService, TrafficPattern, drive, generate)
from p2pnetwork_tpu.serve.service import (  # noqa: E402
    TICK_PHASES, ticket_trace)
from p2pnetwork_tpu.sim import engine  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402
from p2pnetwork_tpu.supervise.heal import RetryPolicy  # noqa: E402
from p2pnetwork_tpu.telemetry import history, spans  # noqa: E402
from p2pnetwork_tpu.telemetry.httpd import dashboard_doc  # noqa: E402
from p2pnetwork_tpu.telemetry.slo import (  # noqa: E402
    Objective, SLOEngine, serve_objectives)
from p2pnetwork_tpu.utils.logging import EventLog  # noqa: E402

pytestmark = pytest.mark.sight

KEY = jax.random.key(0)


@pytest.fixture(scope="module")
def ws256():
    return G.watts_strogatz(256, 4, 0.2, seed=0)


@pytest.fixture()
def tracer():
    t = spans.Tracer("sight-test")
    prev = spans.install_tracer(t)
    yield t
    spans.install_tracer(prev)


@pytest.fixture()
def no_dispatch_chaos():
    prev = install_dispatch_chaos(None)
    yield
    install_dispatch_chaos(prev)


def _svc(g, **kw):
    kw.setdefault("capacity", 16)
    kw.setdefault("chunk_rounds", 4)
    kw.setdefault("seed", 0)
    kw.setdefault("registry", telemetry.Registry())
    return SimService(g, **kw)


# ------------------------------------------------- trace-id correlation


class TestTraceOverride:
    def test_trace_kwarg_overrides_span_trace_id(self):
        t = spans.Tracer("base")
        t.point("plain")
        t.point("scoped", trace="tkt-t01")
        with t.span("also-scoped", trace="tkt-t01"):
            pass
        by_name = {sp.name: sp for sp in t.spans()}
        assert by_name["plain"].trace_id == t.trace_id
        assert by_name["scoped"].trace_id == "tkt-t01"
        assert by_name["also-scoped"].trace_id == "tkt-t01"

    def test_module_emit_carries_trace(self):
        t = spans.Tracer("base")
        prev = spans.install_tracer(t)
        try:
            spans.emit("ev", trace="tkt-t02", lane=3)
        finally:
            spans.install_tracer(prev)
        (sp,) = t.find("ev")
        assert sp.trace_id == "tkt-t02" and sp.args["lane"] == 3

    def test_to_chrome_filters_one_trace(self):
        t = spans.Tracer("base")
        t.point("a", trace="tkt-x")
        t.point("b", trace="tkt-y")
        t.point("c", trace="tkt-x")
        doc = t.to_chrome(trace_id="tkt-x")
        assert [e["name"] for e in doc["traceEvents"]] == ["a", "c"]
        assert all(e["args"]["trace_id"] == "tkt-x"
                   for e in doc["traceEvents"])
        assert doc["metadata"]["trace_id"] == "tkt-x"
        assert doc["metadata"]["traces"] == 1

    def test_traces_table_insertion_ordered(self):
        t = spans.Tracer("base")
        t.point("a", trace="tkt-1")
        t.point("b", trace="tkt-2")
        t.point("c", trace="tkt-1")
        by = t.traces()
        assert list(by) == [t.trace_id, "tkt-1", "tkt-2"]
        assert by["tkt-1"] == 2 and by["tkt-2"] == 1

    def test_ticket_trace_shape(self):
        assert ticket_trace("t00000007") == "tkt-t00000007"


class TestOverflowMetadata:
    def test_to_chrome_reports_dropped_spans(self):
        # Satellite 1: an overflowed store must SAY so in the export's
        # metadata, not silently read as complete.
        t = spans.Tracer("tiny", max_spans=4)
        for i in range(10):
            t.point(f"p{i}")
        doc = t.to_chrome()
        meta = doc["metadata"]
        assert meta["dropped_spans"] == 6 == t.dropped_spans
        assert meta["spans"] == len(doc["traceEvents"]) == 5  # root + 4
        assert meta["traces"] == 1
        assert meta["trace_id"] == t.trace_id

    def test_unfiltered_metadata_counts_all_traces(self):
        t = spans.Tracer("base")
        t.point("a", trace="tkt-1")
        t.point("b", trace="tkt-2")
        meta = t.to_chrome()["metadata"]
        assert meta["dropped_spans"] == 0
        assert meta["traces"] == 3  # base + two ticket traces


# ------------------------------------------------------ httpd endpoints


def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            return r.status, r.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8")


class TestHttpdQueryParams:
    def _server(self, reg, **kw):
        return telemetry.MetricsServer(reg, port=0, **kw)

    def test_history_last_n(self):
        reg = telemetry.Registry()
        reg.gauge("sight_g", "g").set(0.0)
        hist = history.History(reg, capacity=16)
        for i in range(6):
            reg.gauge("sight_g", "g").set(float(i))
            hist.sample(ts=float(i))
        with self._server(reg, history=hist) as srv:
            code, body = _get(srv.port, "/history?n=2")
            assert code == 200
            doc = json.loads(body)
            pts = doc["series"]["sight_g"][0]["points"]
            assert pts == [[4.0, 4.0], [5.0, 5.0]]
            code, body = _get(srv.port, "/history")
            assert len(json.loads(body)["series"]["sight_g"][0]["points"]) \
                == 6

    def test_history_bad_n_is_400_not_500(self):
        reg = telemetry.Registry()
        hist = history.History(reg, capacity=4)
        with self._server(reg, history=hist) as srv:
            for q in ("n=zero", "n=0", "n=-3"):
                code, body = _get(srv.port, f"/history?{q}")
                assert code == 400, q
                assert "n must be" in json.loads(body)["error"]

    def test_trace_filtered_by_trace_id(self):
        reg = telemetry.Registry()
        t = spans.Tracer("srv")
        t.point("mine", trace="tkt-t0")
        t.point("other", trace="tkt-t1")
        with self._server(reg, tracer=t) as srv:
            code, body = _get(srv.port, "/trace?trace_id=tkt-t0")
            assert code == 200
            doc = json.loads(body)
            assert [e["name"] for e in doc["traceEvents"]] == ["mine"]
            assert doc["metadata"]["trace_id"] == "tkt-t0"

    def test_trace_empty_trace_id_is_400(self):
        reg = telemetry.Registry()
        with self._server(reg, tracer=spans.Tracer("srv")) as srv:
            code, body = _get(srv.port, "/trace?trace_id=")
            assert code == 400
            assert "trace_id" in json.loads(body)["error"]

    def test_history_snapshot_last_validation(self):
        hist = history.History(telemetry.Registry(), capacity=4)
        with pytest.raises(ValueError, match="last"):
            hist.snapshot(last=0)

    def test_dashboard_html_and_json(self):
        reg = telemetry.Registry()
        reg.counter("sight_total", "c").inc()
        hist = history.History(reg, capacity=4)
        hist.sample(ts=1.0)
        t = spans.Tracer("srv")
        t.point("ev", trace="tkt-t0")
        slo = SLOEngine(serve_objectives(slo_rounds=8), registry=reg)
        slo.record("completion_rounds", 4.0)
        slo.evaluate(0)
        with self._server(reg, history=hist, tracer=t, slo=slo) as srv:
            code, body = _get(srv.port, "/dashboard.json")
            assert code == 200
            doc = json.loads(body)
            assert doc["slo"]["objectives"]["completion_p99_rounds"][
                "samples"] == 1
            assert doc["traces"]["recent"]["tkt-t0"] == 1
            assert doc["metrics"]  # registry snapshot embedded
            code, page = _get(srv.port, "/dashboard")
            assert code == 200
            assert page.startswith("<!DOCTYPE html>")
            # The JSON island round-trips (the "</" embedding escape
            # must not corrupt it).
            island = page.split('<script id="data" '
                                'type="application/json">')[1]
            island = island.split("</script>")[0].replace("<\\/", "</")
            assert json.loads(island)["slo"] is not None

    def test_dashboard_without_slo_or_service(self):
        reg = telemetry.Registry()
        hist = history.History(reg, capacity=4)
        doc = dashboard_doc(reg, hist, None, None, None)
        assert doc["slo"] is None and doc["service"] is None \
            and doc["traces"] is None
        json.dumps(doc)


# ---------------------------------------------------------- SLO engine


class TestObjective:
    def test_validation(self):
        with pytest.raises(ValueError, match="mode"):
            Objective("o", metric="m", target=1.0, mode="eq")
        with pytest.raises(ValueError, match="goal"):
            Objective("o", metric="m", target=1.0, goal=1.0)
        with pytest.raises(ValueError, match="fast_window"):
            Objective("o", metric="m", target=1.0, fast_window=8,
                      slow_window=4)
        with pytest.raises(ValueError, match="burn_threshold"):
            Objective("o", metric="m", target=1.0, burn_threshold=0.0)
        with pytest.raises(ValueError, match="duplicate"):
            SLOEngine([Objective("o", metric="m", target=1.0)] * 2,
                      registry=telemetry.Registry())

    def test_good_modes(self):
        le = Objective("o", metric="m", target=10.0, mode="le")
        assert le.good(10.0) and not le.good(10.5)
        ge = Objective("o", metric="m", target=0.9, mode="ge")
        assert ge.good(0.95) and not ge.good(0.5)

    def test_serve_objectives_set(self):
        objs = serve_objectives(slo_rounds=24)
        names = [o.name for o in objs]
        assert names == ["completion_p99_rounds", "shed_rate", "heal_rate"]
        assert [o.admission_signal for o in objs] == [True, False, False]
        wall = serve_objectives(slo_rounds=24, wall_s=2.0)
        assert wall[1].name == "completion_p99_wall_s"
        assert not wall[1].admission_signal  # wall-clock never steers


class TestSLOEngine:
    def _eng(self, **obj_kw):
        obj_kw.setdefault("fast_window", 4)
        obj_kw.setdefault("slow_window", 8)
        obj_kw.setdefault("goal", 0.5)
        obj = Objective("rounds_p", metric="rounds", target=10.0, **obj_kw)
        reg = telemetry.Registry()
        return SLOEngine([obj], registry=reg, log=EventLog()), reg

    def test_burn_math(self):
        eng, _ = self._eng()
        for v in [1.0] * 4 + [99.0] * 4:  # half bad, budget 0.5
            eng.record("rounds", v)
        st = eng.evaluate(0)["rounds_p"]
        assert st["burn_slow"] == pytest.approx(1.0)  # exactly on budget
        assert st["burn_fast"] == pytest.approx(2.0)  # fast window all bad
        assert st["good_ratio"] == pytest.approx(0.5)

    def test_no_verdict_before_fast_window_fills(self):
        eng, _ = self._eng()
        eng.record("rounds", 99.0)  # one bad first observation
        st = eng.evaluate(0)["rounds_p"]
        assert st["burn_fast"] == pytest.approx(2.0)  # over threshold...
        assert not st["firing"]  # ...but unwarmed: one bad obs can't page

    def test_multi_window_needs_both(self):
        eng, _ = self._eng()
        for _ in range(6):
            eng.record("rounds", 1.0)  # slow window seeded good
        for _ in range(4):
            eng.record("rounds", 99.0)  # fast window all bad
        st = eng.evaluate(1)["rounds_p"]
        assert st["burn_fast"] >= 2.0
        assert st["burn_slow"] < 2.0
        assert not st["firing"]  # the slow window vetoes the page
        for _ in range(8):
            eng.record("rounds", 99.0)  # now the slow window burns too
        assert eng.evaluate(2)["rounds_p"]["firing"]

    def test_transitions_alert_records_counters_gauges(self):
        eng, reg = self._eng()
        for _ in range(8):
            eng.record("rounds", 99.0)
        eng.evaluate(3)
        assert eng.firing() == ["rounds_p"]
        assert reg.value("slo_firing", objective="rounds_p") == 1.0
        assert reg.value("slo_burn_rate", objective="rounds_p",
                         window="fast") == pytest.approx(2.0)
        assert reg.value("slo_alerts_total", objective="rounds_p",
                         transition="fire") == 1
        # A second evaluate while still firing is NOT a new transition.
        eng.evaluate(4)
        assert reg.value("slo_alerts_total", objective="rounds_p",
                         transition="fire") == 1
        for _ in range(8):
            eng.record("rounds", 1.0)
        eng.evaluate(5)
        assert eng.firing() == []
        assert reg.value("slo_alerts_total", objective="rounds_p",
                         transition="resolve") == 1
        alerts = [r for r in eng.log.snapshot() if r.event == "slo_alert"]
        assert [a.data["transition"] for a in alerts] == ["fire", "resolve"]
        assert alerts[0].data["objective"] == "rounds_p"
        assert alerts[0].data["tick"] == 3

    def test_admission_only_filter(self):
        objs = [Objective("det", metric="rounds", target=1.0, goal=0.5,
                          fast_window=2, slow_window=2,
                          admission_signal=True),
                Objective("wall", metric="wall", target=1.0, goal=0.5,
                          fast_window=2, slow_window=2)]
        eng = SLOEngine(objs, registry=telemetry.Registry())
        for _ in range(2):
            eng.record("rounds", 9.0)
            eng.record("wall", 9.0)
        eng.evaluate(0)
        assert sorted(eng.firing()) == ["det", "wall"]
        assert eng.firing(admission_only=True) == ["det"]

    def test_record_unjudged_stream_dropped(self):
        eng, _ = self._eng()
        eng.record("unknown_stream", 1.0)  # no ring, no crash
        assert eng.evaluate(0)["rounds_p"]["samples"] == 0

    def test_snapshot_before_and_after_evaluate(self):
        eng, _ = self._eng()
        snap = eng.snapshot()
        assert not snap["objectives"]["rounds_p"]["firing"]
        assert snap["objectives"]["rounds_p"]["metric"] == "rounds"
        for _ in range(8):
            eng.record("rounds", 99.0)
        eng.evaluate(7)
        snap = eng.snapshot()
        assert snap["objectives"]["rounds_p"]["firing"]
        assert snap["alerts"][-1]["data"]["transition"] == "fire"
        json.dumps(snap)

    def test_evaluate_is_pure_in_observations(self):
        runs = []
        for _ in range(2):
            eng, _ = self._eng()
            for v in [1.0, 99.0, 3.0, 99.0, 99.0, 1.0, 99.0, 99.0]:
                eng.record("rounds", v)
            runs.append(eng.evaluate(0))
        assert runs[0] == runs[1]


# ------------------------------------------------- tick-phase profiler


class TestTickPhases:
    def test_profile_populates_and_dashboard_slice(self, ws256):
        reg = telemetry.Registry()
        svc = _svc(ws256, registry=reg)
        for s in (1, 2, 3):
            svc.submit(s)
        for _ in range(4):
            svc.tick()
        tp = svc.tick_phases()
        assert tp["ticks"] == 4
        assert set(tp["per_phase"]) == set(TICK_PHASES)
        for ph in TICK_PHASES:
            st = tp["per_phase"][ph]
            assert st["total_s"] >= st["max_s"] >= st["last_s"] >= 0.0
            assert st["mean_s"] == pytest.approx(st["total_s"] / 4)
        assert len(tp["recent"]) == 4
        assert all(set(row) >= set(TICK_PHASES) for row in tp["recent"])
        # Joinable with the history ring: last-tick gauges per phase.
        assert reg.value("serve_tick_phase_wall_s", phase="dispatch") \
            is not None
        snap = reg.snapshot()
        assert "serve_tick_phase_seconds" in snap
        ds = svc.dashboard_slice()
        assert set(ds) == {"stats", "tick_phases"}
        assert ds["stats"]["tick"] == 4
        svc.close()

    def test_phase_spans_under_serve_tick(self, ws256, tracer):
        svc = _svc(ws256)
        svc.submit(1)
        svc.tick()
        svc.close()
        ticks = tracer.find("serve_tick")
        assert ticks, "one serve_tick span per tick when traced"
        children = {sp.name for sp in tracer.spans()
                    if sp.parent_id == ticks[0].span_id}
        assert {f"tick_{ph}" for ph in TICK_PHASES} <= children
        (pt,) = [sp for sp in tracer.spans()
                 if sp.name == "tick_phases"
                 and sp.parent_id == ticks[0].span_id]
        assert set(pt.args) >= set(TICK_PHASES)

    def test_ring_bounded(self, ws256):
        svc = _svc(ws256, capacity=4, chunk_rounds=1)
        for _ in range(140):
            svc.tick()  # idle ticks still profile
        tp = svc.tick_phases()
        assert tp["ticks"] == 140
        assert len(tp["recent"]) == 32  # snapshot tail
        with svc._phase_lock:
            assert len(svc._phase_ring) == 128  # ring bound
        svc.close()


# -------------------------------------------------- SLO -> AIMD signal


class TestSLOAdmission:
    def test_firing_admission_objective_halves_budget(self, ws256):
        # A tight deterministic objective (every completion "bad") must
        # fire once warmed and multiplicatively decrease the admit
        # budget — the explicit SLO signal beside the slo_rounds rule.
        reg = telemetry.Registry()
        slo = SLOEngine(
            [Objective("tight_rounds", metric="completion_rounds",
                       target=0.5, goal=0.5, fast_window=2, slow_window=4,
                       burn_threshold=2.0, admission_signal=True)],
            registry=reg, log=EventLog())
        svc = _svc(ws256, registry=reg, slo=slo)
        start_budget = svc.stats()["admit_budget"]
        for s in range(1, 9):
            svc.submit(s)
        for _ in range(10):
            svc.tick()
        assert slo.firing(admission_only=True) == ["tight_rounds"]
        assert svc.stats()["admit_budget"] < start_budget
        assert reg.value("slo_firing", objective="tight_rounds") == 1.0
        assert reg.value("slo_alerts_total", objective="tight_rounds",
                         transition="fire") == 1
        svc.close()

    def test_healthy_run_keeps_budget(self, ws256):
        reg = telemetry.Registry()
        slo = SLOEngine(serve_objectives(slo_rounds=1024),
                        registry=reg, log=EventLog())
        svc = _svc(ws256, registry=reg, slo=slo)
        start_budget = svc.stats()["admit_budget"]
        for s in range(1, 5):
            svc.submit(s)
        for _ in range(6):
            svc.tick()
        assert slo.firing() == []
        assert svc.stats()["admit_budget"] >= start_budget
        svc.close()

    def test_shed_and_heal_streams_fed(self, ws256):
        from p2pnetwork_tpu.serve.service import Rejected
        reg = telemetry.Registry()
        slo = SLOEngine(serve_objectives(slo_rounds=1024),
                        registry=reg, log=EventLog())
        svc = _svc(ws256, capacity=4, queue_depth=1, registry=reg, slo=slo)
        shed = 0
        for s in range(1, 20):
            try:
                svc.submit(s)
            except Rejected:
                shed += 1
        assert shed > 0
        svc.tick()
        snap = slo.snapshot()["objectives"]
        assert snap["shed_rate"]["samples"] == 19  # every submit observed
        assert snap["heal_rate"]["samples"] == 1   # one dispatching tick
        svc.close()


# ------------------------------------- chaos-under-load acceptance row


class TestChaosPerfettoAcceptance:
    def _drive(self, svc, n_tickets=3, ticks=8):
        tids = [svc.submit(s) for s in range(1, n_tickets + 1)]
        for _ in range(ticks):
            svc.tick()
        recs = [svc.poll(t) for t in tids]
        svc.close()
        return tids, recs

    def test_faulted_ticket_one_trace_tree_bit_identical(
            self, ws256, monkeypatch, no_dispatch_chaos):
        heal = RetryPolicy(max_attempts=3, backoff_base_s=0.0)
        # Reference: heal-configured, UNfaulted, UNinstrumented.
        ref = _svc(ws256, heal=heal, record_seen_hash=True)
        ref_tids, ref_recs = self._drive(ref)
        assert all(r["status"] == "done" for r in ref_recs)

        # Chaos run: a one-shot silent corruption of the first chunk's
        # carry (zeroed seen words -> monotonicity IntegrityViolation)
        # plus an armed chip-loss at a later dispatch; tracer on.
        real = engine.run_batch_until_coverage
        armed = {"on": True}

        def corrupting(graph, protocol, batch, key, **kw):
            b, out = real(graph, protocol, batch, key, **kw)
            if armed["on"]:
                armed["on"] = False
                b = dataclasses.replace(b, seen=jnp.zeros_like(b.seen))
            return b, out

        monkeypatch.setattr(engine, "run_batch_until_coverage", corrupting)
        install_dispatch_chaos(DispatchChaos(preempt_at=(2,)))
        t = spans.Tracer("chaos-serve")
        prev = spans.install_tracer(t)
        try:
            reg = telemetry.Registry()
            svc = _svc(ws256, heal=heal, record_seen_hash=True,
                       registry=reg)
            tids, recs = self._drive(svc)
        finally:
            spans.install_tracer(prev)
        # Per-ticket results bit-identical to the unfaulted,
        # uninstrumented reference (seen hashes included).
        assert tids == ref_tids
        assert recs == ref_recs
        assert reg.value("quake_integrity_failures_total",
                         kind="monotonicity") == 1
        assert reg.value("heal_rollbacks_total", source="retained") >= 1
        assert reg.value("serve_healed_ticks_total") == 2

        # One Perfetto document per faulted ticket: the whole lifecycle
        # under a single trace id.
        tr = ticket_trace(tids[0])
        doc = t.to_chrome(trace_id=tr)
        json.dumps(doc)  # Perfetto-loadable
        names = [e["name"] for e in doc["traceEvents"]]
        assert all(e["args"]["trace_id"] == tr for e in doc["traceEvents"])
        chain = ["ticket_submit", "ticket_admit", "ticket_chunk",
                 "ticket_fault", "ticket_integrity_fail",
                 "ticket_heal_retry", "ticket_done"]
        first = {n: names.index(n) for n in chain}
        assert [first[n] for n in chain] == sorted(first[n] for n in chain)
        assert "ticket_heal_recovered" in names
        fails = [e for e in doc["traceEvents"]
                 if e["name"] == "ticket_integrity_fail"]
        assert fails[0]["args"]["kind"] == "monotonicity"
        assert fails[0]["args"]["leaf"] == "seen"
        kinds = {e["args"]["kind"] for e in doc["traceEvents"]
                 if e["name"] == "ticket_fault"}
        assert kinds == {"integrity", "preempt"}
        # The chunk events name their faulted ticks.
        chunk_faulted = [e["args"]["faulted"] for e in doc["traceEvents"]
                         if e["name"] == "ticket_chunk"]
        assert chunk_faulted.count(True) == 2
        # The heal plane's own (non-ticket) events landed too.
        assert t.find("heal_retry") and t.find("heal_rollback")
        assert t.find("heal_recovered") and t.find("dispatch_fault")

    def test_heal_report_driver_confined_shape(self, ws256, monkeypatch,
                                               no_dispatch_chaos):
        heal = RetryPolicy(max_attempts=3, backoff_base_s=0.0)
        real = engine.run_batch_until_coverage
        armed = {"on": True}

        def corrupting(graph, protocol, batch, key, **kw):
            b, out = real(graph, protocol, batch, key, **kw)
            if armed["on"]:
                armed["on"] = False
                b = dataclasses.replace(b, seen=jnp.zeros_like(b.seen))
            return b, out

        monkeypatch.setattr(engine, "run_batch_until_coverage", corrupting)
        svc = _svc(ws256, heal=heal)
        svc.submit(1)
        svc.tick()
        rep = svc._healer.last_report
        assert rep["healed"] and not rep["exhausted"]
        assert rep["attempts"] == 2 and not rep["fallback"]
        (ev,) = rep["events"]
        assert ev["failure"] == "integrity"
        assert ev["integrity_kind"] == "monotonicity"
        assert ev["leaf"] == "seen"
        assert ev["attempt"] == 1
        svc.close()


# --------------------------------------------- determinism satellites


class TestBitIdentityUnderTrace:
    def test_traced_chaos_healed_drive_matches_untraced(
            self, ws256, no_dispatch_chaos):
        # Satellite 4: tracer-on == tracer-off for a chaos-healed serve
        # run over seeded traffic (per-ticket records, hashes included).
        pattern = TrafficPattern(ticks=8, rate=2.0, coverage_target=0.9)
        sched = generate(pattern, ws256.n_nodes, seed=7)
        heal = RetryPolicy(max_attempts=3, backoff_base_s=0.0)

        ref = _svc(ws256, heal=heal, record_seen_hash=True)
        drive(ref, sched)
        ref.close()

        install_dispatch_chaos(DispatchChaos(wedge_at=(1,)))
        t = spans.Tracer("traced-drive")
        prev = spans.install_tracer(t)
        try:
            svc = _svc(ws256, heal=heal, record_seen_hash=True)
            drive(svc, sched)
            svc.close()
        finally:
            spans.install_tracer(prev)
        assert svc.tickets() == ref.tickets()
        ticket_traces = [tid for tid in t.traces() if tid.startswith("tkt-")]
        assert len(ticket_traces) == len(ref.tickets())

    def test_sight_scenario_registered_builtin(self):
        from p2pnetwork_tpu.analysis.race.scenarios import builtin_names
        assert "sight_scrape_under_serve" in builtin_names()


class TestEngineBatchSummaryEvent:
    def test_batch_summary_point_inside_batch_run(self, ws256, tracer):
        from p2pnetwork_tpu.models.messagebatch import BatchFlood
        proto = BatchFlood()
        batch = proto.init(ws256, [1, 2], capacity=4)
        _, out = engine.run_batch_until_coverage(
            ws256, proto, batch, KEY, max_rounds=64, donate=False)
        (ev,) = tracer.find("batch_summary")
        assert ev.args["rounds"] == int(out["rounds"])
        assert ev.args["newly_completed"] == 2
        (run,) = tracer.find("batch_run")
        assert ev.parent_id == run.span_id


class TestBenchProbePolicySummary:
    def test_gave_up_session_summarized(self, monkeypatch):
        import bench
        monkeypatch.setattr(bench, "_PROBE_LOG", [])
        monkeypatch.setattr(bench, "_probe_backend_once",
                            lambda t: "wedged")
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        err = bench._backend_alive(window_s=300, probe_timeout_s=1,
                                   max_attempts=2)
        assert err is not None
        (summary,) = [e for e in bench._PROBE_LOG
                      if e.get("policy_summary")]
        assert summary["outcome"] == "gave_up"
        assert summary["attempts"] == 2
        assert len(summary["backoff_schedule_s"]) == 2
        # The schedule IS the attempts' recorded backoffs (satellite 3:
        # replayable from the artifact alone).
        logged = [e["backoff_s"] for e in bench._PROBE_LOG
                  if "backoff_s" in e]
        assert logged == summary["backoff_schedule_s"]
        json.dumps(bench._PROBE_LOG)

    def test_healed_and_clean_outcomes(self, monkeypatch):
        import bench
        monkeypatch.setattr(bench, "_PROBE_LOG", [])
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        outcomes = iter(["wedged once", None])
        monkeypatch.setattr(bench, "_probe_backend_once",
                            lambda t: next(outcomes))
        assert bench._backend_alive(window_s=300, probe_timeout_s=1,
                                    max_attempts=3) is None
        (summary,) = [e for e in bench._PROBE_LOG
                      if e.get("policy_summary")]
        assert summary["outcome"] == "healed" and summary["attempts"] == 2
        bench._PROBE_LOG.clear()
        monkeypatch.setattr(bench, "_probe_backend_once", lambda t: None)
        assert bench._backend_alive(window_s=300, probe_timeout_s=1) is None
        (summary,) = [e for e in bench._PROBE_LOG
                      if e.get("policy_summary")]
        assert summary["outcome"] == "clean" and summary["attempts"] == 1


# ------------------------------------------------------ overhead ratchet


class TestOverheadRatchet:
    @pytest.mark.slow
    def test_instrumented_serve_tick_within_ratchet(self, ws256,
                                                    no_dispatch_chaos):
        # Acceptance: tracer+SLO+profiler on <= 1.10x off for the serve
        # tick path (ratio-based, interleaved best-of-7 — the PR-12
        # flight-recorder ratchet extended to the serving plane).
        g = G.watts_strogatz(20_000, 8, 0.1, seed=0)

        def run(instrumented):
            t = prev = slo = None
            if instrumented:
                t = spans.Tracer("ratchet", max_spans=200_000)
                prev = spans.install_tracer(t)
                slo = SLOEngine(serve_objectives(slo_rounds=1024),
                                registry=telemetry.Registry(),
                                log=EventLog())
            try:
                svc = _svc(g, capacity=32, chunk_rounds=8,
                           slo=slo)
                # A rolling submit stream keeps every timed tick
                # dispatching a real batch — idle ticks would let the
                # fixed per-tick instrumentation dominate the ratio.
                src = 1
                t0 = time.perf_counter()
                for _ in range(6):
                    for _ in range(8):
                        svc.submit(src)
                        src += 1
                    svc.tick()
                wall = time.perf_counter() - t0
                svc.close()
            finally:
                if instrumented:
                    spans.install_tracer(prev)
            return wall

        run(False)  # warm the engine program before timing
        run(True)
        offs, ons = [], []
        for _ in range(7):  # interleaved best-of-7, CPU-noise-robust
            offs.append(run(False))
            ons.append(run(True))
        ratio = min(ons) / min(offs)
        assert ratio <= 1.10, (
            f"graftsight serve-tick overhead {ratio:.3f}x exceeds the "
            f"1.10x ratchet (off {min(offs):.4f}s on {min(ons):.4f}s)")
