"""AdaptiveFlood vs Flood: bit-identical results through every regime.

The adaptive protocol must be indistinguishable from the dense one — same
seen sets, same per-round messages / coverage / frontier stats, same
rounds-to-coverage — across sparse-only runs, dense crossings in both
directions, failures, runtime connects, and resume."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from p2pnetwork_tpu.models import AdaptiveFlood, Flood  # noqa: E402
from p2pnetwork_tpu.sim import engine, failures, topology  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def _assert_matches(g, adaptive, rounds, source=0):
    key = jax.random.key(0)
    st_a, stats_a = engine.run(g, adaptive, key, rounds)
    st_f, stats_f = engine.run(g, Flood(source=source), key, rounds)
    np.testing.assert_array_equal(np.asarray(st_a.seen), np.asarray(st_f.seen))
    np.testing.assert_array_equal(np.asarray(st_a.frontier),
                                  np.asarray(st_f.frontier))
    np.testing.assert_array_equal(np.asarray(stats_a["messages"]),
                                  np.asarray(stats_f["messages"]))
    np.testing.assert_array_equal(np.asarray(stats_a["frontier"]),
                                  np.asarray(stats_f["frontier"]))
    np.testing.assert_allclose(np.asarray(stats_a["coverage"]),
                               np.asarray(stats_f["coverage"]), rtol=1e-6)
    return st_a


class TestAdaptiveFloodParity:
    def test_sparse_only_run(self):
        # k large enough that every round stays sparse.
        g = G.watts_strogatz(1024, 6, 0.2, seed=0, source_csr=True)
        _assert_matches(g, AdaptiveFlood(source=0, k=2048), rounds=8)

    def test_crosses_into_dense_and_back(self):
        # Small k: rounds 1-2 sparse, the middle dense, the tail sparse.
        g = G.watts_strogatz(4096, 6, 0.1, seed=1, source_csr=True)
        _assert_matches(g, AdaptiveFlood(source=7, k=64), rounds=12, source=7)

    def test_always_dense(self):
        # k=1 below even the seed round after one step: dense path all the
        # way, exercising the compaction-on-reentry cond never firing.
        g = G.watts_strogatz(2048, 6, 0.1, seed=2, source_csr=True)
        _assert_matches(g, AdaptiveFlood(source=0, k=1), rounds=8)

    @pytest.mark.parametrize("make", [
        lambda: G.erdos_renyi(700, 0.01, seed=3, source_csr=True),
        lambda: G.ring(512, source_csr=True),
        lambda: G.barabasi_albert(500, 3, seed=4, source_csr=True),
    ])
    def test_other_topologies(self, make):
        _assert_matches(make(), AdaptiveFlood(source=0, k=128), rounds=10)

    def test_under_failures(self):
        g = failures.fail_nodes(
            G.watts_strogatz(2048, 6, 0.1, seed=5, source_csr=True), [3, 500]
        )
        _assert_matches(g, AdaptiveFlood(source=0, k=64), rounds=10)

    def test_under_edge_failures(self):
        # CSR rows are build-time; dead edges must be filtered at gather.
        g = G.watts_strogatz(1024, 6, 0.1, seed=6, source_csr=True)
        g = failures.random_edge_failures(g, jax.random.key(1), 0.3)
        _assert_matches(g, AdaptiveFlood(source=0, k=64), rounds=10)

    def test_with_runtime_connects(self):
        # A dynamic link out of the wave's path must carry in sparse mode.
        g = G.ring(1024, source_csr=True)
        g = topology.connect(topology.with_capacity(g, extra_edges=8),
                             [2], [900])
        _assert_matches(g, AdaptiveFlood(source=0, k=64), rounds=12)

    def test_run_until_coverage_matches(self):
        g = G.watts_strogatz(8192, 8, 0.1, seed=7, source_csr=True)
        _, out_a = engine.run_until_coverage(
            g, AdaptiveFlood(source=0, k=256), jax.random.key(0),
            coverage_target=0.99,
        )
        _, out_f = engine.run_until_coverage(
            g, Flood(source=0), jax.random.key(0), coverage_target=0.99,
        )
        assert out_a["rounds"] == out_f["rounds"]
        assert out_a["messages"] == out_f["messages"]
        assert out_a["coverage"] == pytest.approx(out_f["coverage"], rel=1e-6)

    def test_resume_midway(self):
        g = G.watts_strogatz(2048, 6, 0.1, seed=8, source_csr=True)
        proto = AdaptiveFlood(source=0, k=64)
        key = jax.random.key(0)
        st, _ = engine.run(g, proto, key, 4)
        st, _ = engine.run_from(g, proto, st, key, 4)
        ref, _ = engine.run(g, Flood(source=0), key, 8)
        np.testing.assert_array_equal(np.asarray(st.seen),
                                      np.asarray(ref.seen))

    def test_requires_source_csr(self):
        g = G.ring(256)
        with pytest.raises(ValueError, match="source-CSR"):
            AdaptiveFlood(source=0).init(g, jax.random.key(0))


class TestAdaptiveFloodGrownNodes:
    def test_joined_spare_node_joins_the_wave(self):
        # with_capacity(extra_nodes) must keep src_offsets at i32[N_pad+1];
        # a joined spare node has an empty build-time CSR row and reaches
        # the wave purely through the dynamic edge region.
        g = G.ring(250, source_csr=True)
        g = topology.with_capacity(g, extra_edges=16, extra_nodes=10)
        assert g.src_offsets.shape[0] == g.n_nodes_padded + 1
        spare = 300
        g = topology.join_node(g, spare, [5])
        ga = topology.join_node(
            topology.with_capacity(G.ring(250), extra_edges=16,
                                   extra_nodes=10),
            spare, [5],
        )
        key = jax.random.key(0)
        st_a, _ = engine.run(g, AdaptiveFlood(source=0, k=32), key, 8)
        st_f, _ = engine.run(ga, Flood(source=0), key, 8)
        np.testing.assert_array_equal(np.asarray(st_a.seen),
                                      np.asarray(st_f.seen))
        assert np.asarray(st_a.seen)[spare]  # the joined node got the wave


class TestAdaptiveHopDistance:
    def test_matches_hopdist_through_crossings(self):
        from p2pnetwork_tpu.models import AdaptiveHopDistance, HopDistance

        g = G.watts_strogatz(4096, 6, 0.1, seed=9, source_csr=True)
        key = jax.random.key(0)
        st_a, stats_a = engine.run(g, AdaptiveHopDistance(source=3, k=64),
                                   key, 12)
        st_h, stats_h = engine.run(g, HopDistance(source=3), key, 12)
        np.testing.assert_array_equal(np.asarray(st_a.dist),
                                      np.asarray(st_h.dist))
        for k in ("messages", "frontier", "max_dist"):
            np.testing.assert_array_equal(np.asarray(stats_a[k]),
                                          np.asarray(stats_h[k]))

    def test_coverage_loop_matches(self):
        from p2pnetwork_tpu.models import AdaptiveHopDistance, HopDistance

        g = G.watts_strogatz(8192, 8, 0.1, seed=10, source_csr=True)
        _, out_a = engine.run_until_coverage(
            g, AdaptiveHopDistance(source=0, k=256), jax.random.key(0),
            coverage_target=0.99,
        )
        _, out_h = engine.run_until_coverage(
            g, HopDistance(source=0), jax.random.key(0), coverage_target=0.99,
        )
        assert out_a["rounds"] == out_h["rounds"]
        assert out_a["messages"] == out_h["messages"]

    def test_under_churn(self):
        from p2pnetwork_tpu.models import AdaptiveHopDistance, HopDistance

        g = G.ring(1024, source_csr=True)
        g = topology.connect(
            topology.with_capacity(failures.fail_nodes(g, [7]),
                                   extra_edges=8),
            [2], [900],
        )
        st_a, _ = engine.run(g, AdaptiveHopDistance(source=0, k=32),
                             jax.random.key(0), 20)
        st_h, _ = engine.run(g, HopDistance(source=0), jax.random.key(0), 20)
        np.testing.assert_array_equal(np.asarray(st_a.dist),
                                      np.asarray(st_h.dist))
        assert np.asarray(st_a.dist)[7] == -1


class TestAdaptiveFloodHubGraphs:
    """Degree-skewed graphs: the work-item layout (slice_width chunking)
    must keep sparse rounds exact and bounded on hubs — the one graph
    family the node-count budget excluded (VERDICT r3 #2)."""

    def test_ba_100k_bit_identical(self):
        # BASELINE config 2's graph family at full size: 100K-node
        # Barabási–Albert scale-free, hubs in the thousands of edges.
        g = G.barabasi_albert(100_000, 5, seed=0, source_csr=True)
        _assert_matches(g, AdaptiveFlood(source=0, k=512), rounds=8)

    def test_hub_row_processed_whole_in_one_round(self):
        # A 200-leaf star with slice_width=16: the center's row expands to
        # 13 work items, all scheduled the same round — every leaf must be
        # seen after one step, exactly as the dense flood delivers it.
        leaves = np.arange(1, 201)
        senders = np.concatenate([np.zeros(200, int), leaves])
        receivers = np.concatenate([leaves, np.zeros(200, int)])
        g = G.from_edges(senders, receivers, 201).with_source_csr()
        st = _assert_matches(
            g, AdaptiveFlood(source=0, k=32, slice_width=16), rounds=2)
        assert np.asarray(st.seen)[:201].all()

    def test_hub_seed_tips_dense_by_edge_mass(self):
        # Budgeting is by out-edge mass, not node count: a single hub
        # source whose row exceeds k*W items must make round one dense.
        leaves = np.arange(1, 401)
        senders = np.concatenate([np.zeros(400, int), leaves])
        receivers = np.concatenate([leaves, np.zeros(400, int)])
        g = G.from_edges(senders, receivers, 401).with_source_csr()
        proto = AdaptiveFlood(source=0, k=8, slice_width=4)  # 100 items
        st0 = proto.init(g, jax.random.key(0))
        assert int(st0.fcount) > 8  # seed alone overflows the item budget
        _assert_matches(g, proto, rounds=3)

    @pytest.mark.parametrize("slice_width", [1, 3, 16])
    def test_explicit_slice_width_parity(self, slice_width):
        g = G.watts_strogatz(2048, 6, 0.1, seed=11, source_csr=True)
        _assert_matches(
            g, AdaptiveFlood(source=0, k=256, slice_width=slice_width),
            rounds=10)

    def test_ba_under_failures_and_connects(self):
        g = G.barabasi_albert(2000, 4, seed=12, source_csr=True)
        g = failures.fail_nodes(g, [1, 2])  # BA low ids are the hubs
        g = topology.connect(topology.with_capacity(g, extra_edges=8),
                             [50], [1900])
        _assert_matches(g, AdaptiveFlood(source=0, k=64), rounds=10)


class TestAdaptiveFloodEdgeCases:
    def test_edgeless_graph(self):
        # No edges at all: the wave dies at the seed; coverage never moves.
        g = G.from_edges([], [], 64).with_source_csr()
        st, stats = engine.run(g, AdaptiveFlood(source=3, k=16),
                               jax.random.key(0), 4)
        assert np.asarray(st.seen).sum() == 1
        np.testing.assert_array_equal(np.asarray(stats["messages"]),
                                      [0, 0, 0, 0])

    def test_isolated_source(self):
        g = G.from_edges([0, 1], [1, 0], 8).with_source_csr()  # 2..7 isolated
        st, _ = engine.run(g, AdaptiveFlood(source=5, k=8),
                           jax.random.key(0), 4)
        seen = np.asarray(st.seen)
        assert seen[5] and seen.sum() == 1

    def test_single_node_graph(self):
        g = G.from_edges([], [], 1).with_source_csr()
        _, out = engine.run_until_coverage(
            g, AdaptiveFlood(source=0, k=4), jax.random.key(0),
            coverage_target=0.99,
        )
        assert out["rounds"] == 0 and out["coverage"] == 1.0
