"""Merkle set reconciliation: convergence to the union, the O(diff·log)
message bound that is the technique's whole point, deterministic
conflict resolution, and session edges."""

import random

import pytest

from p2pnetwork_tpu import SyncNode
from tests.helpers import stop_all, wait_until

HOST = "127.0.0.1"


def _pair():
    a = SyncNode(HOST, 0, id="A")
    b = SyncNode(HOST, 0, id="B")
    for n in (a, b):
        n.start()
    assert a.connect_with_node(HOST, b.port)
    assert wait_until(lambda: len(a.all_nodes) == 1
                      and len(b.all_nodes) == 1)
    return a, b


def _fill(node, items):
    for k, v in items:
        node.put(k, v)
    assert wait_until(lambda: all(node.get(k) is not None
                                  for k, _ in items))


def _sync(a, b, timeout=15.0):
    a.sync_with(a.all_nodes[0])
    assert a.wait_synced("B", timeout=timeout), "initiator never quiesced"
    assert b.wait_synced("A", timeout=timeout), "responder never quiesced"


class TestConvergence:
    def test_disjoint_stores_union(self):
        a, b = _pair()
        try:
            _fill(a, [(f"a{i}", f"v{i}") for i in range(40)])
            _fill(b, [(f"b{i}", f"w{i}") for i in range(40)])
            _sync(a, b)
            assert a.store == b.store
            assert len(a.store) == 80
        finally:
            stop_all([a, b])

    def test_identical_stores_one_round_trip(self):
        a, b = _pair()
        try:
            items = [(f"k{i}", f"v{i}") for i in range(50)]
            _fill(a, items)
            _fill(b, items)
            before = a.sync_messages_sent + b.sync_messages_sent
            _sync(a, b)
            moved = (a.sync_messages_sent + b.sync_messages_sent) - before
            assert a.store == b.store
            assert moved == 2, f"identical stores moved {moved} messages"
        finally:
            stop_all([a, b])

    def test_small_diff_moves_few_messages(self):
        # The headline property: 1 differing item over a 500-item store
        # costs O(log n) messages, nowhere near 500.
        a, b = _pair()
        try:
            items = [(f"key-{i}", f"val-{i}") for i in range(500)]
            _fill(a, items)
            _fill(b, items)
            _fill(a, [("only-on-a", "x")])
            before = a.sync_messages_sent + b.sync_messages_sent
            _sync(a, b)
            moved = (a.sync_messages_sent + b.sync_messages_sent) - before
            assert b.get("only-on-a") == "x"
            assert a.store == b.store
            assert moved < 40, f"1-item diff moved {moved} messages"
        finally:
            stop_all([a, b])

    def test_conflict_resolves_deterministically_both_sides(self):
        a, b = _pair()
        try:
            _fill(a, [("k", "apple")])
            _fill(b, [("k", "banana")])
            _sync(a, b)
            # Greater serialized value wins on BOTH replicas.
            assert a.get("k") == b.get("k") == "banana"
        finally:
            stop_all([a, b])

    def test_local_put_obeys_convergence_rule(self):
        a, b = _pair()
        try:
            _fill(a, [("k", "zzz")])
            a.put("k", "aaa")  # smaller: must not regress the value
            _fill(a, [("probe", "1")])  # fence: puts are ordered
            assert a.get("k") == "zzz"
        finally:
            stop_all([a, b])


class TestSessionEdges:
    def test_resync_after_new_writes(self):
        a, b = _pair()
        try:
            _fill(a, [("k1", "v1")])
            _sync(a, b)
            _fill(b, [("k2", "v2")])
            _sync(a, b)
            assert a.store == b.store == {"k1": "v1", "k2": "v2"}
        finally:
            stop_all([a, b])

    def test_either_side_may_initiate(self):
        a, b = _pair()
        try:
            _fill(a, [("x", "1")])
            _fill(b, [("y", "2")])
            b.sync_with(b.all_nodes[0])
            assert b.wait_synced("A", timeout=15.0)
            assert a.wait_synced("B", timeout=15.0)
            assert a.store == b.store == {"x": "1", "y": "2"}
        finally:
            stop_all([a, b])

    def test_simultaneous_mutual_initiation(self):
        a, b = _pair()
        try:
            _fill(a, [(f"a{i}", "1") for i in range(30)])
            _fill(b, [(f"b{i}", "2") for i in range(30)])
            a.sync_with(a.all_nodes[0])
            b.sync_with(b.all_nodes[0])
            assert a.wait_synced("B", timeout=15.0)
            assert b.wait_synced("A", timeout=15.0)
            assert a.store == b.store and len(a.store) == 60
        finally:
            stop_all([a, b])

    def test_dead_peer_releases_waiter(self):
        a, b = _pair()
        try:
            _fill(a, [(f"k{i}", "v") for i in range(20)])
            # Kill B the instant the session starts: A must not block
            # for the whole timeout.
            a.sync_with(a.all_nodes[0])
            b.stop()
            b.join(timeout=10.0)
            assert a.wait_synced("B", timeout=10.0), \
                "waiter not released by peer death"
        finally:
            stop_all([a, b])

    def test_plain_traffic_bypasses(self):
        seen = []

        class App(SyncNode):
            def node_message(self, node, data):
                if isinstance(data, dict) and any(
                        k.startswith("_ms_") for k in data):
                    return super().node_message(node, data)
                seen.append(data)

        a = App(HOST, 0, id="A")
        b = App(HOST, 0, id="B")
        for n in (a, b):
            n.start()
        try:
            assert a.connect_with_node(HOST, b.port)
            assert wait_until(lambda: len(b.all_nodes) == 1)
            a.send_to_nodes({"hello": "world"})
            assert wait_until(lambda: {"hello": "world"} in seen)
        finally:
            stop_all([a, b])


class TestReinitiationMidWalk:
    def test_reinitiation_mid_walk_still_converges(self):
        """A fresh ``_ms_root`` landing while our walk with that peer is
        mid-flight must not be dropped: the active walk may already have
        passed the subtree the peer just mutated, so the responder queues
        the root and runs a follow-up walk before releasing anyone
        (sync.py ``_pending_root``).

        Deterministic injection: A answers B's first ``_ms_pull`` only
        AFTER putting a fresh item into a bucket the walk has already
        skipped (both sides held identical items there, so its hashes
        matched) and re-initiating.  Without the queued-root follow-up,
        B's walk completes on stale hashes and never learns the item.
        """
        from p2pnetwork_tpu import sync as sync_mod

        def key_in_bucket(digit, tag):
            i = 0
            while True:
                k = f"{tag}-{i}"
                if sync_mod._key_digest(k).startswith(digit):
                    return k
                i += 1

        injected = {"done": False}

        class InjectingNode(SyncNode):
            def node_message(self, node, data):
                if (isinstance(data, dict) and "_ms_pull" in data
                        and not injected["done"]):
                    injected["done"] = True
                    # Runs on the event loop, interleaved mid-walk:
                    # mutate an already-compared bucket, re-initiate.
                    self._put_local(key_in_bucket("0", "late"), "LATE")
                    self._send(node, {"_ms_root": self._subtree_hash("")})
                return super().node_message(node, data)

        a = InjectingNode(HOST, 0, id="A")
        b = SyncNode(HOST, 0, id="B")
        for n in (a, b):
            n.start()
        try:
            assert a.connect_with_node(HOST, b.port)
            assert wait_until(lambda: len(a.all_nodes) == 1
                              and len(b.all_nodes) == 1)
            # Bucket "0": identical on both sides -> hashes match at the
            # root descent, skipped.  Bucket "f": A-only -> B pulls it,
            # which triggers the injection.
            shared = [(key_in_bucket("0", f"s{i}"), "v") for i in range(3)]
            _fill(a, shared)
            _fill(b, shared)
            _fill(a, [(key_in_bucket("f", "only-a"), "x")])
            _sync(a, b, timeout=20.0)
            assert injected["done"], "injection point never hit"
            assert b.get(key_in_bucket("0", "late")) == "LATE", \
                "queued re-initiation was dropped: stores diverged"
            assert a.store == b.store
        finally:
            stop_all([a, b])


class TestRandomizedConvergence:
    @pytest.mark.parametrize("seed", [0, 4, 13])
    def test_random_stores_converge_to_union_max(self, seed):
        """Property fuzz: random overlapping stores with conflicting
        values; after one session both stores must equal the element-wise
        max of the union — whatever the diff shape (seeded; failures
        replay)."""
        rng = random.Random(seed)
        a, b = _pair()
        try:
            keys = [f"k{rng.randrange(60)}" for _ in range(80)]
            items_a = {k: f"v{rng.randrange(100):03d}"
                       for k in rng.sample(keys, rng.randrange(10, 40))}
            items_b = {k: f"v{rng.randrange(100):03d}"
                       for k in rng.sample(keys, rng.randrange(10, 40))}
            _fill(a, list(items_a.items()))
            _fill(b, list(items_b.items()))
            want = dict(items_a)
            for k, v in items_b.items():
                want[k] = max(want.get(k, v), v)
            _sync(a, b, timeout=20.0)
            assert a.store == b.store == want
        finally:
            stop_all([a, b])
