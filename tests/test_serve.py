"""graftserve: the serving front-end over the batched message plane.

The contract under test (p2pnetwork_tpu/serve/): a submit becomes a
lane, a lane becomes a deterministic result, and nothing about serving
— queueing, pacing, quotas, shedding, crash recovery — changes what a
broadcast computes. The seeded open-loop generator makes whole service
runs replayable (same seed ⇒ byte-identical schedule AND identical
per-ticket summaries), the preempt/resume pair must be bit-identical to
an uninterrupted run with zero lost admitted lanes, saturation must
shed with a structured reject instead of erroring, and the HTTP surface
rides the telemetry httpd next to /metrics. The slow-marked soak proves
the acceptance row: ≥1k concurrent lanes on a 100k-node WS graph across
a mid-run preempt+resume.
"""

import json
import threading  # graftlint: ignore[raw-concurrency-primitive] -- test harness threads, not library code
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from p2pnetwork_tpu import telemetry
from p2pnetwork_tpu.models.messagebatch import (
    BatchFlood, LaneExhausted, free_lane_count)
from p2pnetwork_tpu.serve import (
    QueueFull, QuotaExceeded, Rejected, ServiceClosed, SimService,
    TrafficPattern, drive, generate)
from p2pnetwork_tpu.serve.service import Preempted, _SIDECAR
from p2pnetwork_tpu.sim import engine
from p2pnetwork_tpu.sim import graph as G
from p2pnetwork_tpu.telemetry.httpd import MetricsServer

pytestmark = pytest.mark.serve

KEY = jax.random.key(0)


@pytest.fixture(scope="module")
def ws300():
    return G.watts_strogatz(300, 6, 0.2, seed=3, source_csr=True)


def make_service(g, **kw):
    kw.setdefault("capacity", 32)
    kw.setdefault("queue_depth", 64)
    kw.setdefault("chunk_rounds", 16)  # one WS-300 flood per tick
    kw.setdefault("seed", 0)
    kw.setdefault("registry", telemetry.Registry())
    return SimService(g, **kw)


# ------------------------------------------------- typed backpressure


class TestLaneExhausted:
    def test_admit_raises_typed_with_fields(self, ws300):
        proto = BatchFlood()
        batch = proto.init(ws300, [1, 2, 3], capacity=4)
        with pytest.raises(LaneExhausted) as ei:
            proto.admit(ws300, batch, list(range(40)))
        e = ei.value
        assert e.requested == 40
        # capacity 4 rounds to one 32-lane word; 3 lanes taken
        assert e.capacity == 32
        assert e.free_lanes == 29
        assert "29 open lanes of 32" in str(e)

    def test_back_compat_catchable_as_valueerror(self, ws300):
        # PR-10 callers catch ValueError on admit — the typed subclass
        # must keep them working.
        proto = BatchFlood()
        batch = proto.init(ws300, [1], capacity=1)
        with pytest.raises(ValueError):
            proto.admit(ws300, batch, list(range(64)))
        assert issubclass(LaneExhausted, ValueError)

    def test_free_lane_count(self, ws300):
        proto = BatchFlood()
        batch = proto.empty(ws300, 40)  # rounds to 64
        assert free_lane_count(batch) == 64
        batch, _ = proto.admit(ws300, batch, [1, 2, 3])
        assert free_lane_count(batch) == 61


class TestEngineNewlyCompleted:
    def test_out_carries_newly_completed_lanes(self, ws300):
        proto = BatchFlood()
        batch = proto.init(ws300, [1, 2, 3], capacity=8)
        batch, out = engine.run_batch_until_coverage(
            ws300, proto, batch, KEY, max_rounds=64, donate=False)
        newly = out["newly_completed_lanes"]
        assert newly.dtype == np.int32
        np.testing.assert_array_equal(
            newly, np.flatnonzero(out["lane_done"]))

    def test_resume_excludes_previously_done(self, ws300):
        proto = BatchFlood()
        batch = proto.init(ws300, [1, 2], capacity=8)
        batch, out = engine.run_batch_until_coverage(
            ws300, proto, batch, KEY, max_rounds=64, donate=False)
        assert set(out["newly_completed_lanes"].tolist()) == {0, 1}
        # Second wave into the same batch: only the new lane is "newly".
        batch, lanes = proto.admit(ws300, batch, [7])
        batch, out2 = engine.run_batch_until_coverage(
            ws300, proto, batch, KEY, max_rounds=64, donate=False)
        assert out2["newly_completed_lanes"].tolist() == lanes.tolist()


# ------------------------------------------------------- request plane


class TestRequestPlane:
    def test_submit_tick_poll_lifecycle(self, ws300):
        svc = make_service(ws300)
        tid = svc.submit(5)
        rec = svc.poll(tid)
        assert rec["status"] == "queued"
        assert rec["lane"] is None
        svc.tick()
        rec = svc.poll(tid)
        assert rec["status"] == "done"
        assert rec["rounds"] >= 1
        assert rec["seen_count"] == 300
        assert rec["coverage"] == 1.0
        assert rec["latency_rounds"] == rec["rounds"]  # admitted same tick
        # wall timestamps never land in records (determinism contract)
        assert not any("wall" in k or "time" in k for k in rec)

    def test_poll_unknown_returns_none(self, ws300):
        svc = make_service(ws300)
        assert svc.poll("t-nope") is None

    def test_bad_source_and_target_are_caller_errors(self, ws300):
        svc = make_service(ws300)
        with pytest.raises(ValueError):
            svc.submit(-1)
        with pytest.raises(ValueError):
            svc.submit(10**9)
        with pytest.raises(ValueError):
            svc.submit(1, target_coverage=1.5)

    def test_zero_knobs_rejected_not_misread(self, ws300, tmp_path):
        # Falsy zeros must be loud errors, not the opposite behavior:
        # max_active_lanes=0 is not "full capacity", slo_rounds=0 is
        # not "no pacing", and retain=1 has a trail-losing prune window.
        with pytest.raises(ValueError):
            make_service(ws300, max_active_lanes=0)
        with pytest.raises(ValueError):
            make_service(ws300, slo_rounds=0.0)
        with pytest.raises(ValueError):
            make_service(ws300, store=str(tmp_path), retain=1)

    def test_cancel_queued_and_running(self, ws300):
        # A long path graph keeps lanes running across ticks so a
        # mid-flight cancel has something to cancel.
        g = G.ring(128, source_csr=True)
        svc = make_service(g, chunk_rounds=2)
        t1 = svc.submit(0)
        t2 = svc.submit(1)
        assert svc.cancel(t1) is True           # still queued
        assert svc.poll(t1)["status"] == "cancelled"
        svc.tick()
        assert svc.poll(t2)["status"] == "running"
        assert svc.cancel(t2) is True           # mid-flight
        assert svc.poll(t2)["status"] == "cancelled"
        assert svc.cancel(t2) is False          # already terminal
        svc.tick()  # the cancelled lane is retired and reusable
        t3 = svc.submit(2)
        for _ in range(40):
            svc.tick()
            if svc.poll(t3)["status"] == "done":
                break
        assert svc.poll(t3)["status"] == "done"

    def test_wait_and_stream_block_until_done(self, ws300):
        svc = make_service(ws300).start()
        try:
            tid = svc.submit(3)
            rec = svc.wait(tid, timeout=30.0)
            assert rec["status"] == "done"
            # stream on an already-terminal ticket yields it and stops
            snaps = list(svc.stream(tid, timeout=30.0))
            assert snaps[-1]["status"] == "done"
            with pytest.raises(KeyError):
                svc.wait("t-unknown", timeout=1.0)
        finally:
            svc.close()

    def test_evicted_awaited_ticket_raises_distinct_error(self, ws300):
        # done_retention=1: the first completion is evicted by the
        # second inside the same harvest. A waiter that HAD seen the
        # ticket must get the honest "evicted" error, not "unknown".
        svc = make_service(ws300, done_retention=1)
        a = svc.submit(1)
        svc.submit(2)
        it = svc.stream(a, timeout=5.0)
        assert next(it)["status"] == "queued"
        svc.tick()  # both complete; retention evicts a's record
        with pytest.raises(KeyError, match="evicted"):
            next(it)
        assert svc.poll(a) is None
        with pytest.raises(KeyError, match="unknown"):
            svc.wait("t-never-existed", timeout=0.1)

    def test_closed_service_refuses_submit(self, ws300):
        svc = make_service(ws300)
        tid = svc.submit(1)
        svc.close()
        with pytest.raises(ServiceClosed):
            svc.submit(2)
        with pytest.raises(ServiceClosed):
            svc.tick()
        # cancel is refused too (symmetric): nothing can reach the
        # durable trail after close, so an "accepted" cancellation
        # would be silently lost on resume.
        assert svc.cancel(tid) is False
        assert svc.poll(tid)["status"] == "queued"

    def test_timeout_ticket_frozen_source(self, ws300):
        # A dead (masked-out) source floods nothing and would spin
        # forever; max_ticket_rounds cuts it off as "timeout".
        from p2pnetwork_tpu.sim import failures
        g = failures.kill_nodes(ws300, [7])
        svc = make_service(g, chunk_rounds=4, max_ticket_rounds=8)
        tid = svc.submit(7)
        for _ in range(5):
            svc.tick()
        rec = svc.poll(tid)
        assert rec["status"] == "timeout"
        assert rec["rounds"] >= 8
        assert svc.stats()["timeout"] == 1


# -------------------------------------------------- shedding and quotas


class TestLoadShedding:
    def test_queue_full_structured_reject(self, ws300):
        reg = telemetry.Registry()
        svc = make_service(ws300, queue_depth=2, max_active_lanes=1,
                           registry=reg)
        accepted = 0
        got = None
        for i in range(6):
            try:
                svc.submit(i)
                accepted += 1
            except QueueFull as e:
                got = e
                break
        assert accepted == 2
        assert isinstance(got, Rejected)
        d = got.to_dict()
        assert d["reason"] == "queue_full"
        assert d["queue_depth"] == 2 and d["queue_limit"] == 2
        assert d["capacity"] == svc.capacity
        assert reg.value("serve_rejected_total", reason="queue_full") == 1
        # sheds are counted, not admitted
        assert svc.stats()["rejected"] == 1
        assert svc.stats()["submitted"] == 2

    def test_quota_bucket_rejects_and_refills_per_tick(self, ws300):
        reg = telemetry.Registry()
        svc = make_service(ws300, quotas={"m": (1.0, 2.0)}, registry=reg)
        svc.submit(1, tenant="m")
        svc.submit(2, tenant="m")  # burst of 2
        with pytest.raises(QuotaExceeded) as ei:
            svc.submit(3, tenant="m")
        assert ei.value.to_dict()["tenant"] == "m"
        assert reg.value("serve_rejected_total", reason="quota") == 1
        # unlimited tenants are untouched
        svc.submit(4, tenant="other")
        svc.tick()  # refills 1 token
        svc.submit(5, tenant="m")
        with pytest.raises(QuotaExceeded):
            svc.submit(6, tenant="m")

    def test_rejects_never_error_the_service(self, ws300):
        # Saturate hard: the service keeps serving through sheds.
        svc = make_service(ws300, capacity=8, queue_depth=4)
        ok, shed = [], 0
        for i in range(200):
            try:
                ok.append(svc.submit(i % 300))
            except Rejected:
                shed += 1
        assert shed > 0
        for _ in range(64):
            if not svc.busy():
                break
            svc.tick()
        assert all(svc.poll(t)["status"] == "done" for t in ok)


class TestAdmissionPacing:
    def test_max_active_lanes_caps_concurrency(self):
        g = G.ring(64, source_csr=True)  # long diameter: lanes span ticks
        svc = make_service(g, capacity=32, max_active_lanes=3,
                           chunk_rounds=2, queue_depth=64)
        for i in range(12):
            svc.submit(i * 5)
        peak = 0
        for _ in range(300):
            info = svc.tick()
            peak = max(peak, info["running"])
            if not svc.busy():
                break
        assert peak <= 3
        assert not svc.busy()

    def test_aimd_halves_budget_past_slo(self, ws300):
        # WS floods complete in ~6 rounds; slo_rounds=1 makes every
        # completing chunk over-SLO, so the budget must fall
        # (chunk_rounds=16 so the first tick carries a completion p99).
        svc = make_service(ws300, capacity=32, slo_rounds=1.0,
                           chunk_rounds=16)
        svc.submit(1)
        svc.tick()
        assert svc.stats()["admit_budget"] == 16  # 32 // 2
        # additive recovery on healthy ticks needs a completing chunk
        # under SLO — relax the SLO and complete another ticket.
        svc.slo_rounds = 1000.0
        svc.submit(2)
        svc.tick()
        assert svc.stats()["admit_budget"] > 16


# ------------------------------------------------------- traffic plane


class TestTraffic:
    def test_same_seed_byte_identical_schedule(self, ws300):
        pat = TrafficPattern(ticks=20, rate=4.0, hot_fraction=0.7,
                             hot_keys=5, diurnal_amplitude=0.5,
                             burst_prob=0.3, tenants=("a", "b"))
        s1 = generate(pat, ws300.n_nodes, seed=11)
        s2 = generate(pat, ws300.n_nodes, seed=11)
        assert s1.to_bytes() == s2.to_bytes()
        s3 = generate(pat, ws300.n_nodes, seed=12)
        assert s1.to_bytes() != s3.to_bytes()

    def test_hot_key_skew_concentrates_sources(self):
        pat = TrafficPattern(ticks=200, rate=8.0, hot_fraction=1.0,
                             hot_keys=4, zipf_s=1.5)
        s = generate(pat, 10_000, seed=0)
        uniq, counts = np.unique(s.source, return_counts=True)
        assert uniq.size == 4  # every arrival from the hot set
        # Zipf: the hottest key dominates a uniform split.
        assert counts.max() > len(s) / 4 * 1.5

    def test_arrivals_partition_the_schedule(self):
        pat = TrafficPattern(ticks=10, rate=3.0)
        s = generate(pat, 100, seed=5)
        total = sum(len(s.arrivals_at(t)) for t in range(pat.ticks))
        assert total == len(s)

    def test_pattern_validation(self):
        with pytest.raises(ValueError):
            TrafficPattern(ticks=0)
        with pytest.raises(ValueError):
            TrafficPattern(hot_fraction=1.5)
        with pytest.raises(ValueError):
            TrafficPattern(tenants=())
        # coverage_target is validated at construction like every other
        # knob — not mid-drive by the first submit it reaches.
        with pytest.raises(ValueError):
            TrafficPattern(coverage_target=0.0)
        with pytest.raises(ValueError):
            TrafficPattern(coverage_target=1.5)
        with pytest.raises(ValueError):
            TrafficPattern(burst_prob=1.5)
        with pytest.raises(ValueError):
            TrafficPattern(burst_mult=-2.0)
        with pytest.raises(ValueError):
            TrafficPattern(hot_keys=0)
        with pytest.raises(ValueError):
            TrafficPattern(diurnal_period=0.0)

    def test_drive_refuses_a_started_service(self, ws300):
        # drive() ticks synchronously; racing the background driver
        # would corrupt the driver-confined batch — enforced, not just
        # documented.
        svc = make_service(ws300).start()
        try:
            sched = generate(TrafficPattern(ticks=2, rate=1.0),
                             ws300.n_nodes, seed=0)
            with pytest.raises(RuntimeError, match="background thread"):
                drive(svc, sched)
        finally:
            svc.close()

    def test_shed_counts_survive_resume(self, ws300, tmp_path):
        # Rejections after the last boundary checkpoint must reach the
        # final close() pair like every other counter.
        svc = make_service(ws300, store=str(tmp_path), resume=False,
                           queue_depth=1, max_active_lanes=1)
        svc.submit(1)
        svc.tick()
        svc.submit(2)          # fills the depth-1 queue
        with pytest.raises(QueueFull):
            svc.submit(3)      # shed after the last checkpoint
        svc.close()
        res = make_service(ws300, store=str(tmp_path), resume=True,
                           queue_depth=1, max_active_lanes=1)
        assert res.stats()["rejected"] == 1

    def test_two_service_runs_identical_summaries(self, ws300):
        # The acceptance determinism row: same seed ⇒ identical
        # per-ticket completion summaries across two FULL service runs,
        # sheds and quota decisions included.
        pat = TrafficPattern(ticks=10, rate=6.0, hot_fraction=0.5,
                             hot_keys=4, burst_prob=0.25,
                             tenants=("a", "b"))
        sched = generate(pat, ws300.n_nodes, seed=3)

        def run():
            svc = make_service(ws300, capacity=16, queue_depth=8,
                               quotas={"b": (2.0, 4.0)},
                               record_seen_hash=True)
            out = drive(svc, sched)
            return svc.tickets(), out

        t1, o1 = run()
        t2, o2 = run()
        assert t1 == t2
        assert o1["shed"] == o2["shed"]
        assert o1["completed"] == o2["completed"]
        assert o1["peak_concurrent_lanes"] == o2["peak_concurrent_lanes"]
        assert any(rec.get("seen_sha256") for rec in t1.values())


# ----------------------------------------------------- crash tolerance


class TestCrashTolerance:
    def _pattern(self):
        return TrafficPattern(ticks=12, rate=5.0, hot_fraction=0.6,
                              hot_keys=4, burst_prob=0.2)

    def _svc(self, g, store=None, resume=True):
        return make_service(g, store=store, resume=resume,
                            chunk_rounds=4, record_seen_hash=True)

    def test_preempt_resume_bit_identical(self, ws300, tmp_path):
        sched = generate(self._pattern(), ws300.n_nodes, seed=7)
        ref = self._svc(ws300)
        drive(ref, sched)

        svc = self._svc(ws300, store=str(tmp_path), resume=False)
        svc.arm_preemption(6)
        with pytest.raises(Preempted):
            drive(svc, sched)
        # Mid-flight kill: some tickets were admitted (running) when it
        # fired — those are the lanes that must not be lost.
        killed = svc.tickets()
        assert any(r["status"] in ("running", "queued")
                   for r in killed.values())

        res = self._svc(ws300, store=str(tmp_path), resume=True)
        assert res.tick_index == 5  # checkpoint of the tick before
        drive(res, sched)
        assert ref.tickets() == res.tickets()  # seen hashes included
        done = [r for r in res.tickets().values() if r["status"] == "done"]
        assert len(done) == len(res.tickets())  # zero lost lanes

    def test_sidecar_references_exact_checkpoint(self, ws300, tmp_path):
        svc = self._svc(ws300, store=str(tmp_path), resume=False)
        svc.submit(1)
        svc.tick()
        side = json.loads((tmp_path / _SIDECAR).read_text())
        assert (tmp_path / side["checkpoint_file"]).exists()
        assert side["tick"] == 1
        assert side["tickets"]

    def test_resume_false_clears_previous_trail(self, ws300, tmp_path):
        svc = self._svc(ws300, store=str(tmp_path), resume=False)
        svc.submit(1)
        svc.tick()
        assert (tmp_path / _SIDECAR).exists()
        fresh = self._svc(ws300, store=str(tmp_path), resume=False)
        assert fresh.tick_index == 0
        assert not (tmp_path / _SIDECAR).exists()
        assert fresh.tickets() == {}

    def test_damaged_checkpoint_is_fresh_start(self, ws300, tmp_path):
        svc = self._svc(ws300, store=str(tmp_path), resume=False)
        svc.submit(1)
        svc.tick()
        side = json.loads((tmp_path / _SIDECAR).read_text())
        (tmp_path / side["checkpoint_file"]).write_bytes(b"garbage")
        res = self._svc(ws300, store=str(tmp_path), resume=True)
        assert res.tick_index == 0
        assert res.tickets() == {}

    def test_resume_with_mismatched_capacity_is_a_caller_error(
            self, ws300, tmp_path):
        # ckpt.load's treedef check is shape-blind (MessageBatch is
        # all-array), so a capacity/graph mismatch must be caught
        # explicitly — as a caller error that PRESERVES the trail, not
        # a silent fresh start that discards real tickets.
        svc = make_service(ws300, store=str(tmp_path), resume=False,
                           capacity=32)
        tid = svc.submit(1)
        svc.tick()
        with pytest.raises(ValueError, match="different capacity"):
            make_service(ws300, store=str(tmp_path), resume=True,
                         capacity=64)
        res = make_service(ws300, store=str(tmp_path), resume=True,
                           capacity=32)
        assert res.poll(tid)["status"] == "done"

    def test_resumed_service_reuses_ticket_ids(self, ws300, tmp_path):
        sched = generate(self._pattern(), ws300.n_nodes, seed=7)
        svc = self._svc(ws300, store=str(tmp_path), resume=False)
        svc.arm_preemption(4)
        with pytest.raises(Preempted):
            drive(svc, sched)
        res = self._svc(ws300, store=str(tmp_path), resume=True)
        before = set(res.tickets())
        drive(res, sched)
        after = set(res.tickets())
        # Re-submitted arrivals reclaim the SAME deterministic ids the
        # killed run handed out (persisted counter).
        assert before <= after
        assert all(t.startswith("t") for t in after)


class _ProtocolHook:
    """Delegating BatchFlood wrapper firing a one-shot callback at a
    chosen seam — the deterministic stand-in for a cancel() landing
    mid-tick from another thread, inside the windows the driver's lock
    does not cover (between the retire/admission device phases and
    their bookkeeping)."""

    def __init__(self, inner):
        self._inner = inner
        self.on_admit = None
        self.on_retire = None

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def admit(self, *a, **kw):
        if self.on_admit is not None:
            cb, self.on_admit = self.on_admit, None
            cb()
        return self._inner.admit(*a, **kw)

    def retire(self, *a, **kw):
        out = self._inner.retire(*a, **kw)
        if self.on_retire is not None:
            cb, self.on_retire = self.on_retire, None
            cb()
        return out


class TestConcurrentCancelWindows:
    def test_cancel_mid_admission_recycles_not_crashes(self, ws300):
        # The window: tick() popped the ticket from the queue (status
        # "running", lane still None) but has not assigned its lane. A
        # cancel() here used to append lane=None to the retire list —
        # TypeError on the next tick, driver dead — and the late lane
        # mapping would flip the cancelled ticket back to "done".
        svc = make_service(ws300)
        hook = _ProtocolHook(svc._protocol)
        svc._protocol = hook
        t1 = svc.submit(1)
        hook.on_admit = lambda: svc.cancel(t1)
        svc.tick()
        assert svc.poll(t1)["status"] == "cancelled"
        svc.tick()  # the retire of the recycled lane must not crash
        t2 = svc.submit(2)
        for _ in range(5):
            svc.tick()
            if svc.poll(t2)["status"] == "done":
                break
        assert svc.poll(t2)["status"] == "done"
        assert svc.poll(t1)["status"] == "cancelled"  # never resurrected

    def test_cancel_plus_eviction_mid_admission(self, ws300):
        # Worst case in the admission gap: the popped ticket is not just
        # cancelled but EVICTED (tiny done_retention) before the lane
        # mapping re-acquires the lock — the driver used to die on a
        # KeyError; the lane must just recycle.
        svc = make_service(ws300, done_retention=1)
        hook = _ProtocolHook(svc._protocol)
        svc._protocol = hook
        t1 = svc.submit(1)

        def cancel_and_evict():
            svc.cancel(t1)           # terminal
            t2 = svc.submit(9)       # queued
            svc.cancel(t2)           # terminal -> evicts t1 (retention 1)
            assert svc.poll(t1) is None

        hook.on_admit = cancel_and_evict
        svc.tick()  # must not KeyError the driver path
        svc.tick()  # recycled lane retires cleanly
        t3 = svc.submit(3)
        for _ in range(3):
            svc.tick()
            if svc.poll(t3)["status"] == "done":
                break
        assert svc.poll(t3)["status"] == "done"

    def test_cancel_between_retire_and_admission_keeps_driver_alive(self):
        # The window: tick() applied its retire snapshot, then a cancel
        # pops a lane from the running map while the lane is STILL
        # admitted on device (until the next tick's retire). Counting
        # it free used to over-admit and kill the driver with the
        # "unreachable" LaneExhausted.
        g = G.ring(128, source_csr=True)
        svc = make_service(g, capacity=32, chunk_rounds=4, queue_depth=64)
        hook = _ProtocolHook(svc._protocol)
        svc._protocol = hook
        tids = [svc.submit(i) for i in range(32)]  # fill every lane
        svc.tick()
        victim = tids[0]
        svc.cancel(tids[1])  # gives tick 2 a retire step to hook
        hook.on_retire = lambda: svc.cancel(victim)
        more = [svc.submit(64 + i) for i in range(32)]
        svc.tick()  # must NOT die with LaneExhausted
        assert svc.poll(victim)["status"] == "cancelled"
        for _ in range(200):
            if not svc.busy():
                break
            svc.tick()
        assert all(svc.poll(t)["status"] in ("done", "cancelled")
                   for t in tids + more)


class TestCloseCheckpoint:
    def test_close_persists_post_boundary_submissions(self, ws300,
                                                      tmp_path):
        # Submissions accepted after the last tick's checkpoint must
        # survive a clean close: the final pair keeps them queued and
        # keeps the ticket counter from re-issuing their ids.
        svc = make_service(ws300, store=str(tmp_path), resume=False)
        t_early = svc.submit(1)
        svc.tick()
        t_late = svc.submit(2)
        svc.close()
        res = make_service(ws300, store=str(tmp_path), resume=True)
        assert res.poll(t_early)["status"] == "done"
        assert res.poll(t_late)["status"] == "queued"
        t_next = res.submit(3)
        assert t_next not in (t_early, t_late)
        res.tick()
        assert res.poll(t_late)["status"] == "done"

    def test_instantly_done_submission_completes_not_leaks(self, ws300):
        # A seed that already meets the target starts its lane done at
        # admission; the engine never reports it as newly completed, so
        # the service must complete the ticket AT admission — it used
        # to pin "running" forever while its lane leaked.
        svc = make_service(ws300, capacity=32, record_seen_hash=True)
        tids = [svc.submit(i, target_coverage=0.001) for i in range(3)]
        svc.tick()
        for tid in tids:
            rec = svc.poll(tid)
            assert rec["status"] == "done"
            assert rec["rounds"] == 0
            assert rec["seen_count"] == 1  # the seed alone met 0.1%
            assert "seen_sha256" in rec
        svc.tick()  # lanes recycled: capacity fully reusable
        t2 = svc.submit(5)
        svc.tick()
        assert svc.poll(t2)["status"] == "done"
        assert svc.stats()["completed"] == 4
        assert svc.stats()["active_lanes"] == 0

    def test_idle_ticks_do_not_rewrite_the_trail(self, ws300, tmp_path):
        # An idle background driver ticks every idle_wait_s for quota
        # refill; with nothing changed it must not re-serialize the
        # batch + sidecar each time.
        svc = make_service(ws300, store=str(tmp_path), resume=False)
        svc.submit(1)
        svc.tick()
        svc.tick()  # retires the harvested lane (a real state change)
        side = (tmp_path / _SIDECAR).read_bytes()
        entries = sorted(p.name for p in tmp_path.glob("ckpt_r*.npz"))
        for _ in range(3):
            svc.tick()  # idle: nothing queued, running or retiring
        assert (tmp_path / _SIDECAR).read_bytes() == side
        assert sorted(p.name
                      for p in tmp_path.glob("ckpt_r*.npz")) == entries
        svc.close()  # clean close with nothing new: also no rewrite
        assert (tmp_path / _SIDECAR).read_bytes() == side

    def test_failed_checkpoint_restores_dirty_for_close(self, ws300,
                                                        tmp_path):
        # A save that dies mid-publish must NOT leave the state marked
        # clean — close()'s final checkpoint would silently skip and
        # the whole trail would be lost.
        svc = make_service(ws300, store=str(tmp_path), resume=False)
        tid = svc.submit(1)
        orig_save = svc._store.save

        def boom(*a, **k):
            raise OSError("disk full")

        svc._store.save = boom
        with pytest.raises(OSError):
            svc.tick()
        svc._store.save = orig_save
        svc.close()  # dirty was restored: the final pair publishes
        res = make_service(ws300, store=str(tmp_path), resume=True)
        assert res.poll(tid)["status"] == "done"

    def test_preempted_service_never_checkpoints_on_close(self, ws300,
                                                          tmp_path):
        # A fired preemption simulates a SIGKILL: close() afterwards
        # must NOT publish a post-kill pair (resume wants the durable
        # state from BEFORE the kill).
        svc = make_service(ws300, store=str(tmp_path), resume=False)
        svc.submit(1)
        svc.tick()
        svc.arm_preemption(2)
        svc.submit(2)
        with pytest.raises(Preempted):
            svc.tick()
        side_before = (tmp_path / _SIDECAR).read_bytes()
        svc.close()
        assert (tmp_path / _SIDECAR).read_bytes() == side_before


class TestAIMDStall:
    def test_stalled_chunks_shrink_never_grow_the_budget(self):
        # A chunk that completes nothing carries no p99; it must never
        # earn additive increase, and once the oldest running lane is
        # past the SLO that silence IS the overload signal.
        g = G.ring(256, source_csr=True)  # ~127 rounds to target
        svc = make_service(g, capacity=32, chunk_rounds=4, slo_rounds=8.0)
        svc.submit(0)
        b0 = svc.stats()["admit_budget"]
        svc.tick()  # oldest 4 <= slo: no evidence, hold
        svc.tick()  # oldest 8 <= slo: hold
        assert svc.stats()["admit_budget"] == b0
        svc.tick()  # oldest 12 > slo: the stall halves the budget
        assert svc.stats()["admit_budget"] == max(1, b0 // 2)


# --------------------------------------------------------- HTTP plane


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read().decode())


def _post(url, doc=None, timeout=10):
    data = json.dumps(doc or {}).encode()
    req = urllib.request.Request(
        url, data=data, method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read().decode())


class TestHTTP:
    def test_submit_poll_stats_cancel_endpoints(self, ws300):
        reg = telemetry.Registry()
        svc = make_service(ws300, registry=reg).start()
        try:
            # One server, both planes: /metrics scrapes the same
            # registry the service reports into, /submit etc. beside it.
            with MetricsServer(registry=reg, port=0, service=svc) as srv:
                base = f"http://127.0.0.1:{srv.port}"
                code, resp = _post(base + "/submit", {"source": 3})
                assert code == 202 and resp["ticket"] == "t00000000"
                rec = svc.wait(resp["ticket"], timeout=30.0)
                assert rec["status"] == "done"
                code, polled = _get(base + f"/poll/{resp['ticket']}")
                assert code == 200 and polled["status"] == "done"
                # GET convenience form for curl one-liners
                code, r2 = _get(base + "/submit?source=4&tenant=cli")
                assert code == 202
                svc.wait(r2["ticket"], timeout=30.0)
                code, stats = _get(base + "/stats")
                assert code == 200 and stats["completed"] >= 2
                code, c = _post(base + f"/cancel/{r2['ticket']}")
                assert code == 200 and c["cancelled"] is False
                # telemetry endpoints still live next to the service
                met = urllib.request.urlopen(base + "/metrics").read()
                assert b"serve_completed_total" in met
        finally:
            svc.close()

    def test_http_errors_are_structured(self, ws300):
        svc = make_service(ws300, queue_depth=0, max_active_lanes=1)
        with MetricsServer(port=0, service=svc) as srv:
            base = f"http://127.0.0.1:{srv.port}"
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(base + "/poll/t-unknown")
            assert ei.value.code == 404
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(base + "/submit", {})
            assert ei.value.code == 400
            # queue_depth=0: every submit sheds as a 429 with the
            # structured reject payload
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(base + "/submit", {"source": 1})
            assert ei.value.code == 429
            doc = json.loads(ei.value.read().decode())
            assert doc["reason"] == "queue_full"
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(base + "/definitely-not-a-route")
            assert ei.value.code == 404

    def test_unbound_metrics_server_unaffected(self):
        # No service bound: the new routes 404 and the old ones work.
        with MetricsServer(port=0) as srv:
            base = f"http://127.0.0.1:{srv.port}"
            urllib.request.urlopen(base + "/metrics").read()
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(base + "/stats")
            assert ei.value.code == 404


class TestMetricsServerLifecycle:
    def test_ephemeral_port_reported_and_rebound(self):
        srv = MetricsServer(port=0)
        srv.start()
        p1 = srv.port
        assert p1 != 0
        urllib.request.urlopen(srv.url, timeout=5).read()
        srv.close()
        srv.start()  # close() released the port; start() rebinds
        assert srv.port != 0
        urllib.request.urlopen(srv.url, timeout=5).read()
        srv.close()

    def test_close_idempotent(self):
        srv = MetricsServer(port=0).start()
        srv.close()
        srv.close()
        srv.stop()  # alias, still a no-op

    def test_concurrent_start_close_settles_clean(self):
        # The satellite pin: racing start/close pairs from several
        # threads must neither crash, deadlock, nor leak a bound server.
        srv = MetricsServer(port=0)
        errors = []

        def churn(n):
            try:
                for i in range(8):
                    if (i + n) % 2:
                        srv.start()
                    else:
                        srv.close()
            except Exception as e:  # pragma: no cover - the failure mode
                errors.append(e)

        threads = [threading.Thread(target=churn, args=(n,))
                   for n in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors
        srv.close()
        assert srv._httpd is None
        # and the server still works after the storm
        srv.start()
        urllib.request.urlopen(srv.url, timeout=5).read()
        srv.close()


# ----------------------------------------------------------- telemetry


class TestServeTelemetry:
    def test_serve_metrics_registered_and_counted(self, ws300):
        reg = telemetry.Registry()
        svc = make_service(ws300, registry=reg,
                           quotas={"alpha": (100.0, 100.0)})
        tid = svc.submit(1, tenant="alpha")
        svc.tick()
        assert svc.poll(tid)["status"] == "done"
        # Configured tenants get their own label child; arbitrary
        # client-supplied tenant strings collapse to "other" so the
        # HTTP surface cannot mint unbounded metric cardinality (the
        # ticket record keeps the raw tenant either way).
        assert reg.value("serve_submitted_total", tenant="alpha") == 1
        t2 = svc.submit(2, tenant="some-random-uuid")
        assert reg.value("serve_submitted_total", tenant="other") == 1
        assert reg.value("serve_submitted_total",
                         tenant="some-random-uuid") == 0
        assert svc.poll(t2)["tenant"] == "some-random-uuid"
        assert reg.value("serve_completed_total") == 1
        assert reg.value("serve_ticks_total") == 1
        assert reg.value("serve_completion_rounds") == 1  # histogram count
        assert reg.value("serve_latency_seconds") == 1
        snap = reg.snapshot()
        assert snap["serve_queue_depth"]["type"] == "gauge"
        assert snap["serve_active_lanes"]["type"] == "gauge"
        assert snap["serve_admit_budget"]["type"] == "gauge"


# ------------------------------------------------------ acceptance soak


@pytest.mark.slow
class TestServingSoak:
    def test_1k_concurrent_lanes_preempt_resume_100k(self, tmp_path):
        # The acceptance row end to end: seeded open-loop traffic on a
        # 100k-node WS graph sustains >= 1k concurrent lanes with
        # published submit→completion p50/p99; a mid-flight kill +
        # supervised resume completes every admitted ticket with
        # per-lane results (seen hashes included) bit-identical to an
        # uninterrupted run; oversubscription sheds structurally
        # instead of erroring.
        g = G.watts_strogatz(100_000, 10, 0.1, seed=0, source_csr=True)
        pat = TrafficPattern(ticks=8, rate=700.0, hot_fraction=0.5,
                             hot_keys=32, burst_prob=0.25, burst_mult=2.0,
                             coverage_target=0.99)
        sched = generate(pat, g.n_nodes, seed=0)

        def svc(store=None, resume=True):
            return SimService(
                g, capacity=1024, queue_depth=2048, chunk_rounds=2,
                seed=0, store=store, resume=resume,
                record_seen_hash=True, registry=telemetry.Registry())

        ref = svc()
        out_ref = drive(ref, sched)
        assert out_ref["peak_concurrent_lanes"] >= 1000
        stats = ref.stats()
        assert stats["completion_rounds_p50"] >= 1
        assert stats["completion_rounds_p99"] >= \
            stats["completion_rounds_p50"]

        killed = svc(store=str(tmp_path), resume=False)
        # Tick 6 lands mid-wave (the t0 cohort completes together at
        # tick 5 and a fresh 1024-lane wave admits right after), so the
        # kill catches genuinely in-flight lanes.
        killed.arm_preemption(6)
        with pytest.raises(Preempted):
            drive(killed, sched)
        admitted_at_kill = [r for r in killed.tickets().values()
                            if r["status"] == "running"]
        assert admitted_at_kill  # the kill was genuinely mid-flight

        res = svc(store=str(tmp_path), resume=True)
        out_res = drive(res, sched)
        assert ref.tickets() == res.tickets()
        assert out_res["completed"] + len(out_res["shed"]) > 0
        # zero dropped admitted lanes: every ticket ever admitted is done
        done = sum(1 for r in res.tickets().values()
                   if r["status"] == "done")
        assert done == len(res.tickets())
