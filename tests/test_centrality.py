"""Brandes betweenness vs the networkx oracle: exact (all sources) on
several graph families, the sampled estimator's scaling, dead-node
masking, and lowering-independence."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import networkx as nx  # noqa: E402

from p2pnetwork_tpu.models import betweenness_sample  # noqa: E402
from p2pnetwork_tpu.sim import failures  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def _nx_graph(g):
    s = np.asarray(g.senders)
    r = np.asarray(g.receivers)
    em = (np.asarray(g.edge_mask)
          & np.asarray(g.node_mask)[s] & np.asarray(g.node_mask)[r])
    H = nx.Graph()
    H.add_nodes_from(np.nonzero(np.asarray(g.node_mask))[0].tolist())
    H.add_edges_from(zip(s[em].tolist(), r[em].tolist()))
    return H


def _exact(g, method="auto"):
    # All live nodes as sources = exact betweenness (directed-sum
    # convention: 2x the undirected unordered-pair count).
    src = np.nonzero(np.asarray(g.node_mask))[0].astype(np.int32)
    return np.asarray(betweenness_sample(g, src, method=method))


def _oracle(g):
    H = _nx_graph(g)
    bc = nx.betweenness_centrality(H, normalized=False)
    out = np.zeros(g.n_nodes_padded, dtype=np.float64)
    for v, x in bc.items():
        out[v] = 2.0 * x  # undirected nx counts each pair once
    return out


class TestBetweennessExact:
    @pytest.mark.parametrize("build", [
        lambda: G.watts_strogatz(60, 4, 0.2, seed=3),
        lambda: G.erdos_renyi(48, 0.12, seed=5),
        lambda: G.kademlia(40, k=1),
        lambda: G.ring(16),
    ])
    def test_matches_networkx(self, build):
        g = build()
        got = _exact(g)
        want = _oracle(g)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_star_center_dominates(self):
        # K_{1,6}: every pair routes through the hub; leaves are 0.
        n = 7
        s = np.array([0] * 6 + list(range(1, 7)), dtype=np.int32)
        r = np.array(list(range(1, 7)) + [0] * 6, dtype=np.int32)
        g = G.from_edges(s, r, n)
        got = _exact(g)
        assert got[0] == pytest.approx(6 * 5)  # 30 ordered pairs via hub
        assert np.allclose(got[1:7], 0.0)

    def test_lowering_independence(self):
        g = G.watts_strogatz(64, 4, 0.1, seed=9)
        a = _exact(g, method="segment")
        b = _exact(g, method="gather")
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)

    def test_dead_nodes_excluded(self):
        g = G.watts_strogatz(40, 4, 0.2, seed=7)
        g = failures.fail_nodes(g, np.array([5, 11, 23]))
        got = _exact(g)
        want = _oracle(g)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
        assert got[5] == got[11] == got[23] == 0.0

    def test_dead_source_contributes_nothing(self):
        g = G.watts_strogatz(32, 4, 0.2, seed=1)
        g = failures.fail_nodes(g, np.array([3]))
        with_dead = np.asarray(betweenness_sample(g, np.array([0, 3, 7])))
        without = np.asarray(betweenness_sample(g, np.array([0, 7])))
        np.testing.assert_allclose(with_dead, without, rtol=1e-6)


class TestCloseness:
    def test_harmonic_matches_networkx(self):
        from p2pnetwork_tpu.models import closeness_sample

        for build in (lambda: G.watts_strogatz(60, 4, 0.2, seed=3),
                      lambda: G.erdos_renyi(48, 0.12, seed=5)):
            g = build()
            src = np.nonzero(np.asarray(g.node_mask))[0].astype(np.int32)
            got = np.asarray(closeness_sample(g, src))
            H = _nx_graph(g)
            want = np.zeros(g.n_nodes_padded)
            for v, x in nx.harmonic_centrality(H).items():
                want[v] = x
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_harmonic_disconnected_finite(self):
        from p2pnetwork_tpu.models import closeness_sample

        # Two components: harmonic centrality stays finite and only
        # counts reachable pairs.
        s = np.array([0, 1, 2, 3], dtype=np.int32)
        r = np.array([1, 0, 3, 2], dtype=np.int32)
        g = G.from_edges(s, r, 4)
        src = np.arange(4, dtype=np.int32)
        got = np.asarray(closeness_sample(g, src))
        assert np.allclose(got[:4], 1.0)  # one neighbor at distance 1

    def test_classic_star(self):
        from p2pnetwork_tpu.models import closeness_sample

        # K_{1,5}: hub at distance 1 from all; leaves at 1 + 4*2.
        s = np.array([0] * 5 + list(range(1, 6)), dtype=np.int32)
        r = np.array(list(range(1, 6)) + [0] * 5, dtype=np.int32)
        g = G.from_edges(s, r, 6)
        src = np.arange(6, dtype=np.int32)
        got = np.asarray(closeness_sample(g, src, harmonic=False))
        assert got[0] == pytest.approx(5 / 5)  # hub: 5 reached / dist 5
        assert got[1] == pytest.approx(5 / 9)  # leaf: 5 reached / dist 9

    def test_sampled_estimator_full_sample_exact(self):
        from p2pnetwork_tpu.models import closeness_sample

        g = G.erdos_renyi(40, 0.15, seed=2)
        src = np.nonzero(np.asarray(g.node_mask))[0].astype(np.int32)
        est = np.asarray(closeness_sample(g, src, normalized=True))
        exact = np.asarray(closeness_sample(g, src))
        np.testing.assert_allclose(est, exact, rtol=1e-5)

    def test_dead_nodes_zero(self):
        from p2pnetwork_tpu.models import closeness_sample

        g = G.watts_strogatz(40, 4, 0.2, seed=7)
        g = failures.fail_nodes(g, np.array([5, 11]))
        src = np.nonzero(np.asarray(g.node_mask))[0].astype(np.int32)
        got = np.asarray(closeness_sample(g, src))
        assert got[5] == got[11] == 0.0


class TestBetweennessSampled:
    def test_normalized_estimator_unbiased_at_full_sample(self):
        g = G.erdos_renyi(40, 0.15, seed=2)
        src = np.nonzero(np.asarray(g.node_mask))[0].astype(np.int32)
        est = np.asarray(betweenness_sample(g, src, normalized=True))
        exact = _exact(g)
        # Full sample: rescale factor is n/n = 1.
        np.testing.assert_allclose(est, exact, rtol=1e-5)

    def test_sampled_tracks_exact_ranking(self):
        g = G.watts_strogatz(128, 4, 0.05, seed=4)
        exact = _exact(g)
        rng = np.random.default_rng(0)
        src = rng.choice(128, size=48, replace=False).astype(np.int32)
        est = np.asarray(betweenness_sample(g, src, normalized=True))
        # The estimator needn't match pointwise at this sample size, but
        # the top-decile hub sets should overlap substantially.
        top_true = set(np.argsort(exact)[-13:].tolist())
        top_est = set(np.argsort(est)[-13:].tolist())
        assert len(top_true & top_est) >= 7
