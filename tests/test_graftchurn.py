"""graftchurn: live overlay growth mid-service, churn storms, and
repad-safe recovery.

The load-bearing claims, bottom of the stack to the top:

- **Growth bit-identity** (sim/graph.py): ``Graph.grow`` must produce
  exactly the arrays a from-scratch ``from_edges`` of the same edge
  list at the grown capacity would — across plain/weighted/capped/CSR/
  blocked layouts, both host paths (native and ``force_fallback()``),
  with the geometric capacity schedule keeping K growth steps to
  O(log K) repads.
- **Repad-safe recovery** (sim/checkpoint.py + supervise):
  ``checkpoint.load(grow=True)`` zero-extends a pre-repad entry into
  the grown template, and a ``SupervisedRun`` resumed onto the grown
  graph is BIT-IDENTICAL to one that ran on it uninterrupted (zero is
  the canonical value for dead padding, and the runner's chunk-key
  schedule is a pure function of the round index).
- **Live mutations mid-service** (serve/service.py): ``grow`` /
  ``apply_delta`` queue and land atomically at the next tick's
  ``mutate`` phase — tickets completed before a mutation are
  byte-identical to a never-mutated run, in-flight lanes terminate
  structurally (never leak), endpoint errors are typed, and the
  checkpoint sidecar's graph fingerprint refuses the wrong overlay
  while replaying recorded growth steps.
- **Churn storms** (chaos/storm.py): one seed → a byte-replayable
  join/leave/grow schedule, driveable deterministically against the
  service, interleaved with traffic — and the slow-marked 100k soak
  serves a storm through graftquake dispatch faults healed mid-storm,
  bit-identical to the unfaulted interleaving.
"""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from p2pnetwork_tpu import native, telemetry  # noqa: E402
from p2pnetwork_tpu.chaos.device import (  # noqa: E402
    DispatchChaos, FaultSchedule, FaultSpec, UnreachableFaultSite,
    install_dispatch_chaos)
from p2pnetwork_tpu.chaos.storm import (  # noqa: E402
    ChurnPattern, ChurnSchedule)
from p2pnetwork_tpu.chaos import storm as storm_mod  # noqa: E402
from p2pnetwork_tpu.models import SIR  # noqa: E402
from p2pnetwork_tpu.models.messagebatch import BatchFlood  # noqa: E402
from p2pnetwork_tpu.serve import (  # noqa: E402
    GraphMismatch, SimService, TrafficPattern)
from p2pnetwork_tpu.serve import traffic as traffic_mod  # noqa: E402
from p2pnetwork_tpu.sim import checkpoint as ckpt  # noqa: E402
from p2pnetwork_tpu.sim import engine  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402
from p2pnetwork_tpu.supervise import SupervisedRun  # noqa: E402
from p2pnetwork_tpu.supervise.heal import RetryPolicy  # noqa: E402
from tests.test_layout_delta import (  # noqa: E402
    assert_graphs_bit_identical)

pytestmark = pytest.mark.churn

KEY = jax.random.key(0)


@pytest.fixture(params=["native", "fallback"])
def host_path(request):
    if request.param == "fallback":
        native.force_fallback(True)
        yield "fallback"
        native.force_fallback(False)
    else:
        if not native.available():
            pytest.skip("no native library on this host")
        yield "native"


@pytest.fixture()
def no_dispatch_chaos():
    prev = install_dispatch_chaos(None)
    yield
    install_dispatch_chaos(prev)


def _edges(rng, n, target):
    s = rng.integers(0, n, target * 3).astype(np.int32)
    r = rng.integers(0, n, target * 3).astype(np.int32)
    keep = s != r
    keys = np.unique(s[keep].astype(np.int64) * n + r[keep])[:target]
    return (keys // n).astype(np.int32), (keys % n).astype(np.int32)


def _base_graph(n=24, seed=3, **kw):
    """A connected undirected random overlay (both directions of every
    pair) — coverage targets on it stay reachable from any node, which
    the mid-service mutation tests rely on."""
    rng = np.random.default_rng(seed)
    s, r = _edges(rng, n, 120)
    lo, hi = np.minimum(s, r), np.maximum(s, r)
    keys = np.unique(lo.astype(np.int64) * n + hi)
    lo = (keys // n).astype(np.int32)
    hi = (keys % n).astype(np.int32)
    s = np.concatenate([lo, hi])
    r = np.concatenate([hi, lo])
    kw.setdefault("node_pad_multiple", 32)
    return G.from_edges(s, r, n, **kw), s, r


def _wire_delta(n0, n_new):
    """Every joiner undirected-wired to a base node — keeps the grown
    overlay connected so coverage targets stay reachable."""
    new = np.arange(n0, n0 + n_new)
    return G.GraphDelta.undirected(add_senders=new, add_receivers=new % n0)


# ------------------------------------------------------------- sim layer


LAYOUTS = {
    "plain": {},
    "weighted": {"weighted": True},
    "capped": {"max_degree": 4},
    "csr": {"source_csr": True},
    "blocked": {"blocked": True},
}


class TestGrow:
    @pytest.mark.parametrize("layout", sorted(LAYOUTS))
    @pytest.mark.parametrize("n_new", [5, 40])
    def test_grow_matches_from_scratch(self, layout, n_new, host_path):
        kw = dict(LAYOUTS[layout])
        weighted = kw.pop("weighted", False)
        rng = np.random.default_rng(11)
        s, r = _edges(rng, 24, 120)
        if weighted:
            kw["weights"] = rng.random(s.size).astype(np.float32)
        g = G.from_edges(s, r, 24, node_pad_multiple=32, **kw)
        grown = G.grow(g, n_new)
        ref = G.from_edges(s, r, 24 + n_new,
                           node_pad_multiple=grown.n_nodes_padded, **kw)
        assert grown.n_nodes == 24 + n_new
        assert_graphs_bit_identical(grown, ref,
                                    ctx=f"{layout}/+{n_new}/{host_path}")

    def test_method_form_and_zero_noop(self):
        g, _, _ = _base_graph()
        assert G.grow(g, 0) is g
        m = g.grow(7)
        assert_graphs_bit_identical(m, G.grow(g, 7), ctx="method")

    def test_capacity_pin_and_validation(self):
        g, _, _ = _base_graph()  # n=24, pad 32
        pinned = G.grow(g, 2, node_capacity=96)
        assert pinned.n_nodes_padded == 96
        with pytest.raises(ValueError, match="node_capacity"):
            G.grow(g, 20, node_capacity=24)  # below grown count
        with pytest.raises(ValueError, match="n_new_nodes"):
            G.grow(g, -1)

    def test_geometric_schedule_amortizes(self):
        # 200 single-node growth steps from capacity 32 must cross only
        # the doubling boundaries: 32 -> 64 -> 128 -> 256 (3 repads for
        # 24 + 200 = 224 nodes), not one repad per step.
        g, _, _ = _base_graph()
        pads = [g.n_nodes_padded]
        for _ in range(200):
            g = G.grow(g, 1)
            if g.n_nodes_padded != pads[-1]:
                pads.append(g.n_nodes_padded)
        assert g.n_nodes == 224
        assert pads == [32, 64, 128, 256]

    def test_grow_then_wire_equals_from_scratch(self, host_path):
        # The full join: grow + apply_delta wiring == from_edges of the
        # merged edge list at the grown capacity (the delta's donate
        # fast path stays valid on grown buffers).
        g, s, r = _base_graph(source_csr=True)
        grown = G.grow(g, 40)
        d = _wire_delta(24, 40)
        wired = G.apply_delta(grown, d, donate=True)
        ms = np.concatenate([s, d.add_senders.astype(np.int32)])
        mr = np.concatenate([r, d.add_receivers.astype(np.int32)])
        ref = G.from_edges(ms, mr, 64,
                           node_pad_multiple=wired.n_nodes_padded,
                           edge_pad_multiple=wired.edge_pad_multiple,
                           source_csr=True)
        assert_graphs_bit_identical(wired, ref, ctx="grow+wire")

    def test_endpoint_error_is_typed(self):
        g, _, _ = _base_graph()
        with pytest.raises(G.EdgeEndpointError):
            G.apply_delta(g, G.GraphDelta(add_senders=[24],
                                          add_receivers=[0]))


class TestBatchRepad:
    def test_repad_matches_fresh_init_on_grown_graph(self):
        g, s, r = _base_graph()
        grown = G.grow(g, 40)  # pad 32 -> 64
        proto = BatchFlood()
        sources = np.asarray([0, 3, 9], dtype=np.int32)
        fresh = proto.init(grown, sources)
        repadded = proto.repad(proto.init(g, sources),
                               grown.n_nodes_padded)
        for a, b in zip(jax.tree_util.tree_leaves(repadded),
                        jax.tree_util.tree_leaves(fresh)):
            assert np.asarray(a).shape == np.asarray(b).shape
            assert (np.asarray(a) == np.asarray(b)).all()
        # ... and the runs from them are the same run.
        b1, o1 = engine.run_batch_until_coverage(
            grown, proto, fresh, KEY, max_rounds=32, donate=False)
        b2, o2 = engine.run_batch_until_coverage(
            grown, proto, repadded, KEY, max_rounds=32, donate=False)
        for a, b in zip(jax.tree_util.tree_leaves((b1, o1)),
                        jax.tree_util.tree_leaves((b2, o2))):
            assert (np.asarray(a) == np.asarray(b)).all()

    def test_shrink_refused(self):
        g, _, _ = _base_graph()
        batch = BatchFlood().init(g, np.asarray([0], dtype=np.int32))
        with pytest.raises(ValueError):
            BatchFlood().repad(batch, g.n_nodes_padded // 2)


# ------------------------------------------------------ checkpoint layer


class TestCheckpointGrow:
    def _states(self):
        small = {"seen": np.zeros((3, 8), dtype=bool),
                 "rank": np.arange(8, dtype=np.float32)}
        big = {"seen": np.zeros((3, 16), dtype=bool),
               "rank": np.zeros(16, dtype=np.float32)}
        return small, big

    def test_grow_state_zero_extends(self):
        small, big = self._states()
        small["seen"][1, 2] = True
        small["rank"][:] = 7.0
        grown = ckpt.grow_state(small, big)
        assert grown["seen"].shape == (3, 16)
        assert grown["seen"][1, 2] and grown["seen"][:, 8:].sum() == 0
        assert (grown["rank"][:8] == 7.0).all()
        assert (grown["rank"][8:] == 0.0).all()

    def test_grow_state_identity_and_refusals(self):
        small, big = self._states()
        same = ckpt.grow_state(small, small)
        assert same["rank"] is small["rank"]  # shape match: pass-through
        with pytest.raises(ValueError, match="not repad-growable"):
            ckpt.grow_state(big, small)  # shrink
        cast = dict(big)
        cast["rank"] = big["rank"].astype(np.float64)
        with pytest.raises(ValueError, match="not repad-growable"):
            ckpt.grow_state(small, cast)  # dtype change
        with pytest.raises(ValueError):
            ckpt.grow_state(small, {"seen": big["seen"]})  # treedef

    def test_load_grow_roundtrip(self, tmp_path):
        small, big = self._states()
        small["rank"][:] = 3.25
        path = str(tmp_path / "c.npz")
        ckpt.save(path, small, KEY, 5, 17)
        state, _, rnd, msgs = ckpt.load(path, big, grow=True)
        assert (rnd, msgs) == (5, 17)
        assert state["rank"].shape == (16,)
        assert (np.asarray(state["rank"])[:8] == 3.25).all()
        # Without grow= the structure-only contract holds: the entry
        # loads with its ORIGINAL shapes (treedef is what's validated).
        plain, _, _, _ = ckpt.load(path, big)
        assert plain["rank"].shape == (8,)

    def test_supervised_resume_across_repad_bit_identical(self, tmp_path):
        # A PRNG-dependent protocol, killed mid-run, resumed onto the
        # GROWN graph — must equal the run that would have executed the
        # same growth interleaving in ONE process: small-graph chunks,
        # zero-extension at the growth boundary, grown-graph chunks.
        # (Chunk keys are the runner's documented pure schedule,
        # fold_in(base_key, chunk_start_round + 1), so the baseline can
        # replicate them exactly; dead padding is all-zero, so the
        # zero-extended restore IS that run's state at the boundary.)
        g, _, _ = _base_graph(n=12, seed=9)
        # 12 -> 22 nodes; pin capacity past 32 so the resume really
        # crosses a repad, not just a live-count bump.
        grown = G.grow(g, 10, node_capacity=64)
        proto = SIR(beta=0.5, gamma=0.1, source=0)

        first = SupervisedRun(g, proto, str(tmp_path / "run"),
                              chunk_rounds=4)
        first.run_rounds(KEY, 8)

        resumed = SupervisedRun(grown, proto, str(tmp_path / "run"),
                                chunk_rounds=4)
        state_r, sum_r = resumed.run_rounds(KEY, 16)
        assert sum_r["resumed_from"] == 8

        state = proto.init(g, KEY)
        for start in (0, 4):
            state, _ = engine.run_from(
                g, proto, state, jax.random.fold_in(KEY, start + 1), 4,
                donate=False)
        template = jax.tree_util.tree_map(
            lambda s: np.zeros(s.shape, s.dtype),
            jax.eval_shape(lambda k: proto.init(grown, k), KEY))
        state = ckpt.grow_state(state, template)
        for start in (8, 12):
            state, _ = engine.run_from(
                grown, proto, state, jax.random.fold_in(KEY, start + 1),
                4, donate=False)
        for a, b in zip(jax.tree_util.tree_leaves(state_r),
                        jax.tree_util.tree_leaves(state)):
            assert (np.asarray(a) == np.asarray(b)).all()

    def test_incompatible_entry_skips_to_fresh_start(self, tmp_path):
        # A trail whose leaves cannot grow into the template (different
        # protocol) must count template_mismatch and fall back to a
        # fresh run, not crash — the resume-over-damage contract.
        g, _, _ = _base_graph(n=12, seed=9)
        reg = telemetry.Registry()
        SupervisedRun(g, SIR(beta=0.5, gamma=0.1, source=0),
                      str(tmp_path), chunk_rounds=4).run_rounds(KEY, 4)
        from p2pnetwork_tpu.models import Flood
        run = SupervisedRun(g, Flood(source=0), str(tmp_path),
                            chunk_rounds=4, registry=reg)
        _, summary = run.run_rounds(KEY, 4)
        assert summary["resumed_from"] is None
        assert reg.value("supervise_checkpoints_skipped_total",
                         reason="template_mismatch") >= 1


# --------------------------------------------------------- serving layer


def _service(g, **kw):
    kw.setdefault("capacity", 8)
    kw.setdefault("chunk_rounds", 2)
    kw.setdefault("seed", 5)
    kw.setdefault("record_seen_hash", True)
    kw.setdefault("registry", telemetry.Registry())
    return SimService(g, **kw)


class TestServiceMutations:
    def test_untouched_tickets_byte_identical(self):
        g, _, _ = _base_graph()
        svc = _service(g)
        t1, t2 = svc.submit(0), svc.submit(3)
        while svc.busy():
            svc.tick()
        ref = svc.tickets()
        svc.close()

        svc = _service(g)
        t1, t2 = svc.submit(0), svc.submit(3)
        while svc.busy():
            svc.tick()
        svc.grow(50)
        svc.apply_delta(_wire_delta(24, 50))
        svc.tick()
        st = svc.stats()
        assert (st["graph_nodes"], st["graph_capacity"]) == (74, 128)
        assert st["mutations"] == 2
        t3 = svc.submit(70)
        while svc.busy():
            svc.tick()
        rec = svc.tickets()
        assert rec[t1] == ref[t1] and rec[t2] == ref[t2]
        assert rec[t3]["status"] == "done" and rec[t3]["coverage"] > 0.99
        svc.close()

    def test_in_flight_lane_terminates_structurally(self):
        # A lane admitted before a growth step may never reach the new
        # coverage denominator (informed nodes do not re-broadcast to
        # late joiners) — it must end in a TERMINAL state, and its lane
        # must recycle, never leak.
        g, _, _ = _base_graph()
        svc = _service(g, max_ticket_rounds=16)
        t = svc.submit(0)
        svc.tick()
        svc.grow(40)
        while svc.busy():
            svc.tick()
        assert svc.poll(t)["status"] in ("done", "timeout")
        t2 = svc.submit(1, target_coverage=0.3)
        while svc.busy():
            svc.tick()
        assert svc.poll(t2)["status"] == "done"
        svc.close()

    def test_mutation_validation_is_typed_and_grow_aware(self):
        g, _, _ = _base_graph()
        svc = _service(g)
        with pytest.raises(G.EdgeEndpointError):
            svc.apply_delta(G.GraphDelta(add_senders=[30],
                                         add_receivers=[0]))
        with pytest.raises(ValueError):
            svc.grow(-1)
        # Queued growth extends the valid endpoint range BEFORE the
        # mutate phase lands it: wiring a just-queued joiner is legal.
        svc.grow(10)
        svc.apply_delta(G.GraphDelta.undirected(add_senders=[30],
                                                add_receivers=[0]))
        svc.tick()
        assert svc.stats()["graph_nodes"] == 34
        with pytest.raises(G.EdgeEndpointError):
            svc.apply_delta(G.GraphDelta(add_senders=[34],
                                         add_receivers=[0]))
        svc.close()
        from p2pnetwork_tpu.serve import ServiceClosed
        with pytest.raises(ServiceClosed):
            svc.grow(1)
        with pytest.raises(ServiceClosed):
            svc.apply_delta(_wire_delta(24, 1))


class TestSidecarFingerprint:
    def test_growth_only_trail_replays_growth(self, tmp_path):
        g, _, _ = _base_graph()
        svc = _service(g, store=str(tmp_path))
        ta = svc.submit(0)
        while svc.busy():
            svc.tick()
        svc.grow(50)
        svc.tick()
        svc.close()
        pre = svc.tickets()

        back = _service(g, store=str(tmp_path))
        assert (back.graph.n_nodes, back.graph.n_nodes_padded) == (74, 128)
        assert back.tickets()[ta] == pre[ta]
        back.close()
        # Replay is idempotent: the base fingerprint is stable, so the
        # SAME trail resumes again.
        again = _service(g, store=str(tmp_path))
        assert again.graph.n_nodes == 74
        again.close()

    def test_delta_trail_refused_then_resumes_on_rebuilt_graph(
            self, tmp_path):
        g, _, _ = _base_graph()
        svc = _service(g, store=str(tmp_path))
        ta = svc.submit(0)
        while svc.busy():
            svc.tick()
        svc.grow(50)
        svc.apply_delta(_wire_delta(24, 50))
        svc.tick()
        tb = svc.submit(70)
        svc.tick()
        svc.close()
        pre = svc.tickets()

        # Deltas are not replayable from the sidecar — resuming from the
        # BASE overlay must refuse, typed, with the trail preserved.
        with pytest.raises(GraphMismatch) as ei:
            _service(g, store=str(tmp_path))
        assert ei.value.directory == str(tmp_path)
        assert os.path.exists(os.path.join(str(tmp_path),
                                           "service_state.json"))

        rebuilt = G.apply_delta(G.grow(g, 50), _wire_delta(24, 50))
        back = _service(rebuilt, store=str(tmp_path))
        while back.busy():
            back.tick()
        rec = back.tickets()
        assert rec[ta] == pre[ta]
        assert rec[tb]["status"] == "done"
        back.close()

    def test_wrong_overlay_refused_trail_preserved(self, tmp_path):
        g, s, r = _base_graph()
        svc = _service(g, store=str(tmp_path))
        svc.submit(0)
        svc.tick()
        svc.close()
        other = G.from_edges(r[:80], s[:80], 24, node_pad_multiple=32)
        with pytest.raises(GraphMismatch):
            _service(other, store=str(tmp_path))
        assert os.path.exists(os.path.join(str(tmp_path),
                                           "service_state.json"))


# ------------------------------------------------------------ churn storms


STORM_PATTERN = ChurnPattern(ticks=24, join_prob=0.5, join_batch=3,
                             fanout=2, leave_prob=0.3, grow_prob=0.2,
                             grow_batch=4)


class TestStorm:
    def test_schedule_byte_replayable(self):
        s1 = storm_mod.generate(STORM_PATTERN, 32, seed=7)
        s2 = storm_mod.generate(STORM_PATTERN, 32, seed=7)
        assert s1.to_bytes() == s2.to_bytes()
        assert s1.to_bytes() != storm_mod.generate(
            STORM_PATTERN, 32, seed=8).to_bytes()
        assert isinstance(s1, ChurnSchedule)
        assert s1.n_final == 32 + sum(
            int(a) for k, a in zip(s1.ev_kind, s1.ev_amount)
            if storm_mod.EVENT_KINDS[int(k)] in ("grow", "join"))

    def test_pattern_validation(self):
        with pytest.raises(ValueError, match="join_prob"):
            ChurnPattern(join_prob=1.5)
        with pytest.raises(ValueError, match="fanout"):
            ChurnPattern(fanout=0)
        with pytest.raises(ValueError, match="ticks"):
            ChurnPattern(ticks=0)

    def test_leaves_only_shed_live_storm_edges(self):
        # Every leave event's removal rows must have been added by an
        # earlier join and not removed since — the invariant that makes
        # each emitted delta valid against the drive-time graph.
        sched = storm_mod.generate(STORM_PATTERN, 32, seed=7)
        live = set()
        for ev in range(len(sched)):
            kind = storm_mod.EVENT_KINDS[int(sched.ev_kind[ev])]
            rows = np.flatnonzero(sched.edge_event == ev)
            pairs = {(int(sched.edge_a[i]), int(sched.edge_b[i]))
                     for i in rows.tolist()}
            if kind == "join":
                assert not (pairs & live)
                live |= pairs
            elif kind == "leave":
                assert pairs <= live
                live -= pairs

    def test_drive_deterministic_with_traffic(self):
        rng = np.random.default_rng(0)
        s, r = _edges(rng, 32, 200)
        # Pre-provision headroom past the storm's growth so both drives
        # compile ONE dispatch shape (repad-under-traffic is pinned by
        # TestServiceMutations and the slow soak; this test pins drive
        # determinism, which must not depend on repad timing anyway).
        g = G.grow(G.from_edges(s, r, 32, node_pad_multiple=32),
                   0, node_capacity=256)
        sched = storm_mod.generate(STORM_PATTERN, 32, seed=7)
        tr = traffic_mod.generate(
            TrafficPattern(ticks=24, rate=1.5, coverage_target=0.5),
            32, seed=3)
        outs = []
        for _ in range(2):
            # A tight round budget: churn legitimately strands lanes
            # (their denominator grew), and the default 1024-round
            # cutoff would spin the drain for ~500 ticks just to prove
            # they time out — 40 rounds (20 ticks) is still an order
            # of magnitude past any completing lane on this graph.
            svc = _service(g, max_ticket_rounds=40)
            outs.append(storm_mod.drive(svc, sched, traffic=tr))
            svc.close()
        assert outs[0] == outs[1]
        assert outs[0]["graph_nodes"] == sched.n_final
        assert outs[0]["events"]["join"] > 0
        assert outs[0]["events"]["leave"] > 0
        # Every admitted lane reached a TERMINAL state — churn may
        # legitimately time a lane out (its denominator grew), but
        # nothing leaks or hangs.
        n_timeout = sum(1 for r in outs[0]["tickets"].values()
                        if r is not None and r["status"] == "timeout")
        assert outs[0]["completed"] + n_timeout + len(
            outs[0]["shed"]) == outs[0]["submitted"]

    def test_drive_refuses_mismatched_traffic(self):
        g, _, _ = _base_graph()
        sched = storm_mod.generate(ChurnPattern(ticks=4), 24, seed=1)
        tr = traffic_mod.generate(TrafficPattern(ticks=8, rate=1.0),
                                  24, seed=1)
        svc = _service(g)
        with pytest.raises(ValueError, match="storm"):
            storm_mod.drive(svc, sched, traffic=tr)
        svc.close()


class TestFaultSiteBounds:
    def test_stale_sites_warn_structurally(self):
        spec = FaultSpec(FaultSchedule(sites=(
            (0, 1, 2, "zero"), (0, 9, 0, "corrupt"), (3, 0, 7, "delay"))))
        with pytest.warns(UnreachableFaultSite, match="2 explicit"):
            spec.make("shards", 4)

    def test_in_range_sites_stay_silent(self):
        import warnings

        spec = FaultSpec(FaultSchedule(sites=((0, 1, 2, "zero"),)))
        with warnings.catch_warnings():
            warnings.simplefilter("error", UnreachableFaultSite)
            spec.make("shards", 4)


# ------------------------------------------------------------- the soak


@pytest.mark.slow
class TestChurnSoak:
    """The acceptance soak: a 100k-node overlay served through a seeded
    join/leave/grow storm interleaved with traffic, with graftquake
    dispatch faults healed mid-storm — zero lost admitted lanes,
    structured shedding only, per-ticket records bit-identical to the
    unfaulted interleaving."""

    def test_soak_100k(self, no_dispatch_chaos):
        g = G.watts_strogatz(100_000, 6, 0.1, seed=0)
        # Pre-provision headroom with the growth machinery itself so
        # join batches land without a 2x repad recompile at 100k scale
        # (the repad path is pinned bit-identical at small scale above).
        g = G.grow(g, 0, node_capacity=1 << 17)
        churn = storm_mod.generate(
            ChurnPattern(ticks=10, join_prob=0.5, join_batch=8, fanout=3,
                         leave_prob=0.3, grow_prob=0.2, grow_batch=16),
            g.n_nodes, seed=11)
        tr = traffic_mod.generate(
            TrafficPattern(ticks=10, rate=2.0, hot_fraction=0.5,
                           hot_keys=4, coverage_target=0.95),
            g.n_nodes, seed=13)
        policy = RetryPolicy(max_attempts=4, backoff_base_s=0.0)

        def svc(**kw):
            return _service(g, capacity=32, chunk_rounds=4, seed=1,
                            heal=policy, **kw)

        ref = svc()
        out_ref = storm_mod.drive(ref, churn, traffic=tr)
        ref.close()
        assert out_ref["submitted"] > 0
        assert out_ref["events"]["join"] > 0
        assert out_ref["events"]["leave"] > 0
        assert out_ref["graph_nodes"] == churn.n_final

        chaos_reg = telemetry.Registry()
        heal_reg = telemetry.Registry()
        install_dispatch_chaos(DispatchChaos(
            preempt_at=(1,), wedge_at=(3,), registry=chaos_reg))
        storm_svc = svc(registry=heal_reg)
        out = storm_mod.drive(storm_svc, churn, traffic=tr)
        storm_svc.close()

        # Faults healed mid-storm, interleaving unchanged: every ticket
        # record (seen-hash witnesses included) bit-identical.
        assert storm_svc.tickets() == ref.tickets()
        assert out["tickets"] == out_ref["tickets"]
        assert all(r["status"] == "done"
                   for r in out["tickets"].values() if r is not None)
        assert out["completed"] + len(out["shed"]) == out["submitted"]
        assert chaos_reg.value("chaos_device_faults_total",
                               kind="preempt") == 1
        assert chaos_reg.value("chaos_device_faults_total",
                               kind="wedge") == 1
        assert heal_reg.value("heal_retries_total", outcome="healed") == 2
        assert heal_reg.value("heal_retries_total",
                              outcome="exhausted") == 0
