"""graftaudit (p2pnetwork_tpu/analysis/ir/) tests.

Three layers, mirroring test_analysis.py's contract for graftlint:

- **rule fixtures** — for every jaxpr rule, a deliberately-broken
  lowering (an intentionally-f64 variant, a host callback, a busted slot
  budget, a donation-dropped engine step) asserting the rule fires at
  the exact LOWERING NAME, with a clean real-registry twin;
- **machinery** — budgets round-trip, ratchet arithmetic (inflated cost
  fails, HEAD passes), collective-census drift, parity-gate mismatch;
- **the live tree** — the full registry must trace clean, the donation
  audit must verify every engine carry seam, and the checked-in
  budgets.json must match HEAD: the CI gate this suite keeps honest.
"""

import copy
import json

import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from p2pnetwork_tpu.analysis.ir import budgets as B  # noqa: E402
from p2pnetwork_tpu.analysis.ir import donation, registry, rules  # noqa: E402
from p2pnetwork_tpu.analysis.ir.registry import Lowering  # noqa: E402

pytestmark = pytest.mark.audit


@pytest.fixture(scope="module")
def traces():
    """One trace of the full registry, shared across the module (the
    costly part is the sharded entry's mesh build)."""
    return [registry.trace_lowering(e) for e in registry.all_lowerings()]


def _entry(name, build, **kw):
    op, rest = name.split("/", 1)
    variant, cls = rest.split("@", 1)
    return Lowering(name=name, op=op, variant=variant, shape_class=cls,
                    build=build, **kw)


def _sig(n=128, dtype=jnp.float32):
    return jnp.zeros(n, dtype=dtype)


def test_package_import_stays_jax_free():
    # The device-free guarantee: `python -m p2pnetwork_tpu.analysis.ir`
    # (and the console script) execute the package __init__ BEFORE
    # main() can pin JAX_PLATFORMS, and jax captures that env var at
    # import time — so importing the package must not import jax.
    import subprocess
    import sys

    code = ("import sys; import p2pnetwork_tpu.analysis.ir; "
            "sys.exit(2 if 'jax' in sys.modules else 0)")
    assert subprocess.run([sys.executable, "-c", code]).returncode == 0


# ------------------------------------------------------------- registry


class TestRegistry:
    def test_full_registry_traces_clean(self, traces):
        assert len(traces) >= 20
        names = [t.entry.name for t in traces]
        assert len(set(names)) == len(names)
        assert [t.entry.name for t in traces if t.error] == []
        for t in traces:
            assert t.out_sig, t.entry.name
            assert t.prims, t.entry.name

    def test_registry_covers_the_lowering_zoo(self, traces):
        variants = {(t.entry.op, t.entry.variant) for t in traces}
        # Every module the audit exists to police appears.
        assert ("or", "segment") in variants
        assert ("or", "blocked") in variants
        assert ("or", "skew") in variants
        assert ("or", "frontier") in variants
        assert ("floodstep", "bitset") in variants
        assert ("cov", "flood-ppermute") in variants

    def test_sharded_collective_census(self, traces):
        t = next(t for t in traces
                 if t.entry.name == "cov/flood-ppermute@ws1k")
        assert t.collectives.get("ppermute", 0) >= 1
        assert t.collectives.get("psum", 0) >= 1
        assert t.ici_bytes_est > 0

    def test_single_chip_lowerings_have_no_collectives(self, traces):
        for t in traces:
            if t.entry.needs_devices == 1:
                assert not t.collectives, t.entry.name


# ----------------------------------------------------------- jaxpr rules


class TestJaxprRules:
    def test_real_registry_has_zero_rule_findings(self, traces):
        assert rules.run_ir_rules(traces) == []

    def test_f64_widen_fires_at_the_lowering_name(self):
        def build():
            def bad(x):
                with jax.experimental.enable_x64():
                    y = x.astype(jnp.float64) * 2.0
                return y.astype(jnp.float32)
            return bad, (_sig(),)

        t = registry.trace_lowering(_entry("or/f64bad@ws1k", build,
                                           parity=False))
        found = [f for f in rules.run_ir_rules([t])
                 if f.rule == "ir-f64-widen"]
        assert found and all(f.file == "or/f64bad@ws1k" for f in found)
        assert any("convert_element_type" in f.message for f in found)

    def test_f64_clean_twin(self):
        def build():
            return (lambda x: x * 2.0), (_sig(),)

        t = registry.trace_lowering(_entry("or/f32ok@ws1k", build,
                                           parity=False))
        assert [f for f in rules.run_ir_rules([t])
                if f.rule == "ir-f64-widen"] == []

    def test_host_callback_fires(self):
        def build():
            def bad(x):
                return jax.pure_callback(
                    lambda v: v, jax.ShapeDtypeStruct(x.shape, x.dtype), x)
            return bad, (_sig(),)

        t = registry.trace_lowering(_entry("or/cb@ws1k", build,
                                           parity=False))
        found = rules.run_ir_rules([t])
        assert [f.rule for f in found] == ["ir-host-callback"]
        assert found[0].severity == "P0"
        assert found[0].file == "or/cb@ws1k"

    def test_trace_error_is_a_finding_not_a_crash(self):
        def build():
            raise RuntimeError("entry rotted")

        t = registry.trace_lowering(_entry("or/dead@ws1k", build))
        found = rules.run_ir_rules([t])
        assert [f.rule for f in found] == ["ir-trace-error"]
        assert "entry rotted" in found[0].message

    def test_gather_slot_budget_fires_when_every_branch_blows_it(self):
        # A cond BOTH of whose branches gather the full table — the
        # compaction invariant (some branch within k·span) is broken.
        def build():
            idx = jnp.arange(4096) % 128

            def fat(x):
                return jax.lax.cond(x.sum() > 0,
                                    lambda s: s[idx], lambda s: s[idx] * 2,
                                    x)
            return fat, (_sig(),)

        t = registry.trace_lowering(_entry("or/fatgather@ws1k", build,
                                           parity=False, slot_budget=64))
        found = [f for f in rules.run_ir_rules([t])
                 if f.rule == "ir-gather-slot-budget"]
        assert found and found[0].file == "or/fatgather@ws1k"
        assert "every branch" in found[0].message

    def test_gather_slot_budget_fires_when_the_cond_is_compiled_out(self):
        def build():
            return (lambda x: x * 2), (_sig(),)

        t = registry.trace_lowering(_entry("or/nocond@ws1k", build,
                                           parity=False, slot_budget=64))
        found = [f for f in rules.run_ir_rules([t])
                 if f.rule == "ir-gather-slot-budget"]
        assert found and "compiled out" in found[0].message

    def test_real_frontier_entries_satisfy_their_budget(self, traces):
        budgeted = [t for t in traces if t.entry.slot_budget is not None]
        assert budgeted, "no frontier entries carry a slot budget"
        assert [f for t in budgeted for f in rules.run_ir_rules([t])
                if f.rule == "ir-gather-slot-budget"] == []


# ------------------------------------------------------------ parity gate


class TestParityGate:
    def test_real_registry_is_parity_clean(self, traces):
        assert rules.parity_findings(traces) == []

    def test_signature_mismatch_is_caught(self, traces):
        g = registry.shape_class("ws1k")

        def build():
            # Same op group as the real `or@ws1k` lowerings, wrong dtype.
            return (lambda x: x.astype(jnp.int32)), (
                jnp.zeros(g.n_nodes_padded, dtype=bool),)

        bad = registry.trace_lowering(_entry("or/badsig@ws1k", build))
        found = rules.parity_findings(list(traces) + [bad])
        assert [f.file for f in found] == ["or/badsig@ws1k"]
        assert found[0].rule == "ir-sig-parity"
        assert found[0].severity == "P0"


# --------------------------------------------------------------- donation


class TestDonationAudit:
    def test_engine_carry_donation_verifies_at_head(self):
        assert donation.audit_donation() == []

    def test_dropped_donate_argnums_is_caught(self):
        # The engine's own donate=False escape-hatch twin IS the
        # dropped-donation artifact: same program, no donate_argnames.
        from p2pnetwork_tpu.models.flood import Flood
        from p2pnetwork_tpu.sim import engine

        g = registry.shape_class("ws1k")
        state = donation._flood_resume_state(g)
        dropped = donation.DonationAudit(
            name="engine/run_from-keeping",
            build=lambda: (engine._run_from_keeping,
                           (g, Flood(source=0), state, jax.random.key(0),
                            4), {}, 2))
        found = donation.audit_donation([dropped])
        assert [f.rule for f in found] == ["ir-donation-dropped"]
        assert found[0].severity == "P0"
        assert found[0].file == "engine/run_from-keeping"

    def test_unbuildable_audit_is_a_finding(self):
        def build():
            raise OSError("no such seam")

        found = donation.audit_donation(
            [donation.DonationAudit(name="x/y", build=build)])
        assert [f.rule for f in found] == ["ir-donation-unverifiable"]

    def test_alias_section_parses_nested_braces(self):
        hlo = ("ENTRY %main, input_output_alias={ {0}: (4, {}, may-alias),"
               " {1}: (5, {}, may-alias) }, entry_computation_layout=x")
        assert len(donation._ALIAS_PAIR.findall(
            donation._alias_section(hlo))) == 2


# ------------------------------------------------------------ cost ratchet


class TestCostRatchet:
    @pytest.fixture(scope="class")
    def head_costs(self, traces):
        return B.collect_costs(traces)

    def test_budgets_round_trip(self, head_costs, tmp_path):
        path = str(tmp_path / "budgets.json")
        B.write_budgets(head_costs, path)
        doc = B.load_budgets(path)
        assert doc["schema"] == B.SCHEMA
        assert set(doc["entries"]) == set(head_costs)
        assert B.check_budgets(head_costs, doc) == []

    def test_checked_in_budgets_match_head(self, head_costs):
        # THE ratchet gate: unexplained cost drift vs the committed file
        # fails CI. A legitimate change is blessed via
        # `graftaudit --write-budgets` (commit the budgets.json diff).
        doc = B.load_budgets()
        assert doc, "analysis/ir/budgets.json is missing"
        assert B.check_budgets(head_costs, doc) == []

    def test_inflated_cost_fails_the_ratchet(self, head_costs):
        doc = copy.deepcopy(B.load_budgets())
        name = "or/segment@ws1k"
        doc["entries"][name]["flops"] /= 1.5  # current looks 1.5x budget
        found = [f for f in B.check_budgets(head_costs, doc)
                 if f.file == name]
        assert found and found[0].rule == "ir-cost-ratchet"
        assert "grew 1.50x" in found[0].message

    def test_shrunk_cost_asks_for_a_re_bless(self, head_costs):
        doc = copy.deepcopy(B.load_budgets())
        name = "or/segment@ws1k"
        doc["entries"][name]["bytes"] *= 2.0  # current is half the budget
        found = [f for f in B.check_budgets(head_costs, doc)
                 if f.file == name]
        assert found and found[0].severity == "P2"
        assert "shrank" in found[0].message

    def test_collective_drift_fails(self, head_costs):
        doc = copy.deepcopy(B.load_budgets())
        name = "cov/flood-ppermute@ws1k"
        doc["entries"][name]["collectives"]["psum"] += 1
        found = [f for f in B.check_budgets(head_costs, doc)
                 if f.file == name]
        assert found and "collective census changed" in found[0].message

    def test_missing_and_stale_entries_are_findings(self, head_costs):
        doc = copy.deepcopy(B.load_budgets())
        doc["entries"]["or/ghost@ws1k"] = {"flops": 1.0, "bytes": 1.0}
        del doc["entries"]["or/segment@ws1k"]
        messages = {f.file: f.message
                    for f in B.check_budgets(head_costs, doc)}
        assert "no blessed budget" in messages["or/segment@ws1k"]
        assert "no longer produces" in messages["or/ghost@ws1k"]

    def test_skipped_lowerings_are_not_stale(self, head_costs):
        # A degraded host (jax imported before graftaudit could pin the
        # virtual mesh) skips the sharded entries; their budgets must NOT
        # read as stale — that advice would regenerate a budgets.json
        # missing them and fail the next full CI run.
        name = "cov/flood-ppermute@ws1k"
        costs = {k: v for k, v in head_costs.items() if k != name}
        doc = B.load_budgets()
        with_skip = B.check_budgets(costs, doc, skipped=[name])
        assert [f for f in with_skip if f.file == name] == []
        without = B.check_budgets(costs, doc)
        assert any(f.file == name and "no longer produces" in f.message
                   for f in without)

    def test_blessed_error_record_is_a_finding_not_an_ungate(self,
                                                             head_costs):
        # A budgets.json entry that is itself an error record (hand-edit,
        # or a bless from before the CLI refused them) has no metrics to
        # compare — it must fail the gate, not skip it forever.
        doc = copy.deepcopy(B.load_budgets())
        name = "or/segment@ws1k"
        doc["entries"][name] = {"error": "RuntimeError: transient OOM"}
        found = [f for f in B.check_budgets(head_costs, doc)
                 if f.file == name]
        assert found and "compile-error record" in found[0].message

    def test_compile_failure_is_gated_not_silent(self):
        # Traces fine, then the cost pass's rebuild blows up — standing in
        # for a lowering the CPU backend cannot compile. The contract
        # under test: the failure becomes a ratchet finding, never a
        # silently ungated entry.
        calls = {"n": 0}

        def build():
            calls["n"] += 1
            if calls["n"] > 1:
                raise RuntimeError("backend cannot lower this entry")
            return (lambda x: x * 2), (_sig(),)

        t = registry.trace_lowering(_entry("or/nocompile@ws1k", build,
                                           parity=False))
        costs = B.collect_costs([t])
        found = B.check_budgets(costs, {"entries": {}})
        assert any("failed to AOT-compile" in f.message for f in found)


# -------------------------------------------------------------------- CLI


class TestCLI:
    def test_head_is_clean_with_json_document(self, capsys):
        from p2pnetwork_tpu.analysis.ir.__main__ import main

        assert main(["--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["ok"] is True
        assert doc["findings"] == []
        assert len(doc["lowerings"]) >= 20
        assert doc["skipped"] == []
        assert "cov/flood-ppermute@ws1k" in doc["census"]
        assert doc["costs"]["or/segment@ws1k"]["flops"] > 0

    def test_no_cost_fast_pass(self, capsys):
        from p2pnetwork_tpu.analysis.ir.__main__ import main

        assert main(["--no-cost"]) == 0
        out = capsys.readouterr().out
        assert "clean" in out

    def test_listings_and_bad_invocations(self, capsys):
        from p2pnetwork_tpu.analysis.ir.__main__ import main

        assert main(["--list-rules"]) == 0
        assert "ir-cost-ratchet" in capsys.readouterr().out
        assert main(["--list-lowerings"]) == 0
        assert "or/frontier@ws1k" in capsys.readouterr().out
        assert main(["--rules", "no-such-rule"]) == 2
        assert main(["--no-cost", "--write-budgets"]) == 2

    def test_write_budgets_round_trips_through_the_cli(self, tmp_path,
                                                       capsys):
        from p2pnetwork_tpu.analysis.ir.__main__ import main

        path = str(tmp_path / "b.json")
        assert main(["--write-budgets", "--budgets", path]) == 0
        capsys.readouterr()
        assert main(["--budgets", path]) == 0

    def test_rebless_preserves_a_custom_tolerance(self, tmp_path, capsys):
        # check_budgets honors the STORED tolerance, so a routine
        # re-bless without --tolerance must keep it, not silently reset
        # to the default and tighten the ratchet.
        from p2pnetwork_tpu.analysis.ir.__main__ import main

        path = str(tmp_path / "b.json")
        assert main(["--write-budgets", "--budgets", path,
                     "--tolerance", "0.35"]) == 0
        assert B.load_budgets(path)["tolerance"] == 0.35
        capsys.readouterr()
        assert main(["--write-budgets", "--budgets", path]) == 0
        assert B.load_budgets(path)["tolerance"] == 0.35

    def test_bless_refuses_compile_error_records(self, tmp_path,
                                                 monkeypatch, capsys):
        # Blessing an error record would write a metric-less budget entry
        # and permanently un-gate that lowering — the CLI must refuse.
        from p2pnetwork_tpu.analysis.ir import __main__ as cli

        real = B.collect_costs

        def with_error(traces):
            costs = real(traces)
            costs["or/segment@ws1k"] = {"error": "RuntimeError: boom"}
            return costs

        monkeypatch.setattr(B, "collect_costs", with_error)
        assert cli.main(["--write-budgets",
                         "--budgets", str(tmp_path / "b.json")]) == 2
        err = capsys.readouterr().err
        assert "fail to compile" in err and "or/segment@ws1k" in err
        assert not (tmp_path / "b.json").exists()

    def test_degraded_run_skips_sharded_and_refuses_bless(self, tmp_path,
                                                          monkeypatch,
                                                          capsys):
        # With fewer devices than the sharded entries need, the gate must
        # still pass (skip list, budgets not stale) and --write-budgets
        # must refuse rather than bless a file missing those entries.
        from p2pnetwork_tpu.analysis.ir import __main__ as cli

        monkeypatch.setattr(jax, "devices", lambda *a: [object()])
        assert cli.main(["--no-cost"]) == 0
        out = capsys.readouterr()
        assert "skipped" in out.err and "flood-ppermute" in out.err
        assert cli.main(["--write-budgets",
                         "--budgets", str(tmp_path / "b.json")]) == 2
        assert "refusing --write-budgets on a degraded run" in \
            capsys.readouterr().err
        assert not (tmp_path / "b.json").exists()
