"""Boruvka minimum spanning forest vs a numpy Kruskal oracle.

Forest weight is compared (unique across all MSTs even under weight
ties — the sorted weight multiset of a minimum spanning forest is an
invariant), plus the structural invariants: committed edge count equals
live nodes minus components, the committed set is acyclic (union-find),
and every committed edge stays inside one final component.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from p2pnetwork_tpu.models import Boruvka  # noqa: E402
from p2pnetwork_tpu.sim import engine, failures  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def _live_edges(g):
    s = np.asarray(g.senders)
    r = np.asarray(g.receivers)
    em = (np.asarray(g.edge_mask)
          & np.asarray(g.node_mask)[s] & np.asarray(g.node_mask)[r])
    w = (np.asarray(g.edge_weight) if g.edge_weight is not None
         else np.ones(s.shape, np.float32))
    return s[em], r[em], w[em]


class _UF:
    def __init__(self, n):
        self.p = list(range(n))

    def find(self, x):
        while self.p[x] != x:
            self.p[x] = self.p[self.p[x]]
            x = self.p[x]
        return x

    def union(self, a, b):
        ra, rb = self.find(a), self.find(b)
        if ra == rb:
            return False
        self.p[ra] = rb
        return True


def _oracle_msf(g):
    """Kruskal over the live undirected edges: (total weight, edge count,
    component count over live nodes)."""
    s, r, w = _live_edges(g)
    # Dedup the two stored directions into one undirected edge each.
    lo, hi = np.minimum(s, r), np.maximum(s, r)
    key = lo.astype(np.int64) * g.n_nodes_padded + hi
    _, first = np.unique(key, return_index=True)
    lo, hi, w = lo[first], hi[first], w[first]
    order = np.lexsort((hi, lo, w))
    uf = _UF(g.n_nodes_padded)
    total, count = 0.0, 0
    for i in order:
        if uf.union(int(lo[i]), int(hi[i])):
            total += float(w[i])
            count += 1
    alive = np.asarray(g.node_mask)
    n_live = int(alive.sum())
    comps = n_live - count
    return total, count, comps


def _run(g, max_rounds=64):
    p = Boruvka()
    st, out = engine.run_until_converged(
        g, p, jax.random.key(0), stat="changed", threshold=1,
        max_rounds=max_rounds)
    return p, st, out


def _check_forest(g, p, st):
    """Structural invariants of the committed edge set."""
    mst = np.asarray(st.mst_edge)
    s = np.asarray(g.senders)[mst]
    r = np.asarray(g.receivers)[mst]
    uf = _UF(g.n_nodes_padded)
    for a, b in zip(s, r):
        assert uf.union(int(a), int(b)), "committed edges form a cycle"
    comp = np.asarray(st.comp)
    assert (comp[s] == comp[r]).all(), "edge straddles two final components"
    oracle_w, oracle_cnt, oracle_comps = _oracle_msf(g)
    got_w = float(st.mst_weight)
    assert mst.sum() == oracle_cnt
    assert int(Boruvka().components(g, st)) == oracle_comps
    assert got_w == pytest.approx(oracle_w, rel=1e-5)
    # mst_weight (incremental sum) agrees with re-summing the mask.
    if g.edge_weight is not None:
        resum = float(np.asarray(g.edge_weight)[mst].sum())
        assert got_w == pytest.approx(resum, rel=1e-5)


def _ws_weighted(n=96, seed=7, **kw):
    g = G.watts_strogatz(n, 4, 0.2, seed=seed, **kw)
    return g.with_weights(
        lambda s, r: 0.25
        + ((jnp.minimum(s, r) * 7919 + jnp.maximum(s, r) * 104729) % 97)
        / 50.0)


class TestBoruvka:
    def test_weighted_ws_matches_kruskal(self):
        g = _ws_weighted()
        p, st, out = _run(g)
        _check_forest(g, p, st)
        # Connected graph: a spanning tree in O(log n) phases.
        assert int(out["rounds"]) <= 12

    def test_unweighted_spanning_forest(self):
        g = G.erdos_renyi(128, 0.06, seed=3)
        p, st, out = _run(g)
        _check_forest(g, p, st)

    def test_equal_weights_tie_stress(self):
        # Every edge weight identical: correctness rests entirely on the
        # direction-independent (lo, hi) tie-break.
        g = G.watts_strogatz(80, 6, 0.3, seed=11).with_weights(
            lambda s, r: jnp.ones(s.shape, jnp.float32))
        p, st, out = _run(g)
        _check_forest(g, p, st)

    def test_two_cliques_forest(self):
        # Two disjoint cliques -> a 2-tree forest, components == 2.
        n = 16
        edges = []
        for base in (0, n // 2):
            for i in range(n // 2):
                for j in range(i + 1, n // 2):
                    edges.append((base + i, base + j))
        s = np.array([e[0] for e in edges] + [e[1] for e in edges],
                     dtype=np.int32)
        r = np.array([e[1] for e in edges] + [e[0] for e in edges],
                     dtype=np.int32)
        g = G.from_edges(s, r, n).with_weights(
            lambda a, b: 1.0
            + ((jnp.minimum(a, b) * 31 + jnp.maximum(a, b) * 17) % 13)
            .astype(jnp.float32))
        p, st, out = _run(g)
        _check_forest(g, p, st)
        assert int(p.components(g, st)) == 2

    def test_dead_nodes_excluded(self):
        g = _ws_weighted(n=64, seed=5)
        dead_ids = np.array([3, 7, 12, 13, 30, 31, 48, 55, 60, 61, 62, 63])
        g = failures.fail_nodes(g, dead_ids)
        p, st, out = _run(g)
        _check_forest(g, p, st)
        dead = ~np.asarray(g.node_mask)
        mst = np.asarray(st.mst_edge)
        s = np.asarray(g.senders)[mst]
        r = np.asarray(g.receivers)[mst]
        assert not dead[s].any() and not dead[r].any()
        assert (np.asarray(st.comp)[dead] == -1).all()

    def test_auto_path_parity(self):
        # GSPMD auto-sharded run is bit-identical to the engine (the
        # scatter-min phases partition like any other reduction).
        from tests.helpers import run_auto_parity

        st_a, st_r = run_auto_parity(_ws_weighted(n=128, seed=13),
                                     Boruvka(), 10)
        assert (np.asarray(st_a.comp) == np.asarray(st_r.comp)).all()
        assert (np.asarray(st_a.mst_edge) == np.asarray(st_r.mst_edge)).all()

    def test_deterministic(self):
        g = _ws_weighted(n=72, seed=9)
        _, st1, _ = _run(g)
        _, st2, _ = _run(g)
        assert (np.asarray(st1.mst_edge) == np.asarray(st2.mst_edge)).all()
        assert (np.asarray(st1.comp) == np.asarray(st2.comp)).all()
