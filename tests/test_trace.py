"""Tracing/profiling: structured per-round records, JSONL sink, profiler
capture smoke test."""

import json

import pytest

jax = pytest.importorskip("jax")

from p2pnetwork_tpu.models import Flood  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402
from p2pnetwork_tpu.utils import trace  # noqa: E402


@pytest.fixture(scope="module")
def graph():
    return G.watts_strogatz(300, 4, 0.1, seed=0)


def test_records_match_engine_stats(graph):
    state, records = trace.run_traced(
        graph, Flood(source=0), jax.random.key(0), 4, label="flood"
    )
    assert len(records) == 4
    for i, rec in enumerate(records):
        assert rec["round"] == i
        assert rec["label"] == "flood"
        assert set(rec) >= {"coverage", "messages", "frontier"}
    # coverage is monotone for flood; final record reflects the final state
    covs = [r["coverage"] for r in records]
    assert covs == sorted(covs)
    import numpy as np

    n_seen = int(np.asarray(state.seen).sum())
    assert covs[-1] == pytest.approx(n_seen / graph.n_nodes)


def test_jsonl_sink(tmp_path, graph):
    path = tmp_path / "trace.jsonl"
    trace.run_traced(graph, Flood(source=0), jax.random.key(0), 3,
                     sink=str(path), label="t")
    lines = [json.loads(line) for line in path.read_text().splitlines()]
    assert len(lines) == 4  # 3 rounds + summary
    assert lines[-1]["summary"] is True
    assert lines[-1]["rounds"] == 3
    assert lines[-1]["n_nodes"] == graph.n_nodes
    assert lines[-1]["wall_s"] > 0


def test_summary_schema_pinned(graph):
    """The summary line is a consumed artifact (BENCH.md tooling, ad-hoc
    jq) — its key set is pinned, telemetry-sourced fields included."""
    import io

    buf = io.StringIO()
    trace.run_traced(graph, Flood(source=0), jax.random.key(0), 2, sink=buf,
                     label="pin")
    summary = json.loads(buf.getvalue().splitlines()[-1])
    assert set(summary) == {"label", "summary", "rounds", "wall_s",
                            "compile_seconds", "device_transfer_bytes",
                            "n_nodes", "n_edges"}
    # stats history: 2 rounds x (coverage, messages, frontier,
    # frontier_occupancy) 4-byte scalars
    assert summary["device_transfer_bytes"] == 2 * 4 * 4
    assert summary["compile_seconds"] >= 0.0


def test_compile_seconds_sourced_from_registry():
    """A run that triggers fresh XLA compilation attributes its compile
    wall time in the summary (jax.monitoring -> registry delta); a warm
    rerun attributes ~none. A fresh graph SHAPE forces the cold compile
    without clearing the module's jit caches."""
    from p2pnetwork_tpu.telemetry import jaxhooks

    if not jaxhooks.install():
        pytest.skip("jax.monitoring unavailable")
    import io

    g = G.watts_strogatz(123, 4, 0.1, seed=3)  # unseen shape -> compiles
    buf = io.StringIO()
    trace.run_traced(g, Flood(source=0), jax.random.key(0), 2, sink=buf)
    first = json.loads(buf.getvalue().splitlines()[-1])
    assert first["compile_seconds"] > 0

    buf = io.StringIO()  # warm cache: no fresh compile attributed
    trace.run_traced(g, Flood(source=0), jax.random.key(0), 2, sink=buf)
    warm = json.loads(buf.getvalue().splitlines()[-1])
    assert warm["compile_seconds"] < first["compile_seconds"] / 10


def test_sink_accepts_file_object(graph):
    import io

    buf = io.StringIO()
    trace.run_traced(graph, Flood(source=0), jax.random.key(0), 2, sink=buf)
    assert len(buf.getvalue().splitlines()) == 3


def test_profile_capture(tmp_path, graph):
    prof_dir = tmp_path / "prof"
    trace.run_traced(graph, Flood(source=0), jax.random.key(0), 2,
                     profile_dir=str(prof_dir))
    # jax.profiler.trace writes a plugins/profile/<ts>/ tree
    captured = list(prof_dir.rglob("*.xplane.pb"))
    assert captured, "no profile artifacts captured"


def test_annotate_is_transparent(graph):
    with trace.annotate("custom-step"):
        out = jax.numpy.sum(jax.numpy.arange(8))
    assert int(out) == 28
