"""Transport-level integration tests: framing beyond one recv chunk,
compression end-to-end, connection info store.

Scenario parity with the reference's tests/test_nodeconnection.py (large
frames crossing the 4096-byte recv boundary) and
tests/test_node_compression.py (codec round-trips over sockets, unknown
algorithm delivering nothing), plus the cases the reference left as TODOs
[ref: tests/test_nodeconnection.py:4-5]: bytes payloads and the buffer bound."""

import pytest

from p2pnetwork_tpu import Node, NodeConfig
from tests.helpers import EventRecorder, stop_all, wait_until


def pair(recorder, **server_kw):
    server = Node("127.0.0.1", 0, callback=recorder, **server_kw)
    server.start()
    client = Node("127.0.0.1", 0)
    client.start()
    assert client.connect_with_node("127.0.0.1", server.port)
    assert wait_until(lambda: len(server.nodes_inbound) == 1)
    return server, client


class TestFraming:
    def test_large_str_frames_reassembled(self):
        # Parity: 5 x 5000-char frames, each larger than one 4096-byte chunk
        # [ref: tests/test_nodeconnection.py:17-77].
        rec = EventRecorder()
        server, client = pair(rec)
        try:
            messages = [f"unittest{i}" * 500 for i in range(5)]
            for m in messages:
                client.send_to_nodes(m)
            assert wait_until(lambda: rec.count("node_message") == 5)
            assert rec.data_for("node_message") == messages
        finally:
            stop_all([server, client])

    def test_large_dict_roundtrip(self):
        # Parity: 5000-element dict via JSON [ref: tests/test_nodeconnection.py:79-143].
        rec = EventRecorder()
        server, client = pair(rec)
        try:
            big = {str(i): i for i in range(5000)}
            client.send_to_nodes(big)
            assert wait_until(lambda: rec.count("node_message") == 1)
            assert rec.data_for("node_message")[0] == big
        finally:
            stop_all([server, client])

    def test_large_bytes_roundtrip(self):
        # The reference's untested TODO [ref: tests/test_nodeconnection.py:4].
        rec = EventRecorder()
        server, client = pair(rec)
        try:
            # 0xfe/0xff are never valid utf-8 (so the payload parses back as
            # bytes) and avoid the EOT byte — raw bytes containing 0x04 break
            # framing by design, exactly as in the reference (see wire.py).
            blob = b"\xfe\xff\xf8raw" * 10_000
            client.send_to_nodes(blob)
            assert wait_until(lambda: rec.count("node_message") == 1)
            assert rec.data_for("node_message")[0] == blob
        finally:
            stop_all([server, client])

    def test_buffer_overflow_closes_connection(self):
        # The reference's acknowledged unbounded-buffer bug
        # [ref: nodeconnection.py:206]; here the connection dies cleanly.
        rec = EventRecorder()
        server, client = pair(rec, config=NodeConfig(max_recv_buffer=10_000))
        try:
            client.send_to_nodes("x" * 50_000)  # one frame, exceeds the bound
            assert wait_until(lambda: len(server.nodes_inbound) == 0)
            assert server.message_count_rerr >= 1
            assert rec.count("node_message") == 0
        finally:
            stop_all([server, client])

    def test_info_store(self):
        rec = EventRecorder()
        server, client = pair(rec)
        try:
            conn = server.nodes_inbound[0]
            conn.set_info("role", "miner")
            assert conn.get_info("role") == "miner"
            assert conn.info == {"role": "miner"}
        finally:
            stop_all([server, client])


class TestCompressionOverSockets:
    @pytest.mark.parametrize("algo", ["zlib", "lzma", "bzip2"])
    def test_codec_roundtrip(self, algo):
        # Parity: tests/test_node_compression.py:16-143.
        rec = EventRecorder()
        server, client = pair(rec)
        try:
            payloads = ["plain " * 500, {"big": ["v"] * 1000}, b"\xfe\xff" * 2000]
            for p in payloads:
                client.send_to_nodes(p, compression=algo)
            assert wait_until(lambda: rec.count("node_message") == 3)
            assert rec.data_for("node_message") == payloads
        finally:
            stop_all([server, client])

    def test_unknown_algorithm_delivers_nothing(self):
        # Parity: unknown algorithm -> zero messages delivered
        # [ref: tests/test_node_compression.py:145-185]; rerr counts it
        # (SURVEY.md 2.3.7).
        rec = EventRecorder()
        server, client = pair(rec)
        try:
            client.send_to_nodes("never arrives", compression="snappy")
            client.send_to_nodes("arrives", compression="zlib")
            assert wait_until(lambda: rec.count("node_message") == 1)
            assert rec.data_for("node_message") == ["arrives"]
            assert client.message_count_rerr >= 1
        finally:
            stop_all([server, client])


class TestSendBackpressure:
    """A peer that stops reading must trip max_send_buffer: the writer
    treats the over-full transport as a failed send and closes the
    connection (the close-on-failure policy the reference applies to
    sendall errors [ref: nodeconnection.py:123-126])."""

    def test_unread_peer_trips_max_send_buffer(self):
        import socket as socketlib

        cfg = NodeConfig(max_send_buffer=64 * 1024)
        sender = Node("127.0.0.1", 0, config=cfg)
        sender.start()
        # A raw socket that handshakes and then never reads again.
        raw = socketlib.create_connection(("127.0.0.1", sender.port))
        try:
            raw.sendall(b"lazy-peer:12345")
            assert raw.recv(4096)  # the sender's id — handshake done
            assert wait_until(lambda: len(sender.nodes_inbound) == 1)
            conn = sender.nodes_inbound[0]
            rerr_before = sender.message_count_rerr
            # Flood far beyond the 64 KiB bound + OS socket buffers while
            # the peer reads nothing.
            # Enough volume to blow past kernel send+recv buffers (which can
            # absorb many MB on loopback) and land in the transport's
            # user-space buffer where the bound is enforced.
            chunk = "x" * 65536
            for _ in range(1500):
                if conn.terminate_flag.is_set():
                    break
                sender.send_to_node(conn, chunk)
            assert wait_until(lambda: len(sender.nodes_inbound) == 0,
                              timeout=10.0)
            assert sender.message_count_rerr > rerr_before
        finally:
            raw.close()
            stop_all([sender])


class TestLengthPrefixedFraming:
    """Opt-in framing="length" (NodeConfig): arbitrary binary — including
    the EOT byte 0x04 the reference's delimiter framing cannot carry
    [ref: nodeconnection.py:38] — travels intact."""

    def pair_length(self, recorder):
        cfg = NodeConfig(framing="length")
        server = Node("127.0.0.1", 0, callback=recorder,
                      config=NodeConfig(framing="length"))
        server.start()
        client = Node("127.0.0.1", 0, config=cfg)
        client.start()
        assert client.connect_with_node("127.0.0.1", server.port)
        assert wait_until(lambda: len(server.nodes_inbound) == 1)
        return server, client

    def test_bytes_with_eot_bytes_survive(self):
        rec = EventRecorder()
        server, client = self.pair_length(rec)
        try:
            # Invalid utf-8 (so the parse chain keeps it as bytes) with
            # embedded EOT 0x04 bytes (which delimiter framing would
            # split) AND a trailing 0x02 (which EOT framing's compression
            # sniff would strip) — length framing carries both intact.
            payload = b"\xff\x04\xfe\x02stuff\x00\x04\xff\x02"
            client.send_to_nodes(payload)
            assert wait_until(lambda: payload in rec.messages())
        finally:
            stop_all([server, client])

    def test_str_dict_and_compression_roundtrip(self):
        rec = EventRecorder()
        server, client = self.pair_length(rec)
        try:
            client.send_to_nodes("hello length mode")
            client.send_to_nodes({"k": [1, 2, 3]}, compression="zlib")
            assert wait_until(lambda: "hello length mode" in rec.messages())
            assert wait_until(lambda: {"k": [1, 2, 3]} in rec.messages())
        finally:
            stop_all([server, client])

    def test_large_frames_cross_recv_chunks(self):
        # The reference's large-frame scenario (5x5000 chars,
        # tests/test_nodeconnection.py:17-77) under the new framing.
        rec = EventRecorder()
        server, client = self.pair_length(rec)
        try:
            msgs = [str(i) * 5000 for i in range(5)]
            for m in msgs:
                client.send_to_nodes(m)
            assert wait_until(
                lambda: all(m in rec.messages() for m in msgs), timeout=10.0)
        finally:
            stop_all([server, client])


class TestCloseSemantics:
    def test_graceful_stop_delivers_in_flight_frames(self):
        # stop() right after send: the close must flush, not abort — the
        # final frame still reaches the peer (abort is reserved for failed
        # transports, e.g. the max_send_buffer trip).
        rec = EventRecorder()
        server, client = pair(rec)
        try:
            client.send_to_nodes("last words " * 2000)
            client.nodes_outbound[0].stop()
            assert wait_until(lambda: "last words " * 2000 in rec.messages(),
                              timeout=10.0)
        finally:
            stop_all([server, client])

    def test_bad_framing_config_rejected_at_construction(self):
        with pytest.raises(ValueError, match="framing"):
            NodeConfig(framing="lenght")

    def test_thread_name_carries_resolved_port(self):
        n = Node("127.0.0.1", 0)
        try:
            assert n.name == f"Node(127.0.0.1:{n.port})"
            assert n.port != 0
        finally:
            stop_all([n])
