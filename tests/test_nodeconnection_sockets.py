"""Transport-level integration tests: framing beyond one recv chunk,
compression end-to-end, connection info store.

Scenario parity with the reference's tests/test_nodeconnection.py (large
frames crossing the 4096-byte recv boundary) and
tests/test_node_compression.py (codec round-trips over sockets, unknown
algorithm delivering nothing), plus the cases the reference left as TODOs
[ref: tests/test_nodeconnection.py:4-5]: bytes payloads and the buffer bound."""

import pytest

from p2pnetwork_tpu import Node, NodeConfig
from tests.helpers import EventRecorder, stop_all, wait_until


def pair(recorder, **server_kw):
    server = Node("127.0.0.1", 0, callback=recorder, **server_kw)
    server.start()
    client = Node("127.0.0.1", 0)
    client.start()
    assert client.connect_with_node("127.0.0.1", server.port)
    assert wait_until(lambda: len(server.nodes_inbound) == 1)
    return server, client


class TestFraming:
    def test_large_str_frames_reassembled(self):
        # Parity: 5 x 5000-char frames, each larger than one 4096-byte chunk
        # [ref: tests/test_nodeconnection.py:17-77].
        rec = EventRecorder()
        server, client = pair(rec)
        try:
            messages = [f"unittest{i}" * 500 for i in range(5)]
            for m in messages:
                client.send_to_nodes(m)
            assert wait_until(lambda: rec.count("node_message") == 5)
            assert rec.data_for("node_message") == messages
        finally:
            stop_all([server, client])

    def test_large_dict_roundtrip(self):
        # Parity: 5000-element dict via JSON [ref: tests/test_nodeconnection.py:79-143].
        rec = EventRecorder()
        server, client = pair(rec)
        try:
            big = {str(i): i for i in range(5000)}
            client.send_to_nodes(big)
            assert wait_until(lambda: rec.count("node_message") == 1)
            assert rec.data_for("node_message")[0] == big
        finally:
            stop_all([server, client])

    def test_large_bytes_roundtrip(self):
        # The reference's untested TODO [ref: tests/test_nodeconnection.py:4].
        rec = EventRecorder()
        server, client = pair(rec)
        try:
            # 0xfe/0xff are never valid utf-8 (so the payload parses back as
            # bytes) and avoid the EOT byte — raw bytes containing 0x04 break
            # framing by design, exactly as in the reference (see wire.py).
            blob = b"\xfe\xff\xf8raw" * 10_000
            client.send_to_nodes(blob)
            assert wait_until(lambda: rec.count("node_message") == 1)
            assert rec.data_for("node_message")[0] == blob
        finally:
            stop_all([server, client])

    def test_buffer_overflow_closes_connection(self):
        # The reference's acknowledged unbounded-buffer bug
        # [ref: nodeconnection.py:206]; here the connection dies cleanly.
        rec = EventRecorder()
        server, client = pair(rec, config=NodeConfig(max_recv_buffer=10_000))
        try:
            client.send_to_nodes("x" * 50_000)  # one frame, exceeds the bound
            assert wait_until(lambda: len(server.nodes_inbound) == 0)
            assert server.message_count_rerr >= 1
            assert rec.count("node_message") == 0
        finally:
            stop_all([server, client])

    def test_info_store(self):
        rec = EventRecorder()
        server, client = pair(rec)
        try:
            conn = server.nodes_inbound[0]
            conn.set_info("role", "miner")
            assert conn.get_info("role") == "miner"
            assert conn.info == {"role": "miner"}
        finally:
            stop_all([server, client])


class TestCompressionOverSockets:
    @pytest.mark.parametrize("algo", ["zlib", "lzma", "bzip2"])
    def test_codec_roundtrip(self, algo):
        # Parity: tests/test_node_compression.py:16-143.
        rec = EventRecorder()
        server, client = pair(rec)
        try:
            payloads = ["plain " * 500, {"big": ["v"] * 1000}, b"\xfe\xff" * 2000]
            for p in payloads:
                client.send_to_nodes(p, compression=algo)
            assert wait_until(lambda: rec.count("node_message") == 3)
            assert rec.data_for("node_message") == payloads
        finally:
            stop_all([server, client])

    def test_unknown_algorithm_delivers_nothing(self):
        # Parity: unknown algorithm -> zero messages delivered
        # [ref: tests/test_node_compression.py:145-185]; rerr counts it
        # (SURVEY.md 2.3.7).
        rec = EventRecorder()
        server, client = pair(rec)
        try:
            client.send_to_nodes("never arrives", compression="snappy")
            client.send_to_nodes("arrives", compression="zlib")
            assert wait_until(lambda: rec.count("node_message") == 1)
            assert rec.data_for("node_message") == ["arrives"]
            assert client.message_count_rerr >= 1
        finally:
            stop_all([server, client])
