"""CRDTs: the merge algebra (commutativity, associativity, idempotence —
the convergence theorem's premises), type semantics (add-wins OR-set,
deterministic LWW ties), wire round-trips, and live multi-node
convergence under concurrent writes."""

import functools
import itertools
import random

import pytest

from p2pnetwork_tpu import (CRDTNode, GCounter, LWWRegister, ORSet,
                            PNCounter)
from tests.helpers import stop_all, wait_until

HOST = "127.0.0.1"


def _sample_gcounters():
    a = GCounter()
    a.increment("A", 3)
    b = GCounter()
    b.increment("A", 1)
    b.increment("B", 5)
    c = GCounter()
    c.increment("C", 2)
    return a, b, c


class TestMergeAlgebra:
    def test_gcounter_laws(self):
        a, b, c = _sample_gcounters()
        assert a.merge(b).counts == b.merge(a).counts
        assert a.merge(b.merge(c)).counts == a.merge(b).merge(c).counts
        assert a.merge(a).counts == a.counts
        # max semantics: A's tallies don't add across replicas' views.
        assert a.merge(b).value == 3 + 5

    def test_orset_laws(self):
        a = ORSet()
        a.add("A", "x")
        a.add("A", "y")
        b = ORSet()
        b.add("B", "x")
        b.remove("x")  # tombstones only B's own observed tag
        c = ORSet()
        c.add("C", "z")
        for u, v in itertools.permutations((a, b, c), 2):
            assert u.merge(v).elements() == v.merge(u).elements()
        assert a.merge(b.merge(c)).elements() \
            == a.merge(b).merge(c).elements()
        assert a.merge(a).elements() == a.elements()

    def test_lww_merge_total_order(self):
        a = LWWRegister("old", 1.0, "A")
        b = LWWRegister("new", 2.0, "B")
        assert a.merge(b).value == b.merge(a).value == "new"
        # Equal timestamps: replica id breaks the tie identically on
        # both sides.
        c = LWWRegister("from-A", 5.0, "A")
        d = LWWRegister("from-B", 5.0, "B")
        assert c.merge(d).value == d.merge(c).value == "from-B"


class TestSemantics:
    def test_pncounter(self):
        p = PNCounter()
        p.increment("A", 10)
        p.decrement("A", 3)
        q = PNCounter()
        q.decrement("B", 2)
        assert p.merge(q).value == 5
        with pytest.raises(ValueError):
            p.increment("A", -1)

    def test_orset_add_wins(self):
        # A removes x having seen only its own tag; concurrently B
        # re-adds x. The merge keeps x — add-wins.
        a = ORSet()
        a.add("A", "x")
        b = a.merge(ORSet())  # b observed A's add
        a.remove("x")
        b.add("B", "x")
        assert "x" in a.merge(b)
        assert "x" in b.merge(a)

    def test_orset_observed_remove(self):
        a = ORSet()
        a.add("A", "x")
        b = a.merge(ORSet())
        b.remove("x")  # b observed the add, so the remove covers it
        assert "x" not in a.merge(b)

    def test_wire_roundtrips(self):
        g = GCounter({"A": 2})
        assert GCounter.from_dict(g.to_dict()).counts == g.counts
        p = PNCounter()
        p.increment("A")
        p.decrement("B", 4)
        assert PNCounter.from_dict(p.to_dict()).value == p.value
        r = LWWRegister("v", 3.5, "A")
        r2 = LWWRegister.from_dict(r.to_dict())
        assert (r2.value, r2.ts, r2.replica) == ("v", 3.5, "A")
        s = ORSet()
        s.add("A", "x")
        s.add("B", "y")
        s.remove("y")
        s2 = ORSet.from_dict(s.to_dict())
        assert s2.elements() == {"x"}
        assert s2.tombs == s.tombs and s2._next == s._next


class TestLiveConvergence:
    def _triangle(self):
        nodes = [CRDTNode(HOST, 0, id=i) for i in "ABC"]
        for n in nodes:
            n.start()
        for i in range(3):
            for j in range(i + 1, 3):
                nodes[i].connect_with_node(HOST, nodes[j].port)
        assert wait_until(lambda: all(len(n.all_nodes) == 2
                                      for n in nodes))
        return nodes

    def test_concurrent_counters_converge(self):
        nodes = self._triangle()
        a, b, c = nodes
        try:
            for n, k in ((a, 5), (b, 3), (c, 9)):
                n.mutate("hits", "pncounter",
                         lambda cr, n=n, k=k: cr.increment(n.id, k))
            assert wait_until(
                lambda: all(n.counter("hits").value == 17 for n in nodes),
                timeout=10.0), [n.counter("hits").value for n in nodes]
        finally:
            stop_all(nodes)

    def test_orset_concurrent_membership(self):
        nodes = self._triangle()
        a, b, c = nodes
        try:
            a.mutate("room", "orset", lambda s: s.add("A", "alice"))
            b.mutate("room", "orset", lambda s: s.add("B", "bob"))
            assert wait_until(
                lambda: all(n.set_("room").elements()
                            == {"alice", "bob"} for n in nodes))
            c.mutate("room", "orset", lambda s: s.remove("alice"))
            assert wait_until(
                lambda: all(n.set_("room").elements() == {"bob"}
                            for n in nodes))
        finally:
            stop_all(nodes)

    def test_late_joiner_catches_up(self):
        nodes = self._triangle()
        a, b, c = nodes
        d = CRDTNode(HOST, 0, id="D")
        try:
            a.mutate("cfg", "lww", lambda r: r.set("A", "v1", ts=1.0))
            assert wait_until(
                lambda: b.register("cfg").value == "v1")
            d.start()
            assert d.connect_with_node(HOST, a.port)
            assert wait_until(lambda: len(a.all_nodes) == 3)
            a.sync_all()
            assert wait_until(lambda: d.register("cfg").value == "v1")
        finally:
            stop_all(nodes + [d])

    def test_kind_mismatch_rejected(self):
        a = CRDTNode(HOST, 0, id="A")
        try:
            a.start()
            a.mutate("x", "pncounter", lambda c: c.increment("A"))
            with pytest.raises(TypeError):
                a.set_("x")
        finally:
            stop_all([a])

    def test_mutation_error_reraised(self):
        # Regression: a raising fn used to vanish into asyncio's handler
        # and mutate() timed out blaming "never ran".
        a = CRDTNode(HOST, 0, id="A")
        try:
            a.start()
            with pytest.raises(ValueError):
                a.mutate("c", "gcounter",
                         lambda g: g.increment("A", -1), timeout=5.0)
            # The gcounter accessor reads what update("gcounter") hosts.
            a.mutate("c", "gcounter", lambda g: g.increment("A", 7))
            assert a.gcounter("c").value == 7
        finally:
            stop_all([a])


class TestRandomizedConvergence:
    """Property fuzz: random op sequences on independent replicas, merged
    in every order (pairwise chains and random shuffles) — the
    convergence theorem says the final state must not depend on merge
    order or duplication. Seeded, so failures replay."""

    @pytest.mark.parametrize("seed", [0, 1, 7])
    def test_pncounter_any_merge_order(self, seed):
        rng = random.Random(seed)
        replicas = []
        for r in range(4):
            c = PNCounter()
            for _ in range(rng.randrange(1, 20)):
                if rng.random() < 0.6:
                    c.increment(f"r{r}", rng.randrange(1, 9))
                else:
                    c.decrement(f"r{r}", rng.randrange(1, 9))
            replicas.append(c)

        def fold(order):
            acc = PNCounter()
            for i in order:
                acc = acc.merge(replicas[i])
                if rng.random() < 0.3:  # duplicate deliveries are free
                    acc = acc.merge(replicas[i])
            return acc.value

        values = {fold(list(p))
                  for p in itertools.permutations(range(4))}
        assert len(values) == 1, f"merge order changed the value: {values}"

    @pytest.mark.parametrize("seed", [2, 5, 11])
    def test_orset_any_merge_order(self, seed):
        rng = random.Random(seed)
        replicas = []
        for r in range(3):
            s = ORSet()
            for _ in range(rng.randrange(2, 25)):
                e = f"e{rng.randrange(8)}"
                if rng.random() < 0.7:
                    s.add(f"r{r}", e)
                else:
                    s.remove(e)  # observed-remove: only locally seen tags
            replicas.append(s)

        def fold(order):
            acc = ORSet()
            for i in order:
                acc = acc.merge(replicas[i])
            return frozenset(acc.elements())

        results = {fold(list(p))
                   for p in itertools.permutations(range(3))}
        assert len(results) == 1, f"merge order changed membership: {results}"

    @pytest.mark.parametrize("seed", [3, 9])
    def test_lww_register_any_merge_order(self, seed):
        rng = random.Random(seed)
        replicas = []
        for r in range(4):
            reg = LWWRegister()
            for i in range(rng.randrange(1, 6)):
                reg.set(f"r{r}", f"v{r}-{i}", ts=rng.randrange(100))
            replicas.append(reg)
        def fold(order):
            acc = functools.reduce(lambda x, y: x.merge(y),
                                   (replicas[i] for i in order))
            return tuple(sorted(acc.to_dict().items(), key=str))

        results = {fold(list(p))
                   for p in itertools.permutations(range(4))}
        assert len(results) == 1
