"""Integration tests of the sockets backend.

These re-create the *scenarios* of the reference suite
(p2pnetwork/tests/test_node.py, SURVEY.md section 4) — topology bookkeeping,
message delivery, event sequences, max_connections, ids — without its
hard-coded sleeps: nodes bind ephemeral ports and tests wait on observable
conditions. Reconnection, which the reference leaves as a TODO
[ref: tests/test_node.py:5], is tested here too."""


from p2pnetwork_tpu import Node, NodeConfig
from tests.helpers import EventRecorder, stop_all, wait_until


def make_node(callback=None, max_connections=0, **kw):
    node = Node("127.0.0.1", 0, callback=callback, max_connections=max_connections, **kw)
    node.start()
    return node


class TestTopology:
    def test_node_connection_bookkeeping(self):
        # Scenario parity: reference test_node.py:15-59.
        n1, n2 = make_node(), make_node()
        try:
            assert n1.connect_with_node("127.0.0.1", n2.port)
            assert wait_until(lambda: len(n1.nodes_outbound) == 1)
            assert wait_until(lambda: len(n2.nodes_inbound) == 1)
            assert n1.nodes_outbound[0].id == n2.id
            assert n2.nodes_inbound[0].id == n1.id
            # Inbound port semantics (SURVEY.md 2.3.8): the stored port of an
            # inbound connection is the peer's *server* port.
            assert n2.nodes_inbound[0].port == n1.port
            assert n1.all_nodes == n1.nodes_inbound + n1.nodes_outbound
        finally:
            stop_all([n1, n2])

    def test_self_connect_refused(self):
        n1 = make_node()
        try:
            assert n1.connect_with_node("127.0.0.1", n1.port) is False
            assert n1.nodes_outbound == []
        finally:
            stop_all([n1])

    def test_duplicate_connect_is_noop_true(self):
        n1, n2 = make_node(), make_node()
        try:
            assert n1.connect_with_node("127.0.0.1", n2.port)
            assert wait_until(lambda: len(n1.nodes_outbound) == 1)
            assert n1.connect_with_node("127.0.0.1", n2.port) is True
            assert len(n1.nodes_outbound) == 1
        finally:
            stop_all([n1, n2])

    def test_duplicate_id_guard(self):
        # Two nodes with the same explicit id: second connection refused with
        # the CLOSING handshake, reported True [ref: node.py:153-156].
        n1 = make_node(id="same")
        n2 = make_node()
        n3 = make_node(id="same")
        try:
            assert n2.connect_with_node("127.0.0.1", n1.port)
            assert wait_until(lambda: len(n2.nodes_outbound) == 1)
            assert n2.connect_with_node("127.0.0.1", n3.port) is True
            # No second outbound connection was registered.
            assert len(n2.nodes_outbound) == 1
        finally:
            stop_all([n1, n2, n3])

    def test_three_node_topology(self):
        # Scenario parity: reference test_node.py:106-194.
        n1, n2, n3 = make_node(), make_node(), make_node()
        try:
            assert n1.connect_with_node("127.0.0.1", n2.port)
            assert n2.connect_with_node("127.0.0.1", n3.port)
            assert n3.connect_with_node("127.0.0.1", n1.port)
            assert wait_until(
                lambda: all(
                    len(n.nodes_inbound) == 1 and len(n.nodes_outbound) == 1
                    for n in (n1, n2, n3)
                )
            )
            assert n1.nodes_outbound[0].id == n2.id
            assert n1.nodes_inbound[0].id == n3.id
        finally:
            stop_all([n1, n2, n3])

    def test_disconnect_with_node(self):
        rec1, rec2 = EventRecorder(), EventRecorder()
        n1, n2 = make_node(rec1), make_node(rec2)
        try:
            n1.connect_with_node("127.0.0.1", n2.port)
            assert wait_until(lambda: len(n2.nodes_inbound) == 1)
            n1.disconnect_with_node(n1.nodes_outbound[0])
            assert wait_until(lambda: len(n1.nodes_outbound) == 0)
            assert wait_until(lambda: len(n2.nodes_inbound) == 0)
            assert rec1.count("node_disconnect_with_outbound_node") == 1
            assert rec1.count("outbound_node_disconnected") == 1
            assert wait_until(lambda: rec2.count("inbound_node_disconnected") == 1)
        finally:
            stop_all([n1, n2])


class TestMessaging:
    def test_str_dict_bytes_delivery(self):
        # Scenario parity: reference test_node.py:61-104 + dict/bytes payloads.
        rec = EventRecorder()
        n1, n2 = make_node(), make_node(rec)
        try:
            n1.connect_with_node("127.0.0.1", n2.port)
            assert wait_until(lambda: len(n2.nodes_inbound) == 1)
            n1.send_to_nodes("hello")
            n1.send_to_nodes({"k": "v", "n": 7})
            n1.send_to_nodes(b"\x00\xffraw")
            assert wait_until(lambda: rec.count("node_message") == 3)
            assert rec.data_for("node_message") == ["hello", {"k": "v", "n": 7}, b"\x00\xffraw"]
            assert n1.message_count_send == 3
            assert n2.message_count_recv == 3
        finally:
            stop_all([n1, n2])

    def test_exclude_list(self):
        rec2, rec3 = EventRecorder(), EventRecorder()
        n1, n2, n3 = make_node(), make_node(rec2), make_node(rec3)
        try:
            n1.connect_with_node("127.0.0.1", n2.port)
            n1.connect_with_node("127.0.0.1", n3.port)
            assert wait_until(lambda: len(n1.nodes_outbound) == 2)
            excluded = [c for c in n1.nodes_outbound if c.id == n3.id]
            n1.send_to_nodes("only for n2", exclude=excluded)
            assert wait_until(lambda: rec2.count("node_message") == 1)
            assert rec3.count("node_message") == 0
        finally:
            stop_all([n1, n2, n3])

    def test_send_to_unknown_node_counts_send(self):
        # Parity: message_count_send increments before the membership check
        # [ref: node.py:116-117].
        n1, n2 = make_node(), make_node()
        try:
            n1.connect_with_node("127.0.0.1", n2.port)
            assert wait_until(lambda: len(n2.nodes_inbound) == 1)
            foreign = n2.nodes_inbound[0]
            n1.send_to_node(foreign, "nope")
            assert n1.message_count_send == 1
        finally:
            stop_all([n1, n2])

    def test_bidirectional_messaging(self):
        rec1, rec2 = EventRecorder(), EventRecorder()
        n1, n2 = make_node(rec1), make_node(rec2)
        try:
            n1.connect_with_node("127.0.0.1", n2.port)
            assert wait_until(lambda: len(n2.nodes_inbound) == 1)
            n1.send_to_nodes("ping")
            assert wait_until(lambda: rec2.count("node_message") == 1)
            n2.send_to_nodes("pong")
            assert wait_until(lambda: rec1.count("node_message") == 1)
            assert rec1.data_for("node_message") == ["pong"]
        finally:
            stop_all([n1, n2])


class TestEvents:
    def test_connect_event_sequence(self):
        # Scenario parity: reference test_node.py:196-276 (event counts), with
        # exact per-node assertions instead of order-tolerant branches.
        rec1, rec2 = EventRecorder(), EventRecorder()
        n1, n2 = make_node(rec1), make_node(rec2)
        try:
            n1.connect_with_node("127.0.0.1", n2.port)
            assert wait_until(lambda: rec1.count("outbound_node_connected") == 1)
            assert wait_until(lambda: rec2.count("inbound_node_connected") == 1)
            n1.stop()
            n1.join()
            assert wait_until(lambda: rec2.count("inbound_node_disconnected") == 1)
            assert rec1.count("node_request_to_stop") == 1
        finally:
            stop_all([n1, n2])

    def test_subclass_override_parity(self):
        # Scenario parity: reference test_node.py:278-396 — the same behavior
        # is reachable by overriding the event methods instead of a callback.
        log = []

        class MyNode(Node):
            def inbound_node_connected(self, node):
                log.append(("in", node.id))
                super().inbound_node_connected(node)

            def node_message(self, node, data):
                log.append(("msg", data))
                super().node_message(node, data)

        server = MyNode("127.0.0.1", 0)
        server.start()
        client = make_node()
        try:
            client.connect_with_node("127.0.0.1", server.port)
            assert wait_until(lambda: len(server.nodes_inbound) == 1)
            client.send_to_nodes("via-override")
            assert wait_until(lambda: ("msg", "via-override") in log)
            assert ("in", client.id) in log
        finally:
            stop_all([server, client])

    def test_connection_error_event(self):
        rec = EventRecorder()
        n1 = make_node(rec)
        try:
            # Nothing listens on this port.
            dead = Node("127.0.0.1", 0)
            free_port = dead.port
            dead.sock.close()
            assert n1.connect_with_node("127.0.0.1", free_port) is False
            assert rec.count("outbound_node_connection_error") == 1
            assert n1.message_count_rerr >= 1  # rerr is live (SURVEY.md 2.3.7)
        finally:
            stop_all([n1])

    def test_event_log_records_history(self):
        n1, n2 = make_node(), make_node()
        try:
            n1.connect_with_node("127.0.0.1", n2.port)
            assert wait_until(lambda: n2.event_log.count("inbound_node_connected") == 1)
            n1.send_to_nodes("x")
            assert wait_until(lambda: n2.event_log.count("node_message") == 1)
            names = [e.event for e in n2.event_log.snapshot()]
            assert names.index("inbound_node_connected") < names.index("node_message")
        finally:
            stop_all([n1, n2])


class TestLimitsAndIds:
    def test_max_connections(self):
        # Scenario parity: reference test_node.py:398-455.
        limited = make_node(max_connections=1)
        n2, n3 = make_node(), make_node()
        try:
            assert n2.connect_with_node("127.0.0.1", limited.port)
            assert wait_until(lambda: len(limited.nodes_inbound) == 1)
            # Second connect is refused by the server; unlike the reference
            # (which registers a phantom empty-id peer on the client) the
            # client reports failure.
            assert n3.connect_with_node("127.0.0.1", limited.port) is False
            assert len(limited.nodes_inbound) == 1
            assert n3.nodes_outbound == []
        finally:
            stop_all([limited, n2, n3])

    def test_explicit_and_generated_ids(self):
        # Scenario parity: reference test_node.py:457-483.
        explicit = Node("127.0.0.1", 0, id=1234)
        generated = Node("127.0.0.1", 0)
        try:
            assert explicit.id == "1234"  # coerced to str [ref: node.py:58]
            assert isinstance(generated.id, str) and len(generated.id) == 128
            assert generated.generate_id() != generated.id
        finally:
            explicit.sock.close()
            generated.sock.close()


class TestLifecycle:
    def test_stop_is_idempotent(self):
        n1 = make_node()
        n1.stop()
        n1.join()
        n1.stop()  # after the loop is gone: still a no-op, no RuntimeError
        assert not n1.is_alive()

    def test_send_after_stop_is_harmless(self):
        n1, n2 = make_node(), make_node()
        n1.connect_with_node("127.0.0.1", n2.port)
        assert wait_until(lambda: len(n1.nodes_outbound) == 1)
        conn = n1.nodes_outbound[0]
        stop_all([n1, n2])
        conn.send("too late")  # loop closed — debug no-op, no exception

    def test_reconnect_nodes_callable_from_event_handler(self):
        # Calling the manual reconnect trigger from inside an event handler
        # (on the node's own loop) must not deadlock.
        class TriggerNode(Node):
            def node_message(self, conn, data):
                self.reconnect_nodes()
                super().node_message(conn, data)

        rec = EventRecorder()
        server = TriggerNode("127.0.0.1", 0, callback=rec)
        server.start()
        client = make_node()
        try:
            client.connect_with_node("127.0.0.1", server.port)
            assert wait_until(lambda: len(server.nodes_inbound) == 1)
            client.send_to_nodes("poke")
            assert wait_until(lambda: rec.count("node_message") == 1)
        finally:
            stop_all([server, client])


class TestReconnect:
    def test_reconnects_after_peer_restart(self):
        # The reference leaves reconnection untested [ref: tests/test_node.py:5]
        # and its implementation has the tries/trials KeyError (SURVEY.md
        # 2.3.1). Here: a registered peer drops and comes back; the client
        # re-establishes automatically.
        cfg = NodeConfig(reconnect_interval=0.1, reconnect_backoff_base=0.1,
                         reconnect_backoff_max=0.5)
        server = make_node()
        server_port = server.port
        client = Node("127.0.0.1", 0, config=cfg)
        client.start()
        try:
            assert client.connect_with_node("127.0.0.1", server_port, reconnect=True)
            assert wait_until(lambda: len(client.nodes_outbound) == 1)
            server.stop()
            server.join()
            assert wait_until(lambda: len(client.nodes_outbound) == 0)
            # Restart a server on the same port.
            server = Node("127.0.0.1", server_port)
            server.start()
            assert wait_until(lambda: len(client.nodes_outbound) == 1, timeout=10.0)
            # The reconnect succeeded, so the trial counter was reset by the
            # next registry tick (a live peer zeroes its entry).
            assert wait_until(
                lambda: client.reconnect_to_nodes[0]["trials"] == 0)
        finally:
            stop_all([server, client])

    def test_policy_hook_deregisters(self):
        cfg = NodeConfig(reconnect_interval=0.05, reconnect_backoff_base=0.05,
                         reconnect_backoff_max=0.2)

        class GiveUpNode(Node):
            def node_reconnection_error(self, host, port, trials):
                return trials < 3  # stop retrying after 3 trials

        server = make_node()
        client = GiveUpNode("127.0.0.1", 0, config=cfg)
        client.start()
        try:
            assert client.connect_with_node("127.0.0.1", server.port, reconnect=True)
            assert wait_until(lambda: len(client.nodes_outbound) == 1)
            port = server.port
            server.stop()
            server.join()
            # With no server to come back, the policy hook gives up and the
            # registry entry is removed.
            assert wait_until(lambda: client.reconnect_to_nodes == [], timeout=10.0)
        finally:
            stop_all([server, client])


class TestThreadParity:
    """Node IS a threading.Thread, like the reference's
    [ref: p2pnetwork/node.py:13] — applications may isinstance-check it,
    read .name/.daemon, and use join/is_alive as Thread methods."""

    def test_node_is_a_thread(self):
        import threading

        n = Node("127.0.0.1", 0)
        try:
            assert isinstance(n, threading.Thread)
            assert n.daemon  # reference sets daemon threads in examples
            assert n.name.startswith("Node(")
            assert not n.is_alive()
            n.start()
            assert n.is_alive()
        finally:
            stop_all([n])
        assert wait_until(lambda: not n.is_alive())

    def test_double_start_raises_thread_error(self):
        n = make_node()
        try:
            import pytest

            with pytest.raises(RuntimeError):
                n.start()  # Thread contract: threads start once
        finally:
            stop_all([n])


class TestConnectFromHandler:
    """The documented contract of connect_with_node when called ON the
    node's own loop (i.e. from an event handler): the attempt is scheduled,
    the call reports True once the guards pass, and failures surface
    through outbound_node_connection_error — the reference's error channel
    [ref: node.py:173-176]."""

    def test_scheduled_connect_failure_fires_error_event(self):
        rec = EventRecorder()
        results = []

        def cb(event, main_node, connected_node, data):
            rec(event, main_node, connected_node, data)
            if event == "node_message" and data == "go":
                # Dead port: nothing listens on port 1 on loopback.
                results.append(main_node.connect_with_node("127.0.0.1", 1))

        n1, n2 = make_node(cb), make_node()
        try:
            assert n2.connect_with_node("127.0.0.1", n1.port)
            assert wait_until(lambda: len(n2.nodes_outbound) == 1)
            n2.send_to_nodes("go")
            # The scheduled path returns True immediately (guards passed)...
            assert wait_until(lambda: results == [True])
            # ...and the real outcome arrives as the error event.
            assert wait_until(
                lambda: "outbound_node_connection_error" in rec.names()
            )
            assert len(n1.nodes_outbound) == 0
        finally:
            stop_all([n1, n2])

    def test_scheduled_connect_success_fires_connected_event(self):
        rec = EventRecorder()

        def cb(event, main_node, connected_node, data):
            rec(event, main_node, connected_node, data)
            if event == "node_message" and isinstance(data, dict):
                main_node.connect_with_node("127.0.0.1", data["port"])

        n1, n2, n3 = make_node(cb), make_node(), make_node()
        try:
            assert n2.connect_with_node("127.0.0.1", n1.port)
            assert wait_until(lambda: len(n2.nodes_outbound) == 1)
            n2.send_to_nodes({"port": n3.port})
            assert wait_until(lambda: len(n1.nodes_outbound) == 1)
            assert n1.nodes_outbound[0].id == n3.id
            assert "outbound_node_connected" in rec.names()
        finally:
            stop_all([n1, n2, n3])
