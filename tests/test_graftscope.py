"""graftscope tests: flight recorder, trace plane, history ring, wiring.

Covers the PR-12 observability plane end to end:

- flight-recorder parity: recorder-on runs bit-identical to recorder-off
  across engine (run_from / coverage_from / batch) and sharded (flood +
  batch, BOTH comm backends), ring contents sane, wrap semantics, ring
  donation honored, and the slow-marked <= 1.10x overhead ratchet on a
  100k-node WS flood;
- trace plane: span trees, thread-local nesting, Chrome/Perfetto +
  JSONL exporters, lane lifecycle events
  (submit/admit/resume/complete/freeze/retire), supervise chunk
  boundaries, and the batched-run Perfetto schema acceptance;
- history ring: sampling, capacity bound, per-run auto-sampling, and
  the ``/history`` + ``/trace`` endpoints (incl. an N-thread concurrent
  scrape hammer and a graftrace-seam scrape storm);
- satellites: Prometheus label/help escaping pin, jaxhooks install
  idempotence, bench probe_log + profiler bracket.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from p2pnetwork_tpu import concurrency, telemetry
from p2pnetwork_tpu.models.flood import Flood
from p2pnetwork_tpu.models.messagebatch import BatchFlood
from p2pnetwork_tpu.sim import engine, flightrec
from p2pnetwork_tpu.sim import graph as G
from p2pnetwork_tpu.telemetry import export, history, jaxhooks, spans

pytestmark = pytest.mark.scope


@pytest.fixture
def fresh_registry():
    fresh = telemetry.Registry()
    prev = telemetry.set_default_registry(fresh)
    yield fresh
    telemetry.set_default_registry(prev)


@pytest.fixture
def fresh_history():
    fresh = history.History()
    prev = history.set_default_history(fresh)
    yield fresh
    history.set_default_history(prev)


@pytest.fixture
def tracer():
    t = spans.Tracer("test-run")
    prev = spans.install_tracer(t)
    yield t
    spans.install_tracer(prev)


@pytest.fixture(scope="module")
def ws_graph():
    return G.watts_strogatz(512, 4, 0.1, seed=0)


def _assert_batch_equal(b1, b2):
    import dataclasses

    for f in dataclasses.fields(b1):
        a = np.asarray(getattr(b1, f.name))
        b = np.asarray(getattr(b2, f.name))
        assert np.array_equal(a, b), f"batch leaf {f.name} diverges"


def _assert_out_equal(o1, o2):
    assert set(o1) == set(o2)
    for k in o1:
        v1, v2 = o1[k], o2[k]
        if isinstance(v1, np.ndarray):
            assert np.array_equal(v1, v2), k
        else:
            assert v1 == v2, (k, v1, v2)


# ------------------------------------------------------ flight recorder unit


class TestFlightRecorderUnit:
    def test_capacity_validation(self):
        with pytest.raises(ValueError, match="capacity"):
            flightrec.FlightRecorder(capacity=0)

    def test_init_shape_and_dtype(self):
        ring = flightrec.FlightRecorder(capacity=5).init()
        assert ring.shape == (5, len(flightrec.REC_COLS))
        assert ring.dtype == jnp.float32

    def test_trim_no_wrap(self):
        ring = np.arange(40, dtype=np.float32).reshape(8, 5)
        fr = flightrec.trim(ring, 3)
        assert fr.rows.shape == (3, 5)
        assert fr.dropped == 0 and fr.rounds == 3
        assert np.array_equal(fr.rows, ring[:3])

    def test_trim_wrap_keeps_last_capacity_rounds(self):
        # 10 rounds into a 4-deep ring: rounds 7..10 survive, slot
        # 10 % 4 = 2 is the oldest surviving row's position.
        cap, rounds = 4, 10
        ring = np.zeros((cap, len(flightrec.REC_COLS)), dtype=np.float32)
        for r in range(rounds):
            ring[r % cap, 0] = r + 1  # the round column
        fr = flightrec.trim(ring, rounds)
        assert fr.dropped == rounds - cap
        assert fr.column("round").tolist() == [7.0, 8.0, 9.0, 10.0]

    def test_as_dict_roundtrips_json(self):
        fr = flightrec.trim(
            np.ones((4, len(flightrec.REC_COLS)), np.float32), 2)
        doc = json.loads(json.dumps(fr.as_dict()))
        assert doc["rounds"] == 2 and doc["capacity"] == 4
        assert set(doc["columns"]) == set(flightrec.REC_COLS)
        assert len(doc["columns"]["round"]) == 2


# ------------------------------------------------------ engine recorder


class TestEngineRecorder:
    def test_coverage_from_parity_and_record(self, ws_graph):
        g = ws_graph
        proto = Flood(source=0)
        key = jax.random.key(0)
        s1, o1 = engine.run_until_coverage_from(
            g, proto, proto.init(g, key), key, donate=False, max_rounds=64)
        s2, o2 = engine.run_until_coverage_from(
            g, proto, proto.init(g, key), key, donate=False, max_rounds=64,
            recorder=flightrec.FlightRecorder(capacity=128))
        fr = o2.pop("flight_record")
        _assert_out_equal(o1, o2)
        assert np.array_equal(np.asarray(s1.seen), np.asarray(s2.seen))
        assert np.array_equal(np.asarray(s1.frontier),
                              np.asarray(s2.frontier))
        # Ring contents: rounds rows, monotone round index, message
        # totals cumulative, final coverage at/above target.
        assert fr.rows.shape[0] == o1["rounds"] and fr.dropped == 0
        assert fr.column("round").tolist() == [
            float(i + 1) for i in range(o1["rounds"])]
        assert np.all(np.diff(fr.column("total")) >= 0)
        assert fr.column("total")[-1] == float(o1["messages"])
        assert fr.column("coverage")[-1] >= 0.99
        assert np.all(fr.column("ici_bytes") == 0)
        assert np.all(fr.column("active_lanes") == 1)

    def test_coverage_from_recorder_wraps(self, ws_graph):
        g = ws_graph
        proto = Flood(source=0)
        key = jax.random.key(0)
        _, o = engine.run_until_coverage_from(
            g, proto, proto.init(g, key), key, donate=False, max_rounds=64,
            recorder=flightrec.FlightRecorder(capacity=4))
        fr = o["flight_record"]
        assert o["rounds"] > 4  # the premise: this run wraps
        assert fr.rows.shape[0] == 4
        assert fr.dropped == o["rounds"] - 4
        assert fr.column("round").tolist() == [
            float(r) for r in range(o["rounds"] - 3, o["rounds"] + 1)]

    def test_coverage_from_steps_per_round_parity(self, ws_graph):
        g = ws_graph
        proto = Flood(source=0)
        key = jax.random.key(3)
        s1, o1 = engine.run_until_coverage_from(
            g, proto, proto.init(g, key), key, donate=False, max_rounds=64,
            steps_per_round=4)
        s2, o2 = engine.run_until_coverage_from(
            g, proto, proto.init(g, key), key, donate=False, max_rounds=64,
            steps_per_round=4,
            recorder=flightrec.FlightRecorder(capacity=64))
        fr = o2.pop("flight_record")
        _assert_out_equal(o1, o2)
        assert np.array_equal(np.asarray(s1.seen), np.asarray(s2.seen))
        # Frozen sub-steps of the final super-step write no rows: row
        # count equals APPLIED rounds exactly.
        assert fr.rows.shape[0] == o1["rounds"]
        assert fr.column("round").tolist() == [
            float(i + 1) for i in range(o1["rounds"])]

    def test_run_from_parity_and_record(self, ws_graph):
        g = ws_graph
        proto = Flood(source=2)
        key = jax.random.key(1)
        s1, stats1 = engine.run_from(g, proto, proto.init(g, key), key, 6,
                                     donate=False)
        s2, stats2, fr = engine.run_from(
            g, proto, proto.init(g, key), key, 6, donate=False,
            recorder=flightrec.FlightRecorder(capacity=16))
        assert np.array_equal(np.asarray(s1.seen), np.asarray(s2.seen))
        for k in stats1:
            assert np.array_equal(np.asarray(stats1[k]),
                                  np.asarray(stats2[k])), k
        # The ring's per-round columns ARE the scan stats, recorded
        # device-side.
        assert np.array_equal(
            fr.column("new"),
            np.asarray(stats1["messages"]).astype(np.float32))
        assert np.array_equal(
            fr.column("coverage"),
            np.asarray(stats1["coverage"]).astype(np.float32))
        assert np.array_equal(
            fr.column("occupancy"),
            np.asarray(stats1["frontier_occupancy"]).astype(np.float32))

    def test_batch_parity_and_record(self, ws_graph):
        g = ws_graph
        proto = BatchFlood()
        key = jax.random.key(2)
        sources = np.arange(40, dtype=np.int32) * 7 % 512
        b1 = proto.init(g, sources)
        b2 = proto.init(g, sources)
        r1, o1 = engine.run_batch_until_coverage(
            g, proto, b1, key, donate=False, max_rounds=64)
        r2, o2 = engine.run_batch_until_coverage(
            g, proto, b2, key, donate=False, max_rounds=64,
            recorder=flightrec.FlightRecorder(capacity=128))
        fr = o2.pop("flight_record")
        _assert_out_equal(o1, o2)
        _assert_batch_equal(r1, r2)
        assert fr.rows.shape[0] == o1["rounds"]
        # active_lanes starts at B and ends at the summary's count.
        assert fr.column("active_lanes")[0] == float(len(sources))
        assert fr.column("active_lanes")[-1] == float(o1["active_lanes"])
        assert fr.column("total")[-1] == float(o1["messages"])

    def test_recorder_ring_donated_and_honored(self, ws_graph):
        from p2pnetwork_tpu.analysis.ir.donation import check_aliasing

        g = ws_graph
        proto = BatchFlood()
        batch = proto.init(g, np.arange(32, dtype=np.int32) * 5 % 512)
        counts = check_aliasing(
            engine._batch_loop_rec_donating,
            (g, proto, batch, jax.random.key(0),
             flightrec.FlightRecorder(capacity=32).init()),
            10, {"max_rounds": 64})
        assert counts["requested"] == counts["honored"] == 10

    def test_recorder_donation_invalidates_state(self, ws_graph):
        g = ws_graph
        proto = Flood(source=0)
        key = jax.random.key(0)
        state = proto.init(g, key)
        # one undonated step first so leaves are distinct buffers
        state, _ = engine.run_from(g, proto, state, key, 1, donate=False)
        engine.run_until_coverage_from(
            g, proto, state, key, max_rounds=4,
            recorder=flightrec.FlightRecorder(capacity=8))
        with pytest.raises(ValueError, match="donated"):
            engine.run_until_coverage_from(g, proto, state, key,
                                           max_rounds=4)

    @pytest.mark.slow
    def test_recorder_overhead_ratchet(self):
        # Acceptance: recorder-on wall <= 1.10x recorder-off on a
        # 100k-node WS flood (ratio-based — no absolute wall clocks).
        g = G.watts_strogatz(100_000, 10, 0.1, seed=0)
        proto = Flood(source=0)
        key = jax.random.key(0)
        rec = flightrec.FlightRecorder(capacity=256)

        def run(recorder):
            state = proto.init(g, key)
            t0 = __import__("time").perf_counter()
            _, out = engine.run_until_coverage_from(
                g, proto, state, key, donate=False, max_rounds=64,
                recorder=recorder)
            return __import__("time").perf_counter() - t0, out

        run(None)  # warm both compiled programs before timing
        run(rec)
        offs, ons = [], []
        for _ in range(7):  # interleaved best-of-7, CPU-noise-robust
            offs.append(run(None)[0])
            ons.append(run(rec)[0])
        ratio = min(ons) / min(offs)
        assert ratio <= 1.10, (
            f"flight recorder overhead {ratio:.3f}x exceeds the 1.10x "
            f"ratchet (off {min(offs):.4f}s on {min(ons):.4f}s)")


# ------------------------------------------------------ sharded recorder


@pytest.fixture(scope="module")
def sharded_setup():
    from p2pnetwork_tpu.parallel import mesh as M
    from p2pnetwork_tpu.parallel import sharded as SH

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    g = G.watts_strogatz(512, 4, 0.1, seed=0)
    mesh = M.ring_mesh(8)
    sg = SH.shard_graph(g, mesh)
    return g, mesh, sg


class TestShardedRecorder:
    @pytest.mark.parametrize("comm", ["ppermute", "pallas"])
    def test_flood_parity_and_ici_column(self, sharded_setup, comm):
        from p2pnetwork_tpu.parallel import sharded as SH

        g, mesh, sg = sharded_setup
        s1, o1 = SH.flood_until_coverage(
            sg, mesh, 0, coverage_target=0.99, max_rounds=64, comm=comm)
        s2, o2 = SH.flood_until_coverage(
            sg, mesh, 0, coverage_target=0.99, max_rounds=64, comm=comm,
            recorder=flightrec.FlightRecorder(capacity=64))
        fr = o2.pop("flight_record")
        _assert_out_equal(o1, o2)
        assert np.array_equal(np.asarray(s1), np.asarray(s2))
        assert fr.rows.shape[0] == o1["rounds"]
        # The ici column carries the static per-round comm-census
        # estimate — nonzero, constant, and backend-agnostic in price
        # (PR 11 pins pallas DMA pricing == ppermute pricing).
        ici = fr.column("ici_bytes")
        assert ici[0] > 0 and np.all(ici == ici[0])
        # coverage column is the psum'd covered-node count here.
        assert fr.column("coverage")[-1] >= 0.99 * 512

    @pytest.mark.parametrize("comm", ["ppermute", "pallas"])
    def test_batch_parity_both_backends(self, sharded_setup, comm):
        from p2pnetwork_tpu.parallel import sharded as SH

        g, mesh, sg = sharded_setup
        proto = BatchFlood()
        sources = np.arange(40, dtype=np.int32) * 3 % 512
        b1 = proto.init(g, sources)
        b2 = proto.init(g, sources)
        r1, o1 = SH.run_batch_until_coverage(
            sg, mesh, proto, b1, max_rounds=64, comm=comm, donate=False)
        r2, o2 = SH.run_batch_until_coverage(
            sg, mesh, proto, b2, max_rounds=64, comm=comm, donate=False,
            recorder=flightrec.FlightRecorder(capacity=64))
        fr = o2.pop("flight_record")
        _assert_out_equal(o1, o2)
        _assert_batch_equal(r1, r2)
        assert fr.column("ici_bytes")[0] > 0

    def test_sharded_rows_match_engine_rows(self, sharded_setup):
        # The sharded batch loop's ring rows must equal the engine
        # loop's on the same batch — every column except the ici
        # estimate (single-chip records 0 there).
        from p2pnetwork_tpu.parallel import sharded as SH

        g, mesh, sg = sharded_setup
        proto = BatchFlood()
        sources = np.arange(40, dtype=np.int32) * 3 % 512
        rec = flightrec.FlightRecorder(capacity=64)
        _, oe = engine.run_batch_until_coverage(
            g, proto, proto.init(g, sources), jax.random.key(0),
            donate=False, max_rounds=64, recorder=rec)
        _, os_ = SH.run_batch_until_coverage(
            sg, mesh, proto, proto.init(g, sources), max_rounds=64,
            donate=False, recorder=rec)
        re_, rs = oe["flight_record"], os_["flight_record"]
        ici_col = flightrec.REC_COLS.index("ici_bytes")
        assert np.array_equal(re_.rows[:, :ici_col], rs.rows[:, :ici_col])

    def test_adaptive_path_refuses_recorder(self, sharded_setup):
        from p2pnetwork_tpu.parallel import sharded as SH

        g, mesh, _ = sharded_setup
        sg = SH.shard_graph(g, mesh, source_csr=True)
        with pytest.raises(ValueError, match="adaptive"):
            SH.flood_until_coverage(
                sg, mesh, 0, adaptive_k=64,
                recorder=flightrec.FlightRecorder())


# ------------------------------------------------------------ trace plane


class TestTracer:
    def test_span_tree_and_parent_links(self):
        clock = iter(float(i) for i in range(100))
        t = spans.Tracer("root", clock=lambda: next(clock))
        with t.span("outer", kind="a") as outer:
            t.point("inner-event", lane=3)
            with t.span("inner") as inner:
                pass
        by_id = {sp.span_id: sp for sp in t.spans()}
        names = {sp.name: sp for sp in t.spans()}
        assert names["outer"].parent_id == t.root
        assert names["inner-event"].parent_id == outer
        assert names["inner"].parent_id == outer
        assert by_id[inner].t1 is not None
        assert names["root"].parent_id is None
        assert names["inner-event"].args == {"lane": 3}

    def test_thread_local_current_stack(self):
        t = spans.Tracer("root")
        seen = {}

        def worker():
            # A foreign thread has no enclosing span context: its
            # events parent to the ROOT, not whatever the main thread
            # currently has open.
            seen["sid"] = t.point("from-thread")

        with t.span("main-only"):
            th = concurrency.thread(target=worker, name="spans-worker")
            th.start()
            th.join(timeout=10)
        sp = [s for s in t.spans() if s.span_id == seen["sid"]][0]
        assert sp.parent_id == t.root

    def test_to_chrome_schema(self):
        t = spans.Tracer("root")
        with t.span("work", step=1):
            t.point("evt")
        t.close()
        doc = t.to_chrome()
        assert set(doc) == {"traceEvents", "displayTimeUnit", "metadata"}
        assert doc["metadata"]["dropped_spans"] == 0  # graftsight's honesty
        assert doc["metadata"]["spans"] == len(doc["traceEvents"])
        for ev in doc["traceEvents"]:
            assert ev["ph"] == "X" and ev["cat"] == "graftscope"
            assert ev["dur"] >= 0 and ev["ts"] > 0
            assert "span_id" in ev["args"] and "parent_id" in ev["args"]
            assert ev["args"]["trace_id"] == t.trace_id
        json.dumps(doc)  # must serialize

    def test_to_records_shared_jsonl_schema(self, tmp_path):
        t = spans.Tracer("root")
        with t.span("work"):
            pass
        recs = t.to_records()
        for rec in recs:
            assert rec["type"] == "event"
            assert set(rec) == {"type", "name", "ts", "labels", "data"}
            assert rec["labels"]["trace"] == t.trace_id
        path = str(tmp_path / "trace.jsonl")
        n = t.write_jsonl(path)
        lines = open(path, encoding="utf-8").read().splitlines()
        assert len(lines) == n == len(recs)
        json.loads(lines[0])

    def test_emit_noop_without_tracer(self):
        assert spans.current_tracer() is None or True  # state-agnostic
        prev = spans.uninstall_tracer()
        try:
            spans.emit("nobody-listening", lane=1)
            with spans.span("nobody") as sid:
                assert sid is None
        finally:
            spans.install_tracer(prev)

    def test_max_spans_bound_drops_oldest_keeps_root(self):
        t = spans.Tracer("root", max_spans=3)
        for i in range(6):
            t.point(f"e{i}")
        assert [s.name for s in t.spans()] == ["root", "e3", "e4", "e5"]
        assert t.dropped_spans == 3
        t.close()
        assert [s for s in t.spans() if s.name == "root"][0].t1 is not None

    def test_install_returns_previous(self):
        t1, t2 = spans.Tracer("a"), spans.Tracer("b")
        prev0 = spans.install_tracer(t1)
        try:
            assert spans.install_tracer(t2) is t1
            assert spans.current_tracer() is t2
        finally:
            spans.install_tracer(prev0)


class TestLaneLifecycleEvents:
    def test_admit_retire_emit(self, ws_graph, tracer):
        proto = BatchFlood()
        batch = proto.init(ws_graph, [1, 2], capacity=40)
        submits = tracer.find("lane_submit")
        assert sorted(s.args["lane"] for s in submits) == [0, 1]
        assert {s.args["source"] for s in submits} == {1, 2}
        proto.retire(batch, [1])
        retires = tracer.find("lane_retire")
        assert [s.args["lane"] for s in retires] == [1]

    def test_admit_under_tracer_keeps_batch_identical(self, ws_graph):
        # Regression: the lane_submit emit loop once shadowed the `src`
        # device array, so tracing-on admits scattered the LAST source
        # id into every lane's metadata. Tracing must change NOTHING
        # about the batch.
        proto = BatchFlood()
        sources = [3, 7, 11]
        b_off = proto.init(ws_graph, sources, capacity=8)
        t = spans.Tracer("admit-regression")
        prev = spans.install_tracer(t)
        try:
            b_on = proto.init(ws_graph, sources, capacity=8)
        finally:
            spans.install_tracer(prev)
        assert np.asarray(b_on.source)[:3].tolist() == sources
        _assert_batch_equal(b_off, b_on)

    def test_run_emits_admit_complete_under_run_span(self, ws_graph,
                                                     tracer, fresh_registry,
                                                     fresh_history):
        proto = BatchFlood()
        batch = proto.init(ws_graph, np.arange(8, dtype=np.int32) + 1)
        engine.run_batch_until_coverage(
            ws_graph, proto, batch, jax.random.key(0), donate=True,
            max_rounds=64)
        runs = tracer.find("batch_run")
        assert len(runs) == 1 and runs[0].args["loop"] == "engine"
        admits = tracer.find("lane_admit")
        completes = tracer.find("lane_complete")
        assert sorted(a.args["lane"] for a in admits) == list(range(8))
        assert sorted(c.args["lane"] for c in completes) == list(range(8))
        for ev in admits + completes:
            assert ev.parent_id == runs[0].span_id
        assert tracer.find("lane_freeze") == []

    def test_freeze_and_resume_events(self, ws_graph, tracer,
                                      fresh_registry, fresh_history):
        proto = BatchFlood()
        batch = proto.init(ws_graph, np.arange(8, dtype=np.int32) + 1)
        # max_rounds=1 cuts every lane off -> freeze events, no completes
        batch, _ = engine.run_batch_until_coverage(
            ws_graph, proto, batch, jax.random.key(0), donate=True,
            max_rounds=1)
        assert sorted(s.args["lane"]
                      for s in tracer.find("lane_freeze")) == list(range(8))
        assert tracer.find("lane_complete") == []
        # second call resumes the cut lanes -> resume + complete
        engine.run_batch_until_coverage(
            ws_graph, proto, batch, jax.random.key(1), donate=True,
            max_rounds=64)
        assert sorted(s.args["lane"]
                      for s in tracer.find("lane_resume")) == list(range(8))
        assert sorted(s.args["lane"]
                      for s in tracer.find("lane_complete")) == list(range(8))


class TestSuperviseSpans:
    def test_chunk_checkpoint_resume_events(self, tmp_path, tracer,
                                            fresh_registry, fresh_history):
        from p2pnetwork_tpu.supervise.runner import SupervisedRun

        g = G.watts_strogatz(128, 4, 0.1, seed=1)
        proto = Flood(source=0)
        key = jax.random.key(0)
        store = str(tmp_path / "trail")
        run = SupervisedRun(g, proto, store, chunk_rounds=3)
        run.run_rounds(key, 9)
        sup = tracer.find("supervised_run")
        assert len(sup) == 1 and sup[0].args["mode"] == "rounds"
        chunks = tracer.find("chunk")
        assert len(chunks) == 3
        assert all(c.parent_id == sup[0].span_id for c in chunks)
        assert [c.args["round"] for c in chunks] == [3, 6, 9]
        assert len(tracer.find("checkpoint")) >= 1
        assert tracer.find("resume") == []
        # a second harness over the same trail resumes -> resume event
        run2 = SupervisedRun(g, proto, store, chunk_rounds=3)
        run2.run_rounds(key, 12)
        resumes = tracer.find("resume")
        assert len(resumes) == 1 and resumes[0].args["round"] == 9


# ------------------------------------------------------------ history ring


class TestHistory:
    def test_sample_gauges_only_and_series(self):
        reg = telemetry.Registry()
        reg.gauge("h_gauge", "g", ("who",)).labels("a").set(1.0)
        reg.counter("h_counter", "c").inc(5)
        h = history.History(reg, capacity=8)
        h.sample(ts=1.0)
        reg.gauge("h_gauge", "g", ("who",)).labels("a").set(2.5)
        h.sample(ts=2.0)
        assert h.series("h_gauge", "a") == [(1.0, 1.0), (2.0, 2.5)]
        assert h.series("h_counter") == []  # counters are not sampled
        assert h.series("h_gauge", "zz") == []  # unknown child

    def test_capacity_bound(self):
        reg = telemetry.Registry()
        g = reg.gauge("b_gauge", "g")
        h = history.History(reg, capacity=3)
        for i in range(7):
            g.set(float(i))
            h.sample(ts=float(i))
        assert [ts for ts, _ in h.series("b_gauge")] == [4.0, 5.0, 6.0]
        assert len(h.rows()) == 3

    def test_snapshot_json_shape(self):
        reg = telemetry.Registry()
        reg.gauge("s_gauge", "g", ("l",)).labels("x").set(7.0)
        h = history.History(reg, capacity=4)
        h.sample(ts=3.0)
        doc = json.loads(json.dumps(h.snapshot()))
        assert doc["capacity"] == 4 and doc["samples"] == 1
        series = doc["series"]["s_gauge"]
        assert series == [{"labels": ["x"], "points": [[3.0, 7.0]]}]

    def test_none_registry_follows_default_swaps(self):
        h = history.History(None, capacity=4)
        fresh = telemetry.Registry()
        prev = telemetry.set_default_registry(fresh)
        try:
            fresh.gauge("follow_gauge", "g").set(9.0)
            h.sample(ts=1.0)
        finally:
            telemetry.set_default_registry(prev)
        assert h.series("follow_gauge") == [(1.0, 9.0)]

    def test_engine_runs_auto_sample(self, ws_graph, fresh_registry,
                                     fresh_history):
        proto = BatchFlood()
        batch = proto.init(ws_graph, [3, 4, 5])
        engine.run_batch_until_coverage(ws_graph, proto, batch,
                                        jax.random.key(0), max_rounds=64)
        series = fresh_history.series("sim_batch_active_lanes")
        assert len(series) == 1 and series[0][1] == 0.0


# --------------------------------------------------------- httpd endpoints


class TestHttpdEndpoints:
    def _get(self, port, path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            return r.status, r.read().decode("utf-8")

    def test_history_and_trace_endpoints(self, fresh_registry):
        reg = fresh_registry
        reg.gauge("sim_batch_active_lanes", "x").set(3.0)
        hist = history.History(reg, capacity=8)
        hist.sample(ts=1.0)
        tracer = spans.Tracer("serve")
        with tracer.span("work"):
            pass
        with telemetry.MetricsServer(reg, port=0, history=hist,
                                     tracer=tracer) as srv:
            code, body = self._get(srv.port, "/history")
            assert code == 200
            doc = json.loads(body)
            assert doc["series"]["sim_batch_active_lanes"][0]["points"] \
                == [[1.0, 3.0]]
            code, body = self._get(srv.port, "/trace")
            assert code == 200
            doc = json.loads(body)
            assert {e["name"] for e in doc["traceEvents"]} >= {"work"}

    def test_trace_endpoint_empty_without_tracer(self, fresh_registry):
        prev = spans.uninstall_tracer()
        try:
            with telemetry.MetricsServer(fresh_registry, port=0) as srv:
                code, body = self._get(srv.port, "/trace")
        finally:
            spans.install_tracer(prev)
        assert code == 200
        assert json.loads(body)["traceEvents"] == []

    def test_concurrent_scrape_hammer(self, fresh_registry):
        # Satellite: N threads hammering /metrics, /history and
        # /metrics.json while counters/gauges mutate — every response
        # 200 and parseable.
        reg = fresh_registry
        hist = history.History(reg, capacity=32)
        stop = concurrency.event()
        errors = []

        def mutate():
            c = reg.counter("hammer_total", "c", ("who",))
            g = reg.gauge("hammer_gauge", "g")
            i = 0
            while not stop.is_set():
                c.labels("a").inc()
                g.set(float(i))
                hist.sample()
                i += 1

        def scrape(port, path):
            try:
                for _ in range(20):
                    code, body = self._get(port, path)
                    assert code == 200
                    if path == "/metrics":
                        for line in body.splitlines():
                            assert line.startswith("#") or " " in line
                    else:
                        json.loads(body)
            except Exception as e:  # surfaced after joins
                errors.append(f"{path}: {type(e).__name__}: {e}")

        with telemetry.MetricsServer(reg, port=0, history=hist) as srv:
            mut = concurrency.thread(target=mutate, name="hammer-mutate")
            mut.start()
            scrapers = [
                concurrency.thread(target=scrape, args=(srv.port, path),
                                   name=f"hammer-{i}")
                for i, path in enumerate(
                    ["/metrics", "/history", "/metrics.json"] * 3)
            ]
            for t in scrapers:
                t.start()
            for t in scrapers:
                t.join(timeout=60)
            stop.set()
            mut.join(timeout=10)
        assert errors == []

    def test_scrape_storm_under_graftrace_seam(self):
        # Satellite: the scrape-side snapshot paths (to_prometheus,
        # history sample/snapshot) driven through the graftrace
        # concurrency seam while counters mutate — no HB race findings,
        # no deadlocks, across seeds.
        from p2pnetwork_tpu.analysis.race import explore
        from p2pnetwork_tpu.analysis.race.detector import watch

        def body():
            reg = watch(telemetry.Registry())
            hist = watch(history.History(reg, capacity=8))

            def mutate():
                g = reg.gauge("storm_gauge", "g")
                c = reg.counter("storm_total", "c", ("who",))
                for i in range(3):
                    g.set(float(i))
                    c.labels("a").inc()

            def scrape():
                for _ in range(2):
                    export.to_prometheus(reg)
                    hist.sample(ts=1.0)
                    hist.snapshot()

            ts = [concurrency.thread(target=f, name=nm)
                  for nm, f in (("mutate", mutate), ("scrape-a", scrape),
                                ("scrape-b", scrape))]
            for t in ts:
                t.start()
            for t in ts:
                t.join()

        for seed in (0, 1, 2):
            res = explore(body, seed=seed)
            assert res.errors == [], res.errors
            assert res.findings == [], [f.message for f in res.findings]


# -------------------------------------------------- perfetto acceptance


class TestPerfettoAcceptance:
    def test_batched_run_span_tree_and_history(self, ws_graph, tracer,
                                               fresh_registry,
                                               fresh_history):
        """Acceptance: a batched run (B >= 32, staggered admit/retire +
        one resume) exports Perfetto trace-event JSON whose span tree
        validates — every lane has admit -> complete/freeze spans
        nested under its run span — and /history serves the sampled
        sim_batch_active_lanes series for the same run."""
        g = ws_graph
        proto = BatchFlood()
        key = jax.random.key(0)
        sources = (np.arange(32, dtype=np.int32) * 11 % 500) + 1
        batch = proto.init(g, sources, capacity=40)
        # run 1: cut off at 1 round (stragglers freeze)...
        batch, o1 = engine.run_batch_until_coverage(
            g, proto, batch, key, max_rounds=1)
        assert o1["active_lanes"] == 32
        # ...resume to completion (one resume), then staggered
        # retire + a second admit wave into recycled lanes.
        batch, o2 = engine.run_batch_until_coverage(
            g, proto, batch, jax.random.key(1), max_rounds=64)
        assert o2["active_lanes"] == 0
        batch = proto.retire(batch, [0, 1, 2, 3])
        batch, lanes = proto.admit(g, batch, [7, 8, 9])
        batch, o3 = engine.run_batch_until_coverage(
            g, proto, batch, jax.random.key(2), max_rounds=64)
        tracer.close()

        doc = json.loads(json.dumps(tracer.to_chrome()))
        events = doc["traceEvents"]
        by_id = {e["args"]["span_id"]: e for e in events}

        def ancestors(ev):
            while ev["args"]["parent_id"] is not None:
                ev = by_id[ev["args"]["parent_id"]]
                yield ev

        runs = [e for e in events if e["name"] == "batch_run"]
        assert len(runs) == 3
        root = [e for e in events if e["args"]["parent_id"] is None]
        assert len(root) == 1  # one tree
        for e in runs:
            assert e["args"]["parent_id"] == root[0]["args"]["span_id"]

        def lane_events(name):
            return [e for e in events if e["name"] == name]

        # Every admitted lane: an admit span and a complete-or-freeze
        # span, both nested under a batch_run span, ordered in time.
        # (Lane ids recycle across retire/admit, so each end event must
        # be preceded by SOME admit of that lane, not the latest one.)
        admits = {}
        for e in lane_events("lane_admit"):
            admits.setdefault(e["args"]["lane"], []).append(e)
        ends = {}
        for e in lane_events("lane_complete") + lane_events("lane_freeze"):
            ends.setdefault(e["args"]["lane"], []).append(e)
        all_lanes = set(range(32)) | set(lanes.tolist())
        assert set(admits) == all_lanes
        for lane in all_lanes:
            assert lane in ends, f"lane {lane} never completed or froze"
            for e in admits[lane] + ends[lane]:
                anc = {a["name"] for a in ancestors(e)}
                assert "batch_run" in anc, (
                    f"{e['name']} of lane {lane} not nested under a "
                    f"batch_run span")
            for end in ends[lane]:
                assert any(a["ts"] <= end["ts"] for a in admits[lane]), (
                    f"lane {lane} has an end event before any admit")
        # every frozen lane later resumed
        frozen = {e["args"]["lane"] for e in lane_events("lane_freeze")}
        resumed = {e["args"]["lane"] for e in lane_events("lane_resume")}
        assert frozen == resumed == set(range(32))
        # completes carry the cumulative per-lane round count
        for e in lane_events("lane_complete"):
            assert e["args"]["rounds"] >= 1
        # retire + submit control-plane events present
        assert {e["args"]["lane"]
                for e in lane_events("lane_retire")} == {0, 1, 2, 3}
        assert len(lane_events("lane_submit")) == 32 + 3

        # /history serves the sampled sim_batch_active_lanes series for
        # the same run: one point per batched call, tracking 32 -> 0.
        with telemetry.MetricsServer(fresh_registry, port=0,
                                     history=fresh_history) as srv:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/history",
                    timeout=10) as r:
                hdoc = json.loads(r.read().decode("utf-8"))
        series = hdoc["series"]["sim_batch_active_lanes"][0]["points"]
        assert [v for _, v in series] == [32.0, 0.0, 0.0]


# ------------------------------------------------------------- satellites


class TestPrometheusEscaping:
    def test_label_values_escaped_per_exposition_format(self):
        reg = telemetry.Registry()
        reg.counter("esc_total", "h", ("l",)).labels('a"b\nc\\d').inc()
        text = export.to_prometheus(reg)
        line = [ln for ln in text.splitlines()
                if ln.startswith("esc_total{")][0]
        assert line == 'esc_total{l="a\\"b\\nc\\\\d"} 1'

    def test_help_escaped(self):
        reg = telemetry.Registry()
        reg.gauge("esc_gauge", "line one\nline two \\ done").set(1)
        text = export.to_prometheus(reg)
        assert "# HELP esc_gauge line one\\nline two \\\\ done" \
            in text.splitlines()

    def test_no_raw_newlines_leak_into_exposition(self):
        reg = telemetry.Registry()
        reg.counter("leak_total", "h\n", ("l",)).labels("x\ny").inc()
        text = export.to_prometheus(reg)
        # every line is a comment or `name{...} value` — a raw newline
        # in a label would produce a parse-breaking orphan line.
        for ln in text.splitlines():
            if not ln:
                continue
            assert ln.startswith("#") or ln.startswith("leak_total"), ln


class TestJaxhooksIdempotence:
    def test_repeated_install_single_count(self):
        # Satellite: repeated install() must not double-count compile
        # seconds (the module documents the no-unregister caveat: ONE
        # process listener, subscription-set semantics). A jit may emit
        # more than one backend_compile event, so the oracle is a
        # SINGLE-installed registry observing the same compiles: a
        # double-registered listener would give the twice-installed
        # registry exactly 2x its counts.
        once, twice = telemetry.Registry(), telemetry.Registry()
        assert jaxhooks.install(once)
        assert jaxhooks.install(twice)
        assert jaxhooks.install(twice)  # repeated install — idempotent
        try:
            jax.jit(lambda x: x * 3.5 + 17)(
                jnp.arange(13, dtype=jnp.float32)).block_until_ready()
            n_once = once.value("jax_compiles_total")
            n_twice = twice.value("jax_compiles_total")
            s_once = jaxhooks.compile_seconds(once)
            s_twice = jaxhooks.compile_seconds(twice)
        finally:
            jaxhooks.uninstall(once)
            jaxhooks.uninstall(twice)
        assert n_once >= 1.0
        assert n_twice == n_once
        assert s_twice == s_once > 0.0
        # the process listener itself is registered exactly once
        import jax.monitoring as monitoring

        listeners = getattr(monitoring, "_event_duration_secs_listeners",
                            None)
        if listeners is not None:  # private, but pin when present
            assert sum(1 for cb in listeners
                       if cb is jaxhooks._on_event_duration) == 1
        # and after uninstall, new compiles stop counting
        jax.jit(lambda x: x * 2.5 - 3)(
            jnp.arange(17, dtype=jnp.float32)).block_until_ready()
        assert twice.value("jax_compiles_total") == n_twice


class TestBenchProbeLog:
    def test_backend_alive_records_structured_probe_log(self, monkeypatch):
        import bench

        monkeypatch.setattr(bench, "_PROBE_LOG", [])
        monkeypatch.setattr(bench, "_probe_backend_once",
                            lambda t: "backend init timed out (wedged?)")
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        err = bench._backend_alive(window_s=300, probe_timeout_s=1,
                                   max_attempts=2)
        assert err is not None and "gave up" in err
        log = bench._PROBE_LOG
        fails = [e for e in log if "error" in e]
        assert [e["attempt"] for e in fails] == [1, 2]
        assert all("wedged" in e["error"] for e in fails)
        assert any("gave_up" in e for e in log)
        json.dumps(log)  # artifact-ready

    def test_recovery_recorded(self, monkeypatch):
        import bench

        monkeypatch.setattr(bench, "_PROBE_LOG", [])
        outcomes = iter(["wedged once", None])
        monkeypatch.setattr(bench, "_probe_backend_once",
                            lambda t: next(outcomes))
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        assert bench._backend_alive(window_s=300, probe_timeout_s=1,
                                    max_attempts=3) is None
        kinds = [("recovered" if e.get("recovered") else "error")
                 for e in bench._PROBE_LOG
                 if not e.get("policy_summary")]  # graftsight's trailer
        assert kinds == ["error", "recovered"]

    def test_probe_log_lands_in_telemetry_artifact(self, tmp_path,
                                                   monkeypatch,
                                                   fresh_registry):
        import bench

        parent_log = [{"attempt": 1, "error": "wedged tunnel",
                       "window_remaining_s": 100.0}]
        # isolate from probes other tests ran in this process
        monkeypatch.setattr(bench, "_PROBE_LOG", [])
        monkeypatch.setenv("BENCH_TELEMETRY_DIR", str(tmp_path))
        # the parent's probes arrive via the env seam _stage_in_child sets
        monkeypatch.setenv("BENCH_PROBE_LOG", json.dumps(parent_log))
        bench._write_stage_telemetry("1m", {}, 0.0)
        doc = json.load(open(tmp_path / "BENCH_TELEMETRY.json",
                             encoding="utf-8"))
        assert doc["probe_log"] == parent_log

    def test_clean_round_has_empty_probe_log(self, tmp_path, monkeypatch,
                                             fresh_registry):
        import bench

        monkeypatch.setattr(bench, "_PROBE_LOG", [])
        monkeypatch.delenv("BENCH_PROBE_LOG", raising=False)
        monkeypatch.setenv("BENCH_TELEMETRY_DIR", str(tmp_path))
        bench._write_stage_telemetry("1m", {}, 0.0)
        doc = json.load(open(tmp_path / "BENCH_TELEMETRY.json",
                             encoding="utf-8"))
        assert doc["probe_log"] == []


class TestBenchProfileBracket:
    def test_noop_without_env(self, monkeypatch):
        import bench

        monkeypatch.delenv("BENCH_PROFILE_DIR", raising=False)
        with bench._maybe_profile("1m"):
            pass  # no profiler started, nothing written

    def test_writes_trace_or_warns(self, tmp_path, monkeypatch, capsys):
        import bench

        monkeypatch.setenv("BENCH_PROFILE_DIR", str(tmp_path))
        with bench._maybe_profile("1m"):
            jax.jit(lambda x: x + 1)(jnp.ones(8)).block_until_ready()
        err = capsys.readouterr().err
        wrote = (tmp_path / "1m").exists() and any(
            (tmp_path / "1m").rglob("*"))
        assert wrote or "bench_profile" in err
