"""ConnectedComponents / LubyMIS / KCore vs numpy oracles.

Oracles are independent re-derivations (union-find, set-property checks,
peeling loop) — not re-runs of the device code — so a wrong lowering
cannot certify itself.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from p2pnetwork_tpu.models import (  # noqa: E402
    ConnectedComponents,
    KCore,
    LubyMIS,
)
from p2pnetwork_tpu.sim import engine, failures, topology  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def _live_edges(g):
    """(senders, receivers) over live edges between live nodes, numpy."""
    alive = np.asarray(g.node_mask)
    send = np.asarray(g.senders)
    recv = np.asarray(g.receivers)
    em = np.asarray(g.edge_mask)
    pairs = [(send[em], recv[em])]
    if g.dyn_senders is not None:
        dm = np.asarray(g.dyn_mask)
        pairs.append((np.asarray(g.dyn_senders)[dm],
                      np.asarray(g.dyn_receivers)[dm]))
    s = np.concatenate([p[0] for p in pairs])
    r = np.concatenate([p[1] for p in pairs])
    ok = alive[s] & alive[r]
    return s[ok], r[ok]


def _union_find_components(g):
    """Component id per live node via union-find (treating edges as
    undirected — valid for the symmetric builders these tests use)."""
    alive = np.asarray(g.node_mask)
    parent = np.arange(g.n_nodes_padded)

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    s, r = _live_edges(g)
    for a, b in zip(s, r):
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[ra] = rb
    roots = np.array([find(i) if alive[i] else -1
                      for i in range(g.n_nodes_padded)])
    return roots, len({x for x in roots if x >= 0})


def _cc_converge(g, method="auto"):
    st, out = engine.run_until_converged(
        g, ConnectedComponents(method=method), jax.random.key(0),
        stat="changed", threshold=1, max_rounds=1024,
    )
    return st, out


class TestConnectedComponents:
    @pytest.mark.parametrize("method", ["segment", "gather"])
    def test_single_component_ws(self, method):
        g = G.watts_strogatz(512, 6, 0.2, seed=0)
        st, _ = _cc_converge(g, method)
        proto = ConnectedComponents(method=method)
        assert int(proto.components(g, st)) == 1
        # Every live node carries the globally highest live id.
        label = np.asarray(st.label)
        alive = np.asarray(g.node_mask)
        assert (label[alive] == np.nonzero(alive)[0].max()).all()

    def test_two_rings_detected_then_merged(self):
        idx = np.arange(64)
        senders = np.concatenate([idx, 64 + idx, (idx + 1) % 64,
                                  64 + (idx + 1) % 64])
        receivers = np.concatenate([(idx + 1) % 64, 64 + (idx + 1) % 64,
                                    idx, 64 + idx])
        g = G.from_edges(senders, receivers, 128)
        st, _ = _cc_converge(g)
        proto = ConnectedComponents()
        assert int(proto.components(g, st)) == 2
        label = np.asarray(st.label)
        assert (label[:64] == 63).all() and (label[64:128] == 127).all()
        # A runtime bridge merges the partitions: count drops to 1.
        g2 = topology.connect(
            topology.with_capacity(g, extra_edges=4), [100, 3], [3, 100])
        st2, _ = _cc_converge(g2)
        assert int(proto.components(g2, st2)) == 1

    def test_component_count_matches_union_find_under_churn(self):
        g = G.watts_strogatz(256, 4, 0.0, seed=1)  # pure ring lattice
        # Cutting a contiguous run of nodes splits the k=4 ring lattice.
        g = failures.fail_nodes(g, [0, 1, 128, 129])
        st, _ = _cc_converge(g)
        proto = ConnectedComponents()
        _, want = _union_find_components(g)
        assert int(proto.components(g, st)) == want
        # Labels agree exactly with per-component maxima.
        roots, _ = _union_find_components(g)
        label = np.asarray(st.label)
        alive = np.asarray(g.node_mask)
        for root in {x for x in roots if x >= 0}:
            members = np.nonzero((roots == root) & alive)[0]
            assert (label[members] == members.max()).all()

    def test_components_stat_is_monotone_nonincreasing(self):
        g = G.watts_strogatz(512, 4, 0.1, seed=2)
        _, stats = engine.run(g, ConnectedComponents(), jax.random.key(0), 24)
        comps = np.asarray(stats["components"])
        assert (np.diff(comps) <= 0).all()
        assert comps[-1] == 1


class TestLubyMIS:
    def _converge(self, g, seed=0):
        st, out = engine.run_until_converged(
            g, LubyMIS(), jax.random.key(seed),
            stat="undecided", threshold=1, max_rounds=256,
        )
        return st, out

    @pytest.mark.parametrize("builder,args", [
        ("watts_strogatz", (512, 6, 0.2)),
        ("erdos_renyi", (256, 0.05)),
        ("barabasi_albert", (256, 3)),
    ])
    def test_independent_and_maximal(self, builder, args):
        g = getattr(G, builder)(*args, seed=3)
        st, out = self._converge(g)
        assert int(out["value"]) == 0  # everyone decided
        in_mis = np.asarray(st.in_mis)
        alive = np.asarray(g.node_mask)
        s, r = _live_edges(g)
        # Independence: no live edge inside the set.
        assert not (in_mis[s] & in_mis[r]).any()
        # Maximality (symmetric overlay): every live non-member hears a
        # member.
        covered = np.zeros_like(in_mis)
        np.logical_or.at(covered, r, in_mis[s])
        assert (in_mis | covered | ~alive).all()
        assert not (in_mis & ~alive).any()

    def test_deterministic_under_key(self):
        g = G.watts_strogatz(256, 4, 0.1, seed=4)
        a, _ = self._converge(g, seed=7)
        b, _ = self._converge(g, seed=7)
        np.testing.assert_array_equal(np.asarray(a.in_mis),
                                      np.asarray(b.in_mis))

    def test_respects_failures(self):
        g = failures.fail_nodes(G.watts_strogatz(256, 6, 0.2, seed=5),
                                [10, 11, 12])
        st, _ = self._converge(g)
        in_mis = np.asarray(st.in_mis)
        assert not in_mis[[10, 11, 12]].any()
        s, r = _live_edges(g)
        assert not (in_mis[s] & in_mis[r]).any()

    def test_complete_graph_elects_exactly_one(self):
        g = G.complete(64)
        st, _ = self._converge(g)
        assert int(np.asarray(st.in_mis).sum()) == 1

    def test_converges_in_log_rounds(self):
        g = G.watts_strogatz(4096, 6, 0.2, seed=6)
        _, out = self._converge(g)
        # Luby's bound is expected O(log n); leave generous slack.
        assert int(out["rounds"]) <= 64


def _kcore_oracle(g, k):
    """Numpy peeling fixpoint (directed in-degree, like the model)."""
    alive = np.asarray(g.node_mask).copy()
    while True:
        s, r = _live_edges(g)
        ok = alive[s] & alive[r]
        deg = np.zeros(g.n_nodes_padded, dtype=np.int64)
        np.add.at(deg, r[ok], 1)
        new = alive & (deg >= k)
        if (new == alive).all():
            return new
        alive = new


class TestKCore:
    def _converge(self, g, k, method="auto"):
        st, out = engine.run_until_converged(
            g, KCore(k=k, method=method), jax.random.key(0),
            stat="removed", threshold=1, max_rounds=1024,
        )
        return st, out

    @pytest.mark.parametrize("method", ["segment", "gather"])
    def test_ws_matches_oracle(self, method):
        g = G.watts_strogatz(512, 6, 0.1, seed=0)
        for k in (2, 4, 6, 7):
            st, _ = self._converge(g, k, method)
            np.testing.assert_array_equal(
                np.asarray(st.in_core), _kcore_oracle(g, k),
                err_msg=f"k={k}")

    def test_ba_hubs_survive_high_k(self):
        g = G.barabasi_albert(512, 4, seed=1)
        st, _ = self._converge(g, 4)
        np.testing.assert_array_equal(np.asarray(st.in_core),
                                      _kcore_oracle(g, 4))
        # The 4-core of a BA(m=4) graph is non-trivial but not everyone.
        core = np.asarray(st.in_core)
        assert 0 < core.sum()

    def test_k_above_max_degree_empties(self):
        g = G.ring(128)  # every node has in-degree 2
        st, out = self._converge(g, 3)
        assert int(np.asarray(st.in_core).sum()) == 0
        assert int(out["rounds"]) >= 2  # peeling cascades, not one shot

    def test_ring_is_its_own_2core(self):
        g = G.ring(128)
        st, _ = self._converge(g, 2)
        np.testing.assert_array_equal(np.asarray(st.in_core),
                                      np.asarray(g.node_mask))

    def test_hybrid_lowering_matches(self):
        g = G.watts_strogatz(512, 6, 0.1, seed=2, hybrid=True)
        st_h, _ = self._converge(g, 5, "hybrid")
        st_s, _ = self._converge(g, 5, "segment")
        np.testing.assert_array_equal(np.asarray(st_h.in_core),
                                      np.asarray(st_s.in_core))

    def test_failures_shrink_the_core(self):
        g = G.watts_strogatz(256, 6, 0.1, seed=3)
        gf = failures.fail_nodes(g, list(range(0, 64)))
        st, _ = self._converge(gf, 4)
        np.testing.assert_array_equal(np.asarray(st.in_core),
                                      _kcore_oracle(gf, 4))
        assert not np.asarray(st.in_core)[:64].any()

    def test_rejects_bad_k(self):
        with pytest.raises(ValueError, match="k must be"):
            KCore(k=0)

    def test_message_accounting_counts_leaver_fanout(self):
        g = G.ring(64)
        _, stats = engine.run(g, KCore(k=3), jax.random.key(0), 3)
        msgs = np.asarray(stats["messages"])
        removed = np.asarray(stats["removed"])
        # Round 1 removes everyone (ring in-degree 2 < 3); each of the 64
        # leavers notifies its 2 out-neighbors exactly once.
        assert removed[0] == 64 and msgs[0] == 128
        assert removed[1:].sum() == 0 and msgs[1:].sum() == 0


class TestColoring:
    def _check_proper(self, g, colors):
        colors = np.asarray(colors)
        alive = np.asarray(g.node_mask)
        assert (colors[alive] >= 0).all()  # every live node colored
        assert (colors[~alive] == -1).all()
        s, r = _live_edges(g)
        assert (colors[s] != colors[r]).all(), "adjacent nodes share a color"

    def test_ws_coloring_is_proper_and_small(self):
        from p2pnetwork_tpu.models import color_via_mis

        g = G.watts_strogatz(512, 6, 0.2, seed=0)
        colors, n = color_via_mis(g, jax.random.key(0))
        self._check_proper(g, colors)
        # Δ+1 bounds it; a WS(k=6) greedy coloring lands far under 64.
        assert 2 <= n <= 16

    def test_ba_hubs_color_legally(self):
        from p2pnetwork_tpu.models import color_via_mis

        g = G.barabasi_albert(512, 3, seed=1)
        colors, n = color_via_mis(g, jax.random.key(1))
        self._check_proper(g, colors)

    def test_respects_failures(self):
        from p2pnetwork_tpu.models import color_via_mis

        g = failures.fail_nodes(G.watts_strogatz(256, 4, 0.1, seed=2),
                                [7, 8, 9])
        colors, _ = color_via_mis(g, jax.random.key(2))
        self._check_proper(g, colors)

    def test_ring_needs_at_least_two(self):
        from p2pnetwork_tpu.models import color_via_mis

        g = G.ring(64)
        colors, n = color_via_mis(g, jax.random.key(3))
        self._check_proper(g, colors)
        assert n >= 2  # a cycle is not 1-colorable

    def test_max_colors_bound_raises(self):
        from p2pnetwork_tpu.models import color_via_mis

        g = G.complete(16)  # needs 16 colors
        with pytest.raises(RuntimeError, match="uncolored"):
            color_via_mis(g, jax.random.key(4), max_colors=3)
