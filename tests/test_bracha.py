"""Bracha reliable broadcast: validity, agreement, and totality on the
complete topology it assumes, under the parity-equivocating adversary.

The oracle is the theorem, not a trajectory sim: with n >= 3f+1 and at
most f Byzantine ids, every honest node must deliver (totality), all
honest deliveries must coincide (agreement), and an honest broadcaster's
value must win (validity). A hand-stepped tiny case pins the round
structure itself.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from p2pnetwork_tpu.models import Bracha  # noqa: E402
from p2pnetwork_tpu.sim import engine  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def _run(g, p, max_rounds=32):
    st, out = engine.run_until_converged(
        g, p, jax.random.key(0), stat="changed", threshold=1,
        max_rounds=max_rounds)
    return st, out


def _honest_values(g, p, st):
    byz = np.zeros(g.n_nodes_padded, dtype=bool)
    if p.byzantine:
        byz[np.asarray(p.byzantine)] = True
    honest = np.asarray(g.node_mask) & ~byz
    return np.asarray(st.value)[honest]


class TestBracha:
    def test_honest_broadcast_delivers_everywhere(self):
        # f=1 tolerance sized in, zero actual faults: INITIAL -> ECHO ->
        # READY -> deliver in 4 rounds, everyone gets source_value.
        g = G.complete(8)
        p = Bracha(source=2, source_value=1, f=1)
        st, out = _run(g, p)
        vals = _honest_values(g, p, st)
        assert (vals == 1).all()
        assert int(out["rounds"]) <= 5

    def test_validity_value_zero(self):
        g = G.complete(7)
        p = Bracha(source=0, source_value=0, f=1)
        st, _ = _run(g, p)
        assert (_honest_values(g, p, st) == 0).all()

    def test_equivocating_members_n_3f_plus_1(self):
        # n = 7 = 3*2+1, f = 2 Byzantine members (not the source):
        # validity must hold — every honest node delivers source_value.
        g = G.complete(7)
        p = Bracha(source=0, source_value=1, f=2, byzantine=(3, 5))
        st, _ = _run(g, p)
        vals = _honest_values(g, p, st)
        assert (vals == 1).all()

    def test_equivocating_broadcaster_agreement(self):
        # Byzantine BROADCASTER splitting the population by parity:
        # agreement still must hold — honest nodes that deliver all
        # deliver the same value (all-or-nothing is allowed to go
        # either way; the theorem only forbids a split).
        for n, f, byz in ((7, 2, (0, 3)), (10, 3, (0, 2, 4))):
            g = G.complete(n)
            p = Bracha(source=0, f=f, byzantine=byz)
            st, _ = _run(g, p)
            vals = _honest_values(g, p, st)
            delivered = vals[vals >= 0]
            assert len(np.unique(delivered)) <= 1, \
                f"honest nodes split on n={n}: {vals}"

    def test_too_many_byzantine_can_split(self):
        # Sanity that the adversary has teeth: the guarantees are only
        # claimed for <= f faults; we do NOT assert a split happens
        # (adversaries aren't obligated to win), only that the run
        # terminates and honest non-delivery states stay well-formed.
        g = G.complete(7)
        p = Bracha(source=0, f=1, byzantine=(0, 2, 4))
        st, out = _run(g, p)
        vals = _honest_values(g, p, st)
        assert set(np.unique(vals)).issubset({-1, 0, 1})

    def test_hand_stepped_rounds(self):
        # K4, f=0, honest. A synchronous round is receive-then-send:
        # r1 INITIAL lands and ECHOs go out; r2 the echo quorum is
        # counted and READYs go out; r3 the ready quorum delivers.
        g = G.complete(4)
        p = Bracha(source=1, source_value=1, f=0)
        st = p.init(g, jax.random.key(0))
        st, _ = p.step(g, st, jax.random.key(0))  # r1
        assert np.asarray(st.echo_sent)[:4, 1].all()
        assert not np.asarray(st.ready_sent).any()
        st, _ = p.step(g, st, jax.random.key(0))  # r2
        assert np.asarray(st.ready_sent)[:4, 1].all()
        assert (np.asarray(st.value)[:4] == -1).all()
        st, _ = p.step(g, st, jax.random.key(0))  # r3
        assert (np.asarray(st.value)[:4] == 1).all()

    def test_totality_amplification(self):
        # READY amplification (f+1 READYs -> READY) is what turns "some
        # honest delivered" into "all honest deliver": with a Byzantine
        # broadcaster run that DID deliver somewhere, every honest node
        # must have delivered.
        g = G.complete(7)
        p = Bracha(source=0, f=2, byzantine=(0,))
        st, _ = _run(g, p)
        vals = _honest_values(g, p, st)
        if (vals >= 0).any():
            assert (vals >= 0).all(), f"partial delivery: {vals}"

    def test_coverage_and_stats(self):
        g = G.complete(8)
        p = Bracha(source=0, source_value=1, f=1)
        st, out = _run(g, p)
        assert float(p.coverage(g, st)) == pytest.approx(1.0)
        assert int(out["rounds"]) <= 6

    def test_auto_path_parity(self):
        # Integer delivery state: exact GSPMD auto parity (the quorum
        # counts are indicator propagate_sums, exact in any partition).
        from tests.helpers import run_auto_parity

        p = Bracha(source=0, f=2, byzantine=(3, 5), method="segment")
        st_a, st_r = run_auto_parity(G.complete(16), p, 8)
        assert (np.asarray(st_a.value) == np.asarray(st_r.value)).all()
        assert (np.asarray(st_a.echo_sent)
                == np.asarray(st_r.echo_sent)).all()

    def test_rejects_bad_params(self):
        with pytest.raises(ValueError):
            Bracha(source_value=2)
        with pytest.raises(ValueError):
            Bracha(f=-1)

    def test_rejects_out_of_range_byzantine(self):
        # Regression: an out-of-range id used to scatter into a masked
        # padded slot — the adversary silently did not exist.
        g = G.complete(4)
        with pytest.raises(ValueError):
            Bracha(byzantine=(g.n_nodes_padded,)).init(g, jax.random.key(0))
        with pytest.raises(ValueError):
            Bracha(byzantine=(-1,)).init(g, jax.random.key(0))
