"""graftlint (p2pnetwork_tpu/analysis/) tests.

Three layers, mirroring the analyzer's contract:

- **rule fixtures** — for every rule, a snippet that deliberately
  deadlocks / host-syncs / retraces, asserting the rule fires at the
  exact ``file:line`` (and a negative twin asserting the disciplined
  variant stays clean);
- **engine machinery** — suppressions, baseline round-trip (including
  line-number drift, which must NOT churn the baseline), CLI exit codes;
- **the live tree** — ``p2pnetwork_tpu/`` must have zero non-baselined
  findings: the CI gate this suite keeps honest;

plus the runtime complement: ``retrace_guard`` must demonstrably catch an
intentionally re-jitting loop and stay silent on a warm one.
"""

import json
import os
import textwrap
import warnings

import pytest

from p2pnetwork_tpu import telemetry
from p2pnetwork_tpu.analysis import (
    RetraceBudgetExceeded,
    analyze_paths,
    analyze_source,
    all_rules,
    apply_baseline,
    load_baseline,
    retrace_guard,
    write_baseline,
)
from p2pnetwork_tpu.analysis import core
from p2pnetwork_tpu.analysis.__main__ import main as graftlint_main

pytestmark = pytest.mark.analysis


def lint(source, path="snippet.py", **kw):
    return analyze_source(textwrap.dedent(source), path=path, **kw)


def line_of(source, needle, which=0):
    """1-based line number of the ``which``-th line containing ``needle``."""
    hits = [i for i, ln in enumerate(textwrap.dedent(source).splitlines(), 1)
            if needle in ln]
    return hits[which]


def only(findings, rule):
    return [f for f in findings if f.rule == rule]


def assert_fires(source, rule, needle, which=0, path="snippet.py"):
    findings = only(lint(source, path=path), rule)
    assert findings, f"{rule} did not fire"
    expected = line_of(source, needle, which)
    lines = [f.line for f in findings]
    assert expected in lines, (
        f"{rule} fired at lines {lines}, expected {path}:{expected}")
    for f in findings:
        assert f.file == path
    return findings


# ===================================================== JAX rule fixtures


class TestJaxRules:
    def test_jit_in_loop_fires_at_line(self):
        src = """
            import jax

            def drive(xs):
                out = []
                for x in xs:
                    out.append(jax.jit(lambda v: v + 1)(x))  # HOT
                return out
        """
        assert_fires(src, "jit-in-loop", "HOT")

    def test_jit_in_nested_loops_is_one_finding(self):
        # A call nested in two loops is walked once per enclosing loop;
        # the rule must still report it once — duplicates inflate counts
        # and bake a count=2 budget into --write-baseline.
        src = """
            import jax

            def drive(rows):
                out = []
                for row in rows:
                    for x in row:
                        out.append(jax.jit(lambda v: v + 1)(x))  # HOT
                return out
        """
        assert len(only(lint(src), "jit-in-loop")) == 1
        assert_fires(src, "jit-in-loop", "HOT")

    def test_jit_hoisted_out_of_loop_is_clean(self):
        src = """
            import jax

            step = jax.jit(lambda v: v + 1)

            def drive(xs):
                return [step(x) for x in xs]
        """
        assert not only(lint(src), "jit-in-loop")

    def test_jit_immediate_call_fires_at_line(self):
        src = """
            import jax

            def f(x):
                return x

            y = jax.jit(f)(3)  # HOT
        """
        assert_fires(src, "jit-immediate-call", "HOT")

    def test_partial_jit_wrapping_is_not_immediate_call(self):
        # partial(jax.jit, ...)(fn) CONSTRUCTS the jitted function — the
        # engine's loop-variant pattern must not be flagged.
        src = """
            import functools
            import jax

            def f(state, n):
                return state

            f_jit = functools.partial(jax.jit, static_argnames=("n",))(f)
        """
        assert not only(lint(src), "jit-immediate-call")

    @pytest.mark.parametrize("stmt, needle", [
        ("total += x.item()", ".item()"),
        ("host = jax.device_get(x)", "device_get"),
        ("total += float(x)", "float(x)"),
        ("buf = np.asarray(x)", "np.asarray"),
        ("buf = np.array(x)", "np.array"),
        ("dev = jnp.asarray(x)", "jnp.asarray"),
        ("dev = jnp.array(x)", "jnp.array"),
    ])
    def test_host_sync_in_loop_forms(self, stmt, needle):
        src = f"""
            import jax
            import jax.numpy as jnp
            import numpy as np

            def drive(xs):
                total = 0
                for x in xs:
                    {stmt}  # HOT
                return total
        """
        assert_fires(src, "host-sync-in-loop", "HOT")

    def test_jnp_asarray_on_literal_in_loop_is_clean(self):
        # The non-literal condition: converting a CONSTANT per iteration
        # is wasteful but not a transfer of loop data — stays clean, like
        # the np.* twins (literal lists/tuples included).
        src = """
            import jax
            import jax.numpy as jnp

            def drive(xs):
                out = []
                for x in xs:
                    out.append(jnp.asarray([1, 2, 3]) + jnp.array(0.5))
                return out
        """
        assert not only(lint(src), "host-sync-in-loop")

    def test_host_sync_outside_loop_is_clean(self):
        src = """
            import jax

            def summarize(x):
                return x.item()
        """
        assert not only(lint(src), "host-sync-in-loop")

    def test_host_sync_needs_jax_import(self):
        src = """
            def drive(xs):
                return [float(x) for x in xs]

            def loop(xs):
                t = 0
                for x in xs:
                    t += float(x)
                return t
        """
        assert not only(lint(src), "host-sync-in-loop")

    def test_tracer_branch_fires_through_assignment(self):
        src = """
            import jax

            @jax.jit
            def f(x):
                y = x + 1
                if y > 0:  # HOT
                    return y
                return x
        """
        findings = assert_fires(src, "tracer-branch", "HOT")
        assert "'y'" in findings[0].message

    def test_tracer_branch_on_shape_is_clean(self):
        src = """
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("mode",))
            def f(x, mode):
                if mode == "fast":      # static arg: fine
                    return x
                if x.shape[0] > 4:      # shape: trace-time constant
                    return x * 2
                while len(x) > 0:       # len: static for arrays
                    return x
                return x
        """
        assert not only(lint(src), "tracer-branch")

    def test_jit_static_array_default_fires(self):
        src = """
            import functools
            import jax
            import numpy as np

            @functools.partial(jax.jit, static_argnames=("weights",))
            def f(x, weights=np.ones(4)):  # HOT
                return x
        """
        assert_fires(src, "jit-static-array", "HOT")

    def test_jit_static_hashable_arg_is_clean(self):
        src = """
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("k",))
            def f(x, k=4):
                return x * k
        """
        assert not only(lint(src), "jit-static-array")

    def test_jit_closure_ndarray_fires(self):
        src = """
            import jax
            import numpy as np

            def build():
                table = np.arange(8)

                def inner(x):
                    return x + table

                return jax.jit(inner)  # HOT
        """
        findings = assert_fires(src, "jit-closure-ndarray", "HOT")
        assert "table" in findings[0].message

    def test_jit_closure_passing_array_as_arg_is_clean(self):
        src = """
            import jax
            import numpy as np

            def build():
                table = np.arange(8)

                def inner(x, table):
                    return x + table

                return jax.jit(inner), table
        """
        assert not only(lint(src), "jit-closure-ndarray")

    def test_f64_literal_forms(self):
        src = """
            import jax.numpy as jnp

            x = jnp.zeros(4, dtype=jnp.float64)   # HOT-ATTR
            y = jnp.arange(3, dtype="float64")    # HOT-STR
        """
        assert_fires(src, "f64-literal", "HOT-ATTR")
        assert_fires(src, "f64-literal", "HOT-STR")

    def test_carry_no_donate_decorator_form(self):
        src = """
            import functools
            import jax
            from jax import lax

            @functools.partial(jax.jit, static_argnames=("n",))
            def run(state, n):  # HOT
                def cond(c):
                    return c.sum() < n

                def body(c):
                    return c + 1

                return lax.while_loop(cond, body, state)
        """
        assert_fires(src, "carry-no-donate", "HOT")

    def test_carry_no_donate_call_form(self):
        src = """
            import jax
            from jax import lax

            def run(state, n):
                return lax.while_loop(lambda c: c[1] < n,
                                      lambda c: (c[0], c[1] + 1), state)

            run_jit = jax.jit(run, static_argnames=("n",))  # HOT
        """
        assert_fires(src, "carry-no-donate", "HOT")

    def test_jit_immediate_call_arg_is_not_carry_target(self):
        # In `jax.jit(f)(state)` the outer call's argument is RUNTIME
        # data, not a function being wrapped — even when its name happens
        # to match a loop-carrying module function, carry-no-donate must
        # not fire on the call site (jit-immediate-call owns that shape).
        src = """
            import jax
            from jax import lax

            def state(carry, xs):
                def step(c, x):
                    return c + x, x
                return lax.scan(step, carry, xs)

            def drive(f, xs):
                return jax.jit(f)(state)
        """
        assert not only(lint(src), "carry-no-donate")

    def test_carry_donated_or_internal_is_clean(self):
        src = """
            import functools
            import jax
            import jax.numpy as jnp
            from jax import lax

            @functools.partial(jax.jit, donate_argnames=("state",))
            def donated(state):
                return lax.while_loop(lambda c: c.sum() < 3,
                                      lambda c: c + 1, state)

            @jax.jit
            def internal(n):
                # Carry built inside the function: donation of arguments
                # has nothing to recycle — must not be flagged.
                carry = jnp.zeros(8)
                return lax.while_loop(lambda c: c.sum() < 3,
                                      lambda c: c + 1, carry)
        """
        assert not only(lint(src), "carry-no-donate")

    def test_unbounded_cache_fires_at_declaration(self):
        # The finding anchors at the declaration line so the
        # suppress-with-rationale lives where the cache is defined,
        # not at every write site.
        src = """
            _CACHE = {}  # HOT

            def lookup(key, build):
                if key not in _CACHE:
                    _CACHE[key] = build(key)
                return _CACHE[key]

            def warm(keys, build):
                for k in keys:
                    _CACHE.setdefault(k, build(k))
        """
        findings = assert_fires(src, "unbounded-cache", "HOT")
        assert "_CACHE" in findings[0].message
        assert "lookup" in findings[0].message

    def test_unbounded_cache_class_attr_fires(self):
        src = """
            class Planner:
                _memo = {}  # HOT

                def plan(self, key):
                    self._memo[key] = key * 2
                    return self._memo[key]
        """
        assert_fires(src, "unbounded-cache", "HOT")

    def test_bounded_cache_is_clean(self):
        # Any eviction anywhere in the module (pop/clear/del/rebind)
        # marks the dict as bounded.
        src = """
            _CACHE = {}

            def lookup(key, build):
                if len(_CACHE) > 128:
                    _CACHE.clear()
                _CACHE[key] = build(key)
                return _CACHE[key]

            _PLAIN = {}  # written nowhere: data, not a cache
        """
        assert not only(lint(src), "unbounded-cache")


# ============================================= concurrency rule fixtures


class TestConcurrencyRules:
    def test_lock_order_cycle_fires(self):
        src = """
            import threading

            a = threading.Lock()
            b = threading.Lock()

            def forward():
                with a:
                    with b:
                        pass

            def backward():
                with b:
                    with a:  # HOT
                        pass
        """
        findings = only(lint(src), "lock-order-cycle")
        assert findings, "cycle not detected"
        assert any("a -> b -> a" in f.message or "b -> a -> b" in f.message
                   for f in findings)

    def test_consistent_lock_order_is_clean(self):
        src = """
            import threading

            a = threading.Lock()
            b = threading.Lock()

            def one():
                with a:
                    with b:
                        pass

            def two():
                with a:
                    with b:
                        pass
        """
        assert not only(lint(src), "lock-order-cycle")

    def test_nonreentrant_self_deadlock_via_call(self):
        src = """
            import threading

            L = threading.Lock()

            def outer():
                with L:
                    inner()  # HOT

            def inner():
                with L:
                    pass
        """
        findings = assert_fires(src, "lock-order-cycle", "HOT")
        assert "re-acquired" in findings[0].message

    def test_rlock_reentry_is_clean(self):
        src = """
            import threading

            L = threading.RLock()

            def outer():
                with L:
                    inner()

            def inner():
                with L:
                    pass
        """
        assert not only(lint(src), "lock-order-cycle")

    def test_blocking_under_lock_direct(self):
        src = """
            import threading
            import time

            L = threading.Lock()

            def f():
                with L:
                    time.sleep(1)  # HOT
        """
        assert_fires(src, "blocking-under-lock", "HOT")

    def test_blocking_under_lock_through_call_edge(self):
        src = """
            import threading
            import time

            L = threading.Lock()

            def helper():
                time.sleep(0.1)

            def f():
                with L:
                    helper()  # HOT
        """
        findings = assert_fires(src, "blocking-under-lock", "HOT")
        assert "helper" in findings[0].message

    def test_blocking_outside_lock_is_clean(self):
        src = """
            import threading
            import time

            L = threading.Lock()

            def f():
                with L:
                    n = 1
                time.sleep(n)
        """
        assert not only(lint(src), "blocking-under-lock")

    def test_untimed_queue_get_under_lock(self):
        src = """
            import queue
            import threading

            L = threading.Lock()
            work_queue = queue.Queue()

            def f():
                with L:
                    item = work_queue.get()  # HOT
                return item
        """
        assert_fires(src, "blocking-under-lock", "HOT")

    def test_lock_across_await_fires(self):
        src = """
            import threading

            L = threading.Lock()

            async def f(peer):
                with L:
                    await peer.flush()  # HOT
        """
        assert_fires(src, "lock-across-await", "HOT")

    def test_copy_then_await_is_clean(self):
        src = """
            import threading

            L = threading.Lock()
            items = []

            async def f(peer):
                with L:
                    snapshot = list(items)
                await peer.send(snapshot)
        """
        assert not only(lint(src), "lock-across-await")

    def test_async_blocking_call_fires(self):
        src = """
            import time

            async def f():
                time.sleep(1)  # HOT
        """
        assert_fires(src, "async-blocking-call", "HOT")

    def test_awaited_asyncio_wait_is_clean(self):
        src = """
            import asyncio

            async def f(ev):
                await asyncio.wait_for(ev.wait(), timeout=2.0)
                await asyncio.sleep(0.1)
        """
        assert not only(lint(src), "async-blocking-call")

    def test_async_blocking_through_call_edge(self):
        src = """
            import time

            def helper():
                time.sleep(0.5)

            async def f():
                helper()  # HOT
        """
        assert_fires(src, "async-blocking-call", "HOT")

    def test_lock_guard_class_attr_fires(self):
        src = """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = {}

                def put(self, k, v):
                    with self._lock:
                        self._items[k] = v

                def peek(self, k):
                    return self._items.get(k)  # HOT
        """
        findings = assert_fires(src, "lock-guard", "HOT")
        assert "_items" in findings[0].message

    def test_lock_guard_consistent_class_is_clean(self):
        src = """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = {}

                def put(self, k, v):
                    with self._lock:
                        self._items[k] = v

                def peek(self, k):
                    with self._lock:
                        return self._items.get(k)
        """
        assert not only(lint(src), "lock-guard")

    def test_lock_guard_module_global_fires(self):
        src = """
            import threading

            _lock = threading.Lock()
            _state = {}

            def set_state(s):
                global _state
                with _lock:
                    _state = s

            def get_state():
                return _state  # HOT
        """
        assert_fires(src, "lock-guard", "HOT")

    def test_lock_open_call_fires(self):
        src = """
            import threading

            class Pub:
                def __init__(self, sink):
                    self._lock = threading.Lock()
                    self._n = 0
                    self._sink = sink

                def bump(self):
                    with self._lock:
                        self._n += 1
                        self._sink.publish(self._n)  # HOT
        """
        findings = assert_fires(src, "lock-open-call", "HOT")
        assert "_sink.publish" in findings[0].message

    def test_lock_open_call_names_derived_receiver(self):
        # `mine = self._crdts.get(name); mine.merge(x)` must be reported
        # as a call on `mine` (derived from self._crdts), not as
        # `self._crdts.merge()` — a method the container doesn't have.
        src = """
            import threading

            class Store:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._crdts = {}

                def absorb(self, name, incoming):
                    with self._lock:
                        mine = self._crdts.get(name)
                        merged = mine.merge(incoming)  # HOT
        """
        findings = assert_fires(src, "lock-open-call", "HOT")
        assert "mine.merge()" in findings[0].message
        assert "derived from self._crdts" in findings[0].message
        assert "self._crdts.merge" not in findings[0].message

    def test_lock_open_call_copy_then_call_is_clean(self):
        src = """
            import threading

            class Pub:
                def __init__(self, sink):
                    self._lock = threading.Lock()
                    self._n = 0
                    self._sink = sink

                def bump(self):
                    with self._lock:
                        self._n += 1
                        n = self._n
                    self._sink.publish(n)
        """
        assert not only(lint(src), "lock-open-call")

    def test_wait_untimed_fires_and_timed_is_clean(self):
        src = """
            def bad(ev):
                ev.wait()  # HOT

            def good(ev):
                return ev.wait(5.0)
        """
        findings = assert_fires(src, "wait-untimed", "HOT")
        assert len(findings) == 1

    def test_wait_untimed_result_and_join(self):
        src = """
            def bad(fut, thread):
                fut.result()    # HOT-RESULT
                thread.join()   # HOT-JOIN

            def fine(parts):
                return ",".join(parts)
        """
        assert_fires(src, "wait-untimed", "HOT-RESULT")
        assert_fires(src, "wait-untimed", "HOT-JOIN")
        findings = only(lint(src), "wait-untimed")
        assert len(findings) == 2  # str.join(args) untouched

    def test_raw_concurrency_primitive_fires_per_construction(self):
        src = """
            import queue
            import threading
            import time
            from threading import Event

            def build():
                lk = threading.Lock()     # HOT-LOCK
                ev = Event()              # HOT-EVENT
                q = queue.Queue()         # HOT-QUEUE
                time.sleep(0.1)           # HOT-SLEEP
                return lk, ev, q
        """
        for needle in ("HOT-LOCK", "HOT-EVENT", "HOT-QUEUE", "HOT-SLEEP"):
            assert_fires(src, "raw-concurrency-primitive", needle)
        assert len(only(lint(src), "raw-concurrency-primitive")) == 4

    def test_raw_concurrency_primitive_seam_twin_is_clean(self):
        # The clean twin: the same primitives built through the seam, and
        # non-primitive threading surface (local storage, queries) is
        # never flagged.
        src = """
            import threading
            from p2pnetwork_tpu import concurrency

            _tls = threading.local()

            def build():
                lk = concurrency.lock()
                ev = concurrency.event()
                q = concurrency.fifo_queue()
                concurrency.sleep(0.1)
                me = threading.current_thread()
                return lk, ev, q, me
        """
        assert not only(lint(src), "raw-concurrency-primitive")

    def test_seam_factories_join_the_lock_inventory(self):
        # The inventory must keep full-strength guard analysis on
        # seam-constructed locks, or the refactor silently downgrades
        # every lock rule to the name heuristic.
        src = """
            from p2pnetwork_tpu import concurrency

            class C:
                def __init__(self):
                    self._mu = concurrency.lock()
                    self.state = {}

                def put(self, k, v):
                    with self._mu:
                        self.state[k] = v

                def peek(self):
                    return self.state  # HOT
        """
        assert_fires(src, "lock-guard", "HOT")

    def test_seam_sleep_is_blocking_under_lock(self):
        src = """
            import threading
            from p2pnetwork_tpu import concurrency

            L = threading.Lock()

            def f():
                with L:
                    concurrency.sleep(1)  # HOT
        """
        assert_fires(src, "blocking-under-lock", "HOT")


# ======================================================= engine machinery


class TestEngine:
    BLOCKING = """
        import threading
        import time

        L = threading.Lock()

        def f():
            with L:
                time.sleep(1){suffix}
    """

    def test_inline_suppression_silences_one_rule(self):
        src = self.BLOCKING.format(
            suffix="  # graftlint: ignore[blocking-under-lock] -- test")
        assert not only(lint(src), "blocking-under-lock")

    def test_bare_suppression_silences_all_rules(self):
        src = self.BLOCKING.format(suffix="  # graftlint: ignore")
        # The raw construction line needs its own bare ignore now that
        # raw-concurrency-primitive polices it — per-line semantics.
        src = src.replace("L = threading.Lock()",
                          "L = threading.Lock()  # graftlint: ignore")
        assert not lint(src)

    def test_suppression_does_not_leak_to_other_lines(self):
        src = textwrap.dedent(self.BLOCKING.format(suffix=""))
        src += textwrap.dedent("""
            def g():
                with L:
                    time.sleep(2)  # graftlint: ignore[blocking-under-lock]
        """)
        findings = only(lint(src), "blocking-under-lock")
        assert len(findings) == 1
        assert findings[0].line == line_of(src, "time.sleep(1)")

    def test_no_suppressions_mode_reports_everything(self):
        src = self.BLOCKING.format(suffix="  # graftlint: ignore")
        assert only(lint(src, respect_suppressions=False),
                    "blocking-under-lock")

    def test_standalone_comment_does_not_silence_enclosing_block(self):
        # A marker on its own comment line between statements must not
        # map to the whole enclosing function — that would let one stray
        # comment swallow every later finding in it (silent P0 false
        # negatives behind a green gate).
        src = """
            import threading
            import time

            L = threading.Lock()

            def f(ev):
                # graftlint: ignore -- stray comment, binds to nothing
                ev.wait()  # HOT-WAIT
                with L:
                    time.sleep(1)  # HOT-SLEEP
        """
        assert_fires(src, "wait-untimed", "HOT-WAIT")
        assert_fires(src, "blocking-under-lock", "HOT-SLEEP")

    def test_header_suppression_covers_header_not_body(self):
        # On a compound statement's header line the marker covers the
        # header (e.g. a with-expression finding) but not the body.
        src = """
            import threading
            import time

            L = threading.Lock()

            def f(ev):
                with L:  # graftlint: ignore[blocking-under-lock] -- t
                    time.sleep(1)  # HOT
        """
        assert_fires(src, "blocking-under-lock", "HOT")

    def test_unknown_rule_in_suppression_does_not_silence(self):
        src = self.BLOCKING.format(
            suffix="  # graftlint: ignore[some-other-rule]")
        assert only(lint(src), "blocking-under-lock")

    def test_every_rule_has_fixture_coverage(self):
        # The rule registry and this test file must move together: a new
        # rule without a deliberate-failure fixture is untested policy.
        expected = {
            "jit-in-loop", "jit-immediate-call", "host-sync-in-loop",
            "tracer-branch", "jit-static-array", "jit-closure-ndarray",
            "f64-literal", "carry-no-donate", "unbounded-cache",
            "lock-order-cycle", "lock-across-await", "blocking-under-lock",
            "async-blocking-call", "lock-guard", "lock-open-call",
            "wait-untimed", "raw-concurrency-primitive",
        }
        assert set(all_rules()) == expected

    def _tree(self, tmp_path, source):
        f = tmp_path / "mod.py"
        f.write_text(textwrap.dedent(source))
        return f

    def test_baseline_roundtrip_and_line_drift(self, tmp_path):
        src = """
            import threading
            import time

            L = threading.Lock()

            def f():
                with L:
                    time.sleep(1)
        """
        self._tree(tmp_path, src)
        modules = {}
        findings = analyze_paths([str(tmp_path)], root=str(tmp_path),
                                 collect_sources=modules)
        assert findings
        bl_path = tmp_path / "baseline.json"
        write_baseline(findings, modules, str(bl_path))
        baseline = load_baseline(str(bl_path))
        new, old = apply_baseline(findings, modules, baseline)
        assert new == [] and len(old) == len(findings)

        # Drift the line numbers: the baseline must still absorb the
        # findings (fingerprints key on source text, not line numbers).
        drifted = "# a new leading comment\n\n" + textwrap.dedent(src)
        (tmp_path / "mod.py").write_text(drifted)
        modules2 = {}
        findings2 = analyze_paths([str(tmp_path)], root=str(tmp_path),
                                  collect_sources=modules2)
        new2, old2 = apply_baseline(findings2, modules2, baseline)
        assert new2 == [] and len(old2) == len(findings2)

    def test_baseline_does_not_absorb_new_duplicates(self, tmp_path):
        src = """
            import threading
            import time

            L = threading.Lock()

            def f():
                with L:
                    time.sleep(1)
        """
        self._tree(tmp_path, src)
        modules = {}
        findings = analyze_paths([str(tmp_path)], root=str(tmp_path),
                                 collect_sources=modules)
        bl_path = tmp_path / "baseline.json"
        write_baseline(findings, modules, str(bl_path))

        # A second, NEW copy of the same offending line must not ride in
        # on the old entry's fingerprint.
        doubled = textwrap.dedent(src) + textwrap.dedent("""
            def g():
                with L:
                    time.sleep(1)
        """)
        (tmp_path / "mod.py").write_text(doubled)
        modules2 = {}
        findings2 = analyze_paths([str(tmp_path)], root=str(tmp_path),
                                  collect_sources=modules2)
        new2, old2 = apply_baseline(findings2, modules2,
                                    load_baseline(str(bl_path)))
        assert len(old2) == len(findings)
        assert len(new2) == len(findings2) - len(findings)

    def test_parse_error_is_a_finding_not_a_crash(self, tmp_path):
        (tmp_path / "broken.py").write_text("def broken(:\n")
        findings = analyze_paths([str(tmp_path)], root=str(tmp_path))
        assert [f.rule for f in findings] == ["parse-error"]

    def test_cli_exit_codes(self, tmp_path, capsys, monkeypatch):
        self._tree(tmp_path, """
            import threading
            import time

            L = threading.Lock()

            def f():
                with L:
                    time.sleep(1)
        """)
        monkeypatch.chdir(tmp_path)
        bl = tmp_path / "bl.json"
        assert graftlint_main(["mod.py", "--baseline", str(bl)]) == 1
        out = capsys.readouterr().out
        assert "blocking-under-lock" in out and "mod.py:" in out

        assert graftlint_main(["mod.py", "--baseline", str(bl),
                               "--write-baseline"]) == 0
        assert graftlint_main(["mod.py", "--baseline", str(bl)]) == 0
        assert "clean" in capsys.readouterr().out

    def test_cli_json_output(self, tmp_path, monkeypatch, capsys):
        self._tree(tmp_path, """
            def bad(ev):
                ev.wait()
        """)
        monkeypatch.chdir(tmp_path)
        bl = tmp_path / "bl.json"
        rc = graftlint_main(["mod.py", "--json", "--baseline", str(bl)])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 1 and doc["ok"] is False
        assert doc["findings"][0]["rule"] == "wait-untimed"
        assert doc["findings"][0]["file"] == "mod.py"

    def test_cli_list_rules(self, capsys):
        assert graftlint_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule in ("lock-order-cycle", "tracer-branch"):
            assert rule in out

    def test_suppression_covers_multiline_statement(self):
        # The marker may sit on a continuation line of the statement the
        # finding anchors to — the documented "inside the flagged
        # statement" contract.
        src = """
            import jax

            def drive(xs):
                out = []
                for x in xs:
                    out.append(jax.device_get(
                        x))  # graftlint: ignore[host-sync-in-loop] -- t
                return out
        """
        assert not only(lint(src), "host-sync-in-loop")

    def test_null_byte_file_is_a_finding_not_a_crash(self, tmp_path):
        (tmp_path / "nul.py").write_bytes(b"x = 1\x00\n")
        findings = analyze_paths([str(tmp_path)], root=str(tmp_path))
        assert [f.rule for f in findings] == ["parse-error"]

    def test_write_baseline_refuses_filtered_runs(self, tmp_path,
                                                  monkeypatch):
        self._tree(tmp_path, "def f(ev):\n    ev.wait()\n")
        monkeypatch.chdir(tmp_path)
        bl = tmp_path / "bl.json"
        rc = graftlint_main(["mod.py", "--baseline", str(bl),
                             "--rules", "wait-untimed",
                             "--write-baseline"])
        assert rc == 2 and not bl.exists()

    def test_missing_path_is_exit_2_not_clean(self, tmp_path, monkeypatch,
                                              capsys):
        # A typo'd target must not analyze zero files and exit 0 — that
        # would permanently disable the gate with a green check.
        monkeypatch.chdir(tmp_path)
        rc = graftlint_main(["no_such_dir_xyz",
                             "--baseline", str(tmp_path / "bl.json")])
        assert rc == 2
        assert "no such file" in capsys.readouterr().err
        with pytest.raises(FileNotFoundError):
            analyze_paths([str(tmp_path / "missing.py")])

    def test_root_resolves_to_repo_root_from_subdir(self, monkeypatch):
        # Running from a subdirectory of the checkout must key files
        # exactly as the checked-in baseline does (repo-root-relative),
        # or grandfathered findings report as new.
        from p2pnetwork_tpu import analysis
        from p2pnetwork_tpu.analysis.__main__ import _resolve_root
        pkg_dir = os.path.dirname(os.path.abspath(analysis.__file__))
        repo_root = os.path.dirname(os.path.dirname(pkg_dir))
        monkeypatch.chdir(pkg_dir)
        assert _resolve_root(None, ["core.py"]) == repo_root

    def test_write_baseline_path_subset_keeps_other_files(self, tmp_path,
                                                          monkeypatch):
        # `--write-baseline <subset>` must preserve grandfathered entries
        # for files outside the subset — otherwise a narrow regeneration
        # silently un-grandfathers the rest of the tree and the next full
        # gate fails on findings nobody introduced.
        (tmp_path / "a.py").write_text("def f(ev):\n    ev.wait()\n")
        (tmp_path / "b.py").write_text("def g(ev):\n    ev.wait()\n")
        monkeypatch.chdir(tmp_path)
        bl = tmp_path / "bl.json"
        assert graftlint_main(["a.py", "b.py", "--baseline", str(bl),
                               "--write-baseline"]) == 0
        assert graftlint_main(["a.py", "b.py", "--baseline", str(bl)]) == 0
        # Regenerate from a.py alone: b.py's entry must survive.
        assert graftlint_main(["a.py", "--baseline", str(bl),
                               "--write-baseline"]) == 0
        assert graftlint_main(["a.py", "b.py", "--baseline", str(bl)]) == 0
        files = {e["file"] for e in
                 json.loads(bl.read_text())["findings"]}
        assert files == {"a.py", "b.py"}
        # ...while a fixed analyzed file still shrinks the baseline.
        (tmp_path / "a.py").write_text("def f(ev):\n    ev.wait(1.0)\n")
        assert graftlint_main(["a.py", "--baseline", str(bl),
                               "--write-baseline"]) == 0
        files = {e["file"] for e in
                 json.loads(bl.read_text())["findings"]}
        assert files == {"b.py"}

    def test_no_suppressions_audit_keeps_exit_code(self, tmp_path,
                                                   monkeypatch, capsys):
        self._tree(tmp_path, """
            def f(ev):
                ev.wait()  # graftlint: ignore[wait-untimed] -- test
        """)
        monkeypatch.chdir(tmp_path)
        bl = tmp_path / "bl.json"
        assert graftlint_main(["mod.py", "--baseline", str(bl)]) == 0
        capsys.readouterr()
        # Audit mode shows the suppressed finding but must not gate on it.
        assert graftlint_main(["mod.py", "--baseline", str(bl),
                               "--no-suppressions"]) == 0
        out = capsys.readouterr().out
        assert "suppressed finding" in out and "wait-untimed" in out

    def test_gate_matches_baseline_from_any_cwd(self, tmp_path,
                                                monkeypatch, capsys):
        # The installed `graftlint` script runs from arbitrary
        # directories; relative baseline paths must still resolve.
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        monkeypatch.chdir(tmp_path)
        rc = graftlint_main([os.path.join(repo, "p2pnetwork_tpu")])
        out = capsys.readouterr().out
        assert rc == 0, out


# ======================================================== the live tree


class TestLiveTree:
    def test_package_has_zero_nonbaselined_findings(self):
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        modules = {}
        findings = analyze_paths(
            [os.path.join(repo, "p2pnetwork_tpu")], root=repo,
            collect_sources=modules)
        new, _ = apply_baseline(findings, modules, load_baseline())
        assert new == [], "\n".join(f.render() for f in new)

    def test_checked_in_baseline_is_not_stale(self):
        # Every baseline entry must still correspond to a real finding —
        # fixed findings must leave the baseline (regenerate with
        # --write-baseline) or the gate slowly goes blind.
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        modules = {}
        findings = analyze_paths(
            [os.path.join(repo, "p2pnetwork_tpu")], root=repo,
            collect_sources=modules)
        baseline = load_baseline()
        _, grandfathered = apply_baseline(findings, modules, baseline)
        assert len(grandfathered) == sum(baseline.values()), (
            "baseline over-claims: regenerate with --write-baseline")


# ======================================================== retrace_guard


class TestRetraceGuard:
    def test_catches_intentionally_rejitting_loop(self):
        import jax
        import jax.numpy as jnp

        reg = telemetry.Registry()
        with pytest.raises(RetraceBudgetExceeded) as exc:
            with retrace_guard("rejit", budget=2, registry=reg):
                for i in range(5):
                    # A FRESH jit wrapper per iteration: the compile
                    # cache misses every time — the exact bug class
                    # jaxrules' jit-in-loop flags statically.
                    jax.jit(lambda x, _i=i: x + _i)(jnp.arange(4))
        assert exc.value.compiles > exc.value.budget == 2
        assert reg.value("retrace_guard_breaches_total", block="rejit") == 1
        assert reg.value("retrace_guard_compiles_total",
                         block="rejit") >= exc.value.compiles

    def test_warm_loop_stays_within_zero_budget(self):
        import jax
        import jax.numpy as jnp

        reg = telemetry.Registry()
        step = jax.jit(lambda x: x * 2)
        step(jnp.arange(8))  # compile OUTSIDE the guard
        with retrace_guard("steady", budget=0, registry=reg) as g:
            for _ in range(5):
                step(jnp.arange(8))
        assert g.compiles == 0 and not g.breached

    def test_warn_mode_warns_and_continues(self):
        import jax
        import jax.numpy as jnp

        reg = telemetry.Registry()
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            with retrace_guard("warned", budget=0, registry=reg,
                               on_breach="warn") as g:
                jax.jit(lambda x: x - 1)(jnp.arange(3))
        assert g.breached
        assert any("retrace_guard[warned]" in str(w.message) for w in caught)

    def test_callable_breach_handler(self):
        import jax
        import jax.numpy as jnp

        reg = telemetry.Registry()
        seen = []
        with retrace_guard("cb", budget=0, registry=reg,
                           on_breach=seen.append) as g:
            jax.jit(lambda x: x + 7)(jnp.arange(3))
        assert seen == [g] and g.breached

    def test_block_exception_outranks_breach(self):
        import jax
        import jax.numpy as jnp

        reg = telemetry.Registry()
        with pytest.raises(KeyError):
            with retrace_guard("err", budget=0, registry=reg):
                jax.jit(lambda x: x)(jnp.arange(2))
                raise KeyError("the real failure")

    def test_guard_validates_arguments(self):
        with pytest.raises(ValueError):
            retrace_guard("x", budget=-1)
        with pytest.raises(ValueError):
            retrace_guard("x", budget=0, on_breach="explode")
