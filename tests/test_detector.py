"""FailureDetector (SWIM-style ping/ack) against ground-truth liveness."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from p2pnetwork_tpu.models import FailureDetector  # noqa: E402
from p2pnetwork_tpu.sim import engine, failures  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def _detect(g, threshold=3, loss=0.0, max_rounds=512, key=0):
    p = FailureDetector(threshold=threshold, loss_prob=loss)
    st, out = engine.run_until_converged(
        g, p, jax.random.key(key), stat="undetected", threshold=1,
        max_rounds=max_rounds)
    return p, st, out


class TestMarkUnresponsive:
    def test_tables_and_edges_stay_intact(self):
        g = G.watts_strogatz(64, 4, 0.1, seed=0)
        gm = failures.mark_unresponsive(g, [5, 9])
        np.testing.assert_array_equal(np.asarray(gm.neighbor_mask),
                                      np.asarray(g.neighbor_mask))
        np.testing.assert_array_equal(np.asarray(gm.edge_mask),
                                      np.asarray(g.edge_mask))
        np.testing.assert_array_equal(np.asarray(gm.in_degree),
                                      np.asarray(g.in_degree))
        alive = np.asarray(gm.node_mask)
        assert not alive[5] and not alive[9] and alive[:64].sum() == 62


class TestFailureDetector:
    def test_lossless_detects_all_with_no_false_positives(self):
        g = failures.mark_unresponsive(
            G.watts_strogatz(128, 4, 0.1, seed=1), [7, 40, 99])
        p, st, out = _detect(g, threshold=3)
        assert int(out["value"]) == 0  # undetected at quiescence
        # Every declaration is real: no responsive target ever declared.
        declared = np.asarray(st.declared)
        dead = np.asarray(p._dead_watched(g))
        assert not (declared & ~dead).any()
        # Latching needs at least `threshold` probes of the slot.
        assert int(out["rounds"]) >= 3

    def test_nothing_to_detect_quiesces_immediately(self):
        g = G.ring(32)
        _, st, out = _detect(g)
        assert int(out["rounds"]) <= 1
        assert not np.asarray(st.declared).any()

    def test_lossy_channel_still_converges(self):
        g = failures.mark_unresponsive(G.ring(64), [10, 30])
        p, st, out = _detect(g, threshold=4, loss=0.3, max_rounds=2048,
                             key=2)
        assert int(out["value"]) == 0
        stats_fp = int(np.asarray(
            p.step(g, st, jax.random.key(3))[1]["false_positives"]))
        # False positives are possible but bounded by the latched count.
        assert stats_fp <= int(np.asarray(st.declared).sum())

    def test_threshold_is_a_precision_dial(self):
        # Same lossy channel: a higher threshold declares fewer live slots.
        g = failures.mark_unresponsive(G.ring(128), [5])
        fps = []
        for thr in (1, 6):
            p, st, _ = _detect(g, threshold=thr, loss=0.4, max_rounds=256,
                               key=4)
            dead = np.asarray(p._dead_watched(g))
            fps.append(int((np.asarray(st.declared) & ~dead).sum()))
        assert fps[1] <= fps[0]

    def test_requires_neighbor_table(self):
        g = G.ring(16, build_neighbor_table=False)
        with pytest.raises(ValueError, match="neighbor table"):
            FailureDetector().init(g, jax.random.key(0))
