"""Batched query lanes: byte-budgeted non-boolean carriers, the three
query families (min-plus routing, DHT lookups, push-sum aggregation),
and the batched query engine loop.

The contract under test (models/querybatch.py, ops/lanes.py): batching K
queries into one compiled program changes the COST of answering them,
never the answers. Min-plus and DHT lanes pin BIT-identity against
independent single-query references (min is order-blind in f32; cursors
are ints); push-sum pins the float-op-order contract — eager batched
steps bitwise equal models/pushsum.py steps, and one-admitted-lane runs
of the same compiled program bitwise equal the full batch (lane
isolation). The byte budget is the other half: no family can admit past
``ops/lanes.lane_budget`` silently — the typed
:class:`LaneBudgetExceeded` is the contract. The slow-marked ratchets
pin the point of it all: ≥10x aggregate throughput vs warm sequential
capacity-1 runs at the bench-default K on 100k-node graphs, ratio-based
on CPU.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.models.pushsum import PushSum, PushSumState
from p2pnetwork_tpu.models.querybatch import (
    DhtLookups, LaneBudgetExceeded, MinPlusQueries, PushSumQueries,
    free_query_lanes, lane_dist)
from p2pnetwork_tpu.models.messagebatch import LaneExhausted
from p2pnetwork_tpu.ops import lanes as L
from p2pnetwork_tpu.ops import segment as S
from p2pnetwork_tpu.sim import engine, failures, flightrec
from p2pnetwork_tpu.sim import graph as G
from p2pnetwork_tpu.telemetry import spans
from p2pnetwork_tpu.utils import accum

pytestmark = pytest.mark.query

KEY = jax.random.key(0)


def ws(n=300, seed=3, **kw):
    kw.setdefault("source_csr", True)
    return G.watts_strogatz(n, 6, 0.2, seed=seed, **kw)


# ------------------------------------------------------ reference runs


def minplus_reference(g, src, tgt, max_rounds=256):
    """Independent single-query Bellman-Ford: the per-lane kernel
    (propagate_min_plus) iterated with the family's completion rule.
    Returns (dist field, applied rounds)."""
    seed = jnp.zeros(g.n_nodes_padded, bool).at[int(src)].set(True)
    seed = seed & g.node_mask
    d = jnp.where(seed, 0.0, jnp.inf).astype(jnp.float32)
    if bool(seed[int(src)]) and int(src) == int(tgt):
        return d, 0  # settled at admission
    unweighted = g.edge_weight is None
    r = 0
    while r < max_rounds:
        nd = jnp.minimum(d, S.propagate_min_plus(g, d, "auto"))
        r += 1
        changed = bool(jnp.any(nd != d))
        d = nd
        if (unweighted and bool(jnp.isfinite(d[int(tgt)]))) or not changed:
            break
    return d, r


def dht_reference(g, origin, key_id, metric, max_rounds=128):
    """Independent single-lookup greedy walk (numpy). Returns
    (final cursor, applied rounds)."""
    nbrs = np.asarray(g.neighbors)
    nmask = np.asarray(g.neighbor_mask)
    alive = np.asarray(g.node_mask)
    n = g.n_nodes
    cur, tgt = int(origin), int(key_id)
    if cur == tgt or not alive[cur]:
        return cur, 0
    rounds = 0
    while rounds < max_rounds:
        cand = nbrs[cur]
        valid = nmask[cur] & alive[cand]
        if metric == "ring":
            dn = np.where(valid, (tgt - cand) % n,
                          np.uint64(2 ** 32 - 1)).astype(np.uint64)
            dcur = (tgt - cur) % n
        else:
            dn = np.where(valid, (cand.astype(np.int64) ^ tgt),
                          np.uint64(2 ** 32 - 1)).astype(np.uint64)
            dcur = cur ^ tgt
        j = int(np.argmin(dn))
        rounds += 1  # a live lane applies the round, hop or stall
        if dn[j] < dcur:
            cur = int(cand[j])
            if cur == tgt:
                break  # arrived — frozen before the next round
        else:
            break  # stalled — that round applied but didn't move
    return cur, rounds


def pushsum_seed_state(g, seed, salt=0):
    vals = jax.random.normal(
        jax.random.fold_in(jax.random.key(salt), int(seed)),
        (g.n_nodes_padded,), dtype=jnp.float32)
    return PushSumState(s=vals * g.node_mask,
                        w=g.node_mask.astype(jnp.float32))


# -------------------------------------------------------- byte budget


class TestLaneBudget:
    def test_bit_lane_vs_f32_lane_asymmetry(self):
        # 1024 boolean lanes pack 32 per u32 word; 1024 f32 lanes pay
        # full width — the 32x the budget exists to make explicit.
        n = 1000
        bits = L.lane_bytes(1024, bool, n)
        floats = L.lane_bytes(1024, jnp.float32, n)
        assert bits == 32 * 4 * n  # ceil(1024/32) words x 4 bytes
        assert floats == 1024 * 4 * n
        assert floats == 32 * bits

    def test_ragged_bool_capacity_rounds_up_to_words(self):
        assert L.lane_bytes(33, bool, 10) == 2 * 4 * 10

    def test_carriers_multiply(self):
        one = L.lane_bytes(8, jnp.float32, 100, carriers=1)
        assert L.lane_bytes(8, jnp.float32, 100, carriers=2) == 2 * one

    def test_i32_lanes_price_like_f32(self):
        assert (L.lane_bytes(64, jnp.int32, 500)
                == L.lane_bytes(64, jnp.float32, 500))

    @pytest.mark.parametrize("bad", [
        dict(capacity=0, dtype=jnp.float32, n_pad=1),
        dict(capacity=4, dtype=jnp.float32, n_pad=0),
        dict(capacity=4, dtype=jnp.float32, n_pad=1, carriers=0),
    ])
    def test_invalid_args_raise(self, bad):
        with pytest.raises(ValueError):
            L.lane_bytes(**bad)

    def test_under_budget_returns_cost(self):
        assert L.lane_budget(4, jnp.float32, 100,
                             budget_bytes=10_000) == 1600

    def test_over_budget_raises_typed_error_naming_bytes(self):
        with pytest.raises(LaneBudgetExceeded) as ei:
            L.lane_budget(1000, jnp.float32, 1000, budget_bytes=1_000_000)
        err = ei.value
        assert isinstance(err, ValueError)  # back-compat except clause
        assert err.requested_bytes == 4_000_000
        assert err.budget_bytes == 1_000_000
        assert err.capacity == 1000
        assert "4,000,000" in str(err) and "1,000,000" in str(err)

    def test_env_budget_override(self, monkeypatch):
        monkeypatch.setenv("P2P_LANE_BUDGET_BYTES", "100")
        with pytest.raises(LaneBudgetExceeded):
            L.lane_budget(4, jnp.float32, 100)
        monkeypatch.setenv("P2P_LANE_BUDGET_BYTES", "100000")
        assert L.lane_budget(4, jnp.float32, 100) == 1600


class TestBudgetGate:
    """No family can allocate or admit past the budget silently —
    acceptance criterion, pinned per family."""

    def test_minplus_init_over_budget(self):
        g = ws(64)
        proto = MinPlusQueries(budget_bytes=100)
        with pytest.raises(LaneBudgetExceeded):
            proto.init(g, [0, 1], [2, 3])

    def test_dht_init_over_budget(self):
        g = G.chord(64)
        proto = DhtLookups(budget_bytes=8)
        with pytest.raises(LaneBudgetExceeded):
            proto.init(g, [0, 1, 2], [3, 4, 5])

    def test_pushsum_init_over_budget_counts_both_carriers(self):
        g = ws(64)
        n_pad = g.n_nodes_padded
        # one f32 carrier of 4 lanes fits; push-sum carries TWO
        fits_one = 4 * 4 * n_pad
        assert MinPlusQueries(budget_bytes=fits_one).empty(g, 4)
        with pytest.raises(LaneBudgetExceeded):
            PushSumQueries(budget_bytes=fits_one).empty(g, 4)

    @pytest.mark.parametrize("family", ["minplus", "dht", "pushsum"])
    def test_over_budget_admit_raises_typed_error(self, family):
        # Regression (acceptance): a batch built OUTSIDE the budget gate
        # (hand-constructed, or a config whose budget shrank) must still
        # refuse admission loudly — admit re-runs the gate.
        g = ws(64)
        roomy = dict(minplus=MinPlusQueries(),
                     dht=DhtLookups(),
                     pushsum=PushSumQueries())[family]
        qb = roomy.empty(g, 4)
        tight = dict(minplus=MinPlusQueries(budget_bytes=16),
                     dht=DhtLookups(budget_bytes=4),
                     pushsum=PushSumQueries(budget_bytes=16))[family]
        with pytest.raises(LaneBudgetExceeded):
            if family == "pushsum":
                tight.admit(g, qb, [1])
            else:
                tight.admit(g, qb, [1], [2])


# ------------------------------------------------------ kernel units


class TestLaneKernels:
    def test_minplus_lanes_gather_segment_and_vmap_agree(self):
        g = ws(200)
        rng = np.random.default_rng(0)
        d = rng.uniform(0, 5, (g.n_nodes_padded, 6)).astype(np.float32)
        d[rng.random(d.shape) < 0.5] = np.inf
        dj = jnp.asarray(d)
        out_g = L.propagate_min_plus_lanes(g, dj, "gather")
        out_s = L.propagate_min_plus_lanes(g, dj, "segment")
        ref = jax.vmap(lambda c: S.propagate_min_plus(g, c, "segment"),
                       in_axes=1, out_axes=1)(dj)
        assert bool(jnp.all(out_g == out_s))
        assert bool(jnp.all(out_g == ref))

    def test_sum_lanes_columns_match_segment_kernel_bitwise(self):
        # The float-op-order contract: both lane lowerings accumulate in
        # propagate_sum(method="segment")'s edge order.
        g = ws(200)
        rng = np.random.default_rng(1)
        v = jnp.asarray(rng.normal(
            size=(g.n_nodes_padded, 5)).astype(np.float32))
        for method in ("gather", "segment"):
            out = L.propagate_sum_lanes(g, v, method)
            for k in range(5):
                ref = S.propagate_sum(g, v[:, k], "segment")
                assert bool(jnp.all(out[:, k] == ref)), (method, k)

    def test_lane_kernels_reject_unknown_methods(self):
        g = ws(64)
        m = jnp.zeros((g.n_nodes_padded, 2), jnp.float32)
        with pytest.raises(ValueError, match="skew"):
            L.propagate_min_plus_lanes(g, m, "skew")
        with pytest.raises(ValueError, match="lane form"):
            L.propagate_sum_lanes(g, m, "blocked")

    def test_dht_hop_ties_break_to_first_slot(self):
        # Two equidistant closer neighbors: argmin takes the first table
        # slot — the determinism the identity sweep relies on.
        g = G.ring(8)
        cur = jnp.array([0], jnp.int32)
        keys = jnp.array([4], jnp.int32)  # ring: 1 and 7 both distance 3
        nxt, hopped = L.dht_hop_lanes(g, cur, keys, "ring")
        assert bool(hopped[0])
        first_slot = int(np.asarray(g.neighbors)[0, 0])
        d_first = (4 - first_slot) % 8
        others = [int(v) for v, m in zip(np.asarray(g.neighbors)[0],
                                         np.asarray(g.neighbor_mask)[0])
                  if m]
        best = min((4 - v) % 8 for v in others)
        if d_first == best:
            assert int(nxt[0]) == first_slot

    def test_dht_hop_rejects_unknown_metric(self):
        g = G.chord(16)
        with pytest.raises(ValueError, match="metric"):
            L.dht_hop_lanes(g, jnp.zeros(1, jnp.int32),
                            jnp.zeros(1, jnp.int32), "euclid")

    def test_gather_requires_complete_table(self):
        g = ws(200, max_degree=2)  # width-capped table
        m = jnp.zeros((g.n_nodes_padded, 2), jnp.float32)
        with pytest.raises(ValueError, match="capped|neighbor table"):
            L.propagate_min_plus_lanes(g, m, "gather")
        with pytest.raises(ValueError):
            L.dht_hop_lanes(g, jnp.zeros(1, jnp.int32),
                            jnp.zeros(1, jnp.int32), "ring")


# ---------------------------------------------------------- min-plus


class TestMinPlusQueries:
    def _sweep(self, g, srcs, tgts, proto=None, max_rounds=256):
        proto = proto or MinPlusQueries()
        qb = proto.init(g, srcs, tgts)
        qb, out = engine.run_queries_until_done(g, proto, qb, KEY,
                                                max_rounds=max_rounds)
        for k, (s, t) in enumerate(zip(srcs, tgts)):
            d_ref, r_ref = minplus_reference(g, s, t, max_rounds)
            assert int(out["lane_rounds"][k]) == r_ref, (k, s, t)
            v = float(out["lane_values"][k])
            ref_v = float(d_ref[int(t)])
            assert (v == ref_v) or (np.isinf(v) and np.isinf(ref_v)), k
            if r_ref > 0:
                assert bool(jnp.all(lane_dist(qb, k) == d_ref)), k
        assert bool(np.all(out["lane_done"][:len(srcs)]))
        return qb, out

    def test_identity_sweep_ws(self):
        g = ws(300)
        rng = np.random.default_rng(0)
        srcs = rng.integers(0, 300, 9).astype(np.int32)
        tgts = rng.integers(0, 300, 9).astype(np.int32)
        srcs[3] = tgts[3] = 17          # settled at admission
        srcs[4], tgts[4] = srcs[0], tgts[0]  # duplicate query
        self._sweep(g, srcs, tgts)

    def test_identity_sweep_er(self):
        g = G.erdos_renyi(257, 0.03, seed=5, source_csr=True)
        rng = np.random.default_rng(2)
        self._sweep(g, rng.integers(0, 257, 7).astype(np.int32),
                    rng.integers(0, 257, 7).astype(np.int32))

    def test_unreachable_target_settles_at_fixpoint_with_inf(self):
        # Two disjoint rings: a cross-component query has no path — the
        # lane must freeze at its fixpoint with +inf, not spin.
        src = np.arange(8, dtype=np.int32)
        dst = (src + 1) % 8
        s2 = src + 8
        d2 = (src + 1) % 8 + 8
        g = G.from_edges(np.concatenate([src, dst, s2, d2]),
                         np.concatenate([dst, src, d2, s2]), 16,
                         source_csr=True)
        qb, out = self._sweep(g, [0, 0], [4, 12])
        assert np.isfinite(out["lane_values"][0])
        assert np.isinf(out["lane_values"][1])

    def test_dead_source_settles_unreachable(self):
        g = failures.fail_nodes(ws(120), [7])
        qb, out = self._sweep(g, [7, 3], [30, 30])
        assert np.isinf(out["lane_values"][0])
        assert np.isfinite(out["lane_values"][1])

    def test_weighted_graph_completes_at_fixpoint_with_exact_costs(self):
        g = ws(200).with_weights(
            lambda s, r: 1.0 + ((s * 31 + r) % 7).astype(jnp.float32))
        rng = np.random.default_rng(3)
        srcs = rng.integers(0, 200, 5).astype(np.int32)
        tgts = rng.integers(0, 200, 5).astype(np.int32)
        self._sweep(g, srcs, tgts)

    def test_batched_equals_capacity_one_runs_bitwise(self):
        g = ws(256, seed=9)
        rng = np.random.default_rng(4)
        srcs = rng.integers(0, 256, 6).astype(np.int32)
        tgts = rng.integers(0, 256, 6).astype(np.int32)
        proto = MinPlusQueries()
        qb = proto.init(g, srcs, tgts)
        qb, out = engine.run_queries_until_done(g, proto, qb, KEY)
        for k in range(6):
            q1 = proto.init(g, srcs[k:k + 1], tgts[k:k + 1])
            q1, o1 = engine.run_queries_until_done(g, proto, q1, KEY)
            assert int(o1["lane_rounds"][0]) == int(out["lane_rounds"][k])
            assert float(o1["lane_values"][0]) == float(
                out["lane_values"][k])
            assert bool(jnp.all(q1.payload["dist"][:, 0]
                                == qb.payload["dist"][:, k]))

    def test_admit_validation(self):
        g = ws(100)
        proto = MinPlusQueries()
        with pytest.raises(ValueError, match="at least one"):
            proto.init(g, [], [])
        with pytest.raises(ValueError, match="pairs"):
            proto.init(g, [0, 1], [2])
        with pytest.raises(ValueError, match="out of range"):
            proto.init(g, [-1], [2])
        with pytest.raises(ValueError, match="out of range"):
            proto.init(g, [0], [g.n_nodes_padded])
        with pytest.raises(ValueError, match="capacity"):
            proto.init(g, [0, 1], [2, 3], capacity=1)

    def test_lane_exhaustion_is_the_backpressure_signal(self):
        g = ws(100)
        proto = MinPlusQueries()
        qb = proto.init(g, [0, 1], [5, 6], capacity=3)
        assert free_query_lanes(qb) == 1
        with pytest.raises(LaneExhausted) as ei:
            proto.admit(g, qb, [2, 3], [7, 8])
        assert ei.value.free_lanes == 1 and ei.value.capacity == 3

    def test_retire_recycles_and_second_wave_matches(self):
        g = ws(256, seed=11)
        proto = MinPlusQueries()
        qb = proto.init(g, [3, 99], [200, 10], capacity=2)
        qb, out1 = engine.run_queries_until_done(g, proto, qb, KEY)
        first_vals = out1["lane_values"].copy()
        qb = proto.retire(qb)                    # all done -> all open
        assert free_query_lanes(qb) == 2
        assert bool(jnp.all(jnp.isinf(qb.payload["dist"])))
        qb, lanes = proto.admit(g, qb, [50], [123])
        qb, out2 = engine.run_queries_until_done(g, proto, qb, KEY)
        d_ref, r_ref = minplus_reference(g, 50, 123)
        lane = int(lanes[0])
        assert int(out2["lane_rounds"][lane]) == r_ref
        assert float(out2["lane_values"][lane]) == float(d_ref[123])
        del first_vals

    def test_retire_bounds_check(self):
        g = ws(64)
        qb = MinPlusQueries().init(g, [0], [5])
        with pytest.raises(ValueError, match="capacity"):
            MinPlusQueries().retire(qb, [-1])

    def test_lane_dist_bounds_check(self):
        g = ws(64)
        qb = MinPlusQueries().init(g, [0], [5])
        with pytest.raises(ValueError, match="capacity"):
            lane_dist(qb, 99)

    def test_frozen_lanes_stay_byte_identical_through_second_wave(self):
        g = ws(256, seed=13)
        proto = MinPlusQueries()
        qb = proto.init(g, [3], [200], capacity=2)
        qb, _ = engine.run_queries_until_done(g, proto, qb, KEY,
                                              donate=False)
        frozen = np.asarray(qb.payload["dist"][:, 0]).copy()
        qb, _ = proto.admit(g, qb, [50], [123])
        qb, _ = engine.run_queries_until_done(g, proto, qb, KEY,
                                              donate=False)
        assert bool(np.all(np.asarray(qb.payload["dist"][:, 0])
                           == frozen))


# --------------------------------------------------------------- DHT


class TestDhtLookups:
    @pytest.mark.parametrize("builder,metric", [
        (lambda: G.chord(128), "ring"),
        (lambda: G.kademlia(128), "xor"),
        (lambda: G.kademlia(100, k=2), "xor"),  # partially-populated ids
    ])
    def test_identity_sweep_vs_numpy_greedy_walk(self, builder, metric):
        g = builder()
        rng = np.random.default_rng(0)
        K = 23
        orgs = rng.integers(0, g.n_nodes, K).astype(np.int32)
        keys = rng.integers(0, g.n_nodes, K).astype(np.int32)
        orgs[5] = keys[5]  # arrived at admission
        proto = DhtLookups(metric=metric)
        qb = proto.init(g, orgs, keys)
        qb, out = engine.run_queries_until_done(g, proto, qb, KEY,
                                                max_rounds=64)
        assert bool(np.all(out["lane_done"][:K]))
        assert out["lane_values"].dtype == np.int32
        for k in range(K):
            cur_ref, r_ref = dht_reference(g, orgs[k], keys[k], metric)
            assert int(out["lane_values"][k]) == cur_ref, k
            assert int(out["lane_rounds"][k]) == r_ref, k

    def test_fully_populated_chord_resolves_every_lookup(self):
        g = G.chord(256)
        rng = np.random.default_rng(1)
        orgs = rng.integers(0, 256, 64).astype(np.int32)
        keys = rng.integers(0, 256, 64).astype(np.int32)
        proto = DhtLookups(metric="ring")
        qb = proto.init(g, orgs, keys)
        qb, out = engine.run_queries_until_done(g, proto, qb, KEY)
        assert bool(np.all(out["lane_values"] == keys))
        # O(log n) resolution: chord lookups finish in <= log2(n) hops
        assert int(np.max(out["lane_rounds"][:64])) <= 8

    def test_dead_responsible_node_stalls_not_found(self):
        g = failures.fail_nodes(G.chord(128), [40])
        proto = DhtLookups(metric="ring")
        qb = proto.init(g, [3], [40])
        qb, out = engine.run_queries_until_done(g, proto, qb, KEY)
        assert bool(out["lane_done"][0])
        assert int(out["lane_values"][0]) != 40  # stalled short of it

    def test_dead_origin_completes_immediately(self):
        g = failures.fail_nodes(G.chord(128), [3])
        qb = DhtLookups().init(g, [3], [40])
        assert bool(qb.done[0])
        qb, out = engine.run_queries_until_done(g, DhtLookups(), qb, KEY)
        assert int(out["lane_rounds"][0]) == 0

    def test_key_range_validation(self):
        g = G.chord(64)
        with pytest.raises(ValueError, match="id space"):
            DhtLookups().init(g, [0], [64])
        with pytest.raises(ValueError, match="id space"):
            DhtLookups().init(g, [0], [-1])

    def test_metric_validated_at_construction(self):
        with pytest.raises(ValueError, match="metric"):
            DhtLookups(metric="cosine")


# ----------------------------------------------------------- push-sum


class TestPushSumQueries:
    def test_eager_mass_trajectory_bitwise_vs_pushsum(self):
        # The float-op-order contract: K batched lanes stepped eagerly
        # produce bit-for-bit the masses of K independent
        # models/pushsum.py runs, round for round.
        g = ws(200, seed=7)
        seeds = np.array([1, 9, 42], dtype=np.int32)
        proto = PushSumQueries()
        qb = proto.init(g, seeds, threshold=1e-30)  # nothing freezes
        ref = PushSum(method="segment")
        sts = [pushsum_seed_state(g, s) for s in seeds]
        for r in range(10):
            qb, _ = proto.step(g, qb, KEY)
            for k in range(3):
                sts[k], _ = ref.step(g, sts[k], KEY)
                assert bool(jnp.all(qb.payload["s"][:, k]
                                    == sts[k].s)), (r, k)
                assert bool(jnp.all(qb.payload["w"][:, k]
                                    == sts[k].w)), (r, k)

    def test_engine_rounds_match_single_convergence_and_values(self):
        g = ws(200, seed=7)
        seeds = np.array([1, 9, 42, 77], dtype=np.int32)
        th = 1e-3
        proto = PushSumQueries()
        qb = proto.init(g, seeds, threshold=th)
        qb, out = engine.run_queries_until_done(g, proto, qb, KEY,
                                                max_rounds=512)
        ref = PushSum(method="segment")
        mask = np.asarray(g.node_mask)
        for k, s in enumerate(seeds):
            st = pushsum_seed_state(g, s)
            true_mean = float(np.sum(np.asarray(st.s)) / mask.sum())
            r = 0
            while r < 512:
                st, stats = ref.step(g, st, KEY)
                r += 1
                if float(stats["variance"]) < th:
                    break
            assert int(out["lane_rounds"][k]) == r, k
            np.testing.assert_allclose(
                np.asarray(qb.payload["s"][:, k]), np.asarray(st.s),
                rtol=1e-5, atol=1e-7)
            # the query's answer: the converged network-mean estimate
            np.testing.assert_allclose(float(out["lane_values"][k]),
                                       true_mean, rtol=0.2, atol=0.05)

    def test_one_admitted_lane_in_full_width_batch_is_bit_identical(self):
        # Lane isolation at the SAME compiled width: a K-wide batch with
        # one admitted lane reproduces that lane of the full batch bit
        # for bit — queries cannot interfere.
        g = ws(200, seed=7)
        seeds = np.array([1, 9, 42, 77], dtype=np.int32)
        th = 1e-3
        proto = PushSumQueries()
        qb = proto.init(g, seeds, threshold=th)
        qb, out = engine.run_queries_until_done(g, proto, qb, KEY,
                                                max_rounds=512)
        lone = proto.empty(g, 4)
        lone, _ = proto.admit(g, lone, seeds[2:3], threshold=th)
        lone, o1 = engine.run_queries_until_done(g, proto, lone, KEY,
                                                 max_rounds=512)
        assert int(o1["lane_rounds"][0]) == int(out["lane_rounds"][2])
        assert float(o1["lane_values"][0]) == float(out["lane_values"][2])
        assert bool(jnp.all(lone.payload["s"][:, 0]
                            == qb.payload["s"][:, 2]))
        assert bool(jnp.all(lone.payload["w"][:, 0]
                            == qb.payload["w"][:, 2]))

    def test_already_converged_at_admission_completes_with_zero_rounds(self):
        g = ws(100)
        proto = PushSumQueries()
        qb = proto.init(g, [5], threshold=1e6)  # var(seed) ~1 << 1e6
        qb, out = engine.run_queries_until_done(g, proto, qb, KEY)
        assert bool(out["lane_done"][0])
        assert int(out["lane_rounds"][0]) == 0

    def test_threshold_validation(self):
        g = ws(100)
        with pytest.raises(ValueError, match="threshold"):
            PushSumQueries().init(g, [1], threshold=0.0)

    def test_seed_salt_changes_the_value_field(self):
        g = ws(100)
        a = PushSumQueries(seed_salt=0).init(g, [1], threshold=1e-3)
        b = PushSumQueries(seed_salt=1).init(g, [1], threshold=1e-3)
        assert not bool(jnp.all(a.payload["s"] == b.payload["s"]))


# ------------------------------------------------- engine + summary


class TestQueryEngine:
    def test_packed_summary_roundtrip_float_and_int_values(self):
        done = jnp.array([True, False, True, False, True], dtype=bool)
        rounds = jnp.array([3, 0, 7, 1, 2], jnp.int32)
        fvals = jnp.array([1.5, jnp.inf, -2.0, 0.0, 3.25], jnp.float32)
        ivals = jnp.array([7, -1, 123456789, 0, 42], jnp.int32)
        for vals, vf in ((fvals, True), (ivals, False)):
            packed = accum.pack_query_summary(
                jnp.int32(9), jnp.int32(2), jnp.int32(3),
                (jnp.int32(1), jnp.uint32(5)), jnp.float32(0.25),
                _pack_done(done), rounds, vals, values_float=vf)
            out = accum.unpack_query_summary(packed, 5, values_float=vf)
            assert out["rounds"] == 9
            assert out["active_lanes"] == 2 and out["completed"] == 3
            assert out["messages"] == (1 << 32) + 5
            assert out["occupancy_mean"] == 0.25
            assert bool(np.all(out["lane_done"] == np.asarray(done)))
            assert bool(np.all(out["lane_rounds"] == np.asarray(rounds)))
            assert bool(np.all(out["lane_values"] == np.asarray(vals)))

    def test_newly_completed_excludes_pre_run_done_on_resume(self):
        g = ws(256, seed=15)
        proto = MinPlusQueries()
        qb = proto.init(g, [0, 100], [200, 50])
        qb, out1 = engine.run_queries_until_done(g, proto, qb, KEY,
                                                 max_rounds=1)
        # round-1 cut: nothing settles on a 256-ring-ish graph in one
        # round (sources != targets here)
        qb, out2 = engine.run_queries_until_done(g, proto, qb, KEY)
        done_after_1 = set(np.flatnonzero(out1["lane_done"]).tolist())
        newly2 = set(out2["newly_completed_lanes"].tolist())
        assert newly2.isdisjoint(done_after_1)
        assert done_after_1 | newly2 == {0, 1}
        # lane_rounds are resume-cumulative
        assert int(out2["lane_rounds"][0]) >= int(out1["lane_rounds"][0])

    def test_default_donation_invalidates_and_keeps_on_request(self):
        g = ws(100)
        proto = MinPlusQueries()
        qb = proto.init(g, [0], [50])
        kept, _ = engine.run_queries_until_done(g, proto, qb, KEY)
        assert qb.payload["dist"].is_deleted()
        with pytest.raises(ValueError, match="donated"):
            engine.run_queries_until_done(g, proto, qb, KEY)
        qb2 = proto.init(g, [0], [50])
        _, _ = engine.run_queries_until_done(g, proto, qb2, KEY,
                                             donate=False)
        assert not qb2.payload["dist"].is_deleted()
        del kept

    def test_resume_equals_one_shot(self):
        g = ws(256, seed=17)
        proto = MinPlusQueries()
        qb = proto.init(g, [0, 30], [200, 150])
        one, out_one = engine.run_queries_until_done(g, proto, qb, KEY)
        qb2 = proto.init(g, [0, 30], [200, 150])
        qb2, _ = engine.run_queries_until_done(g, proto, qb2, KEY,
                                               max_rounds=2)
        qb2, out2 = engine.run_queries_until_done(g, proto, qb2, KEY)
        assert bool(jnp.all(qb2.payload["dist"]
                            == one.payload["dist"]))
        assert bool(np.all(out2["lane_rounds"] == out_one["lane_rounds"]))

    def test_max_rounds_freezes_stragglers_reported_active(self):
        g = ws(300, seed=19)
        proto = MinPlusQueries()
        qb = proto.init(g, [0, 1], [250, 251])
        qb, out = engine.run_queries_until_done(g, proto, qb, KEY,
                                                max_rounds=1)
        assert out["rounds"] == 1
        assert out["active_lanes"] == 2
        assert out["completed"] == 0

    def test_query_telemetry_registered(self):
        from p2pnetwork_tpu import telemetry
        g = ws(100)
        proto = MinPlusQueries()
        qb = proto.init(g, [0], [60])
        engine.run_queries_until_done(g, proto, qb, KEY)
        reg = telemetry.default_registry()
        assert reg.value("sim_query_active_lanes") == 0.0
        assert reg.value("sim_runs_total", loop="query") >= 1.0
        hist = reg.histogram(
            "sim_query_completion_rounds",
            "Rounds each batched query took to settle (one observation "
            "per lane completed in a run_queries_until_done call).",
            buckets=engine._COMPLETION_BUCKETS)
        assert hist.count >= 1

    def test_dht_lane_values_survive_large_node_ids(self):
        # i32 answers ride the packed summary raw — an f32 bitcast would
        # corrupt node ids past 2^24; pin exactness of a 2^24+ id.
        big = 17_000_000
        packed = accum.pack_query_summary(
            jnp.int32(1), jnp.int32(0), jnp.int32(1),
            (jnp.int32(0), jnp.uint32(0)), jnp.float32(0.0),
            _pack_done(jnp.array([True])), jnp.array([5], jnp.int32),
            jnp.array([big], jnp.int32), values_float=False)
        out = accum.unpack_query_summary(packed, 1, values_float=False)
        assert int(out["lane_values"][0]) == big


def _pack_done(done):
    from p2pnetwork_tpu.ops import bitset
    return bitset.pack_bits(jnp.asarray(done))


# -------------------------------------------------- observability


class TestQueryObservability:
    def test_recorder_on_is_bit_identical_and_rows_describe_rounds(self):
        g = ws(256, seed=21)
        proto = MinPlusQueries()
        qb1 = proto.init(g, [0, 9, 77], [200, 10, 140])
        q_off, out_off = engine.run_queries_until_done(g, proto, qb1, KEY)
        qb2 = proto.init(g, [0, 9, 77], [200, 10, 140])
        rec = flightrec.FlightRecorder(capacity=64)
        q_on, out_on = engine.run_queries_until_done(g, proto, qb2, KEY,
                                                     recorder=rec)
        assert bool(jnp.all(q_on.payload["dist"] == q_off.payload["dist"]))
        for key in ("rounds", "messages", "completed"):
            assert out_on[key] == out_off[key], key
        assert bool(np.all(out_on["lane_rounds"] == out_off["lane_rounds"]))
        assert bool(np.all(out_on["lane_values"] == out_off["lane_values"]))
        fr = out_on["flight_record"]
        assert fr.rounds == out_on["rounds"]
        assert fr.rows.shape[0] == out_on["rounds"]
        assert list(fr.column("round")) == list(
            range(1, out_on["rounds"] + 1))
        # active_lanes is non-increasing (queries only ever freeze)
        active = fr.column("active_lanes")
        assert bool(np.all(np.diff(active) <= 0))
        # coverage column carries the settled-lane count; final row shows
        # every lane done
        assert fr.column("coverage")[-1] == 3

    def test_trace_events_cover_the_lane_lifecycle(self):
        g = ws(256, seed=23)
        proto = MinPlusQueries()
        t = spans.Tracer("query-test")
        prev = spans.install_tracer(t)
        try:
            qb = proto.init(g, [0, 9], [200, 10])
            qb, out = engine.run_queries_until_done(g, proto, qb, KEY)
            qb = proto.retire(qb)
        finally:
            spans.install_tracer(prev)
        assert len(t.find("query_run")) == 1
        submits = sorted(sp.args["lane"] for sp in t.find("lane_submit"))
        assert submits == [0, 1]
        admits = sorted(sp.args["lane"] for sp in t.find("lane_admit"))
        assert admits == [0, 1]
        completes = {sp.args["lane"]: sp.args["rounds"]
                     for sp in t.find("lane_complete")}
        assert set(completes) == {0, 1}
        for lane, r in completes.items():
            assert r == int(out["lane_rounds"][lane])
        assert sorted(sp.args["lane"] for sp in t.find("lane_retire")) \
            == [0, 1]
        assert t.find("lane_freeze") == []

    def test_trace_freeze_and_resume_events(self):
        g = ws(300, seed=25)
        proto = MinPlusQueries()
        t = spans.Tracer("query-freeze")
        prev = spans.install_tracer(t)
        try:
            qb = proto.init(g, [0], [250])
            qb, _ = engine.run_queries_until_done(g, proto, qb, KEY,
                                                  max_rounds=1)
            qb, _ = engine.run_queries_until_done(g, proto, qb, KEY)
        finally:
            spans.install_tracer(prev)
        assert [sp.args["lane"] for sp in t.find("lane_freeze")] == [0]
        assert [sp.args["lane"] for sp in t.find("lane_resume")] == [0]
        assert [sp.args["lane"] for sp in t.find("lane_complete")] == [0]

    def test_recorder_ring_is_donated(self):
        # The rec twin donates the ring alongside the state (the audit
        # covers the compiled artifact; this pins the runtime behavior).
        g = ws(100)
        proto = MinPlusQueries()
        qb = proto.init(g, [0], [60])
        rec = flightrec.FlightRecorder(capacity=16)
        ring = rec.init()
        engine._query_loop_rec_donating(g, proto, qb, KEY, ring,
                                        max_rounds=8)
        assert ring.is_deleted()


# ------------------------------------------------- slow ratchets


def _ws100k():
    return G.watts_strogatz(100_000, 10, 0.1, seed=0, source_csr=True)


@pytest.mark.slow
class TestAggregateRatchets:
    """The acceptance ratchets: >= 10x aggregate throughput vs warm
    sequential capacity-1 runs of the same family, at the bench-default
    K on 100k-node graphs — ratio-based (one machine measures both
    sides), no wall-clock thresholds — plus the per-lane identity sweep
    at the same scale."""

    def test_minplus_ratchet_and_identity_at_bench_k(self):
        import time
        g = _ws100k()
        K = 64  # bench default (BENCH_QUERY_K_MINPLUS)
        rng = np.random.default_rng(0)
        srcs = rng.integers(0, g.n_nodes, K).astype(np.int32)
        tgts = rng.integers(0, g.n_nodes, K).astype(np.int32)
        proto = MinPlusQueries()

        def batched():
            qb = proto.init(g, srcs, tgts)
            return engine.run_queries_until_done(g, proto, qb, KEY,
                                                 max_rounds=256)
        batched()  # warm
        times = []
        for _ in range(2):  # best-of, like bench.py's best_s
            t0 = time.perf_counter()
            _, out = batched()
            times.append(time.perf_counter() - t0)
        batch_s = min(times)
        assert int(out["completed"]) == K

        def single(i):
            q1 = proto.init(g, srcs[i:i + 1], tgts[i:i + 1])
            return engine.run_queries_until_done(g, proto, q1, KEY,
                                                 max_rounds=256)
        single(0)  # warm the capacity-1 program
        seq = 0.0
        for i in range(K):
            t0 = time.perf_counter()
            _, o1 = single(i)
            seq += time.perf_counter() - t0
            # identity at scale: every lane bitwise equals its
            # independent capacity-1 run
            assert float(o1["lane_values"][0]) == float(
                out["lane_values"][i]), i
            assert int(o1["lane_rounds"][0]) == int(
                out["lane_rounds"][i]), i
        ratio = seq / batch_s
        assert ratio >= 10.0, f"minplus aggregate ratio {ratio:.1f}x < 10x"

    def test_dht_ratchet_and_identity_at_bench_k(self):
        import time
        g = G.chord(100_000)
        K = 2048  # bench default (BENCH_QUERY_K_DHT)
        rng = np.random.default_rng(0)
        orgs = rng.integers(0, g.n_nodes, K).astype(np.int32)
        keys = rng.integers(0, g.n_nodes, K).astype(np.int32)
        proto = DhtLookups(metric="ring")

        def batched():
            qb = proto.init(g, orgs, keys)
            return engine.run_queries_until_done(g, proto, qb, KEY,
                                                 max_rounds=128)
        batched()
        times = []
        for _ in range(2):
            t0 = time.perf_counter()
            _, out = batched()
            times.append(time.perf_counter() - t0)
        batch_s = min(times)
        assert int(out["completed"]) == K
        # identity at scale: every lane vs the numpy greedy walk
        for k in range(K):
            cur_ref, r_ref = dht_reference(g, orgs[k], keys[k], "ring")
            assert int(out["lane_values"][k]) == cur_ref, k
            assert int(out["lane_rounds"][k]) == r_ref, k
        # fully-populated chord: every lookup arrives
        assert bool(np.all(out["lane_values"] == keys))

        def single(i):
            q1 = proto.init(g, orgs[i:i + 1], keys[i:i + 1])
            return engine.run_queries_until_done(g, proto, q1, KEY,
                                                 max_rounds=128)
        single(0)
        seq = 0.0
        sample = 64  # extrapolated: 2048 sequential runs would dominate
        for i in range(sample):
            t0 = time.perf_counter()
            single(i)
            seq += time.perf_counter() - t0
        ratio = (seq / sample) * K / batch_s
        assert ratio >= 10.0, f"dht aggregate ratio {ratio:.1f}x < 10x"

    def test_pushsum_ratchet_and_isolation_at_bench_k(self):
        import time
        g = _ws100k()
        K = 32  # bench default (BENCH_QUERY_K_PUSHSUM)
        seeds = (np.arange(K) * 7 + 1).astype(np.int32)
        th = 1e-4
        proto = PushSumQueries()

        def batched():
            qb = proto.init(g, seeds, threshold=th)
            return engine.run_queries_until_done(g, proto, qb, KEY,
                                                 max_rounds=512)
        batched()
        times = []
        for _ in range(3):  # best-of: this box's noise swings ~25%
            t0 = time.perf_counter()
            qb, out = batched()
            times.append(time.perf_counter() - t0)
        batch_s = min(times)
        assert int(out["completed"]) == K

        def single(i):
            q1 = proto.init(g, seeds[i:i + 1], threshold=th)
            return engine.run_queries_until_done(g, proto, q1, KEY,
                                                 max_rounds=512)
        single(0)
        seq = 0.0
        sample = 8
        for i in range(sample):
            t0 = time.perf_counter()
            _, o1 = single(i)
            seq += time.perf_counter() - t0
            assert int(o1["lane_rounds"][0]) == int(out["lane_rounds"][i])
        ratio = (seq / sample) * K / batch_s
        assert ratio >= 10.0, f"pushsum aggregate ratio {ratio:.1f}x < 10x"
        # identity at scale: a one-admitted-lane run of the SAME width
        # reproduces its lane of the full batch bit for bit
        lone = proto.empty(g, K)
        lone, _ = proto.admit(g, lone, seeds[3:4], threshold=th)
        lone, o1 = engine.run_queries_until_done(g, proto, lone, KEY,
                                                 max_rounds=512)
        assert int(o1["lane_rounds"][0]) == int(out["lane_rounds"][3])
        assert bool(jnp.all(lone.payload["s"][:, 0]
                            == qb.payload["s"][:, 3]))
        assert bool(jnp.all(lone.payload["w"][:, 0]
                            == qb.payload["w"][:, 3]))
