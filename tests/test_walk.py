"""RandomWalks (peer sampling / overlay discovery) — property oracles.

The walk is PRNG-driven, so instead of replaying jax's RNG in numpy the
oracles pin structural invariants: every hop follows a live edge, stuck
walkers stay, dead nodes are never stood on, the visited set is exactly
the union of positions, and discovery covers connected overlays.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from p2pnetwork_tpu.models import RandomWalks  # noqa: E402
from p2pnetwork_tpu.sim import engine, failures, topology  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def _live_edge_set(g):
    alive = np.asarray(g.node_mask)
    s = np.asarray(g.senders)
    r = np.asarray(g.receivers)
    em = np.asarray(g.edge_mask)
    ok = em & alive[s] & alive[r]
    pairs = set(zip(s[ok].tolist(), r[ok].tolist()))
    if g.dyn_senders is not None:
        dm = np.asarray(g.dyn_mask)
        ds, dr = np.asarray(g.dyn_senders), np.asarray(g.dyn_receivers)
        ok = dm & alive[ds] & alive[dr]
        pairs |= set(zip(ds[ok].tolist(), dr[ok].tolist()))
    return pairs


class TestRandomWalks:
    def test_every_hop_is_a_live_edge(self):
        g = G.watts_strogatz(512, 6, 0.2, seed=0, source_csr=True)
        proto = RandomWalks(n_walkers=64)
        edges = _live_edge_set(g)
        state = proto.init(g, jax.random.key(0))
        key = jax.random.key(1)
        for i in range(20):
            prev = np.asarray(state.pos)
            state, stats = proto.step(g, state, jax.random.fold_in(key, i))
            cur = np.asarray(state.pos)
            for a, b in zip(prev.tolist(), cur.tolist()):
                assert a == b or (a, b) in edges, f"illegal hop {a}->{b}"

    def test_visited_is_union_of_positions_and_monotone(self):
        g = G.erdos_renyi(256, 0.05, seed=1, source_csr=True)
        proto = RandomWalks(n_walkers=32)
        state = proto.init(g, jax.random.key(0))
        seen = set(np.asarray(state.pos).tolist())
        key = jax.random.key(2)
        prev_visited = np.asarray(state.visited).copy()
        for i in range(15):
            state, _ = proto.step(g, state, jax.random.fold_in(key, i))
            seen |= set(np.asarray(state.pos).tolist())
            visited = np.asarray(state.visited)
            assert visited[prev_visited].all(), "visited must be monotone"
            prev_visited = visited.copy()
        assert set(np.nonzero(prev_visited)[0].tolist()) == seen

    def test_stuck_walker_stays_on_sink(self):
        # Directed chain 0->1->2; node 2 is a sink: a walker reaching it
        # must stay (and report stuck), never jump.
        g = G.from_edges(np.array([0, 1]), np.array([1, 2]), 3,
                         source_csr=True)
        proto = RandomWalks(n_walkers=4)
        state = proto.init(g, jax.random.key(0))
        key = jax.random.key(3)
        for i in range(8):
            state, stats = proto.step(g, state, jax.random.fold_in(key, i))
        assert (np.asarray(state.pos) == 2).all()
        assert int(stats["stuck"]) == 4
        assert int(stats["messages"]) == 0

    def test_discovers_connected_overlay(self):
        g = G.watts_strogatz(1024, 8, 0.3, seed=2, source_csr=True)
        proto = RandomWalks(n_walkers=128)
        state, out = engine.run_until_coverage(
            g, proto, jax.random.key(0), coverage_target=0.99,
            max_rounds=512,
        )
        assert float(out["coverage"]) >= 0.99
        assert int(out["messages"]) > 0

    def test_never_stands_on_dead_nodes(self):
        g = G.watts_strogatz(256, 6, 0.2, seed=3, source_csr=True)
        dead = list(range(50, 90))
        gf = failures.fail_nodes(g, dead)
        proto = RandomWalks(n_walkers=64)
        state = proto.init(gf, jax.random.key(0))
        assert not np.isin(np.asarray(state.pos), dead).any()
        key = jax.random.key(4)
        for i in range(20):
            state, _ = proto.step(gf, state, jax.random.fold_in(key, i))
            assert not np.isin(np.asarray(state.pos), dead).any()
        assert not np.asarray(state.visited)[dead].any()

    def test_walks_dynamic_links(self):
        # Two directed rings bridged only by a runtime link: walkers
        # seeded in the low ring can only reach the high ring across it.
        idx = np.arange(32)
        g = G.from_edges(np.r_[idx, 32 + idx],
                         np.r_[(idx + 1) % 32, 32 + (idx + 1) % 32], 64,
                         source_csr=True)
        g = topology.connect(topology.with_capacity(g, extra_edges=4),
                             [5], [40])
        edges = _live_edge_set(g)
        assert (5, 40) in edges  # the runtime bridge is a legal hop
        proto = RandomWalks(n_walkers=8)
        state = proto.init(g, jax.random.key(0))
        # Force every walker into the LOW ring: crossing then requires
        # the dynamic 5 -> 40 link (the strided default seeds both rings,
        # which would make the assertion vacuous).
        import jax.numpy as jnp
        state = type(state)(pos=state.pos % 32, start=state.start % 32,
                            visited=jnp.zeros_like(state.visited)
                            .at[state.pos % 32].set(True))
        key = jax.random.key(5)
        crossed = False
        for i in range(200):
            prev = np.asarray(state.pos)
            state, _ = proto.step(g, state, jax.random.fold_in(key, i))
            cur = np.asarray(state.pos)
            for a, b in zip(prev.tolist(), cur.tolist()):
                assert a == b or (a, b) in edges
            crossed = crossed or (cur >= 32).any()
        assert crossed, "no walker ever took the runtime bridge"

    def test_restart_returns_to_start(self):
        g = G.ring(64, source_csr=True)
        proto = RandomWalks(n_walkers=16, restart_p=1.0)
        state = proto.init(g, jax.random.key(0))
        start = np.asarray(state.start).copy()
        state, _ = proto.step(g, state, jax.random.key(1))
        np.testing.assert_array_equal(np.asarray(state.pos), start)

    def test_deterministic_under_key(self):
        g = G.watts_strogatz(256, 4, 0.1, seed=6, source_csr=True)
        proto = RandomWalks(n_walkers=32, restart_p=0.1)
        a, _ = engine.run(g, proto, jax.random.key(9), 25)
        b, _ = engine.run(g, proto, jax.random.key(9), 25)
        np.testing.assert_array_equal(np.asarray(a.pos), np.asarray(b.pos))
        np.testing.assert_array_equal(np.asarray(a.visited),
                                      np.asarray(b.visited))

    def test_walker_count_conserved_and_spread(self):
        g = G.watts_strogatz(1024, 6, 0.1, seed=7, source_csr=True)
        proto = RandomWalks(n_walkers=256)
        state = proto.init(g, jax.random.key(0))
        assert state.pos.shape == (256,)
        # Even spread: no node hosts more than ceil(W / n_live) + slack.
        counts = np.bincount(np.asarray(state.pos), minlength=1024)
        assert counts.max() == 1  # 256 walkers, 1024 live nodes

    def test_validates_arguments_and_graph(self):
        with pytest.raises(ValueError, match="n_walkers"):
            RandomWalks(n_walkers=0)
        with pytest.raises(ValueError, match="restart_p"):
            RandomWalks(restart_p=1.5)
        g = G.ring(32)  # no source CSR
        with pytest.raises(ValueError, match="source_csr"):
            RandomWalks(n_walkers=4).init(g, jax.random.key(0))

    def test_uniformity_on_a_star_hub(self):
        # Hub 0 points at 255 leaves; a large cohort of single-step moves
        # from the hub must hit leaves roughly uniformly (chi-square-ish
        # sanity, not a strict test).
        n = 256
        leaves = np.arange(1, n)
        g = G.from_edges(np.zeros(n - 1, np.int32), leaves, n,
                         source_csr=True)
        proto = RandomWalks(n_walkers=4096)
        state = proto.init(g, jax.random.key(0))
        # Force every walker onto the hub.
        state = type(state)(
            pos=state.pos * 0, start=state.start * 0,
            visited=state.visited,
        )
        state, _ = proto.step(g, state, jax.random.key(1))
        counts = np.bincount(np.asarray(state.pos), minlength=n)[1:]
        assert counts.sum() == 4096
        # Expected 16 per leaf; all leaves hit within a generous band.
        assert counts.min() >= 2 and counts.max() <= 48

class TestShardedWalk:
    """The walker cohort on the ring: bit-identical to the engine for any
    shard count, because candidate draws are keyed by edge identity
    (utils/edgehash.py), not array slot."""

    @pytest.mark.parametrize("n_shards", [1, 2, 8])
    def test_matches_engine_bitexact(self, n_shards):
        from p2pnetwork_tpu.parallel import mesh as M, sharded

        g = G.watts_strogatz(512, 6, 0.2, seed=0, source_csr=True)
        proto = RandomWalks(n_walkers=64)
        ref_state, ref_stats = engine.run(g, proto, jax.random.key(0), 15)
        mesh = M.ring_mesh(n_shards)
        sg = sharded.shard_graph(g, mesh, source_csr=True)
        (pos, _, visited), stats = sharded.walk(
            sg, mesh, proto, jax.random.key(0), 15, return_state=True)
        np.testing.assert_array_equal(np.asarray(pos),
                                      np.asarray(ref_state.pos))
        np.testing.assert_array_equal(np.asarray(visited).reshape(-1),
                                      np.asarray(ref_state.visited))
        np.testing.assert_array_equal(np.asarray(stats["messages"]),
                                      np.asarray(ref_stats["messages"]))
        np.testing.assert_array_equal(np.asarray(stats["stuck"]),
                                      np.asarray(ref_stats["stuck"]))

    def test_restart_parity(self):
        from p2pnetwork_tpu.parallel import mesh as M, sharded

        g = G.watts_strogatz(256, 4, 0.1, seed=1, source_csr=True)
        proto = RandomWalks(n_walkers=32, restart_p=0.3)
        ref_state, _ = engine.run(g, proto, jax.random.key(5), 20)
        mesh = M.ring_mesh(4)
        sg = sharded.shard_graph(g, mesh, source_csr=True)
        (pos, _, _), _ = sharded.walk(sg, mesh, proto, jax.random.key(5),
                                      20, return_state=True)
        np.testing.assert_array_equal(np.asarray(pos),
                                      np.asarray(ref_state.pos))

    def test_coverage_loop_matches_engine(self):
        from p2pnetwork_tpu.parallel import mesh as M, sharded

        g = G.watts_strogatz(512, 8, 0.3, seed=2, source_csr=True)
        proto = RandomWalks(n_walkers=64)
        ref_state, ref_out = engine.run_until_coverage(
            g, proto, jax.random.key(3), coverage_target=0.9,
            max_rounds=512,
        )
        mesh = M.ring_mesh(8)
        sg = sharded.shard_graph(g, mesh, source_csr=True)
        visited, out = sharded.walk_until_coverage(
            sg, mesh, proto, jax.random.key(3), coverage_target=0.9,
            max_rounds=512,
        )
        assert out["rounds"] == ref_out["rounds"]
        assert out["messages"] == ref_out["messages"]
        np.testing.assert_array_equal(np.asarray(visited).reshape(-1),
                                      np.asarray(ref_state.visited))

    @pytest.mark.parametrize("T", [3, 8])
    def test_coverage_loop_batched_bitexact(self, T):
        # steps_per_round on the ring: same T=1 oracle contract as the
        # engine loop, same trajectory across shard counts.
        from p2pnetwork_tpu.parallel import mesh as M, sharded

        g = G.watts_strogatz(512, 8, 0.3, seed=2, source_csr=True)
        proto = RandomWalks(n_walkers=64)
        ref_state, ref_out = engine.run_until_coverage(
            g, proto, jax.random.key(3), coverage_target=0.9,
            max_rounds=512,
        )
        mesh = M.ring_mesh(8)
        sg = sharded.shard_graph(g, mesh, source_csr=True)
        visited, out = sharded.walk_until_coverage(
            sg, mesh, proto, jax.random.key(3), coverage_target=0.9,
            max_rounds=512, steps_per_round=T,
        )
        assert out == ref_out
        np.testing.assert_array_equal(np.asarray(visited).reshape(-1),
                                      np.asarray(ref_state.visited))

    def test_churn_and_dynamic_links_parity(self):
        from p2pnetwork_tpu.parallel import mesh as M, sharded
        from p2pnetwork_tpu.sim import failures as F

        g = G.ring(256, source_csr=True)
        gc = topology.connect(
            topology.with_capacity(F.fail_nodes(g, [7, 100]),
                                   extra_edges=8),
            [10, 200], [180, 30],
        )
        mesh = M.ring_mesh(4)
        sg = sharded.shard_graph(g, mesh, source_csr=True)
        sg = sharded.connect(
            sharded.with_capacity(sharded.fail_nodes(sg, [7, 100]), 8),
            [10, 200], [180, 30],
        )
        proto = RandomWalks(n_walkers=16)
        ref_state, ref_stats = engine.run(gc, proto, jax.random.key(9), 60)
        (pos, _, visited), stats = sharded.walk(
            sg, mesh, proto, jax.random.key(9), 60, return_state=True)
        np.testing.assert_array_equal(np.asarray(pos),
                                      np.asarray(ref_state.pos))
        np.testing.assert_array_equal(np.asarray(visited).reshape(-1),
                                      np.asarray(ref_state.visited))
        np.testing.assert_array_equal(np.asarray(stats["messages"]),
                                      np.asarray(ref_stats["messages"]))

    def test_resume_roundtrip(self):
        from p2pnetwork_tpu.parallel import mesh as M, sharded

        g = G.watts_strogatz(256, 6, 0.2, seed=4, source_csr=True)
        proto = RandomWalks(n_walkers=32)
        mesh = M.ring_mesh(2)
        sg = sharded.shard_graph(g, mesh, source_csr=True)
        state, _ = sharded.walk(sg, mesh, proto, jax.random.key(1), 5,
                                return_state=True)
        state2, out = sharded.walk_until_coverage(
            sg, mesh, proto, jax.random.key(2), coverage_target=0.8,
            max_rounds=512, state0=state, return_state=True,
        )
        assert out["coverage"] >= 0.8
        # visited only grows across the resume.
        v1 = np.asarray(state[2]).reshape(-1)
        v2 = np.asarray(state2[2]).reshape(-1)
        assert v2[v1].all()

    def test_requires_csr(self):
        from p2pnetwork_tpu.parallel import mesh as M, sharded

        g = G.ring(128)
        mesh = M.ring_mesh(2)
        sg = sharded.shard_graph(g, mesh)
        with pytest.raises(ValueError, match="source_csr"):
            sharded.walk(sg, mesh, RandomWalks(n_walkers=4),
                         jax.random.key(0), 3)


class TestBatchedSteps:
    """steps_per_round=T batches T protocol steps per while-loop iteration
    (engine._stat_while) to amortize the per-iteration dispatch floor on
    rounds-bound runs. The contract is BIT-exactness vs T=1 — sub-steps
    re-check the predicate and freeze once it fails — so every T, even ones
    that do not divide the round count, must reproduce the oracle run."""

    @pytest.mark.parametrize("T", [2, 3, 7, 16])
    def test_walk_coverage_bitexact_vs_T1(self, T):
        g = G.watts_strogatz(512, 4, 0.2, seed=3, source_csr=True)
        proto = RandomWalks(n_walkers=8)
        key = jax.random.key(5)
        s1, o1 = engine.run_until_coverage(
            g, proto, key, coverage_target=0.95, max_rounds=512)
        sT, oT = engine.run_until_coverage(
            g, proto, key, coverage_target=0.95, max_rounds=512,
            steps_per_round=T)
        assert o1 == oT, f"summary diverged at T={T}: {o1} vs {oT}"
        assert (np.asarray(s1.pos) == np.asarray(sT.pos)).all()
        assert (np.asarray(s1.visited) == np.asarray(sT.visited)).all()

    @pytest.mark.parametrize("T", [2, 5])
    def test_flood_coverage_bitexact_vs_T1(self, T):
        from p2pnetwork_tpu.models.flood import Flood

        g = G.watts_strogatz(256, 4, 0.1, seed=0)
        key = jax.random.key(0)
        s1, o1 = engine.run_until_coverage(
            g, Flood(source=0), key, coverage_target=0.99, max_rounds=64)
        sT, oT = engine.run_until_coverage(
            g, Flood(source=0), key, coverage_target=0.99, max_rounds=64,
            steps_per_round=T)
        assert o1 == oT
        assert (np.asarray(s1.seen) == np.asarray(sT.seen)).all()

    def test_max_rounds_respected_within_superstep(self):
        # max_rounds that is not a multiple of T: the frozen sub-steps
        # must not let the round counter sail past the cap.
        g = G.watts_strogatz(256, 4, 0.1, seed=1, source_csr=True)
        proto = RandomWalks(n_walkers=2)  # cannot reach 99% in 5 rounds
        _, out = engine.run_until_coverage(
            g, proto, jax.random.key(0), coverage_target=0.99, max_rounds=5,
            steps_per_round=4)
        assert out["rounds"] == 5

    @pytest.mark.parametrize("T", [3])
    def test_converged_loop_bitexact_vs_T1(self, T):
        from p2pnetwork_tpu.models.pushsum import PushSum

        g = G.watts_strogatz(128, 4, 0.1, seed=2)
        key = jax.random.key(1)
        s1, o1 = engine.run_until_converged(
            g, PushSum(), key, stat="variance", threshold=1e-3, max_rounds=256)
        sT, oT = engine.run_until_converged(
            g, PushSum(), key, stat="variance", threshold=1e-3, max_rounds=256,
            steps_per_round=T)
        assert o1 == oT
        assert (np.asarray(s1.s) == np.asarray(sT.s)).all()

    def test_rejects_bad_T(self):
        g = G.watts_strogatz(64, 4, 0.1, seed=0)
        from p2pnetwork_tpu.models.flood import Flood

        with pytest.raises(ValueError, match="steps_per_round"):
            engine.run_until_coverage(g, Flood(source=0), jax.random.key(0),
                                      steps_per_round=0)

    @pytest.mark.parametrize("T", [4])
    def test_adaptive_flood_on_hub_graph_bitexact(self, T):
        # Batched super-steps compose with the adaptive wave machinery on
        # a degree-skewed graph (hub rows chunk into work items).
        from p2pnetwork_tpu.models.adaptive_flood import AdaptiveFlood

        g = G.barabasi_albert(2048, 4, seed=2, source_csr=True,
                              skew_table=True)
        key = jax.random.key(0)
        proto = AdaptiveFlood(source=0, method="auto", k=128)
        s1, o1 = engine.run_until_coverage(
            g, proto, key, coverage_target=0.99, max_rounds=64)
        sT, oT = engine.run_until_coverage(
            g, proto, key, coverage_target=0.99, max_rounds=64,
            steps_per_round=T)
        assert o1 == oT
        assert (np.asarray(s1.seen) == np.asarray(sT.seen)).all()

    def test_resume_path_bitexact(self):
        # run_until_coverage_from with batching: resuming a half-done
        # crawl must land exactly where the unbatched resume does.
        g = G.watts_strogatz(512, 4, 0.2, seed=5, source_csr=True)
        proto = RandomWalks(n_walkers=8)
        key = jax.random.key(9)
        mid, _ = engine.run(g, proto, key, 40)
        # donate=False on the first resume: ``mid`` is resumed twice.
        s1, o1 = engine.run_until_coverage_from(
            g, proto, mid, key, coverage_target=0.9, max_rounds=512,
            donate=False)
        sT, oT = engine.run_until_coverage_from(
            g, proto, mid, key, coverage_target=0.9, max_rounds=512,
            steps_per_round=8)
        assert o1 == oT
        assert (np.asarray(s1.visited) == np.asarray(sT.visited)).all()
