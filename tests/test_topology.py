"""Dynamic topology: runtime connects/joins must be visible to every
aggregation method immediately, with exact degree bookkeeping."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from p2pnetwork_tpu.models import Flood  # noqa: E402
from p2pnetwork_tpu.ops import segment  # noqa: E402
from p2pnetwork_tpu.sim import engine, topology  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def _brute_or(g, signal):
    sig = np.asarray(signal)
    out = np.zeros(g.n_nodes_padded, dtype=bool)
    emask = np.asarray(g.edge_mask)
    for a, b in zip(np.asarray(g.senders)[emask], np.asarray(g.receivers)[emask]):
        out[b] |= sig[a]
    if g.dyn_mask is not None:
        dm = np.asarray(g.dyn_mask)
        for a, b in zip(np.asarray(g.dyn_senders)[dm],
                        np.asarray(g.dyn_receivers)[dm]):
            out[b] |= sig[a]
    return out & np.asarray(g.node_mask)


class TestConnect:
    def test_new_edge_seen_by_all_methods(self):
        g = G.watts_strogatz(500, 4, 0.2, seed=0, blocked=True, hybrid=True)
        g = topology.with_capacity(g, extra_edges=16)
        g = topology.connect(g, [3], [441])
        sig = jnp.zeros(g.n_nodes_padded, dtype=bool).at[3].set(True)
        ref = _brute_or(g, sig)
        assert ref[441]  # sanity: the new link carries
        for method in ("segment", "gather", "pallas", "hybrid"):
            out = np.asarray(segment.propagate_or(g, sig, method))
            np.testing.assert_array_equal(out, ref, err_msg=method)

    def test_undirected_both_ways(self):
        g = topology.with_capacity(G.ring(200), extra_edges=8)
        g = topology.connect(g, [0], [100])
        sig = jnp.zeros(g.n_nodes_padded, dtype=bool).at[100].set(True)
        out = np.asarray(segment.propagate_or(g, sig, "segment"))
        assert out[0] and out[99] and out[101]

    def test_degrees_updated(self):
        g = topology.with_capacity(G.ring(200), extra_edges=8)
        g2 = topology.connect(g, [0], [100])
        assert int(np.asarray(g2.in_degree)[0]) == 3
        assert int(np.asarray(g2.out_degree)[100]) == 3
        g3 = topology.disconnect(g2, [0], [100])
        assert int(np.asarray(g3.in_degree)[0]) == 2
        assert int(np.asarray(g3.out_degree)[100]) == 2

    def test_capacity_exhaustion_raises(self):
        g = topology.with_capacity(G.ring(200), extra_edges=4)
        # 128-slot minimum allocation: fill it, then overflow
        s = np.arange(64, dtype=np.int32)
        g = topology.connect(g, s, (s + 7) % 200)  # 128 directed slots
        with pytest.raises(ValueError, match="dynamic edge region full"):
            topology.connect(g, [0], [9])

    def test_requires_capacity(self):
        with pytest.raises(ValueError, match="with_capacity"):
            topology.connect(G.ring(100), [0], [5])


class TestJoin:
    def test_join_bridges_into_flood(self):
        # 200 real nodes, padding rows beyond are spare peers.
        g = G.ring(200)
        assert g.n_nodes_padded >= 201
        g = topology.with_capacity(g, extra_edges=8)
        new_id = 200  # a padding row
        g2 = topology.join_node(g, new_id, [0, 100])
        state, _ = engine.run(g2, Flood(source=new_id), jax.random.key(0), 60)
        seen = np.asarray(state.seen)
        assert seen[new_id] and seen[:200].all()  # reaches the whole ring

    def test_flood_mid_run_topology_change(self):
        # Partitioned ring: flood stalls; a runtime connect bridges it.
        # Once stalled the frontier is empty — like the reference, holders
        # do not spontaneously re-send to new peers — so the resume models
        # re-announcement: frontier reset to the seen set.
        import dataclasses

        from p2pnetwork_tpu.sim import failures

        g = topology.with_capacity(G.ring(100), extra_edges=8)
        g_cut = failures.fail_nodes(g, [25, 75])
        proto = Flood(source=0)
        state, _ = engine.run(g_cut, proto, jax.random.key(0), 60)
        assert not np.asarray(state.seen)[26:75].any()
        g_bridged = topology.connect(g_cut, [10], [50])
        reannounce = dataclasses.replace(state, frontier=state.seen)
        state2, _ = engine.run_from(g_bridged, proto, reannounce,
                                    jax.random.key(0), 60)
        seen = np.asarray(state2.seen)[:100]
        alive = np.asarray(g_bridged.node_mask)[:100]
        assert (seen | ~alive).all()  # every live node reached

    def test_messages_count_dynamic_edges(self):
        g = topology.with_capacity(G.ring(200), extra_edges=8)
        g = topology.connect(g, [0], [100])
        frontier = jnp.zeros(g.n_nodes_padded, dtype=bool).at[0].set(True)
        msgs = int(segment.frontier_messages(g, frontier))
        assert msgs == 3  # two ring edges + the new link


def test_reconnect_after_disconnect_does_not_clobber():
    # Regression: slot allocation by used-count overwrote live edges that
    # sat past holes left by disconnect().
    g = topology.with_capacity(G.ring(200), extra_edges=8)
    g = topology.connect(g, [0], [100])
    g = topology.connect(g, [5], [150])
    g = topology.disconnect(g, [0], [100])
    g = topology.connect(g, [7], [170])
    sig = jnp.zeros(g.n_nodes_padded, dtype=bool).at[5].set(True)
    out = np.asarray(segment.propagate_or(g, sig, "segment"))
    assert out[150]  # 5<->150 must survive the reconnect
    sig = jnp.zeros(g.n_nodes_padded, dtype=bool).at[7].set(True)
    assert np.asarray(segment.propagate_or(g, sig, "segment"))[170]
    sig = jnp.zeros(g.n_nodes_padded, dtype=bool).at[0].set(True)
    assert not np.asarray(segment.propagate_or(g, sig, "segment"))[100]


def test_node_failure_kills_dynamic_edges():
    # Regression: a crashed peer kept transmitting over its dynamic links.
    from p2pnetwork_tpu.sim import failures

    g = topology.with_capacity(G.ring(200), extra_edges=8)
    g = topology.connect(g, [0], [100])
    gf = failures.fail_nodes(g, [0])
    sig = jnp.zeros(g.n_nodes_padded, dtype=bool).at[0].set(True)
    out = np.asarray(segment.propagate_or(gf, sig, "segment"))
    assert not out.any()  # dead sender: neither ring nor dynamic edges fire
    assert int(np.asarray(gf.in_degree)[100]) == 2  # dyn edge degree gone
    assert int(np.asarray(gf.out_degree)[100]) == 2


def test_grow_capacity_preserves_links():
    # Regression: re-running with_capacity zeroed the dynamic region.
    g = topology.with_capacity(G.ring(200), extra_edges=4)
    g = topology.connect(g, [0], [100])
    g = topology.with_capacity(g, extra_edges=256)
    assert g.dyn_mask.shape[0] >= 256
    sig = jnp.zeros(g.n_nodes_padded, dtype=bool).at[0].set(True)
    assert np.asarray(segment.propagate_or(g, sig, "segment"))[100]


def test_connect_out_of_range_raises():
    g = topology.with_capacity(G.ring(200), extra_edges=8)
    with pytest.raises(ValueError, match="node id out of range"):
        topology.connect(g, [0], [5000])
    with pytest.raises(ValueError, match="node id out of range"):
        topology.join_node(g, 5000, [0])


def test_with_capacity_extra_nodes():
    g = G.ring(128)  # n_pad == 128, no spare rows
    assert g.n_nodes_padded == 128
    g2 = topology.with_capacity(g, extra_nodes=5, extra_edges=8)
    assert g2.n_nodes_padded == 256
    assert int(np.asarray(g2.node_mask).sum()) == 128
    g3 = topology.join_node(g2, 128, [0])
    assert int(np.asarray(g3.node_mask).sum()) == 129
    sig = jnp.zeros(g3.n_nodes_padded, dtype=bool).at[128].set(True)
    out = np.asarray(segment.propagate_or(g3, sig, "segment"))
    assert out[0]

def test_gossip_after_connect_samples_only_stored_neighbors():
    # Regression: a runtime connect bumps in_degree past the stored table
    # row; the old prefix-window sampling then drew padding slots (node id
    # 0 garbage). Partner draws must stay within the valid table entries.
    import dataclasses

    import jax.numpy as jnp

    from p2pnetwork_tpu.models import Gossip

    # Directed: node 2's only stored in-neighbor is node 1.
    g = G.from_edges([1], [2], 8)
    g = topology.with_capacity(g, extra_edges=8)
    g = topology.connect(g, [3], [2], undirected=False)  # dynamic in-edge
    assert int(np.asarray(g.in_degree)[2]) == 2  # table row still width 1
    proto = Gossip(alpha=0.5)
    values = jnp.zeros(g.n_nodes_padded).at[1].set(10.0).at[3].set(99.0)
    from p2pnetwork_tpu.models.gossip import GossipState

    for seed in range(5):
        st, _ = proto.step(g, GossipState(values=values), jax.random.key(seed))
        # Node 2 pulls from its stored neighbor (1), never the dynamic
        # link's endpoint (3) and never padding garbage (node 0).
        assert float(np.asarray(st.values)[2]) == 5.0  # 0.5*0 + 0.5*10


def test_edge_exists_probe_matches_brute():
    # The searchsorted window probe must agree with the O(B*E) broadcast
    # compare it replaced, on a degree-skewed graph (BA), for a batch mixing
    # existing static edges, existing dynamic edges, dead-edge pairs, and
    # absent pairs — including the padded last node id, whose receiver run
    # includes the COO padding tail.
    import dataclasses

    from p2pnetwork_tpu.sim import failures

    g = topology.with_capacity(G.barabasi_albert(300, 3, seed=1), extra_edges=8)
    g = topology.connect(g, [7], [250])
    g = failures.fail_nodes(g, [17])
    emask = np.asarray(g.edge_mask)
    s_static = np.asarray(g.senders)[emask][:10]
    r_static = np.asarray(g.receivers)[emask][:10]
    dead = ~np.asarray(g.edge_mask) & (np.asarray(g.senders) == 17)
    qs = np.concatenate([
        s_static, [7, 250], np.asarray(g.senders)[dead][:2],
        [0, 5, g.n_nodes_padded - 1],
    ]).astype(np.int32)
    qr = np.concatenate([
        r_static, [250, 7], np.asarray(g.receivers)[dead][:2],
        [299, 299, g.n_nodes_padded - 1],
    ]).astype(np.int32)
    fast = np.asarray(topology._edge_exists(g, jnp.asarray(qs), jnp.asarray(qr)))
    brute = np.asarray(
        topology._edge_exists(
            dataclasses.replace(g, max_in_span=0), jnp.asarray(qs), jnp.asarray(qr)
        )
    )
    np.testing.assert_array_equal(fast, brute)
    assert fast[:12].all() and not fast[12:].any()


def test_connect_batch_no_capacity_check_jittable():
    # The sustained-churn path: check_capacity=False must trace cleanly
    # (no host sync) and produce the same graph as the checked path.
    g0 = topology.with_capacity(G.ring(200), extra_edges=16)

    @jax.jit
    def step(g, s, r):
        return topology.connect(g, s, r, check_capacity=False)

    s = jnp.asarray([0, 3], jnp.int32)
    r = jnp.asarray([100, 103], jnp.int32)
    g_jit = step(g0, s, r)
    g_ref = topology.connect(g0, s, r)
    np.testing.assert_array_equal(np.asarray(g_jit.dyn_mask), np.asarray(g_ref.dyn_mask))
    np.testing.assert_array_equal(np.asarray(g_jit.dyn_senders), np.asarray(g_ref.dyn_senders))
    np.testing.assert_array_equal(np.asarray(g_jit.in_degree), np.asarray(g_ref.in_degree))


def test_connect_duplicates_at_near_capacity_do_not_corrupt():
    # Regression (ADVICE r1, high): with free slots scarce, a batch mixing
    # already-existing pairs with new ones padded the free-slot list with
    # index 0 and scattered a new edge over whatever lived in slot 0.
    g = topology.with_capacity(G.ring(200), extra_edges=4)  # 128 slots
    g = topology.connect(g, [0], [7])  # slots 0,1: the victim edge
    s = np.arange(1, 63, dtype=np.int32)  # 62 pairs -> 124 slots: 2 free
    g = topology.connect(g, s, s + 80)
    assert int(np.asarray(g.dyn_mask).sum()) == 126
    # Batch: one duplicate pair (0<->7) + one new pair (190<->20).
    g = topology.connect(g, [0, 190], [7, 20])
    # The duplicate must be a no-op; the new pair must land; edge 0->7
    # must survive.
    sig = jnp.zeros(g.n_nodes_padded, dtype=bool).at[0].set(True)
    assert np.asarray(segment.propagate_or(g, sig, "segment"))[7]
    sig = jnp.zeros(g.n_nodes_padded, dtype=bool).at[190].set(True)
    assert np.asarray(segment.propagate_or(g, sig, "segment"))[20]
    sig = jnp.zeros(g.n_nodes_padded, dtype=bool).at[20].set(True)
    assert np.asarray(segment.propagate_or(g, sig, "segment"))[190]
    assert int(np.asarray(g.dyn_mask).sum()) == 128
    # Degrees stay in sync with the edges (the bug left in_degree counting
    # a destroyed edge).
    # 2 ring + 0<->7 + 87<->7 (from the bulk batch) = 4; the bug left a
    # fifth phantom count for the destroyed slot-0 edge.
    assert int(np.asarray(g.in_degree)[7]) == 4
    assert int(np.asarray(g.out_degree)[7]) == 4


class TestConsolidate:
    def test_flood_parity_after_churn(self):
        from p2pnetwork_tpu.models import Flood
        from p2pnetwork_tpu.sim import engine, failures

        g = G.watts_strogatz(512, 6, 0.2, seed=0)
        g = failures.fail_nodes(topology.with_capacity(g, extra_edges=16),
                                [7, 300])
        g = topology.connect(g, [2, 5], [400, 450])
        c = topology.consolidate(g)
        # Runtime links became static edges; nothing rides the dyn region.
        assert c.dyn_senders is None
        assert c.n_edges == int(np.asarray(g.edge_mask).sum()
                                + np.asarray(g.dyn_mask).sum())
        key = jax.random.key(0)
        st_g, stats_g = engine.run(g, Flood(source=0), key, 8)
        st_c, stats_c = engine.run(c, Flood(source=0), key, 8)
        np.testing.assert_array_equal(
            np.asarray(st_c.seen)[: g.n_nodes],
            np.asarray(st_g.seen)[: g.n_nodes],
        )
        np.testing.assert_array_equal(np.asarray(stats_c["messages"]),
                                      np.asarray(stats_g["messages"]))
        assert not np.asarray(st_c.seen)[7]  # failed stays failed

    def test_joined_spare_survives_and_gossip_samples_new_links(self):
        from p2pnetwork_tpu.sim import failures

        g = topology.with_capacity(G.ring(250), extra_edges=16,
                                   extra_nodes=10)
        g = topology.join_node(g, 300, [5])
        c = topology.consolidate(g, extra_edges=8)
        alive = np.asarray(c.node_mask)
        assert alive[300] and alive[:250].all() and not alive[250:300].any()
        # The runtime link entered the neighbor table (partner sampling).
        row = np.asarray(c.neighbors[300])
        msk = np.asarray(c.neighbor_mask[300])
        assert 5 in set(row[msk])
        assert c.dyn_senders is not None  # capacity re-reserved

    def test_rebuild_layouts_on_request(self):
        g = topology.connect(
            topology.with_capacity(G.watts_strogatz(256, 4, 0.2, seed=1),
                                   extra_edges=8),
            [0], [99],
        )
        c = topology.consolidate(g, hybrid=True, source_csr=True)
        assert c.hybrid is not None and c.src_eid is not None
        from p2pnetwork_tpu.models import AdaptiveFlood, Flood
        from p2pnetwork_tpu.sim import engine

        key = jax.random.key(0)
        st_a, _ = engine.run(c, AdaptiveFlood(source=0, k=32), key, 6)
        st_f, _ = engine.run(g, Flood(source=0), key, 6)
        np.testing.assert_array_equal(
            np.asarray(st_a.seen)[:256], np.asarray(st_f.seen)[:256]
        )


class TestConnectLiveness:
    def test_connect_to_dead_endpoint_is_rejected(self):
        # Reference parity: connect_with_node to a crashed peer fails
        # [ref: node.py:173-176]. Without this, fail-then-connect vs
        # connect-then-fail left different live link sets.
        from p2pnetwork_tpu.sim import failures

        g = failures.fail_nodes(
            topology.with_capacity(G.ring(256), extra_edges=8), [77]
        )
        before = int(np.asarray(g.out_degree).sum())
        g2 = topology.connect(g, [3], [77])
        assert int(np.asarray(g2.dyn_mask).sum()) == 0
        assert int(np.asarray(g2.out_degree).sum()) == before

    def test_order_independence_fail_vs_connect(self):
        from p2pnetwork_tpu.sim import failures

        base = topology.with_capacity(G.ring(256), extra_edges=8)
        a = topology.connect(failures.fail_nodes(base, [9]), [3], [9])
        b = failures.fail_nodes(topology.connect(base, [3], [9]), [9])
        np.testing.assert_array_equal(np.asarray(a.out_degree),
                                      np.asarray(b.out_degree))
        assert int(np.asarray(a.dyn_mask).sum()) == 0

    def test_sharded_connect_liveness_parity(self):
        from p2pnetwork_tpu.parallel import mesh as M, sharded

        g = G.ring(512)
        mesh = M.ring_mesh(4)
        sg = sharded.with_capacity(
            sharded.fail_nodes(sharded.shard_graph(g, mesh), [100]), 8
        )
        sg2 = sharded.connect(sg, [3], [100])
        assert int(np.asarray(sg2.dyn_mask).sum()) == 0
        np.testing.assert_array_equal(np.asarray(sg2.out_degree),
                                      np.asarray(sg.out_degree))

    def test_consolidate_extra_nodes_with_layouts(self):
        # Growth + kernel layouts together: layouts attach after growth.
        g = topology.connect(
            topology.with_capacity(G.ring(250), extra_edges=8), [0], [99]
        )
        c = topology.consolidate(g, extra_nodes=10, extra_edges=8,
                                 hybrid=True, source_csr=True)
        assert c.hybrid is not None and c.src_eid is not None
        assert c.src_offsets.shape[0] == c.n_nodes_padded + 1
        assert c.n_nodes_padded > 256  # grown padding present


class TestConsolidateNeighborTable:
    """Neighbor-table settings carry over like kernel layouts (ADVICE r3):
    the documented 10M-node path builds with build_neighbor_table=False and
    consolidation must not silently rebuild an O(N*max_in_degree) table."""

    def test_no_table_stays_no_table(self):
        g = G.watts_strogatz(256, 4, 0.2, seed=0,
                             build_neighbor_table=False, source_csr=True)
        g = topology.connect(topology.with_capacity(g, extra_edges=4),
                             [1], [200])
        c = topology.consolidate(g)
        assert g.neighbors is None
        assert c.neighbors is None
        assert c.src_eid is not None  # layouts still carried

    def test_capped_table_keeps_its_cap(self):
        g = G.watts_strogatz(256, 6, 0.2, seed=1, max_degree=3)
        assert g.neighbors.shape[1] == 3 and not g.neighbors_complete
        c = topology.consolidate(g)
        assert c.neighbors.shape[1] <= 3

    def test_uncapped_table_may_widen(self):
        # An uncapped table's width is just the old true max — the merged
        # edge list may exceed it, and must be allowed to.
        g = G.ring(64)  # every out-degree is 1... ring() is k=1 each way
        w0 = g.neighbors.shape[1]
        g = topology.with_capacity(g, extra_edges=8)
        g = topology.connect(g, [5, 7, 9], [20, 20, 20])
        c = topology.consolidate(g)
        assert c.neighbors_complete
        assert c.neighbors.shape[1] >= w0

    def test_explicit_kwargs_still_win(self):
        g = G.watts_strogatz(128, 4, 0.2, seed=2,
                             build_neighbor_table=False)
        c = topology.consolidate(g, build_neighbor_table=True)
        assert c.neighbors is not None
