"""Gossip + SIR protocol tests: exact determinism, physical invariants."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from p2pnetwork_tpu.models import SIR, Gossip  # noqa: E402
from p2pnetwork_tpu.models.sir import INFECTED, RECOVERED, SUSCEPTIBLE  # noqa: E402
from p2pnetwork_tpu.sim import engine  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


class TestGossip:
    def test_variance_decays_to_consensus(self):
        # BASELINE configs[2] shape: Barabási–Albert + push-pull averaging.
        g = G.barabasi_albert(500, 4, seed=0)
        _, stats = engine.run(g, Gossip(), jax.random.key(0), 50)
        var = np.asarray(stats["variance"])
        assert var[-1] < 0.05 * var[0]

    def test_values_stay_in_initial_hull(self):
        g = G.watts_strogatz(256, 4, 0.1, seed=1)
        proto = Gossip()
        key = jax.random.key(2)
        state0 = proto.init(g, key)
        v0 = np.asarray(state0.values)[: g.n_nodes]
        state, _ = engine.run(g, proto, key, 30)
        v = np.asarray(state.values)[: g.n_nodes]
        assert v.min() >= v0.min() - 1e-5 and v.max() <= v0.max() + 1e-5

    def test_deterministic(self):
        g = G.ring(128)
        key = jax.random.key(3)
        s1, _ = engine.run(g, Gossip(), key, 10)
        s2, _ = engine.run(g, Gossip(), key, 10)
        np.testing.assert_array_equal(np.asarray(s1.values), np.asarray(s2.values))

    def test_isolated_nodes_unchanged(self):
        # Nodes 3/4 are disconnected from everything.
        g = G.from_edges([0, 1], [1, 0], 5)
        proto = Gossip()
        key = jax.random.key(4)
        state0 = proto.init(g, key)
        state, _ = engine.run(g, proto, key, 5)
        v0 = np.asarray(state0.values)
        v = np.asarray(state.values)
        np.testing.assert_array_equal(v[2:5], v0[2:5])


class TestSIR:
    def test_conservation_and_monotonicity(self):
        g = G.watts_strogatz(1000, 6, 0.05, seed=5)
        _, stats = engine.run(g, SIR(beta=0.4, gamma=0.2), jax.random.key(1), 40)
        s = np.asarray(stats["s_frac"])
        i = np.asarray(stats["i_frac"])
        r = np.asarray(stats["r_frac"])
        np.testing.assert_allclose(s + i + r, 1.0, atol=1e-5)
        assert (np.diff(s) <= 1e-6).all()  # susceptibles never increase
        assert (np.diff(r) >= -1e-6).all()  # recovered never decrease

    def test_epidemic_spreads_from_source(self):
        g = G.watts_strogatz(2000, 8, 0.1, seed=6)
        _, stats = engine.run(g, SIR(beta=0.6, gamma=0.05), jax.random.key(2), 30)
        assert float(np.asarray(stats["coverage"])[-1]) > 0.5

    def test_no_transmission_when_beta_zero(self):
        g = G.complete(32)
        state, stats = engine.run(g, SIR(beta=0.0, gamma=0.5), jax.random.key(3), 10)
        status = np.asarray(state.status)[:32]
        # Only the source ever left S, and with gamma it recovered.
        assert (status == SUSCEPTIBLE).sum() == 31
        assert status[0] in (INFECTED, RECOVERED)

    def test_statuses_valid_and_deterministic(self):
        g = G.erdos_renyi(300, 0.03, seed=7)
        key = jax.random.key(4)
        s1, _ = engine.run(g, SIR(), key, 15)
        s2, _ = engine.run(g, SIR(), key, 15)
        np.testing.assert_array_equal(np.asarray(s1.status), np.asarray(s2.status))
        assert set(np.unique(np.asarray(s1.status))) <= {0, 1, 2}

    def test_run_until_coverage_works_for_sir(self):
        g = G.watts_strogatz(1000, 8, 0.1, seed=8)
        _, out = engine.run_until_coverage(
            g, SIR(beta=0.9, gamma=0.0), jax.random.key(5),
            coverage_target=0.9, max_rounds=100,
        )
        assert float(out["coverage"]) >= 0.9
