"""Dijkstra-Scholten termination detection over real loopback sockets.

The oracle is the algorithm's claim itself: when the root's detection
fires, every work message anywhere must already have been processed —
checked with a TTL-ripple computation whose total work count is known in
advance, so premature detection (firing while ripples are still in
flight) shows up as a processed-count shortfall at detection time.
"""

from p2pnetwork_tpu import TerminationNode
from tests.helpers import stop_all, wait_until

HOST = "127.0.0.1"


class RippleNode(TerminationNode):
    """Work = {"ttl": k}: process it, and while ttl > 0 forward a
    decremented ripple to every peer. On a triangle, a root ripple of
    TTL t spawns exactly 2^(t+1) - 1 work messages total."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.processed = 0

    def work_message(self, node, comp_id, data):
        self.processed += 1
        if data["ttl"] > 0:
            for peer in self.all_nodes:
                self.send_work(peer, {"ttl": data["ttl"] - 1})


def _triangle(cls=RippleNode):
    a = cls(HOST, 0, id="A")
    b = cls(HOST, 0, id="B")
    c = cls(HOST, 0, id="C")
    nodes = [a, b, c]
    for n in nodes:
        n.start()
    assert a.connect_with_node(HOST, b.port)
    assert b.connect_with_node(HOST, c.port)
    assert c.connect_with_node(HOST, a.port)
    assert wait_until(lambda: all(len(n.all_nodes) == 2 for n in nodes))
    return nodes


class TestTermination:
    def test_no_work_terminates_immediately(self):
        nodes = _triangle()
        try:
            # Root handler sends nothing (ttl 0): tree = root alone.
            cid = nodes[0].start_diffusing({"ttl": 0})
            assert nodes[0].wait_terminated(cid, timeout=5.0)
            assert nodes[0].processed == 1
        finally:
            stop_all(nodes)

    def test_detection_only_after_all_work_processed(self):
        nodes = _triangle()
        a = nodes[0]
        try:
            ttl = 6
            expected = 2 ** (ttl + 1) - 1  # binary ripple tree on K3
            done = []
            orig = a.computation_terminated.__func__

            def on_done(comp_id):
                # Record the GLOBAL processed count at the instant of
                # detection — the algorithm's whole claim.
                done.append(sum(n.processed for n in nodes))
                orig(a, comp_id)

            a.computation_terminated = on_done
            cid = a.start_diffusing({"ttl": ttl})
            assert a.wait_terminated(cid, timeout=30.0), "never terminated"
            assert done[0] == expected, (
                f"terminated after {done[0]}/{expected} messages processed")
            assert all(n.deficit(cid) == 0 for n in nodes)
        finally:
            stop_all(nodes)

    def test_nonroot_detaches_and_reengages(self):
        nodes = _triangle()
        a, b, c = nodes
        try:
            cid = a.start_diffusing({"ttl": 2})
            assert a.wait_terminated(cid, timeout=15.0)
            # After global termination everyone detached.
            assert all(n.deficit(cid) == 0 for n in nodes)
            # A fresh computation under a new id runs cleanly on the same
            # overlay (nodes re-engage from scratch).
            cid2 = a.start_diffusing({"ttl": 2})
            assert a.wait_terminated(cid2, timeout=15.0)
        finally:
            stop_all(nodes)

    def test_concurrent_computations_tracked_independently(self):
        nodes = _triangle()
        a, b, c = nodes
        try:
            cid_a = a.start_diffusing({"ttl": 4})
            cid_b = b.start_diffusing({"ttl": 4})
            assert cid_a != cid_b
            assert a.wait_terminated(cid_a, timeout=20.0)
            assert b.wait_terminated(cid_b, timeout=20.0)
        finally:
            stop_all(nodes)

    def test_duplicate_comp_id_rejected(self):
        nodes = _triangle()
        a = nodes[0]
        try:
            # Reusing a running id raises EAGERLY on the caller thread
            # (a loop-side raise would vanish into asyncio's handler and
            # the caller would mistake the old run's completion for the
            # new one's); the first computation completes untouched.
            a.start_diffusing({"ttl": 8}, comp_id="fixed")
            import pytest as _pytest
            with _pytest.raises(ValueError):
                a.start_diffusing({"ttl": 1}, comp_id="fixed")
            assert a.wait_terminated("fixed", timeout=30.0)
            # Finished ids stay rejected until explicitly forgotten.
            with _pytest.raises(ValueError):
                a.start_diffusing({"ttl": 1}, comp_id="fixed")
            a.forget_computation("fixed")
            a.start_diffusing({"ttl": 1}, comp_id="fixed")
            assert a.wait_terminated("fixed", timeout=15.0)
        finally:
            stop_all(nodes)

    def test_plain_messages_bypass(self):
        nodes = _triangle()
        a, b = nodes[0], nodes[1]
        try:
            a.send_to_nodes("just a string")
            assert wait_until(
                lambda: b.message_count_recv >= 1
                and nodes[2].message_count_recv >= 1)
            # No computation state was created by plain traffic.
            assert not a._comps and not b._comps
        finally:
            stop_all(nodes)
