"""Triangle counting / clustering vs numpy set-intersection oracles."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from p2pnetwork_tpu.models import (  # noqa: E402
    count_triangles,
    local_clustering,
    transitivity,
    transitivity_sample,
    triangles_per_node,
)
from p2pnetwork_tpu.sim import failures, topology  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def _adj_sets(g):
    adj = [set() for _ in range(g.n_nodes_padded)]
    s = np.asarray(g.senders)
    r = np.asarray(g.receivers)
    em = np.asarray(g.edge_mask)
    for a, b in zip(s[em], r[em]):
        adj[b].add(int(a))  # in-neighbors; symmetric graphs: == neighbors
        adj[a].add(int(b))
    return adj


def _oracle_tri_per_node(g):
    adj = _adj_sets(g)
    tri = np.zeros(g.n_nodes_padded, dtype=np.int64)
    for v, nv in enumerate(adj):
        t = 0
        for u in nv:
            t += len(nv & adj[u])
        tri[v] = t // 2
    return tri


def _oracle_total(g):
    return int(_oracle_tri_per_node(g).sum()) // 3


class TestExactCounts:
    def test_single_triangle(self):
        g = G.from_edges(*G._undirect(np.array([0, 1, 2]), np.array([1, 2, 0])), 3)
        assert count_triangles(g) == 1
        np.testing.assert_array_equal(
            np.asarray(triangles_per_node(g))[:3], [1, 1, 1])

    def test_ring_has_none(self):
        assert count_triangles(G.ring(64)) == 0

    def test_complete_graph(self):
        g = G.complete(8)
        assert count_triangles(g) == 8 * 7 * 6 // 6
        np.testing.assert_allclose(np.asarray(local_clustering(g))[:8], 1.0)
        assert transitivity(g) == pytest.approx(1.0)

    @pytest.mark.parametrize("build", [
        lambda: G.watts_strogatz(256, 6, 0.2, seed=1),
        lambda: G.erdos_renyi(200, 0.05, seed=2),
        lambda: G.barabasi_albert(200, 3, seed=3),
    ])
    def test_random_graphs_match_oracle(self, build):
        g = build()
        assert count_triangles(g) == _oracle_total(g)
        np.testing.assert_array_equal(
            np.asarray(triangles_per_node(g), dtype=np.int64),
            _oracle_tri_per_node(g))

    def test_small_edge_block_same_answer(self):
        g = G.watts_strogatz(128, 6, 0.2, seed=0)
        assert count_triangles(g, edge_block=7) == count_triangles(g)

    def test_failures_respected(self):
        g = G.watts_strogatz(128, 6, 0.1, seed=4)
        gf = failures.fail_nodes(g, [3, 17, 60])
        assert count_triangles(gf) == _oracle_total(gf)

    def test_local_clustering_matches_oracle(self):
        g = G.erdos_renyi(150, 0.06, seed=5)
        tri = _oracle_tri_per_node(g)
        d = np.asarray(g.in_degree, dtype=np.int64)
        want = np.where(d >= 2, 2.0 * tri / np.maximum(d * (d - 1), 1), 0.0)
        np.testing.assert_allclose(np.asarray(local_clustering(g)), want,
                                   rtol=1e-6)

    def test_transitivity_matches_formula(self):
        g = G.barabasi_albert(150, 3, seed=6)
        d = np.asarray(g.in_degree, dtype=np.int64)
        wedges = int((d * (d - 1)).sum()) // 2
        assert transitivity(g) == pytest.approx(
            3.0 * _oracle_total(g) / wedges)


class TestGuards:
    def test_dynamic_region_rejected(self):
        g = topology.with_capacity(G.ring(16), extra_edges=4)
        with pytest.raises(ValueError, match="consolidate"):
            count_triangles(g)
        with pytest.raises(ValueError, match="consolidate"):
            transitivity_sample(g, jax.random.key(0))

    def test_capped_table_rejected(self):
        g = G.watts_strogatz(64, 6, 0.1, seed=0, max_degree=2)
        with pytest.raises(ValueError, match="capped"):
            count_triangles(g)

    def test_sampler_needs_source_csr(self):
        g = G.ring(16)
        with pytest.raises(ValueError, match="source_csr"):
            transitivity_sample(g, jax.random.key(0))


class TestSampler:
    def test_complete_graph_closes_every_wedge(self):
        g = G.complete(12, source_csr=True)
        assert transitivity_sample(g, jax.random.key(0), 2048) == 1.0

    def test_ring_closes_none(self):
        g = G.ring(64, source_csr=True)
        assert transitivity_sample(g, jax.random.key(1), 2048) == 0.0

    def test_estimate_tracks_exact(self):
        g = G.barabasi_albert(300, 4, seed=7, source_csr=True)
        exact = transitivity(g)
        est = transitivity_sample(g, jax.random.key(2), 1 << 16)
        assert abs(est - exact) < 0.03
