"""graftdur: the serving plane's durability contract.

Under test (serve/journal.py, serve/standby.py, chaos/crashstorm.py,
plus the SimService durability plumbing): every ACKNOWLEDGED
admission-plane intent survives any SIGKILL — the write-ahead journal
closes the sub-boundary window the checkpoint pair left open — with
the SAME ticket ids and bit-identical per-ticket results; a torn tail
costs exactly the one record that was never acknowledged; a journal
append failure degrades LOUDLY (typed DurabilityLost 503s), never into
silently un-journaled work; and hot-standby promotion fences the trail
so a zombie primary's late publish dies as FencedEpoch. The slow tests
run the acceptance row: the subprocess crash-storm campaign on a
100k-node graph (≥5 seeded SIGKILLs, zero acked-ticket loss,
bit-identity incl. seen hashes) and the fsync=tick overhead ratchet.
"""

import json
import os
import struct
import urllib.error
import urllib.request

import pytest

import jax  # noqa: F401  — device runtime required by the serve plane

from p2pnetwork_tpu import telemetry
from p2pnetwork_tpu.chaos import crashstorm
from p2pnetwork_tpu.serve import (
    DurabilityLost, FencedEpoch, Journal, Rejected, SimService, Standby,
    TrafficPattern, drive, generate)
from p2pnetwork_tpu.serve.journal import clear_segments, read_records
from p2pnetwork_tpu.serve.service import _SIDECAR
from p2pnetwork_tpu.sim import graph as G
from p2pnetwork_tpu.supervise.store import atomic_write_json
from p2pnetwork_tpu.telemetry.httpd import MetricsServer
from p2pnetwork_tpu.telemetry.slo import serve_objectives

pytestmark = pytest.mark.dur


@pytest.fixture(scope="module")
def ws300():
    return G.watts_strogatz(300, 6, 0.2, seed=3, source_csr=True)


def make_service(g, **kw):
    kw.setdefault("capacity", 32)
    kw.setdefault("queue_depth", 64)
    kw.setdefault("chunk_rounds", 4)
    kw.setdefault("seed", 0)
    kw.setdefault("record_seen_hash", True)
    kw.setdefault("registry", telemetry.Registry())
    return SimService(g, **kw)


class _Kill(Exception):
    """In-process stand-in for SIGKILL: raised out of a crash seam,
    caught by the test, the service object abandoned un-closed."""


# ------------------------------------------------------- journal unit


class TestJournal:
    def test_append_read_roundtrip(self, tmp_path):
        d = str(tmp_path)
        j = Journal(d, fsync="off")
        assert j.append("submit", ticket="t0", source=3, tick=0) == 1
        assert j.append("shed", reason="queue_full", tick=0) == 2
        assert j.append("grow", n=8, tick=1) == 3
        assert j.last_seq == 3
        j.close()
        records, corrupt = read_records(d)
        assert corrupt == 0
        assert [r["kind"] for r in records] == ["submit", "shed", "grow"]
        assert [r["seq"] for r in records] == [1, 2, 3]
        assert records[0]["ticket"] == "t0"
        assert records[2]["n"] == 8

    def test_reopen_recovers_and_continues_in_fresh_segment(
            self, tmp_path):
        d = str(tmp_path)
        j = Journal(d, fsync="off")
        j.append("submit", ticket="t0")
        j.close()
        j2 = Journal(d, fsync="off")
        assert [r["seq"] for r in j2.records()] == [1]
        assert j2.append("submit", ticket="t1") == 2  # seqs continue
        j2.close()
        # Two segment files: the first life's and the second's — a
        # reopened journal NEVER appends to a possibly-torn tail.
        segs = [n for n in os.listdir(d) if n.endswith(".wal")]
        assert len(segs) == 2
        records, corrupt = read_records(d)
        assert corrupt == 0 and [r["seq"] for r in records] == [1, 2]

    def test_rotate_compact_bounds_segments(self, tmp_path):
        d = str(tmp_path)
        j = Journal(d, fsync="off")
        for i in range(3):
            j.append("submit", ticket=f"t{i}")
            j.rotate()
        assert j.stats()["segments"] == 3
        j.compact(2)  # covers seqs 1..2 → two segments reclaimed
        assert j.stats()["segments"] == 1
        records, _ = read_records(d)
        assert [r["seq"] for r in records] == [3]
        j.close()

    def test_failed_journal_refuses_further_appends(self, tmp_path):
        j = Journal(str(tmp_path), fsync="off")

        def hook(event, seq):
            if event == "append_begin":
                raise OSError(28, "No space left on device (injected)")
        j.fault_hook = hook
        with pytest.raises(OSError):
            j.append("submit", ticket="t0")
        assert j.failed is not None
        j.fault_hook = None
        with pytest.raises(OSError, match="failed previously"):
            j.append("submit", ticket="t1")
        assert j.stats()["failed"]

    def test_closed_journal_refuses_appends(self, tmp_path):
        j = Journal(str(tmp_path), fsync="off")
        j.append("submit", ticket="t0")
        j.close()
        with pytest.raises(OSError, match="closed"):
            j.append("submit", ticket="t1")

    def test_fsync_policy_validated(self, tmp_path):
        with pytest.raises(ValueError, match="fsync policy"):
            Journal(str(tmp_path), fsync="sometimes")

    def test_record_policy_fsyncs_every_append(self, tmp_path):
        j = Journal(str(tmp_path), fsync="record")
        j.append("submit", ticket="t0")
        j.append("submit", ticket="t1")
        assert j.stats()["fsyncs"] == 2
        j.close()

    def test_tick_policy_fsyncs_at_barrier_only(self, tmp_path):
        j = Journal(str(tmp_path), fsync="tick")
        j.append("submit", ticket="t0")
        j.append("submit", ticket="t1")
        assert j.stats()["fsyncs"] == 0
        j.tick_barrier()
        assert j.stats()["fsyncs"] == 1
        j.tick_barrier()  # nothing new appended: no extra sync
        assert j.stats()["fsyncs"] == 1
        j.close()

    def test_clear_segments(self, tmp_path):
        d = str(tmp_path)
        j = Journal(d, fsync="off")
        j.append("submit", ticket="t0")
        j.close()
        clear_segments(d)
        assert read_records(d) == ([], 0)


# --------------------------------------------- torn-write fuzz (satellite)


class TestTornTail:
    def _journal_blob(self, d):
        j = Journal(d, fsync="off")
        for i in range(5):
            j.append("submit", ticket=f"t{i:08d}", source=i, tick=i)
        j.close()
        segs = [n for n in os.listdir(d) if n.endswith(".wal")]
        assert len(segs) == 1
        path = os.path.join(d, segs[0])
        with open(path, "rb") as f:
            blob = f.read()
        # Record start offsets, parsed independently of the journal.
        offsets, off = [], 0
        while off < len(blob):
            length, _ = struct.unpack_from("<II", blob, off)
            offsets.append(off)
            off += 8 + length
        assert len(offsets) == 5
        return path, blob, offsets

    def test_truncation_at_every_tail_byte_recovers_prefix(
            self, tmp_path):
        d = str(tmp_path / "j")
        path, blob, offsets = self._journal_blob(d)
        tail_start = offsets[-1]
        prefix = [f"t{i:08d}" for i in range(4)]
        for cut in range(tail_start, len(blob)):
            with open(path, "wb") as f:
                f.write(blob[:cut])
            records, corrupt = read_records(d)
            assert [r["ticket"] for r in records] == prefix, cut
            # cut == tail_start is a CLEAN end (the tail record simply
            # never started); every byte past it is a torn record.
            assert corrupt == (0 if cut == tail_start else 1), cut

    def test_corrupt_tail_surfaces_in_stats_and_fresh_segment(
            self, tmp_path):
        d = str(tmp_path / "j")
        path, blob, offsets = self._journal_blob(d)
        with open(path, "wb") as f:
            f.write(blob[:offsets[-1] + 11])  # mid-tail-record
        j = Journal(d, fsync="off")
        st = j.stats()
        assert st["corrupt_tail"] == 1
        assert st["recovered"] == 4
        assert st["last_seq"] == 4
        assert j.append("submit", ticket="t-next") == 5
        j.close()

    def test_bit_rot_in_tail_truncates_at_crc(self, tmp_path):
        d = str(tmp_path / "j")
        path, blob, offsets = self._journal_blob(d)
        flipped = bytearray(blob)
        flipped[offsets[-1] + 12] ^= 0xFF  # payload byte of the tail
        with open(path, "wb") as f:
            f.write(bytes(flipped))
        records, corrupt = read_records(d)
        assert len(records) == 4 and corrupt == 1


# ------------------------------------------- service-side durability


class TestServiceJournal:
    def test_journal_requires_store(self, ws300):
        with pytest.raises(ValueError, match="store"):
            make_service(ws300, journal=True)

    def test_journal_fsync_validated(self, ws300, tmp_path):
        with pytest.raises(ValueError):
            make_service(ws300, store=str(tmp_path),
                         journal_fsync="bogus")

    def test_stats_carry_durability_fields(self, ws300, tmp_path):
        svc = make_service(ws300, store=str(tmp_path), resume=False)
        svc.submit(1)
        svc.tick()
        st = svc.stats()
        assert st["epoch"] == 0
        assert st["durability_lost"] is None
        assert st["replay_pending"] == 0
        assert st["journal"]["fsync_policy"] == "tick"
        assert st["journal"]["appended"] >= 1
        assert st["journal_covered"] >= 1
        svc.close()

    def test_acked_after_boundary_submits_survive_kill(
            self, ws300, tmp_path):
        svc = make_service(ws300, store=str(tmp_path), resume=False,
                           checkpoint_every_ticks=10)
        t0 = svc.submit(1)
        svc.tick()  # no boundary yet (cadence 10)
        t1 = svc.submit(2)
        t2 = svc.submit(3)
        # SIGKILL stand-in: abandon without close — nothing flushed,
        # no final checkpoint. Only the journal knows t0..t2.
        del svc
        res = make_service(ws300, store=str(tmp_path), resume=True)
        assert res.replay_pending() == 3
        replayed = [res.replay_next()["ticket"]
                    for _ in range(res.replay_pending())]
        assert replayed == [t0, t1, t2]  # SAME acknowledged ids
        for _ in range(40):
            res.tick()
            if not res.busy():
                break
        recs = res.tickets()
        assert {recs[t]["status"] for t in (t0, t1, t2)} == {"done"}
        res.close()

    def test_replay_reissues_same_ids_bit_identical(
            self, ws300, tmp_path):
        pattern = TrafficPattern(ticks=10, rate=5.0, hot_fraction=0.6,
                                 hot_keys=4, burst_prob=0.2)
        sched = generate(pattern, ws300.n_nodes, seed=7)
        ref = make_service(ws300)
        drive(ref, sched)

        svc = make_service(ws300, store=str(tmp_path), resume=False,
                           checkpoint_every_ticks=3)
        crashstorm.install(
            svc, crashstorm.KillPoint("tick", 5),
            action=lambda: (_ for _ in ()).throw(_Kill()))
        with pytest.raises(_Kill):
            drive(svc, sched)
        del svc
        res = make_service(ws300, store=str(tmp_path), resume=True)
        assert res.replay_pending() > 0  # acked past the boundary
        out = drive(res, sched)
        assert out["replayed"] > 0
        assert ref.tickets() == res.tickets()  # seen hashes included
        ref.close()
        res.close()

    @pytest.mark.parametrize("seam,at", [("sidecar_publish", 4),
                                         ("journal_append", 9)])
    def test_kill_seams_resume_bit_identical(self, ws300, tmp_path,
                                             seam, at):
        pattern = TrafficPattern(ticks=8, rate=4.0, hot_fraction=0.5,
                                 hot_keys=4)
        sched = generate(pattern, ws300.n_nodes, seed=11)
        ref = make_service(ws300)
        drive(ref, sched)

        svc = make_service(ws300, store=str(tmp_path), resume=False,
                           checkpoint_every_ticks=2)

        def die():
            raise _Kill()
        crashstorm.install(svc, crashstorm.KillPoint(seam, at),
                           action=die)
        with pytest.raises(_Kill):
            drive(svc, sched)
        del svc
        res = make_service(ws300, store=str(tmp_path), resume=True)
        if seam == "journal_append":
            # The kill fired mid-record: the torn tail was truncated
            # and its intent (never acknowledged) re-submits fresh.
            assert res.stats()["journal"]["corrupt_tail"] == 1
        drive(res, sched)
        assert ref.tickets() == res.tickets()
        ref.close()
        res.close()

    def test_pending_delta_survives_kill_via_replay(
            self, ws300, tmp_path):
        svc = make_service(ws300, store=str(tmp_path), resume=False)
        svc.submit(1)
        svc.tick()
        base_edges = int(svc.graph.n_edges)
        delta = G.GraphDelta.undirected(add_senders=[0],
                                        add_receivers=[7])
        svc.apply_delta(delta)  # acknowledged: journaled, NOT applied
        del svc  # killed before the next tick's mutate phase
        res = make_service(ws300, store=str(tmp_path), resume=True)
        assert res.replay_pending() == 1
        assert res.replay_peek()["kind"] == "delta"
        res.replay_next()
        res.tick()  # mutate phase applies the replayed delta
        assert int(res.graph.n_edges) == base_edges + 2
        res.close()

    def test_journal_compacted_at_boundaries(self, ws300, tmp_path):
        svc = make_service(ws300, store=str(tmp_path), resume=False,
                           checkpoint_every_ticks=1)
        for i in range(6):
            svc.submit(i)
            svc.tick()
        # Every boundary rotated + compacted its covered prefix: the
        # journal holds a bounded suffix, not six ticks of history.
        assert svc.stats()["journal"]["segments"] <= 2
        svc.close()

    def test_resume_false_clears_journal(self, ws300, tmp_path):
        svc = make_service(ws300, store=str(tmp_path), resume=False,
                           checkpoint_every_ticks=10)
        svc.submit(1)
        del svc
        fresh = make_service(ws300, store=str(tmp_path), resume=False)
        assert fresh.replay_pending() == 0
        assert fresh.submit(2) == "t00000000"  # counter restarted
        fresh.close()

    def test_legacy_unjournaled_service(self, ws300, tmp_path):
        svc = make_service(ws300, store=str(tmp_path), resume=False,
                           journal=False)
        svc.submit(1)
        svc.tick()
        st = svc.stats()
        assert "journal" not in st
        assert st["journal_covered"] is None
        assert read_records(str(tmp_path)) == ([], 0)
        svc.close()


# ------------------------------------------------- loud degradation


class TestDurabilityLost:
    def _degraded(self, ws300, tmp_path, **kw):
        reg = telemetry.Registry()
        svc = make_service(ws300, store=str(tmp_path), resume=False,
                           registry=reg, **kw)
        crashstorm.install(svc, crashstorm.KillPoint("disk_full", 1))
        return svc, reg

    def test_disk_full_flips_to_shedding(self, ws300, tmp_path):
        svc, reg = self._degraded(ws300, tmp_path)
        with pytest.raises(DurabilityLost) as ei:
            svc.submit(1)
        assert ei.value.reason == "durability"
        assert issubclass(DurabilityLost, Rejected)
        assert svc.stats()["durability_lost"]
        # Sticky: later submits shed immediately, no journal touched.
        with pytest.raises(DurabilityLost):
            svc.submit(2)
        assert reg.value("serve_rejected_total",
                         reason="durability") == 2
        svc.close()

    def test_mutations_and_cancel_refused_when_lost(
            self, ws300, tmp_path):
        svc, _ = self._degraded(ws300, tmp_path)
        with pytest.raises(DurabilityLost):
            svc.submit(1)
        with pytest.raises(DurabilityLost):
            svc.grow(4)
        with pytest.raises(DurabilityLost):
            svc.apply_delta(G.GraphDelta.undirected(
                add_senders=[0], add_receivers=[7]))
        svc.close()

    def test_driver_survives_degradation(self, ws300, tmp_path):
        svc, _ = self._degraded(ws300, tmp_path)
        with pytest.raises(DurabilityLost):
            svc.submit(1)
        svc.tick()  # the driver keeps ticking (drains, checkpoints)
        assert svc.stats()["durability_lost"]
        svc.close()

    def test_http_durability_surface(self, ws300, tmp_path):
        reg = telemetry.Registry()
        svc = make_service(ws300, store=str(tmp_path), resume=False,
                           registry=reg)
        crashstorm.install(svc, crashstorm.KillPoint("disk_full", 1))
        with MetricsServer(registry=reg, port=0, service=svc) as srv:
            base = f"http://127.0.0.1:{srv.port}"
            code, st = _get(base + "/stats")
            assert code == 200
            assert st["durability_lost"] is None
            assert st["journal"]["fsync_policy"] == "tick"
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(base + "/submit", {"source": 3})
            assert ei.value.code == 503
            body = json.loads(ei.value.read().decode())
            assert body["reason"] == "durability"
            code, st = _get(base + "/stats")
            assert st["durability_lost"]
        svc.close()

    def test_slo_objective_opt_in(self):
        names = [o.name for o in serve_objectives(64.0)]
        assert names == ["completion_p99_rounds", "shed_rate",
                         "heal_rate"]
        objs = serve_objectives(64.0, durability_goal=0.999)
        dur = [o for o in objs if o.name == "durability"]
        assert len(dur) == 1
        assert dur[0].metric == "durability"
        assert not dur[0].admission_signal


# --------------------------------------------------- standby failover


class TestFailover:
    def test_promote_fences_zombie_and_replays_acks(
            self, ws300, tmp_path):
        d = str(tmp_path)
        primary = make_service(ws300, store=d, resume=False,
                               checkpoint_every_ticks=10)
        t0 = primary.submit(1)
        primary.tick()
        sb = Standby(ws300, d, capacity=32, queue_depth=64,
                     chunk_rounds=4, seed=0, record_seen_hash=True,
                     registry=telemetry.Registry())
        obs = sb.refresh()
        assert obs["epoch"] == 0
        assert obs["journal_last_seq"] >= 1
        assert sb.last_observation == obs
        t1 = primary.submit(2)  # acked after the boundary: journal-only
        assert sb.refresh()["replay_pending"] >= 1
        promoted = sb.promote()
        assert promoted.stats()["epoch"] == 1
        # The zombie's late publish is refused, typed and attributed.
        with pytest.raises(FencedEpoch) as ei:
            primary.checkpoint()
        assert ei.value.ours == 0 and ei.value.current == 1
        # The promoted service completes the dead primary's acks with
        # the SAME ticket ids.
        while promoted.replay_pending():
            promoted.replay_next()
        for _ in range(40):
            promoted.tick()
            if not promoted.busy():
                break
        recs = promoted.tickets()
        assert recs[t0]["status"] == "done"
        assert recs[t1]["status"] == "done"
        # Zombie close(): the final dirty checkpoint fences too —
        # close() reports it as a warning (the trail just ends) rather
        # than masking the close.
        with pytest.warns(RuntimeWarning,
                          match="final close checkpoint failed"):
            primary.close()
        promoted.close()

    def test_checkpoint_without_store_is_an_error(self, ws300):
        svc = make_service(ws300)
        with pytest.raises(ValueError, match="store"):
            svc.checkpoint()
        svc.close()

    def test_standby_owns_trail_kwargs(self, ws300, tmp_path):
        with pytest.raises(ValueError, match="resume"):
            Standby(ws300, str(tmp_path), resume=False)

    def test_pinned_epoch_survives_resume(self, ws300, tmp_path):
        d = str(tmp_path)
        svc = make_service(ws300, store=d, resume=False, epoch=7)
        svc.submit(1)
        svc.tick()
        svc.close()
        side = json.loads((tmp_path / _SIDECAR).read_text())
        assert side["epoch"] == 7
        res = make_service(ws300, store=d, resume=True)  # adopts
        assert res.stats()["epoch"] == 7
        res.close()


# ------------------------------------------------ crash-storm schedule


class TestCrashSchedule:
    def test_generation_is_byte_replayable(self):
        a = crashstorm.generate(6, seed=9, ticks=32)
        b = crashstorm.generate(6, seed=9, ticks=32)
        assert a.to_bytes() == b.to_bytes()
        assert len(a) == 6

    def test_required_kinds_present(self):
        sched = crashstorm.generate(5, seed=0, ticks=24)
        kinds = {k.kind for k in sched.kills}
        assert "journal_append" in kinds
        assert "sidecar_publish" in kinds

    def test_validation(self):
        with pytest.raises(ValueError):
            crashstorm.generate(1, require=("journal_append",
                                            "sidecar_publish"))
        with pytest.raises(ValueError):
            crashstorm.generate(3, require=("disk_full",))
        with pytest.raises(ValueError):
            crashstorm.KillPoint("meteor", 3)
        with pytest.raises(ValueError):
            crashstorm.KillPoint("tick", 0)

    def test_campaign_rejects_disk_full_kills(self, tmp_path):
        sched = crashstorm.CrashSchedule(
            kills=(crashstorm.KillPoint("disk_full", 1),), seed=0)
        with pytest.raises(crashstorm.CampaignError,
                           match="availability"):
            crashstorm.run_campaign(str(tmp_path), sched)

    def test_acked_tickets_reads_sidecar_and_journal(
            self, ws300, tmp_path):
        d = str(tmp_path)
        svc = make_service(ws300, store=d, resume=False,
                           checkpoint_every_ticks=10)
        t0 = svc.submit(1)
        svc.tick()
        t1 = svc.submit(2)  # journal-only
        assert crashstorm.acked_tickets(d) == {t0, t1}
        del svc


# ------------------------------------------------- store hardening


class TestAtomicWriteDurable:
    def test_durable_default_roundtrip(self, tmp_path):
        path = str(tmp_path / "doc.json")
        atomic_write_json(path, {"a": 1})
        with open(path) as f:
            assert json.load(f) == {"a": 1}
        assert os.listdir(str(tmp_path)) == ["doc.json"]  # tmp gone

    def test_durable_off_roundtrip(self, tmp_path):
        path = str(tmp_path / "doc.json")
        atomic_write_json(path, {"b": 2}, durable=False)
        with open(path) as f:
            assert json.load(f) == {"b": 2}

    def test_failure_cleans_temp(self, tmp_path):
        path = str(tmp_path / "doc.json")
        with pytest.raises(TypeError):
            atomic_write_json(path, {"bad": object()})
        assert os.listdir(str(tmp_path)) == []


# ------------------------------------------------- acceptance (slow)


@pytest.mark.slow
class TestCrashStormAcceptance:
    def test_campaign_100k_zero_acked_loss_and_fencing(self, tmp_path):
        sched = crashstorm.generate(5, seed=3, ticks=24)
        kinds = [k.kind for k in sched.kills]
        assert "journal_append" in kinds
        assert "sidecar_publish" in kinds
        report = crashstorm.run_campaign(
            str(tmp_path), sched,
            config={"n_nodes": 100_000, "capacity": 64, "rate": 8.0,
                    "chunk_rounds": 8, "checkpoint_every_ticks": 4},
            env={"JAX_PLATFORMS": "cpu"}, timeout=1200.0)
        # run_campaign itself raises on acked loss / divergence; the
        # report must additionally show the storm did real work.
        assert report["tickets"] > 0
        assert sum(1 for k in report["kills"] if k["landed"]) >= 3
        assert report["acked_seen"] <= report["tickets"]

        # Failover over the stormed trail: promote, then the zombie's
        # publish dies as FencedEpoch — the acceptance row's last leg.
        g = G.watts_strogatz(100_000, 6, 0.1, seed=3)
        trail = os.path.join(str(tmp_path), "trail")
        zombie = SimService(g, capacity=64, chunk_rounds=8, seed=0,
                            store=trail, resume=True,
                            record_seen_hash=True,
                            registry=telemetry.Registry())
        promoted = Standby(g, trail, capacity=64, chunk_rounds=8,
                           seed=0, record_seen_hash=True,
                           registry=telemetry.Registry()).promote()
        assert promoted.stats()["epoch"] == zombie.stats()["epoch"] + 1
        with pytest.raises(FencedEpoch):
            zombie.checkpoint()
        promoted.close()


@pytest.mark.slow
class TestJournalOverheadRatchet:
    def test_fsync_tick_overhead_within_ratchet(self):
        import bench
        # Serving scale: the ratio is workload-dependent (a tiny drive
        # is all fsync), and the ratchet pins the regime the service
        # actually runs in — engine work per tick >> one fsync.
        g = G.watts_strogatz(100_000, 6, 0.1, seed=1, source_csr=True)
        ratio = None
        for _ in range(3):  # retries: shared boxes jitter
            col = bench.time_durability(g, cap=64, chunk=8, ticks=10,
                                        rate=8.0)
            ratio = col["fsync"]["tick"]["overhead_ratio"]
            if ratio <= 1.10:
                break
        assert ratio <= 1.10, (
            f"fsync=tick journaling cost {ratio}x an unjournaled "
            "drive (ratchet: <= 1.10x)")
        assert col["replay_scan_ms_per_1k"] < 1000.0


# ----------------------------------------------------------- helpers


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read().decode())


def _post(url, doc=None, timeout=10):
    data = json.dumps(doc or {}).encode()
    req = urllib.request.Request(
        url, data=data, method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read().decode())
