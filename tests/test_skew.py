"""Two-level (skew-split) neighbor table — ops/skew.py.

The hub-proof aggregation layout VERDICT r4 asked for: fixed-width
virtual rows a hub cannot widen, combined by a sorted per-row segment
reduction. Oracle everywhere is the ``segment`` lowering (exact for
or/max/min on any graph; sum parity is tested on exactly-representable
values, the same contract the MXU lowerings document).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from p2pnetwork_tpu.ops import segment, skew  # noqa: E402
from p2pnetwork_tpu.sim import failures, graph as G  # noqa: E402


def _ba(n=2000, m=4, **kw):
    return G.barabasi_albert(n, m, seed=0, skew_table=True, **kw)


def _signals(g, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.random(g.n_nodes_padded) < 0.3)


class TestBuild:
    def test_structure_invariants(self):
        g = _ba()
        t = g.skew
        assert t is not None
        owner = np.asarray(t.owner)
        assert (np.diff(owner) >= 0).all(), "owner must be non-decreasing"
        # Mask slot count == build edge count (every edge exactly once).
        assert int(np.asarray(t.mask).sum()) == g.n_edges
        # Padding rows own the padding node with empty masks.
        live_rows = int(
            (np.asarray(t.mask).any(axis=1)).sum())
        assert (owner[live_rows:] == g.n_nodes_padded - 1).all()
        # A hub of degree d owns ceil(d/W) rows.
        deg = np.asarray(g.in_degree)
        hub = int(deg.argmax())
        w = t.width
        assert (owner == hub).sum() == -(-int(deg[hub]) // w)

    def test_waste_is_bounded_on_hub_graphs(self):
        g = _ba()
        t = g.skew
        # The whole point: the plain table's waste here is huge (one hub
        # widens every row); the two-level table stays under ~2.2x + the
        # one-row-per-node floor, whatever the skew.
        plain = G.barabasi_albert(2000, 4, seed=0)
        plain_waste = (plain.neighbors.shape[0] * plain.neighbors.shape[1]
                       / plain.n_edges)
        wasted = t.n_slots / g.n_edges
        assert plain_waste > 10
        assert wasted < plain_waste / 4
        # Structural bound: slots <= E + (rows * (W-1)) is trivially true;
        # assert the chosen width keeps rows near N (one per node).
        assert t.n_rows < 2 * g.n_nodes_padded

    def test_pick_width_prefers_small_on_low_degree(self):
        assert skew.pick_width(np.full(1000, 6)) == 8
        # Uniform degree-128 rows: W=128 wastes nothing and minimizes rows.
        assert skew.pick_width(np.full(1000, 128)) == 128

    def test_empty_graph(self):
        g = G.from_edges([], [], 4, skew_table=True)
        sig = jnp.zeros(g.n_nodes_padded, dtype=bool)
        out = segment.propagate_or(g, sig, "skew")
        assert not bool(out.any())


class TestParityWithSegment:
    @pytest.mark.parametrize("maker", [
        lambda: _ba(),
        lambda: G.watts_strogatz(1024, 6, 0.2, seed=1, skew_table=True),
        lambda: G.erdos_renyi(777, 0.01, seed=2, skew_table=True),
    ])
    def test_or_parity(self, maker):
        g = maker()
        sig = _signals(g)
        np.testing.assert_array_equal(
            np.asarray(segment.propagate_or(g, sig, "skew")),
            np.asarray(segment.propagate_or(g, sig, "segment")))

    def test_or_parity_star_hub(self):
        # The adversarial shape: one node receives from everyone.
        n = 500
        src = np.arange(1, n)
        g = G.from_edges(np.concatenate([src, np.zeros(n - 1, np.int32)]),
                         np.concatenate([np.zeros(n - 1, np.int32), src]),
                         n, skew_table=True)
        sig = _signals(g, seed=3)
        np.testing.assert_array_equal(
            np.asarray(segment.propagate_or(g, sig, "skew")),
            np.asarray(segment.propagate_or(g, sig, "segment")))

    def test_sum_parity_exact_values(self):
        g = _ba()
        rng = np.random.default_rng(4)
        sig = jnp.asarray(
            rng.integers(0, 7, g.n_nodes_padded).astype(np.float32))
        np.testing.assert_array_equal(
            np.asarray(segment.propagate_sum(g, sig, "skew")),
            np.asarray(segment.propagate_sum(g, sig, "segment")))

    def test_max_parity(self):
        g = _ba()
        rng = np.random.default_rng(5)
        sig = jnp.asarray(rng.integers(-50, 50, g.n_nodes_padded)
                          .astype(np.int32))
        np.testing.assert_array_equal(
            np.asarray(segment.propagate_max(g, sig, "skew")),
            np.asarray(segment.propagate_max(g, sig, "segment")))

    def test_min_plus_parity_weighted(self):
        n, m = 1200, 3
        base = G.barabasi_albert(n, m, seed=6)
        e = base.n_edges
        rng = np.random.default_rng(7)
        s = np.asarray(base.senders)[:e]
        r = np.asarray(base.receivers)[:e]
        w = rng.uniform(0.5, 3.0, e).astype(np.float32)
        g = G.from_edges(s, r, n, skew_table=True, weights=w)
        dist = jnp.where(jnp.arange(g.n_nodes_padded) == 0, 0.0, jnp.inf)
        np.testing.assert_array_equal(
            np.asarray(segment.propagate_min_plus(g, dist, "skew")),
            np.asarray(segment.propagate_min_plus(g, dist, "segment")))

    def test_with_weights_builds_aligned_view(self):
        g = _ba()
        gw = g.with_weights(lambda s, r: 1.0 + (s % 3).astype(np.float32))
        assert gw.skew.weight is not None
        dist = jnp.where(jnp.arange(g.n_nodes_padded) == 5, 0.0, jnp.inf)
        np.testing.assert_array_equal(
            np.asarray(segment.propagate_min_plus(gw, dist, "skew")),
            np.asarray(segment.propagate_min_plus(gw, dist, "segment")))


class TestAutoRouting:
    def test_auto_uses_skew_on_hub_graphs(self):
        g = _ba()
        assert segment._auto_method(g) == "skew"

    def test_auto_keeps_gather_on_quasi_regular(self):
        g = G.watts_strogatz(1024, 6, 0.1, seed=0, skew_table=True)
        assert segment._auto_method(g) == "gather"

    def test_auto_segment_without_any_table(self):
        g = G.barabasi_albert(2000, 4, seed=0, build_neighbor_table=False)
        assert segment._auto_method(g) == "segment"


class TestLiveness:
    def test_node_failures_remask(self):
        g = _ba()
        deg = np.asarray(g.in_degree)
        hub = int(deg.argmax())
        gf = failures.fail_nodes(g, [hub, 17, 400])
        sig = _signals(g, seed=8)
        np.testing.assert_array_equal(
            np.asarray(segment.propagate_or(gf, sig, "skew")),
            np.asarray(segment.propagate_or(gf, sig, "segment")))

    def test_edge_failures_remask_exactly(self):
        g = _ba()
        rng = np.random.default_rng(9)
        cut = rng.choice(g.n_edges, size=200, replace=False)
        gf = failures.fail_edges(g, cut)
        sig = _signals(g, seed=10)
        np.testing.assert_array_equal(
            np.asarray(segment.propagate_or(gf, sig, "skew")),
            np.asarray(segment.propagate_or(gf, sig, "segment")))
        np.testing.assert_array_equal(
            np.asarray(segment.propagate_max(
                gf, sig.astype(jnp.int32), "skew")),
            np.asarray(segment.propagate_max(
                gf, sig.astype(jnp.int32), "segment")))

    def test_dynamic_edges_fold_in(self):
        from p2pnetwork_tpu.sim import topology

        g = topology.with_capacity(_ba(), extra_edges=8)
        g = topology.connect(g, [3], [1999])
        sig = jnp.zeros(g.n_nodes_padded, dtype=bool).at[3].set(True)
        out = segment.propagate_or(g, sig, "skew")
        assert bool(out[1999])


class TestProtocolsAndPersistence:
    def test_adaptive_flood_dense_skew_bitexact(self):
        from p2pnetwork_tpu.models.adaptive_flood import AdaptiveFlood
        from p2pnetwork_tpu.models.flood import Flood
        from p2pnetwork_tpu.sim import engine

        g = G.barabasi_albert(3000, 4, seed=0, skew_table=True,
                              source_csr=True)
        key = jax.random.key(0)
        s_ref, o_ref = engine.run_until_coverage(
            g, Flood(source=0, method="segment"), key,
            coverage_target=0.99, max_rounds=64)
        s_sk, o_sk = engine.run_until_coverage(
            g, AdaptiveFlood(source=0, method="skew", k=64), key,
            coverage_target=0.99, max_rounds=64)
        assert o_sk == o_ref
        np.testing.assert_array_equal(np.asarray(s_sk.seen),
                                      np.asarray(s_ref.seen))

    def test_save_load_roundtrip(self, tmp_path):
        from p2pnetwork_tpu.sim import checkpoint as ckpt

        g = _ba(n=1500)
        p = str(tmp_path / "g.npz")
        ckpt.save_graph(p, g)
        g2 = ckpt.load_graph(p)
        assert g2.skew is not None
        assert g2.skew.width == g.skew.width
        sig = _signals(g, seed=11)
        np.testing.assert_array_equal(
            np.asarray(segment.propagate_or(g2, sig, "skew")),
            np.asarray(segment.propagate_or(g, sig, "skew")))


class TestAutoPath:
    def test_gspmd_auto_skew_parity_8dev(self):
        # The multi-chip story: shard_graph_auto places the virtual rows
        # along the mesh (owner-sorted rows align with their receiver
        # shard) and GSPMD partitions the same engine program; results
        # must equal the unsharded engine exactly.
        from p2pnetwork_tpu.models.flood import Flood
        from p2pnetwork_tpu.parallel import auto, mesh as M
        from p2pnetwork_tpu.sim import engine

        g = G.barabasi_albert(4096, 4, seed=0, skew_table=True)
        mesh = M.ring_mesh(8)
        ga = auto.shard_graph_auto(g, mesh)
        assert ga.skew is not None
        proto = Flood(source=0, method="skew")
        st_a, _ = auto.run_auto(ga, proto, jax.random.key(1), 5)
        st_r, _ = engine.run(g, proto, jax.random.key(1), 5)
        np.testing.assert_array_equal(np.asarray(st_a.seen),
                                      np.asarray(st_r.seen))


class TestPostFailureAttach:
    def test_with_skew_table_after_failures_respects_masks(self):
        # Regression: a table attached AFTER edge/node failures must not
        # resurrect dead edges (build applies the current edge_mask).
        g = failures.fail_edges(
            G.barabasi_albert(300, 3, seed=0), list(range(50)))
        g = failures.fail_nodes(g, [7])
        g = g.with_skew_table()
        ones = jnp.ones(g.n_nodes_padded, dtype=jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(segment.propagate_sum(g, ones, "skew")),
            np.asarray(segment.propagate_sum(g, ones, "segment")))
