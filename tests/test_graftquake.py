"""graftquake: device-plane fault injection, integrity checking and
self-healing recovery.

The sockets plane has a chaos plane; until this PR the DEVICE plane — the
sharded ring engine and graftserve — had zero fault coverage. These tests
pin the three halves and their composition:

- **Injection** (chaos/device.py): seeded `FaultSchedule` halo-hop faults
  through the `_RingComm` seam (`FaultSpec` as a ``comm=`` value) —
  byte-replayable, bit-identical across comm backends, keyed on the
  GLOBAL round so chunked runs hit the same sites as unchunked ones, and
  exactly counted into ``chaos_device_faults_total``; one-shot
  `DispatchChaos` chip-preemption/wedge faults at the engine/serve chunk
  dispatch gates.
- **Detection** (supervise/heal.py): template/finiteness audits,
  batch-plane monotonicity invariants, checksum cross-validation against
  a replicated reference fold — typed `IntegrityViolation`.
- **Recovery**: `RetryPolicy` (seeded deterministic backoff,
  per-failure-class routing) driving `Healer` rollback-and-retry —
  healed runs BIT-IDENTICAL to unfaulted ones — adopted by graftserve's
  tick loop and `SupervisedRun`; plus the satellites (payload-template
  `CommPayloadMismatch`, manifest-missing store accounting, bench probe
  backoff) and the slow-marked 100k chaos soak.
"""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from p2pnetwork_tpu import telemetry  # noqa: E402
from p2pnetwork_tpu.chaos.device import (  # noqa: E402
    FAULT_KINDS, ChipLost, DispatchChaos, FaultSchedule, FaultSpec,
    WedgedDispatch, install_dispatch_chaos, record_faults)
from p2pnetwork_tpu.models.flood import Flood  # noqa: E402
from p2pnetwork_tpu.models.messagebatch import BatchFlood  # noqa: E402
from p2pnetwork_tpu.parallel import commviz, sharded  # noqa: E402
from p2pnetwork_tpu.parallel import mesh as M  # noqa: E402
from p2pnetwork_tpu.serve import (  # noqa: E402
    SimService, TrafficPattern, drive, generate)
from p2pnetwork_tpu.serve.service import Preempted  # noqa: E402
from p2pnetwork_tpu.sim import engine, failures  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402
from p2pnetwork_tpu.supervise import (  # noqa: E402
    CheckpointStore, SupervisedRun)
from p2pnetwork_tpu.supervise.heal import (  # noqa: E402
    Healer, IntegrityViolation, RetryPolicy, audit_state, check_monotonic,
    classify_failure, state_checksum)

pytestmark = pytest.mark.quake

S = 8
needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < S, reason=f"needs {S} devices (virtual CPU mesh)")

KEY = jax.random.key(0)


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < S:
        pytest.skip(f"needs {S} devices")
    return M.ring_mesh(S)


@pytest.fixture(scope="module")
def ws256():
    return G.watts_strogatz(256, 4, 0.2, seed=0)


@pytest.fixture(scope="module")
def sg256(mesh, ws256):
    return sharded.shard_graph(ws256, mesh)


@pytest.fixture()
def no_dispatch_chaos():
    """Guarantee the process-global injector is restored."""
    prev = install_dispatch_chaos(None)
    yield
    install_dispatch_chaos(prev)


def _batch(g, sources, capacity=8, target=0.95):
    proto = BatchFlood()
    b = proto.empty(g, capacity)
    b, _ = proto.admit(g, b, list(sources), coverage_target=target)
    return proto, b


# ------------------------------------------------------- fault schedules


class TestFaultSchedule:
    def test_validation(self):
        with pytest.raises(ValueError, match="probabilities"):
            FaultSchedule(corrupt=0.7, zero=0.4)
        with pytest.raises(ValueError, match="probabilities"):
            FaultSchedule(delay=-0.1)
        with pytest.raises(ValueError, match="corrupt_density"):
            FaultSchedule(corrupt_density=0.0)
        with pytest.raises(ValueError, match="kind"):
            FaultSchedule(sites=((0, 0, 0, "explode"),))

    def test_active(self):
        assert not FaultSchedule(seed=9).active
        assert FaultSchedule(zero=0.1).active
        assert FaultSchedule(sites=((2, 0, 1, "delay"),)).active

    def test_sites_between_replayable_and_windowed(self):
        sched = FaultSchedule(seed=4, corrupt=0.1, zero=0.1, delay=0.1,
                              start_round=2, stop_round=5)
        a = sched.sites_between(0, 8, S - 1, S)
        b = sched.sites_between(0, 8, S - 1, S)
        assert a == b and a  # byte-replayable, non-empty at these rates
        assert all(2 <= r < 5 for r, _, _, _ in a)
        assert all(k in FAULT_KINDS for _, _, _, k in a)
        # window slices compose: [0, 8) == [0, 3) + [3, 8)
        assert a == (sched.sites_between(0, 3, S - 1, S)
                     + sched.sites_between(3, 8, S - 1, S))

    def test_explicit_sites_override_window(self):
        sched = FaultSchedule(seed=0, sites=((7, 2, 3, "zero"),))
        assert sched.sites_between(0, 10, S - 1, S) == [(7, 2, 3, "zero")]

    def test_counts_match_sites(self):
        sched = FaultSchedule(seed=1, zero=0.2, delay=0.1)
        sites = sched.sites_between(0, 6, S - 1, S)
        counts = sched.counts_between(0, 6, S - 1, S)
        for kind in FAULT_KINDS:
            assert counts[kind] == sum(1 for s in sites if s[3] == kind)

    def test_corrupt_payload_shape_dtype_and_determinism(self):
        sched = FaultSchedule(seed=2, corrupt=1.0, corrupt_density=0.25)
        for arr in (jnp.arange(64, dtype=jnp.uint32),
                    jnp.linspace(0.0, 1.0, 64, dtype=jnp.float32),
                    jnp.zeros(64, bool)):
            out1 = sched.corrupt_payload(arr, 1, 2, 3)
            out2 = sched.corrupt_payload(arr, 1, 2, 3)
            assert out1.shape == arr.shape and out1.dtype == arr.dtype
            np.testing.assert_array_equal(np.asarray(out1),
                                          np.asarray(out2))
            assert not np.array_equal(np.asarray(out1), np.asarray(arr))


class TestFaultSpec:
    def test_backend_validation(self):
        with pytest.raises(ValueError, match="resolve 'auto'"):
            FaultSpec(FaultSchedule(), backend="auto")

    def test_hashable_cache_key(self):
        a = FaultSpec(FaultSchedule(seed=1, zero=0.1), "ppermute")
        b = FaultSpec(FaultSchedule(seed=1, zero=0.1), "ppermute")
        assert a == b and hash(a) == hash(b)
        assert {a: 1}[b] == 1


# ------------------------------------------------- halo-hop injection


class TestHaloInjection:
    def test_empty_schedule_bit_identical_to_bare_backend(self, mesh,
                                                          sg256):
        seen0, out0 = sharded.flood_until_coverage(sg256, mesh, 3)
        spec = FaultSpec(FaultSchedule(seed=9), "ppermute")
        seen1, out1 = sharded.flood_until_coverage(sg256, mesh, 3,
                                                   comm=spec)
        np.testing.assert_array_equal(np.asarray(seen0), np.asarray(seen1))
        assert out0 == out1

    def test_faulted_flood_deterministic_and_degraded(self, mesh, sg256):
        _, clean = sharded.flood_until_coverage(sg256, mesh, 3)
        spec = FaultSpec(FaultSchedule(seed=7, zero=0.15, delay=0.1),
                         "ppermute")
        sa, oa = sharded.flood_until_coverage(sg256, mesh, 3, comm=spec)
        sb, ob = sharded.flood_until_coverage(sg256, mesh, 3, comm=spec)
        np.testing.assert_array_equal(np.asarray(sa), np.asarray(sb))
        assert oa == ob
        # Lost/stalled hops cost rounds; coverage still completes (zero
        # and delay faults cannot mint spurious seen bits).
        assert oa["rounds"] > clean["rounds"]
        assert oa["coverage"] >= clean["coverage"] * 0.99

    def test_cross_backend_faulted_parity(self, mesh):
        # The fault math rides ABOVE the halo transfer, and the two
        # backends are bit-identical peers — so the same schedule on
        # ppermute and pallas (interpret mode) must stay bit-identical.
        g = G.watts_strogatz(192, 4, 0.2, seed=0)
        sg = sharded.shard_graph(g, mesh)
        sched = FaultSchedule(seed=5, corrupt=0.05, zero=0.1, delay=0.1)
        sp, op = sharded.flood_until_coverage(
            sg, mesh, 2, comm=FaultSpec(sched, "ppermute"))
        sl, ol = sharded.flood_until_coverage(
            sg, mesh, 2, comm=FaultSpec(sched, "pallas"))
        np.testing.assert_array_equal(np.asarray(sp), np.asarray(sl))
        assert op == ol

    def test_windowed_blackout_round_changes_the_run(self, mesh, sg256):
        # Round 1 loses EVERY halo hop (zero=1.0 over [1, 2)): only
        # intra-shard edges deliver that round, so the trajectory must
        # diverge from clean — and stay byte-replayable.
        clean_seen, clean = sharded.flood_until_coverage(
            sg256, mesh, 3, max_rounds=4)
        spec = FaultSpec(FaultSchedule(seed=0, zero=1.0, start_round=1,
                                       stop_round=2), "ppermute")
        s1, o1 = sharded.flood_until_coverage(sg256, mesh, 3, max_rounds=4,
                                              comm=spec)
        s2, o2 = sharded.flood_until_coverage(sg256, mesh, 3, max_rounds=4,
                                              comm=spec)
        np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
        assert o1 == o2
        assert not np.array_equal(np.asarray(s1), np.asarray(clean_seen)) \
            or o1 != clean

    def test_chunked_equals_unchunked_via_fault_round0(self, mesh, ws256,
                                                       sg256):
        # THE determinism pin: a chunked serving-style drive that
        # threads fault_round0 hits byte-identical fault sites — final
        # per-lane state bit-identical to one unchunked faulted run.
        proto, batch = _batch(ws256, [3, 10, 77])
        spec = FaultSpec(FaultSchedule(seed=3, zero=0.2, delay=0.05),
                         "ppermute")
        bu, ou = sharded.run_batch_until_coverage(
            sg256, mesh, proto, batch, donate=False, comm=spec,
            max_rounds=64)
        bc, r = batch, 0
        for _ in range(32):
            bc, oc = sharded.run_batch_until_coverage(
                sg256, mesh, proto, bc, donate=False, comm=spec,
                max_rounds=4, fault_round0=r)
            r += oc["rounds"]
            if oc["rounds"] == 0 or not oc["active_lanes"]:
                break
        assert state_checksum(bc) == state_checksum(bu)
        assert r == ou["rounds"]

    def test_counter_reflects_schedule_exactly(self, mesh, sg256):
        sched = FaultSchedule(seed=11, zero=0.2, delay=0.1)
        spec = FaultSpec(sched, "ppermute")
        reg = telemetry.default_registry()
        before = {k: reg.value("chaos_device_faults_total", kind=k)
                  for k in FAULT_KINDS}
        _, out = sharded.flood_until_coverage(sg256, mesh, 3, comm=spec)
        counts = sched.counts_between(0, out["rounds"], S - 1, S)
        for k in FAULT_KINDS:
            assert (reg.value("chaos_device_faults_total", kind=k)
                    - before[k]) == counts[k]

    def test_adaptive_path_refuses_fault_specs(self, mesh):
        g = G.watts_strogatz(256, 4, 0.2, seed=0, source_csr=True)
        sg = sharded.shard_graph(g, mesh, source_csr=True)
        with pytest.raises(ValueError, match="adaptive"):
            sharded.flood_until_coverage(
                sg, mesh, 3, adaptive_k=16,
                comm=FaultSpec(FaultSchedule(zero=0.1), "ppermute"))

    def test_record_faults_host_replay(self):
        reg = telemetry.Registry()
        sched = FaultSchedule(seed=1, zero=0.3)
        counts = record_faults(sched, rounds=5, n_steps=S - 1, n_shards=S,
                               registry=reg)
        assert counts == sched.counts_between(0, 5, S - 1, S)
        assert reg.value("chaos_device_faults_total",
                         kind="zero") == counts["zero"]


# ------------------------------------------------- dispatch chaos


class TestDispatchChaos:
    def test_engine_batch_gate_preempts_once(self, ws256,
                                             no_dispatch_chaos):
        proto, batch = _batch(ws256, [3, 9])
        reg = telemetry.Registry()
        install_dispatch_chaos(DispatchChaos(preempt_at=(0,), registry=reg))
        with pytest.raises(ChipLost) as e:
            engine.run_batch_until_coverage(ws256, proto, batch, KEY,
                                            donate=False)
        assert e.value.dispatch_index == 0
        assert reg.value("chaos_device_faults_total", kind="preempt") == 1
        # One-shot: the retry dispatch lands clean.
        _, out = engine.run_batch_until_coverage(ws256, proto, batch, KEY,
                                                 donate=False)
        assert out["completed"] == 2

    def test_coverage_and_sharded_gates_wedge(self, mesh, ws256, sg256,
                                              no_dispatch_chaos):
        proto, batch = _batch(ws256, [3])
        install_dispatch_chaos(DispatchChaos(wedge_at=(0, 1)))
        with pytest.raises(WedgedDispatch):
            engine.run_until_coverage_from(
                ws256, Flood(source=0), Flood(source=0).init(ws256, KEY),
                KEY, donate=False, max_rounds=4)
        with pytest.raises(WedgedDispatch):
            sharded.run_batch_until_coverage(sg256, mesh, proto, batch,
                                             donate=False)

    def test_uninstalled_gate_is_a_noop(self, ws256, no_dispatch_chaos):
        proto, batch = _batch(ws256, [3])
        _, out = engine.run_batch_until_coverage(ws256, proto, batch, KEY,
                                                 donate=False)
        assert out["completed"] == 1

    def test_install_returns_previous(self, no_dispatch_chaos):
        a, b = DispatchChaos(), DispatchChaos()
        assert install_dispatch_chaos(a) is None
        assert install_dispatch_chaos(b) is a
        assert install_dispatch_chaos(None) is b


# ------------------------------------------------- payload templates


class TestCommPayloadMismatch:
    def test_mismatch_raises_typed_at_trace_time(self, mesh):
        def body(x):
            rc = sharded._RingComm("ppermute", "shards", S)
            out = rc.shift(x[0])
            rc.shift(x[0][: x.shape[1] // 2])  # half-width payload
            return out[None]

        fn = sharded.shard_map(body, mesh=mesh, in_specs=(P("shards"),),
                               out_specs=P("shards"))
        x = jnp.zeros((S, 16), jnp.float32)
        with pytest.raises(sharded.CommPayloadMismatch, match="template"):
            jax.jit(fn)(x)

    def test_directions_own_separate_templates(self):
        rc = sharded._RingComm("ppermute", "shards", S)
        rc._check_payload(jnp.zeros(8, bool), "shift")
        rc._check_payload(jnp.zeros(8, jnp.int32), "shift_back")  # ok
        rc._check_payload(jnp.zeros(8, bool), "shift")  # repeat ok
        with pytest.raises(sharded.CommPayloadMismatch):
            rc._check_payload(jnp.zeros(8, jnp.int32), "shift")
        with pytest.raises(sharded.CommPayloadMismatch):
            rc._check_payload(jnp.zeros(4, jnp.int32), "shift_back")

    def test_typed_as_type_error(self):
        assert issubclass(sharded.CommPayloadMismatch, TypeError)


# ------------------------------------------------- integrity checks


class TestIntegrityChecks:
    def test_audit_state_passes_and_detects(self):
        tpl = {"a": np.zeros((4,), np.float32), "b": np.zeros(2, np.int32)}
        audit_state({"a": np.ones(4, np.float32),
                     "b": np.ones(2, np.int32)}, tpl)  # clean
        with pytest.raises(IntegrityViolation, match="template"):
            audit_state({"a": np.zeros(5, np.float32),
                         "b": np.zeros(2, np.int32)}, tpl)
        with pytest.raises(IntegrityViolation, match="template"):
            audit_state({"a": np.zeros(4, np.float64),
                         "b": np.zeros(2, np.int32)}, tpl)
        with pytest.raises(IntegrityViolation) as e:
            audit_state({"a": np.array([1.0, np.nan, 0.0, 0.0],
                                       np.float32),
                         "b": np.zeros(2, np.int32)}, tpl)
        assert e.value.kind == "nonfinite" and "a" in e.value.leaf

    def test_monotonicity_invariants(self, ws256):
        proto, b0 = _batch(ws256, [3, 9])
        b1, _ = engine.run_batch_until_coverage(ws256, proto, b0, KEY,
                                                max_rounds=2, donate=False)
        check_monotonic(b0, b1)  # forward progress is clean
        with pytest.raises(IntegrityViolation, match="seen"):
            check_monotonic(b1, b0)  # reversed: seen bits lost
        import dataclasses
        bad = dataclasses.replace(
            b1, rounds=np.asarray(b1.rounds) - 1)
        with pytest.raises(IntegrityViolation, match="rounds"):
            check_monotonic(b1, bad)
        done_b = dataclasses.replace(
            b1, done=np.zeros_like(np.asarray(b1.done)))
        if np.asarray(b1.done).any():
            with pytest.raises(IntegrityViolation, match="done"):
                check_monotonic(b1, done_b)
        check_monotonic((1, 2), (3, 4))  # non-batch states pass through

    def test_state_checksum_bit_sensitivity(self):
        a = {"x": np.arange(16, dtype=np.uint32)}
        b = {"x": np.arange(16, dtype=np.uint32)}
        assert state_checksum(a) == state_checksum(b)
        b["x"][7] ^= 1
        assert state_checksum(a) != state_checksum(b)

    def test_classify_failure(self):
        from p2pnetwork_tpu.supervise.watchdog import StallTimeout

        assert classify_failure(IntegrityViolation("checksum")) \
            == "integrity"
        assert classify_failure(ChipLost(0)) == "preempt"
        assert classify_failure(WedgedDispatch(1)) == "wedged"
        assert classify_failure(StallTimeout("x", 1.0, 0.5)) == "wedged"
        assert classify_failure(ValueError("nope")) is None


# ------------------------------------------------- retry policy


class TestRetryPolicy:
    def test_backoff_deterministic_and_bounded(self):
        p = RetryPolicy(max_attempts=5, backoff_base_s=0.1,
                        backoff_max_s=0.5, jitter=0.5, seed=42)
        q = RetryPolicy(max_attempts=5, backoff_base_s=0.1,
                        backoff_max_s=0.5, jitter=0.5, seed=42)
        assert p.delays(5) == q.delays(5)
        for a in range(1, 6):
            base = min(0.1 * 2 ** (a - 1), 0.5)
            d = p.backoff_s(a)
            assert base * 0.75 <= d <= base * 1.25
        assert p.delays(3, salt=1) != p.delays(3, salt=2)
        assert RetryPolicy(seed=1).delays(3) != RetryPolicy(seed=2).delays(3)

    def test_validation_and_routing(self):
        with pytest.raises(ValueError):
            RetryPolicy(max_attempts=0)
        with pytest.raises(ValueError):
            RetryPolicy(jitter=2.0)
        with pytest.raises(ValueError, match="route"):
            RetryPolicy(routes={"integrity": "pray"})
        p = RetryPolicy()
        assert p.action_for("integrity") == "fallback"
        assert p.action_for("preempt") == "retry"
        assert p.action_for("wedged") == "retry"
        assert p.action_for("unknown") == "raise"
        assert p.action_for(None) == "raise"
        with pytest.raises(ValueError, match="1-based"):
            p.backoff_s(0)


# ------------------------------------------------- healer


class TestHealer:
    def _policy(self, **kw):
        kw.setdefault("backoff_base_s", 0.0)
        return RetryPolicy(**kw)

    def test_heals_one_shot_fault_and_counts(self):
        reg = telemetry.Registry()
        calls = []

        def dispatch(s):
            calls.append(s)
            if len(calls) == 1:
                raise ChipLost(0)
            return s + 1, {"ok": True}

        h = Healer(self._policy(max_attempts=3), registry=reg)
        state, out = h.run_chunk(dispatch, 10, chunk_index=0)
        assert state == 11 and out == {"ok": True}
        assert len(calls) == 2 and calls[1] == 10  # retained rollback
        assert reg.value("heal_retries_total", outcome="retry") == 1
        assert reg.value("heal_retries_total", outcome="healed") == 1

    def test_exhausted_budget_raises(self):
        reg = telemetry.Registry()

        def dispatch(s):
            raise WedgedDispatch(0)

        h = Healer(self._policy(max_attempts=2), registry=reg)
        with pytest.raises(WedgedDispatch):
            h.run_chunk(dispatch, 0, chunk_index=0)
        assert reg.value("heal_retries_total", outcome="exhausted") == 1
        assert reg.value("heal_retries_total", outcome="retry") == 1

    def test_unroutable_errors_propagate_untouched(self):
        def dispatch(s):
            raise KeyError("caller bug, not a device fault")

        h = Healer(self._policy())
        with pytest.raises(KeyError):
            h.run_chunk(dispatch, 0)

    def test_integrity_routes_to_fallback(self):
        reg = telemetry.Registry()
        tpl = {"x": np.zeros(4, np.float32)}

        def bad(s):  # mints NaNs — semantically-consistent corruption
            return {"x": np.full(4, np.nan, np.float32)}, {}

        def good(s):
            return {"x": np.ones(4, np.float32)}, {}

        h = Healer(self._policy(max_attempts=3), template=tpl,
                   fallback_dispatch=good, registry=reg)
        state, _ = h.run_chunk(bad, {"x": np.zeros(4, np.float32)},
                               chunk_index=1)
        np.testing.assert_array_equal(state["x"], np.ones(4, np.float32))
        assert reg.value("heal_retries_total", outcome="fallback") == 1
        assert reg.value("heal_retries_total", outcome="healed") == 1

    def test_checksum_verify_catches_silent_corruption(self, mesh, ws256,
                                                       sg256):
        # Bit-flip corruption can mint SPURIOUS seen bits — individually
        # well-formed state that no local invariant rejects. Only the
        # replicated reference fold catches it; the heal must then land
        # bit-identical to the clean path. This is the no-silent-wrong-
        # answers acceptance pin.
        proto, batch = _batch(ws256, [3, 9])
        spec = FaultSpec(FaultSchedule(seed=11, corrupt=0.3), "ppermute")

        def faulty(b):
            return sharded.run_batch_until_coverage(
                sg256, mesh, proto, b, donate=False, comm=spec)

        def clean(b):
            return sharded.run_batch_until_coverage(
                sg256, mesh, proto, b, donate=False)

        reg = telemetry.Registry()
        h = Healer(self._policy(max_attempts=3), fallback_dispatch=clean,
                   verify_dispatch=clean, registry=reg)
        healed, _ = h.run_chunk(faulty, batch, chunk_index=0)
        ref, _ = clean(batch)
        assert state_checksum(healed) == state_checksum(ref)
        assert reg.value("heal_retries_total", outcome="fallback") == 1
        assert reg.value("heal_retries_total", outcome="healed") == 1

    def test_store_rollback_prefers_durable_entry(self, tmp_path):
        store = CheckpointStore(str(tmp_path), registry=telemetry.Registry())
        tpl = {"x": np.zeros(4, np.int32)}
        durable = {"x": np.arange(4, dtype=np.int32)}
        store.save(durable, KEY, 3, 30)
        inputs = []

        def dispatch(s):
            inputs.append(np.asarray(s["x"]).copy())
            if len(inputs) == 1:
                raise ChipLost(0)
            return s, {}

        h = Healer(self._policy(max_attempts=2), template=tpl, store=store,
                   monotonic=False, registry=telemetry.Registry())
        h.run_chunk(dispatch, {"x": np.zeros(4, np.int32)}, chunk_index=0)
        np.testing.assert_array_equal(inputs[1], durable["x"])


# ------------------------------------------------- serve + supervise


class TestServeHealing:
    def _svc(self, g, **kw):
        kw.setdefault("capacity", 16)
        kw.setdefault("chunk_rounds", 4)
        kw.setdefault("seed", 0)
        kw.setdefault("record_seen_hash", True)
        kw.setdefault("registry", telemetry.Registry())
        kw.setdefault("heal", RetryPolicy(max_attempts=3,
                                          backoff_base_s=0.0))
        return SimService(g, **kw)

    def test_wedged_tick_heals_transparently(self, ws256,
                                             no_dispatch_chaos):
        pattern = TrafficPattern(ticks=8, rate=2.0, coverage_target=0.9)
        sched = generate(pattern, ws256.n_nodes, seed=7)
        ref = self._svc(ws256)
        drive(ref, sched)
        ref.close()

        reg = telemetry.Registry()
        chaos_reg = telemetry.Registry()
        svc = self._svc(ws256, registry=reg)
        install_dispatch_chaos(DispatchChaos(wedge_at=(1,),
                                             registry=chaos_reg))
        drive(svc, sched)
        svc.close()
        assert svc.tickets() == ref.tickets()  # seen hashes included
        assert chaos_reg.value("chaos_device_faults_total",
                               kind="wedge") == 1
        assert reg.value("heal_retries_total", outcome="healed") == 1

    def test_chip_loss_mid_traffic_loses_no_lane(self, ws256,
                                                 no_dispatch_chaos):
        pattern = TrafficPattern(ticks=6, rate=3.0, coverage_target=0.9)
        sched = generate(pattern, ws256.n_nodes, seed=3)
        ref = self._svc(ws256)
        drive(ref, sched)
        ref.close()

        svc = self._svc(ws256)
        install_dispatch_chaos(DispatchChaos(preempt_at=(0, 2)))
        out = drive(svc, sched)
        svc.close()
        assert svc.tickets() == ref.tickets()
        done = [r for r in out["tickets"].values()
                if r and r["status"] == "done"]
        assert len(done) == len(out["tickets"])  # zero lost lanes

    def test_service_preemption_not_swallowed(self, ws256):
        # Healing covers DETECTED device faults; the supervise plane's
        # deterministic kill must still escape (resume owns recovery).
        svc = self._svc(ws256)
        svc.submit(3)
        svc.arm_preemption(1)
        with pytest.raises(Preempted):
            svc.tick()


class TestSupervisedHealing:
    def test_chip_loss_mid_run_heals_bit_identical(self, tmp_path,
                                                   no_dispatch_chaos):
        g = G.watts_strogatz(512, 6, 0.1, seed=1)
        ref = SupervisedRun(g, Flood(source=0), str(tmp_path / "ref"),
                            chunk_rounds=3)
        st_ref, sum_ref = ref.run_until_coverage(KEY, max_rounds=64)

        reg = telemetry.Registry()
        run = SupervisedRun(g, Flood(source=0), str(tmp_path / "heal"),
                            chunk_rounds=3,
                            heal=RetryPolicy(max_attempts=3,
                                             backoff_base_s=0.0),
                            registry=reg)
        install_dispatch_chaos(DispatchChaos(preempt_at=(1,)))
        st, summary = run.run_until_coverage(KEY, max_rounds=64)
        np.testing.assert_array_equal(np.asarray(st.seen),
                                      np.asarray(st_ref.seen))
        assert summary["rounds"] == sum_ref["rounds"]
        assert summary["messages"] == sum_ref["messages"]
        assert reg.value("heal_retries_total", outcome="healed") == 1


# ------------------------------------------------- store satellites


class TestStoreManifestMissing:
    def _fill(self, store, rounds):
        state = {"x": np.arange(8, dtype=np.int32)}
        for r in rounds:
            state = {"x": state["x"] + 1}
            store.save(state, KEY, r, r * 10)

    def test_scan_fallback_counted_and_logged(self, tmp_path):
        reg = telemetry.Registry()
        store = CheckpointStore(str(tmp_path), retain=3, registry=reg)
        self._fill(store, [1, 2])
        os.unlink(tmp_path / "manifest.json")
        # Corrupt the newest entry too: the scan fallback must still
        # resume from the older good entry (satellite acceptance).
        newest = sorted(n for n in os.listdir(tmp_path)
                        if n.endswith(".npz"))[-1]
        path = tmp_path / newest
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) // 2)
        with pytest.warns(RuntimeWarning, match="directory scan"):
            got = store.load_latest({"x": np.zeros(8, np.int32)})
        assert got is not None and got[2] == 1
        assert reg.value("supervise_checkpoints_skipped_total",
                         reason="manifest-missing") == 1
        assert reg.value("supervise_checkpoints_skipped_total",
                         reason="corrupt") == 1

    def test_fresh_directory_counts_nothing(self, tmp_path):
        reg = telemetry.Registry()
        store = CheckpointStore(str(tmp_path), registry=reg)
        assert store.load_latest({"x": np.zeros(1)}) is None
        assert reg.value("supervise_checkpoints_skipped_total",
                         reason="manifest-missing") == 0


class TestFaultStormResume:
    def test_preempt_corrupt_manifest_loss_resumes_bit_identical(
            self, tmp_path, no_dispatch_chaos):
        # The full storm: deterministic preemption, then the newest
        # checkpoint entry corrupted AND the manifest deleted, then a
        # healed chip loss during the resumed run — the final state must
        # still be bit-identical to an uninterrupted run (PRNG-dependent
        # protocol, so the per-chunk key discipline is what's proven).
        from p2pnetwork_tpu.models import SIR

        g = G.watts_strogatz(512, 6, 0.1, seed=3)
        proto = SIR(beta=0.4, gamma=0.15)
        ref = SupervisedRun(g, proto, str(tmp_path / "ref"),
                            chunk_rounds=4)
        st_ref, sum_ref = ref.run_rounds(jax.random.key(5), 16)

        run = SupervisedRun(g, proto, str(tmp_path / "storm"),
                            chunk_rounds=4, retain=4,
                            heal=RetryPolicy(max_attempts=3,
                                             backoff_base_s=0.0))
        failures.preempt(run, at_round=12)
        with pytest.raises(Preempted):
            run.run_rounds(jax.random.key(5), 16)
        newest = run.store.entries()[-1]
        assert newest["round"] == 8
        path = os.path.join(run.store.directory, newest["file"])
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) // 2)
        os.unlink(os.path.join(run.store.directory, "manifest.json"))
        install_dispatch_chaos(DispatchChaos(preempt_at=(0,)))
        with pytest.warns(RuntimeWarning, match="directory scan"):
            st, summary = run.run_rounds(jax.random.key(5), 16)
        assert summary["resumed_from"] == 4
        assert summary["rounds"] == sum_ref["rounds"] == 16
        assert summary["messages"] == sum_ref["messages"]
        assert state_checksum(jax.device_get(st)) \
            == state_checksum(jax.device_get(st_ref))


# ------------------------------------------------- bench probe backoff


class TestBenchProbeBackoff:
    @pytest.fixture()
    def wedged(self, monkeypatch):
        import bench

        bench._PROBE_LOG.clear()
        monkeypatch.setattr(
            bench, "_probe_backend_once",
            lambda t: "JAX backend init hung (device tunnel wedged?)")
        sleeps = []
        monkeypatch.setattr(bench.time, "sleep",
                            lambda s: sleeps.append(s))
        return bench, sleeps

    def test_probe_log_records_seeded_backoff(self, wedged, monkeypatch):
        bench, sleeps = wedged
        bench._backend_alive(window_s=10_000, probe_timeout_s=1,
                             max_attempts=4)
        entries = [e for e in bench._PROBE_LOG if "backoff_s" in e]
        assert len(entries) == 4  # every failed attempt records its gap
        first = [e["backoff_s"] for e in entries]
        # The slept gaps ARE the recorded backoffs (window not binding).
        assert sleeps == pytest.approx([round(b, 3) for b in first[:3]],
                                       abs=1e-3)
        # Exponential-with-cap shape: 60 s base, 120 s cap, ±25% jitter.
        assert 45.0 <= first[0] <= 75.0
        assert all(90.0 <= b <= 150.0 for b in first[1:])
        # Seeded: a replay produces byte-identical delays…
        bench._PROBE_LOG.clear()
        sleeps.clear()
        bench._backend_alive(window_s=10_000, probe_timeout_s=1,
                             max_attempts=4)
        second = [e["backoff_s"] for e in bench._PROBE_LOG
                  if "backoff_s" in e]
        assert second == first
        # …and a different seed de-synchronizes the retry storm.
        monkeypatch.setenv("BENCH_PROBE_BACKOFF_SEED", "1")
        bench._PROBE_LOG.clear()
        bench._backend_alive(window_s=10_000, probe_timeout_s=1,
                             max_attempts=4)
        third = [e["backoff_s"] for e in bench._PROBE_LOG
                 if "backoff_s" in e]
        assert third != first

    def test_shares_the_heal_retry_policy(self):
        # The probe ladder IS RetryPolicy.backoff_s — not a parallel
        # implementation that can drift.
        p = RetryPolicy(max_attempts=4, backoff_base_s=60.0,
                        backoff_max_s=120.0, jitter=0.5, seed=0)
        import bench

        bench._PROBE_LOG.clear()
        import unittest.mock as mock

        with mock.patch.object(bench, "_probe_backend_once",
                               lambda t: "wedged"), \
                mock.patch.object(bench.time, "sleep", lambda s: None):
            bench._backend_alive(window_s=10_000, probe_timeout_s=1,
                                 max_attempts=3)
        logged = [e["backoff_s"] for e in bench._PROBE_LOG
                  if "backoff_s" in e]
        assert logged == [round(p.backoff_s(a), 3) for a in (1, 2, 3)]


# ------------------------------------------------- comm census pricing


class TestCommCensus:
    def test_faulted_path_never_prices_as_zero_ici(self, mesh, sg256):
        # graftaudit/commviz gate: the FaultyComm wrapper delegates the
        # real transfer to the inner backend, so the census prices an
        # injected ring exactly like the clean ring it wraps — an
        # injected path can never read as zero ICI bytes.
        block = sg256.block
        common_shapes = (
            jnp.float32(0.99), sg256.bkt_src, sg256.bkt_dst, sg256.bkt_mask,
            *sharded._dyn_or_empty(sg256), *sharded._mxu_or_empty(sg256),
            sharded._diag_masks_or_empty(sg256), sg256.node_mask,
            sg256.out_degree,
            jnp.zeros((S, block), bool), jnp.zeros((S, block), bool),
        )
        clean_fn = sharded._flood_cov_fn(mesh, "shards", S, block, 8)
        clean = commviz.ici_bytes_estimate(clean_fn, common_shapes, S)
        spec = FaultSpec(FaultSchedule(seed=1, zero=0.2), "ppermute")
        fault_fn = sharded._flood_cov_fn(mesh, "shards", S, block, 8,
                                         comm=spec)
        faulted = commviz.ici_bytes_estimate(
            fault_fn, (*common_shapes, jnp.int32(0)), S)
        assert clean > 0
        assert faulted >= clean


# ------------------------------------------------- overhead + soak


@pytest.mark.slow
class TestOverheadRatchet:
    def test_integrity_checks_within_1_10x(self, ws256):
        # Recorder-style ratchet: a healed (undonated + checked) chunk
        # loop must stay within 1.10x of the bare donating loop on a
        # 100k-node batch drive (ratio-based, interleaved best-of-N —
        # no absolute wall clocks).
        import time as _time

        g = G.watts_strogatz(100_000, 10, 0.1, seed=0)
        proto = BatchFlood()
        healer = Healer(RetryPolicy(backoff_base_s=0.0), monotonic=True)

        def run(heal):
            b = proto.empty(g, 32)
            b, _ = proto.admit(g, b, list(range(1, 25)),
                               coverage_target=0.95)
            t0 = _time.perf_counter()
            for chunk in range(8):
                if heal:
                    b, out = healer.run_chunk(
                        lambda s: engine.run_batch_until_coverage(
                            g, proto, s, KEY, max_rounds=4, donate=False),
                        b, chunk_index=chunk)
                else:
                    b, out = engine.run_batch_until_coverage(
                        g, proto, b, KEY, max_rounds=4, donate=False)
                if out["rounds"] == 0:
                    break
            return _time.perf_counter() - t0

        run(False), run(True)  # warm both programs before timing
        offs, ons = [], []
        for _ in range(5):
            offs.append(run(False))
            ons.append(run(True))
        ratio = min(ons) / min(offs)
        assert ratio <= 1.10, (
            f"integrity-check overhead {ratio:.3f}x exceeds the 1.10x "
            f"ratchet (off {min(offs):.4f}s on {min(ons):.4f}s)")


@pytest.mark.slow
class TestChaosSoak:
    """The acceptance soak: 100k-node seeded traffic through a storm of
    comm corruption and two chunk-boundary preemptions — served to
    completion with zero lost admitted lanes, per-ticket results
    bit-identical to an uninterrupted run, and the fault/heal counters
    reflecting the schedule exactly."""

    def test_soak_100k(self, tmp_path, no_dispatch_chaos):
        g = G.watts_strogatz(100_000, 6, 0.1, seed=0)
        pattern = TrafficPattern(ticks=10, rate=2.0, hot_fraction=0.5,
                                 hot_keys=4, coverage_target=0.95)
        sched = generate(pattern, g.n_nodes, seed=13)
        policy = RetryPolicy(max_attempts=4, backoff_base_s=0.0)

        def svc(**kw):
            kw.setdefault("capacity", 32)
            kw.setdefault("chunk_rounds", 4)
            kw.setdefault("seed", 1)
            kw.setdefault("record_seen_hash", True)
            kw.setdefault("heal", policy)
            kw.setdefault("registry", telemetry.Registry())
            return SimService(g, **kw)

        # Uninterrupted reference.
        ref = svc()
        drive(ref, sched)
        ref.close()
        assert ref.tickets(), "soak needs traffic"

        # Storm: a healed chip loss + a healed wedge mid-traffic, plus
        # TWO service preemptions with resume from the store.
        chaos_reg = telemetry.Registry()
        heal_reg = telemetry.Registry()
        install_dispatch_chaos(DispatchChaos(
            preempt_at=(1,), wedge_at=(3,), registry=chaos_reg))
        storm = svc(store=str(tmp_path), resume=False, registry=heal_reg)
        storm.arm_preemption(4)
        with pytest.raises(Preempted):
            drive(storm, sched)
        storm2 = svc(store=str(tmp_path), resume=True, registry=heal_reg)
        storm2.arm_preemption(8)
        with pytest.raises(Preempted):
            drive(storm2, sched)
        final = svc(store=str(tmp_path), resume=True, registry=heal_reg)
        out = drive(final, sched)
        final.close()

        # Zero lost admitted lanes; every ticket bit-identical
        # (seen-hash witnesses included in the records).
        assert final.tickets() == ref.tickets()
        assert all(r["status"] == "done"
                   for r in final.tickets().values())
        assert out["completed"] + len(out["shed"]) >= out["submitted"]

        # Counters reflect the storm exactly: one chip loss, one wedge,
        # each healed by exactly one policy retry.
        assert chaos_reg.value("chaos_device_faults_total",
                               kind="preempt") == 1
        assert chaos_reg.value("chaos_device_faults_total",
                               kind="wedge") == 1
        assert heal_reg.value("heal_retries_total", outcome="retry") == 2
        assert heal_reg.value("heal_retries_total", outcome="healed") == 2
        assert heal_reg.value("heal_retries_total", outcome="exhausted") == 0

    @needs_mesh
    def test_soak_100k_comm_corruption_sharded(self, mesh):
        # The comm-corruption half on the multi-chip plane: a corrupt
        # storm over the 100k-node ring batch, detected by the checksum
        # cross-validation and healed onto the clean path — final lanes
        # bit-identical, faults counted exactly per the schedule replay.
        g = G.watts_strogatz(100_000, 6, 0.1, seed=0)
        sg = sharded.shard_graph(g, mesh)
        proto, batch = _batch(g, [3, 999, 54_321], capacity=32,
                              target=0.95)
        sched = FaultSchedule(seed=17, corrupt=0.05)
        spec = FaultSpec(sched, "ppermute")

        reg = telemetry.default_registry()
        before = reg.value("chaos_device_faults_total", kind="corrupt")
        faulted, of = sharded.run_batch_until_coverage(
            sg, mesh, proto, batch, donate=False, comm=spec)
        counts = sched.counts_between(0, of["rounds"], S - 1, S)
        assert (reg.value("chaos_device_faults_total", kind="corrupt")
                - before) == counts["corrupt"] > 0

        def dispatch_faulty(b):
            return sharded.run_batch_until_coverage(
                sg, mesh, proto, b, donate=False, comm=spec)

        def dispatch_clean(b):
            return sharded.run_batch_until_coverage(
                sg, mesh, proto, b, donate=False)

        heal_reg = telemetry.Registry()
        healer = Healer(RetryPolicy(max_attempts=3, backoff_base_s=0.0),
                        fallback_dispatch=dispatch_clean,
                        verify_dispatch=dispatch_clean, registry=heal_reg)
        healed, _ = healer.run_chunk(dispatch_faulty, batch, chunk_index=0)
        ref, _ = dispatch_clean(batch)
        assert state_checksum(healed) == state_checksum(ref)
        assert heal_reg.value("heal_retries_total", outcome="healed") == 1
