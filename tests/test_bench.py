"""The bench harness itself: stage orchestration, early headline emission,
graph caching, and hang containment.

The driver's scoreboard is one run of ``bench.py`` parsed from its last
JSON stdout line — and this environment's device tunnel has wedged exactly
during that run twice (BENCH_r03/r04 both ``value: null``). These tests pin
the machinery that makes a wedge a bounded error instead of a lost round:
the 1M record printed before the 10M stage starts, per-stage child
processes under hard timeouts, and the build-once graph cache that shrinks
the healthy-window a successful run needs.

Runs tiny configs (BENCH_N_*) on the CPU backend: orchestration behavior,
not performance, is under test.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _env(cache_dir, **extra):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "BENCH_N_1M": "2000",
        "BENCH_N_10M": "3000",
        # The batched message-plane column rides the 1m stage: tiny B and
        # graph so orchestration (not throughput) is what the tests pay.
        "BENCH_BATCH_N": "1500",
        "BENCH_BATCH_B": "40",
        # The serving column drives open-loop traffic through SimService
        # on the batched class: tiny capacity/rate so orchestration (not
        # sustained throughput) is what the tests pay.
        "BENCH_SERVE_CAP": "40",
        "BENCH_SERVE_TICKS": "4",
        "BENCH_SERVE_RATE": "15",
        # The queries column runs the three batched query families:
        # tiny K and a tiny chord overlay so orchestration (not the
        # 100k-node ratchet shapes) is what the tests pay — and OFF by
        # default in this suite: six extra XLA compiles per bench child
        # would tax every orchestration test, so only the shared
        # first_run fixture (which pins the published column) pays them.
        "BENCH_QUERIES": "0",
        "BENCH_QUERY_K_MINPLUS": "8",
        "BENCH_QUERY_K_PUSHSUM": "4",
        "BENCH_QUERY_K_DHT": "16",
        "BENCH_QUERY_DHT_N": "512",
        # The multichip ring column spawns its own 8-virtual-device
        # child: tiny graph so the tests pay orchestration, not the
        # interpret/compile bill.
        "BENCH_MULTICHIP_N": "1024",
        "BENCH_BACKEND_WINDOW_S": "5",
        "BENCH_PROBE_TIMEOUT_S": "60",
        "BENCH_CACHE_DIR": str(cache_dir),
        # Stage children write BENCH_TELEMETRY*.json; keep test artifacts
        # out of the repo root.
        "BENCH_TELEMETRY_DIR": str(cache_dir),
    })
    env.update({k: str(v) for k, v in extra.items()})
    # The suite conftest pins XLA_FLAGS for the 8-device mesh; children
    # inherit it harmlessly (bench uses only the default device).
    return env


def _run(cache_dir, timeout=600, **extra):
    r = subprocess.run([sys.executable, BENCH], env=_env(cache_dir, **extra),
                       capture_output=True, text=True, timeout=timeout,
                       cwd=REPO)
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    return r, [json.loads(ln) for ln in lines]


@pytest.fixture(scope="module")
def first_run(tmp_path_factory):
    cache = tmp_path_factory.mktemp("bench_cache")
    r, recs = _run(cache, BENCH_QUERIES="1")
    # Snapshot THIS run's 1M artifact: later tests re-run bench over the
    # same cache dir with the suite's default env (queries off), which
    # overwrites BENCH_TELEMETRY.json — column tests that need the
    # queries-enabled artifact read the snapshot. Guarded: a failed
    # bench child writes no artifact, and the dependent tests' own
    # returncode asserts must surface that stderr, not a copy error.
    import shutil
    if (cache / "BENCH_TELEMETRY.json").exists():
        shutil.copy(cache / "BENCH_TELEMETRY.json",
                    cache / "BENCH_TELEMETRY_first.json")
    return cache, r, recs


class TestOrchestration:
    def test_emits_headline_before_and_after_scale_stage(self, first_run):
        _, r, recs = first_run
        assert r.returncode == 0, r.stderr[-2000:]
        # Four JSON lines: two provisional null records (one before the
        # backend probe, one after it passes — so a caller killing the
        # process at ANY point finds a parseable last line whose error
        # names the phase that was running), the 1M-only record the
        # moment it is measured, then the merged record with scale_10M.
        # The driver parses the LAST line; a mid-10M wedge leaves the 1M
        # record as that line.
        assert len(recs) == 4
        prov_probe, prov_measure, early, merged = recs
        assert prov_probe["value"] is None
        assert "probing" in prov_probe["error"]
        assert prov_measure["value"] is None
        assert "measuring" in prov_measure["error"]
        assert early["value"] is not None and early["value"] > 0
        assert "scale_10M" not in early
        assert merged["value"] == early["value"]
        assert merged["scale_10M"]["value_s"] > 0
        assert merged["vs_baseline"] == pytest.approx(1.0 / merged["value"],
                                                      rel=1e-3)

    def test_graphs_cached_on_first_run(self, first_run):
        cache, _, recs = first_run
        names = os.listdir(cache)
        assert any(n.startswith("ws_n2000") for n in names)
        assert any(n.startswith("ws_n3000") for n in names)
        assert recs[-1]["graph_cached"] is False
        assert recs[-1]["scale_10M"]["graph_cached"] is False

    def test_second_run_loads_from_cache(self, first_run):
        cache, _, _ = first_run
        r, recs = _run(cache)
        assert r.returncode == 0, r.stderr[-2000:]
        merged = recs[-1]
        assert merged["graph_cached"] is True
        assert merged["scale_10M"]["graph_cached"] is True
        assert merged["value"] > 0

    def test_cache_corruption_falls_back_to_build(self, tmp_path):
        sys.path.insert(0, REPO)
        import bench

        fp = bench._layout_fingerprint()
        (tmp_path / f"ws_n2000_k10_p0.1_s0_{fp}.npz").write_bytes(b"not npz")
        r, recs = _run(tmp_path)
        assert r.returncode == 0, r.stderr[-2000:]
        assert recs[-1]["value"] > 0
        assert recs[-1]["graph_cached"] is False
        # The fallback is reported, not swallowed: a structured WARN event
        # in the telemetry JSONL schema names the corrupt file...
        warns = [json.loads(ln.split("# WARN ", 1)[1])
                 for ln in r.stderr.splitlines() if ln.startswith("# WARN ")]
        corrupt = [w for w in warns if w["name"] == "bench_cache_miss"
                   and w["data"]["reason"] == "corrupt"]
        assert corrupt and corrupt[0]["type"] == "event"
        assert "ws_n2000" in corrupt[0]["data"]["path"]
        # ...and the bench_cache_miss_total counter lands in the stage's
        # telemetry artifact.
        tel = json.loads((tmp_path / "BENCH_TELEMETRY.json").read_text())
        samples = tel["metrics"]["bench_cache_miss_total"]["samples"]
        by_reason = {s["labels"]["reason"]: s["value"] for s in samples}
        assert by_reason["corrupt"] == 1

    def test_stale_layout_cache_not_loaded(self, first_run):
        # The cache key folds in a fingerprint of the graph/layout sources:
        # a file under a different fingerprint (layout code since edited)
        # must be ignored, not measured.
        cache, _, _ = first_run
        import shutil

        sys.path.insert(0, REPO)
        import bench

        fp = bench._layout_fingerprint()
        real = next(p for p in os.listdir(cache)
                    if p.startswith("ws_n2000") and fp in p)
        stale_dir = str(cache) + "_stale"
        os.makedirs(stale_dir, exist_ok=True)
        shutil.copy(os.path.join(cache, real),
                    os.path.join(stale_dir, real.replace(fp, "0" * len(fp))))
        r, recs = _run(stale_dir)
        assert r.returncode == 0, r.stderr[-2000:]
        assert recs[-1]["graph_cached"] is False


class TestStageTelemetry:
    @pytest.mark.slow  # its own full bench run (~1 min); the cheap
    # artifact checks ride first_run in the tests below
    def test_stage_artifacts_written_with_nonzero_timings(self, tmp_path):
        # Each measuring stage leaves a per-stage telemetry artifact beside
        # the headline: BENCH_TELEMETRY.json (1M) / _10M.json (scale row),
        # with non-zero graph-build and compile attributions and the full
        # registry snapshot. Own run, own dirs: other tests re-run bench
        # against the shared first_run cache and overwrite its artifacts.
        r, recs = _run(tmp_path)
        assert r.returncode == 0, r.stderr[-2000:]
        for fname, stage in (("BENCH_TELEMETRY.json", "1m"),
                             ("BENCH_TELEMETRY_10M.json", "10m")):
            tel = json.loads((tmp_path / fname).read_text())
            assert tel["schema"] == "bench-telemetry-v1"
            assert tel["stage"] == stage
            st = tel["stages"]
            assert st["graph_build_s"] > 0
            assert st["compile_s"] > 0
            assert st["run_s"] > 0
            assert st["transfer_s"] > 0
            assert st["transfer_bytes"] > 0
            assert st["cache_hit"] is False
            assert "sim_runs_total" in tel["metrics"]
        tel_1m = json.loads((tmp_path / "BENCH_TELEMETRY.json").read_text())
        # headline and artifact must agree on the graph-build attribution
        assert tel_1m["stages"]["graph_build_s"] == pytest.approx(
            recs[-1]["graph_build_s"], abs=0.01)
        # A cold run built the graph, so the per-phase build attribution
        # (sim/graph.py) rides along: dedup + sort at minimum for the WS
        # family, CSR because the spec builds source_csr=True.
        phases = tel_1m["build_phases"]
        assert phases["sort_s"] >= 0 and phases["dedup_s"] >= 0
        assert "source_csr_s" in phases
        assert set(tel_1m["per_method"]) == {
            "pallas", "hybrid", "adaptive-1024", "adaptive-2048", "frontier"}
        # The frontier column carries the per-round occupancy attribution
        # the crossover constant is re-fit from.
        occ = tel_1m["per_method"]["frontier"]["frontier_occupancy_per_round"]
        assert len(occ) == recs[-1]["rounds"]
        assert all(0.0 <= v <= 1.0 for v in occ)

    def test_artifacts_exist_with_nonzero_core_timings(self, first_run):
        # Cheap coverage that rides first_run (later tests may re-run bench
        # over the same dir and overwrite cache_hit, so only the fields
        # invariant across runs are asserted here; the full check is the
        # slow-marked test above).
        cache, _, _ = first_run
        for fname in ("BENCH_TELEMETRY.json", "BENCH_TELEMETRY_10M.json"):
            tel = json.loads((cache / fname).read_text())
            assert tel["schema"] == "bench-telemetry-v1"
            assert tel["stages"]["graph_build_s"] > 0
            assert tel["stages"]["compile_s"] > 0
            assert tel["stages"]["transfer_bytes"] > 0
            # the per-phase build breakdown is always present (empty only
            # on cache-hit runs, which built nothing)
            assert isinstance(tel["build_phases"], dict)
            # The graftaudit static cost model rides beside the measured
            # numbers: the stage's shape-class slice of budgets.json.
            model = tel["ir_cost_model"]
            assert model["shape_class"] == "ws1k"
            assert model["entries"]["or/frontier@ws1k"]["flops"] > 0
            assert "cov/flood-ppermute@ws1k" in model["entries"]

    def test_memory_slice_published_with_device_stats(self, first_run):
        # The graftmem slice (schema-pinned): the static capacity plan
        # from the checked-in membudgets coefficients beside the live
        # `device_memory_stats` snapshot. On the CPU backend the
        # allocator stats are honestly unavailable (per-device stats:
        # None, available: False) — never missing, never a crash.
        cache, _, _ = first_run
        for fname, nodes in (("BENCH_TELEMETRY.json", 1_000_000),
                             ("BENCH_TELEMETRY_10M.json", 10_000_000)):
            tel = json.loads((cache / fname).read_text())
            mem = tel["memory"]
            dms = mem["device_memory_stats"]
            assert isinstance(dms["available"], bool)
            assert dms["devices"], "no per-device rows"
            for row in dms["devices"]:
                assert set(row) == {"id", "platform", "stats"}
                if not dms["available"]:
                    assert row["stats"] is None
            plan = mem["plan"]
            assert "error" not in plan, plan
            assert plan["n_nodes"] == nodes
            assert plan["n_pad"] % 128 == 0
            assert plan["lane_words"] == 313
            assert plan["global_bytes"] > 0

    def test_batched_column_published_with_p99(self, first_run):
        # The batched message-plane column (ROADMAP 2a) lands in the 1M
        # stage artifact: B in-flight floods per compiled program, the
        # completion-rounds p99, and the aggregate-throughput ratio vs
        # sequential single-message runs.
        cache, _, _ = first_run
        tel = json.loads((cache / "BENCH_TELEMETRY.json").read_text())
        col = tel["batched"]
        assert "error" not in col, col
        assert col["B"] == 40
        assert col["completed"] + col["active_lanes_end"] >= 1
        assert col["batch_completion_rounds_p99"] is not None
        assert col["batch_completion_rounds_p99"] >= 1
        assert col["aggregate_speedup_vs_sequential"] > 0
        assert col["best_s"] > 0 and col["messages"] > 0
        assert col["seq_sample_runs"] >= 1

    def test_serving_column_published_with_percentiles(self, first_run):
        # The serving column (ROADMAP 2): seeded open-loop traffic
        # through the admission-controlled service — sustained lanes/s,
        # submit→completion p50/p99 rounds, peak concurrency, shed rate.
        cache, _, _ = first_run
        tel = json.loads((cache / "BENCH_TELEMETRY.json").read_text())
        col = tel["serving"]
        assert "error" not in col, col
        assert col["capacity"] == 64  # 40 requested, rounded to words
        assert col["completed"] >= 1
        assert col["submit_to_completion_rounds_p50"] >= 1
        assert col["submit_to_completion_rounds_p99"] >= \
            col["submit_to_completion_rounds_p50"]
        assert col["sustained_lanes_per_s"] > 0
        assert col["peak_concurrent_lanes"] >= 1
        assert 0.0 <= col["shed_rate"] <= 1.0
        assert col["offered"] == col["submitted"] + col["shed"]

    def test_serving_column_disabled_is_empty_not_missing(self, tmp_path):
        # BENCH_SERVE=0 (what the cpu-fallback parent pins) must publish
        # an EMPTY column, keeping the artifact schema stable.
        r = subprocess.run(
            [sys.executable, BENCH, "--stage", "1m"],
            env=_env(tmp_path, BENCH_SERVE="0"), capture_output=True,
            text=True, timeout=600, cwd=REPO)
        assert r.returncode == 0, r.stderr[-2000:]
        tel = json.loads((tmp_path / "BENCH_TELEMETRY.json").read_text())
        assert tel["serving"] == {}

    def test_queries_column_published_per_family(self, first_run):
        # The queries column (ROADMAP 3): the three non-boolean batched
        # query families each publish aggregate speedup vs warm
        # sequential capacity-1 runs, lanes/s, and completion
        # percentiles.
        cache, _, _ = first_run
        # the fixture's snapshot: the live artifact may since have been
        # overwritten by a re-run with the suite's queries-off default
        tel = json.loads(
            (cache / "BENCH_TELEMETRY_first.json").read_text())
        col = tel["queries"]
        assert "error" not in col, col
        for fam, k in (("minplus", 8), ("pushsum", 4), ("dht", 16)):
            f = col[fam]
            assert "error" not in f, (fam, f)
            assert f["K"] == k
            assert f["completed"] + f["active_lanes_end"] >= 1
            assert f["best_s"] > 0
            assert f["lanes_per_s"] > 0
            assert f["completion_rounds_p99"] is not None
            assert f["completion_rounds_p99"] >= \
                f["completion_rounds_p50"] >= 0
            assert f["aggregate_speedup_vs_sequential"] > 0
            assert f["seq_sample_runs"] >= 1
        # the DHT family rides its own chord overlay
        assert col["dht"]["n_nodes"] == 512
        assert col["minplus"]["n_nodes"] == col["pushsum"]["n_nodes"]

    def test_queries_column_disabled_is_empty_not_missing(self, tmp_path):
        # BENCH_QUERIES=0 (what the cpu-fallback parent pins) must
        # publish an EMPTY column, keeping the artifact schema stable.
        # The sibling columns are disabled and the method contest
        # trimmed to one entry: this subprocess only proves the queries
        # key's disabled shape.
        r = subprocess.run(
            [sys.executable, BENCH, "--stage", "1m"],
            env=_env(tmp_path, BENCH_QUERIES="0", BENCH_BATCH="0",
                     BENCH_SERVE="0", BENCH_MULTICHIP="0",
                     BENCH_METHODS="segment"),
            capture_output=True, text=True, timeout=600, cwd=REPO)
        assert r.returncode == 0, r.stderr[-2000:]
        tel = json.loads((tmp_path / "BENCH_TELEMETRY.json").read_text())
        assert tel["queries"] == {}

    def test_multichip_column_published_with_ici_bytes(self, first_run):
        # The multichip ring column (the promoted dryrun_multichip): the
        # ring-sharded flood's wall, the single-chip scaling ratio, and
        # the per-round ICI byte estimates of BOTH halo backends — a
        # Pallas-comm program must never read as zero ICI bytes.
        cache, _, _ = first_run
        tel = json.loads((cache / "BENCH_TELEMETRY.json").read_text())
        col = tel["multichip"]
        assert "error" not in col and "skipped" not in col, col
        assert col["n_devices"] >= 2
        assert col["best_s"] > 0 and col["single_chip_best_s"] > 0
        assert col["scaling_ratio"] > 0
        assert col["rounds"] >= 1 and col["coverage"] > 0
        per_round = col["per_round_ici_bytes"]
        assert per_round["ppermute"] > 0
        assert per_round["pallas"] > 0
        # the acceptance bound: pallas within 20% of ppermute
        assert 0.8 <= per_round["pallas"] / per_round["ppermute"] <= 1.2
        assert col["ici_census"]["pallas"]["ring_dma"]["count"] >= 1
        assert col["ici_bytes_total_est"] == \
            per_round[col["comm"]] * col["rounds"]

    def test_multichip_column_disabled_is_empty_not_missing(self, tmp_path):
        r = subprocess.run(
            [sys.executable, BENCH, "--stage", "1m"],
            env=_env(tmp_path, BENCH_MULTICHIP="0"), capture_output=True,
            text=True, timeout=600, cwd=REPO)
        assert r.returncode == 0, r.stderr[-2000:]
        tel = json.loads((tmp_path / "BENCH_TELEMETRY.json").read_text())
        assert tel["multichip"] == {}

    def test_batched_column_disabled_is_empty_not_missing(self, tmp_path):
        # BENCH_BATCH=0 (what the cpu-fallback parent pins) must publish
        # an EMPTY column, keeping the artifact schema stable.
        r = subprocess.run(
            [sys.executable, BENCH, "--stage", "1m"],
            env=_env(tmp_path, BENCH_BATCH="0"), capture_output=True,
            text=True, timeout=600, cwd=REPO)
        assert r.returncode == 0, r.stderr[-2000:]
        tel = json.loads((tmp_path / "BENCH_TELEMETRY.json").read_text())
        assert tel["batched"] == {}

    def test_headline_format_unchanged_by_telemetry(self, first_run):
        # The driver parses the LAST stdout line; the artifact must not
        # perturb its key set.
        _, _, recs = first_run
        assert {"metric", "value", "unit", "vs_baseline", "method",
                "rounds", "coverage", "messages", "graph_build_s",
                "graph_cached", "n_nodes", "n_edges",
                "scale_10M"} <= set(recs[-1])

    def test_missing_cache_reported_as_structured_miss(self, first_run):
        cache, r, _ = first_run
        warns = [json.loads(ln.split("# WARN ", 1)[1])
                 for ln in r.stderr.splitlines() if ln.startswith("# WARN ")]
        missing = [w for w in warns if w["name"] == "bench_cache_miss"
                   and w["data"]["reason"] == "missing"]
        assert missing, "first run must report its cold cache misses"


class TestProbeCap:
    """The BENCH_r05 regression: 8 x 120 s wedged-backend probes burned
    the entire window and the round published a null headline. Probes are
    now capped (default 2) BEFORE the cpu-fallback child runs, so a real
    record is always published with most of the window left."""

    @pytest.fixture()
    def wedged(self, monkeypatch):
        """An always-wedged backend probe, counting attempts."""
        import bench

        calls = []

        def stub(timeout_s):
            calls.append(timeout_s)
            return "JAX backend init hung for 120s (device tunnel wedged?)"

        monkeypatch.setattr(bench, "_probe_backend_once", stub)
        return bench, calls

    def test_always_wedged_probe_stops_at_cap(self, wedged, monkeypatch):
        bench, calls = wedged
        sleeps = []
        monkeypatch.setattr(bench.time, "sleep",
                            lambda s: sleeps.append(s))
        # A wide-open window must NOT be spent probing: the cap decides.
        err = bench._backend_alive(window_s=600, probe_timeout_s=1)
        assert len(calls) == 2
        assert "cap 2" in err and "wedged" in err
        assert len(sleeps) == 1  # exactly one retry gap, then hand-off

    def test_cap_env_override(self, wedged, monkeypatch):
        bench, calls = wedged
        monkeypatch.setenv("BENCH_PROBE_MAX_ATTEMPTS", "1")
        err = bench._backend_alive(window_s=1, probe_timeout_s=1)
        assert len(calls) == 1 and "cap 1" in err

    def test_window_still_bounds_when_cap_is_raised(self, wedged):
        bench, calls = wedged
        err = bench._backend_alive(window_s=0, probe_timeout_s=1,
                                   max_attempts=50)
        assert len(calls) == 1
        assert "gave up after 1 probes over 0s" in err

    def test_healthy_probe_returns_none_first_try(self, monkeypatch):
        import bench

        monkeypatch.setattr(bench, "_probe_backend_once", lambda t: None)
        assert bench._backend_alive(window_s=5, probe_timeout_s=1) is None


class TestHangContainment:
    def test_stage_timeout_is_bounded_error_not_hang(self, tmp_path):
        # A 1s stage budget cannot fit backend init: the child must be
        # killed and the run must still emit a parseable record whose
        # error names the stage.
        r, recs = _run(tmp_path, BENCH_STAGE_TIMEOUT_S=1)
        assert r.returncode == 1
        assert recs, "no JSON emitted on stage timeout"
        last = recs[-1]
        assert last["value"] is None
        assert "stage 1m" in last["error"]

    def test_stage_exception_carried_into_record(self, tmp_path):
        # A stage child dying on an exception must surface the actual
        # cause in the parsed record, not a bare "exited rc=1".
        r, recs = _run(tmp_path, BENCH_N_1M="not-a-number")
        assert r.returncode == 1
        last = recs[-1]
        assert last["value"] is None
        assert "stage 1m" in last["error"]
        assert "ValueError" in last["error"]

    def test_dead_backend_falls_back_to_cpu_record(self, tmp_path):
        # An unsatisfiable platform makes every probe fail fast and the
        # tiny window exhausts; the bench must then publish a REAL
        # cpu-fallback record — never value: null when a fallback number
        # is obtainable (BENCH_r05 lost a whole round to exactly that).
        r, recs = _run(tmp_path, JAX_PLATFORMS="nonexistent-platform",
                       BENCH_BACKEND_WINDOW_S=2, BENCH_PROBE_TIMEOUT_S=30)
        assert r.returncode == 0, r.stderr[-2000:]
        last = recs[-1]
        assert last["backend"] == "cpu-fallback"
        assert last["value"] is not None and last["value"] > 0
        assert last["platform"] == "cpu"  # the child really measured on cpu
        assert "backend_error" in last  # the outage cause rides along
        assert "skipped" in last["scale_10M"]  # 10M is chip-only

    def test_dead_backend_and_dead_fallback_is_structured_error(self, tmp_path):
        # When the cpu fallback ALSO fails (here: a poisoned stage config),
        # the old structured-error contract still holds.
        r, recs = _run(tmp_path, JAX_PLATFORMS="nonexistent-platform",
                       BENCH_BACKEND_WINDOW_S=2, BENCH_PROBE_TIMEOUT_S=30,
                       BENCH_N_1M="not-a-number")
        assert r.returncode == 1
        last = recs[-1]
        assert last["value"] is None
        assert "cpu fallback also failed" in last["error"]


class TestPrebuild:
    def test_prebuild_populates_cache_for_measuring_runs(self, tmp_path):
        # --stage prebuild builds + caches both graphs without measuring;
        # a later measuring run must find them (graph_cached: true).
        r = subprocess.run([sys.executable, BENCH, "--stage", "prebuild"],
                           env=_env(tmp_path), capture_output=True,
                           text=True, timeout=600, cwd=REPO)
        assert r.returncode == 0, r.stderr[-2000:]
        last = json.loads(
            [ln for ln in r.stdout.splitlines() if ln.strip()][-1])
        assert last == {"prebuilt": True}
        names = os.listdir(tmp_path)
        assert any(n.startswith("ws_n2000") for n in names)
        assert any(n.startswith("ws_n3000") for n in names)
        r2, recs = _run(tmp_path)
        assert r2.returncode == 0, r2.stderr[-2000:]
        assert recs[-1]["graph_cached"] is True
        assert recs[-1]["scale_10M"]["graph_cached"] is True
