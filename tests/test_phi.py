"""Phi-accrual detector: the estimator driven with synthetic clocks
(deterministic — phi's monotonic growth in silence, adaptation to slow
cadences, the min-std floor), plus a live two-node heartbeat check."""

from p2pnetwork_tpu import PhiAccrualNode
from tests.helpers import stop_all, wait_until

HOST = "127.0.0.1"


def _node(**kw):
    return PhiAccrualNode(HOST, 0, id="me", **kw)


def _feed(n, peer, times):
    for t in times:
        n._record_heartbeat(peer, now=t)


class TestEstimator:
    def test_no_data_no_verdict(self):
        n = _node()
        assert n.phi("ghost") == 0.0
        assert not n.suspected("ghost")

    def test_phi_grows_with_silence(self):
        n = _node()
        _feed(n, "p", [i * 1.0 for i in range(20)])  # 1 Hz heartbeat
        last = 19.0
        phis = [n.phi("p", now=last + dt) for dt in (0.5, 2.0, 5.0, 10.0)]
        assert all(a < b for a, b in zip(phis, phis[1:])), phis
        assert phis[0] < 1.0  # a normal gap is unsuspicious
        assert phis[-1] > 8.0  # 10 missed beats is a verdict

    def test_adapts_to_slow_cadence(self):
        # A 5-second heartbeat peer must NOT be suspected at a 6-second
        # gap that would damn a 1-second peer.
        fast, slow = _node(), _node()
        _feed(fast, "p", [i * 1.0 for i in range(20)])
        _feed(slow, "p", [i * 5.0 for i in range(20)])
        gap = 6.0
        assert fast.phi("p", now=19.0 + gap) > 8.0
        assert slow.phi("p", now=95.0 + gap) < 2.0

    def test_jittery_peer_earns_tolerance(self):
        # Variance widens the distribution: the same absolute gap is
        # less damning for a jittery stream.
        steady, jittery = _node(), _node()
        _feed(steady, "p", [i * 1.0 for i in range(30)])
        ts, t = [], 0.0
        for i in range(30):
            t += 0.4 if i % 2 == 0 else 1.6  # mean 1.0, high variance
            ts.append(t)
        _feed(jittery, "p", ts)
        gap = 3.0
        assert steady.phi("p", now=29.0 + gap) \
            > jittery.phi("p", now=ts[-1] + gap)

    def test_min_std_floor_prevents_hair_trigger(self):
        # Perfectly regular arrivals would estimate std 0 and alarm on
        # any jitter; the floor keeps a small gap unsuspicious.
        n = _node(min_std=0.05)
        _feed(n, "p", [i * 1.0 for i in range(50)])
        assert n.phi("p", now=49.0 + 1.05) < 4.0

    def test_window_bounds_memory(self):
        n = _node(window=10)
        _feed(n, "p", [i * 1.0 for i in range(100)])
        assert len(n._arrivals["p"].intervals) == 10


class TestLive:
    def test_heartbeats_keep_phi_low_then_silence_raises_it(self):
        import time

        a = PhiAccrualNode(HOST, 0, id="A", min_std=0.05)
        b = PhiAccrualNode(HOST, 0, id="B", min_std=0.05)
        nodes = [a, b]
        try:
            for n in nodes:
                n.start()
            assert a.connect_with_node(HOST, b.port)
            assert wait_until(lambda: len(a.all_nodes) == 1
                              and len(b.all_nodes) == 1)
            for _ in range(30):
                a.tick()
                b.tick()
                time.sleep(0.02)
            assert wait_until(lambda: "A" in b._arrivals
                              and len(b._arrivals["A"].intervals) >= 10)
            assert b.phi("A") < 8.0
            # A goes silent (no more ticks): suspicion must climb.
            assert wait_until(lambda: b.phi("A") > 8.0, timeout=10.0), \
                b.phi("A")
            assert b.suspected("A")
        finally:
            stop_all(nodes)

    def test_heartbeats_invisible_to_app(self):
        seen = []

        class App(PhiAccrualNode):
            def node_message(self, node, data):
                if isinstance(data, dict) and "_phi_hb" in data:
                    return super().node_message(node, data)
                seen.append(data)

        a = App(HOST, 0, id="A")
        b = App(HOST, 0, id="B")
        try:
            for n in (a, b):
                n.start()
            assert a.connect_with_node(HOST, b.port)
            assert wait_until(lambda: len(b.all_nodes) == 1)
            a.tick()
            a.send_to_nodes("app traffic")
            assert wait_until(lambda: "app traffic" in seen)
            assert seen == ["app traffic"]
        finally:
            stop_all([a, b])
