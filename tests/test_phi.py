"""Phi-accrual detector: the estimator driven with synthetic clocks
(deterministic — phi's monotonic growth in silence, adaptation to slow
cadences, the min-std floor), plus a live two-node heartbeat check, plus
the quarantine -> probe -> readmit lifecycle driven with synthetic clocks."""

import pytest

from p2pnetwork_tpu import PhiAccrualNode, telemetry
from tests.helpers import stop_all, wait_until

HOST = "127.0.0.1"


def _node(**kw):
    return PhiAccrualNode(HOST, 0, id="me", **kw)


def _feed(n, peer, times):
    for t in times:
        n._record_heartbeat(peer, now=t)


class TestEstimator:
    def test_no_data_no_verdict(self):
        n = _node()
        assert n.phi("ghost") == 0.0
        assert not n.suspected("ghost")

    def test_phi_grows_with_silence(self):
        n = _node()
        _feed(n, "p", [i * 1.0 for i in range(20)])  # 1 Hz heartbeat
        last = 19.0
        phis = [n.phi("p", now=last + dt) for dt in (0.5, 2.0, 5.0, 10.0)]
        assert all(a < b for a, b in zip(phis, phis[1:])), phis
        assert phis[0] < 1.0  # a normal gap is unsuspicious
        assert phis[-1] > 8.0  # 10 missed beats is a verdict

    def test_adapts_to_slow_cadence(self):
        # A 5-second heartbeat peer must NOT be suspected at a 6-second
        # gap that would damn a 1-second peer.
        fast, slow = _node(), _node()
        _feed(fast, "p", [i * 1.0 for i in range(20)])
        _feed(slow, "p", [i * 5.0 for i in range(20)])
        gap = 6.0
        assert fast.phi("p", now=19.0 + gap) > 8.0
        assert slow.phi("p", now=95.0 + gap) < 2.0

    def test_jittery_peer_earns_tolerance(self):
        # Variance widens the distribution: the same absolute gap is
        # less damning for a jittery stream.
        steady, jittery = _node(), _node()
        _feed(steady, "p", [i * 1.0 for i in range(30)])
        ts, t = [], 0.0
        for i in range(30):
            t += 0.4 if i % 2 == 0 else 1.6  # mean 1.0, high variance
            ts.append(t)
        _feed(jittery, "p", ts)
        gap = 3.0
        assert steady.phi("p", now=29.0 + gap) \
            > jittery.phi("p", now=ts[-1] + gap)

    def test_min_std_floor_prevents_hair_trigger(self):
        # Perfectly regular arrivals would estimate std 0 and alarm on
        # any jitter; the floor keeps a small gap unsuspicious.
        n = _node(min_std=0.05)
        _feed(n, "p", [i * 1.0 for i in range(50)])
        assert n.phi("p", now=49.0 + 1.05) < 4.0

    def test_window_bounds_memory(self):
        n = _node(window=10)
        _feed(n, "p", [i * 1.0 for i in range(100)])
        assert len(n._arrivals["p"].intervals) == 10


class TestLive:
    def test_heartbeats_keep_phi_low_then_silence_raises_it(self):
        import time

        a = PhiAccrualNode(HOST, 0, id="A", min_std=0.05)
        b = PhiAccrualNode(HOST, 0, id="B", min_std=0.05)
        nodes = [a, b]
        try:
            for n in nodes:
                n.start()
            assert a.connect_with_node(HOST, b.port)
            assert wait_until(lambda: len(a.all_nodes) == 1
                              and len(b.all_nodes) == 1)
            for _ in range(30):
                a.tick()
                b.tick()
                time.sleep(0.02)
            assert wait_until(lambda: "A" in b._arrivals
                              and len(b._arrivals["A"].intervals) >= 10)
            assert b.phi("A") < 8.0
            # A goes silent (no more ticks): suspicion must climb.
            assert wait_until(lambda: b.phi("A") > 8.0, timeout=10.0), \
                b.phi("A")
            assert b.suspected("A")
        finally:
            stop_all(nodes)

    def test_quarantine_probe_readmit_live(self):
        # End-to-end lifecycle over real TCP: B stops ticking -> A
        # quarantines it; B resumes -> A's probes see it and readmit.
        import time

        a = PhiAccrualNode(HOST, 0, id="A", min_std=0.01,
                           quarantine_threshold=8.0)
        b = PhiAccrualNode(HOST, 0, id="B", min_std=0.01)
        try:
            a.start()
            b.start()
            assert a.connect_with_node(HOST, b.port)
            assert wait_until(lambda: len(a.all_nodes) == 1
                              and len(b.all_nodes) == 1)

            def beat(both, seconds):
                deadline = time.monotonic() + seconds
                while time.monotonic() < deadline:
                    a.tick()
                    if both:
                        b.tick()
                    time.sleep(0.02)

            beat(both=True, seconds=1.0)  # A learns B's ~50 Hz cadence
            assert not a.is_quarantined("B")
            # B goes silent; A keeps ticking (probing + sweeping).
            assert wait_until(lambda: (beat(both=False, seconds=0.2)
                                       or a.is_quarantined("B")),
                              timeout=10.0)
            # B recovers: probes are still flowing, so its heartbeats
            # resume and it earns readmission.
            assert wait_until(lambda: (beat(both=True, seconds=0.2)
                                       or not a.is_quarantined("B")),
                              timeout=10.0)
        finally:
            stop_all([a, b])

    def test_heartbeats_invisible_to_app(self):
        seen = []

        class App(PhiAccrualNode):
            def node_message(self, node, data):
                if isinstance(data, dict) and "_phi_hb" in data:
                    return super().node_message(node, data)
                seen.append(data)

        a = App(HOST, 0, id="A")
        b = App(HOST, 0, id="B")
        try:
            for n in (a, b):
                n.start()
            assert a.connect_with_node(HOST, b.port)
            assert wait_until(lambda: len(b.all_nodes) == 1)
            a.tick()
            a.send_to_nodes("app traffic")
            assert wait_until(lambda: "app traffic" in seen)
            assert seen == ["app traffic"]
        finally:
            stop_all([a, b])


class FakeConn:
    """Stands in for a NodeConnection in synthetic-clock lifecycle tests:
    just an id, a send recorder, and a stop recorder."""

    def __init__(self, id):
        self.id = id
        self.sent = []
        self.stopped = False

    def send(self, data, compression="none"):
        self.sent.append(data)

    def stop(self):
        self.stopped = True


class TestQuarantineLifecycle:
    """The quarantine -> probe -> readmit state machine, driven entirely
    with synthetic clocks (no sockets, no sleeps): a degrading peer is
    excluded from app broadcasts but keeps being probed, earns
    readmission when its heartbeats resume, and is evicted only past
    ``evict_after``."""

    def _node(self, **kw):
        reg = telemetry.Registry()
        n = PhiAccrualNode(HOST, 0, id="me", min_std=0.05,
                           registry=reg, **kw)
        conn = FakeConn("p")
        n.nodes_inbound.append(conn)
        _feed(n, "p", [float(i) for i in range(20)])  # 1 Hz cadence
        return n, conn, reg

    def test_inverted_hysteresis_rejected(self):
        with pytest.raises(ValueError):
            PhiAccrualNode(HOST, 0, id="me", quarantine_threshold=8.0,
                           readmit_threshold=10.0)

    def test_disabled_by_default(self):
        n, conn, _ = self._node()
        try:
            assert n.quarantine_threshold is None
            n.check_quarantine(now=1000.0)  # silent no-op
            assert not n.is_quarantined("p")
        finally:
            n.sock.close()

    def test_quarantine_excludes_then_readmits(self):
        n, conn, reg = self._node(quarantine_threshold=8.0)
        try:
            # Healthy: normal gap, peer stays active and reachable.
            n.check_quarantine(now=19.5)
            assert not n.is_quarantined("p")
            n.send_to_nodes({"app": 1})
            assert conn.sent == [{"app": 1}]
            # Long silence: phi blows past the threshold -> quarantined,
            # excluded from app broadcasts.
            n.check_quarantine(now=40.0)
            assert n.is_quarantined("p")
            assert n.quarantined()  # seconds-in-quarantine view
            n.send_to_nodes({"app": 2})
            assert {"app": 2} not in conn.sent
            assert reg.value("p2p_quarantine_transitions_total",
                             node="me", transition="quarantine") == 1
            assert reg.value("p2p_quarantined_peers", node="me") == 1
            # The peer recovers: fresh heartbeat pulls phi down ->
            # readmitted, broadcasts flow again.
            n._record_heartbeat("p", now=41.0)
            n.check_quarantine(now=41.1)
            assert not n.is_quarantined("p")
            n.send_to_nodes({"app": 3})
            assert {"app": 3} in conn.sent
            assert reg.value("p2p_quarantine_transitions_total",
                             node="me", transition="readmit") == 1
            assert reg.value("p2p_quarantined_peers", node="me") == 0
        finally:
            n.sock.close()

    def test_hysteresis_between_thresholds(self):
        # A peer whose phi sits between readmit and quarantine thresholds
        # neither flaps in nor out.
        n, conn, reg = self._node(quarantine_threshold=8.0,
                                  readmit_threshold=2.0)
        try:
            n.check_quarantine(now=40.0)
            assert n.is_quarantined("p")
            # One heartbeat resumes, then a probe instant where phi sits
            # BETWEEN the thresholds (above readmit, below quarantine):
            # the peer stays put.
            n._record_heartbeat("p", now=41.0)
            gap = next(dt / 4.0 for dt in range(1, 200)
                       if 2.0 < n.phi("p", now=41.0 + dt / 4.0) < 8.0)
            n.check_quarantine(now=41.0 + gap)
            assert n.is_quarantined("p")
            assert reg.value("p2p_quarantine_transitions_total",
                             node="me", transition="readmit") == 0
        finally:
            n.sock.close()

    def test_evict_after_deadline(self):
        n, conn, reg = self._node(quarantine_threshold=8.0, evict_after=5.0)
        try:
            n.check_quarantine(now=40.0)
            assert n.is_quarantined("p")
            n.check_quarantine(now=44.0)  # within the grace window
            assert not conn.stopped
            n.check_quarantine(now=46.0)  # past it: graceful eviction
            assert conn.stopped
            assert reg.value("p2p_quarantine_transitions_total",
                             node="me", transition="evict") == 1
        finally:
            n.sock.close()

    def test_disconnect_clears_quarantine(self):
        n, conn, reg = self._node(quarantine_threshold=8.0)
        try:
            n.check_quarantine(now=40.0)
            assert n.is_quarantined("p")
            n.node_disconnected(conn)
            assert not n.is_quarantined("p")
            assert reg.value("p2p_quarantined_peers", node="me") == 0
        finally:
            n.sock.close()
