"""Message accounting past int32 (utils/accum.py).

The reference's counters are unbounded Python ints [ref: p2pnetwork/
node.py:64-67]; the engine's device-side run-to-coverage accumulator must
not wrap where a 10M-node run's totals routinely exceed 2^31.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from p2pnetwork_tpu.sim import engine
from p2pnetwork_tpu.sim import graph as G
from p2pnetwork_tpu.utils import accum


class TestAccum:
    def test_exact_past_int32(self):
        acc = accum.zero()
        big = jnp.int32(2**31 - 1)
        for _ in range(4):
            acc = accum.add(acc, big)
        assert accum.value(acc) == 4 * (2**31 - 1)  # 8589934588 > 2^31

    def test_matches_python_sum_random(self):
        rng = np.random.default_rng(0)
        xs = rng.integers(0, 2**31, size=64, dtype=np.int64)
        acc = accum.zero()
        for x in xs:
            acc = accum.add(acc, jnp.int32(x))
        assert accum.value(acc) == int(xs.sum())

    def test_jittable_in_scan(self):
        def body(acc, x):
            return accum.add(acc, x), None

        xs = jnp.full((100,), 2**31 - 1, dtype=jnp.int32)
        acc, _ = jax.jit(lambda: jax.lax.scan(body, accum.zero(), xs))()
        assert accum.value(acc) == 100 * (2**31 - 1)


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class _BigCounter:
    """Synthetic protocol: every round claims 2^31 - 1 messages, so five
    rounds overflow an int32 accumulator by 5x."""

    per_round: int = 2**31 - 1

    def init(self, graph, key):
        return jnp.float32(0.0)

    def coverage(self, graph, state):
        return state

    def step(self, graph, state, key):
        state = state + jnp.float32(0.2)
        return state, {"coverage": state, "messages": jnp.int32(self.per_round)}


class TestEngineWideMessages:
    def test_run_until_coverage_totals_past_int32(self):
        g = G.ring(4)
        _, out = engine.run_until_coverage(
            g, _BigCounter(), jax.random.key(0), coverage_target=0.99
        )
        rounds = int(np.asarray(out["rounds"]))
        assert rounds == 5
        assert isinstance(out["messages"], int)
        assert out["messages"] == rounds * (2**31 - 1)  # > 2^33

    def test_flood_totals_still_match_per_round_sum(self):
        g = G.watts_strogatz(512, 6, 0.1, seed=0)
        from p2pnetwork_tpu.models.flood import Flood

        _, out = engine.run_until_coverage(g, Flood(source=0), jax.random.key(0))
        rounds = int(np.asarray(out["rounds"]))
        _, stats = engine.run(g, Flood(source=0), jax.random.key(0), rounds)
        assert out["messages"] == int(np.asarray(stats["messages"]).sum())
