"""Communication behavior of the GSPMD auto path (parallel/auto.py).

The auto idiom hands partitioning to the compiler, so its bandwidth story
needs EVIDENCE, not hope: the worry is the compiler deciding to all-gather
edge-extent arrays (E entries) every round instead of just the node-extent
frontier (N bools — an order of magnitude smaller at avg degree ~10).
These tests compile the auto-sharded program on the real 8-device mesh and
inspect the HLO's collectives: every collective's payload must be
node-extent, never edge-extent, and collectives must exist at all (the
program is genuinely partitioned, not silently replicated).
"""


import numpy as np
import pytest

jax = pytest.importorskip("jax")

from p2pnetwork_tpu.models import SIR, Flood  # noqa: E402
from p2pnetwork_tpu.parallel import auto  # noqa: E402
from p2pnetwork_tpu.parallel import mesh as M  # noqa: E402
from p2pnetwork_tpu.sim import engine  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402

# The parser lives in the library (p2pnetwork_tpu/parallel/commviz.py)
# so the shipped diagnostics and these assertions share one definition;
# the aliases keep this module's historical names.
from p2pnetwork_tpu.parallel.commviz import (  # noqa: E402
    COLLECTIVE_LINE as _LINE,
    collectives as _collectives,
)


def test_parser_sees_variadic_and_async_collectives():
    # Regression: the first parser missed tuple-shaped (combined)
    # collectives entirely — the exact form XLA's combiner emits.
    hlo = """
      %ar = (s32[], s32[], f32[4096]{0}) all-reduce(%a, %b, %c), to_apply=%add
      %ag = pred[4096]{0} all-gather(%x), channel_id=1
      %rs = f32[512]{0} reduce-scatter(%y), channel_id=2
      %ags = (f32[1024]{0}, f32[1024]{0}) all-gather-start(%z), channel_id=3
    """
    colls = _collectives(hlo)
    ops = [c[0] for c in colls]
    assert ops.count("all-reduce") == 3  # tuple flattened
    assert "reduce-scatter" in ops and ops.count("all-gather") == 3
    assert max(c[3] for c in colls) == 4096 * 4


@pytest.mark.parametrize("protocol", [
    Flood(source=0, method="segment"),
    SIR(beta=0.3, gamma=0.1, method="segment"),
])
def test_auto_collectives_are_node_extent_only(protocol):
    g = G.watts_strogatz(4096, 6, 0.2, seed=0)
    gs = auto.shard_graph_auto(g, M.ring_mesh(8))
    hlo = engine.run.lower(gs, protocol, jax.random.key(0), 5).compile().as_text()
    colls = _collectives(hlo)
    # Partitioned for real: cross-shard edges force at least one collective.
    assert colls, "no collectives found — program was not partitioned"
    node_extent_bytes = g.n_nodes_padded * 4
    edge_extent_bytes = g.n_edges_padded * 4
    assert edge_extent_bytes > 4 * node_extent_bytes  # the test has teeth
    for op, dtype, shape, nbytes in colls:
        assert nbytes <= node_extent_bytes, (
            f"{op} moves {nbytes} bytes ({dtype}{list(shape)}) — "
            f"edge-extent traffic; the auto path would not be "
            f"bandwidth-sane at scale"
        )


def test_auto_flood_gathers_frontier_not_edges():
    # The specific expected shape: ONE pred[N] all-gather (the frontier)
    # inside the round loop, nothing larger.
    g = G.watts_strogatz(4096, 6, 0.2, seed=0)
    gs = auto.shard_graph_auto(g, M.ring_mesh(8))
    hlo = engine.run.lower(
        gs, Flood(source=0, method="segment"), jax.random.key(0), 5
    ).compile().as_text()
    gathers = [c for c in _collectives(hlo) if c[0] == "all-gather"]
    assert gathers
    for op, dtype, shape, nbytes in gathers:
        assert dtype == "pred" and nbytes <= g.n_nodes_padded


class TestHybridBlockedAuto:
    """The hybrid layout under GSPMD (VERDICT r3 #3): method="hybrid-blocked"
    keeps the diagonal rolls + einsum remainder — all partitionable ops —
    so the auto path no longer pays the full segment-scatter floor. The
    communication bound must hold for it exactly as for segment."""

    def _hlo(self, protocol, rounds=5):
        g = G.watts_strogatz(4096, 6, 0.2, seed=0, hybrid=True)
        gs = auto.shard_graph_auto(g, M.ring_mesh(8))
        return g, engine.run.lower(
            gs, protocol, jax.random.key(0), rounds
        ).compile().as_text()

    def test_collectives_are_node_extent_only(self):
        g, hlo = self._hlo(Flood(source=0, method="hybrid-blocked"))
        colls = _collectives(hlo)
        assert colls, "no collectives found — program was not partitioned"
        node_extent_bytes = g.n_nodes_padded * 4
        for op, dtype, shape, nbytes in colls:
            assert nbytes <= node_extent_bytes, (
                f"{op} moves {nbytes} bytes ({dtype}{list(shape)}) — "
                f"edge-extent traffic"
            )

    def test_matches_segment_auto_results(self):
        g = G.watts_strogatz(4096, 6, 0.2, seed=0, hybrid=True)
        gs = auto.shard_graph_auto(g, M.ring_mesh(8))
        key = jax.random.key(0)
        st_h, stats_h = auto.run_auto(
            gs, Flood(source=0, method="hybrid-blocked"), key, 8)
        st_s, stats_s = engine.run(
            g, Flood(source=0, method="segment"), key, 8)
        assert (np.asarray(st_h.seen) == np.asarray(st_s.seen)).all()
        np.testing.assert_array_equal(np.asarray(stats_h["messages"]),
                                      np.asarray(stats_s["messages"]))

    def test_sum_path_matches(self):
        g = G.watts_strogatz(2048, 6, 0.2, seed=1, hybrid=True)
        gs = auto.shard_graph_auto(g, M.ring_mesh(8))
        key = jax.random.key(0)
        st_h, _ = auto.run_auto(
            gs, SIR(beta=0.3, gamma=0.1, method="hybrid-blocked"), key, 6)
        st_s, _ = engine.run(
            g, SIR(beta=0.3, gamma=0.1, method="segment"), key, 6)
        assert (np.asarray(st_h.status) == np.asarray(st_s.status)).all()
