"""Batched message plane: lane-packed kernels, MessageBatch lifecycle,
and the batched engine loop — every lane bit-identical to an independent
single-message Flood run.

The contract under test (models/messagebatch.py): packing 32 broadcast
states per uint32 word changes the COST of a round, never its result.
The seeded sweep pins per-lane ``seen`` sets, round counts, and message
totals against independent ``Flood`` runs across graph families, batch
widths (B=1, ragged, multi-word), duplicate sources, failure-masked
edges, and resume/donation; the slow-marked ratchet pins the point of it
all — ≥20x aggregate throughput at B=1024 on the 100k-node WS class,
ratio-based on CPU.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.models import Flood
from p2pnetwork_tpu.models.flood import FloodState
from p2pnetwork_tpu.models.messagebatch import (
    BatchFlood, lane_frontier, lane_messages, lane_seen)
from p2pnetwork_tpu.ops import bitset, frontier as FR, segment as S
from p2pnetwork_tpu.sim import engine, failures
from p2pnetwork_tpu.sim import graph as G
from p2pnetwork_tpu.utils import accum

pytestmark = pytest.mark.batch

KEY = jax.random.key(0)

#: One reference protocol instance: parity runs resume from hand-seeded
#: states through run_until_coverage_from, so the compiled reference loop
#: is shared across every source instead of recompiling per
#: Flood(source=s) (identical semantics — the resume loop seeds cov0 from
#: the true state coverage, exactly like a fresh init'd run).
_REF = Flood(source=0)


def ws(n=300, seed=3, **kw):
    kw.setdefault("source_csr", True)
    return G.watts_strogatz(n, 6, 0.2, seed=seed, **kw)


def single_run(g, source, *, target=0.99, max_rounds=64):
    """An independent single-message engine run — the parity reference."""
    seed = jnp.zeros(g.n_nodes_padded, bool).at[int(source)].set(True)
    seed = seed & g.node_mask
    state = FloodState(seen=seed | jnp.zeros_like(seed),
                       frontier=seed | jnp.zeros_like(seed))
    return engine.run_until_coverage_from(
        g, _REF, state, KEY, coverage_target=target,
        max_rounds=max_rounds, donate=False)


def assert_lane_parity(g, batch, out, lane, source, *, target=0.99,
                       max_rounds=64, msgs=None):
    st, single = single_run(g, source, target=target, max_rounds=max_rounds)
    np.testing.assert_array_equal(
        np.asarray(lane_seen(batch, lane)), np.asarray(st.seen),
        err_msg=f"lane {lane} seen diverged from Flood(source={source})")
    assert int(out["lane_rounds"][lane]) == int(single["rounds"])
    if msgs is not None:
        assert int(msgs[lane]) == int(single["messages"])


# ------------------------------------------------------------- lane algebra


class TestLaneAlgebra:
    def test_expand_collapse_roundtrip(self):
        rng = np.random.default_rng(0)
        lanes = jnp.asarray(rng.integers(0, 2**32, size=97, dtype=np.uint32))
        assert (np.asarray(bitset.collapse_lanes(bitset.expand_lanes(lanes)))
                == np.asarray(lanes)).all()

    def test_lane_counts_matches_expansion(self):
        rng = np.random.default_rng(1)
        for n in (7, 32, 96, 100, 1024):
            lanes = jnp.asarray(
                rng.integers(0, 2**32, size=n, dtype=np.uint32))
            fast = np.asarray(bitset.lane_counts(lanes))
            planes = np.asarray(bitset.expand_lanes(lanes)).astype(np.int64)
            assert (fast == planes.sum(axis=0)).all(), n

    def test_lane_counts_weighted(self):
        rng = np.random.default_rng(2)
        lanes = jnp.asarray(rng.integers(0, 2**32, size=64, dtype=np.uint32))
        w = jnp.asarray(rng.integers(0, 50, size=64, dtype=np.int32))
        got = np.asarray(bitset.lane_counts(lanes, w))
        planes = np.asarray(bitset.expand_lanes(lanes)).astype(np.int64)
        assert (got == (planes * np.asarray(w)[:, None]).sum(axis=0)).all()

    def test_transpose_bits32_involution(self):
        # Double transpose is the identity (both axis reversals cancel).
        rng = np.random.default_rng(3)
        a = jnp.asarray(rng.integers(0, 2**32, size=(5, 32), dtype=np.uint32))
        assert (np.asarray(bitset.transpose_bits32(
            bitset.transpose_bits32(a))) == np.asarray(a)).all()

    def test_or_scatter_lanes_duplicates_compose(self):
        # Two different bit patterns landing on one receiver must OR, the
        # exact case a word-level .at[].max scatter gets wrong.
        idx = jnp.asarray([2, 2, 5], dtype=jnp.int32)
        vals = jnp.asarray([0b01, 0b10, 0b100], dtype=jnp.uint32)
        out = np.asarray(bitset.or_scatter_lanes(8, idx, vals))
        assert out[2] == 0b11 and out[5] == 0b100 and out.sum() == 7

    def test_or_scatter_lanes_out_of_range_drops(self):
        out = np.asarray(bitset.or_scatter_lanes(
            4, jnp.asarray([4]), jnp.asarray([0xFFFF], dtype=jnp.uint32)))
        assert (out == 0).all()


# ---------------------------------------------------------- kernel parity


def lanes_from_bool(sig):
    """bool[B, N] -> u32[ceil(B/32), N] in lane order b = 32w + L."""
    B, n = sig.shape
    W = bitset.n_words(B)
    padded = np.zeros((W * 32, n), dtype=bool)
    padded[:B] = sig
    return jnp.stack([
        bitset.collapse_lanes(jnp.asarray(padded[w * 32:(w + 1) * 32].T))
        for w in range(W)])


class TestPropagateOrLanes:
    @pytest.mark.parametrize("method", ["segment", "gather", "frontier",
                                        "auto"])
    def test_matches_per_lane_propagate_or(self, method):
        rng = np.random.default_rng(4)
        g = ws()
        n = g.n_nodes_padded
        sig = rng.random((40, n)) < 0.04
        sig &= np.asarray(g.node_mask)[None, :]
        out = S.propagate_or_lanes(g, lanes_from_bool(sig), method)
        for b in range(40):
            w, L = divmod(b, 32)
            got = np.asarray((out[w] >> np.uint32(L)) & 1).astype(bool)
            want = np.asarray(S.propagate_or(g, jnp.asarray(sig[b]),
                                             "segment"))
            np.testing.assert_array_equal(got, want, err_msg=f"{method}/{b}")

    def test_frontier_sparse_branch_taken(self):
        # A one-node union frontier must ride the compacted branch and
        # still match dense word-for-word.
        g = ws()
        lanes = jnp.zeros((2, g.n_nodes_padded), jnp.uint32
                          ).at[1, 9].set(jnp.uint32(0b1001))
        out = S.propagate_or_lanes(g, lanes, "frontier",
                                   frontier_crossover=0.9)
        want = S.propagate_or_lanes(g, lanes, "segment")
        assert (np.asarray(out) == np.asarray(want)).all()
        assert int(np.asarray(out[0]).sum()) == 0  # untouched word stays 0

    def test_frontier_requires_csr(self):
        g = G.watts_strogatz(100, 4, 0.1, seed=0, source_csr=False)
        lanes = jnp.zeros((1, g.n_nodes_padded), jnp.uint32)
        with pytest.raises(ValueError, match="source-CSR"):
            S.propagate_or_lanes(g, lanes, "frontier")

    def test_unknown_method_rejected(self):
        g = ws()
        with pytest.raises(ValueError, match="word-level"):
            S.propagate_or_lanes(
                g, jnp.zeros((1, g.n_nodes_padded), jnp.uint32), "skew")

    def test_dynamic_edges_fold_in(self):
        from p2pnetwork_tpu.sim import topology

        g = topology.with_capacity(ws(), extra_edges=8)
        g = topology.connect(g, jnp.asarray([5]), jnp.asarray([250]))
        sig = np.zeros((1, g.n_nodes_padded), dtype=bool)
        sig[0, 5] = True
        out = S.propagate_or_lanes(g, lanes_from_bool(sig), "auto")
        want = np.asarray(S.propagate_or(g, jnp.asarray(sig[0]), "auto"))
        got = np.asarray((out[0] >> np.uint32(0)) & 1).astype(bool)
        np.testing.assert_array_equal(got, want)
        assert want[250]  # the dynamic link actually delivered

    def test_budget_slots_lanes_is_word_scaled(self):
        g = ws()
        assert FR.budget_slots_lanes(g, n_words=2) == \
            FR.budget_slots(g) * 32 * 2


# ------------------------------------------------------- batch-vs-sequential


class TestBatchParity:
    @pytest.mark.parametrize("graph_fn,B", [
        (lambda: ws(n=300, seed=3), 1),
        (lambda: ws(n=300, seed=3), 5),
        (lambda: ws(n=200, seed=4), 32),
        (lambda: G.erdos_renyi(150, 0.04, seed=5, source_csr=True), 40),
    ])
    def test_seeded_sweep_bit_identical(self, graph_fn, B):
        g = graph_fn()
        rng = np.random.default_rng(B)
        sources = rng.integers(0, g.n_nodes, size=B).astype(np.int32)
        proto = BatchFlood(method="auto")
        batch = proto.init(g, sources, coverage_target=0.99)
        batch, out = engine.run_batch_until_coverage(
            g, proto, batch, KEY, max_rounds=64, donate=False)
        msgs = np.asarray(lane_messages(g, batch))
        for i, s in enumerate(sources):
            assert_lane_parity(g, batch, out, i, s, msgs=msgs)
        # Aggregate two-limb total == sum of exact per-lane totals.
        assert out["messages"] == int(msgs[:B].sum())
        # Ragged pad lanes stay inert.
        for lane in range(B, batch.capacity):
            assert not np.asarray(lane_seen(batch, lane)).any()
            assert not bool(out["lane_done"][lane])

    def test_duplicate_sources_are_independent_identical_lanes(self):
        g = ws()
        proto = BatchFlood()
        batch = proto.init(g, [17, 17, 17])
        batch, out = engine.run_batch_until_coverage(
            g, proto, batch, KEY, max_rounds=64, donate=False)
        s0 = np.asarray(lane_seen(batch, 0))
        for lane in (1, 2):
            np.testing.assert_array_equal(
                np.asarray(lane_seen(batch, lane)), s0)
        assert len({int(r) for r in out["lane_rounds"][:3]}) == 1

    def test_failure_masked_edges_parity(self):
        g = ws(n=260, seed=6)
        cut = np.arange(0, g.n_edges, 7, dtype=np.int32)
        gf = failures.fail_edges(g, cut)
        proto = BatchFlood(method="auto")
        sources = [0, 33, 123]
        batch = proto.init(gf, sources)
        batch, out = engine.run_batch_until_coverage(
            gf, proto, batch, KEY, max_rounds=32, donate=False)
        msgs = np.asarray(lane_messages(gf, batch))
        for i, s in enumerate(sources):
            assert_lane_parity(gf, batch, out, i, s, max_rounds=32,
                               msgs=msgs)

    def test_frontier_method_parity(self):
        g = ws(n=300, seed=7)
        proto = BatchFlood(method="frontier")
        sources = [1, 2, 250]
        batch = proto.init(g, sources)
        batch, out = engine.run_batch_until_coverage(
            g, proto, batch, KEY, max_rounds=64, donate=False)
        for i, s in enumerate(sources):
            assert_lane_parity(g, batch, out, i, s)

    def test_max_rounds_freezes_stragglers(self):
        # A 2-regular ring floods one hop per round: max_rounds cuts the
        # run off exactly like the single-message loop's bound.
        g = G.ring(64, source_csr=True)
        proto = BatchFlood()
        batch = proto.init(g, [0, 10])
        batch, out = engine.run_batch_until_coverage(
            g, proto, batch, KEY, max_rounds=5, donate=False)
        assert out["rounds"] == 5 and out["completed"] == 0
        assert out["active_lanes"] == 2
        for i, s in enumerate((0, 10)):
            assert_lane_parity(g, batch, out, i, s, max_rounds=5,
                               msgs=np.asarray(lane_messages(g, batch)))


# ------------------------------------------------- lifecycle and admission


class TestLifecycle:
    def test_staggered_admission_recycles_lanes(self):
        g = ws()
        proto = BatchFlood()
        batch = proto.init(g, [1, 2], capacity=40)
        batch, _ = engine.run_batch_until_coverage(
            g, proto, batch, KEY, max_rounds=64, donate=False)
        batch = proto.retire(batch)
        assert int(np.asarray(batch.admitted).sum()) == 0
        batch, lanes = proto.admit(g, batch, [5, 6, 7])
        assert list(lanes) == [0, 1, 2]  # recycled, not appended
        batch, out = engine.run_batch_until_coverage(
            g, proto, batch, KEY, max_rounds=64, donate=False)
        msgs = np.asarray(lane_messages(g, batch))
        for lane, s in zip(lanes, (5, 6, 7)):
            assert_lane_parity(g, batch, out, int(lane), s, msgs=msgs)

    def test_mixed_wave_resume_only_steps_running_lanes(self):
        # Wave 2 admitted mid-flight: wave-1 lanes are already done and
        # frozen; wave-2 lanes still match their independent runs.
        g = ws(n=220, seed=8)
        proto = BatchFlood()
        batch = proto.init(g, [3], capacity=64)
        batch, _ = engine.run_batch_until_coverage(
            g, proto, batch, KEY, max_rounds=64, donate=False)
        seen_w1 = np.asarray(lane_seen(batch, 0)).copy()
        batch, lanes = proto.admit(g, batch, [99])
        batch, out = engine.run_batch_until_coverage(
            g, proto, batch, KEY, max_rounds=64, donate=False)
        np.testing.assert_array_equal(
            np.asarray(lane_seen(batch, 0)), seen_w1)  # frozen lane inert
        assert_lane_parity(g, batch, out, int(lanes[0]), 99,
                           msgs=np.asarray(lane_messages(g, batch)))

    def test_admit_empty_wave_is_noop(self):
        # An idle admission tick (the serving loop polled an empty queue)
        # must hand the batch back unchanged, not crash.
        g = ws()
        proto = BatchFlood()
        batch = proto.init(g, [1, 2])
        same, lanes = proto.admit(g, batch, [])
        assert lanes.size == 0
        for a, b in zip(jax.tree_util.tree_leaves(same),
                        jax.tree_util.tree_leaves(batch)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_admit_backpressure_raises(self):
        g = ws()
        proto = BatchFlood()
        batch = proto.init(g, [1, 2, 3])  # capacity rounds to 32
        with pytest.raises(ValueError, match="open lanes"):
            proto.admit(g, batch, list(range(30)))

    def test_admit_rejects_out_of_range_source(self):
        g = ws()
        proto = BatchFlood()
        with pytest.raises(ValueError):
            proto.init(g, [0, g.n_nodes_padded + 5])

    def test_init_requires_sources_and_capacity(self):
        g = ws()
        proto = BatchFlood()
        with pytest.raises(ValueError, match="at least one"):
            proto.init(g, [])
        with pytest.raises(ValueError, match="capacity"):
            proto.init(g, [1, 2, 3], capacity=2)

    def test_retire_rejects_out_of_range_lane(self):
        # retire(-1) would numpy-wrap and erase the LAST lane's
        # in-flight state — the write-side twin of the _lane_word guard.
        g = ws()
        proto = BatchFlood()
        batch = proto.init(g, [1, 2])
        with pytest.raises(ValueError, match="capacity"):
            proto.retire(batch, lanes=[-1])
        with pytest.raises(ValueError, match="capacity"):
            proto.retire(batch, lanes=[32])

    def test_completion_is_latched_across_failure_resume(self):
        # A completed message stays delivered even when later failures
        # drop its masked coverage under target (documented divergence
        # from single-message resume: the freeze cleared its frontier;
        # re-broadcast after churn is a NEW message via admit).
        g = ws(n=220, seed=12)
        proto = BatchFlood()
        batch = proto.init(g, [3], coverage_target=0.9)
        batch, out1 = engine.run_batch_until_coverage(
            g, proto, batch, KEY, max_rounds=64, donate=False)
        assert out1["completed"] == 1
        seen = np.flatnonzero(np.asarray(lane_seen(batch, 0)))
        gf = failures.kill_nodes(g, seen[: len(seen) // 2].astype(np.int32))
        batch, out2 = engine.run_batch_until_coverage(
            gf, proto, batch, KEY, max_rounds=64, donate=False)
        assert bool(out2["lane_done"][0]) and out2["rounds"] == 0

    def test_lane_views_reject_out_of_range_lane(self):
        # An out-of-range lane id must raise, not silently clamp to the
        # last word and hand back another message's predicate.
        g = ws()
        batch = BatchFlood().init(g, [1])  # capacity 32, one word
        with pytest.raises(ValueError, match="capacity"):
            lane_seen(batch, 40)
        with pytest.raises(ValueError, match="capacity"):
            lane_frontier(batch, -1)

    def test_retire_specific_lanes(self):
        g = ws()
        proto = BatchFlood()
        batch = proto.init(g, [1, 2])
        batch, _ = engine.run_batch_until_coverage(
            g, proto, batch, KEY, max_rounds=64, donate=False)
        batch = proto.retire(batch, lanes=[0])
        adm = np.asarray(batch.admitted)
        assert not adm[0] and adm[1]
        assert not np.asarray(lane_seen(batch, 0)).any()
        assert np.asarray(lane_seen(batch, 1)).any()
        assert not np.asarray(lane_frontier(batch, 0)).any()

    def test_resume_after_node_failures_recounts_masked_coverage(self):
        # Node failures applied BETWEEN engine calls shrink the masked
        # numerator: a resumed batch must re-count against the current
        # mask (refresh + absolute per-round recount), not freeze lanes
        # early off a stale accumulated seen_count — pinned against the
        # single-message resume, which recomputes every round.
        g = ws(n=200, seed=11)
        proto = BatchFlood()
        batch = proto.init(g, [0])
        batch, _ = engine.run_batch_until_coverage(
            g, proto, batch, KEY, max_rounds=3, donate=False)
        dead = np.arange(100, 200, dtype=np.int32)
        gf = failures.kill_nodes(g, dead)
        batch, out = engine.run_batch_until_coverage(
            gf, proto, batch, KEY, max_rounds=64, donate=False)
        # independent single-message resume from the same mid-state
        seed = jnp.zeros(g.n_nodes_padded, bool).at[0].set(True)
        st0 = FloodState(seen=seed & g.node_mask,
                         frontier=seed & g.node_mask)
        st_mid, _ = engine.run_until_coverage_from(
            g, _REF, st0, KEY, coverage_target=0.99, max_rounds=3,
            donate=False)
        st_fin, single = engine.run_until_coverage_from(
            gf, _REF, st_mid, KEY, coverage_target=0.99, max_rounds=64,
            donate=False)
        np.testing.assert_array_equal(
            np.asarray(lane_seen(batch, 0)), np.asarray(st_fin.seen))
        # lane_rounds is cumulative: 3 pre-failure + the resumed rounds
        assert int(out["lane_rounds"][0]) == 3 + int(single["rounds"])
        # true masked coverage of the batch lane meets the target
        cov = (np.asarray(lane_seen(batch, 0))
               & np.asarray(gf.node_mask)).sum() / \
            np.asarray(gf.node_mask).sum()
        assert bool(out["lane_done"][0]) == (cov >= 0.99)

    def test_refresh_completed_lane_observes_completion_this_call(self):
        # A lane the entry refresh itself completes (failures shrank the
        # denominator between calls) completed in THIS call: it must get
        # completion percentiles/histogram observations, not vanish
        # between the two calls' done snapshots.
        from p2pnetwork_tpu import telemetry

        g = G.ring(64, source_csr=True)
        proto = BatchFlood()
        batch = proto.init(g, [0], coverage_target=0.5)
        batch, out1 = engine.run_batch_until_coverage(
            g, proto, batch, KEY, max_rounds=10, donate=False)
        assert out1["completed"] == 0  # 10 hops of a 64-ring < 50%
        # 10 rounds reach nodes 0..10 and 54..63; killing 20..63 leaves
        # 20 live of which 11 are seen -> 0.55 >= 0.5 at refresh time.
        gf = failures.kill_nodes(g, np.arange(20, 64, dtype=np.int32))
        fresh = telemetry.Registry()
        prev = telemetry.set_default_registry(fresh)
        try:
            batch, out2 = engine.run_batch_until_coverage(
                gf, proto, batch, KEY, max_rounds=10, donate=False)
        finally:
            telemetry.set_default_registry(prev)
        assert out2["completed"] == 1 and out2["rounds"] == 0
        assert out2["completion_rounds_p99"] is not None
        h = fresh.get("sim_batch_completion_rounds")
        assert h is not None and h._anon().count == 1

    def test_dead_source_spins_to_max_rounds_like_single_run(self):
        g = failures.kill_nodes(ws(), [44])
        proto = BatchFlood()
        batch = proto.init(g, [44])
        batch, out = engine.run_batch_until_coverage(
            g, proto, batch, KEY, max_rounds=8, donate=False)
        assert out["completed"] == 0 and out["rounds"] == 8
        assert not np.asarray(lane_seen(batch, 0)).any()
        _, single = single_run(g, 44, max_rounds=8)
        assert int(out["lane_rounds"][0]) == int(single["rounds"]) == 8


# ------------------------------------------------------ donation and resume


class TestDonation:
    def test_donated_batch_invalidated_and_resume_guard_names_fix(self):
        g = ws()
        proto = BatchFlood()
        b0 = proto.init(g, [3])
        b1, _ = engine.run_batch_until_coverage(
            g, proto, b0, KEY, max_rounds=3)  # donate=True default
        assert any(leaf.is_deleted()
                   for leaf in jax.tree_util.tree_leaves(b0))
        with pytest.raises(ValueError, match="donate=False"):
            engine.run_batch_until_coverage(g, proto, b0, KEY, max_rounds=3)
        # the returned carry resumes fine
        engine.run_batch_until_coverage(g, proto, b1, KEY, max_rounds=3)

    def test_donate_false_retains_and_resume_matches_one_shot(self):
        g = ws(n=260, seed=9)
        proto = BatchFlood()
        sources = [2, 77]
        b0 = proto.init(g, sources)
        mid, _ = engine.run_batch_until_coverage(
            g, proto, b0, KEY, max_rounds=3, donate=False)
        assert not any(leaf.is_deleted()
                       for leaf in jax.tree_util.tree_leaves(b0))
        fin, out = engine.run_batch_until_coverage(
            g, proto, mid, KEY, max_rounds=64, donate=False)
        one, oneout = engine.run_batch_until_coverage(
            g, proto, b0, KEY, max_rounds=64, donate=False)
        for leaf_a, leaf_b in zip(jax.tree_util.tree_leaves(fin),
                                  jax.tree_util.tree_leaves(one)):
            np.testing.assert_array_equal(np.asarray(leaf_a),
                                          np.asarray(leaf_b))
        msgs = np.asarray(lane_messages(g, fin))
        for i, s in enumerate(sources):
            assert_lane_parity(g, fin, out, i, s, msgs=msgs)

    def test_fresh_init_is_donatable(self):
        # init/admit build every leaf as a distinct buffer, so the very
        # first run already donates (unlike Flood's aliased fresh init).
        g = ws()
        b0 = BatchFlood().init(g, [1])
        assert engine._donatable(b0, g, KEY)


# --------------------------------------------------------- summary packing


class TestBatchSummary:
    def test_pack_unpack_roundtrip(self):
        done_words = jnp.asarray([0b101, 0], dtype=jnp.uint32)
        lane_rounds = jnp.arange(64, dtype=jnp.int32)
        packed = accum.pack_batch_summary(
            jnp.int32(9), jnp.int32(3), jnp.int32(61),
            (jnp.int32(2), jnp.uint32(7)), jnp.float32(0.25),
            done_words, lane_rounds)
        out = accum.unpack_batch_summary(packed, 2)
        assert out["rounds"] == 9 and out["active_lanes"] == 3
        assert out["completed"] == 61
        assert out["messages"] == (2 << 32) + 7
        assert abs(out["occupancy_mean"] - 0.25) < 1e-7
        assert out["lane_done"][0] and not out["lane_done"][1]
        assert out["lane_done"][2] and out["lane_done"].sum() == 2
        assert (out["lane_rounds"] == np.arange(64)).all()

    def test_engine_summary_percentiles(self):
        g = ws()
        proto = BatchFlood()
        batch = proto.init(g, [0, 1, 2, 3])
        _, out = engine.run_batch_until_coverage(
            g, proto, batch, KEY, max_rounds=64, donate=False)
        assert out["completed"] == 4
        assert out["completion_rounds_p99"] >= out["completion_rounds_p50"]
        assert out["completion_rounds_p99"] <= out["rounds"]


# ------------------------------------------------------------ the ratchet


@pytest.mark.slow
class TestThroughputRatchet:
    def test_b1024_100k_ws_aggregate_20x_and_bit_identical(self):
        """The acceptance bar: B=1024 concurrent floods on the 100k-node
        WS class at >=20x the aggregate throughput of sequential
        single-message runs — ratio-based (both sides measured on this
        host, CPU included), with EVERY lane bit-identical to its
        independent single-message run.

        The per-lane reference reuses ONE compiled resume loop
        (run_until_coverage_from with a hand-seeded FloodState): a
        reference via Flood(source=s) would recompile per source and
        spend minutes proving the same bits."""
        import time

        g = G.watts_strogatz(100_000, 10, 0.1, seed=0, source_csr=True)
        B = 1024
        rng = np.random.default_rng(0)
        sources = rng.integers(0, g.n_nodes, size=B).astype(np.int32)
        proto = BatchFlood(method="auto")

        def batched_once():
            batch = proto.init(g, sources, coverage_target=0.99)
            return engine.run_batch_until_coverage(
                g, proto, batch, KEY, max_rounds=64)

        batched_once()  # compile + warm
        t0 = time.perf_counter()
        batch, out = batched_once()
        batch_s = time.perf_counter() - t0
        assert out["completed"] == B

        single_run(g, sources[0])  # compile once; cached across sources
        sample = sources[:8]
        t0 = time.perf_counter()
        for s in sample:
            single_run(g, s)
        seq_per_run = (time.perf_counter() - t0) / len(sample)
        ratio = seq_per_run * B / batch_s
        assert ratio >= 20.0, (
            f"aggregate throughput ratio {ratio:.1f}x < 20x "
            f"(batch {batch_s:.3f}s vs {seq_per_run:.4f}s/run sequential)")

        # Every lane bit-identical to its independent run (same compiled
        # reference loop; seen + rounds + exact message count per lane).
        msgs = np.asarray(lane_messages(g, batch))
        seen_np = np.asarray(batch.seen)
        for i, s in enumerate(sources):
            st, single = single_run(g, s)
            w, L = divmod(i, 32)
            got = (seen_np[w] >> np.uint32(L)) & 1
            np.testing.assert_array_equal(
                got.astype(bool), np.asarray(st.seen), err_msg=f"lane {i}")
            assert int(out["lane_rounds"][i]) == int(single["rounds"]), i
            assert int(msgs[i]) == int(single["messages"]), i
