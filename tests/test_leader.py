"""LeaderElection (highest-live-id flooding) vs a numpy fixpoint oracle,
plus the sharded max-propagation seam."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from p2pnetwork_tpu.models import LeaderElection  # noqa: E402
from p2pnetwork_tpu.sim import engine, failures, topology  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def _oracle(g):
    """Per-node fixpoint of max-of-neighbors over live edges (numpy)."""
    n_pad = g.n_nodes_padded
    alive = np.asarray(g.node_mask)
    known = np.where(alive, np.arange(n_pad), -1)
    send = np.asarray(g.senders)
    recv = np.asarray(g.receivers)
    em = np.asarray(g.edge_mask)
    pairs = [(send[em], recv[em])]
    if g.dyn_senders is not None:
        dm = np.asarray(g.dyn_mask)
        pairs.append((np.asarray(g.dyn_senders)[dm],
                      np.asarray(g.dyn_receivers)[dm]))
    for _ in range(n_pad):
        before = known.copy()
        for s, r in pairs:
            ok = alive[s] & alive[r]
            np.maximum.at(known, r[ok], known[s[ok]])
        known = np.where(alive, known, -1)
        if (known == before).all():
            break
    return known


def _run_to_convergence(g, method="auto"):
    _, out = engine.run_until_converged(
        g, LeaderElection(method=method), jax.random.key(0),
        stat="changed", threshold=1, max_rounds=512,
    )
    st, _ = engine.run(g, LeaderElection(method=method), jax.random.key(0),
                       int(out["rounds"]))
    return st, out


class TestLeaderElection:
    @pytest.mark.parametrize("method", ["segment", "gather"])
    def test_ring_converges_to_max_id(self, method):
        g = G.ring(128)
        st, out = _run_to_convergence(g, method)
        np.testing.assert_array_equal(np.asarray(st.known), _oracle(g))
        alive = np.asarray(g.node_mask)
        assert (np.asarray(st.known)[alive] == 127).all()
        # Highest-id flooding on a ring needs about a diameter of rounds.
        assert int(out["rounds"]) >= 32

    def test_ws_matches_oracle(self):
        g = G.watts_strogatz(1024, 6, 0.2, seed=0)
        st, _ = _run_to_convergence(g)
        np.testing.assert_array_equal(np.asarray(st.known), _oracle(g))

    def test_dead_top_node_is_not_elected(self):
        g = failures.fail_nodes(G.watts_strogatz(256, 6, 0.2, seed=1), [255])
        st, _ = _run_to_convergence(g)
        known = np.asarray(st.known)
        alive = np.asarray(g.node_mask)
        assert (known[alive] == 254).all()
        assert known[255] == -1
        np.testing.assert_array_equal(known, _oracle(g))

    def test_disconnected_components_elect_separately(self):
        # Two disjoint directed rings: 0..63 and 64..127.
        idx = np.arange(64)
        senders = np.concatenate([idx, 64 + idx])
        receivers = np.concatenate([(idx + 1) % 64, 64 + (idx + 1) % 64])
        g = G.from_edges(senders, receivers, 128)
        st, _ = _run_to_convergence(g)
        known = np.asarray(st.known)
        assert (known[:64] == 63).all() and (known[64:128] == 127).all()
        # Global coverage plateaus at the majority component's share.
        proto = LeaderElection()
        cov = float(proto.coverage(g, st))
        assert cov == pytest.approx(
            (known[: g.n_nodes] == known[: g.n_nodes].max()).mean())

    def test_runtime_link_merges_components(self):
        idx = np.arange(64)
        senders = np.concatenate([idx, 64 + idx])
        receivers = np.concatenate([(idx + 1) % 64, 64 + (idx + 1) % 64])
        g = G.from_edges(senders, receivers, 128)
        g = topology.connect(topology.with_capacity(g, extra_edges=4),
                             [100], [3])  # high ring -> low ring
        st, _ = _run_to_convergence(g)
        known = np.asarray(st.known)
        assert (known[: 128] == 127).all()  # everyone agrees now
        np.testing.assert_array_equal(known, _oracle(g))

    def test_message_accounting_quiesces(self):
        g = G.watts_strogatz(512, 4, 0.1, seed=2)
        _, stats = engine.run(g, LeaderElection(), jax.random.key(0), 40)
        msgs = np.asarray(stats["messages"])
        changed = np.asarray(stats["changed"])
        # Once nothing changes, nothing is sent the round after — a
        # converged overlay is silent (unlike naive re-broadcast).
        done = np.nonzero(changed == 0)[0]
        assert done.size > 0
        assert (msgs[done[0] + 1:] == 0).all()


class TestShardedMaxPropagate:
    @pytest.mark.parametrize("n_shards", [2, 8])
    def test_leader_election_via_max_seam(self, n_shards):
        from p2pnetwork_tpu.parallel import mesh as M, sharded

        g = G.watts_strogatz(1024, 6, 0.2, seed=3)
        mesh = M.ring_mesh(n_shards)
        sg = sharded.shard_graph(g, mesh)
        S, block = sg.n_shards, sg.block
        ids = jnp.arange(S * block, dtype=jnp.int32).reshape(S, block)
        known = jnp.where(sg.node_mask, ids, -1)
        for _ in range(40):
            heard = sharded.propagate(sg, mesh, known, op="max")
            known = jnp.where(sg.node_mask, jnp.maximum(known, heard), -1)
        np.testing.assert_array_equal(
            np.asarray(known).reshape(-1), _oracle(g))

    def test_max_rejects_mxu_layout(self):
        from p2pnetwork_tpu.parallel import mesh as M, sharded

        g = G.watts_strogatz(1024, 6, 0.2, seed=4)
        mesh = M.ring_mesh(4)
        sg = sharded.shard_graph(g, mesh, hybrid=True, min_count=32)
        with pytest.raises(ValueError, match="max"):
            sharded.propagate(sg, mesh, sg.node_mask.astype(jnp.int32),
                              op="max")

    def test_max_with_dynamic_links_and_failures(self):
        from p2pnetwork_tpu.parallel import mesh as M, sharded
        from p2pnetwork_tpu.sim import failures as F

        g = G.ring(256)
        mesh = M.ring_mesh(4)
        sg = sharded.shard_graph(g, mesh)
        sg = sharded.with_capacity(sharded.fail_nodes(sg, [255]), 8)
        sg = sharded.connect(sg, [10], [200])
        gc = topology.connect(
            topology.with_capacity(F.fail_nodes(g, [255]), extra_edges=8),
            [10], [200],
        )
        S, block = sg.n_shards, sg.block
        ids = jnp.arange(S * block, dtype=jnp.int32).reshape(S, block)
        known = jnp.where(sg.node_mask, ids, -1)
        for _ in range(300):
            heard = sharded.propagate(sg, mesh, known, op="max")
            known = jnp.where(sg.node_mask, jnp.maximum(known, heard), -1)
        np.testing.assert_array_equal(
            np.asarray(known).reshape(-1), _oracle(gc))


class TestLeaderUntilQuiet:
    """Device-side run-to-quiescence on the ring (leader_until_quiet) —
    rounds and message totals must match the engine's
    run_until_converged(stat='changed', threshold=1) exactly."""

    @pytest.mark.parametrize("n_shards", [1, 2, 8])
    def test_matches_engine_convergence(self, n_shards):
        from p2pnetwork_tpu.parallel import mesh as M, sharded

        g = G.watts_strogatz(1024, 6, 0.2, seed=5)
        mesh = M.ring_mesh(n_shards)
        sg = sharded.shard_graph(g, mesh)
        known, out = sharded.leader_until_quiet(sg, mesh)
        _, ref = engine.run_until_converged(
            g, LeaderElection(), jax.random.key(0),
            stat="changed", threshold=1, max_rounds=512,
        )
        assert out["rounds"] == ref["rounds"]
        assert out["messages"] == ref["messages"]
        assert out["coverage"] == pytest.approx(1.0)
        np.testing.assert_array_equal(
            np.asarray(known).reshape(-1), _oracle(g))

    def test_under_failures_and_links(self):
        from p2pnetwork_tpu.parallel import mesh as M, sharded

        g = G.ring(512)
        mesh = M.ring_mesh(4)
        sg = sharded.shard_graph(g, mesh)
        sg = sharded.with_capacity(sharded.fail_nodes(sg, [511, 7]), 8)
        sg = sharded.connect(sg, [100], [300])
        gc = topology.connect(
            topology.with_capacity(failures.fail_nodes(g, [511, 7]),
                                   extra_edges=8),
            [100], [300],
        )
        known, out = sharded.leader_until_quiet(sg, mesh)
        np.testing.assert_array_equal(
            np.asarray(known).reshape(-1), _oracle(gc))
        flat = np.asarray(known).reshape(-1)
        assert flat[np.asarray(gc.node_mask)].max() == 510  # 511 is dead

    def test_rejects_mxu_layout(self):
        from p2pnetwork_tpu.parallel import mesh as M, sharded

        g = G.watts_strogatz(1024, 6, 0.2, seed=6)
        sg = sharded.shard_graph(g, M.ring_mesh(4), hybrid=True,
                                 min_count=32)
        with pytest.raises(ValueError, match="MXU"):
            sharded.leader_until_quiet(sg, M.ring_mesh(4))


class TestLeaderOnSimNode:
    def test_jaxsimnode_runs_election_to_convergence(self):
        # The bridge is protocol-agnostic: a JaxSimNode population runs
        # the election with the same run_until_converged surface.
        from p2pnetwork_tpu.sim.simnode import JaxSimNode

        g = G.watts_strogatz(2048, 6, 0.2, seed=7)
        node = JaxSimNode(graph=g, protocol=LeaderElection(), id="sim")
        out = node.run_until_converged("changed", 1, max_rounds=128)
        assert out["value"] == 0  # quiet: nobody learned anything
        known = np.asarray(node.sim_state.known)
        np.testing.assert_array_equal(known, _oracle(g))
        assert node.sim_message_count > 0
