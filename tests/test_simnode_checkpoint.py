"""JaxSimNode bridge + checkpoint/resume tests."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from p2pnetwork_tpu.models import SIR, Flood  # noqa: E402
from p2pnetwork_tpu.sim import checkpoint as ckpt  # noqa: E402
from p2pnetwork_tpu.sim import engine  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402
from p2pnetwork_tpu.sim.simnode import JaxSimNode  # noqa: E402
from tests.helpers import EventRecorder, stop_all, wait_until  # noqa: E402


class TestJaxSimNode:
    def test_rounds_fire_node_message_events(self):
        rec = EventRecorder()
        g = G.watts_strogatz(512, 6, 0.1, seed=0)
        node = JaxSimNode("127.0.0.1", 0, graph=g, protocol=Flood(source=0), callback=rec)
        stats = node.run_rounds(4)
        assert stats["coverage"].shape == (4,)
        msgs = rec.data_for("node_message")
        assert len(msgs) == 4
        assert msgs[0]["sim_round"] == 1
        assert msgs[-1]["sim_round"] == 4
        assert 0 < msgs[-1]["coverage"] <= 1.0
        assert isinstance(rec.events[0][1], str) and rec.events[0][1].startswith("sim:")
        assert node.sim_message_count > 0

    def test_is_still_a_real_sockets_node(self):
        # The bridge keeps the full sockets surface: a live peer can connect
        # to a JaxSimNode and exchange messages while a simulation runs.
        from p2pnetwork_tpu import Node

        rec = EventRecorder()
        g = G.ring(256)
        sim_node = JaxSimNode("127.0.0.1", 0, graph=g, protocol=Flood(source=0), callback=rec)
        sim_node.start()
        peer = Node("127.0.0.1", 0)
        peer.start()
        try:
            assert peer.connect_with_node("127.0.0.1", sim_node.port)
            assert wait_until(lambda: len(sim_node.nodes_inbound) == 1)
            peer.send_to_nodes("hello from a socket peer")
            sim_node.run_rounds(2)
            assert wait_until(
                lambda: "hello from a socket peer" in rec.data_for("node_message")
            )
            sim_rounds = [d for d in rec.data_for("node_message")
                          if isinstance(d, dict) and "sim_round" in d]
            assert len(sim_rounds) == 2
        finally:
            stop_all([sim_node, peer])

    def test_run_until_coverage(self):
        g = G.watts_strogatz(1024, 8, 0.1, seed=1)
        node = JaxSimNode(graph=g, protocol=Flood(source=0))
        out = node.run_until_coverage(0.99)
        assert out["coverage"] >= 0.99
        assert node.sim_round == out["rounds"]

    def test_run_until_coverage_resumes_from_current_state(self):
        # Regression: run_until_coverage used to silently re-init the
        # protocol state, throwing away progress from earlier run_rounds.
        g = G.watts_strogatz(1024, 8, 0.1, seed=1)
        node = JaxSimNode(graph=g, protocol=Flood(source=0))
        node.run_rounds(3)
        seen_before = int(np.asarray(node.sim_state.seen).sum())
        out = node.run_until_coverage(0.99)
        # A fresh flood needs ~7 rounds on this graph; resuming after 3
        # completed rounds must need strictly fewer.
        fresh = JaxSimNode(graph=g, protocol=Flood(source=0))
        fresh_out = fresh.run_until_coverage(0.99)
        assert out["rounds"] < fresh_out["rounds"]
        assert int(np.asarray(node.sim_state.seen).sum()) >= seen_before
        assert node.sim_round == 3 + out["rounds"]
        # Calling again on a finished run must be a no-op (regression: the
        # loop used to seed coverage=0 and run one spurious round).
        round_before = node.sim_round
        again = node.run_until_coverage(0.99)
        assert again["rounds"] == 0
        assert node.sim_round == round_before

    def test_incremental_equals_one_shot(self):
        g = G.watts_strogatz(256, 4, 0.2, seed=2)
        a = JaxSimNode(graph=g, protocol=Flood(source=0), seed=7)
        b = JaxSimNode(graph=g, protocol=Flood(source=0), seed=7)
        a.run_rounds(2)
        a.run_rounds(3)
        # Flood is PRNG-independent, so segmentation must not matter.
        b.run_rounds(5)
        np.testing.assert_array_equal(
            np.asarray(a.sim_state.seen), np.asarray(b.sim_state.seen)
        )

    def test_fail_and_connect_sim_nodes(self):
        from p2pnetwork_tpu.sim import topology

        rec = EventRecorder()
        g = topology.with_capacity(G.ring(200), extra_edges=16)
        node = JaxSimNode(graph=g, protocol=Flood(source=0), seed=0,
                         callback=rec)
        node.fail_sim_nodes([25, 75])  # partition the ring
        node.run_rounds(140)  # ring radius within the cut component is 124
        seen = np.asarray(node.sim_state.seen)[:100]
        assert not seen[26:75].any()
        topo_events = [d for d in rec.data_for("node_message")
                       if isinstance(d, dict) and "sim_topology" in d]
        assert topo_events and topo_events[0]["sim_topology"] == "fail_nodes"
        assert topo_events[0]["alive_nodes"] == 198
        node.connect_sim_nodes([10], [50])  # bridge + re-announce
        import dataclasses

        node.sim_state = dataclasses.replace(
            node.sim_state, frontier=node.sim_state.seen
        )
        node.run_rounds(140)
        seen = np.asarray(node.sim_state.seen)
        alive = np.asarray(node.sim_graph.node_mask)
        assert (seen | ~alive)[:200].all()

    def test_inject_sim_churn(self):
        node = JaxSimNode(graph=G.watts_strogatz(1000, 4, 0.1, seed=0),
                          protocol=Flood(source=0), seed=0)
        node.inject_sim_churn(0.5, seed=1)
        alive = int(np.asarray(node.sim_graph.node_mask).sum())
        assert 380 < alive < 620

    def test_inject_sim_churn_default_seed(self):
        # Regression: the documented default path (no seed) crashed with
        # AttributeError because _churn_count was never initialized.
        node = JaxSimNode(graph=G.watts_strogatz(1000, 4, 0.1, seed=0),
                          protocol=Flood(source=0), seed=0)
        node.inject_sim_churn(0.3)
        alive1 = int(np.asarray(node.sim_graph.node_mask).sum())
        assert 600 < alive1 < 800
        # A second call draws FRESH randomness: more nodes die (a repeated
        # key would re-select the same, already-dead set).
        node.inject_sim_churn(0.3)
        alive2 = int(np.asarray(node.sim_graph.node_mask).sum())
        assert alive2 < alive1

    def test_sim_peer_send_is_noop(self):
        g = G.ring(128)
        node = JaxSimNode(graph=g, protocol=Flood(source=0))
        node.sim_peer.send("into the void")  # no exception
        node.sim_peer.set_info("k", 1)
        assert node.sim_peer.get_info("k") == 1


class TestCheckpoint:
    def test_save_load_roundtrip(self, tmp_path):
        g = G.erdos_renyi(300, 0.02, seed=3)
        proto = SIR(beta=0.4, gamma=0.1)
        key = jax.random.key(5)
        state = proto.init(g, key)
        path = str(tmp_path / "sim.npz")
        ckpt.save(path, state, key, 17, message_count=4242)
        loaded, lkey, lround, lmsgs = ckpt.load(path, proto.init(g, jax.random.key(0)))
        np.testing.assert_array_equal(np.asarray(loaded.status), np.asarray(state.status))
        assert lround == 17
        assert lmsgs == 4242
        np.testing.assert_array_equal(
            jax.random.key_data(lkey), jax.random.key_data(key)
        )

    def test_structure_mismatch_rejected(self, tmp_path):
        g = G.ring(128)
        flood_state = Flood(source=0).init(g, jax.random.key(0))
        sir_state = SIR().init(g, jax.random.key(0))
        path = str(tmp_path / "sim.npz")
        ckpt.save(path, flood_state, jax.random.key(0), 0)
        with pytest.raises(ValueError, match="structure mismatch"):
            ckpt.load(path, sir_state)

    def test_orbax_roundtrip_preserves_sharding(self, tmp_path):
        from p2pnetwork_tpu.parallel import mesh as M

        mesh = M.ring_mesh(8)
        g = G.watts_strogatz(1024, 6, 0.1, seed=1)
        proto = Flood(source=0)
        key = jax.random.key(3)
        state = proto.init(g, key)
        sharded_seen = jax.device_put(state.seen, M.shard_spec(mesh))
        import dataclasses

        state = dataclasses.replace(state, seen=sharded_seen)
        path = str(tmp_path / "orbax_ckpt")
        ckpt.save_orbax(path, state, key, 9, message_count=77)

        template = dataclasses.replace(
            proto.init(g, jax.random.key(0)),
            seen=jax.device_put(
                proto.init(g, jax.random.key(0)).seen, M.shard_spec(mesh)
            ),
        )
        loaded, lkey, lround, lmsgs = ckpt.load_orbax(path, template)
        assert lround == 9 and lmsgs == 77
        np.testing.assert_array_equal(
            np.asarray(loaded.seen), np.asarray(state.seen)
        )
        np.testing.assert_array_equal(
            jax.random.key_data(lkey), jax.random.key_data(key)
        )
        # restored WITH the template's sharding, not funneled to one device
        assert len(loaded.seen.sharding.device_set) == 8

    def test_topology_survives_checkpoint(self, tmp_path):
        # The reference's peer lists ARE its state [ref: p2pnetwork/
        # node.py:46-52]: a run that failed nodes, churned, and grew links
        # must restore onto the damaged/grown network — no manual damage
        # re-application — and continue bit-identically.
        from p2pnetwork_tpu.sim import topology

        g = topology.with_capacity(
            G.watts_strogatz(600, 6, 0.1, seed=4), extra_edges=16
        )
        proto = SIR(beta=0.5, gamma=0.2)
        path = str(tmp_path / "topo.npz")

        a = JaxSimNode(graph=g, protocol=proto, seed=9)
        a.run_rounds(3)
        a.fail_sim_nodes([10, 20, 30])
        a.inject_sim_churn(0.1)
        a.connect_sim_nodes([5, 7], [505, 597])
        a.run_rounds(2)
        a.save_checkpoint(path)
        a.run_rounds(5)

        b = JaxSimNode(graph=g, protocol=proto, seed=9)
        b.load_checkpoint(path)
        # The restored graph is the mutated one, not the pristine build.
        for field in ("node_mask", "edge_mask", "in_degree", "out_degree",
                      "neighbor_mask", "dyn_senders", "dyn_receivers",
                      "dyn_mask"):
            got_a = np.asarray(getattr(a.sim_graph, field))
            got_b = np.asarray(getattr(b.sim_graph, field))
            np.testing.assert_array_equal(got_b, got_a, err_msg=field)
        assert int(np.asarray(b.sim_graph.node_mask).sum()) < 600
        b.run_rounds(5)
        np.testing.assert_array_equal(
            np.asarray(a.sim_state.status), np.asarray(b.sim_state.status)
        )
        # The churn counter is state too: the NEXT churn event must draw the
        # same fresh randomness on both, not replay pre-checkpoint draws.
        a.inject_sim_churn(0.1)
        b.inject_sim_churn(0.1)
        np.testing.assert_array_equal(
            np.asarray(a.sim_graph.node_mask), np.asarray(b.sim_graph.node_mask)
        )

    def test_topology_checkpoint_with_kernel_layouts(self, tmp_path):
        # blocked/hybrid kernel masks are re-masked by failures; restoring
        # must bring THOSE back too, or the fast aggregation paths would
        # disagree with the COO truth on the restored node.
        from p2pnetwork_tpu.ops import segment

        g = G.watts_strogatz(512, 6, 0.1, seed=1, blocked=True, hybrid=True)
        proto = Flood(source=0)
        path = str(tmp_path / "kern.npz")
        a = JaxSimNode(graph=g, protocol=proto, seed=0)
        a.fail_sim_nodes([3, 141, 399])
        a.save_checkpoint(path)

        b = JaxSimNode(graph=g, protocol=proto, seed=0)
        b.load_checkpoint(path)
        sig = np.zeros(g.n_nodes_padded, dtype=bool)
        sig[[2, 140, 400]] = True
        ref = np.asarray(segment.propagate_or(b.sim_graph, jax.numpy.asarray(sig), "segment"))
        for method in ("blocked", "pallas", "hybrid"):
            out = np.asarray(segment.propagate_or(b.sim_graph, jax.numpy.asarray(sig), method))
            np.testing.assert_array_equal(out, ref, err_msg=method)

    def test_connect_works_after_restore(self, tmp_path):
        # Regression: apply_topology_state installed raw numpy arrays from
        # the npz, so the first post-restore connect crashed on .at[].
        from p2pnetwork_tpu.sim import topology

        g = topology.with_capacity(G.ring(200), extra_edges=16)
        proto = Flood(source=0)
        path = str(tmp_path / "grow.npz")
        a = JaxSimNode(graph=g, protocol=proto, seed=0)
        a.connect_sim_nodes([0], [100])
        a.save_checkpoint(path)
        b = JaxSimNode(graph=g, protocol=proto, seed=0)
        b.load_checkpoint(path)
        b.connect_sim_nodes([2], [101])  # must not crash
        assert int(np.asarray(b.sim_graph.dyn_mask).sum()) == 4

    def test_restore_after_capped_table_dropped(self, tmp_path):
        # Regression: fail_edges on a width-capped neighbor table drops the
        # table; the checkpoint then lacks neighbor_mask and restoring onto
        # the documented pristine construction was rejected outright.
        from p2pnetwork_tpu.sim import failures

        g = G.barabasi_albert(300, 3, seed=1, max_degree=2)
        assert not g.neighbors_complete
        proto = SIR(beta=0.4, gamma=0.1)
        path = str(tmp_path / "capped.npz")
        a = JaxSimNode(graph=g, protocol=proto, seed=5)
        a.run_rounds(2)
        a.sim_graph = failures.fail_edges(a.sim_graph, [0, 1])
        a.run_rounds(2)
        a.save_checkpoint(path)
        a.run_rounds(3)

        b = JaxSimNode(graph=g, protocol=proto, seed=5)
        b.load_checkpoint(path)
        # The restore mirrors the drop instead of erroring...
        assert b.sim_graph.neighbors is None and b.sim_graph.neighbor_mask is None
        np.testing.assert_array_equal(
            np.asarray(b.sim_graph.edge_mask), np.asarray(a.sim_graph.edge_mask)
        )
        # ...and the run continues bit-identically.
        b.run_rounds(3)
        np.testing.assert_array_equal(
            np.asarray(a.sim_state.status), np.asarray(b.sim_state.status)
        )

    def test_topology_mismatch_rejected(self, tmp_path):
        from p2pnetwork_tpu.sim import topology

        g_cap = topology.with_capacity(G.ring(200), extra_edges=16)
        proto = Flood(source=0)
        path = str(tmp_path / "mismatch.npz")
        a = JaxSimNode(graph=g_cap, protocol=proto, seed=0)
        a.save_checkpoint(path)
        # Restoring onto a graph WITHOUT the dynamic region must fail
        # loudly, not silently drop the runtime links.
        b = JaxSimNode(graph=G.ring(200), protocol=proto, seed=0)
        with pytest.raises(ValueError, match="structure mismatch|keys mismatch"):
            b.load_checkpoint(path)

    def test_legacy_protocol_only_checkpoint_still_loads(self, tmp_path):
        # Pre-topology-format checkpoints (protocol state as the root
        # pytree) must keep loading: protocol state restores, the graph
        # resumes as attached, and the restored leaves are device arrays.
        g = G.watts_strogatz(512, 6, 0.1, seed=4)
        proto = SIR(beta=0.5, gamma=0.2)
        path = str(tmp_path / "legacy.npz")
        state = proto.init(g, jax.random.key(9))
        ckpt.save(path, state, jax.random.key(9), 7, message_count=123)

        b = JaxSimNode(graph=g, protocol=proto, seed=9)
        b.load_checkpoint(path)
        assert b.sim_round == 7 and b.sim_message_count == 123
        assert isinstance(b.sim_state.status, jax.Array)
        np.testing.assert_array_equal(
            np.asarray(b.sim_state.status), np.asarray(state.status)
        )
        b.run_rounds(2)  # still a working node

    def test_rejected_load_leaves_node_untouched(self, tmp_path):
        # Regression: same tree STRUCTURE but different shapes passed the
        # treedef check, mutated the node, then failed topology validation
        # — leaving a 384-wide protocol state on a 256-wide graph.
        from p2pnetwork_tpu.sim import topology

        proto = Flood(source=0)
        path = str(tmp_path / "foreign.npz")
        a = JaxSimNode(graph=topology.with_capacity(G.ring(300), extra_edges=16),
                       protocol=proto, seed=0)
        a.run_rounds(2)
        a.save_checkpoint(path)
        b = JaxSimNode(graph=topology.with_capacity(G.ring(200), extra_edges=16),
                       protocol=proto, seed=0)
        b.run_rounds(1)
        round_before = b.sim_round
        seen_before = np.asarray(b.sim_state.seen).copy()
        with pytest.raises(ValueError, match="topology state mismatch"):
            b.load_checkpoint(path)
        assert b.sim_round == round_before
        np.testing.assert_array_equal(np.asarray(b.sim_state.seen), seen_before)
        b.run_rounds(2)  # still a working node

    def test_resume_is_bit_identical(self, tmp_path):
        # Run 10 rounds straight vs save@5 -> load -> 5 more: same result.
        g = G.watts_strogatz(512, 6, 0.1, seed=4)
        proto = SIR(beta=0.5, gamma=0.2)
        path = str(tmp_path / "resume.npz")

        a = JaxSimNode(graph=g, protocol=proto, seed=9)
        a.run_rounds(5)
        a.save_checkpoint(path)
        a.run_rounds(5)

        b = JaxSimNode(graph=g, protocol=proto, seed=9)
        b.load_checkpoint(path)
        assert b.sim_round == 5
        b.run_rounds(5)
        np.testing.assert_array_equal(
            np.asarray(a.sim_state.status), np.asarray(b.sim_state.status)
        )
        # The message counter is part of the checkpoint: both nodes report
        # the same cumulative total after the same 10 rounds.
        assert a.sim_message_count == b.sim_message_count


class TestGraphPersistence:
    def _roundtrip(self, g, tmp_path):
        from p2pnetwork_tpu.sim import checkpoint as ckpt
        p = str(tmp_path / "graph.npz")
        ckpt.save_graph(p, g)
        return ckpt.load_graph(p)

    def test_full_layout_roundtrip(self, tmp_path):
        g = G.watts_strogatz(512, 6, 0.2, seed=0, blocked=True, hybrid=True,
                             source_csr=True)
        g = g.with_weights(lambda s, r: 1.0 + (s % 7).astype(np.float32))
        g2 = self._roundtrip(g, tmp_path)
        assert (g2.n_nodes, g2.n_edges) == (g.n_nodes, g.n_edges)
        assert g2.max_in_span == g.max_in_span
        assert g2.max_out_span == g.max_out_span
        for name in ("senders", "receivers", "edge_mask", "node_mask",
                     "in_degree", "out_degree", "neighbors", "neighbor_mask",
                     "src_eid", "src_offsets", "edge_weight",
                     "neighbor_weight"):
            np.testing.assert_array_equal(
                np.asarray(getattr(g2, name)), np.asarray(getattr(g, name)),
                err_msg=name)
        assert g2.blocked.block == g.blocked.block
        np.testing.assert_array_equal(np.asarray(g2.blocked.src),
                                      np.asarray(g.blocked.src))
        assert g2.hybrid.offsets == g.hybrid.offsets
        np.testing.assert_array_equal(np.asarray(g2.hybrid.masks),
                                      np.asarray(g.hybrid.masks))

    def test_flood_parity_after_reload(self, tmp_path):
        from p2pnetwork_tpu.models import Flood
        g = G.watts_strogatz(256, 4, 0.2, seed=1, hybrid=True)
        g2 = self._roundtrip(g, tmp_path)
        a, out_a = engine.run_until_coverage(
            g, Flood(source=0, method="hybrid"), jax.random.key(0))
        b, out_b = engine.run_until_coverage(
            g2, Flood(source=0, method="hybrid"), jax.random.key(0))
        assert out_a == out_b
        np.testing.assert_array_equal(np.asarray(a.seen), np.asarray(b.seen))

    def test_churned_graph_roundtrips(self, tmp_path):
        from p2pnetwork_tpu.sim import failures, topology
        g = G.ring(64)
        g = topology.connect(topology.with_capacity(
            failures.fail_nodes(g, [5]), extra_edges=8), [0], [32])
        g2 = self._roundtrip(g, tmp_path)
        np.testing.assert_array_equal(np.asarray(g2.node_mask),
                                      np.asarray(g.node_mask))
        np.testing.assert_array_equal(np.asarray(g2.dyn_senders),
                                      np.asarray(g.dyn_senders))
        np.testing.assert_array_equal(np.asarray(g2.dyn_mask),
                                      np.asarray(g.dyn_mask))
