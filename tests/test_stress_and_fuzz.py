"""Robustness beyond the reference's 3-node scenarios: a larger live
topology on the sockets backend, and seeded fuzz over both stream
decoders (the reference's framing scan has no tests at all for malformed
input [ref: tests/test_nodeconnection.py:4-5])."""

import random

import pytest

from p2pnetwork_tpu import Node, wire
from tests.helpers import EventRecorder, stop_all, wait_until


class TestManyNodeTopology:
    def test_twenty_node_ring_gossip_delivers_everywhere(self):
        # 20 nodes in a directed ring; a token broadcast hop-by-hop (each
        # node forwards first sightings) must reach every node — the
        # flood protocol the reference tells users to write themselves,
        # at a size its suite never exercises.
        n_nodes = 20
        recs = [EventRecorder() for _ in range(n_nodes)]
        nodes = []

        def make_cb(i):
            def cb(event, main_node, connected_node, data):
                recs[i](event, main_node, connected_node, data)
                if event == "node_message" and data not in getattr(
                        main_node, "_seen_msgs", set()):
                    seen = getattr(main_node, "_seen_msgs", set())
                    seen.add(data)
                    main_node._seen_msgs = seen
                    main_node.send_to_nodes(data)  # forward along the ring
            return cb

        for i in range(n_nodes):
            node = Node("127.0.0.1", 0, callback=make_cb(i), id=f"n{i}")
            node.start()
            nodes.append(node)
        try:
            for i in range(n_nodes):
                assert nodes[i].connect_with_node(
                    "127.0.0.1", nodes[(i + 1) % n_nodes].port)
            assert wait_until(
                lambda: all(len(n.nodes_outbound) == 1 for n in nodes),
                timeout=15.0)
            nodes[0].send_to_nodes("token-7")
            assert wait_until(
                lambda: all("token-7" in r.messages() for r in recs[1:]),
                timeout=20.0)
        finally:
            stop_all(nodes)

    def test_fanout_hub_with_many_spokes(self):
        # One hub, 15 spokes; hub broadcast reaches all spokes, spoke
        # unicasts reach the hub — max_connections=0 (unlimited) parity.
        hub_rec = EventRecorder()
        hub = Node("127.0.0.1", 0, callback=hub_rec, id="hub")
        hub.start()
        spokes, recs = [], []
        try:
            for i in range(15):
                r = EventRecorder()
                s = Node("127.0.0.1", 0, callback=r, id=f"s{i}")
                s.start()
                assert s.connect_with_node("127.0.0.1", hub.port)
                spokes.append(s)
                recs.append(r)
            assert wait_until(lambda: len(hub.nodes_inbound) == 15,
                              timeout=15.0)
            hub.send_to_nodes({"round": 1})
            assert wait_until(
                lambda: all({"round": 1} in r.messages() for r in recs),
                timeout=15.0)
            for s in spokes:
                s.send_to_nodes(f"ack-{s.id}")
            assert wait_until(
                lambda: len(hub_rec.messages()) == 15, timeout=15.0)
        finally:
            stop_all([hub] + spokes)


class TestDecoderFuzz:
    """Seeded random streams through both decoders: no crash, bounded
    buffers, and every well-formed frame that goes in comes out."""

    @pytest.mark.parametrize("framing", ["eot", "length"])
    @pytest.mark.parametrize("seed", [0, 1, 7, 12, 42])
    def test_roundtrip_under_random_chunking(self, framing, seed):
        rng = random.Random(seed)
        payloads = []
        for _ in range(200):
            kind = rng.randrange(3)
            if kind == 0:
                payloads.append("".join(chr(rng.randrange(32, 127))
                                        for _ in range(rng.randrange(0, 300))))
            elif kind == 1:
                payloads.append({"k": rng.randrange(1000),
                                 "v": [rng.random() for _ in range(5)]})
            else:
                body = bytes(rng.randrange(256)
                             for _ in range(rng.randrange(1, 200)))
                if framing == "eot":
                    # EOT framing cannot carry the delimiter, and its
                    # parse chain sniffs a trailing 0x02 as the
                    # compression marker (reference parity). Length
                    # framing carries BOTH unmodified — that is its point.
                    body = body.replace(wire.EOT_CHAR, b"\xfe")
                    while body.endswith(wire.COMPR_CHAR):
                        body = body[:-1] + b"\xfe"
                    if not body:
                        body = b"\xfe"
                payloads.append(body)
        stream = b"".join(wire.encode_frame(p, framing=framing)
                          for p in payloads)
        dec = wire.make_decoder(framing)
        parse = (wire.parse_length_body if framing == "length"
                 else wire.parse_packet)
        out = []
        i = 0
        while i < len(stream):
            step = rng.randrange(1, 50)
            out.extend(parse(b) for b in dec.feed(stream[i:i + step]))
            i += step
        assert dec.pending == 0
        assert len(out) == len(payloads)
        # bytes that happen to be valid utf-8 decode to str/json — the
        # reference's parse chain loses the type; compare decoded forms.
        for got, sent in zip(out, payloads):
            if isinstance(sent, bytes):
                assert got == wire.decode_payload(sent)
            else:
                assert got == sent

    @pytest.mark.parametrize("framing", ["eot", "length"])
    @pytest.mark.parametrize("seed", [0, 1, 7, 8, 10, 12])
    def test_garbage_never_crashes_and_buffer_stays_bounded(self, framing,
                                                            seed):
        rng = random.Random(seed)
        dec = wire.make_decoder(framing, max_buffer=4096)
        overflows = 0
        for _ in range(300):
            chunk = bytes(rng.randrange(256)
                          for _ in range(rng.randrange(1, 400)))
            parse = (wire.parse_length_body if framing == "length"
                     else wire.parse_packet)
            try:
                for packet in dec.feed(chunk):
                    parse(packet)  # must not raise either
            except wire.FrameOverflowError:
                overflows += 1  # allowed: bound enforced, stream reset
            # Header-inclusive bound: never more than max_buffer buffered.
            assert dec.pending <= 4096
        # With random bytes the 4 KiB bound must have tripped at least
        # once in 300 x ~200 B for the length decoder (huge bogus
        # headers) — proves the bound is live, not decorative.
        if framing == "length":
            assert overflows >= 1
